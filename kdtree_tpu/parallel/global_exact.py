"""Scalable EXACT median-split global k-d tree (SURVEY.md §7(b)).

The capability hole the round-2 verdict named: an exact median-split tree
whose N scales with the mesh. The bitonic ``global_tree`` is exact but
replicates an O(N) heap per chip; the Morton ``global_morton`` forest scales
but is not the median-split tree. This engine is both:

- **Top log2(P) levels: true global exact medians.** Each level, every live
  segment spans a contiguous device group. The segment's exact median (by
  the same (coordinate, id) composite order as the single-chip build — f32
  ties break identically) is found by a **distributed radix select**: 32
  bit-rounds over a monotone u32 image of the axis coordinate, then 31
  rounds over ids among ties; each round is one ``lax.psum`` of a
  [segments]-vector, so all segments of a level select simultaneously.
  The selected medians ARE the single-chip tree's top nodes — verified
  node-for-node against ``build_jit`` (tests/test_global_exact.py).
- **One mirror ppermute per level.** After classification against the
  median, rows that sit in the wrong half of their device group cross to
  the mirror device (``p ^ half``) in ONE ``lax.ppermute``; rows in the
  right half stay put. No slot bookkeeping, no all_to_all matrix: total
  exchanged ≈ log2(P) · N/(2P) rows per device — the "top levels
  redistribute, rest is chip-local" shape §7(b) promised. Fixed-capacity
  buffers with overflow detection (uniform data stays ~balanced; heavy
  skew raises with a retry hint, the same contract as ``global_morton``).
- **Chip-local exact build below.** After log2(P) levels each device owns
  exactly one segment (~N/P rows) and builds it with the same
  ``build_impl`` as the single-chip path — one algorithm core. Padding
  rows (+inf) follow the ensemble-mode convention; sub-tree medians are
  medians of the padded local segment (documented deviation — the top
  L levels are the exact global medians, which is what balance and
  routing depend on).

Query: replicated queries; each device answers its local subtree exactly
(AABB-less classic prune via ``_knn_batch``), the P partial k-buffers plus
the top-heap node points merge through one all_gather + top-k — exact
because segments partition the point set and top nodes are explicitly
scanned.

State per chip: O(N/P) rows + a 2P-node replicated top heap. Communication:
64-ish scalar-vector psum rounds + one ~N/(2P)-row ppermute per top level.

Generative like ``global_morton_knn``: takes (seed, dim, num_points), each
device draws only its own rows (``kdtree_mpi.cpp:19-41``'s discard trick,
counter-based); no [N, D] array ever exists.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from kdtree_tpu import obs
from kdtree_tpu.models.tree import tree_spec
from kdtree_tpu.ops.build import build_impl, spec_arrays
from kdtree_tpu.ops.query import _knn_batch
from kdtree_tpu.utils.guards import check_rows_fit_i32

from .global_morton import _merge_partials
from .mesh import SHARD_AXIS, shard_map

DEFAULT_SLACK = 1.6


# ---------------------------------------------------------------------------
# static layout: sizes of the top-level segments (exact split arithmetic)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=64)
def _top_layout(n: int, p: int) -> Tuple[Tuple[int, ...], ...]:
    """Per top level, the static segment sizes in position order. Mirrors
    the reference's split arithmetic (left c//2, median 1, right c-c//2-1,
    ``kdtree_sequential.cpp:51-56``)."""
    levels = []
    sizes = [n]
    L = p.bit_length() - 1
    for _ in range(L):
        levels.append(tuple(sizes))
        nxt = []
        for c in sizes:
            m = c // 2
            nxt.append(m)
            nxt.append(max(c - m - 1, 0))
        sizes = nxt
    return tuple(levels)


def _f32_key(x):
    """Monotone u32 image of f32 (total order; +inf maps to the top)."""
    b = lax.bitcast_convert_type(x, jnp.uint32)
    neg = (b >> 31) == 1
    return jnp.where(neg, ~b, b | jnp.uint32(0x80000000))


def _radix_select(key_u32, tie_i32, valid, seg, k_by_seg, S, axis_name):
    """Distributed (key, tie) lexicographic k-th smallest per segment.

    key_u32/tie_i32/valid: this device's rows; ``seg``: this device's
    (static) segment index; ``k_by_seg``: i32[S] replicated 0-based ranks.
    Returns (med_key u32[S], med_tie i32[S]) replicated on every device.
    All devices run identical control flow; counts flow through one psum of
    an [S]-vector per bit round.
    """
    onehot = (jnp.arange(S) == seg).astype(jnp.int32)  # [S]

    def count_seg(mask):
        cnt = jnp.sum(mask.astype(jnp.int32))
        return lax.psum(cnt * onehot, axis_name)  # [S] per-segment totals

    def select(bits_from, values, candidates, krem):
        """MSB-first radix select of the krem-th smallest ``values`` among
        ``candidates``; bit masks are static Python ints (unrolled)."""
        prefix = jnp.zeros(S, values.dtype)
        for b in range(bits_from, -1, -1):
            above = (~((1 << (b + 1)) - 1)) & 0xFFFFFFFF
            cand0 = (
                candidates
                & ((values & values.dtype.type(above)) == (prefix[seg] & values.dtype.type(above)))
                & (((values >> b) & 1) == 0)
            )
            cnt = count_seg(cand0)
            take1 = krem >= cnt
            prefix = jnp.where(take1, prefix | values.dtype.type(1 << b), prefix)
            krem = jnp.where(take1, krem - cnt, krem)
        return prefix, krem

    med_key, krem = select(31, key_u32, valid, k_by_seg)
    # rank among exact key ties, by id (ids are unique, >= 0, < 2^31)
    tie_u = tie_i32.astype(jnp.uint32)
    eq = valid & (key_u32 == med_key[seg])
    med_tie, _ = select(30, tie_u, eq, krem)
    return med_key, med_tie.astype(jnp.int32)


def _mirror_exchange(pts, gid, ship, keep, cap: int, half: int,
                     axis_name: str, p_total: int):
    """Send ``ship``-marked rows to device ``p ^ half`` in one ppermute;
    merge ``keep`` rows + received into a same-width buffer. Returns
    (pts, gid, overflow) where overflow counts rows dropped by EITHER the
    ship buffer cap or the merge width — both detected, never silent."""
    W, d = pts.shape

    # pack shipped rows into [cap]
    ship_rank = jnp.cumsum(ship.astype(jnp.int32)) - 1
    over_ship = jnp.sum((ship & (ship_rank >= cap)).astype(jnp.int32))
    slot = jnp.where(ship & (ship_rank < cap), ship_rank, cap)
    send_pts = jnp.full((cap + 1, d), jnp.inf, pts.dtype).at[slot].set(
        jnp.where(ship[:, None], pts, jnp.inf), mode="drop"
    )[:cap]
    send_gid = jnp.full((cap + 1,), -1, jnp.int32).at[slot].set(
        jnp.where(ship, gid, -1), mode="drop"
    )[:cap]

    perm = [(i, i ^ half) for i in range(p_total)]
    recv_pts = lax.ppermute(send_pts, axis_name, perm)
    recv_gid = lax.ppermute(send_gid, axis_name, perm)

    # survivors first (stable), then received; compact back to width W
    all_pts = jnp.concatenate([jnp.where(keep[:, None], pts, jnp.inf), recv_pts])
    all_gid = jnp.concatenate([jnp.where(keep, gid, -1), recv_gid])
    order = jnp.argsort(jnp.where(all_gid < 0, 1, 0), stable=True)
    n_valid = jnp.sum((all_gid >= 0).astype(jnp.int32))
    over_merge = jnp.maximum(n_valid - W, 0)
    pts2 = all_pts[order][:W]
    gid2 = all_gid[order][:W]
    overflow = lax.psum(over_ship + over_merge, axis_name)
    return pts2, gid2, overflow


@jax.tree_util.register_pytree_node_class
class GlobalExactTree:
    """The scalable exact-median tree: a replicated 2P-node top heap (true
    global medians) over P chip-local classic k-d trees.

    Stacked leading-device-axis arrays (sharded in live use; dense after a
    checkpoint load): local_pts/node_point/split_val are the per-device
    ``KDTree`` columns, local_gid maps local rows to global point ids.
    """

    def __init__(self, top_pts, top_gid, local_pts, local_node, local_split,
                 local_gid, num_points, seed):
        self.top_pts = top_pts        # [Htop, D] node coordinates (inf if absent)
        self.top_gid = top_gid        # [Htop] global ids (-1 if absent)
        self.local_pts = local_pts    # [P, W, D]
        self.local_node = local_node  # [P, H]
        self.local_split = local_split  # [P, H]
        self.local_gid = local_gid    # [P, W]
        self.num_points = num_points
        self.seed = seed

    @property
    def devices(self) -> int:
        return self.local_pts.shape[0]

    @property
    def dim(self) -> int:
        return self.local_pts.shape[2]

    @property
    def n_real(self) -> int:
        return self.num_points

    def tree_flatten(self):
        return (
            (self.top_pts, self.top_gid, self.local_pts, self.local_node,
             self.local_split, self.local_gid),
            (self.num_points, self.seed),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    def __repr__(self):
        return (
            f"GlobalExactTree(n={self.num_points}, devices={self.devices}, "
            f"dim={self.dim})"
        )


def _build_local_body(start, seed, structure, *, dim, rows, width, num_points,
                      p, cap, htop, num_levels, axis_name, med_ks,
                      distribution):
    """SPMD body: generate own rows -> L levels of (select median, mirror
    exchange) -> local classic build."""
    from .global_morton import _gen_shard

    L = p.bit_length() - 1
    W = width
    # generate this device's `rows` real rows into a W-wide work buffer:
    # the extra width is headroom for exchange-occupancy fluctuation
    # (binomial ~sqrt(rows) per level), never real data
    pts = _gen_shard(distribution, seed[0], dim, start[0], W)
    # kdt-lint: disable=KDT101 per-shard SPMD body traced under shard_map;
    # num_points is guarded at the build_global_exact entry
    gid = (start[0] + jnp.arange(W)).astype(jnp.int32)
    valid0 = (jnp.arange(W) < rows) & (gid < num_points)
    pts = jnp.where(valid0[:, None], pts, jnp.inf)
    gid = jnp.where(valid0, gid, -1)

    rank = lax.axis_index(axis_name)
    top_pts = jnp.full((htop, dim), jnp.inf, pts.dtype)
    top_gid = jnp.full((htop,), -1, jnp.int32)
    overflow = jnp.int32(0)

    for lvl in range(L):
        S = 1 << lvl
        seg = rank >> (L - lvl)  # high bits of rank = segment in position order
        axis = lvl % dim
        k_by_seg = jnp.asarray(med_ks[lvl], jnp.int32)  # [S] static medians
        key = _f32_key(pts[:, axis])
        valid = gid >= 0
        med_key, med_gid = _radix_select(
            key, gid, valid, seg, k_by_seg, S, axis_name
        )
        # emit this level's nodes into the replicated top heap (the median
        # row exists on exactly ONE device; everyone else contributes zeros,
        # so the psum lands each node's coords/gid exactly once)
        is_med = valid & (key == med_key[seg]) & (gid == med_gid[seg])
        node = (S - 1) + seg  # heap id: level-order complete numbering
        contrib_p = jnp.where(is_med[:, None], pts, 0.0).sum(axis=0)  # [D]
        contrib_g = jnp.where(is_med, gid + 1, 0).sum()
        tp = lax.psum(jnp.zeros((htop, dim), pts.dtype).at[node].set(contrib_p),
                      axis_name)
        tg = lax.psum(jnp.zeros((htop,), jnp.int32).at[node].set(contrib_g),
                      axis_name)
        top_pts = jnp.where((tg > 0)[:, None], tp, top_pts)
        top_gid = jnp.where(tg > 0, tg - 1, top_gid)

        # classify against (med_key, med_gid), lexicographic; the consumed
        # median is neither kept nor shipped — it lives in the top heap now
        mk, mg = med_key[seg], med_gid[seg]
        left = valid & ((key < mk) | ((key == mk) & (gid < mg)))
        right = valid & ~left & ~is_med
        half = 1 << (L - lvl - 1)  # device-distance to the mirror half
        in_left_half = (rank & half) == 0
        ship = jnp.where(in_left_half, right, left)
        keep = valid & ~ship & ~is_med
        pts, gid, ov = _mirror_exchange(
            pts, gid, ship, keep, cap, half, axis_name, p
        )
        overflow = overflow + ov

    tree = build_impl(pts, *structure, num_levels=num_levels)
    return (
        top_pts,
        top_gid,
        tree.points[None],
        tree.node_point[None],
        tree.split_val[None],
        gid[None],
        overflow[None],
    )


# kdt-lint: disable=KDT102 exercised vs the oracle on legacy jax in tier-1
# (test_global_exact); the 0.4.x miscompile is specific to the fused
# ensemble build+query program — see parallel/ensemble.py:_FUSED_JIT_SAFE
@functools.partial(
    jax.jit,
    static_argnames=("mesh", "dim", "rows", "width", "num_points", "cap",
                     "htop", "num_levels", "distribution"),
)
def _build_jit(starts, seed, structure, mesh, dim, rows, width, num_points,
               cap, htop, num_levels, distribution):
    p = mesh.shape[SHARD_AXIS]
    med_ks = tuple(
        tuple(c // 2 for c in sizes) for sizes in _top_layout(num_points, p)
    )
    fn = shard_map(
        functools.partial(
            _build_local_body,
            dim=dim, rows=rows, width=width, num_points=num_points, p=p,
            cap=cap, htop=htop, num_levels=num_levels, axis_name=SHARD_AXIS,
            med_ks=med_ks, distribution=distribution,
        ),
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(None), P(None)),
        out_specs=(
            P(None, None), P(None), P(SHARD_AXIS), P(SHARD_AXIS),
            P(SHARD_AXIS), P(SHARD_AXIS), P(None),
        ),
        check_vma=False,
    )
    return fn(starts, seed, structure)


def build_global_exact(
    seed: int,
    dim: int,
    num_points: int,
    mesh: Mesh | None = None,
    slack: float = DEFAULT_SLACK,
    distribution: str = "uniform",
) -> GlobalExactTree:
    """Build the scalable exact-median global tree; generative (shard-local
    row generation, no [N, D] anywhere). P must be a power of two.
    ``distribution`` selects the row stream ("uniform" | "clustered"
    Gaussian mixture) — exact medians keep the partition perfectly balanced
    either way; what skew stresses is the mirror-exchange occupancy.

    Raises RuntimeError on mirror-exchange capacity overflow (heavily
    skewed data; retry with higher ``slack``).
    """
    check_rows_fit_i32(num_points, "generative problem")
    if mesh is None:
        from .mesh import make_mesh

        mesh = make_mesh()
    p = mesh.shape[SHARD_AXIS]
    if p & (p - 1):
        raise ValueError(f"global-exact needs a power-of-2 device count, got {p}")
    rows = -(-num_points // p)
    # work width: per-device occupancy after an exchange is mean `rows` with
    # ~sqrt(rows) binomial fluctuation per level — give it ~5-sigma headroom
    # (tail cases are detected as overflow and retried with higher slack)
    width = rows + max(16, int(4 * rows ** 0.5 * max(slack / DEFAULT_SLACK, 1.0)))
    cap = max(1, min(width, int(width / 2 * slack)))
    htop = max(p - 1, 1)
    structure = spec_arrays(width, dim)
    num_levels = tree_spec(width).num_levels
    starts = jnp.asarray([i * rows for i in range(p)], jnp.int32)
    (top_pts, top_gid, lpts, lnode, lsplit, lgid, overflow) = _build_jit(
        starts, jnp.asarray([seed], jnp.int32), structure, mesh, dim, rows,
        width, num_points, cap, htop, num_levels, distribution,
    )
    ov = int(overflow[0])  # kdt-lint: disable=KDT201 build-time exactness gate: the overflow count must be read to refuse a partial index
    if ov > 0:
        raise RuntimeError(
            f"mirror-exchange capacity overflow ({ov} rows); "
            f"retry with slack > {slack}"
        )
    obs.count_build("global-exact", num_points)
    return GlobalExactTree(
        top_pts, top_gid, lpts, lnode, lsplit, lgid,
        num_points=num_points, seed=seed,
    )


def _fold_top(md, mi, top_pts, top_gid, queries, k: int):
    """Fold the top-heap node points (which live in no local tree) into
    merged (d2, id) buffers: dense distances to the tiny [Htop] heap, then
    one more top-k + the framework-standard stable (distance, id) sort."""
    diff = queries[:, None, :] - top_pts[None]  # [Q, Htop, D]
    td2 = jnp.sum(diff * diff, axis=-1)
    td2 = jnp.where((top_gid >= 0)[None, :], td2, jnp.inf)
    cat_d = jnp.concatenate([md, td2], axis=1)
    cat_i = jnp.concatenate(
        [mi, jnp.broadcast_to(top_gid[None], td2.shape)], axis=1
    )
    kk = min(k, cat_d.shape[1])
    neg, sel = lax.top_k(-cat_d, kk)
    return lax.sort((-neg, jnp.take_along_axis(cat_i, sel, axis=1)),
                    num_keys=2, is_stable=True)


def _query_local_body(top_pts, top_gid, lpts, lnode, lsplit, lgid, queries,
                      *, k, num_levels, axis_name):
    d2, li = _knn_batch(lnode[0], lpts[0], queries, k, num_levels)
    gi = jnp.where(li >= 0, lgid[0][jnp.maximum(li, 0)], -1)
    d2 = jnp.where(gi >= 0, d2, jnp.inf)
    all_d = lax.all_gather(d2, axis_name)  # [P, Q, k]
    all_i = lax.all_gather(gi, axis_name)
    md, mi = _merge_partials(all_d, all_i, k)
    return _fold_top(md, mi, top_pts, top_gid, queries, k)


# kdt-lint: disable=KDT102 exercised vs the oracle on legacy jax in tier-1
# (test_global_exact); the miscompile is specific to the fused ensemble
# build+query program — see parallel/ensemble.py:_FUSED_JIT_SAFE
@functools.partial(jax.jit, static_argnames=("mesh", "k", "num_levels"))
def _query_jit(tree_arrays, queries, mesh, k, num_levels):
    fn = shard_map(
        functools.partial(
            _query_local_body, k=k, num_levels=num_levels,
            axis_name=SHARD_AXIS,
        ),
        mesh=mesh,
        in_specs=(
            P(None, None), P(None), P(SHARD_AXIS), P(SHARD_AXIS),
            P(SHARD_AXIS), P(SHARD_AXIS), P(None, None),
        ),
        out_specs=(P(None, None), P(None, None)),
        check_vma=False,
    )
    return fn(*tree_arrays, queries)


@functools.partial(jax.jit, static_argnames=("k", "num_levels"))
def _query_meshfree_jit(top_pts, top_gid, lpts, lnode, lsplit, lgid, queries,
                        k, num_levels):
    """vmap-over-devices query for a checkpointed tree on other hardware."""

    def one_device(pts_, node_, gid_):
        d2, li = _knn_batch(node_, pts_, queries, k, num_levels)
        gi = jnp.where(li >= 0, gid_[jnp.maximum(li, 0)], -1)
        return jnp.where(gi >= 0, d2, jnp.inf), gi

    all_d, all_i = jax.vmap(one_device)(lpts, lnode, lgid)
    md, mi = _merge_partials(all_d, all_i, k)
    return _fold_top(md, mi, top_pts, top_gid, queries, k)


def _exact_to_forest(tree: GlobalExactTree, bucket_cap: int = 128):
    """One-time view of the exact-median tree as a GlobalMortonForest (the
    top-heap medians excepted — they live in no local tree and are folded
    separately). Cached on the tree object: dense serving pays one local
    sort per device once, then every batch uses the tiled engine."""
    from .global_morton import GlobalMortonForest

    forest = getattr(tree, "_forest_cache", None)
    if forest is not None:
        return forest
    from kdtree_tpu.ops.morton import check_build_capacity, default_bits

    # The conversion materializes a second copy of every local row set
    # (bucket_pts + gids + AABB heaps). On a matching mesh each device only
    # sorts its own rows; mesh-free (single-chip checkpoint serving) ALL
    # device slices land on one chip — exactly the compile-crash shape the
    # HBM guard exists for. Size the check by rows-per-physical-device.
    p, rows = tree.local_pts.shape[:2]
    try:
        ndev = max(1, len(tree.local_pts.devices()))
    except Exception:
        ndev = 1
    check_build_capacity(-((p * rows) // -ndev), tree.dim)
    bits = default_bits(tree.dim)
    # the shared no-exchange local-build map (vmap over the device axis —
    # with mesh-sharded inputs XLA keeps the sorts where the rows live);
    # occ rides along so tile planning sees the real density (r4 weak #6)
    from .global_morton import _local_forest_jit

    nl, nh, bp, bg, occ = _local_forest_jit(tree.local_pts, tree.local_gid,
                                            bucket_cap, bits)
    occ_max = int(jnp.max(occ))  # kdt-lint: disable=KDT201 one scalar fetch per tree at view-build time; occ_max is a STATIC planning fact
    forest = GlobalMortonForest(
        nl, nh, bp, bg, num_points=tree.num_points, seed=tree.seed,
        bucket_cap=bucket_cap, bits=bits, occ_max=occ_max,
    )
    tree._forest_cache = forest
    return forest


@functools.partial(jax.jit, static_argnames=("k",))
def _fold_top_jit(md, mi, top_pts, top_gid, queries, k):
    return _fold_top(md, mi, top_pts, top_gid, queries, k)


def global_exact_query_tiled(
    tree: GlobalExactTree,
    queries: jax.Array,
    k: int = 1,
    mesh: Mesh | None = None,
) -> Tuple[jax.Array, jax.Array]:
    """Big-Q serving path for the exact-median tree: per-device Morton
    views (built once, cached) served by the tiled engine — SPMD under a
    matching mesh, sequential otherwise — plus one dense fold over the
    top-heap medians. Exact: local trees + top heap partition the point
    set. Supersedes the per-query DFS at dense low-D shapes (VERDICT r3
    missing #1 covered for BOTH global engines)."""
    from .global_morton import global_morton_query_tiled

    k = min(k, tree.num_points)
    forest = _exact_to_forest(tree)
    md, mi = global_morton_query_tiled(forest, queries, k=k, mesh=mesh)
    return _fold_top_jit(md, mi, tree.top_pts, tree.top_gid, queries, k)


def global_exact_query(
    tree: GlobalExactTree,
    queries: jax.Array,
    k: int = 1,
    mesh: Mesh | None = None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact k-NN against the scalable exact-median tree. Falls back to a
    mesh-free vmap query when the hardware doesn't match ``tree.devices``
    (checkpoint portability); dense low-D batches route to the tiled
    serving path (the framework's measured crossover). Returns
    (d2 f32[Q, k], ids i32[Q, k])."""
    from kdtree_tpu.ops.tile_query import dense_lowd

    rows = tree.local_pts.shape[1]
    num_levels = tree_spec(rows).num_levels
    k = min(k, tree.num_points)
    if not obs.is_tracer(queries):
        from .global_morton import _count_sharded_query

        _count_sharded_query("global-exact", queries.shape[0], tree.devices)
    if mesh is None and len(jax.devices()) >= tree.devices:
        from .mesh import make_mesh

        mesh = make_mesh(tree.devices)
    if dense_lowd(queries.shape[0], tree.num_points, tree.dim):
        from kdtree_tpu.ops.morton import BuildCapacityError

        try:
            return global_exact_query_tiled(tree, queries, k=k, mesh=mesh)
        except BuildCapacityError:
            # the forest view of this tree won't fit the local chip(s)
            # (mesh-free serving of a big checkpoint): the DFS path below
            # queries the exact tree in place without materializing a
            # second copy — slower per query, but it completes
            pass
    if mesh is not None and mesh.shape[SHARD_AXIS] == tree.devices:
        return _query_jit(
            (tree.top_pts, tree.top_gid, tree.local_pts, tree.local_node,
             tree.local_split, tree.local_gid),
            queries, mesh, k, num_levels,
        )
    return _query_meshfree_jit(
        tree.top_pts, tree.top_gid, tree.local_pts, tree.local_node,
        tree.local_split, tree.local_gid, queries, k, num_levels,
    )


def global_exact_knn(
    seed: int,
    dim: int,
    num_points: int,
    queries: jax.Array,
    k: int = 1,
    mesh: Mesh | None = None,
    slack: float = DEFAULT_SLACK,
) -> Tuple[jax.Array, jax.Array]:
    """Build + query in one call (generative, like ``global_morton_knn``)."""
    if mesh is None:
        from .mesh import make_mesh

        mesh = make_mesh()
    tree = build_global_exact(seed, dim, num_points, mesh=mesh, slack=slack)
    return global_exact_query(tree, queries, k=k, mesh=mesh)
