"""Device-mesh helpers.

The reference's distributed substrate is MPI (``MPI_Init``/``Bcast``/``Reduce``
over ranks, ``kdtree_mpi.cpp:177-199,253``). Here the substrate is a
``jax.sharding.Mesh``: ranks become mesh axis positions, the Bcast becomes
replication, and reductions become XLA collectives riding ICI/DCN. Tests fake a
pod with ``--xla_force_host_platform_device_count`` — the analog of the
reference's ``mpirun --oversubscribe`` (``Makefile:36``).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

SHARD_AXIS = "shards"


def make_mesh(num_devices: int | None = None, axis: str = SHARD_AXIS) -> Mesh:
    """1-D mesh over the first ``num_devices`` devices (default: all)."""
    devs = jax.devices()
    if num_devices is not None:
        if num_devices > len(devs):
            raise ValueError(f"requested {num_devices} devices, have {len(devs)}")
        devs = devs[:num_devices]
    return Mesh(np.array(devs), (axis,))


def shard_map(f, *, mesh: Mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``shard_map``: newer jax exposes it as
    ``jax.shard_map`` with a ``check_vma`` kwarg; older releases (e.g. the
    0.4.x line) only have ``jax.experimental.shard_map.shard_map`` with
    the same check under its previous name ``check_rep``. Every SPMD
    entry point in :mod:`kdtree_tpu.parallel` routes through here so the
    framework runs on both without 11 scattered version checks."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )
