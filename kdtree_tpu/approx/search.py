"""Bounded-visit approximate k-NN: a cap on the exact candidate ranking.

The exact tiled query (:mod:`kdtree_tpu.ops.tile_query`) already does
the hard part of best-bin-first search: its collect pass ranks every
candidate bucket lb-ascending per tile, and its dense scan consumes
that ranking front-to-back behind an early exit. The approximate mode
is therefore a **truncation**, not a new algorithm: scan only the
``visit_cap`` nearest buckets and stop. Three properties fall out of
reusing the exact machinery verbatim:

- **monotone recall**: truncations of one fixed ranking are nested
  (the cap-M bucket set is a subset of the cap-M' set for M' > M), so
  growing the cap can only add candidates — recall@k never decreases
  (property-tested, tests/test_approx.py);
- **exactness at full cap**: a cap at least as wide as the collected
  list makes the truncation a no-op — the program is the exact program,
  byte for byte (test-pinned across shapes);
- **the per-answer distances stay true**: an approximate answer is the
  exact top-k over the visited points — distances are never estimated,
  only the candidate set is bounded. What approximation costs is
  *membership* (a true neighbor in an unvisited bucket), which is
  exactly what recall@k measures.

``resolve_visit_cap`` maps a ``recall_target`` to a cap: from the
plan-store calibration the recall harness persisted when one exists
(measured on this problem signature, :mod:`kdtree_tpu.approx.recall`),
from a conservative fraction-of-buckets heuristic otherwise. Both are
advisory — a wrong cap costs recall (visible on the ``kdtree_recall*``
gauges and the recall SLO), never a crash or a silently-wrong exact
answer.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

from kdtree_tpu import obs

# the calibration grid the harness measures and serving resolves
# against; ascending, so "smallest calibrated target covering the
# request" is a scan
DEFAULT_TARGETS = (0.5, 0.75, 0.9, 0.95, 0.99)

# uncalibrated fallback: fraction of the bucket count visited per
# recall band. Deliberately conservative (recall misses cost answers,
# visits only cost time) — the harness's measured calibration replaces
# this with much smaller caps on real shapes (docs/SERVING.md
# "Degradation ladder", calibration trust model).
_HEURISTIC_FRACS = (
    (0.99, 0.5),
    (0.95, 0.33),
    (0.9, 0.25),
    (0.0, 0.125),
)
_MIN_VISIT = 2

# the wire contract's rejection text — shared by every validator so the
# shard server and the router front cannot drift apart
RECALL_TARGET_ERROR = "recall_target must be a number in (0, 1]"


def parse_recall_target(raw) -> Tuple[bool, Optional[float]]:
    """Validate one wire ``recall_target`` value: ``(ok, normalized)``.
    ``ok`` False means reject with :data:`RECALL_TARGET_ERROR`;
    ``normalized`` is None for absent / 1.0 (both spell exact), the
    float target otherwise. ONE implementation — the shard server and
    the router validate through here, so a change to the accepted
    range can never make the router 400 requests the shards accept."""
    if raw is None:
        return True, None
    if not isinstance(raw, (int, float)) or isinstance(raw, bool) or \
            not (0.0 < raw <= 1.0):
        return False, None
    target = float(raw)
    return True, None if target >= 1.0 else target


def _min_cap_for_k(k: int, bucket_size: int) -> int:
    """Visiting fewer than ceil(k / B) buckets cannot even produce k
    real candidates; one extra bucket keeps the k-th slot contested."""
    return max(_MIN_VISIT, -(-int(k) // max(int(bucket_size), 1)) + 1)


def _calibrated_cap(recall_caps: dict, target: float) -> Optional[int]:
    """The smallest calibrated cap whose measured target covers the
    requested one, or None when no calibrated entry is >= target.
    ``recall_caps`` is the store's ``{"0.99": 12, ...}`` mapping —
    string keys (JSON) with positive-int values; anything malformed
    reads as absent, same advisory contract as plan profiles."""
    best: Optional[int] = None
    for raw_t, raw_cap in (recall_caps or {}).items():
        try:
            t, cap = float(raw_t), int(raw_cap)
        except (TypeError, ValueError):
            continue
        if isinstance(raw_cap, bool) or cap < 1 or t < float(target):
            continue
        if best is None or cap < best:
            best = cap
    return best


def resolve_visit_cap(
    recall_target: Optional[float],
    nbp: int,
    k: int,
    bucket_size: int,
    sig=None,
    profile: Optional[dict] = None,
) -> Optional[int]:
    """The visit cap serving a ``recall_target`` — None means exact.

    Resolution order: an explicit ``profile`` (or the plan-store
    profile for ``sig``) with a ``recall_caps`` calibration wins; the
    documented fraction-of-buckets heuristic otherwise. ``None`` and
    targets >= 1.0 resolve to exact (the default contract); the result
    is always clamped so at least k real candidates are reachable and
    never exceeds the bucket count (where it equals exact anyway)."""
    if recall_target is None or float(recall_target) >= 1.0:
        return None
    target = float(recall_target)
    nbp = int(nbp)
    if profile is None and sig is not None:
        from kdtree_tpu import tuning

        profile = tuning.profile_for(sig)
    cap = None
    if isinstance(profile, dict):
        cap = _calibrated_cap(profile.get("recall_caps"), target)
    if cap is None:
        frac = _HEURISTIC_FRACS[-1][1]
        for floor, f in _HEURISTIC_FRACS:
            if target >= floor:
                frac = f
                break
        cap = int(math.ceil(nbp * frac))
    cap = max(cap, _min_cap_for_k(k, bucket_size))
    if cap >= nbp:
        return None  # visiting everything IS the exact path
    return cap


def morton_knn_approx(
    tree,
    queries,
    k: int = 1,
    visit_cap: Optional[int] = None,
    recall_target: Optional[float] = None,
    plan=None,
) -> Tuple[object, object]:
    """Approximate k-NN over a Morton tree: the tiled engine with its
    dense scan bounded to the ``visit_cap`` nearest candidate buckets
    per tile. Same signature contract as
    :func:`~kdtree_tpu.ops.tile_query.morton_knn_tiled` (d2 f32[Q, k],
    ids i32[Q, k], ascending; answers exact over the visited points).

    Exactly one of ``visit_cap`` / ``recall_target`` bounds the visit:
    an explicit cap wins; a target resolves through
    :func:`resolve_visit_cap` (calibration, then heuristic). Both
    ``None`` — or a cap/target that resolves to the full bucket count —
    run the exact path unchanged."""
    from kdtree_tpu.ops.tile_query import morton_knn_tiled

    if visit_cap is None and recall_target is not None:
        visit_cap = resolve_visit_cap(
            recall_target, tree.num_buckets, k, tree.bucket_size,
        )
    if visit_cap is not None:
        visit_cap = min(max(int(visit_cap), 1), int(tree.num_buckets))
        obs.get_registry().gauge("kdtree_approx_visit_cap").set(visit_cap)
        if visit_cap >= int(tree.num_buckets):
            visit_cap = None
    return morton_knn_tiled(tree, queries, k=k, plan=plan,
                            visit_cap=visit_cap)
