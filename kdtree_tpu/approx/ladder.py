"""The serving degradation ladder: gears between exact and the cliff.

Before this module, overload had two gears: exact-warm or
exact-brute-force-degraded (the PR 4 deadline path). Production ANN
systems put a *dial* between them — trade recall for latency, a gear at
a time — and this ladder is that dial wired to the PR 7 burn-rate
engine:

    exact → approx(0.99) → approx(0.9) → brute-force-deadline

The controller is deliberately boring and deterministic (the SLO
engine's own discipline): it reads the watched SLOs' states on every
history-sampler tick, steps DOWN one gear after ``down_after``
consecutive PAGE ticks, and climbs UP one gear after ``up_after``
consecutive all-OK ticks — hysteresis on both edges, so a flapping
burn cannot saw the gear. Every transition is flight-recorded
(``ladder.shift``), counted
(``kdtree_recall_ladder_transitions_total``), and exported as the
``kdtree_recall_gear`` gauge, with the gear's recall estimate on
``kdtree_recall_estimate`` — the gauge the recall SLO watches, so a
ladder stuck below its floor pages like any other burn.

The last gear, ``brute-deadline``, answers every request through the
proven exact brute-force path (flagged degraded) — immune to
batch-shape compiles, the PR 4 behavior as the FLOOR of the ladder
instead of its only step. Recall there is 1.0 again: the ladder trades
latency differently per gear, and the estimate gauge says so honestly.

Tests drive the ladder deterministically through the PR 9 fault layer
(a ``batch=latency`` clause inflates the dispatch histogram the
watched p99 SLO reads) or by ticking a synthetic SLO engine directly
(docs/SERVING.md "Degradation ladder").
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple

from kdtree_tpu import obs
from kdtree_tpu.analysis import lockwatch
from kdtree_tpu.obs import flight
from kdtree_tpu.obs.slo import PAGE


class GearSpec(NamedTuple):
    """One ladder gear. ``recall_target`` None = exact candidate set;
    ``brute`` routes dispatch through the exact brute-force fallback.
    ``recall_estimate`` is the gauge value exported while the gear is
    engaged — the gear's PROMISE, replaced by the measured calibration
    value when one exists (see ``DegradationLadder.engaged``)."""

    name: str
    recall_target: Optional[float]
    recall_estimate: float
    brute: bool = False


GEARS: Tuple[GearSpec, ...] = (
    GearSpec("exact", None, 1.0),
    GearSpec("approx-0.99", 0.99, 0.99),
    GearSpec("approx-0.9", 0.9, 0.9),
    GearSpec("brute-deadline", None, 1.0, brute=True),
)

# watched SLOs: the serving burn signals that mean "shed or slow" —
# the two failure shapes a recall gear can actually relieve
DEFAULT_WATCH = ("request-p99-latency", "shed-rate")
DEFAULT_DOWN_AFTER = 2   # consecutive PAGE ticks before a downshift
DEFAULT_UP_AFTER = 5     # consecutive OK ticks before an upshift


def gear_token(spec: GearSpec) -> Optional[str]:
    """The wire token a response's ``gear`` field carries for this
    gear: None for exact (absent field), ``approx:<target>`` /
    ``brute-deadline`` otherwise. One definition — the server, the
    router merge, and the loadgen classifier all read this format."""
    if spec.brute:
        return "brute-deadline"
    if spec.recall_target is not None:
        return f"approx:{spec.recall_target:g}"
    return None


class DegradationLadder:
    """The gear state machine. ``tick()`` runs on the history-sampler
    tick (after the SLO engine evaluated); readers (``gear()``,
    ``spec()``) are lock-cheap — the batcher consults them per batch."""

    def __init__(
        self,
        slo_engine=None,
        gears: Sequence[GearSpec] = GEARS,
        watch: Sequence[str] = DEFAULT_WATCH,
        down_after: int = DEFAULT_DOWN_AFTER,
        up_after: int = DEFAULT_UP_AFTER,
        enabled: bool = True,
    ) -> None:
        if not gears:
            raise ValueError("ladder needs at least one gear")
        self.slo_engine = slo_engine
        self.gears = tuple(gears)
        self.watch = tuple(watch)
        self.down_after = max(int(down_after), 1)
        self.up_after = max(int(up_after), 1)
        self.enabled = bool(enabled)
        self._lock = lockwatch.make_lock("approx.ladder")
        self._gear = 0
        self._page_streak = 0
        self._ok_streak = 0
        reg = obs.get_registry()
        self._g_gear = reg.gauge("kdtree_recall_gear")
        self._g_estimate = reg.gauge("kdtree_recall_estimate")
        self._g_gear.set(0)
        self._g_estimate.set(self.gears[0].recall_estimate)

    # -- readers -------------------------------------------------------------

    def gear(self) -> int:
        with self._lock:
            return self._gear

    def spec(self) -> GearSpec:
        with self._lock:
            return self.gears[self._gear]

    def engaged(self, recall_estimate: Optional[float] = None) -> None:
        """Report the recall estimate the CURRENT gear actually serves
        — the batcher calls this (for LADDER-forced batches only) with
        the measured calibration value when the engine resolved one,
        so the recall SLO watches measurement, not promise."""
        if recall_estimate is not None and self.enabled:
            self._g_estimate.set(float(recall_estimate))

    # -- the controller ------------------------------------------------------

    def _burning(self) -> bool:
        if self.slo_engine is None:
            return False
        states = self.slo_engine.states()
        return any(states.get(name, 0) == PAGE for name in self.watch)

    def tick(self, burning: Optional[bool] = None) -> int:
        """One controller step; returns the (possibly new) gear index.
        ``burning`` overrides the SLO read for deterministic tests.
        Never raises — it runs on the sampler thread of a live server."""
        if not self.enabled:
            return 0
        try:
            burn = self._burning() if burning is None else bool(burning)
        except Exception:
            return self.gear()
        shift = None
        with self._lock:
            if burn:
                self._page_streak += 1
                self._ok_streak = 0
                if (self._page_streak >= self.down_after
                        and self._gear < len(self.gears) - 1):
                    shift = (self._gear, self._gear + 1, "burn")
                    self._gear += 1
                    self._page_streak = 0
            else:
                self._ok_streak += 1
                self._page_streak = 0
                if self._ok_streak >= self.up_after and self._gear > 0:
                    # climb back ONE gear per quiet period: recovery is
                    # gradual on purpose — jumping straight to exact
                    # after a burn re-offers the full load that caused it
                    shift = (self._gear, self._gear - 1, "recovered")
                    self._gear -= 1
                    self._ok_streak = 0
            gear = self._gear
        if shift is not None:
            self._report(*shift)
        return gear

    def _report(self, old: int, new: int, reason: str) -> None:
        old_spec, new_spec = self.gears[old], self.gears[new]
        self._g_gear.set(new)
        self._g_estimate.set(new_spec.recall_estimate)
        reg = obs.get_registry()
        reg.counter(
            "kdtree_recall_ladder_transitions_total",
            labels={"to": new_spec.name},
        ).inc()
        flight.record(
            "ladder.shift", previous=old_spec.name, to=new_spec.name,
            reason=reason, gear=new,
        )
        if new > old:
            # a downshift IS an incident artifact: the ring dump carries
            # the burn that caused it (rate-limited per reason, like
            # every auto dump)
            flight.auto_dump("ladder-downshift")
