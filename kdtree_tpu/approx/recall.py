"""The recall harness: measure the recall dial against the exact oracle.

This repository's rare property (ROADMAP direction 1) is that the exact
answer is always computable — so recall@k is a *measurement*, never an
estimate. The harness sweeps a ladder of visit caps over one seeded
problem, answers each cap with the bounded-visit engine
(:mod:`kdtree_tpu.approx.search`) and the full cap with the exact tiled
engine, and reports per cap:

- **recall@k** — the fraction of the oracle's true top-k ids the
  bounded answer found (padding-aware; deterministic for a seeded
  problem, which is what lets CI gate on it);
- **q/s and speedup** — warmup-excluded timed runs, the same
  discipline as ``kdtree-tpu tune`` (compile + cap settling outside
  the clock).

Two artifacts come out of a sweep:

- the **curve** (the sidecar ``recall`` block, RECALL_VERSION-stamped):
  ``kdtree-tpu trend`` compares it across rounds and flags a
  ``recall-drop`` exactly like a throughput drop — a tree-layout change
  that silently tanks the dial's quality fails CI;
- the **calibration** (``recall_caps``: recall_target → smallest cap
  measured to reach it), persisted into the PR 2 plan store under the
  problem's plan signature. Serving resolves per-request
  ``recall_target`` through it. Advisory, like every profile: a stale
  calibration costs recall (watched by the recall SLO) or speed, never
  exactness — requests without a target never consult it.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from kdtree_tpu import obs
from kdtree_tpu.approx.search import DEFAULT_TARGETS

RECALL_VERSION = 1


def recall_at_k(approx_ids, exact_ids) -> float:
    """Mean per-query recall@k: |approx ∩ oracle| / |oracle real ids|.

    Both arguments are [Q, k] id arrays with the engines' -1 padding;
    padding ids never count as members on either side, and a query whose
    oracle row is all padding (k > n) contributes recall 1.0 — there was
    nothing to find."""
    a = np.asarray(approx_ids)
    e = np.asarray(exact_ids)
    if a.shape != e.shape:
        raise ValueError(
            f"approx ids {a.shape} and oracle ids {e.shape} must match"
        )
    total = 0.0
    rows = a.shape[0]
    for qi in range(rows):
        truth = set(int(x) for x in e[qi] if x >= 0)
        if not truth:
            total += 1.0
            continue
        found = set(int(x) for x in a[qi] if x >= 0)
        total += len(truth & found) / len(truth)
    return total / max(rows, 1)


def default_caps(nbp: int) -> List[int]:
    """The sweep ladder: powers of two up to (and including) the bucket
    count — the full-cap point is what pins recall 1.0 / byte-identity."""
    caps = []
    c = 2
    while c < int(nbp):
        caps.append(c)
        c *= 2
    caps.append(int(nbp))
    return caps


def _timed(tree, queries, k: int, visit_cap: Optional[int], plan):
    """Warmup + one timed run (the tuner's measurement discipline);
    returns (seconds, d2, ids) of the timed pass."""
    from kdtree_tpu.ops.tile_query import morton_knn_tiled

    d2, ids = morton_knn_tiled(tree, queries, k=k, plan=plan,
                               visit_cap=visit_cap)
    obs.hard_sync([d2, ids])  # warmup: compile + cap settling
    t0 = time.perf_counter()
    d2, ids = morton_knn_tiled(tree, queries, k=k, plan=plan,
                               visit_cap=visit_cap)
    obs.hard_sync([d2, ids])
    return time.perf_counter() - t0, d2, ids


def sweep_recall(
    tree,
    queries,
    k: int,
    caps: Optional[Sequence[int]] = None,
    log=None,
) -> Dict:
    """Sweep ``caps`` (default: the pow2 ladder up to the bucket count)
    against the exact oracle; returns the sidecar ``recall`` block:
    ``{recall_version, n, q, k, nbp, exact_qps, curve: [{visit_cap,
    recall, qps, speedup, seconds}]}`` with the curve ascending in
    ``visit_cap``."""
    import jax

    from kdtree_tpu.ops.tile_query import plan_tiled

    Q, D = queries.shape
    nbp = int(tree.num_buckets)
    caps = sorted({min(max(int(c), 1), nbp)
                   for c in (caps or default_caps(nbp))})
    # ONE plan for every run: the sweep must compare visit caps, not
    # plan-store luck (a warm exact plan against heuristic approx plans
    # would skew every speedup). Explicit source => nothing recorded.
    plan = plan_tiled(Q, D, tree.n_real, nbp, tree.bucket_size, k,
                      tile=None, use_pallas=jax.default_backend() == "tpu")
    exact_s, _, exact_ids = _timed(tree, queries, k, None, plan)
    exact_ids = np.asarray(exact_ids)
    exact_qps = Q / exact_s if exact_s > 0 else None
    curve = []
    for cap in caps:
        dt, _, ids = _timed(tree, queries, k,
                            None if cap >= nbp else cap, plan)
        row = {
            "visit_cap": cap,
            "recall": round(recall_at_k(np.asarray(ids), exact_ids), 6),
            "seconds": round(dt, 6),
            "qps": round(Q / dt, 3) if dt > 0 else None,
            "speedup": round(exact_s / dt, 3) if dt > 0 else None,
        }
        curve.append(row)
        if log is not None:
            log(row)
    reg = obs.get_registry()
    reg.counter("kdtree_recall_sweeps_total").inc()
    return {
        "recall_version": RECALL_VERSION,
        "n": int(tree.n_real),
        "q": int(Q),
        "k": int(k),
        "nbp": nbp,
        "exact_qps": (round(exact_qps, 3)
                      if exact_qps is not None else None),
        "exact_seconds": round(exact_s, 6),
        "curve": curve,
    }


def calibrate_caps(
    curve: List[dict],
    targets: Sequence[float] = DEFAULT_TARGETS,
) -> Dict[str, int]:
    """recall_target → smallest measured cap reaching it. Targets no
    swept cap reached are omitted (resolution falls back to the
    heuristic there) — a calibration must never promise a recall the
    harness did not see."""
    out: Dict[str, int] = {}
    for target in targets:
        for row in sorted(curve, key=lambda r: r["visit_cap"]):
            if row["recall"] >= float(target):
                out[f"{float(target):g}"] = int(row["visit_cap"])
                break
    return out


def persist_calibration(
    tree, Q: int, D: int, k: int, block: Dict,
    targets: Sequence[float] = DEFAULT_TARGETS,
    store=None,
) -> Dict:
    """Write the sweep's calibration into the plan store (merge
    semantics — launch knobs a tuner settled there survive).

    Recorded under EVERY pow2 Q-bucket signature from the serving
    batcher's smallest bucket up to the sweep's own Q: serving
    resolves a request's target at its BATCH's plan signature, and a
    calibration keyed only by the harness's sweep width would be
    invisible to every micro-batch (the plan_keys_for warm-ladder
    idea, applied to calibration). Returns ``{"recall_caps": ...,
    "persisted": bool, "path": ...}``; disabled stores persist
    nothing, crisply."""
    from kdtree_tpu import tuning
    from kdtree_tpu.serve.batcher import MIN_BUCKET
    from kdtree_tpu.tuning.store import _pow2_ceil

    store = store if store is not None else tuning.default_store()
    caps = calibrate_caps(block["curve"], targets)
    top_sig = tuning.make_signature(Q, D, tree.n_real, k,
                                    tree.bucket_size, tree.num_buckets,
                                    devices=1)
    persisted = False
    if caps and store.enabled:
        # measured recall per calibrated cap rides along: serving's
        # recall-estimate gauge reports the MEASURED value for a gear,
        # so a miscalibrated dial burns the recall SLO instead of
        # silently claiming its target
        measured = {
            t: next((r["recall"] for r in block["curve"]
                     if r["visit_cap"] == cap), None)
            for t, cap in caps.items()
        }
        q = MIN_BUCKET
        buckets = []
        while q < _pow2_ceil(max(Q, 1)):
            buckets.append(q)
            q *= 2
        buckets.append(_pow2_ceil(max(Q, 1)))
        for q in buckets:
            sig = tuning.make_signature(q, D, tree.n_real, k,
                                        tree.bucket_size,
                                        tree.num_buckets, devices=1)
            if store.record(sig, recall_caps=caps,
                            recall_measured=measured):
                persisted = True
    return {
        "recall_caps": caps,
        "persisted": bool(persisted),
        "path": store.path_for(top_sig) if store.enabled else None,
        "signature": top_sig.key,
    }
