"""kdtree_tpu.approx — approximate k-NN with a measured recall dial.

The rest of this repository is exact by contract. This package is the
deliberate exception — and it exists precisely BECAUSE the exact oracle
is always available: every approximation here is *measured* against it,
never assumed (ROADMAP direction 1).

Three pieces:

- :mod:`~kdtree_tpu.approx.search` — bounded-visit / best-bin-first
  search over the bucketed Morton tree. The tile query already ranks
  every candidate bucket by box lower bound; the approximate mode is a
  **cap on that ranking** (scan only the ``visit_cap`` nearest buckets
  per tile), not a new traversal. Truncations of one fixed lb-ascending
  ranking are nested, so recall@k is monotone in ``visit_cap``, and the
  full cap is byte-identical to the exact engine (both test-pinned).
  ``resolve_visit_cap`` turns a ``recall_target`` into a cap — from a
  measured calibration in the plan store when one exists, from a
  conservative documented heuristic otherwise.
- :mod:`~kdtree_tpu.approx.recall` — the recall harness
  (``kdtree-tpu recall``): sweep visit caps against the exact oracle,
  emit recall@k-vs-speedup curves (bench-sidecar ``recall`` block, a
  ``kdtree-tpu trend`` input — regressions gate CI like throughput
  drops), and persist the measured recall_target → visit_cap
  calibration per plan signature into the PR 2 plan store.
- :mod:`~kdtree_tpu.approx.ladder` — the serving degradation ladder:
  under sustained SLO burn the batcher steps
  exact → approx(0.99) → approx(0.9) → brute-force-deadline and climbs
  back on recovery, every transition flight-recorded and exported
  (docs/SERVING.md "Degradation ladder").

Trust model: calibrations are ADVISORY, like plan profiles — they tune
the recall/latency trade, never the exactness contract. A request
without ``recall_target`` runs the exact path, byte-identical to a
build without this package.
"""

from __future__ import annotations

from kdtree_tpu.approx.ladder import (
    GEARS,
    DegradationLadder,
    GearSpec,
    gear_token,
)
from kdtree_tpu.approx.recall import (
    RECALL_VERSION,
    calibrate_caps,
    recall_at_k,
    sweep_recall,
)
from kdtree_tpu.approx.search import (
    DEFAULT_TARGETS,
    RECALL_TARGET_ERROR,
    morton_knn_approx,
    parse_recall_target,
    resolve_visit_cap,
)

__all__ = [
    "DEFAULT_TARGETS",
    "RECALL_TARGET_ERROR",
    "parse_recall_target",
    "DegradationLadder",
    "GEARS",
    "GearSpec",
    "RECALL_VERSION",
    "calibrate_caps",
    "gear_token",
    "morton_knn_approx",
    "recall_at_k",
    "resolve_visit_cap",
    "sweep_recall",
]
