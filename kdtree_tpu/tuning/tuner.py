"""Explicit (tile, cmax) + block-shape sweep: measure candidates on a
query sample and persist the winner — the operator-driven way to seed the
plan store (``kdtree-tpu tune``), complementing the passive per-run
feedback loop.

The sweep is deliberately simple and honest: every candidate gets a
warmup run (compile + cap settling excluded from timing, same discipline
as bench.py) and one timed run synced by a host fetch; a candidate whose
timed run still needed overflow-retry doubling is marked invalid (its cap
does not hold for this geometry, so its time includes retry recompiles
and its steady state would too). The winner is the fastest valid
candidate — persisted under the sample's signature, so serve-time
``plan_tiled`` calls with the same shape start there directly.

Two phases (docs/TUNING.md "Raw speed"):

1. **(tile, cmax)** — the launch grid, at the heuristic block shape.
2. **block shape (v, tb)** — scan-kernel knobs swept AT the phase-1
   winner: ``v`` (buckets per fold chunk — the candidate pad; the fused
   Pallas kernel's DMA/fold group) picks the fold regime (narrow traced
   extract vs wide ``top_k``), ``tb`` (tiles per scan block) sets the
   early-exit granularity. A full 4-D cross product would square the
   sweep cost for knobs that interact weakly with (tile, cmax); the
   two-phase factorization keeps ``tune`` proportional to the grid sizes.

The persisted profile carries ``v``/``tb`` only when phase 2 actually
measured them — ``plan_tiled`` treats absent block knobs as "use the
heuristic", so a phase-1-only profile keeps tracking heuristic
improvements while a swept one is pinned to its measurement.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

from kdtree_tpu import obs
from kdtree_tpu.tuning.store import PlanStore, default_store, make_signature

DEFAULT_TILES = (64, 128, 256, 512, 1024)
DEFAULT_CMAXS = (32, 64, 128, 256)
# block-shape defaults: v=None / tb=None rows mean "the plan heuristic's
# choice" — always measured so the sweep can only ever confirm or beat it
DEFAULT_VS = (1, 8)
DEFAULT_TBS = (1, 4, 32)


def _measure(tree, queries, k: int, retc, **knobs) -> dict:
    """Warmup + one timed run of the tiled engine at ``knobs``; returns a
    result row with seconds/qps/overflow_retries."""
    from kdtree_tpu.ops.tile_query import morton_knn_tiled

    Q = queries.shape[0]
    d2, _ = morton_knn_tiled(tree, queries, k=k, **knobs)
    obs.hard_sync(d2)  # warmup: compile + first cap settle
    r0 = retc.value
    t0 = time.perf_counter()
    d2, _ = morton_knn_tiled(tree, queries, k=k, **knobs)
    obs.hard_sync(d2)
    dt = time.perf_counter() - t0
    return {
        "seconds": dt,
        "qps": Q / dt if dt > 0 else None,
        "overflow_retries": int(retc.value - r0),
    }


def _prev_block_knobs(store, sig, tile: int):
    """The previously persisted tuner-swept block shape, or ``None`` —
    only when the stored profile's TILE matches: block knobs measured at
    one tile width pinned onto (or defended at) another would hard-code
    the wrong fold regime for it. The match deliberately ignores cmax —
    the feedback recorder rewrites it on cap drift while preserving
    v/tb, and keying on a field that mutates after the sweep would
    silently drop the swept knobs on the next re-tune."""
    from kdtree_tpu.ops.tile_query import _opt_knob

    prev = store.get(sig)
    if prev is None or tile != _opt_knob(prev.get("tile")):
        return None
    pv, ptb = _opt_knob(prev.get("v")), _opt_knob(prev.get("tb"))
    if pv is None or ptb is None:
        return None
    return pv, ptb


def sweep(
    tree,
    queries,
    k: int,
    tiles: Optional[Sequence[int]] = None,
    cmaxs: Optional[Sequence[int]] = None,
    vs: Optional[Sequence[int]] = None,
    tbs: Optional[Sequence[int]] = None,
    sweep_blocks: bool = True,
    store: Optional[PlanStore] = None,
    log=None,
) -> dict:
    """Time each (tile, cmax) candidate on ``queries`` against ``tree``,
    sweep the scan block shape at the winner, persist the overall winner,
    and return the full result table.

    Returns ``{"results": [...], "block_results": [...], "winner": {...},
    "persisted": bool, "path": str | None}``; each result row carries
    tile, cmax, (v, tb for block rows), seconds, qps, and the
    overflow-retry count its timed run incurred.
    """
    import jax

    from kdtree_tpu.ops.tile_query import DEFAULT_SEEDS

    use_pallas = jax.default_backend() == "tpu"
    Q = queries.shape[0]
    nbp = tree.num_buckets
    tiles = [t for t in (tiles or DEFAULT_TILES) if t <= max(Q, 1)] or [
        max(Q, 1)
    ]
    cmaxs = [c for c in (cmaxs or DEFAULT_CMAXS) if c <= nbp] or [nbp]
    retc = obs.get_registry().counter("kdtree_tile_overflow_retries_total")

    results = []
    for tile in tiles:
        for cmax in cmaxs:
            row = {"tile": tile, "cmax": cmax, "v": None, "tb": None}
            row.update(_measure(tree, queries, k, retc, tile=tile,
                                cmax=cmax))
            results.append(row)
            if log is not None:
                log(row)

    valid = [r for r in results if r["overflow_retries"] == 0]
    store = store if store is not None else default_store()
    sig = make_signature(
        Q, queries.shape[1], tree.n_real, k, tree.bucket_size, nbp,
        devices=1,
    )
    if not valid:
        # every candidate's cap overflowed. The retry COUNTER can't tell
        # doubling rounds from per-batch straggler increments, so the true
        # settled cap is unrecoverable here — persisting the raw candidate
        # would hand warm runs a cap known to overflow, and an inflated
        # guess would lock in oversized buffers (feedback never shrinks a
        # cap). Persist nothing and tell the operator to widen the grid.
        winner = min(results, key=lambda r: r["seconds"])
        return {
            "results": results,
            "block_results": [],
            "winner": winner,
            "persisted": False,
            "path": store.path_for(sig) if store.enabled else None,
            "reason": "every candidate overflowed its cap; re-run with "
                      "larger --cmax values",
        }
    winner = min(valid, key=lambda r: r["seconds"])

    block_results = []
    if sweep_blocks:
        # phase 2: block shape at the winning launch config. The winner's
        # own (heuristic-block) time is already on the table, so a sweep
        # that finds nothing faster changes nothing.
        tbs_eff = list(tbs or DEFAULT_TBS)
        if use_pallas:
            # the fused Pallas kernel has no tb knob (scan_tiles_fused
            # takes V only), so distinct tb candidates time IDENTICAL
            # configurations — collapse the axis instead of multiplying
            # the sweep cost by len(tbs) for nothing
            tbs_eff = tbs_eff[:1]
        pairs = [(int(v), int(tb)) for v in (vs or DEFAULT_VS)
                 for tb in tbs_eff]
        # a previously swept block shape at the SAME launch config joins
        # the candidate grid: a routine re-tune whose default grid lacks
        # it must not drop a proven-faster (v, tb) without RE-MEASURING
        # it — it defends its store slot on the clock like everyone else
        prev_knobs = _prev_block_knobs(store, sig, winner["tile"])
        if prev_knobs is not None and use_pallas:
            # tb is a no-op on the fused kernel: normalize the defended
            # pair's tb to the collapsed axis so it can't re-time (and
            # arbitrarily persist) a byte-identical configuration
            prev_knobs = (prev_knobs[0], tbs_eff[0])
        if prev_knobs is not None and prev_knobs not in pairs:
            pairs.append(prev_knobs)
        for v, tb in pairs:
            row = {"tile": winner["tile"], "cmax": winner["cmax"],
                   "v": v, "tb": tb}
            row.update(_measure(
                tree, queries, k, retc, tile=winner["tile"],
                cmax=winner["cmax"], scan_v=v, scan_tb=tb,
            ))
            block_results.append(row)
            if log is not None:
                log(row)
        block_valid = [r for r in block_results
                       if r["overflow_retries"] == 0]
        winner = min([winner, *block_valid], key=lambda r: r["seconds"])

    profile = {
        "tile": int(winner["tile"]),
        "cmax": int(winner["cmax"]),
        "seeds": DEFAULT_SEEDS,
        "use_pallas": use_pallas,
        "source": "tune",
        "tune_qps": winner["qps"],
        "tune_seconds": winner["seconds"],
        "overflow_retries": 0,
    }
    if winner["v"] is not None:
        profile["v"] = int(winner["v"])
        profile["tb"] = int(winner["tb"])
    elif not sweep_blocks:
        # a --no-block-sweep refresh measured NOTHING about the block
        # shape: preserve previously tuner-swept knobs (at a confirmed
        # launch config) instead of silently erasing them — same
        # contract as the feedback recorder's merge; only a sweep that
        # actually measured block candidates and saw the heuristic win
        # may clear them
        prev_knobs = _prev_block_knobs(store, sig, profile["tile"])
        if prev_knobs is not None:
            profile["v"], profile["tb"] = prev_knobs
    persisted = store.put(sig, profile)
    return {
        "results": results,
        "block_results": block_results,
        "winner": winner,
        "persisted": persisted,
        "path": store.path_for(sig) if store.enabled else None,
    }
