"""Explicit (tile, cmax) sweep: measure candidates on a query sample and
persist the winner — the operator-driven way to seed the plan store
(``kdtree-tpu tune``), complementing the passive per-run feedback loop.

The sweep is deliberately simple and honest: every candidate pair gets a
warmup run (compile + cap settling excluded from timing, same discipline
as bench.py) and one timed run synced by a host fetch; a candidate whose
timed run still needed overflow-retry doubling is marked invalid (its cap
does not hold for this geometry, so its time includes retry recompiles
and its steady state would too). The winner is the fastest valid pair —
persisted under the sample's signature, so serve-time ``plan_tiled``
calls with the same shape start there directly.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

from kdtree_tpu import obs
from kdtree_tpu.tuning.store import PlanStore, default_store, make_signature

DEFAULT_TILES = (64, 128, 256, 512, 1024)
DEFAULT_CMAXS = (32, 64, 128, 256)


def sweep(
    tree,
    queries,
    k: int,
    tiles: Optional[Sequence[int]] = None,
    cmaxs: Optional[Sequence[int]] = None,
    store: Optional[PlanStore] = None,
    log=None,
) -> dict:
    """Time each (tile, cmax) candidate on ``queries`` against ``tree``,
    persist the winner, and return the full result table.

    Returns ``{"results": [...], "winner": {...}, "persisted": bool,
    "path": str | None}``; each result row carries tile, cmax, seconds,
    qps, and the overflow-retry count its timed run incurred.
    """
    import jax

    from kdtree_tpu.ops.tile_query import DEFAULT_SEEDS, morton_knn_tiled

    use_pallas = jax.default_backend() == "tpu"
    Q = queries.shape[0]
    nbp = tree.num_buckets
    tiles = [t for t in (tiles or DEFAULT_TILES) if t <= max(Q, 1)] or [
        max(Q, 1)
    ]
    cmaxs = [c for c in (cmaxs or DEFAULT_CMAXS) if c <= nbp] or [nbp]
    retc = obs.get_registry().counter("kdtree_tile_overflow_retries_total")

    results = []
    for tile in tiles:
        for cmax in cmaxs:
            d2, _ = morton_knn_tiled(tree, queries, k=k, tile=tile, cmax=cmax)
            obs.hard_sync(d2)  # warmup: compile + first cap settle
            r0 = retc.value
            t0 = time.perf_counter()
            d2, _ = morton_knn_tiled(tree, queries, k=k, tile=tile, cmax=cmax)
            obs.hard_sync(d2)
            dt = time.perf_counter() - t0
            row = {
                "tile": tile,
                "cmax": cmax,
                "seconds": dt,
                "qps": Q / dt if dt > 0 else None,
                "overflow_retries": int(retc.value - r0),
            }
            results.append(row)
            if log is not None:
                log(row)

    valid = [r for r in results if r["overflow_retries"] == 0]
    store = store if store is not None else default_store()
    sig = make_signature(
        Q, queries.shape[1], tree.n_real, k, tree.bucket_size, nbp,
        devices=1,
    )
    if not valid:
        # every candidate's cap overflowed. The retry COUNTER can't tell
        # doubling rounds from per-batch straggler increments, so the true
        # settled cap is unrecoverable here — persisting the raw candidate
        # would hand warm runs a cap known to overflow, and an inflated
        # guess would lock in oversized buffers (feedback never shrinks a
        # cap). Persist nothing and tell the operator to widen the grid.
        winner = min(results, key=lambda r: r["seconds"])
        return {
            "results": results,
            "winner": winner,
            "persisted": False,
            "path": store.path_for(sig) if store.enabled else None,
            "reason": "every candidate overflowed its cap; re-run with "
                      "larger --cmax values",
        }
    winner = min(valid, key=lambda r: r["seconds"])
    persisted = store.put(sig, {
        "tile": int(winner["tile"]),
        "cmax": int(winner["cmax"]),
        "seeds": DEFAULT_SEEDS,
        "use_pallas": use_pallas,
        "source": "tune",
        "tune_qps": winner["qps"],
        "tune_seconds": winner["seconds"],
        "overflow_retries": 0,
    })
    return {
        "results": results,
        "winner": winner,
        "persisted": persisted,
        "path": store.path_for(sig) if store.enabled else None,
    }
