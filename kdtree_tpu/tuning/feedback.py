"""Feedback recorder: write each tiled run's settled reality back into the
plan store — the half of the auto-tune loop that replaces guessing.

Two tiers, matching the obs cost model:

- **Loop-closing facts (always, host-cheap):** the settled ``cmax`` and
  this run's overflow-retry count are already host-side when the batch
  driver finishes (the retry loop fetched the flags), so
  :meth:`PlanFeedback.settled` records them immediately — one small JSON
  write per query *call*, and only when the profile actually changed
  (``PlanStore.record`` suppresses no-op rewrites, so a steady-state
  serving loop settles to zero writes).
- **Telemetry-priced stats (gated on ``obs.enabled()``):** the observed
  prune rate and bucket-occupancy quantile come from device fetches the
  instrumentation defers to report time; the enrichment rides the same
  ``obs.defer`` queue, AFTER the metric flush callbacks that produce
  those numbers, so it reads settled gauges instead of adding a sync.

The recorded profile is exactly what ``plan_tiled`` consults on the next
run with the same signature — see :mod:`kdtree_tpu.tuning.store` for the
trust model (profiles are advisory; overflow-retry still guards
exactness).
"""

from __future__ import annotations

from typing import Optional

from kdtree_tpu import obs
from kdtree_tpu.tuning.store import (
    PlanSignature,
    PlanStore,
    _pow2_ceil,
    default_store,
)


def occupancy_quantile(q: float, registry=None) -> Optional[float]:
    """Approximate q-quantile of the ``kdtree_bucket_occupancy`` histogram
    (upper bound of the first bucket whose cumulative count reaches the
    quantile) — the load-skew signal slack selection wants. None when the
    histogram has no observations (occupancy is device-fetch-priced and
    only recorded under ``obs.enabled()``)."""
    reg = registry or obs.get_registry()
    snap = reg.snapshot()["histograms"].get("kdtree_bucket_occupancy")
    if not snap or not snap["count"]:
        return None
    target = q * snap["count"]
    for upper, cum in snap["buckets"].items():
        if cum >= target:
            return None if upper == "+Inf" else float(upper)
    return None


def occupancy_p90_hint(
    dim: int, n: int, bucket_cap: int, devices: int,
    backend: Optional[str] = None, store: Optional[PlanStore] = None,
) -> Optional[float]:
    """The best available ``occupancy_p90`` observation for a build of
    this shape, read from warm plan-store profiles — the signal the
    sample-sort slack sizing consults (docs/TUNING.md).

    Profiles are keyed by *query* signatures, so the match is on the
    build-relevant fields only: same dim, same bucket capacity, same
    backend, and a device/row-bucket combination this build could have
    produced — ``devices`` equal to the forest's shard count (the SPMD
    per-shard plans) or 1 (the single-tree and mesh-free paths), with the
    profile's quantized row bucket no larger than this build's total and
    no smaller than half a shard's share (a profile from a much smaller
    problem says nothing about this one's skew). The MAX over matches is
    returned: overestimating occupancy only buys slack headroom, while
    underestimating re-creates the overflow-retry the sizing exists to
    avoid. None when no matching profile carries the field."""
    store = store if store is not None else default_store()
    if not store.enabled:
        return None
    if backend is None:
        import jax

        backend = jax.default_backend()
    n_hi = _pow2_ceil(max(int(n), 1))
    n_lo = max(1, _pow2_ceil(max(int(n) // max(int(devices), 1), 1)) // 2)
    best: Optional[float] = None
    for sig, prof in store.scan():
        occ = prof.get("occupancy_p90")
        if not isinstance(occ, (int, float)) or isinstance(occ, bool) \
                or occ <= 0:
            continue
        if sig.get("dim") != int(dim) or \
                sig.get("bucket_size") != int(bucket_cap) or \
                sig.get("backend") != str(backend):
            continue
        if sig.get("devices") not in (1, int(devices)):
            continue
        nb = sig.get("n_bucket")
        if not isinstance(nb, int) or not (n_lo <= nb <= n_hi):
            continue
        best = occ if best is None else max(best, occ)
    return best


class PlanFeedback:
    """One tiled run's report-back handle; created by :func:`feedback_for`
    and driven by ``drive_batches`` once the cap has settled."""

    def __init__(self, sig: PlanSignature, plan, store: PlanStore) -> None:
        self.sig = sig
        self.plan = plan
        self.store = store

    def settled(self, cmax: int, retries: int) -> None:
        """Record the run's settled launch config (called by the batch
        driver after every batch has a clean overflow flag)."""
        self.store.record(
            self.sig,
            tile=int(self.plan.tile),
            cmax=int(cmax),
            seeds=int(self.plan.seeds),
            use_pallas=bool(self.plan.use_pallas),
            overflow_retries=int(retries),
            source="feedback",
        )

    def record_stats(self, prune_rate=None) -> None:
        """Telemetry-priced enrichment, called by the batch driver's OWN
        deferred candidate-flush callback with THIS run's prune rate (the
        process-global gauge would cross-contaminate signatures when
        several shapes flush together). A rate of 0.0 is recorded too —
        "prunes nothing" is the degraded geometry an operator most wants
        to see in the profile. The occupancy quantile is a best-effort
        process-level read (the histogram is per-build, not per-run)."""
        stats = {}
        if prune_rate is not None:
            stats["prune_rate"] = round(float(prune_rate), 6)
        occ = occupancy_quantile(0.9)
        if occ is not None:
            stats["occupancy_p90"] = occ
        if stats:
            self.store.record(self.sig, **stats)


def feedback_for(
    plan, store: Optional[PlanStore] = None,
) -> Optional[PlanFeedback]:
    """The feedback handle for an auto-planned tiled run, or None when
    nothing should be recorded: the store is disabled, or the plan's knobs
    were forced by the caller (``source == "explicit"`` — recording a
    user's one-off override would poison the profile for every auto run
    that follows). Records under ``plan.sig`` — the exact signature
    ``plan_tiled``'s lookup consulted, so lookup and recording can never
    drift apart."""
    if getattr(plan, "source", "explicit") == "explicit":
        return None
    sig = getattr(plan, "sig", None)
    if sig is None:
        return None
    store = store if store is not None else default_store()
    if not store.enabled:
        return None
    return PlanFeedback(sig, plan, store)
