"""Persistent tiled-query plan store: JSON profiles keyed by a quantized
problem signature.

The tiled engine's launch knobs (tile, cmax, seeds) are STATIC jit
arguments: a mis-guessed cmax costs a synchronous first-batch settling
probe plus one fresh XLA compile per doubling round — and because the
knowledge lived only in-process, every restart paid for the same guess
again. This store is the process-boundary-crossing half of the auto-tune
loop: a settled plan (from a previous run's feedback, or an explicit
``kdtree-tpu tune`` sweep) is written as one small JSON profile under a
cache dir, and the next run with the same problem *shape* starts from the
settled configuration directly — no probe, no doubling rounds, no
recompiles.

**Signature quantization.** Profiles are keyed by
:class:`PlanSignature`: (Q-bucket, D, n-bucket, k, bucket size,
num-buckets, backend, device count), where Q and n are rounded UP to the
next power of two. Quantizing keeps run-to-run jitter in the row counts
(a 1.00M vs 1.05M ingest) from scattering profiles across hundreds of
near-identical keys, while everything that changes the compiled program
or the density model (D, k, bucket geometry, backend, shard count) keys
exactly. The same quantization idea as ``_shard_n_real``'s occupancy
rounding — track the shape, don't bust the cache on noise.

**Trust model.** Profiles are advisory launch configurations, never
correctness inputs: the tiled engine's overflow-retry contract still
guards every batch, so a stale or even adversarially wrong profile can
only cost speed. Corrupt files, unknown versions, and out-of-range values
all read as a miss (:meth:`PlanStore.get` returns None) — the caller
falls back to the static density heuristic exactly as if no profile
existed.

Layout: one ``plan-<signature>.json`` per signature under the cache dir
(``KDTREE_TPU_PLAN_CACHE`` env var; default
``$XDG_CACHE_HOME/kdtree_tpu/plans``; ``none``/``off``/``0``/empty
disables the store entirely). Writes are atomic (tmp + ``os.replace``)
and never raise into the run they observe — same contract as the
telemetry exporters. See ``docs/TUNING.md``.
"""

from __future__ import annotations

import json
import os
import time
from typing import NamedTuple, Optional

from kdtree_tpu import obs

PROFILE_VERSION = 1

ENV_CACHE_DIR = "KDTREE_TPU_PLAN_CACHE"
_DISABLED_VALUES = ("", "0", "none", "off")

# the launch knobs a profile must carry to be usable; everything else
# (prune_rate, occupancy_p90, ...) is observability payload
_REQUIRED_INT_FIELDS = ("tile", "cmax", "seeds")


def _pow2_ceil(x: int) -> int:
    """Smallest power of two >= x (1 for x <= 1)."""
    return 1 << max(int(x) - 1, 0).bit_length()


class PlanSignature(NamedTuple):
    """Quantized problem signature — the plan-store key."""

    q_bucket: int
    dim: int
    n_bucket: int
    k: int
    bucket_size: int
    num_buckets: int
    backend: str
    devices: int

    @property
    def key(self) -> str:
        return (
            f"q{self.q_bucket}-d{self.dim}-n{self.n_bucket}-k{self.k}"
            f"-b{self.bucket_size}-nb{self.num_buckets}"
            f"-{self.backend}-p{self.devices}"
        )


def make_signature(
    Q: int, D: int, n: int, k: int, bucket_size: int, num_buckets: int,
    devices: int = 1, backend: Optional[str] = None,
) -> PlanSignature:
    """Signature for one tiled-query problem shape. ``backend`` defaults to
    the backend jax would actually run on (lazy import — signature
    construction must stay cheap for jax-free callers that pass it
    explicitly)."""
    if backend is None:
        import jax

        backend = jax.default_backend()
    return PlanSignature(
        q_bucket=_pow2_ceil(Q),
        dim=int(D),
        n_bucket=_pow2_ceil(n),
        k=int(k),
        bucket_size=int(bucket_size),
        num_buckets=int(num_buckets),
        backend=str(backend),
        devices=int(devices),
    )


# In-process read memo: {profile path: (mtime_ns, size, validated profile)}.
# Steady-state serving consults the store on EVERY query call (lookup +
# the recorder's read-modify-write); without a memo that is two file
# reads + JSON parses per call forever. A stat() is enough to stay
# coherent with other processes (any writer replaces the file, changing
# mtime/size), so the steady state costs one stat instead of a parse.
_read_memo: dict = {}


def default_cache_dir() -> Optional[str]:
    """Resolve the cache dir from the environment; None = store disabled."""
    raw = os.environ.get(ENV_CACHE_DIR)
    if raw is not None:
        return None if raw.strip().lower() in _DISABLED_VALUES else raw
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    return os.path.join(base, "kdtree_tpu", "plans")


class PlanStore:
    """File-backed plan profiles; every operation is failure-tolerant (a
    broken cache dir degrades to the heuristic path, never to an error)."""

    def __init__(self, cache_dir: Optional[str] = None) -> None:
        self.cache_dir = cache_dir if cache_dir is not None else default_cache_dir()

    @property
    def enabled(self) -> bool:
        return self.cache_dir is not None

    def path_for(self, sig: PlanSignature) -> str:
        return os.path.join(self.cache_dir or "", f"plan-{sig.key}.json")

    def get(self, sig: PlanSignature) -> Optional[dict]:
        """The validated profile for ``sig``, or None on miss / corrupt
        file / stale version / unusable launch knobs."""
        return self._validate(self.get_raw(sig))

    def get_raw(self, sig: PlanSignature) -> Optional[dict]:
        """The version-checked profile for ``sig`` WITHOUT the
        launch-knob requirement — for advisory-payload consumers (the
        recall calibration, occupancy enrichment) reading a profile no
        tuner has settled launch knobs into yet. LAUNCHING from a
        profile still goes through :meth:`get` — same split as
        :meth:`scan` documents."""
        if not self.enabled:
            return None
        path = self.path_for(sig)
        try:
            st = os.stat(path)
        except OSError:
            _read_memo.pop(path, None)
            return None
        memo = _read_memo.get(path)
        if memo is not None and memo[0] == st.st_mtime_ns and \
                memo[1] == st.st_size:
            return memo[2]
        try:
            with open(path) as f:
                prof = json.load(f)
        except ValueError:
            prof = None  # corrupt file: memoize the miss too, or a
            # permanently broken profile re-pays the parse every call
        except OSError:
            return None  # transient read error: retry next call
        else:
            prof = self._version_check(prof)
        _read_memo[path] = (st.st_mtime_ns, st.st_size, prof)
        return prof

    def raw_for_key(self, key: str) -> Optional[dict]:
        """Version-checked raw profile by signature KEY — for consumers
        that hold key strings rather than signatures (the snapshot
        manifest's ``plan_profiles`` payload ships profiles under their
        keys). One implementation of the file naming and version gate,
        shared with the signature-keyed read path; no memo (callers are
        once-per-save, not per-query)."""
        if not self.enabled:
            return None
        try:
            with open(os.path.join(self.cache_dir,
                                   f"plan-{key}.json")) as f:
                prof = json.load(f)
        except (OSError, ValueError):
            return None
        return self._version_check(prof)

    @staticmethod
    def _version_check(prof) -> Optional[dict]:
        if not isinstance(prof, dict):
            return None
        if prof.get("version") != PROFILE_VERSION:
            return None  # stale format: treat as a miss, never guess
        return prof

    @classmethod
    def _validate(cls, prof) -> Optional[dict]:
        prof = cls._version_check(prof)
        if prof is None:
            return None
        for field in _REQUIRED_INT_FIELDS:
            v = prof.get(field)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                return None
        return prof

    def put(self, sig: PlanSignature, profile: dict) -> bool:
        """Atomically write ``profile`` (version stamp + timestamp added).
        Returns False (without raising) when the store is disabled or the
        write fails — plan persistence must never fail the run."""
        if not self.enabled:
            return False
        rec = dict(profile)
        rec["version"] = PROFILE_VERSION
        rec["signature"] = sig._asdict()
        rec["updated_unix"] = time.time()
        path = self.path_for(sig)
        # pid AND thread id: concurrent same-shape queries from a threaded
        # serving process must not interleave into one tmp file and
        # os.replace a corrupt profile into place
        import threading

        tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
        try:
            os.makedirs(self.cache_dir, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(rec, f, indent=2, sort_keys=True)
                f.write("\n")
            os.replace(tmp, path)
            st = os.stat(path)
            _read_memo[path] = (st.st_mtime_ns, st.st_size,
                                self._version_check(rec))
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        obs.get_registry().counter("kdtree_plan_cache_writes_total").inc()
        return True

    def scan(self):
        """Yield ``(signature dict, raw profile dict)`` for every readable
        profile in the store — the cross-signature view consumers like the
        occupancy→slack sizing need (they match on *parts* of a signature,
        so the keyed :meth:`get` path cannot serve them). Failure-tolerant
        like everything else here: unreadable files and profiles without a
        signature are skipped, never raised. Enrichment-only profiles
        (occupancy recorded before any settled launch config) are yielded
        too — :meth:`_validate`'s launch-knob check guards *launching* from
        a profile, not reading its observability payload."""
        if not self.enabled:
            return
        try:
            names = sorted(os.listdir(self.cache_dir))
        except OSError:
            return
        for fname in names:
            if not (fname.startswith("plan-") and fname.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.cache_dir, fname)) as f:
                    prof = json.load(f)
            except (OSError, ValueError):
                continue
            if not isinstance(prof, dict) or \
                    prof.get("version") != PROFILE_VERSION:
                continue
            sig = prof.get("signature")
            if not isinstance(sig, dict):
                continue
            yield sig, prof

    def record(self, sig: PlanSignature, **fields) -> bool:
        """Merge ``fields`` into the profile for ``sig``, writing only when
        something other than the timestamp actually changed — a steady-state
        serving loop that re-observes the same settled plan on every query
        call must not rewrite the file each time."""
        if not self.enabled:
            return False
        # merge over the RAW profile: an advisory-only profile (e.g. a
        # recall calibration written before any tuner settled launch
        # knobs) fails get()'s launch validation, and merging over the
        # resulting None would silently erase it on the next feedback
        existing = self.get_raw(sig) or {}
        base = {
            k: v for k, v in existing.items()
            if k not in ("version", "signature", "updated_unix")
        }
        merged = dict(base)
        merged.update(fields)
        if merged == base:
            return False
        return self.put(sig, merged)


def default_store() -> PlanStore:
    """A store bound to the current environment's cache dir. Constructed
    per call (it holds only the resolved path) so env changes — tests,
    operator overrides — take effect without process-global state."""
    return PlanStore()
