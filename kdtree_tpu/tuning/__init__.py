"""kdtree_tpu.tuning — the closed auto-tune loop for the tiled query path.

Three pieces (see ``docs/TUNING.md``):

- :mod:`~kdtree_tpu.tuning.store` — persistent plan profiles (JSON under a
  cache dir) keyed by a quantized problem signature; survives process
  restarts, which is the whole point — a settled plan is knowledge about
  the *data*, not about one process;
- :mod:`~kdtree_tpu.tuning.feedback` — per-run report-back of the settled
  cmax / retry count (host-cheap, immediate) and prune-rate / occupancy
  stats (telemetry-priced, deferred to the obs flush);
- :mod:`~kdtree_tpu.tuning.tuner` — the explicit ``kdtree-tpu tune``
  sweep that measures (tile, cmax) candidates and persists the winner.

``plan_tiled`` (:mod:`kdtree_tpu.ops.tile_query`) consults the store via
:func:`lookup` on every auto-planned run: a warm hit skips the
synchronous first-batch cap-settling probe, the doubling-retry rounds,
and their per-shape XLA recompiles. Profiles are advisory only — the
overflow-retry contract still guards exactness, so the worst a bad
profile can do is run at yesterday's speed.
"""

from __future__ import annotations

from typing import Optional

from kdtree_tpu import obs
from kdtree_tpu.tuning.feedback import (
    PlanFeedback,
    feedback_for,
    occupancy_p90_hint,
)
from kdtree_tpu.tuning.store import (
    ENV_CACHE_DIR,
    PlanSignature,
    PlanStore,
    default_cache_dir,
    default_store,
    make_signature,
)


def lookup(
    sig: PlanSignature, use_pallas: Optional[bool] = None,
    store: Optional[PlanStore] = None,
) -> Optional[dict]:
    """Warm-plan lookup for one problem signature (build it with
    :func:`make_signature`); returns the stored profile dict or None
    (store disabled / miss / corrupt). A profile recorded for the other
    scan engine (``use_pallas`` disagrees with this run's) reads as a
    miss — Pallas-tuned tiles are wrong for the XLA scan and vice versa,
    and the two paths share a signature key. Hit-or-miss lands in the
    ``kdtree_plan_cache_{hits,misses}_total`` counters so a serving
    process's warm ratio is visible in every telemetry report."""
    store = store if store is not None else default_store()
    if not store.enabled:
        return None
    prof = store.get(sig)
    if prof is not None and use_pallas is not None and \
            "use_pallas" in prof and bool(prof["use_pallas"]) != use_pallas:
        prof = None
    reg = obs.get_registry()
    if prof is None:
        reg.counter("kdtree_plan_cache_misses_total").inc()
    else:
        reg.counter("kdtree_plan_cache_hits_total").inc()
    return prof


def profile_for(
    sig: PlanSignature, store: Optional[PlanStore] = None,
) -> Optional[dict]:
    """The RAW (version-checked, launch-knob-free) profile for one
    signature — the read path for advisory payload like the recall
    calibration (:mod:`kdtree_tpu.approx`), which may live in a profile
    no tuner has settled launch knobs into. Does not touch the
    hit/miss counters: those measure the warm-plan ratio, and a
    per-batch calibration read would drown it."""
    store = store if store is not None else default_store()
    return store.get_raw(sig)


__all__ = [
    "ENV_CACHE_DIR",
    "PlanFeedback",
    "PlanSignature",
    "PlanStore",
    "default_cache_dir",
    "default_store",
    "feedback_for",
    "lookup",
    "make_signature",
    "occupancy_p90_hint",
    "profile_for",
]
