from kdtree_tpu.models.tree import KDTree, TreeSpec, node_levels, tree_spec

__all__ = ["KDTree", "TreeSpec", "node_levels", "tree_spec"]
