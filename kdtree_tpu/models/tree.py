"""Implicit array k-d tree model.

The reference (``/root/reference/kdtree_sequential.cpp:30-70``) builds a heap of
``Node{Point*, left, right}`` objects by host recursion. On TPU, pointer trees
and host recursion are non-starters: everything under ``jit`` must have static
shapes and compiler-friendly control flow. So the tree here is *data*:

- ``points``      f32[N, D]   the point cloud (unchanged, never permuted)
- ``node_point``  i32[H]      heap-indexed: node ``i`` has children ``2i+1`` /
                              ``2i+2``; value is the index into ``points`` of
                              the point stored at that node, or -1 if the node
                              does not exist (empty subtree)
- ``split_val``   f32[H]      the node's coordinate on its split axis
                              (``axis = level(i) % D``, mirroring the cyclic
                              axis choice at ``kdtree_sequential.cpp:42``)

The *shape* of the tree (which heap slots exist, which permutation positions
become which node) depends only on N — the reference's exact-median split
(``median = n/2``; left ``n/2``, right ``n - n/2 - 1``,
``kdtree_sequential.cpp:51-56``) makes every segment size a static function of
N. ``TreeSpec`` precomputes that static structure once on the host (NumPy) and
is cached per N; the device build (:mod:`kdtree_tpu.ops.build`) then only moves
the dynamic content (the permutation) through ``lax.sort`` calls.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Tuple

import jax
import numpy as np


@dataclass(frozen=True)
class TreeSpec:
    """Static (host-side) structure of a k-d tree over ``n`` points.

    Attributes:
      n: number of points.
      num_levels: number of level-synchronous build rounds (= max tree depth).
      heap_size: size of the implicit heap arrays (max node id + 1).
      level_medpos: per level, the permutation positions consumed as that
        level's node points (the segment medians), in segment order.
      level_nodes: per level, the heap node ids those medians become.
    """

    n: int
    num_levels: int
    heap_size: int
    level_medpos: Tuple[np.ndarray, ...]
    level_nodes: Tuple[np.ndarray, ...]
    level_segstart: Tuple[np.ndarray, ...]  # per level: segment start per node

    @property
    def consume_level(self) -> np.ndarray:
        """i32[N]: build level at which each permutation position is consumed
        as a node (positions never move after that level). Static in position
        space — the single N-sized constant that lets the device build run as
        one ``fori_loop`` with a single fused sort in the compiled program."""
        out = np.empty(self.n, np.int32)
        for lvl, pos in enumerate(self.level_medpos):
            out[pos] = lvl
        return out

    @property
    def position_node(self) -> np.ndarray:
        """i32[N]: heap node id that each permutation position becomes (every
        position is consumed exactly once). Static — lets sharded builds map
        owned positions to nodes without host coordination."""
        out = np.empty(self.n, np.int32)
        for pos, nodes in zip(self.level_medpos, self.level_nodes):
            out[pos] = nodes
        return out

    @property
    def all_medpos(self) -> np.ndarray:
        return np.concatenate(self.level_medpos) if self.level_medpos else np.zeros(0, np.int32)

    @property
    def all_nodes(self) -> np.ndarray:
        return np.concatenate(self.level_nodes) if self.level_nodes else np.zeros(0, np.int32)


@functools.lru_cache(maxsize=64)
def tree_spec(n: int) -> TreeSpec:
    """Simulate the reference's recursion shape (sizes only) level by level.

    Mirrors the arithmetic of ``build_tree_rec``
    (``kdtree_sequential.cpp:51-56``): a segment of ``c`` points puts its
    median at local offset ``c // 2``; the left child gets ``c // 2`` points,
    the right child ``c - c//2 - 1``. Positions consumed as medians stay fixed
    ("dead") for all deeper levels, so child segments are exactly the maximal
    runs of live positions.
    """
    if n <= 0:
        raise ValueError(f"n must be positive, got {n}")
    segs = [(0, n, 0)]  # (start, count, heap node id)
    level_medpos = []
    level_nodes = []
    level_segstart = []
    max_node = 0
    while segs:
        medpos = np.empty(len(segs), np.int32)
        nodes = np.empty(len(segs), np.int32)
        starts = np.empty(len(segs), np.int32)
        nxt = []
        for i, (s, c, node) in enumerate(segs):
            m = c // 2
            medpos[i] = s + m
            nodes[i] = node
            starts[i] = s
            max_node = max(max_node, node)
            if m > 0:
                nxt.append((s, m, 2 * node + 1))
            if c - m - 1 > 0:
                nxt.append((s + m + 1, c - m - 1, 2 * node + 2))
        level_medpos.append(medpos)
        level_nodes.append(nodes)
        level_segstart.append(starts)
        segs = nxt
    return TreeSpec(
        n=n,
        num_levels=len(level_medpos),
        heap_size=max_node + 1,
        level_medpos=tuple(level_medpos),
        level_nodes=tuple(level_nodes),
        level_segstart=tuple(level_segstart),
    )


def node_levels(heap_size: int) -> np.ndarray:
    """Static level of each heap node: level(i) = floor(log2(i + 1))."""
    # frexp is exact for ints < 2**53 (unlike log2 which can round).
    return (np.frexp(np.arange(1, heap_size + 1, dtype=np.int64).astype(np.float64))[1] - 1).astype(np.int32)


@jax.tree_util.register_pytree_node_class
class KDTree:
    """The built tree: a pytree of three arrays, jit/shard_map friendly."""

    def __init__(self, points, node_point, split_val):
        self.points = points
        self.node_point = node_point
        self.split_val = split_val

    @property
    def n(self) -> int:
        return self.points.shape[0]

    @property
    def dim(self) -> int:
        return self.points.shape[1]

    @property
    def heap_size(self) -> int:
        return self.node_point.shape[0]

    def tree_flatten(self):
        return (self.points, self.node_point, self.split_val), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return f"KDTree(n={self.n}, dim={self.dim}, heap_size={self.heap_size})"
