"""Benchmark timing — the working replacement for the reference's DEBUG timer.

The reference wraps all of main in one chrono timer behind a compile-time
macro (``kdtree_sequential.cpp:146-154,186-191``), conflating generation,
build, and query, and conflating compile with run. Here: named phases, each
fenced so async dispatch can't lie, and explicit warmup so compile time is
reported separately.

``PhaseTimer`` is now a thin compatibility wrapper over the telemetry
subsystem's span tracer (:mod:`kdtree_tpu.obs.spans`): each phase is a
span, so phases land in the metrics registry, nest under any enclosing
span, name themselves in ``jax.profiler`` traces, and share the single
:func:`kdtree_tpu.obs.spans.hard_sync` host-fetch barrier (formerly
duplicated here and in ``bench.py`` — on axon, ``block_until_ready`` can
return early under a deep dispatch queue; the 1-element host fetch is a
true data-dependent barrier and costs only the tunnel RTT).

Measured pitfall on the axon TPU platform (see .claude/skills/verify/SKILL.md):
re-running a jitted function on the *same* input array can report ~0s; always
time with fresh inputs.
"""

from __future__ import annotations

import contextlib
from typing import Dict

from kdtree_tpu.obs.spans import span


class PhaseTimer:
    """Collects named phase durations; each phase hard-syncs the outputs
    appended to the yielded handle before its clock stops."""

    def __init__(self) -> None:
        self.phases: Dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        sp = None
        try:
            with span(name) as sp:
                yield sp
        finally:
            if sp is not None and sp.duration is not None:
                self.phases[name] = (
                    self.phases.get(name, 0.0) + sp.duration
                )

    def total(self) -> float:
        return sum(self.phases.values())

    def report(self) -> Dict[str, float]:
        out = dict(self.phases)
        out["total"] = self.total()
        return out
