"""Benchmark timing — the working replacement for the reference's DEBUG timer.

The reference wraps all of main in one chrono timer behind a compile-time
macro (``kdtree_sequential.cpp:146-154,186-191``), conflating generation,
build, and query, and conflating compile with run. Here: named phases, each
fenced with ``jax.block_until_ready`` so async dispatch can't lie, and
explicit warmup so compile time is reported separately.

Measured pitfall on the axon TPU platform (see .claude/skills/verify/SKILL.md):
re-running a jitted function on the *same* input array can report ~0s; always
time with fresh inputs.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict

import jax


class PhaseTimer:
    """Collects named phase durations; each phase blocks on its outputs."""

    def __init__(self) -> None:
        self.phases: Dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        holder: list[Any] = []
        t0 = time.perf_counter()
        try:
            # names the phase in a jax.profiler trace (no-op when not tracing)
            with jax.profiler.TraceAnnotation(name):
                yield holder
        finally:
            if holder:
                jax.block_until_ready(holder)
                # belt-and-braces sync: on axon, block_until_ready can return
                # early under a deep dispatch queue; a 1-element host fetch of
                # each output is a true data-dependent barrier and costs only
                # the tunnel RTT.
                import numpy as _np

                for leaf in jax.tree_util.tree_leaves(holder):
                    if hasattr(leaf, "ravel"):
                        _np.asarray(leaf.ravel()[:1])
            self.phases[name] = self.phases.get(name, 0.0) + time.perf_counter() - t0

    def total(self) -> float:
        return sum(self.phases.values())

    def report(self) -> Dict[str, float]:
        out = dict(self.phases)
        out["total"] = self.total()
        return out
