"""Input guards: fail loudly at the edges instead of silently mis-sorting.

The framework's sorts and reductions treat +inf as PADDING by design
(SURVEY.md §5 race/sanitizer plan) — but a NaN coordinate is never
meaningful: NaN poisons Morton quantization (every comparison false), so a
poisoned point lands in an arbitrary bucket and silently corrupts k-NN
answers near it. The reference has no guards at all (``Utility.cpp`` exits
only on bad argv); here every load/ingest boundary calls
:func:`assert_no_nan`, and :func:`checked_build_morton` offers a
checkify-instrumented build for debugging numeric corruption that appears
mid-pipeline rather than at the edges.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from kdtree_tpu.obs import get_registry


_MAX_ROWS_I32 = 1 << 31  # global point ids are int32 everywhere


def check_rows_fit_i32(n: int, what: str) -> None:
    """Global point ids (``bucket_gid``, result ids) are int32 throughout
    the engines; rows past 2**31-1 would wrap their gids negative and be
    silently treated as padding by every downstream mask — data loss, not
    an error. Refuse crisply at the door instead.

    Every function that materializes a gid array must call this on the
    row count — enforced by ``kdtree-tpu lint`` (KDT101, the mechanized
    form of the wrap found at 3 forest-build sites)."""
    if n >= _MAX_ROWS_I32:
        raise ValueError(
            f"{what} has {n} rows, but global point ids are int32 "
            f"(max {_MAX_ROWS_I32 - 1} rows per index); split the data "
            "across multiple forests"
        )


def assert_no_nan(arr: jax.Array, name: str = "points") -> jax.Array:
    """Raise ValueError if ``arr`` contains NaN (host-synced, edge use only).

    +inf is allowed — it is the framework-wide padding sentinel; NaN never
    is. Returns the array so call sites can stay expression-shaped.

    Each invocation and its wall-clock cost (the reduction IS a host sync)
    land in the registry (``kdtree_guard_nan_checks_total`` /
    ``kdtree_guard_nan_check_seconds_total``), so the guard's hot-path
    overhead is a measurement, not an assumption.
    """
    t0 = time.perf_counter()
    bad = bool(jnp.any(jnp.isnan(arr)))
    reg = get_registry()
    reg.counter("kdtree_guard_nan_checks_total").inc()
    reg.counter("kdtree_guard_nan_check_seconds_total").inc(
        time.perf_counter() - t0
    )
    if bad:
        raise ValueError(
            f"{name} contains NaN coordinates; refusing to build/query — "
            "NaN breaks Morton quantization silently (every comparison is "
            "false). Clean the input or drop the offending rows."
        )
    return arr


def checked_build_morton(points: jax.Array, **kw):
    """Debug entry point: the Morton build under ``checkify`` float checks.

    Returns (error, tree); ``error.throw()`` raises with the location of the
    first NaN produced anywhere INSIDE the traced build — for corruption
    that appears mid-pipeline, where the edge guard can't see it. Not for
    production paths (checkify instruments every float op).
    """
    from jax.experimental import checkify

    from kdtree_tpu.ops.morton import build_morton_impl, default_bits

    n, d = points.shape
    bits = kw.pop("bits", None) or default_bits(d)
    bucket_cap = kw.pop("bucket_cap", 128)
    # padding +inf rows are deliberate; limit to NaN checks
    checked = checkify.checkify(
        lambda p: build_morton_impl(p, bucket_cap=bucket_cap, bits=bits),
        errors=checkify.nan_checks,
    )
    return checked(points)


def validate_loaded_tree(tree) -> None:
    """Checkpoint-load guard: NaN anywhere in a tree's arrays is corruption
    (inf is legal padding in bucket/box arrays)."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            if bool(jnp.any(jnp.isnan(leaf))):
                raise ValueError(
                    f"loaded tree contains NaN in a {leaf.shape} array — "
                    "checkpoint is corrupt"
                )


def has_nan(arr) -> bool:
    """Host-side NaN probe for numpy/jax arrays (no exception)."""
    return bool(np.any(np.isnan(np.asarray(arr))))
