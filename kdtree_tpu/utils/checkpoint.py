"""Tree serialization.

The reference's tree lives only in process memory (heap ``Node``s freed at
exit, ``Utility.cpp:40-45``) — no persistence at all. The implicit-array
representation makes checkpointing trivial: three arrays to npz. Save/load is
deterministic and device-agnostic (arrays come back on the default device).
"""

from __future__ import annotations

import numpy as np

from kdtree_tpu.models.tree import KDTree


def save_tree(path: str, tree: KDTree) -> None:
    np.savez_compressed(
        path,
        points=np.asarray(tree.points),
        node_point=np.asarray(tree.node_point),
        split_val=np.asarray(tree.split_val),
    )


def load_tree(path: str) -> KDTree:
    import jax.numpy as jnp

    with np.load(path) as z:
        return KDTree(
            points=jnp.asarray(z["points"]),
            node_point=jnp.asarray(z["node_point"]),
            split_val=jnp.asarray(z["split_val"]),
        )
