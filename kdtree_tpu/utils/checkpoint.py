"""Tree serialization.

The reference's tree lives only in process memory (heap ``Node``s freed at
exit, ``Utility.cpp:40-45``) — no persistence at all. The implicit-array
representation makes checkpointing trivial: every tree class here is a
registered pytree of arrays plus static aux ints, so save/load is a generic
flatten → npz → unflatten round trip. Deterministic and device-agnostic
(arrays come back on the default device). Provenance metadata (seed,
generator, ...) rides along so a later load can reconstruct the matching
problem instead of trusting the caller to pass consistent flags.
"""

from __future__ import annotations

import numpy as np


def _registry():
    from kdtree_tpu.models.tree import KDTree
    from kdtree_tpu.ops.bucket import BucketKDTree
    from kdtree_tpu.ops.morton import MortonTree
    from kdtree_tpu.parallel.global_exact import GlobalExactTree
    from kdtree_tpu.parallel.global_morton import GlobalMortonForest
    from kdtree_tpu.parallel.global_tree import GlobalKDTree

    return {
        "classic": KDTree,
        "bucket": BucketKDTree,
        "morton": MortonTree,
        "global": GlobalKDTree,
        "global-morton": GlobalMortonForest,
        "global-exact": GlobalExactTree,
    }


# Above this many total bytes a forest checkpoint automatically switches to
# the per-device-shard format: one npz per mesh position plus a manifest, so
# neither save nor (mesh) load ever holds more than ~one device's arrays on
# the host. A GlobalMortonForest at the 1B north star IS the point set —
# funnelling it through one np.savez would stop the checkpoint story scaling
# exactly where the build story starts (VERDICT r3 weak #4).
_SHARD_SAVE_BYTES = 1 << 30
_SHARDED_KINDS = ("global-morton", "global-exact")

# Mesh-free loads of a sharded checkpoint concatenate every shard into dense
# host arrays — exactly the host-memory funnel the format exists to avoid.
# Above this budget the load fails crisply instead of OOMing; callers that
# really want the dense fallback pass allow_host_materialize=True (CLI:
# `query --allow-host-materialize`). 4x headroom over the auto-shard
# threshold: a checkpoint just past _SHARD_SAVE_BYTES still cross-loads on
# an ordinary host; north-star-scale ones fail crisply. Override with
# KDTREE_TPU_HOST_MATERIALIZE_BYTES for big-RAM hosts.
_HOST_MATERIALIZE_BYTES = 4 << 30


def _host_materialize_budget() -> int:
    import os

    raw = os.environ.get("KDTREE_TPU_HOST_MATERIALIZE_BYTES")
    if raw is None:
        return _HOST_MATERIALIZE_BYTES
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"KDTREE_TPU_HOST_MATERIALIZE_BYTES must be an integer byte "
            f"count, got {raw!r}"
        ) from None


def _shard_path(path: str, i: int, tag: str) -> str:
    # the tag makes each save's shard set self-contained: a crashed re-save
    # leaves orphaned new-tag files but the old manifest still references a
    # complete old-tag set — never a silent mix (the manifest itself is
    # replaced atomically, last)
    return f"{path}.shard{i}-{tag}.npz"


def _aux_payload(tree, aux) -> np.ndarray | None:
    if aux is None:
        return None
    # the format stores aux as a flat i64 vector; anything richer (nested
    # tuples, dtypes, strings) must fail HERE, not corrupt a later load
    if not all(isinstance(a, (int, np.integer)) for a in aux):
        raise TypeError(
            f"{type(tree).__name__}.tree_flatten aux must be a flat tuple "
            f"of ints for checkpointing, got {aux!r}"
        )
    return np.asarray(aux, dtype=np.int64)


def _cleanup_stale_shards(path: str, keep_tag: str | None) -> None:
    """Best-effort removal of shard/tmp files from superseded saves at this
    path — runs on EVERY save (a single-npz save over a previously sharded
    checkpoint must not leave GiB of dead sidecar files behind)."""
    import os

    base = os.path.basename(path)
    dirname = os.path.dirname(os.path.abspath(path))
    try:
        names = os.listdir(dirname)
    except OSError:
        return
    for fname in names:
        stale_shard = (fname.startswith(f"{base}.shard")
                       and fname.endswith(".npz")
                       and (keep_tag is None or f"-{keep_tag}." not in fname))
        stale_tmp = (fname.startswith(f"{base}.tmp-")
                     and (keep_tag is None or not fname.endswith(keep_tag)))
        if stale_shard or stale_tmp:
            try:
                os.remove(os.path.join(dirname, fname))
            except OSError:
                pass


def save_tree(path: str, tree, meta: dict | None = None,
              sharded: bool | None = None) -> str:
    """Save any framework tree + meta. Returns the format written
    (``"single"`` or ``"sharded"`` — callers surface the difference because
    a sharded checkpoint is NOT one self-contained file).

    ``sharded=None`` auto-selects: forest-shaped trees (leading device axis)
    above ``_SHARD_SAVE_BYTES`` use the per-device manifest format; small
    trees use one npz. Pass True/False to force either format.
    """
    kinds = _registry()
    kind = next((k for k, cls in kinds.items() if isinstance(tree, cls)), None)
    if kind is None:
        raise TypeError(f"not a checkpointable tree: {type(tree)!r}")
    # the class protocol (not tree_flatten utils) so aux static ints persist
    children, aux = type(tree).tree_flatten(tree)
    if sharded is None:
        total = sum(
            int(np.prod(c.shape)) * c.dtype.itemsize for c in children
        )
        sharded = kind in _SHARDED_KINDS and total > _SHARD_SAVE_BYTES
    if sharded:
        if kind not in _SHARDED_KINDS:
            raise TypeError(
                f"sharded checkpoints need a leading device axis; "
                f"{type(tree).__name__} has none"
            )
        _save_sharded(path, kind, tree, children, aux, meta)
        return "sharded"
    payload = {f"child_{i}": np.asarray(c) for i, c in enumerate(children)}
    auxv = _aux_payload(tree, aux)
    if auxv is not None:
        payload["aux"] = auxv
    payload["kind"] = np.asarray(kind)
    payload.update({f"meta_{k}": np.asarray(v) for k, v in (meta or {}).items()})
    # write through an open file object: np.savez_compressed(str_path)
    # silently appends '.npz' to extension-less paths, while the sharded
    # manifest writes byte-exact — the on-disk name must not depend on
    # which format the auto-threshold picked. Write to a tmp file and
    # os.replace so a crash mid-write never truncates the previous
    # checkpoint (the sharded manifest already does this).
    import os
    import uuid

    tmp = f"{path}.tmp-{uuid.uuid4().hex[:8]}"
    try:
        with open(tmp, "wb") as f:
            np.savez_compressed(f, **payload)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    _cleanup_stale_shards(path, keep_tag=None)
    return "single"


def _save_sharded(path, kind, tree, children, aux, meta) -> None:
    """Manifest npz at ``path`` + one ``path.shard{i}-{tag}.npz`` per mesh
    position.

    Children with the device leading axis (shape[0] == tree.devices — the
    big ones: per-device points/ids/trees) are written one device-side slice
    ``c[i:i+1]`` at a time, so peak host memory is ~total/P instead of the
    whole point set. Replicated children (e.g. GlobalExactTree's top heap,
    leading dim Htop != P) are small by construction and ride in the
    manifest. Shard files carry a per-save tag and the manifest is replaced
    atomically LAST, so an interrupted re-save can never leave a manifest
    pointing at a mixed shard set.
    """
    import os
    import uuid

    p = int(tree.devices)
    auxv = _aux_payload(tree, aux)  # validate aux BEFORE writing anything
    is_dev = [c.ndim >= 1 and c.shape[0] == p for c in children]
    if not any(is_dev):
        raise TypeError(
            f"sharded save found no child with the device leading axis "
            f"({p}) on {type(tree).__name__}"
        )
    tag = uuid.uuid4().hex[:8]
    for i in range(p):
        shard = {
            f"child_{j}": np.asarray(c[i : i + 1])
            for j, c in enumerate(children)
            if is_dev[j]
        }
        np.savez_compressed(_shard_path(path, i, tag), **shard)
    manifest = {
        "kind": np.asarray(kind),
        "format": np.asarray("sharded-v1"),
        "tag": np.asarray(tag),
        "num_shards": np.asarray(p, dtype=np.int64),
        "num_children": np.asarray(len(children), dtype=np.int64),
        "sharded_mask": np.asarray(is_dev, dtype=np.bool_),
        # uncompressed bytes of ONE shard's arrays, so the mesh-free load
        # can size its host-materialize check without decompressing a shard
        "shard_bytes": np.asarray(
            sum(int(np.prod(c.shape[1:])) * c.dtype.itemsize
                for j, c in enumerate(children) if is_dev[j]),
            dtype=np.int64,
        ),
    }
    for j, c in enumerate(children):
        if not is_dev[j]:
            manifest[f"repl_{j}"] = np.asarray(c)
    if auxv is not None:
        manifest["aux"] = auxv
    manifest.update({f"meta_{k}": np.asarray(v) for k, v in (meta or {}).items()})
    tmp = f"{path}.tmp-{tag}"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **manifest)
    os.replace(tmp, path)
    _cleanup_stale_shards(path, keep_tag=tag)


def _load_sharded(path: str, z, meta, allow_host_materialize: bool = False):
    """Assemble a forest from per-device shard files.

    With a mesh of >= num_shards devices available, each sharded child is
    device_put straight onto its mesh position and the global arrays are
    assembled with ``jax.make_array_from_single_device_arrays`` — host RSS
    peaks at ~one shard. Without one (cross-hardware load), shards
    concatenate into dense host arrays (the mesh-free query path's input) —
    but only up to ``_HOST_MATERIALIZE_BYTES`` unless the caller opts in,
    because at auto-shard scale that concatenation would OOM the host.
    Replicated children come straight out of the manifest.
    """
    import jax
    import jax.numpy as jnp

    p = int(z["num_shards"])
    nchild = int(z["num_children"])
    tag = str(z["tag"])
    mask = [bool(b) for b in z["sharded_mask"]]
    cls = _registry()[str(z["kind"])]
    aux = tuple(int(a) for a in z["aux"]) if "aux" in z.files else None
    dev_idx = [j for j in range(nchild) if mask[j]]

    mesh = None
    if len(jax.devices()) >= p:
        from jax.sharding import NamedSharding, PartitionSpec
        from kdtree_tpu.parallel.mesh import SHARD_AXIS, make_mesh

        mesh = make_mesh(p)
    def _open_shard(i: int):
        sp = _shard_path(path, i, tag)
        try:
            return np.load(sp)
        except OSError as e:
            # a sharded checkpoint is manifest + P sidecar files; copying
            # just the manifest is the common way to hit this — say so
            raise FileNotFoundError(
                f"sharded checkpoint {path} references sidecar file {sp} "
                f"which cannot be read ({e}); a sharded checkpoint is the "
                f"manifest plus {p} '*.shard*-{tag}.npz' files and must be "
                "copied as a set"
            ) from e

    assembled = {}
    if mesh is not None:
        singles = {j: [] for j in dev_idx}
        devs = list(mesh.devices.flat)
        for i in range(p):
            with _open_shard(i) as zs:
                for j in dev_idx:
                    singles[j].append(
                        jax.device_put(zs[f"child_{j}"], devs[i])
                    )
        sharding = NamedSharding(mesh, PartitionSpec(SHARD_AXIS))
        for j in dev_idx:
            shape = (p,) + singles[j][0].shape[1:]
            assembled[j] = jax.make_array_from_single_device_arrays(
                shape, sharding, singles[j]
            )
    else:
        # size the dense fallback WITHOUT touching shard data: the manifest
        # records one shard's uncompressed bytes (pre-r5 manifests lack the
        # key; fall back to decompressing shard 0's arrays for their shapes)
        if "shard_bytes" in z.files:
            shard_bytes = int(z["shard_bytes"])
        else:
            with _open_shard(0) as z0:
                shard_bytes = 0
                for j in dev_idx:
                    c = z0[f"child_{j}"]  # one decompression per child
                    shard_bytes += int(np.prod(c.shape)) * c.dtype.itemsize
        total = shard_bytes * p
        if total > _host_materialize_budget() and not allow_host_materialize:
            raise ValueError(
                f"sharded checkpoint {path} holds ~{total / 2**30:.1f} GiB "
                f"across {p} shards but only {len(jax.devices())} device(s) "
                f"are visible — the mesh-free fallback would materialize all "
                f"of it in host memory. Load on a mesh of >= {p} devices, "
                f"pass allow_host_materialize=True to load_tree (CLI: "
                f"`query --allow-host-materialize`), or raise "
                f"KDTREE_TPU_HOST_MATERIALIZE_BYTES."
            )
        parts = {j: [] for j in dev_idx}
        for i in range(p):
            with _open_shard(i) as zs:
                for j in dev_idx:
                    parts[j].append(zs[f"child_{j}"])
        for j in dev_idx:
            assembled[j] = jnp.concatenate(parts[j], axis=0)
    children = tuple(
        assembled[j] if mask[j] else jnp.asarray(z[f"repl_{j}"])
        for j in range(nchild)
    )
    return cls.tree_unflatten(aux, children), meta


def load_tree(path: str, allow_host_materialize: bool = False):
    """Returns (tree, meta); the tree type round-trips via the saved kind.

    ``allow_host_materialize`` opts in to the dense host fallback when a
    sharded checkpoint is loaded without a big-enough mesh (see
    ``_load_sharded``).
    """
    import jax.numpy as jnp

    with np.load(path) as z:
        meta = {
            k[len("meta_"):]: z[k].item() if z[k].ndim == 0 else z[k]
            for k in z.files
            if k.startswith("meta_")
        }
        if "format" in z.files and str(z["format"]) == "sharded-v1":
            tree, meta = _load_sharded(path, z, meta, allow_host_materialize)
            from kdtree_tpu.utils.guards import validate_loaded_tree

            validate_loaded_tree(tree)
            return tree, meta
        if "kind" not in z.files:  # legacy round-1 format: classic tree only
            from kdtree_tpu.models.tree import KDTree

            tree = KDTree(
                points=jnp.asarray(z["points"]),
                node_point=jnp.asarray(z["node_point"]),
                split_val=jnp.asarray(z["split_val"]),
            )
        else:
            cls = _registry()[str(z["kind"])]
            nchild = sum(1 for k in z.files if k.startswith("child_"))
            children = tuple(jnp.asarray(z[f"child_{i}"]) for i in range(nchild))
            aux = tuple(int(a) for a in z["aux"]) if "aux" in z.files else None
            tree = cls.tree_unflatten(aux, children)
    from kdtree_tpu.utils.guards import validate_loaded_tree

    validate_loaded_tree(tree)  # NaN in a checkpoint = corruption, fail here
    return tree, meta
