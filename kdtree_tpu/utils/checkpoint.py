"""Tree serialization.

The reference's tree lives only in process memory (heap ``Node``s freed at
exit, ``Utility.cpp:40-45``) — no persistence at all. The implicit-array
representation makes checkpointing trivial: three arrays to npz. Save/load is
deterministic and device-agnostic (arrays come back on the default device).
"""

from __future__ import annotations

import numpy as np

from kdtree_tpu.models.tree import KDTree


def save_tree(path: str, tree: KDTree, meta: dict | None = None) -> None:
    """Save a tree plus optional provenance metadata (seed, generator, ...)
    so a later load can reconstruct the matching problem instead of trusting
    the caller to pass consistent flags."""
    extra = {f"meta_{k}": np.asarray(v) for k, v in (meta or {}).items()}
    np.savez_compressed(
        path,
        points=np.asarray(tree.points),
        node_point=np.asarray(tree.node_point),
        split_val=np.asarray(tree.split_val),
        **extra,
    )


def load_tree(path: str) -> tuple[KDTree, dict]:
    """Returns (tree, meta) where meta holds whatever save_tree recorded."""
    import jax.numpy as jnp

    with np.load(path) as z:
        tree = KDTree(
            points=jnp.asarray(z["points"]),
            node_point=jnp.asarray(z["node_point"]),
            split_val=jnp.asarray(z["split_val"]),
        )
        meta = {
            k[len("meta_"):]: z[k].item() if z[k].ndim == 0 else z[k]
            for k in z.files
            if k.startswith("meta_")
        }
    return tree, meta
