"""Tree serialization.

The reference's tree lives only in process memory (heap ``Node``s freed at
exit, ``Utility.cpp:40-45``) — no persistence at all. The implicit-array
representation makes checkpointing trivial: every tree class here is a
registered pytree of arrays plus static aux ints, so save/load is a generic
flatten → npz → unflatten round trip. Deterministic and device-agnostic
(arrays come back on the default device). Provenance metadata (seed,
generator, ...) rides along so a later load can reconstruct the matching
problem instead of trusting the caller to pass consistent flags.
"""

from __future__ import annotations

import numpy as np


def _registry():
    from kdtree_tpu.models.tree import KDTree
    from kdtree_tpu.ops.bucket import BucketKDTree
    from kdtree_tpu.ops.morton import MortonTree
    from kdtree_tpu.parallel.global_exact import GlobalExactTree
    from kdtree_tpu.parallel.global_morton import GlobalMortonForest
    from kdtree_tpu.parallel.global_tree import GlobalKDTree

    return {
        "classic": KDTree,
        "bucket": BucketKDTree,
        "morton": MortonTree,
        "global": GlobalKDTree,
        "global-morton": GlobalMortonForest,
        "global-exact": GlobalExactTree,
    }


def save_tree(path: str, tree, meta: dict | None = None) -> None:
    """Save any framework tree (KDTree / BucketKDTree / GlobalKDTree) + meta."""
    kinds = _registry()
    kind = next((k for k, cls in kinds.items() if isinstance(tree, cls)), None)
    if kind is None:
        raise TypeError(f"not a checkpointable tree: {type(tree)!r}")
    # the class protocol (not tree_flatten utils) so aux static ints persist
    children, aux = type(tree).tree_flatten(tree)
    payload = {f"child_{i}": np.asarray(c) for i, c in enumerate(children)}
    if aux is not None:
        # the format stores aux as a flat i64 vector; anything richer (nested
        # tuples, dtypes, strings) must fail HERE, not corrupt a later load
        if not all(isinstance(a, (int, np.integer)) for a in aux):
            raise TypeError(
                f"{type(tree).__name__}.tree_flatten aux must be a flat tuple "
                f"of ints for checkpointing, got {aux!r}"
            )
        payload["aux"] = np.asarray(aux, dtype=np.int64)
    payload["kind"] = np.asarray(kind)
    payload.update({f"meta_{k}": np.asarray(v) for k, v in (meta or {}).items()})
    np.savez_compressed(path, **payload)


def load_tree(path: str):
    """Returns (tree, meta); the tree type round-trips via the saved kind."""
    import jax.numpy as jnp

    with np.load(path) as z:
        meta = {
            k[len("meta_"):]: z[k].item() if z[k].ndim == 0 else z[k]
            for k in z.files
            if k.startswith("meta_")
        }
        if "kind" not in z.files:  # legacy round-1 format: classic tree only
            from kdtree_tpu.models.tree import KDTree

            tree = KDTree(
                points=jnp.asarray(z["points"]),
                node_point=jnp.asarray(z["node_point"]),
                split_val=jnp.asarray(z["split_val"]),
            )
        else:
            cls = _registry()[str(z["kind"])]
            nchild = sum(1 for k in z.files if k.startswith("child_"))
            children = tuple(jnp.asarray(z[f"child_{i}"]) for i in range(nchild))
            aux = tuple(int(a) for a in z["aux"]) if "aux" in z.files else None
            tree = cls.tree_unflatten(aux, children)
    from kdtree_tpu.utils.guards import validate_loaded_tree

    validate_loaded_tree(tree)  # NaN in a checkpoint = corruption, fail here
    return tree, meta
