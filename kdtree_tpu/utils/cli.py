"""Command-line interface.

Covers both reference entry modes (SURVEY.md C10) plus framework subcommands:

- ``harness``: the course grading protocol — ``READY`` on stdout, seed from
  stdin (interactive; hardcoded dim=128, n=500000 like ``Utility.cpp:92-102``)
  or ``SEED DIM NUM_POINTS`` argv mode (``Utility.cpp:104-120``), result lines
  ``ID: <id> \t DISTANCE: <d>`` (``Utility.cpp:122-124``), then ``DONE``.
  Unlike the reference, no compile-time DEBUG gate — both modes always exist.
- ``bench``: per-phase timing (gen/build/query) with compile separated.
- ``build`` / ``query``: build-and-save / load-and-query (npz checkpoint).
- ``stats``: render a ``--metrics-out`` telemetry report human-readably.

Any subcommand run with the top-level ``--metrics-out PATH`` flag writes a
one-shot JSON telemetry report (metrics registry + spans + JAX runtime
facts — see docs/OBSERVABILITY.md) on exit, including failed exits: a
degraded run's report is exactly the one worth reading.

Engine selection is honest about hardware: ``auto`` picks by measured
crossovers (see ``_resolve_engine``) — MXU brute force in high D (the
curse-of-dimensionality regime that masked the reference's sort bug,
SURVEY.md §3.5) and for small scan jobs, the tiled Pallas engine for dense
low-D query batches, the Morton tree otherwise. All engines are exact, so
results agree.
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

NUM_QUERIES = 10  # hardcoded in the reference: kdtree_sequential.cpp:144
HARNESS_DIM = 128  # Utility.cpp:98
HARNESS_NUM_POINTS = 500000  # Utility.cpp:99
AUTO_TREE_DIM_MAX = 16


def _validate_input(seed: int, dim: int, num_points: int) -> None:
    """Mirrors Utility::validate_input (Utility.cpp:66-89) incl. exit codes."""
    if seed == 0:
        print("Warning: default value 0 used as seed.", file=sys.stderr)
    if seed < 0:
        print("Seed has to be larger than 0!", file=sys.stderr)
        sys.exit(1)
    if dim <= 0:
        print("Dimension has to be larger than 0!", file=sys.stderr)
        sys.exit(1)
    if num_points <= 0:
        print("Number of points has to be larger than 0!", file=sys.stderr)
        sys.exit(1)
    print(f"\tUsing seed {seed}", file=sys.stderr)
    print(f"\tUsing point dimensions {dim}", file=sys.stderr)
    print(f"\tUsing number of points {num_points}\n", file=sys.stderr)


def _format_distance(d: float) -> str:
    """C++ ``std::cout << float`` default formatting (6 significant digits)."""
    return f"{d:g}"


def print_result_line(point_id: int, distance: float, file=None) -> None:
    # exact byte layout of Utility.cpp:123: "ID: <id> \t DISTANCE: <d>".
    # file=None resolves to sys.stdout at CALL time (a def-time sys.stdout
    # default would bypass contextlib.redirect_stdout for in-process
    # drivers of main())
    print(f"ID: {point_id} \t DISTANCE: {_format_distance(distance)}", file=file)


def _generate(seed: int, dim: int, num_points: int, generator: str):
    """(points, queries, generator_used) by generator choice; mt19937 replays
    the reference stream bit-exactly (native C++), threefry is the TPU-native
    default. The returned generator name is what actually ran (the mt19937
    path falls back to threefry without a toolchain) — checkpoint provenance
    must record *that*, not the request.

    The threefry problem is the counter-based ROW stream
    (``generate_points_rowwise``), not ``generate_problem``'s block draws:
    one seeded problem definition for every engine, so a generative engine
    (shard-local generation, no [N, D] anywhere) and a materialized one
    answer identically under the same CLI flags."""
    if generator == "mt19937":
        from kdtree_tpu import native

        if not native.available():
            print("native generator unavailable; falling back to threefry", file=sys.stderr)
            generator = "threefry"
        else:
            import jax.numpy as jnp

            pts, qs = native.generate_problem_mt19937(seed, dim, num_points, NUM_QUERIES)
            return jnp.asarray(pts), jnp.asarray(qs), "mt19937"
    from kdtree_tpu.ops.generate import generate_points_rowwise, generate_queries

    pts = generate_points_rowwise(seed, dim, num_points)
    qs = generate_queries(seed, dim, NUM_QUERIES)
    return pts, qs, "threefry"


def _generate_queries(seed: int, dim: int, num_points: int, generator: str):
    """Only the NUM_QUERIES query rows — never materializes the N points.

    mt19937: the native generator supports arbitrary row windows, so rows
    [N, N+10) come straight off the stream (the reference's MPI discard trick,
    kdtree_mpi.cpp:19-41, generalized). threefry: generate_queries is
    bit-identical to generate_problem's query block by construction.

    Unlike generation at build time, there is NO fallback here: the points are
    frozen in a checkpoint, so swapping generators could only produce queries
    from a different problem — that must be an error, never a warning.
    """
    if generator == "mt19937":
        from kdtree_tpu import native

        if not native.available():
            raise SystemExit(
                "checkpoint was built with the mt19937 generator but the "
                "native generator is unavailable here (no g++ toolchain); "
                "refusing to answer queries from a different problem"
            )
        import jax.numpy as jnp

        return jnp.asarray(native.generate_rows(seed, dim, num_points, NUM_QUERIES))
    from kdtree_tpu.ops.generate import generate_queries

    return generate_queries(seed, dim, NUM_QUERIES)


def _dense_lowd(q: int, n: int, dim: int) -> bool:
    """The measured tiled-engine crossover — canonical definition lives in
    :func:`kdtree_tpu.ops.tile_query.dense_lowd` (lazy import keeps the CLI
    startup free of jax until an engine actually runs)."""
    from kdtree_tpu.ops.tile_query import dense_lowd

    return dense_lowd(q, n, dim)


def _resolve_engine(engine: str, dim: int, q: int | None = None,
                    n: int | None = None) -> str:
    """Q-aware engine choice, grounded in v5e measurements (round 3,
    n=1M..16M, exactness identical across engines so only speed differs):

    - high D: the k-d prune is dead (curse of dimensionality), and the MXU
      brute scan beat the DFS tree by 64x at D=16 / Q=4096 — brute force.
    - dense low-D batches (Q >= n/64): the Hilbert-tiled Pallas engine won
      4x over brute at the north-star shape (1M queries, 16M pts, D=3);
      sparse batches invert (tiled lost 15x at Q=4096 over 1M pts) because
      each sparse tile's box covers most buckets — so density gates it.
    - small jobs (Q*n*D scan work under ~2e13 madds, i.e. sub-second):
      brute force; a tree build cannot pay for itself.
    - remainder (big sparse low-D): the Morton DFS tree.
    """
    if engine != "auto":
        return engine
    if dim > AUTO_TREE_DIM_MAX:
        return "bruteforce"
    if q is not None and n is not None:
        if _dense_lowd(q, n, dim):
            return "tiled"
        if q * n * dim <= 2e13:
            return "bruteforce"
    return "morton"


def _build_index(points, engine: str, mesh_devices: int | None = None,
                 problem=None, slack: float | None = None):
    """Build phase: the index object for an engine.

    ``problem`` = (seed, dim, num_points) is required by the generative
    ``global-morton`` engine, whose build NEVER materializes the [N, D]
    array (shard-local generation is fused into the build; ``points`` is
    ignored there and may be None). ``slack`` overrides the scale engines'
    exchange-capacity factor (the overflow errors name it as the remedy).
    """
    if engine in ("morton", "tiled"):
        from kdtree_tpu.ops.morton import build_morton

        return build_morton(points)
    if engine == "tree":
        from kdtree_tpu.ops.build import build_jit

        return build_jit(points)
    if engine == "bucket":
        from kdtree_tpu.ops.bucket import build_bucket

        return build_bucket(points)
    if engine == "bruteforce":
        return points  # the index IS the point array (MXU distance scans)
    if engine == "global":
        from kdtree_tpu.parallel import make_mesh
        from kdtree_tpu.parallel.global_tree import build_global

        return build_global(points, mesh=make_mesh(mesh_devices))
    if engine == "global-morton":
        from kdtree_tpu.parallel import make_mesh
        from kdtree_tpu.parallel.global_morton import build_global_morton

        seed, dim, num_points = problem[:3]
        kw = {} if slack is None else {"slack": slack}
        return build_global_morton(
            seed, dim, num_points, mesh=make_mesh(mesh_devices),
            distribution=_problem_distribution(problem), **kw,
        )
    if engine == "global-exact":
        from kdtree_tpu.parallel import make_mesh
        from kdtree_tpu.parallel.global_exact import build_global_exact

        seed, dim, num_points = problem[:3]
        kw = {} if slack is None else {"slack": slack}
        return build_global_exact(
            seed, dim, num_points, mesh=make_mesh(mesh_devices),
            distribution=_problem_distribution(problem), **kw,
        )
    raise SystemExit(f"engine {engine!r} has no split build phase")


def _problem_distribution(problem) -> str:
    """problem is (seed, dim, n) or (seed, dim, n, distribution)."""
    return problem[3] if len(problem) > 3 else "uniform"


def _check_distribution(engine: str, dist: str) -> None:
    """Non-uniform row streams exist only for the generative scale engines
    (shard-local generation); one guard shared by bench and build so the
    two subcommands can't drift."""
    if dist != "uniform" and engine not in ("global-morton", "global-exact"):
        print(f"--distribution {dist} needs a generative scale engine "
              "(global-morton / global-exact); other engines define their "
              "problems by the uniform stream or user --points data",
              file=sys.stderr)
        sys.exit(1)


def _query_index(index, queries, k: int, engine: str,
                 mesh_devices: int | None = None):
    """Query phase against the object _build_index returned."""
    if engine == "morton":
        from kdtree_tpu.ops.morton import morton_knn

        return morton_knn(index, queries, k=k)
    if engine == "tiled":
        from kdtree_tpu.ops.tile_query import morton_knn_tiled

        return morton_knn_tiled(index, queries, k=k)
    if engine == "tree":
        from kdtree_tpu.ops.query import knn

        return knn(index, queries, k=k)
    if engine == "bucket":
        from kdtree_tpu.ops.bucket import bucket_knn

        return bucket_knn(index, queries, k=k)
    if engine == "bruteforce":
        from kdtree_tpu.ops import bruteforce

        return bruteforce.knn(index, queries, k=k)
    if engine == "global":
        from kdtree_tpu.parallel.global_tree import global_knn

        return global_knn(index, queries, k=k)
    if engine == "global-morton":
        from kdtree_tpu.parallel import make_mesh
        from kdtree_tpu.parallel.global_morton import global_morton_query

        return global_morton_query(
            index, queries, k=k, mesh=make_mesh(mesh_devices)
        )
    if engine == "global-exact":
        from kdtree_tpu.parallel import make_mesh
        from kdtree_tpu.parallel.global_exact import global_exact_query

        return global_exact_query(
            index, queries, k=k, mesh=make_mesh(mesh_devices)
        )
    raise SystemExit(f"engine {engine!r} has no split query phase")


def _solve(points, queries, k: int, engine: str, mesh_devices: int | None = None,
           problem=None):
    """Returns (d2[Q,k], idx[Q,k]) by the chosen engine."""
    dim = queries.shape[1]
    n = points.shape[0] if points is not None else (problem[2] if problem else None)
    engine = _resolve_engine(engine, dim, q=queries.shape[0], n=n)
    if engine == "ensemble":
        # deliberately fused: local build + query + merge is ONE SPMD program
        # (the reference MPI semantics, kdtree_mpi.cpp:204-253)
        from kdtree_tpu.parallel import ensemble_knn, ensemble_knn_gen, make_mesh

        mesh = make_mesh(mesh_devices)
        if points is None:
            # generative seeded problem: shard-local generation fused into
            # the SPMD program — no [N, D] array anywhere (the reference's
            # discard trick, kdtree_mpi.cpp:19-41)
            seed, pdim, num_points = problem[:3]
            return ensemble_knn_gen(seed, pdim, num_points, queries, k=k,
                                    mesh=mesh)
        return ensemble_knn(points, queries, k=k, mesh=mesh)
    index = _build_index(points, engine, mesh_devices, problem=problem)
    return _query_index(index, queries, k, engine, mesh_devices)


def _generative(engine: str, generator: str) -> bool:
    """Engines whose build consumes the seeded row stream shard-locally,
    never materializing [N, D]. The global engines are generative by
    construction; ensemble is generative exactly when the problem is the
    threefry stream (mt19937 replay requires the materialized sequential
    stream for bit-exactness — its per-rank window trick would still build
    the full array on the host)."""
    return engine in ("global-morton", "global-exact") or (
        engine == "ensemble" and generator == "threefry"
    )


def cmd_harness(args) -> None:
    if args.spec:
        # argv mode (Utility.cpp:104-120): READY after arg count check
        print("READY", flush=True)
        try:
            seed, dim, num_points = (int(x) for x in args.spec)
        except ValueError:
            print(f"Invalid problem spec {args.spec!r}: SEED DIM_POINTS "
                  "NUM_POINTS must be integers", file=sys.stderr)
            sys.exit(1)
    else:
        # interactive mode (Utility.cpp:92-102)
        print("READY", flush=True)
        print("Specify seed ", file=sys.stderr, end="", flush=True)
        try:
            seed = int(sys.stdin.readline())
        except ValueError:
            # mirror the reference's cin>> failed-extraction path
            # (Utility.cpp:95-97 leaves seed at its default): warn + seed 0
            print("Invalid seed input; using default seed 0", file=sys.stderr)
            seed = 0
        dim, num_points = HARNESS_DIM, HARNESS_NUM_POINTS
    _validate_input(seed, dim, num_points)

    engine = _resolve_engine(args.engine, dim, q=NUM_QUERIES, n=num_points)
    if _generative(engine, args.generator):
        # generative engine: the point set is the threefry row stream,
        # shard-generated inside the build — never materialized here.
        # (ensemble joins this path only under --generator threefry: its
        # mt19937 mode keeps the bit-exact materialized reference replay)
        if args.generator != "threefry":
            print(f"note: {engine} defines its points by the threefry "
                  "row stream (shard-local generation); using threefry "
                  "queries", file=sys.stderr)
        from kdtree_tpu.ops.generate import generate_queries

        queries = generate_queries(seed, dim, NUM_QUERIES)
        d2, _ = _solve(None, queries, k=1, engine=engine,
                       mesh_devices=args.devices,
                       problem=(seed, dim, num_points))
    else:
        points, queries, _ = _generate(seed, dim, num_points, args.generator)
        d2, _ = _solve(points, queries, k=1, engine=engine,
                       mesh_devices=args.devices)
    dists = np.sqrt(np.asarray(d2[:, 0], dtype=np.float64))
    for q in range(NUM_QUERIES):
        # reference query ids are num_points + q (kdtree_sequential.cpp:170)
        print_result_line(num_points + q, float(dists[q]))
    print("DONE", flush=True)


def cmd_bench(args) -> None:
    import contextlib

    from kdtree_tpu.utils.timing import PhaseTimer

    engine = _resolve_engine(args.engine, args.dim, q=NUM_QUERIES, n=args.n)
    fused_gen = _generative(engine, args.generator)  # gen is fused into the build
    fused_bq = engine == "ensemble"  # one SPMD program by design

    dist = getattr(args, "distribution", "uniform")
    _check_distribution(engine, dist)

    def run(seed: int, timer: PhaseTimer | None):
        t = timer or PhaseTimer()
        problem = (seed, args.dim, args.n, dist)
        if fused_gen:
            from kdtree_tpu.ops.generate import generate_queries

            with t.phase("generate") as h:
                queries = generate_queries(seed, args.dim, NUM_QUERIES)
                h += [queries]
            points = None
        else:
            with t.phase("generate") as h:
                points, queries, _ = _generate(seed, args.dim, args.n,
                                               args.generator)
                h += [points, queries]
        if fused_bq:
            with t.phase("build+query") as h:
                d2, idx = _solve(points, queries, k=args.k, engine=engine,
                                 mesh_devices=args.devices, problem=problem)
                h += [d2, idx]
        else:
            with t.phase("build") as h:
                index = _build_index(points, engine, args.devices,
                                     problem=problem)
                h += [index]
            with t.phase("query") as h:
                d2, idx = _query_index(index, queries, args.k, engine,
                                       args.devices)
                h += [d2, idx]
        return d2

    import time as _time

    import jax

    from kdtree_tpu.obs import jaxrt

    # device-init duration + platform/device-count facts land in the
    # registry (and thus any --metrics-out report) before any compile
    t0 = _time.perf_counter()
    devices = jax.devices()
    jaxrt.record_device_init(_time.perf_counter() - t0)

    # warmup on a distinct seed: compiles everything, excluded from timing.
    # Timed run uses a fresh seed — re-running a jitted fn on the very same
    # arrays can report ~0s (see .claude/skills/verify/SKILL.md).
    np.asarray(run(args.seed + 1000, None))

    timer = PhaseTimer()
    trace = (jax.profiler.trace(args.trace) if getattr(args, "trace", None)
             else contextlib.nullcontext())
    with trace:
        run(args.seed, timer)
    rep = timer.report()
    # pts/s excludes generation for every engine (for global-morton the
    # "generate" phase is only the 10 queries; its point generation is fused
    # into the build by design and cannot be excluded)
    solve_s = rep["total"] - rep["generate"]
    rep.update(
        n=args.n, dim=args.dim, k=args.k, engine=engine,
        pts_per_sec=(args.n / solve_s) if solve_s > 0 else None,
        platform=devices[0].platform, device_count=len(devices),
    )
    print(json.dumps(rep))


def _build_tree_for_engine(points, engine: str, mesh_devices: int | None,
                           problem=None, slack: float | None = None):
    """Build the tree object matching the engine choice (for checkpointing).

    "auto" resolves to the Morton tree — same as _solve's auto for low D, and
    still the right checkpoint for high D (exact; a loaded tree answers with
    morton_knn even where the harness's auto would have used brute force).
    "tiled" shares the Morton tree (it is a query strategy, not an index)."""
    if engine in ("auto", "morton", "tiled"):
        from kdtree_tpu.ops.morton import build_morton

        return build_morton(points)
    if engine in ("bucket", "tree", "global", "global-morton", "global-exact"):
        return _build_index(points, engine, mesh_devices, problem=problem,
                            slack=slack)
    raise SystemExit(f"engine {engine!r} does not produce a checkpointable tree")


def _tree_knn(tree, queries, k: int):
    """Dispatch k-NN on whichever tree type a checkpoint contained.

    Dense low-D query batches route to the tiled engines (same measured
    crossover as ``_resolve_engine``: the per-query DFS is ~100x slower at
    the north-star query shape) — this matters for ``query --queries`` with
    a big user file."""
    from kdtree_tpu.models.tree import KDTree
    from kdtree_tpu.ops.bucket import BucketKDTree, bucket_knn
    from kdtree_tpu.ops.morton import MortonTree, morton_knn
    from kdtree_tpu.parallel.global_exact import (
        GlobalExactTree, global_exact_query,
    )
    from kdtree_tpu.parallel.global_morton import (
        GlobalMortonForest, global_morton_query,
    )
    from kdtree_tpu.parallel.global_tree import GlobalKDTree, global_knn

    q, dim = queries.shape

    def dense(n):
        return _dense_lowd(q, n, dim)

    if isinstance(tree, GlobalMortonForest):
        # global_morton_query routes dense batches to the tiled engine
        # itself (same crossover) and falls back to the mesh-free query
        # when the local device count doesn't match the forest's build mesh
        return global_morton_query(tree, queries, k=k)
    if isinstance(tree, GlobalExactTree):
        # same mesh-free portability contract as the Morton forest
        return global_exact_query(tree, queries, k=k)
    if isinstance(tree, MortonTree):
        if dense(tree.n_real):
            from kdtree_tpu.ops.tile_query import morton_knn_tiled

            return morton_knn_tiled(tree, queries, k=k)
        return morton_knn(tree, queries, k=k)
    if isinstance(tree, BucketKDTree):
        if dense(tree.n_real):
            def bucket_flat():
                import jax.numpy as jnp

                # the bucket tree's SPLIT points live in the internal nodes,
                # not in any bucket — the view must include both (absent
                # node slots masked to the standard inf/-1 padding)
                node_pts = jnp.where(
                    (tree.node_gid >= 0)[:, None], tree.node_coords, jnp.inf
                )
                flat = jnp.concatenate(
                    [tree.bucket_pts.reshape(-1, dim), node_pts], axis=0
                )
                gids = jnp.concatenate(
                    [tree.bucket_gid.reshape(-1), tree.node_gid]
                )
                return dict(points=flat, gid=gids, n_real=tree.n_real)

            out = _serve_dense_via_view(tree, queries, k, bucket_flat)
            if out is not None:
                return out
        return bucket_knn(tree, queries, k=k)
    if isinstance(tree, GlobalKDTree):
        return global_knn(tree, queries, k=k)
    assert isinstance(tree, KDTree)
    if dense(tree.points.shape[0]):
        # classic tree stores the original [N, D] array; its Morton view
        # serves dense batches with ids that are already original rows
        out = _serve_dense_via_view(
            tree, queries, k, lambda: dict(points=tree.points)
        )
        if out is not None:
            return out
    from kdtree_tpu import knn

    return knn(tree, queries, k=k)


def _serve_dense_via_view(tree, queries, k: int, make_flat):
    """Serve a dense batch on a checkpointed classic/bucket tree with the
    tiled engine via the shared cached-view helper; None (caller falls
    back to its memory-lean DFS engine) when the view won't fit."""
    from kdtree_tpu.ops.morton import serving_view
    from kdtree_tpu.ops.tile_query import morton_knn_tiled

    view = serving_view(tree, make_flat)
    if view is None:
        return None
    return morton_knn_tiled(view, queries, k=k)


def _load_array(path: str, what: str) -> "np.ndarray":
    """Load a user-supplied [N, D] f32 array (.npy, or .npz key 'points'/
    'queries'/first array). Rejects NaN rows loudly (SURVEY §5 guards)."""
    import zipfile

    try:
        arr = np.load(path, allow_pickle=False)
        if hasattr(arr, "files"):  # npz
            for key in (what, "points", "queries"):
                if key in arr.files:
                    arr = arr[key]
                    break
            else:
                arr = arr[arr.files[0]]
        arr = np.asarray(arr, dtype=np.float32)
    except (OSError, ValueError, zipfile.BadZipFile) as e:
        # missing file, corrupt npz, object-dtype arrays under
        # allow_pickle=False, non-numeric dtypes — same crisp stderr +
        # exit-code contract as the other validation branches (C10)
        print(f"cannot load {what} file {path}: {e}", file=sys.stderr)
        sys.exit(1)
    if arr.ndim != 2:
        print(f"{what} file {path} must be [N, D], got shape {arr.shape}",
              file=sys.stderr)
        sys.exit(1)
    if arr.shape[0] < 1 or arr.shape[1] < 1:
        # an empty axis would fail deep inside the engines with an opaque
        # reshape/reduction error — reject it at the door instead
        print(f"{what} file {path} must be non-empty [N, D], got shape "
              f"{arr.shape}", file=sys.stderr)
        sys.exit(1)
    if not np.isfinite(arr).all():
        print(f"{what} file {path} contains non-finite values", file=sys.stderr)
        sys.exit(1)
    return arr


def _open_points_streaming(path: str) -> "np.ndarray":
    """Open a user point file for shard-block streaming ingest.

    ``.npy`` opens as a memmap — the scale tier's whole reason to ingest is
    files bigger than one host/device can hold, so the array must never
    fully materialize here (per-block finiteness checks happen in
    ``_stream_rows_to_mesh`` as each shard block is touched). Anything else
    (npz, odd dtypes) falls back to the validating in-memory loader."""
    if path.endswith(".npy"):
        try:
            arr = np.load(path, mmap_mode="r", allow_pickle=False)
        except (OSError, ValueError) as e:
            print(f"cannot load points file {path}: {e}", file=sys.stderr)
            sys.exit(1)
        if arr.ndim != 2 or arr.shape[0] < 1 or arr.shape[1] < 1:
            print(f"points file {path} must be non-empty [N, D], got shape "
                  f"{arr.shape}", file=sys.stderr)
            sys.exit(1)
        if not np.issubdtype(arr.dtype, np.number):
            print(f"points file {path} must be numeric, got dtype "
                  f"{arr.dtype}", file=sys.stderr)
            sys.exit(1)
        return arr
    return _load_array(path, "points")


def cmd_build(args) -> None:
    from kdtree_tpu.utils.checkpoint import save_tree

    dist = getattr(args, "distribution", "uniform")
    _check_distribution(args.engine, dist)
    if not args.out and not getattr(args, "save", None):
        print("build needs --out FILE (npz checkpoint) and/or --save DIR "
              "(serving snapshot)", file=sys.stderr)
        sys.exit(1)
    if getattr(args, "points", None):
        # user data, not a seeded problem: build over an arbitrary point set
        # (the reference can only generate; a framework must also ingest)
        if args.engine == "global-exact":
            print("engine global-exact is generative (exact-median row "
                  "streams); use global-morton for scale-tier --points "
                  "ingest, or a materialized engine", file=sys.stderr)
            sys.exit(1)
        if args.engine == "global-morton":
            from kdtree_tpu.parallel import make_mesh

            import os

            # PRE-SHARDED ingest intent: a {i} placeholder, or any other
            # braces that do NOT name an existing file — so a malformed
            # placeholder like {i:02d} is rejected crisply here instead of
            # falling through to a confusing file-load error, while a real
            # single file whose PATH happens to contain literal braces
            # ("runs{v2}/points.npy") still loads through the plain branch
            if "{i}" in args.points or (
                ("{" in args.points or "}" in args.points)
                and not os.path.exists(args.points)
            ):
                # maps file i -> device i verbatim, no redistribution
                # (exactness only needs the shards to partition the point
                # set — right for spatially-partitioned exports the
                # sample-sort exchange would concentrate onto one
                # destination)
                import glob as globmod

                from kdtree_tpu.parallel.global_morton import (
                    build_global_morton_from_shard_files,
                )

                # only the LITERAL {i} placeholder is substituted; a
                # formatted variant like {i:02d} would format fine but the
                # stray-file glob below only knows "{i}" — its pattern
                # would keep the braces verbatim, match nothing, and the
                # gap check would silently pass on a partial dataset
                if "{" in args.points.replace("{i}", "") or \
                        "}" in args.points.replace("{i}", ""):
                    print(f"bad --points pattern {args.points}: only the "
                          "literal {i} placeholder is supported (no format "
                          "specs like {i:02d}, no other fields)",
                          file=sys.stderr)
                    sys.exit(1)
                paths = []
                while os.path.exists(args.points.format(i=len(paths))):
                    paths.append(args.points.format(i=len(paths)))
                if not paths:
                    print(f"no shard files match {args.points} (i=0...)",
                          file=sys.stderr)
                    sys.exit(1)
                # a GAP in the sequence (part-3 deleted) would silently
                # index a partial dataset: every file matching the pattern
                # must be part of the contiguous 0..P-1 run. The literal
                # parts are glob-escaped — a path with [, ?, or * in it
                # must match itself, not act as a wildcard that matches
                # nothing and waves the gap check through
                glob_pat = "*".join(
                    globmod.escape(part)
                    for part in args.points.split("{i}")
                )
                stray = set(globmod.glob(glob_pat)) - set(paths)
                if stray:
                    print(f"shard sequence has a gap: {len(paths)} "
                          f"contiguous file(s) from i=0, but also found "
                          f"{sorted(stray)[:3]}... — refusing to build a "
                          "partial index", file=sys.stderr)
                    sys.exit(1)
                if args.devices is not None and args.devices != len(paths):
                    print(f"--devices {args.devices} conflicts with "
                          f"{len(paths)} shard files (file i maps to "
                          "device i verbatim)", file=sys.stderr)
                    sys.exit(1)
                try:
                    tree = build_global_morton_from_shard_files(paths)
                except (OSError, ValueError) as e:
                    print(f"cannot build from {args.points}: {e}",
                          file=sys.stderr)
                    sys.exit(1)
                n, dim = tree.num_points, tree.dim
                meta = {"generator": "file"}
            else:
                # scale-tier ingest (VERDICT r4 missing #3): rows stream
                # host -> mesh block-cyclically (memmap for .npy — the file
                # never fully materializes on the host), then the standard
                # one-all_to_all sample-sort partition
                from kdtree_tpu.parallel.global_morton import (
                    build_global_morton_from_points,
                )

                arr = _open_points_streaming(args.points)
                skw = ({} if getattr(args, "slack", None) is None
                       else {"slack": args.slack})
                try:
                    tree = build_global_morton_from_points(
                        arr, mesh=make_mesh(args.devices), **skw)
                except (ValueError, RuntimeError) as e:
                    print(f"cannot build from {args.points}: {e}",
                          file=sys.stderr)
                    sys.exit(1)
                n, dim = arr.shape
                meta = {"generator": "file"}
        else:
            import jax.numpy as jnp

            points = jnp.asarray(_load_array(args.points, "points"))
            tree = _build_tree_for_engine(points, args.engine, args.devices)
            n, dim = points.shape
            meta = {"generator": "file"}
    elif args.engine in ("global-morton", "global-exact"):
        # generative: never materialize [N, D]; provenance = threefry rows
        if args.generator != "threefry":
            print(f"note: {args.engine} defines its points by the threefry "
                  "row stream (shard-local generation); --generator "
                  f"{args.generator} does not apply", file=sys.stderr)
        try:
            tree = _build_tree_for_engine(
                None, args.engine, args.devices,
                problem=(args.seed, args.dim, args.n, dist),
                slack=getattr(args, "slack", None),
            )
        except RuntimeError as e:
            # sample-sort capacity overflow (now user-reachable via
            # --slack) — crisp stderr + exit code, not a traceback (C10)
            print(f"cannot build: {e}", file=sys.stderr)
            sys.exit(1)
        n, dim = args.n, args.dim
        meta = {"seed": args.seed, "generator": "threefry",
                "distribution": dist}
    else:
        points, _, gen_used = _generate(args.seed, args.dim, args.n,
                                        args.generator)
        tree = _build_tree_for_engine(points, args.engine, args.devices)
        n, dim = points.shape
        meta = {"seed": args.seed, "generator": gen_used}
    if args.out:
        try:
            fmt = save_tree(args.out, tree, meta=meta,
                            sharded=True if getattr(args, "sharded", False)
                            else None)
        except TypeError as e:
            # --sharded with an engine whose tree has no device axis: the
            # same crisp stderr + exit-code contract as the other branches
            print(f"cannot save sharded: {e}", file=sys.stderr)
            sys.exit(1)
        suffix = ""
        if fmt == "sharded":
            # the checkpoint is NOT one self-contained file — say so, or
            # the next person copies just the manifest to another machine
            suffix = f" (+ per-device shard files {args.out}.shard*.npz)"
        print(f"saved {type(tree).__name__} (n={n}, dim={dim}) to "
              f"{args.out}{suffix}")
    if getattr(args, "save", None):
        # serving snapshot (docs/SERVING.md "Snapshots & replica
        # fleets"): the built index's device arrays as checksummed flat
        # .npy segments + a versioned manifest, so `serve --snapshot`
        # replicas cold-start in seconds without re-running the build
        from kdtree_tpu import snapshot as snap
        from kdtree_tpu.serve.lifecycle import tree_for_serving

        try:
            serving = tree_for_serving(tree)
        except TypeError as e:
            print(f"cannot snapshot: {e}", file=sys.stderr)
            sys.exit(1)
        keys = snap.plan_keys_for(serving, k=16)
        man = snap.save_snapshot(
            args.save, serving, epoch=0,
            plan_keys=keys,
            # pre-ship any locally settled plan profiles for those keys
            # so replicas cold-starting from this snapshot seed their
            # store and warm up without re-tuning (docs/SERVING.md
            # "Snapshots & replica fleets")
            plan_profiles=snap.collect_plan_profiles(keys),
            meta=dict(meta),
            keep=max(getattr(args, "snapshot_keep", 1) or 1, 1),
        )
        print(f"serving snapshot v{man['version']} (epoch "
              f"{man['epoch']}, n={man['signature']['n_real']}) saved "
              f"to {snap.resolve_dir(args.save)}")


def cmd_partition(args) -> None:
    """Spatial partitioner (docs/SERVING.md "Spatial sharding &
    selective fan-out"): cut one point cloud into N contiguous
    Morton-range shards — each written as a ready-to-serve snapshot
    whose manifest carries the shard's region (grid + code range) and
    whose global ids are the Morton ranks, so the shard's id set AND
    its region are both contiguous. A fleet served from these shards
    gives the router disjoint, tight bounding boxes to prune against:
    the sub-linear fan-out ROADMAP direction 3 names."""
    import os

    import jax.numpy as jnp

    from kdtree_tpu import snapshot as snap
    from kdtree_tpu.ops.morton import morton_view
    from kdtree_tpu.serve import spatial as sp

    if args.shards < 2:
        print(f"--shards must be >= 2 (got {args.shards}); one shard "
              "needs no partition", file=sys.stderr)
        sys.exit(1)
    if args.points:
        pts = np.asarray(_load_array(args.points, "points"),
                         dtype=np.float32)
        src_meta = {"generator": "file", "points": args.points}
    else:
        if args.generator != "threefry":
            print("note: partition's seeded problem is the threefry "
                  f"row stream; --generator {args.generator} does not "
                  "apply", file=sys.stderr)
        from kdtree_tpu.ops.generate import generate_points_rowwise

        pts = np.asarray(
            generate_points_rowwise(args.seed, args.dim, args.n),
            dtype=np.float32,
        )
        src_meta = {"seed": args.seed, "generator": "threefry"}
    try:
        plan = sp.plan_partition(pts, args.shards, bits=args.bits)
    except ValueError as e:
        print(f"cannot partition: {e}", file=sys.stderr)
        sys.exit(1)
    base = snap.resolve_dir(args.out_dir)
    os.makedirs(base, exist_ok=True)
    keep = max(getattr(args, "snapshot_keep", 1) or 1, 1)
    shard_dirs = []
    n_total = pts.shape[0]
    for i, ((s, e), (c0, c1), (blo, bhi)) in enumerate(
        zip(plan["bounds"], plan["code_ranges"], plan["boxes"])
    ):
        rows = plan["order"][s:e]
        # global ids ARE the morton ranks: shard i owns ids [s, e) —
        # contiguous ids and a contiguous code range, by construction
        tree = morton_view(
            jnp.asarray(pts[rows]),
            gid=jnp.asarray(np.arange(s, e, dtype=np.int32)),
            n_real=int(e - s),
        )
        sdir = os.path.join(base, f"shard-{i:02d}")
        plan_keys = snap.plan_keys_for(tree, k=args.k,
                                       max_batch=args.max_batch)
        snap.save_snapshot(
            sdir, tree, epoch=0, id_offset=0,
            plan_keys=plan_keys,
            plan_profiles=snap.collect_plan_profiles(plan_keys),
            meta={**src_meta, "spatial": {
                "grid": plan["grid"].to_json(),
                "code_range": [int(c0), int(c1)],
                "id_range": [int(s), int(e)],
                "shard": i,
                "shards": int(args.shards),
            }},
            keep=keep,
        )
        shard_dirs.append(sdir)
        box = ", ".join(f"[{float(a):g}, {float(b):g}]"
                        for a, b in zip(blo, bhi))
        print(f"shard {i}: n={e - s} ids [{s}, {e}) "
              f"code [{c0}, {c1})  box {box}")
    man_path = sp.write_fleet_manifest(base, plan, shard_dirs)
    print(f"partitioned {n_total} points into {args.shards} "
          f"Morton-range shards under {base} ({man_path})")
    print("serve each with: kdtree-tpu serve --snapshot "
          f"{shard_dirs[0]} --port 0 ...  (id_offset stays 0 — shard "
          "trees answer GLOBAL morton-rank ids directly); then route "
          "them and the router prunes by their /healthz boxes",
          file=sys.stderr)


def cmd_query(args) -> None:
    from kdtree_tpu.utils.checkpoint import load_tree

    import zipfile

    try:
        tree, meta = load_tree(
            args.tree,
            allow_host_materialize=getattr(
                args, "allow_host_materialize", False),
        )
    except (OSError, ValueError, zipfile.BadZipFile) as e:
        # missing manifest, missing sharded sidecar files, corrupt or
        # truncated npz (BadZipFile is neither OSError nor ValueError) —
        # crisp stderr + exit code, not a traceback (C10 contract)
        print(f"cannot load tree {args.tree}: {e}", file=sys.stderr)
        sys.exit(1)
    n = tree.n if hasattr(tree, "n") else tree.n_real
    if getattr(args, "queries", None):
        # user-supplied query set; results go to --out (npz: d2, ids) or,
        # without --out, to stdout in the protocol line format
        import jax.numpy as jnp

        qarr = _load_array(args.queries, "queries")
        if qarr.shape[1] != tree.dim:
            print(f"queries are {qarr.shape[1]}-D but the tree is "
                  f"{tree.dim}-D", file=sys.stderr)
            sys.exit(1)
        if args.k > n:
            # the engines clamp k to n internally — without this note the
            # --out npz would silently have fewer columns than requested
            print(f"note: k={args.k} exceeds the tree's {n} points; "
                  f"returning k={n} neighbors", file=sys.stderr)
        if args.k > 1 and not args.out:
            # protocol lines carry only the nearest distance per query —
            # silently dropping the other k-1 neighbors (and every real
            # neighbor id) would misrepresent the answer
            print("k > 1 results need --out FILE (npz with d2[Q, k] and "
                  "ids[Q, k]); protocol lines only carry the nearest "
                  "distance", file=sys.stderr)
            sys.exit(1)
        d2, ids = _tree_knn(tree, jnp.asarray(qarr), k=args.k)
        if args.out:
            np.savez(args.out, d2=np.asarray(d2), ids=np.asarray(ids))
            print(f"saved d2[{d2.shape[0]}, {d2.shape[1]}] + ids to {args.out}")
            return
        dists = np.sqrt(np.asarray(d2[:, 0], dtype=np.float64))  # ONE fetch
        for q in range(qarr.shape[0]):
            print_result_line(n + q, float(dists[q]))
        print("DONE")
        return
    # the checkpoint's provenance wins over CLI defaults — querying a seed-7
    # tree with seed-42 queries would silently answer a problem that never
    # existed
    if "seed" in meta:
        seed = int(meta["seed"])
    else:
        seed = args.seed if args.seed is not None else 42
    generator = str(meta.get("generator", args.generator))
    if generator == "file":
        print("checkpoint was built from --points data; protocol queries "
              "need --queries FILE", file=sys.stderr)
        sys.exit(1)
    if args.seed is not None and args.seed != seed:
        print(f"note: using checkpoint seed {seed} (ignoring --seed {args.seed})",
              file=sys.stderr)
    queries = _generate_queries(seed, tree.dim, n, generator)
    d2, _ = _tree_knn(tree, queries, k=args.k)
    for q in range(queries.shape[0]):
        print_result_line(n + q, float(np.sqrt(d2[q, 0])))
    print("DONE")


def cmd_serve(args) -> None:
    """Long-lived online k-NN serving (docs/SERVING.md): micro-batched
    ``POST /v1/knn``, ``GET /healthz`` readiness, and the live Prometheus
    ``GET /metrics`` endpoint over the whole telemetry registry."""
    import signal
    import threading
    import zipfile

    from kdtree_tpu.serve import lifecycle, server as srv

    snap_dir = getattr(args, "snapshot", None)
    follow_s = getattr(args, "snapshot_follow", None)
    save_dir = getattr(args, "snapshot_save", None)
    sources = [s for s in (args.index, args.points) if s]
    if len(sources) > 1 or (args.index and snap_dir):
        print("serve needs ONE index source: --snapshot, --index, "
              "--points, or the seeded --seed/--dim/--n problem "
              "(--snapshot may pair with --points as the corruption "
              "fallback)", file=sys.stderr)
        sys.exit(1)
    if follow_s is not None and not snap_dir:
        print("--snapshot-follow needs --snapshot DIR (the manifest the "
              "secondary polls)", file=sys.stderr)
        sys.exit(1)
    if follow_s is not None and save_dir:
        print("--snapshot-follow and --snapshot-save are exclusive: a "
              "secondary adopts snapshots, only the shard primary emits "
              "them", file=sys.stderr)
        sys.exit(1)
    snap_version = getattr(args, "snapshot_version", None)
    if snap_version is not None and not snap_dir:
        print("--snapshot-version needs --snapshot DIR (the retained "
              "generation to roll back to)", file=sys.stderr)
        sys.exit(1)
    if snap_version is not None and follow_s is not None:
        print("--snapshot-version and --snapshot-follow are exclusive: "
              "a follower converges to the LIVE manifest, which would "
              "immediately replace the pinned generation",
              file=sys.stderr)
        sys.exit(1)
    tree = points = problem = None
    meta = {}
    epoch0 = 0
    loaded_version = 0
    loaded_from_snapshot = False
    # an explicit --id-offset always wins; a snapshot of a non-zero-
    # offset shard carries its partition start in the manifest, and a
    # replica cold-started without the flag must inherit it — an
    # offset-0 default would overlap shard 0's id range in the
    # router's owner table
    id_offset = args.id_offset if args.id_offset is not None else 0
    if snap_dir:
        from kdtree_tpu import snapshot as snap

        try:
            tree, man = snap.load_snapshot(snap_dir,
                                           version=snap_version)
            epoch0 = int(man.get("epoch", 0))
            loaded_version = int(man.get("version", 0))
            loaded_from_snapshot = True
            if args.id_offset is None and man.get("id_offset"):
                id_offset = int(man["id_offset"])
                print(f"id_offset {id_offset} inherited from the "
                      "snapshot manifest (pass --id-offset to "
                      "override)", file=sys.stderr)
            meta = {"snapshot": {
                "dir": snap.resolve_dir(snap_dir),
                "version": loaded_version,
                "epoch": epoch0,
                "role": ("secondary" if follow_s is not None
                         else "primary" if save_dir else "static"),
            }}
            if isinstance(man.get("meta"), dict) and \
                    "spatial" in man["meta"]:
                # a spatially-partitioned shard (kdtree-tpu partition):
                # surface the region contract (grid + owned Morton code
                # range) on /healthz so the router can learn write
                # ownership and prune reads by box
                meta["spatial"] = man["meta"]["spatial"]
            seeded = snap.seed_plan_store(man)
            if seeded:
                # pre-shipped plan profiles (the manifest rode them from
                # the primary's store): the warmup ladder below now
                # resolves them warm instead of re-settling locally
                print(f"plan store seeded with {seeded} pre-shipped "
                      "profile(s) from the snapshot manifest",
                      file=sys.stderr)
            print(f"snapshot loaded: v{loaded_version} epoch {epoch0} "
                  f"(n={tree.n_real}) from {snap.resolve_dir(snap_dir)}",
                  file=sys.stderr)
        except snap.SnapshotError as e:
            # named failure (schema skew / checksum mismatch / missing
            # segment — never a half-read mmap), already counted in
            # kdtree_snapshot_load_errors_total + flight-recorded by
            # the store. Fall back to a from-source rebuild when one
            # was provided; otherwise fail crisply.
            if args.points or getattr(args, "snapshot_fallback", False):
                src = "--points" if args.points else "the seeded problem"
                print(f"snapshot load failed: {e}", file=sys.stderr)
                print(f"falling back to a from-scratch rebuild from "
                      f"{src} (--snapshot-fallback contract)",
                      file=sys.stderr)
                meta = {"snapshot": {
                    "dir": snap.resolve_dir(snap_dir),
                    "role": "fallback-rebuild",
                    "error": str(e)[:200],
                    # pre-seed the keys the follower's on-adopt hook
                    # updates: this dict is shared with the /healthz
                    # body, and ADDING keys during a concurrent
                    # json.dumps raises "dictionary changed size";
                    # overwriting existing values does not
                    "version": 0,
                    "epoch": 0,
                }}
                if args.points:
                    points = _load_array(args.points, "points")
                    meta["points"] = args.points
                else:
                    problem = (args.seed, args.dim, args.n)
                    meta.update(seed=args.seed, generator="threefry")
            else:
                print(f"cannot load snapshot {snap_dir}: {e}",
                      file=sys.stderr)
                print("hint: pass --points FILE (or --snapshot-fallback "
                      "with the seeded --seed/--dim/--n) to rebuild "
                      "from source when the snapshot is unusable",
                      file=sys.stderr)
                sys.exit(1)
    elif args.index:
        from kdtree_tpu.utils.checkpoint import load_tree

        try:
            tree, meta = load_tree(args.index)
        except (OSError, ValueError, zipfile.BadZipFile) as e:
            print(f"cannot load tree {args.index}: {e}", file=sys.stderr)
            sys.exit(1)
    elif args.points:
        points = _load_array(args.points, "points")
        meta = {"points": args.points}
    else:
        if args.generator != "threefry":
            print("note: serve's seeded problem is the threefry row "
                  f"stream; --generator {args.generator} does not apply",
                  file=sys.stderr)
        problem = (args.seed, args.dim, args.n)
        meta = {"seed": args.seed, "generator": "threefry"}
    snapshot_sink = None
    if save_dir:
        from kdtree_tpu import snapshot as snap

        def snapshot_sink(tree_, epoch, _dir=save_dir,
                          _off=id_offset, _k=args.k,
                          _mb=args.max_batch,
                          _keep=max(getattr(args, "snapshot_keep", 1)
                                    or 1, 1),
                          _spatial=(meta.get("spatial")
                                    if isinstance(meta, dict) else None)):
            keys = snap.plan_keys_for(tree_, _k, _mb)
            snap.save_snapshot(
                _dir, tree_, epoch=epoch, id_offset=_off,
                plan_keys=keys,
                # pre-ship this primary's settled plan profiles so a
                # snapshot-follow secondary adopts WARM (PR 13's open
                # half): by emit time the warmup ladder has settled
                # every key into the local store
                plan_profiles=snap.collect_plan_profiles(keys),
                meta=({"spatial": _spatial} if _spatial else None),
                keep=_keep,
            )
    try:
        state = lifecycle.build_state(
            tree=tree, points=points, problem=problem, k=args.k,
            max_batch=args.max_batch, meta=meta,
            id_offset=id_offset,
            max_delta_rows=args.max_delta_rows,
            max_delta_frac=args.max_delta_frac,
            read_only=follow_s is not None,
            epoch0=epoch0,
            snapshot_sink=snapshot_sink,
            ladder_enabled=not getattr(args, "no_ladder", False),
        )
    except TypeError as e:
        # un-servable checkpoint kind — crisp stderr + exit code (C10)
        print(f"cannot serve: {e}", file=sys.stderr)
        sys.exit(1)
    if save_dir:
        # primary bootstrap emit: make the save dir's artifact match the
        # epoch this process serves, so secondaries can cold-start from
        # it immediately. Skipped only when this process just loaded the
        # identical content from the same dir.
        from kdtree_tpu import snapshot as snap

        same = (loaded_from_snapshot and snap_dir
                and snap.resolve_dir(snap_dir) == snap.resolve_dir(save_dir))
        if not same or snap.read_manifest(snap.resolve_dir(save_dir)) is None:
            snapshot_sink(state.engine.tree, state.engine.epoch)
            print(f"serving snapshot emitted to "
                  f"{snap.resolve_dir(save_dir)} (epoch "
                  f"{state.engine.epoch}); epoch rebuilds re-emit on "
                  "every swap", file=sys.stderr)
    try:
        httpd = srv.make_server(
            state, host=args.host, port=args.port,
            max_wait_ms=args.max_wait_ms, queue_rows=args.queue_depth,
            debug_faults=args.debug_faults,
            recall_sample=max(getattr(args, "recall_sample", 0.0) or 0.0,
                              0.0),
        )
    except srv.FaultSpecError as e:
        # a typo'd KDTREE_TPU_FAULTS must fail the drill at startup,
        # crisply — never silently arm nothing (C10 contract)
        print(f"bad KDTREE_TPU_FAULTS: {e}", file=sys.stderr)
        sys.exit(1)
    host, port = httpd.server_address[:2]
    stop = threading.Event()

    def _on_signal(signum, frame):
        stop.set()

    signal.signal(signal.SIGINT, _on_signal)
    signal.signal(signal.SIGTERM, _on_signal)
    # SIGUSR2 -> atomic flight-recorder dump (docs/OBSERVABILITY.md): the
    # operator's "what is this process doing" button, no restart needed
    from kdtree_tpu.obs import flight

    if flight.install_signal_handler():
        print("flight recorder armed: kill -USR2 this pid dumps the "
              "recent-event ring", file=sys.stderr)
    from kdtree_tpu.obs import history as obs_history

    print(f"slo engine armed: {len(state.slo_engine.specs)} SLOs over a "
          f"{obs_history.default_period():g}s-period metric-history ring "
          "(GET /debug/history; burn-rate verdicts in /healthz and "
          "kdtree_slo_* on /metrics)", file=sys.stderr)
    thr = state.engine.rebuild_threshold()
    print("mutable index armed: POST /v1/upsert + /v1/delete, epoch "
          "rebuild at backlog >= "
          f"{'disabled' if thr is None else thr} rows "
          "(docs/SERVING.md \"Mutable index\")", file=sys.stderr)
    if state.ladder_enabled:
        print("degradation ladder armed: exact -> approx(0.99) -> "
              "approx(0.9) -> brute-force-deadline under sustained "
              "burn; per-request recall_target on /v1/knn "
              "(docs/SERVING.md \"Degradation ladder\")",
              file=sys.stderr)
    print(f"kdtree-tpu serve: binding http://{host}:{port} "
          f"(n={state.engine.tree.n_real}, dim={state.engine.tree.dim}, "
          f"k<={state.engine.k}); warming up...", file=sys.stderr)
    try:
        httpd.start()  # returns once warmup compiles are done
    except Exception:
        # a failed warmup must not leave the non-daemon accept thread
        # holding the process open with /healthz stuck at 503 forever
        httpd.stop()
        raise
    follower = None
    if follow_s is not None:
        # blue/green secondary: poll the snapshot manifest, adopt new
        # versions (load -> pre-warm -> atomic engine swap), report the
        # adopted epoch on /healthz. Started AFTER warmup so the adopt
        # pre-warms exactly the batch shapes serving compiled.
        from kdtree_tpu.snapshot import SnapshotFollower

        snap_block = state.meta.setdefault("snapshot", {})

        def _on_adopt(man, _blk=snap_block):
            _blk["version"] = int(man.get("version", 0))
            _blk["epoch"] = int(man.get("epoch", 0))

        follower = SnapshotFollower(
            state.engine, snap_dir, poll_s=follow_s,
            start_version=loaded_version, on_adopt=_on_adopt,
        )
        follower.start()
        print(f"snapshot follower armed: polling {follower.dir} every "
              f"{follower.poll_s:g}s for blue/green epoch swaps "
              "(this replica is read-only — writes 403)",
              file=sys.stderr)
    print(f"ready: POST /v1/knn, GET /healthz, GET /metrics on port "
          f"{port}", file=sys.stderr)
    stop.wait()
    print("shutting down: draining in-flight requests...", file=sys.stderr)
    if follower is not None:
        follower.stop()
    httpd.stop()
    print("drained; bye", file=sys.stderr)


def cmd_route(args) -> None:
    """Scatter/gather routing over per-shard serve processes
    (docs/SERVING.md "Routing & fault tolerance"): fan each POST /v1/knn
    to every shard, merge per-shard top-k by (distance, id), and keep
    the service available through shard failure — deadlines, bounded
    retry with jittered backoff, p95 hedging, per-shard circuit
    breakers, health ejection, and exact partial-result degradation."""
    import signal
    import threading

    from kdtree_tpu.serve import faults as faults_mod
    from kdtree_tpu.serve import router as rt

    urls = []
    for chunk in args.shard or []:
        urls.extend(u.strip() for u in chunk.split(",") if u.strip())
    if not urls:
        print("route needs at least one --shard http://host:port "
              "(repeat the flag or comma-separate)", file=sys.stderr)
        sys.exit(1)
    # fail a typo'd KDTREE_TPU_FAULTS crisply here too: the router does
    # not inject faults itself, but a drill operator exporting the spec
    # into the wrong process should hear about it
    try:
        faults_mod.from_env()
    except faults_mod.FaultSpecError as e:
        print(f"bad KDTREE_TPU_FAULTS: {e}", file=sys.stderr)
        sys.exit(1)
    try:
        config = rt.RouterConfig(
            deadline_s=args.deadline_ms / 1e3,
            retries=args.retries,
            hedge_min_s=args.hedge_ms / 1e3,
            quorum=args.quorum,
            breaker_failures=args.breaker_failures,
            breaker_reset_s=args.breaker_reset_s,
            health_period_s=args.health_period_s,
            fanout=args.fanout,
            trace_frac=args.trace_frac,
            pool=args.pool,
            pool_max_idle=args.pool_max_idle,
            spec_wave=args.spec_wave,
            parent=args.parent,
        )
        engine = None
        if args.slo:
            from kdtree_tpu.obs import slo as obs_slo

            engine = obs_slo.SloEngine(specs=obs_slo.router_specs())
        httpd = rt.make_router(urls, host=args.host, port=args.port,
                               config=config, slo_engine=engine)
    except ValueError as e:
        print(f"cannot route: {e}", file=sys.stderr)
        sys.exit(1)
    host, port = httpd.server_address[:2]
    stop = threading.Event()

    def _on_signal(signum, frame):
        stop.set()

    signal.signal(signal.SIGINT, _on_signal)
    signal.signal(signal.SIGTERM, _on_signal)
    from kdtree_tpu.obs import flight

    if flight.install_signal_handler():
        print("flight recorder armed: kill -USR2 this pid dumps the "
              "recent-event ring", file=sys.stderr)
    kind = "child router(s)" if config.parent else "shard(s)"
    print(f"kdtree-tpu route: {len(urls)} {kind}, quorum "
          f"{httpd.quorum}, deadline {config.deadline_s * 1e3:g} ms, "
          f"retries {config.retries}, breaker "
          f"{config.breaker_failures}x/{config.breaker_reset_s:g}s, "
          f"pool {'on' if config.pool else 'off'}, spec-wave "
          f"{'on' if config.spec_wave else 'off'}",
          file=sys.stderr)
    httpd.start()
    print(f"ready: routing POST /v1/knn, GET /healthz, GET /metrics on "
          f"port {port}", file=sys.stderr)
    stop.wait()
    print("shutting down: draining in-flight scatters...", file=sys.stderr)
    httpd.stop()
    print("drained; bye", file=sys.stderr)


def cmd_loadgen(args) -> None:
    """Open-loop production load harness (docs/OBSERVABILITY.md "Load
    harness & capacity curves"): drive a live serve/route process with
    seeded Poisson arrivals at a rate ladder and a query/upsert/delete
    mix, measure latency from INTENDED send times (coordinated omission
    cannot hide queueing), and emit a capacity block — per-step
    quantiles, goodput, shed/degraded fractions, and the knee rate —
    that ``kdtree-tpu trend`` diffs across rounds."""
    from kdtree_tpu.loadgen import runner as lg_runner
    from kdtree_tpu.loadgen import schedule as lg_schedule
    from kdtree_tpu.obs.export import _capacity_lines

    try:
        rates = [float(x) for x in args.rates.split(",") if x.strip()]
    except ValueError:
        print(f"--rates must be a comma-separated number list, got "
              f"{args.rates!r}", file=sys.stderr)
        sys.exit(1)
    if not rates or any(r <= 0 for r in rates):
        print(f"--rates values must be positive, got {args.rates!r}",
              file=sys.stderr)
        sys.exit(1)
    try:
        mix = lg_schedule.parse_mix(args.mix)
    except ValueError as e:
        print(f"bad --mix: {e}", file=sys.stderr)
        sys.exit(1)
    try:
        recall_mix = lg_schedule.parse_recall_mix(args.recall_target)
    except ValueError as e:
        print(f"bad --recall-target: {e}", file=sys.stderr)
        sys.exit(1)
    try:
        verb_mix = lg_schedule.parse_verb_mix(args.verb_mix)
    except ValueError as e:
        print(f"bad --verb-mix: {e}", file=sys.stderr)
        sys.exit(1)
    if round(args.slo_quantile, 4) not in (0.5, 0.95, 0.99):
        # fail BEFORE the sweep runs: the knee must be judged at a
        # quantile the steps actually report, never silently at p99
        print(f"--slo-quantile must be 0.5, 0.95, or 0.99 (the reported "
              f"step quantiles), got {args.slo_quantile}",
              file=sys.stderr)
        sys.exit(1)
    ab_base = None
    if args.ab_baseline:
        # read + validate the baseline BEFORE the sweep runs: a sweep
        # whose A/B anchor turns out to be garbage was minutes wasted
        try:
            with open(args.ab_baseline) as f:
                base_rep = json.load(f)
        except (OSError, ValueError) as e:
            print(f"cannot read --ab-baseline {args.ab_baseline}: {e}",
                  file=sys.stderr)
            sys.exit(1)
        base_cap = (base_rep or {}).get("capacity") \
            if isinstance(base_rep, dict) else None
        if not isinstance(base_cap, dict) \
                or "knee_rate" not in base_cap:
            print(f"{args.ab_baseline} is not a loadgen capacity "
                  "report (missing capacity.knee_rate); was it written "
                  "by loadgen --out?", file=sys.stderr)
            sys.exit(1)
        ab_base = base_cap
    try:
        facts = lg_runner.discover(args.target,
                                   retries=args.ready_retries)
    except (RuntimeError, ValueError) as e:
        print(f"cannot reach target: {e}", file=sys.stderr)
        sys.exit(1)
    dim = args.dim if args.dim is not None else facts["dim"]
    k = min(args.k, facts["k_max"])
    write_base = (args.write_base if args.write_base is not None
                  else facts["write_base"])
    try:
        sched = lg_schedule.build_schedule(
            rates, args.step_seconds, args.seed, dim, mix=mix,
            regions=args.regions, zipf_s=args.zipf_s, shape=args.shape,
            diurnal_amp=args.diurnal_amp, write_base=write_base,
            recall_mix=recall_mix, verb_mix=verb_mix,
        )
    except ValueError as e:
        print(f"cannot build schedule: {e}", file=sys.stderr)
        sys.exit(1)
    desc = sched.describe()
    print(f"loadgen: target {args.target} (n={facts['n']}, dim={dim}, "
          f"k={k}); {desc['arrivals']} arrivals over "
          f"{sched.duration_s:g}s, mix {desc['ops']}, seed {args.seed}",
          file=sys.stderr)

    def on_step(step, rate):
        print(f"  step {step}: offering {rate:g} req/s for "
              f"{args.step_seconds:g}s", file=sys.stderr)

    report = lg_runner.run_load(
        args.target, sched, k=k, slo_ms=args.slo_ms,
        slo_quantile=args.slo_quantile, max_bad_frac=args.max_bad_frac,
        max_inflight=args.max_inflight, timeout_s=args.timeout_ms / 1e3,
        on_step=on_step, verb_radius=args.verb_radius,
        knee_band=args.knee_band,
    )
    cap = report["capacity"]
    if args.variant:
        cap["variant"] = args.variant
    if ab_base is not None:
        import os

        # the A/B block the trend knee-drop rule judges: this run is
        # the CANDIDATE, the embedded knee is the bar it must clear —
        # strictly, or by a strictly lower p99 when both arms top out
        # at the same ladder step
        base_p99 = next(
            (s.get("p99_ms") for s in ab_base.get("steps") or []
             if isinstance(s, dict)
             and s.get("rate") == ab_base["knee_rate"]), None)
        cap["ab"] = {
            "baseline_file": os.path.basename(args.ab_baseline),
            "baseline_variant": ab_base.get("variant"),
            "baseline_knee_rate": float(ab_base["knee_rate"]),
            "baseline_p99_ms_at_knee": base_p99,
            "knee_delta": round(
                float(cap["knee_rate"]) - float(ab_base["knee_rate"]),
                3),
        }
    if args.out:
        import os

        tmp = f"{args.out}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, args.out)
        print(f"capacity report written to {args.out}", file=sys.stderr)
    # the telemetry sidecar (--metrics-out) carries the same capacity
    # block, so one artifact is a self-contained trend input
    args._telemetry_extra = {"capacity": cap}
    print("\n".join(_capacity_lines(cap)), file=sys.stderr)
    print(json.dumps({
        "knee_rate": cap["knee_rate"],
        "slo_ms": cap["slo_ms"],
        "steps": len(cap["steps"]),
        "arrivals": desc["arrivals"],
        "out": args.out,
        # the capacity-headroom model's A/B verdict (None when the
        # target exported no cost counters): predicted sustainable
        # rate from measured cost/query vs the knee the ladder found
        "predicted_rate": (cap.get("predicted")
                           or {}).get("predicted_rate"),
        "predicted_within_band": (cap.get("predicted")
                                  or {}).get("within_band"),
    }))


def _load_report(path: str) -> dict:
    """Load + validate one --metrics-out telemetry report (shared by
    ``stats`` and ``stats --diff`` so both reject garbage identically)."""
    try:
        with open(path) as f:
            rep = json.load(f)
    except (OSError, ValueError) as e:
        print(f"cannot read telemetry report {path}: {e}", file=sys.stderr)
        sys.exit(1)
    if not isinstance(rep, dict) or "counters" not in rep:
        print(f"{path} is not a kdtree-tpu telemetry report "
              "(missing 'counters'); was it written by --metrics-out?",
              file=sys.stderr)
        sys.exit(1)
    return rep


def cmd_stats(args) -> None:
    """Render a --metrics-out JSON telemetry report human-readably (the
    registry snapshot is machine-first; this is the operator view).
    ``--diff OLD NEW`` renders two reports side-by-side with deltas —
    the bench-regression triage view."""
    from kdtree_tpu.obs import export

    if args.diff:
        if len(args.report) != 2:
            print("stats --diff needs exactly two reports: OLD NEW",
                  file=sys.stderr)
            sys.exit(1)
        old, new = (_load_report(p) for p in args.report)
        sys.stdout.write(export.render_report_diff(old, new))
        return
    if len(args.report) != 1:
        print("stats renders one report (use --diff OLD NEW to compare "
              "two)", file=sys.stderr)
        sys.exit(1)
    sys.stdout.write(export.render_report(_load_report(args.report[0])))


def cmd_profile(args) -> None:
    """Device-timeline profiling (docs/OBSERVABILITY.md "Profiling"):
    run a representative tiled-query workload under a ``jax.profiler``
    capture window, join the emitted device op slices back to the host
    spans by time overlap, and report where the accelerator was busy vs
    waiting — per batch dispatch, with dispatch-to-execution lag and any
    compile slices that polluted the window. Writes the timeline report
    JSON to --out and renders it human-readably."""
    import os
    import tempfile

    from kdtree_tpu import obs
    from kdtree_tpu.obs import profile as obs_profile
    from kdtree_tpu.obs import timeline as obs_timeline
    from kdtree_tpu.ops.generate import generate_points_rowwise, generate_queries
    from kdtree_tpu.ops.morton import build_morton
    from kdtree_tpu.ops.tile_query import morton_knn_tiled

    trace_dir = args.trace_dir or tempfile.mkdtemp(
        prefix="kdtree-tpu-profile-"
    )
    print(f"profiling: n={args.n} dim={args.dim} q={args.q} k={args.k} "
          f"(trace dir {trace_dir})", file=sys.stderr)
    pts = generate_points_rowwise(args.seed, args.dim, args.n)
    # a distinct seed for the query sample — profiling query==point
    # geometry would overstate the prune rate (same idiom as tune)
    queries = generate_queries(args.seed + 1, args.dim, args.q)
    with obs.span("profile.build") as h:
        tree = build_morton(pts)
        h += [tree]
    if not args.cold:
        # warmup OUTSIDE the window: compiles would otherwise dominate
        # the capture and the busy/idle numbers would describe XLA, not
        # the steady state (--cold keeps them in, deliberately)
        d2, ids = morton_knn_tiled(tree, queries, k=args.k)
        obs.hard_sync([d2, ids])
    with obs_profile.capture(trace_dir) as cap:
        with obs.span("profile.query") as h:
            d2, ids = morton_knn_tiled(tree, queries, k=args.k)
            h += [d2, ids]
    if cap.trace_file is None:
        print(f"profiler produced no trace under {trace_dir}",
              file=sys.stderr)
        sys.exit(1)
    try:
        rep = obs_timeline.analyze_trace_file(cap.trace_file)
    except (OSError, ValueError) as e:
        print(f"cannot parse trace {cap.trace_file}: {e}", file=sys.stderr)
        sys.exit(1)
    rep["workload"] = {
        "seed": args.seed, "dim": args.dim, "n": args.n, "q": args.q,
        "k": args.k, "cold": bool(args.cold),
    }
    tmp = f"{args.out}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(rep, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, args.out)
    if args.format == "json":
        print(json.dumps({
            "out": args.out,
            "trace_file": cap.trace_file,
            "correlated_spans": rep["correlated_spans"],
            "device_busy_frac": rep["device"]["busy_frac"],
            "dispatches": rep["dispatches"]["count"],
            "compiles_in_window": rep["compile"]["count"],
        }))
    else:
        sys.stdout.write(obs_timeline.render_timeline(rep))
    print(f"timeline report written to {args.out}; raw trace: "
          f"{cap.trace_file}", file=sys.stderr)


def cmd_trace(args) -> None:
    """Fetch one distributed trace from a live serve/route process and
    render the ASCII waterfall (docs/OBSERVABILITY.md "Distributed
    tracing"): ``--id T`` names the trace, ``--last-slow`` asks the
    target's pinned-trace index for the most recent slow-promoted id.
    A router target assembles across its shards (``?assemble=1``,
    clock-corrected by the health loop's RTT-midpoint offsets); a
    shard target renders its local spans. ``--out`` keeps the JSON
    artifact the waterfall was rendered from."""
    import urllib.error
    import urllib.request

    from kdtree_tpu.obs import trace as trace_mod

    base = args.target.rstrip("/")

    def fetch(path: str) -> dict:
        with urllib.request.urlopen(f"{base}{path}",
                                    timeout=args.timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))

    try:
        tid = args.id
        if tid is None:
            idx = fetch("/debug/trace")
            tid = (idx.get("last_promoted") or {}).get("slow")
            if not tid:
                # no slow promotion yet: fall back to the newest pinned
                # trace — an errored/hedged waterfall beats "nothing"
                pinned = idx.get("pinned") or []
                tid = pinned[-1]["trace_id"] if pinned else None
            if not tid:
                print("no promoted traces at the target yet (nothing "
                      "slow/errored/hedged so far; head-sample boring "
                      "requests with route --trace-frac)",
                      file=sys.stderr)
                sys.exit(1)
        try:
            payload = fetch(f"/debug/trace/{tid}?assemble=1")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                print(f"no such trace at {base}: {tid} (aged out or "
                      "never recorded)", file=sys.stderr)
                sys.exit(1)
            raise
    except (OSError, ValueError) as e:
        print(f"cannot fetch trace from {base}: {e}", file=sys.stderr)
        sys.exit(1)
    if payload.get("assembled"):
        assembled = payload
    else:
        # a shard target ignores ?assemble=1 and answers its local span
        # list — assemble the single-source forest client-side so the
        # rendering path is one shape
        assembled = trace_mod.assemble(tid, [{
            "source": f"pid{payload.get('pid', '?')}",
            "clock_offset_s": 0.0,
            "spans": payload.get("spans") or [],
            "error": None,
        }])
        assembled["reasons"] = payload.get("reasons", [])
        assembled["pinned"] = payload.get("pinned", False)
    sys.stdout.write(trace_mod.render_waterfall(assembled) + "\n")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(assembled, f, indent=2, sort_keys=True, default=str)
            f.write("\n")
        print(f"trace artifact written to {args.out}", file=sys.stderr)


def _render_cost_report(rep: dict, indent: str = "") -> list:
    """Human lines for one shard's ``/debug/costs`` payload: the
    per-class cost table, the windowed cost-per-query, the headroom
    verdict, and the maintenance (unattributed) spend."""
    lines = []
    classes = rep.get("classes") or []
    if classes:
        lines.append(f"{indent}{'class':<34s}  {'req':>8s}  "
                     f"{'cost/q':>10s}  {'rows':>8s}  {'retries':>7s}  "
                     f"{'bytes out':>10s}")
        for row in classes:
            ck = "/".join((str(row.get("verb", "?")),
                           str(row.get("gear", "?")),
                           str(row.get("outcome", "?"))))
            cm = row.get("cost_ms")
            lines.append(
                f"{indent}{ck:<34s}  {row.get('requests', 0):>8g}  "
                f"{f'{cm:.3f}ms' if cm is not None else '-':>10s}  "
                f"{row.get('rows', 0):>8g}  {row.get('retries', 0):>7g}  "
                f"{row.get('bytes_out', 0):>10g}"
            )
    else:
        lines.append(f"{indent}no answered requests yet")
    window = rep.get("window")
    if isinstance(window, dict):
        lines.append(
            f"{indent}window ({window.get('window_s', 0):g}s): "
            f"{window.get('requests', 0):g} req at "
            f"{window.get('observed_rate', 0):g} req/s, cost/query "
            f"{window.get('cost_per_query_ms', 0):g} ms"
        )
    hr = rep.get("headroom")
    if isinstance(hr, dict):
        if hr.get("data"):
            lines.append(
                f"{indent}headroom: {hr.get('headroom_frac', 0):.1%} "
                f"(observed {hr.get('observed_rate', 0):g} vs predicted "
                f"{hr.get('predicted_rate', 0):g} req/s"
                + (f", busy {hr['busy_frac']:.2f}"
                   if hr.get("busy_frac") is not None else "")
                + ")"
            )
        else:
            lines.append(f"{indent}headroom: no data (no answered "
                         "requests in the window)")
    maint = rep.get("maintenance")
    if isinstance(maint, dict) and any(maint.values()):
        lines.append(
            f"{indent}maintenance: corrections "
            f"{maint.get('correction_ms', 0):g} ms / "
            f"{maint.get('correction_rows', 0):g} rows, writes "
            f"{maint.get('write_ms', 0):g} ms, rebuilds "
            f"{maint.get('rebuilds', 0):g} ({maint.get('rebuild_ms', 0):g}"
            " ms) — device/wall time no request class is charged for"
        )
    return lines


def cmd_costs(args) -> None:
    """Fetch ``/debug/costs`` from a live serve or route process and
    render the cost-attribution view (docs/OBSERVABILITY.md "Cost
    accounting & capacity headroom"): the per-class cost/query table, the
    windowed cost-per-query, and the capacity-headroom verdict. A router
    target renders every shard's ledger plus the fleet aggregation;
    ``--json`` emits the raw payload for scripting."""
    import urllib.request

    base = args.target.rstrip("/")
    url = f"{base}/debug/costs?window={args.window_s:g}"
    try:
        with urllib.request.urlopen(url, timeout=args.timeout_s) as resp:
            payload = json.loads(resp.read().decode("utf-8"))
    except (OSError, ValueError) as e:
        print(f"cannot fetch costs from {base}: {e}", file=sys.stderr)
        sys.exit(1)
    if args.json:
        json.dump(payload, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
        return
    lines = []
    if "shards" in payload and "classes" not in payload:
        # router payload: per-shard ledgers + the fleet headroom block
        for ent in payload.get("shards") or []:
            tag = (f"shard {ent.get('shard', '?')}"
                   + (f"/r{ent['replica']}" if ent.get("replica") else "")
                   + f" ({ent.get('url', '?')})")
            if "error" in ent:
                lines.append(f"== {tag}: {ent['error']} ==")
                continue
            lines.append(f"== {tag} ==")
            lines.extend(_render_cost_report(ent.get("costs") or {},
                                             indent="  "))
        fleet = payload.get("headroom") or {}
        lines.append("== fleet ==")
        if fleet.get("data"):
            lines.append(
                f"  headroom: {fleet.get('headroom_frac', 0):.1%} "
                f"(observed {fleet.get('observed_rate', 0):g} vs "
                f"predicted {fleet.get('predicted_rate', 0):g} req/s "
                f"over {fleet.get('shards_reporting', 0)}/"
                f"{fleet.get('shards_total', 0)} shards)"
            )
        else:
            lines.append(
                f"  headroom: no data "
                f"({fleet.get('shards_reporting', 0)}/"
                f"{fleet.get('shards_total', 0)} shards reporting)"
            )
    else:
        lines.extend(_render_cost_report(payload))
    sys.stdout.write("\n".join(lines) + "\n")


def cmd_lint(args) -> None:
    """Project-invariant linter (docs/STATIC_ANALYSIS.md): AST rules for
    the bug classes this project actually shipped — int32 gid wrap,
    device syncs in hot paths, jit-over-shard_map on legacy jax, unsafe
    telemetry listeners, re-derived Morton bits, nondeterminism. Exits 1
    when findings exist that are neither suppressed inline (with a
    reason) nor grandfathered in the committed baseline."""
    import os

    from kdtree_tpu.analysis import baseline as bl
    from kdtree_tpu.analysis import reporting, run_lint

    # --root makes the run cwd-independent (the PR 3 NOTE papercut:
    # lint only worked from the repo root): default paths, the relative
    # baseline, and finding relpaths all resolve against it
    root = os.path.abspath(args.root) if args.root else os.getcwd()
    if args.root and not os.path.isdir(root):
        print(f"cannot lint: --root {args.root} is not a directory",
              file=sys.stderr)
        sys.exit(2)
    paths = args.paths or [os.path.join(root, "kdtree_tpu")]
    paths = [p if os.path.isabs(p) else os.path.join(root, p)
             for p in paths]
    baseline_path = (args.baseline if os.path.isabs(args.baseline)
                     else os.path.join(root, args.baseline))
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"cannot lint: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        sys.exit(2)
    if args.prune_baseline and args.changed is not None:
        # prune compares the FULL finding set against the baseline;
        # a narrowed emission set would mark live debt stale
        print("cannot lint: --prune-baseline requires a full run "
              "(drop --changed)", file=sys.stderr)
        sys.exit(2)
    if args.changed is not None:
        # diff-aware mode: emit findings only for files changed vs REF
        # (plus untracked ones), but build the interprocedural program
        # over the FULL lint paths — a wrapper's summary must not depend
        # on which files happen to be in the diff
        import subprocess

        try:
            diff = subprocess.run(
                ["git", "-C", root, "diff", "--name-only",
                 "--diff-filter=d", args.changed, "--", "*.py"],
                capture_output=True, text=True, check=True, timeout=60,
            ).stdout
            untracked = subprocess.run(
                ["git", "-C", root, "ls-files", "--others",
                 "--exclude-standard", "--", "*.py"],
                capture_output=True, text=True, check=True, timeout=60,
            ).stdout
        except (OSError, subprocess.SubprocessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            print(f"cannot lint --changed {args.changed}: "
                  f"{detail.strip()}", file=sys.stderr)
            sys.exit(2)
        lint_dirs = [os.path.abspath(p) for p in paths]
        changed = []
        for rel in sorted(set(diff.splitlines()) | set(untracked.splitlines())):
            full = os.path.abspath(os.path.join(root, rel))
            if not os.path.exists(full):
                continue
            if any(full == d or full.startswith(d + os.sep)
                   for d in lint_dirs):
                changed.append(full)
        if not changed:
            print(f"lint --changed {args.changed}: no changed .py files "
                  "under the lint paths")
            return
        result = run_lint(changed, root=root, context_paths=paths)
    else:
        result = run_lint(paths, root=root)
    if result.errors and not result.findings:
        # un-parseable inputs with nothing else to report: that is a
        # usage-shaped failure, not a lint verdict
        for err in result.errors:
            print(f"error: {err}", file=sys.stderr)
        sys.exit(2)
    if args.update_baseline:
        count = bl.save(baseline_path, result.findings)
        print(f"wrote {len(result.findings)} finding(s) "
              f"({count} fingerprint(s)) to {baseline_path}")
        return
    try:
        base = bl.load(baseline_path)
    except (OSError, ValueError) as e:
        print(f"cannot read baseline {baseline_path}: {e}",
              file=sys.stderr)
        sys.exit(2)
    new = bl.partition(result.findings, base)
    if args.format == "json":
        render = reporting.render_json(result, new_count=len(new))
    elif args.format == "sarif":
        render = reporting.render_sarif(result, root=root)
    else:
        render = reporting.render_human(result, new_count=len(new))
    sys.stdout.write(render)
    stale = base.stale_entries() if args.prune_baseline else []
    if stale:
        for e in stale:
            print(
                f"stale baseline entry: {e['rule']} {e['path']} "
                f"[{e.get('scope', '<module>')}] x{e['stale']} — the "
                "linter no longer finds this; remove it "
                "(--update-baseline rewrites the file)",
                file=sys.stderr,
            )
    if new or stale:
        if new:
            print(
                f"{len(new)} new finding(s): fix them, suppress inline "
                "with a reason (# kdt-lint: disable=KDTxxx <why>), or "
                "grandfather with --update-baseline (see "
                "docs/STATIC_ANALYSIS.md)",
                file=sys.stderr,
            )
        if stale:
            print(
                f"{len(stale)} stale baseline fingerprint(s): run "
                "--update-baseline to burn them down",
                file=sys.stderr,
            )
        sys.exit(1)


def cmd_trend(args) -> None:
    """Bench-trend sentinel (docs/OBSERVABILITY.md "Trend"): scan a
    chronological series of bench artifacts (driver BENCH_r*.json,
    headline lines, telemetry sidecars) for platform fallbacks,
    beyond-the-noise-band throughput drops, and recompile growth —
    grandfathered by a committed baseline exactly like the linter, so
    CI fails only on NEW regressions."""
    from kdtree_tpu.obs import trend as tr

    runs = []
    for p in args.reports:
        try:
            runs.append(tr.load_run(p))
        except (OSError, ValueError) as e:
            print(f"cannot read bench report {p}: {e}", file=sys.stderr)
            sys.exit(2)
    if len(runs) < 2:
        print("trend needs >= 2 reports in chronological order (oldest "
              "first) — one run has no trend", file=sys.stderr)
        sys.exit(2)
    findings, band = tr.analyze(runs, band=args.band)
    if args.update_baseline:
        count = tr.save_baseline(args.baseline, findings)
        print(f"wrote {len(findings)} finding(s) ({count} fingerprint(s)) "
              f"to {args.baseline}")
        return
    try:
        base = tr.load_baseline(args.baseline)
    except (OSError, ValueError) as e:
        print(f"cannot read trend baseline {args.baseline}: {e}",
              file=sys.stderr)
        sys.exit(2)
    new = tr.partition(findings, base)
    render = tr.render_json if args.format == "json" else tr.render_human
    sys.stdout.write(render(runs, findings, new, band))
    if new:
        print(
            f"{len(new)} new trend regression(s): fix the regression, or "
            "grandfather a known-degraded trajectory with "
            "--update-baseline (see docs/OBSERVABILITY.md)",
            file=sys.stderr,
        )
        sys.exit(1)


def _parse_int_list(raw: str | None, what: str):
    """Comma-separated positive ints for the tune sweep grids."""
    if raw is None:
        return None
    try:
        vals = [int(x) for x in raw.split(",") if x.strip()]
    except ValueError:
        print(f"--{what} must be a comma-separated int list, got {raw!r}",
              file=sys.stderr)
        sys.exit(1)
    if not vals or any(v < 1 for v in vals):
        print(f"--{what} values must be positive, got {raw!r}",
              file=sys.stderr)
        sys.exit(1)
    return vals


def cmd_tune(args) -> None:
    """Sweep (tile, cmax) candidates for the tiled engine on a query
    sample and persist the winner to the plan store — after this, every
    run with the same problem signature (see docs/TUNING.md) starts at
    the tuned configuration with no cap-settling probe or doubling-retry
    recompiles."""
    from kdtree_tpu import tuning
    from kdtree_tpu.ops.generate import generate_points_rowwise, generate_queries
    from kdtree_tpu.ops.morton import build_morton
    from kdtree_tpu.tuning import tuner

    store = tuning.default_store()
    if not store.enabled:
        print("plan store is disabled (KDTREE_TPU_PLAN_CACHE is set to "
              "none/off); nothing to persist a winner into", file=sys.stderr)
        sys.exit(1)
    if args.generator != "threefry":
        # same idiom as the generative scale engines: tune's problem IS the
        # threefry row stream — silently measuring a different point set
        # than the flag suggests would misrepresent the persisted winner
        print("note: tune defines its points by the threefry row stream; "
              f"--generator {args.generator} does not apply",
              file=sys.stderr)
    tiles = _parse_int_list(args.tiles, "tiles")
    cmaxs = _parse_int_list(args.cmax, "cmax")
    vs = _parse_int_list(args.scan_v, "scan-v")
    tbs = _parse_int_list(args.scan_tb, "scan-tb")
    pts = generate_points_rowwise(args.seed, args.dim, args.n)
    # a distinct seed for the sample: tuning on the points themselves
    # would overfit the plan to query==point geometry
    queries = generate_queries(args.seed + 1, args.dim, args.q)
    tree = build_morton(pts)

    def log(row):
        block = ""
        if row.get("v") is not None:
            block = f" v={row['v']:<3d} tb={row['tb']:<5d}"
        print(f"  tile={row['tile']:<5d} cmax={row['cmax']:<5d}{block} "
              f"{row['seconds']*1e3:9.1f} ms  "
              f"{row['qps']:>10.0f} q/s  retries={row['overflow_retries']}",
              file=sys.stderr)

    print(f"sweeping tiled plans: n={args.n} dim={args.dim} q={args.q} "
          f"k={args.k}", file=sys.stderr)
    out = tuner.sweep(tree, queries, k=args.k, tiles=tiles, cmaxs=cmaxs,
                      vs=vs, tbs=tbs, sweep_blocks=not args.no_block_sweep,
                      store=store, log=log)
    if out["persisted"]:
        print(f"persisted winner to {out['path']}", file=sys.stderr)
    elif "reason" in out:
        print(f"warning: nothing persisted — {out['reason']}",
              file=sys.stderr)
    else:
        print("warning: winner could not be persisted (cache dir not "
              "writable?)", file=sys.stderr)
    print(json.dumps({
        "winner": out["winner"],
        "persisted": out["persisted"],
        "path": out["path"],
        "candidates": len(out["results"]) + len(out["block_results"]),
        "block_candidates": len(out["block_results"]),
    }))


def cmd_recall(args) -> None:
    """The recall harness (docs/SERVING.md "Degradation ladder"):
    sweep bounded-visit caps over a seeded problem against the exact
    oracle, print the recall@k-vs-speedup curve, persist the measured
    recall_target → visit_cap calibration into the plan store (unless
    --no-calibrate), and emit the curve as the sidecar "recall" block
    `kdtree-tpu trend` gates on."""
    from kdtree_tpu import approx, tuning
    from kdtree_tpu.ops.generate import generate_points_rowwise, generate_queries
    from kdtree_tpu.ops.morton import build_morton

    if args.generator != "threefry":
        print("note: recall defines its points by the threefry row "
              f"stream; --generator {args.generator} does not apply",
              file=sys.stderr)
    caps = _parse_int_list(args.caps, "caps")
    pts = generate_points_rowwise(args.seed, args.dim, args.n)
    # a distinct seed for the query sample — measuring recall on
    # query==point geometry would flatter every cap (same idiom as tune)
    queries = generate_queries(args.seed + 1, args.dim, args.q)
    tree = build_morton(pts)
    print(f"recall sweep: n={args.n} dim={args.dim} q={args.q} "
          f"k={args.k} buckets={tree.num_buckets}", file=sys.stderr)

    def log(row):
        print(f"  cap={row['visit_cap']:<6d} recall={row['recall']:.4f} "
              f"{row['qps']:>10.0f} q/s  {row['speedup']:>6.2f}x",
              file=sys.stderr)

    block = approx.sweep_recall(tree, queries, k=args.k, caps=caps,
                                log=log)
    cal = {"recall_caps": {}, "persisted": False, "path": None}
    if not args.no_calibrate:
        from kdtree_tpu.approx.recall import persist_calibration

        cal = persist_calibration(tree, args.q, args.dim, args.k, block,
                                  store=tuning.default_store())
        if cal["persisted"]:
            print(f"calibration persisted to {cal['path']}: "
                  f"{cal['recall_caps']}", file=sys.stderr)
        elif cal["path"] is None:
            print("plan store disabled (KDTREE_TPU_PLAN_CACHE=none); "
                  "calibration not persisted", file=sys.stderr)
    if args.out:
        import os

        report = {
            "recall_report_version": 1,
            "recall": block,
            "calibration": cal["recall_caps"],
        }
        tmp = f"{args.out}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, args.out)
        print(f"recall report written to {args.out}", file=sys.stderr)
    # the telemetry sidecar carries the same block, so one artifact is
    # a self-contained `kdtree-tpu trend` input (like loadgen's
    # capacity block)
    args._telemetry_extra = {"recall": block}
    print(json.dumps({
        "exact_qps": block["exact_qps"],
        "caps": len(block["curve"]),
        "calibration": cal["recall_caps"],
        "persisted": cal["persisted"],
        "out": args.out,
    }))


def _flight_dump_on_failure() -> None:
    """Dump the flight ring on a failed CLI exit (KDTREE_TPU_FLIGHT_DIR
    governs where; =none disables). The dump observes the failure — it
    must never mask it, so every error is swallowed."""
    try:
        from kdtree_tpu.obs import flight

        path = flight.auto_dump("cli-error", force=True)
        if path:
            print(f"flight recorder dumped to {path}", file=sys.stderr)
    except Exception:
        pass


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="kdtree-tpu", description=__doc__)
    p.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="write a one-shot JSON telemetry report (metrics "
                        "registry + spans + JAX runtime facts) on exit; "
                        "also enables the device-side metrics that cost a "
                        "fetch (bucket occupancy, tile candidate counts). "
                        "Render it with the 'stats' subcommand")
    p.add_argument("--platform", default=None,
                   help="pin jax_platforms (e.g. 'cpu') — needed because the "
                        "axon sitecustomize overrides the JAX_PLATFORMS env var")
    p.add_argument("--generator", choices=["threefry", "mt19937"], default="mt19937",
                   help="problem generator (mt19937 = bit-exact reference replay)")
    p.add_argument("--engine",
                   choices=["auto", "morton", "tiled", "tree", "bucket",
                            "bruteforce", "ensemble", "global",
                            "global-morton", "global-exact"],
                   default="auto",
                   help="tiled = Morton tree + Hilbert-tiled batched scan "
                        "(large query counts); global-morton = the scale "
                        "engine (shard-local generation + one all_to_all "
                        "sample-sort partition); global-exact = the scalable "
                        "exact-median tree (distributed radix-select medians "
                        "for the top log2 P levels, chip-local below)")
    p.add_argument("--devices", type=int, default=None,
                   help="device count for sharded engines (default: all)")
    sub = p.add_subparsers(dest="cmd", required=True)

    h = sub.add_parser("harness", help="course grading protocol (READY/DONE)")
    h.add_argument("spec", nargs="*", metavar="SEED DIM NUM_POINTS",
                   help="argv mode; omit for interactive stdin mode")
    h.set_defaults(fn=cmd_harness)

    b = sub.add_parser("bench", help="per-phase timing")
    b.add_argument("--seed", type=int, default=42)
    b.add_argument("--dim", type=int, default=3)
    b.add_argument("--n", type=int, default=1 << 20)
    b.add_argument("--k", type=int, default=1)
    b.add_argument("--distribution", choices=["uniform", "clustered"],
                   default="uniform",
                   help="generative row stream for the scale engines "
                        "(clustered = Gaussian-mixture load-imbalance stress)")
    b.add_argument("--trace", default=None, metavar="DIR",
                   help="write a jax.profiler trace (Perfetto) of the timed "
                        "run; phases appear as named TraceAnnotations")
    b.set_defaults(fn=cmd_bench)

    bu = sub.add_parser("build", help="build a tree and save to npz")
    bu.add_argument("--seed", type=int, default=42)
    bu.add_argument("--dim", type=int, default=3)
    bu.add_argument("--n", type=int, default=1 << 20)
    bu.add_argument("--points", default=None, metavar="FILE",
                    help="build over user data ([N, D] .npy/.npz) instead of "
                         "a seeded problem; with --engine global-morton a "
                         "'{i}' placeholder (e.g. part-{i}.npy) maps "
                         "pre-sharded files onto devices verbatim")
    bu.add_argument("--distribution", choices=["uniform", "clustered"],
                    default="uniform",
                    help="generative row stream for the scale engines")
    bu.add_argument("--slack", type=float, default=None,
                    help="scale-engine exchange capacity factor (the "
                         "'capacity overflow ... retry with slack > X' "
                         "errors name this as the remedy)")
    bu.add_argument("--out", default=None,
                    help="npz checkpoint path (required unless --save "
                         "is given)")
    bu.add_argument("--save", default=None, metavar="DIR",
                    help="also write a versioned SERVING snapshot "
                         "(checksummed flat .npy segments + manifest) "
                         "that `serve --snapshot DIR` replicas "
                         "mmap-load in seconds — the replica-fleet "
                         "cold-start artifact (docs/SERVING.md "
                         "\"Snapshots & replica fleets\")")
    bu.add_argument("--snapshot-keep", type=int, default=1,
                    metavar="N",
                    help="with --save: retain the last N snapshot "
                         "generations (segments refcounted by "
                         "manifest; older generations GC'd) — "
                         "`serve --snapshot DIR --snapshot-version V` "
                         "rolls back to a retained one (default 1)")
    bu.add_argument("--sharded", action="store_true",
                    help="force the per-device shard checkpoint format "
                         "(forest engines auto-shard above 1 GiB)")
    bu.set_defaults(fn=cmd_build)

    pa = sub.add_parser(
        "partition",
        help="spatial partitioner: cut one point cloud into N "
             "contiguous Morton-range shard snapshots (global ids = "
             "morton ranks; each manifest carries the shard's region) "
             "for the router's selective fan-out (docs/SERVING.md "
             "\"Spatial sharding & selective fan-out\")",
    )
    pa.add_argument("--points", default=None, metavar="FILE",
                    help="partition user data ([N, D] .npy/.npz) "
                         "instead of a seeded problem")
    pa.add_argument("--seed", type=int, default=42)
    pa.add_argument("--dim", type=int, default=3)
    pa.add_argument("--n", type=int, default=1 << 20)
    pa.add_argument("--shards", type=int, required=True,
                    help="how many Morton-range shards to cut (>= 2)")
    pa.add_argument("--out-dir", required=True, metavar="DIR",
                    help="output directory: one serving snapshot per "
                         "shard (shard-00/, shard-01/, ...) plus a "
                         "PARTITION.json fleet summary (relative paths "
                         "resolve under KDTREE_TPU_SNAPSHOT_DIR)")
    pa.add_argument("--bits", type=int, default=None,
                    help="Morton quantization bits per axis (default: "
                         "the shared default_bits rule for this D)")
    pa.add_argument("--k", type=int, default=16,
                    help="the k the shard servers will serve at (plan "
                         "keys/profiles in each manifest are computed "
                         "for it)")
    pa.add_argument("--max-batch", type=int, default=1024,
                    help="the serve --max-batch the plan keys cover")
    pa.add_argument("--snapshot-keep", type=int, default=1, metavar="N",
                    help="snapshot generations each shard dir retains")
    pa.set_defaults(fn=cmd_partition)

    q = sub.add_parser("query", help="load a tree and run the 10 protocol queries")
    q.add_argument("--tree", required=True)
    q.add_argument("--seed", type=int, default=None,
                   help="override checkpoint seed (normally read from the npz)")
    q.add_argument("--k", type=int, default=1)
    q.add_argument("--queries", default=None, metavar="FILE",
                   help="user query set ([Q, D] .npy/.npz) instead of the 10 "
                        "protocol queries")
    q.add_argument("--out", default=None, metavar="FILE",
                   help="with --queries: save (d2, ids) npz instead of "
                        "printing protocol lines")
    q.add_argument("--allow-host-materialize", action="store_true",
                   help="permit a mesh-free load of a sharded checkpoint to "
                        "assemble ALL shards in host memory (otherwise "
                        "loads above the host budget fail crisply)")
    q.set_defaults(fn=cmd_query)

    sv = sub.add_parser(
        "serve",
        help="online k-NN serving: micro-batched POST /v1/knn + /healthz "
             "+ Prometheus /metrics (docs/SERVING.md)",
    )
    sv.add_argument("--index", default=None, metavar="FILE",
                    help="serve a checkpoint (a `build --out` npz; must be "
                         "a Morton-servable tree)")
    sv.add_argument("--points", default=None, metavar="FILE",
                    help="build a Morton index over user data ([N, D] "
                         ".npy/.npz) at startup and serve it")
    sv.add_argument("--seed", type=int, default=42,
                    help="seeded threefry problem (with --dim/--n) when no "
                         "--index/--points is given")
    sv.add_argument("--dim", type=int, default=3)
    sv.add_argument("--n", type=int, default=1 << 20)
    sv.add_argument("--host", default="127.0.0.1",
                    help="bind address (default loopback; 0.0.0.0 exposes "
                         "the server)")
    sv.add_argument("--port", type=int, default=8080,
                    help="TCP port (0 = ephemeral, printed on stderr)")
    sv.add_argument("--k", type=int, default=16,
                    help="max neighbors per query; batches compile at this "
                         "k and per-request k<=K slices the result")
    sv.add_argument("--max-batch", type=int, default=1024,
                    help="micro-batch row cap (rounded up to a power of "
                         "two — the plan-store bucket quantum)")
    sv.add_argument("--max-wait-ms", type=float, default=2.0,
                    help="how long the batcher holds the first request of "
                         "a batch to coalesce arrivals")
    sv.add_argument("--queue-depth", type=int, default=None, metavar="ROWS",
                    help="admission budget in query rows; beyond it "
                         "requests shed with 429 (default 4x max-batch)")
    sv.add_argument("--id-offset", type=int, default=None, metavar="ROWS",
                    help="sharded serving: this process holds rows "
                         "[offset, offset+n) of a partitioned point set "
                         "and answers GLOBAL ids (local id + offset); "
                         "the route subcommand's merge depends on it. "
                         "Default 0, or the --snapshot manifest's "
                         "recorded offset when loading one")
    sv.add_argument("--max-delta-rows", type=int, default=None,
                    metavar="ROWS",
                    help="mutable index: epoch rebuild triggers when the "
                         "write backlog (delta rows + tombstones) reaches "
                         "this many rows (default 4096; <= 0 disables "
                         "this bound)")
    sv.add_argument("--max-delta-frac", type=float, default=None,
                    metavar="FRAC",
                    help="mutable index: epoch rebuild triggers when the "
                         "write backlog reaches this fraction of the "
                         "main tree (default 0.25; <= 0 disables this "
                         "bound; the tighter of the two bounds wins)")
    sv.add_argument("--snapshot", default=None, metavar="DIR",
                    help="load the index from a serving snapshot "
                         "(`build --save` / a primary's epoch emits): "
                         "checksum-verified, mmap-read, ready in "
                         "seconds — no rebuild. Pairs with --points "
                         "or --snapshot-fallback as the corruption "
                         "fallback (docs/SERVING.md)")
    sv.add_argument("--snapshot-save", default=None, metavar="DIR",
                    help="shard PRIMARY: emit a snapshot at startup and "
                         "re-emit on every epoch rebuild swap — the "
                         "blue/green artifact secondaries adopt")
    sv.add_argument("--snapshot-follow", type=float, default=None,
                    metavar="SECONDS",
                    help="read SECONDARY: poll --snapshot DIR's "
                         "manifest at this period and blue/green-swap "
                         "new versions in (load -> warm -> atomic "
                         "engine swap; /healthz reports the adopted "
                         "epoch). Implies read-only — writes 403")
    sv.add_argument("--snapshot-fallback", action="store_true",
                    help="on snapshot load failure (checksum/schema), "
                         "rebuild from the seeded --seed/--dim/--n "
                         "problem instead of exiting (--points falls "
                         "back automatically)")
    sv.add_argument("--snapshot-keep", type=int, default=1, metavar="N",
                    help="with --snapshot-save: retain the last N "
                         "snapshot generations across epoch emits "
                         "(rollback-by-version; default 1 — one "
                         "generation, the historical layout)")
    sv.add_argument("--snapshot-version", type=int, default=None,
                    metavar="V",
                    help="with --snapshot: load a RETAINED generation "
                         "V instead of the live manifest — the "
                         "rollback button --snapshot-keep enables")
    sv.add_argument("--recall-sample", type=float, default=0.02,
                    metavar="FRAC",
                    help="online recall sampler: shadow-answer this "
                         "fraction of approximate-gear batches exactly "
                         "and publish the MEASURED served recall "
                         "(kdtree_recall_sampled — the sampled-recall "
                         "SLO watches it); 0 disables (default 0.02)")
    sv.add_argument("--no-ladder", action="store_true",
                    help="disable the degradation ladder (exact -> "
                         "approx(0.99) -> approx(0.9) -> brute-force-"
                         "deadline under sustained SLO burn, "
                         "docs/SERVING.md \"Degradation ladder\"); "
                         "without it overload has only the historical "
                         "two gears")
    sv.add_argument("--debug-faults", action="store_true",
                    help="arm POST /debug/faults (live fault injection, "
                         "docs/SERVING.md) — a remote wedge-this-process "
                         "button, so it is opt-in; setting "
                         "KDTREE_TPU_FAULTS also arms it")
    sv.set_defaults(fn=cmd_serve)

    ro = sub.add_parser(
        "route",
        help="fault-tolerant scatter/gather router over per-shard serve "
             "processes: merged exact top-k, deadlines, retries, "
             "hedging, circuit breakers, partial results "
             "(docs/SERVING.md)",
    )
    ro.add_argument("--shard", action="append", metavar="URL",
                    help="shard serve process base url (http://host:port); "
                         "repeat the flag or comma-separate. A shard "
                         "entry may be a REPLICA SET — "
                         "'primary|replica1|replica2' — reads "
                         "load-balance across replicas, writes go to "
                         "the first (primary) url "
                         "(docs/SERVING.md \"Snapshots & replica "
                         "fleets\")")
    ro.add_argument("--host", default="127.0.0.1")
    ro.add_argument("--port", type=int, default=8081,
                    help="TCP port (0 = ephemeral, printed on stderr)")
    ro.add_argument("--deadline-ms", type=float, default=2000.0,
                    help="scatter/gather budget per request; a shard "
                         "that cannot answer inside it goes missing, "
                         "never blocking")
    ro.add_argument("--retries", type=int, default=2,
                    help="bounded per-shard retries (jittered exponential "
                         "backoff; shard Retry-After honored)")
    ro.add_argument("--hedge-ms", type=float, default=50.0,
                    help="hedge-delay floor: a second attempt fires when "
                         "a shard call outlives max(its p95, this)")
    ro.add_argument("--quorum", type=int, default=None,
                    help="shards that must answer for a (partial) 200 "
                         "(default: majority)")
    ro.add_argument("--breaker-failures", type=int, default=3,
                    help="consecutive failures that open a shard's "
                         "circuit breaker")
    ro.add_argument("--breaker-reset-s", type=float, default=2.0,
                    help="open-breaker cooldown before the half-open "
                         "probe")
    ro.add_argument("--health-period-s", type=float, default=1.0,
                    help="per-shard /healthz poll period for ejection")
    ro.add_argument("--fanout", choices=["selective", "full"],
                    default="selective",
                    help="selective (default) prunes shards whose "
                         "/healthz bounding box provably cannot hold a "
                         "top-k member (byte-identical answers, fewer "
                         "contacts — docs/SERVING.md \"Spatial "
                         "sharding & selective fan-out\"); full "
                         "restores the contact-every-shard scatter "
                         "(the A/B baseline)")
    ro.add_argument("--trace-frac", type=float, default=0.0,
                    help="head-sampling fraction for distributed "
                         "tracing: deterministically pin this slice of "
                         "BORING requests' traces (tail promotion — "
                         "slow/error/partial/hedged — is always on; "
                         "docs/OBSERVABILITY.md \"Distributed "
                         "tracing\")")
    ro.add_argument("--no-pool", dest="pool", action="store_false",
                    help="open a fresh connection per shard attempt "
                         "instead of pooling keep-alive connections "
                         "(the pooled-vs-fresh A/B baseline — "
                         "docs/SERVING.md \"Scaling the router\")")
    ro.add_argument("--pool-max-idle", type=int, default=8,
                    help="idle keep-alive connections kept per shard "
                         "replica (host, port)")
    ro.add_argument("--no-spec-wave", dest="spec_wave",
                    action="store_false",
                    help="disable speculative overlapped wave 2: wait "
                         "for every wave-1 response before widening "
                         "(answers identical either way; this is the "
                         "latency A/B baseline)")
    ro.add_argument("--no-slo", dest="slo", action="store_false",
                    help="serve without the router SLO ladder: no "
                         "burn-rate pages, no slo block on /healthz — "
                         "so an upstream parent router never ejects "
                         "this router for paging. For benches and "
                         "fleets where paging is handled out-of-band; "
                         "a PAGE is sticky for the burn window, which "
                         "turns a transient overload into minutes of "
                         "ejection")
    ro.add_argument("--parent", action="store_true",
                    help="two-level mode: --shard urls are CHILD "
                         "ROUTERS, not serve shards — prune/scatter/"
                         "merge recurses through them with the same "
                         "exact-merge byte-identity "
                         "(docs/SERVING.md \"Scaling the router\")")
    ro.set_defaults(fn=cmd_route, pool=True, spec_wave=True, slo=True)

    lg = sub.add_parser(
        "loadgen",
        help="open-loop production load harness: seeded Poisson "
             "arrivals at a rate ladder with a query/upsert/delete "
             "mix against a live serve/route process; emits a "
             "capacity block (latency-vs-offered-load curve + knee) "
             "the trend gate diffs (docs/OBSERVABILITY.md)",
    )
    lg.add_argument("--target", required=True, metavar="URL",
                    help="base url of a live serve or route process "
                         "(http://host:port)")
    lg.add_argument("--rates", required=True, metavar="R1,R2,...",
                    help="offered-rate ladder in requests/sec, one "
                         "capacity curve point per step")
    lg.add_argument("--step-seconds", type=float, default=10.0,
                    help="how long each ladder step offers its rate")
    lg.add_argument("--mix", default="query:0.9,upsert:0.08,delete:0.02",
                    help="op mix weights (normalized); deletes target "
                         "ids upserted earlier in the schedule")
    lg.add_argument("--seed", type=int, default=42,
                    help="schedule seed: same seed = identical arrival "
                         "times, ops, and payloads")
    lg.add_argument("--recall-target", default=None, metavar="MIX",
                    help="recall dial for the QUERY share of the mix: "
                         "a single target ('0.99'), or a weighted mix "
                         "('exact:0.5,0.99:0.3,0.9:0.2') so capacity "
                         "curves are driven per serving gear; each "
                         "step records the gear distribution it was "
                         "answered at (default: all exact)")
    lg.add_argument("--verb-mix", default=None, metavar="MIX",
                    help="read-verb mix for the QUERY share of the "
                         "schedule ('knn:0.7,radius:0.2,count:0.1'; "
                         "verbs: knn/radius/range/count, weights "
                         "normalized): each query arrival draws its "
                         "verb seeded and response-blind, per-step "
                         "rows and the capacity block gain per-verb "
                         "latency/goodput columns and knees, and "
                         "trend treats runs at differing mixes as "
                         "incommensurable (default: pure knn, "
                         "schedule byte-identical to pre-verb "
                         "loadgen)")
    lg.add_argument("--verb-radius", type=float, default=0.1,
                    help="search radius (and range half-width) non-knn "
                         "verbs carry, in the unit-cube query space — "
                         "pins verb selectivity so runs at the same "
                         "mix measure the same work")
    lg.add_argument("--k", type=int, default=4,
                    help="neighbors per query (clamped to the target's "
                         "k_max)")
    lg.add_argument("--shape", choices=["steps", "diurnal"],
                    default="steps",
                    help="steps = flat rate per rung; diurnal = "
                         "sinusoidally modulated within each rung "
                         "(Lewis-Shedler thinning, still seeded)")
    lg.add_argument("--diurnal-amp", type=float, default=0.3,
                    help="diurnal modulation amplitude in [0, 1)")
    lg.add_argument("--regions", type=int, default=64,
                    help="spatial regions the Zipf query skew draws "
                         "over")
    lg.add_argument("--zipf-s", type=float, default=1.1,
                    help="Zipf exponent of the region skew (higher = "
                         "hotter hot spots)")
    lg.add_argument("--slo-ms", type=float, default=250.0,
                    help="latency SLO bound the knee is judged against "
                         "(matches the serving request-p99 SLO)")
    lg.add_argument("--slo-quantile", type=float, default=0.99,
                    help="which intended-latency quantile must meet "
                         "--slo-ms (0.5/0.95/0.99)")
    lg.add_argument("--max-bad-frac", type=float, default=0.05,
                    help="max (shed+error+timeout)/sent fraction a "
                         "step may have and still count toward the "
                         "knee")
    lg.add_argument("--max-inflight", type=int, default=64,
                    help="client worker pool size; arrivals beyond it "
                         "queue client-side but latency is measured "
                         "from INTENDED send time either way")
    lg.add_argument("--timeout-ms", type=float, default=10000.0,
                    help="per-request client timeout")
    lg.add_argument("--dim", type=int, default=None,
                    help="query dimensionality (default: discovered "
                         "from the target's /healthz)")
    lg.add_argument("--write-base", type=int, default=None,
                    help="first id upserts mint (default: past the "
                         "target's served id range, from /healthz)")
    lg.add_argument("--ready-retries", type=int, default=60,
                    help="how many times to poll /healthz for "
                         "readiness before giving up")
    lg.add_argument("--out", default="loadgen_report.json",
                    metavar="FILE",
                    help="standalone capacity report artifact (a "
                         "kdtree-tpu trend input); '' disables")
    lg.add_argument("--variant", default=None,
                    help="label for this arm of an A/B (e.g. 'pooled', "
                         "'fresh', 'hier'); recorded in the capacity "
                         "block")
    lg.add_argument("--ab-baseline", default=None, metavar="FILE",
                    help="a previous loadgen report to A/B against: "
                         "embeds its knee in this report's "
                         "capacity.ab block, and the trend knee-drop "
                         "rule fails any run whose knee is not "
                         "strictly better than its baseline")
    lg.add_argument("--knee-band", type=float, default=0.5,
                    help="relative band the cost ledger's predicted "
                         "sustainable rate must land within of the "
                         "measured knee (the capacity.predicted "
                         "within_band verdict)")
    lg.set_defaults(fn=cmd_loadgen)

    st = sub.add_parser(
        "stats", help="render a --metrics-out telemetry report "
                      "(--diff OLD NEW compares two)"
    )
    st.add_argument("report", nargs="+", metavar="REPORT.json",
                    help="path a previous run's --metrics-out wrote "
                         "(two paths with --diff)")
    st.add_argument("--diff", action="store_true",
                    help="render two reports side-by-side with deltas "
                         "(spans, counters, compile counts) — the "
                         "bench-regression triage view")
    st.set_defaults(fn=cmd_stats)

    pr = sub.add_parser(
        "profile",
        help="device-timeline profiling: capture a jax.profiler trace of "
             "a tiled-query workload and report device busy/idle per "
             "batch dispatch (docs/OBSERVABILITY.md)",
    )
    pr.add_argument("--seed", type=int, default=42)
    pr.add_argument("--dim", type=int, default=3)
    pr.add_argument("--n", type=int, default=1 << 16,
                    help="point count of the seeded problem to profile")
    pr.add_argument("--q", type=int, default=1 << 13,
                    help="query-batch size (the dense tiled shape)")
    pr.add_argument("--k", type=int, default=8)
    pr.add_argument("--cold", action="store_true",
                    help="skip the warmup run so the capture includes "
                         "compile slices (default: profile steady state)")
    pr.add_argument("--trace-dir", default=None, metavar="DIR",
                    help="where the raw profiler trace lands (default: a "
                         "temp dir, path printed on stderr); open it in "
                         "Perfetto for the full picture")
    pr.add_argument("--out", default="timeline.json", metavar="FILE",
                    help="timeline report JSON artifact")
    pr.add_argument("--format", choices=["human", "json"], default="human",
                    help="stdout format (the JSON artifact is always "
                         "written to --out)")
    pr.set_defaults(fn=cmd_profile)

    tu = sub.add_parser(
        "tune",
        help="sweep (tile, cmax) candidates for the tiled engine and "
             "persist the winner to the plan store (docs/TUNING.md)",
    )
    tu.add_argument("--seed", type=int, default=42)
    tu.add_argument("--dim", type=int, default=3)
    tu.add_argument("--n", type=int, default=1 << 20,
                    help="point count of the seeded problem to tune on")
    tu.add_argument("--q", type=int, default=16384,
                    help="query-sample size — plans are keyed by the "
                         "quantized Q bucket, so tune at the Q you serve")
    tu.add_argument("--k", type=int, default=16)
    tu.add_argument("--tiles", default=None, metavar="T1,T2,...",
                    help="candidate tile sizes (default 64..1024 pow2)")
    tu.add_argument("--cmax", default=None, metavar="C1,C2,...",
                    help="candidate candidate-bucket caps (default "
                         "32..256 pow2)")
    tu.add_argument("--scan-v", default=None, metavar="V1,V2,...",
                    help="candidate fold-chunk widths (buckets per scan "
                         "chunk) for the block-shape phase (default 1,8)")
    tu.add_argument("--scan-tb", default=None, metavar="T1,T2,...",
                    help="candidate tiles-per-scan-block for the "
                         "block-shape phase (default 1,4,32)")
    tu.add_argument("--no-block-sweep", action="store_true",
                    help="skip the block-shape phase (sweep only the "
                         "(tile, cmax) launch grid)")
    tu.set_defaults(fn=cmd_tune)

    rc = sub.add_parser(
        "recall",
        help="recall harness: sweep bounded-visit caps against the "
             "exact oracle, emit the recall@k-vs-speedup curve (a "
             "trend-gated sidecar block), and persist the "
             "recall_target -> visit_cap calibration to the plan "
             "store (docs/SERVING.md \"Degradation ladder\")",
    )
    rc.add_argument("--seed", type=int, default=42)
    rc.add_argument("--dim", type=int, default=3)
    rc.add_argument("--n", type=int, default=1 << 20,
                    help="point count of the seeded problem to measure")
    rc.add_argument("--q", type=int, default=16384,
                    help="query-sample size; the calibration persists "
                         "for every serve batch bucket up to this Q")
    rc.add_argument("--k", type=int, default=16)
    rc.add_argument("--caps", default=None, metavar="C1,C2,...",
                    help="visit caps to sweep (default: powers of two "
                         "up to the bucket count; the full-cap point "
                         "pins recall 1.0)")
    rc.add_argument("--no-calibrate", action="store_true",
                    help="measure only; do not persist the "
                         "recall_target -> visit_cap table")
    rc.add_argument("--out", default="recall_report.json",
                    metavar="FILE",
                    help="standalone recall report artifact (a "
                         "kdtree-tpu trend input); '' disables")
    rc.set_defaults(fn=cmd_recall)

    tr = sub.add_parser(
        "trend",
        help="bench-trend sentinel: flag platform fallbacks, throughput "
             "drops beyond the noise band, and recompile growth across "
             "a series of bench artifacts (docs/OBSERVABILITY.md)",
    )
    tr.add_argument("reports", nargs="+", metavar="REPORT.json",
                    help="bench artifacts in chronological order, oldest "
                         "first: driver BENCH_r*.json, raw headline JSON, "
                         "or bench telemetry sidecars")
    tr.add_argument("--band", type=float, default=None, metavar="FRAC",
                    help="relative drop treated as a regression (default: "
                         "fitted from --pair sidecar spread when present, "
                         "else 0.5 — container noise is +-40%%)")
    tr.add_argument("--baseline", default="trend_baseline.json",
                    metavar="PATH",
                    help="committed grandfather file; only findings NOT "
                         "in it fail the run")
    tr.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "(grandfather a known-degraded trajectory) and "
                         "exit 0")
    tr.add_argument("--format", choices=["human", "json"], default="human",
                    help="json is the machine report CI uploads")
    tr.set_defaults(fn=cmd_trend)

    li = sub.add_parser(
        "lint",
        help="project-invariant AST linter (docs/STATIC_ANALYSIS.md): "
             "fails on findings not suppressed inline or grandfathered "
             "in the baseline",
    )
    li.add_argument("paths", nargs="*", metavar="PATH",
                    help="files/directories to lint (default: kdtree_tpu "
                         "under --root)")
    li.add_argument("--root", default=None, metavar="DIR",
                    help="repo root: default paths, the relative "
                         "--baseline, and finding paths resolve against "
                         "it (default: cwd) — lint works from anywhere")
    li.add_argument("--format", choices=["human", "json", "sarif"],
                    default="human",
                    help="json is the machine report CI uploads; sarif "
                         "is the SARIF 2.1.0 document GitHub code "
                         "scanning ingests")
    li.add_argument("--baseline", default="lint_baseline.json",
                    metavar="PATH",
                    help="committed grandfather file; only findings NOT in "
                         "it fail the run")
    li.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "(burn down or grandfather debt) and exit 0")
    li.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="diff-aware mode: emit findings only for files "
                         "changed vs REF (default HEAD) plus untracked "
                         "ones — the interprocedural program is still "
                         "built over the FULL lint paths, so summaries "
                         "do not depend on the diff; exits 0 when "
                         "nothing relevant changed")
    li.add_argument("--prune-baseline", action="store_true",
                    help="fail (exit 1) when the baseline carries stale "
                         "fingerprints the linter can no longer find — "
                         "dead debt must leave the file, not sit as a "
                         "grandfather slot for the next collision")
    li.set_defaults(fn=cmd_lint)

    tw = sub.add_parser(
        "trace",
        help="fetch a distributed trace from a live serve/route "
             "process and render the ASCII waterfall (router targets "
             "assemble across shards, clock-corrected); writes the "
             "JSON artifact with --out (docs/OBSERVABILITY.md "
             '"Distributed tracing")',
    )
    tw.add_argument("--target", default="http://127.0.0.1:8081",
                    metavar="URL",
                    help="router (assembled) or shard (local spans) "
                         "base url")
    tw_which = tw.add_mutually_exclusive_group(required=True)
    tw_which.add_argument("--id", default=None, metavar="TRACE_ID",
                          help="trace id to fetch (a request's "
                               "trace_id / X-Request-Id)")
    tw_which.add_argument("--last-slow", action="store_true",
                          help="render the target's most recently "
                               "slow-promoted trace (falls back to "
                               "the newest pinned one)")
    tw.add_argument("--out", default=None, metavar="PATH",
                    help="also write the assembled trace JSON here")
    tw.add_argument("--timeout-s", type=float, default=5.0,
                    help="per-fetch HTTP timeout")
    tw.set_defaults(fn=cmd_trace)

    co = sub.add_parser(
        "costs",
        help="fetch /debug/costs from a live serve/route process and "
             "render per-class cost/query + the capacity-headroom "
             'verdict (docs/OBSERVABILITY.md "Cost accounting & '
             'capacity headroom")',
    )
    co.add_argument("--target", default="http://127.0.0.1:8080",
                    metavar="URL",
                    help="shard (one ledger) or router (per-shard "
                         "ledgers + fleet aggregation) base url")
    co.add_argument("--window-s", type=float, default=60.0,
                    help="history window the cost-per-query and "
                         "headroom verdicts are computed over")
    co.add_argument("--json", action="store_true",
                    help="emit the raw /debug/costs payload instead of "
                         "the rendered table")
    co.add_argument("--timeout-s", type=float, default=5.0,
                    help="HTTP timeout")
    co.set_defaults(fn=cmd_costs)

    args = p.parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    if args.cmd == "harness" and args.spec and len(args.spec) != 3:
        # Usage parity with Utility.cpp:109-112
        print(f"Usage: {p.prog} harness SEED DIM_POINTS  NUM_POINTS", file=sys.stderr)
        sys.exit(1)
    if args.cmd in ("lint", "trend", "trace", "costs"):
        # pure-stdlib paths: dispatch before the engine-error plumbing
        # below. (The kdtree_tpu package import itself still pulls in
        # jax — the ANALYSIS/trend code is stdlib-only, the entry point
        # is not.)
        args.fn(args)
        return
    metrics_out = getattr(args, "metrics_out", None)
    if metrics_out and args.cmd != "stats":
        from kdtree_tpu import obs

        obs.configure(metrics_out=metrics_out)
    from kdtree_tpu.ops.morton import BuildCapacityError

    try:
        args.fn(args)
    except BuildCapacityError as e:
        # the HBM guard (ops/morton.py) protects every subcommand; surface
        # it with the crisp stderr + exit-code contract (C10), not a traceback
        _flight_dump_on_failure()
        print(str(e), file=sys.stderr)
        sys.exit(1)
    except Exception:
        # unhandled crash: dump the flight ring BEFORE the traceback — the
        # last N seconds of spans/events are the context the traceback
        # lacks. (SystemExit is BaseException: validation exits don't dump.)
        _flight_dump_on_failure()
        raise
    finally:
        # write the report even on failed exits — a degraded run's
        # telemetry is exactly the part worth keeping; and a failed WRITE
        # must never replace the run's own exit (telemetry never fails
        # the run it observes)
        if metrics_out and args.cmd != "stats":
            from kdtree_tpu import obs

            try:
                # a subcommand can attach top-level report facts (e.g.
                # loadgen's capacity block) by setting _telemetry_extra
                obs.finalize(extra=getattr(args, "_telemetry_extra",
                                           None))
            except OSError as e:
                print(f"cannot write telemetry report {metrics_out}: {e}",
                      file=sys.stderr)


if __name__ == "__main__":
    main()
