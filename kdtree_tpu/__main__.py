from kdtree_tpu.utils.cli import main

main()
