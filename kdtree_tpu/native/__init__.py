"""ctypes binding for the native mt19937 replay generator.

Compiled on first use with the system g++ into ``_build/`` next to this file;
falls back gracefully (``available() -> False``) when no toolchain exists.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "gen.cpp"
_SO = _HERE / "_build" / "libkdtgen.so"
_lock = threading.Lock()
_lib = None
_failed = False


def _load():
    global _lib, _failed
    if _lib is not None or _failed:
        return _lib
    with _lock:
        if _lib is not None or _failed:
            return _lib
        def _compile():
            # compile to a PID-suffixed temp and os.replace() into place so a
            # concurrent process can never CDLL a partially written file
            _SO.parent.mkdir(parents=True, exist_ok=True)
            tmp = _SO.with_suffix(f".so.{os.getpid()}")
            subprocess.run(
                ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                 str(_SRC), "-o", str(tmp)],
                check=True, capture_output=True,
            )
            os.replace(tmp, _SO)

        try:
            if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
                _compile()
            try:
                lib = ctypes.CDLL(str(_SO))
            except OSError:
                # stale or wrong-arch binary: force one rebuild before giving up
                _compile()
                lib = ctypes.CDLL(str(_SO))
            lib.kdt_generate_rows.argtypes = [
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int64, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_float),
            ]
            lib.kdt_generate_rows.restype = None
            lib.kdt_first_draw.argtypes = [ctypes.c_int32]
            lib.kdt_first_draw.restype = ctypes.c_float
            _lib = lib
        except Exception:
            _failed = True
    return _lib


def available() -> bool:
    return _load() is not None


def generate_rows(seed: int, dim: int, row_start: int, row_count: int) -> np.ndarray:
    """Rows [row_start, row_start+row_count) of the reference mt19937 stream,
    bit-identical to Utility.cpp:6-18 / kdtree_mpi.cpp:19-41."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native generator unavailable (no g++ toolchain?)")
    out = np.empty((row_count, dim), dtype=np.float32)
    lib.kdt_generate_rows(
        seed, dim, row_start, row_count,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    return out


def generate_problem_mt19937(seed: int, dim: int, num_points: int, num_queries: int = 10):
    """(points[N, D], queries[Q, D]) with the reference's exact layout:
    one stream of N+Q rows, queries last (kdtree_sequential.cpp:157,169)."""
    rows = generate_rows(seed, dim, 0, num_points + num_queries)
    return rows[:num_points], rows[num_points:]
