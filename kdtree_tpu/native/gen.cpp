// Bit-exact replay of the reference problem generator.
//
// The reference generates its point cloud host-side with std::mt19937 +
// std::uniform_real_distribution<float>(-100, 100) (Utility.cpp:6-18), and
// its MPI variant skips to a shard's rows with random.discard(rows * dim)
// (kdtree_mpi.cpp:24,32) — one 32-bit draw per float on libstdc++, which is
// what makes the discard arithmetic line up.
//
// The TPU framework generates with threefry on-device by default
// (kdtree_tpu/ops/generate.py); this tiny native library exists so the
// harness protocol can reproduce the course grading stream bit-for-bit and
// the golden-parity tests can compare against the reference binary's output.
//
// Built as a shared library, bound via ctypes (no pybind11 in this image).

#include <cstdint>
#include <random>

extern "C" {

// Fill out[row_count * dim] with rows [row_start, row_start + row_count) of
// the infinite row stream defined by (seed, dim). Row r's floats are draws
// [r*dim, (r+1)*dim) of the distribution stream — the generalization that
// covers both the sequential layout (rows 0..n+q) and the MPI shard-local
// layout (any row window).
void kdt_generate_rows(int32_t seed, int32_t dim, int64_t row_start,
                       int64_t row_count, float* out) {
  std::mt19937 random(seed);
  std::uniform_real_distribution<float> distribution(-100.0f, 100.0f);
  random.discard(static_cast<unsigned long long>(row_start) * dim);
  const int64_t total = row_count * dim;
  for (int64_t i = 0; i < total; ++i) {
    out[i] = distribution(random);
  }
}

// Sanity probe for the binding: first draw of the stream for a seed.
float kdt_first_draw(int32_t seed) {
  std::mt19937 random(seed);
  std::uniform_real_distribution<float> distribution(-100.0f, 100.0f);
  return distribution(random);
}

}  // extern "C"
