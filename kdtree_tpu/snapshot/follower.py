"""Blue/green snapshot following for read replicas.

A secondary replica serves one epoch's snapshot while the shard primary
compacts the next; when the primary's epoch rebuilder emits a fresh
snapshot (``mutable/engine.py`` → ``snapshot/store.py``), the follower
notices the manifest's ``version`` change, loads the new tree (checksum
verified, mmap-read), pre-warms its batch shapes OFF the serving path,
and swaps it into the engine atomically between batches — the same
zero-downtime handoff the in-process epoch rebuilder uses, stretched
across processes. ``/healthz`` then reports the adopted epoch, which is
how a fleet's convergence is observed (docs/SERVING.md "Snapshots &
replica fleets").

The poll loop never raises: a torn manifest mid-write reads as "nothing
new yet" (the writer replaces it atomically, so the next poll sees a
complete one), and a corrupt segment counts a
``kdtree_snapshot_load_errors_total`` and keeps the CURRENT epoch
serving — a replica must degrade to stale, never to down.
"""

from __future__ import annotations

import threading
from typing import Optional

from kdtree_tpu import obs
from kdtree_tpu.obs import flight
from kdtree_tpu.snapshot.store import (
    SnapshotError,
    load_snapshot,
    read_manifest,
    resolve_dir,
    seed_plan_store,
)

DEFAULT_POLL_S = 2.0


class SnapshotFollower:
    """Poll a snapshot directory and blue/green-swap new versions into
    a :class:`~kdtree_tpu.mutable.engine.MutableEngine`.

    ``start_version`` is the manifest version the engine already serves
    (the one the process booted from), so the first poll doesn't
    re-adopt it. ``on_adopt(manifest)`` runs after each successful swap
    — the server uses it to surface the live snapshot version on
    ``/healthz``.
    """

    def __init__(
        self,
        engine,
        dirpath: str,
        poll_s: float = DEFAULT_POLL_S,
        start_version: int = 0,
        on_adopt=None,
    ) -> None:
        self.engine = engine
        self.dir = resolve_dir(dirpath)
        self.poll_s = max(float(poll_s), 0.05)
        self.version = int(start_version)
        # a version whose load FAILED (corrupt at rest): skip it until
        # the manifest changes — re-verifying hundreds of MB of
        # segments every poll tick would burn disk bandwidth retrying
        # an outcome that cannot change without a new save
        self._failed_version: Optional[int] = None
        self._on_adopt = on_adopt
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._g_version = obs.get_registry().gauge(
            "kdtree_snapshot_follow_version")
        self._g_version.set(self.version)
        self._adopts = obs.get_registry().counter(
            "kdtree_snapshot_adoptions_total")

    # -- one poll tick (public for tests: deterministic, no thread) ---------

    def poll_once(self) -> bool:
        """Check the manifest and adopt a changed version; True when a
        swap happened. Never raises."""
        try:
            man = read_manifest(self.dir)
            if man is None:
                return False
            version = int(man.get("version", 0))
            if version == self.version or version == self._failed_version:
                return False
            return self._adopt(version)
        except Exception as e:  # the loop must outlive any single tick
            flight.record("snapshot.follow_error", dir=self.dir,
                          error=repr(e)[:200])
            return False

    def _adopt(self, version: int) -> bool:
        try:
            tree, man = load_snapshot(self.dir)
        except SnapshotError:
            # counted + flight-recorded by the store; keep serving the
            # current epoch. Latch the failed version so the next tick
            # doesn't re-checksum the same broken segment set — only a
            # NEW save (version bump) re-arms the attempt.
            self._failed_version = version
            return False
        except Exception as e:
            # anything past the store's own checks (device transfer
            # OOM, jax runtime) is just as unchangeable until a new
            # save — latch it too, or the replica re-streams the full
            # verify pass every tick retrying an outcome that cannot
            # change (the exact loop the latch exists to prevent)
            self._failed_version = version
            flight.record("snapshot.follow_error", dir=self.dir,
                          version=version, error=repr(e)[:200])
            return False
        # the version ACTUALLY loaded: load_snapshot re-reads the
        # manifest, so a save landing between the poll and the load is
        # already the one adopted here — recording the stale poll
        # version would re-adopt the identical snapshot next tick (and
        # under-report the serving version on the gauge)
        version = int(man.get("version", version))
        epoch = int(man.get("epoch", 0))
        try:
            # seed the local plan store from the manifest's pre-shipped
            # profiles BEFORE the pre-warm below dispatches: adopt_tree's
            # warmup ladder then resolves the primary's settled plans
            # warm instead of locally re-settling them (fill-misses-only
            # — seed_plan_store never overwrites local knowledge; and
            # never raises past its own store tolerance)
            seeded = seed_plan_store(man)
            # pre-warm + swap: adopt_tree compiles the new epoch's
            # batch shapes on THIS thread before the atomic handoff, so
            # serving never dispatches cold (the epoch rebuilder's own
            # discipline)
            self.engine.adopt_tree(tree, epoch=epoch)
        except Exception as e:
            self._failed_version = version
            flight.record("snapshot.follow_error", dir=self.dir,
                          version=version, error=repr(e)[:200])
            return False
        self._failed_version = None
        self.version = version
        self._g_version.set(version)
        self._adopts.inc()
        flight.record("snapshot.follow_swap", dir=self.dir,
                      version=version, epoch=epoch,
                      n=int(tree.n_real), plans_seeded=seeded)
        if self._on_adopt is not None:
            try:
                self._on_adopt(man)
            except Exception:
                pass  # observer hooks must not stall the follower
        return True

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="kdtree-snapshot-follower", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.poll_once()
            self._stop.wait(self.poll_s)

    def stop(self, timeout_s: float = 30.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout_s)
        self._thread = None
