"""Versioned on-disk snapshots of a built serving index.

The reference's MPI layer assumes every process rebuilds its shard from
the seed, and our serving stack inherited that: replica cold-start was
gen/load + the full sample-sort build + the warmup ladder. A snapshot
separates build cost from query cost — the expensive artifact (the
built :class:`~kdtree_tpu.ops.morton.MortonTree`'s device arrays) is
serialized ONCE and every replica mmap-loads it in seconds
(docs/SERVING.md "Snapshots & replica fleets").

On-disk layout (one directory per index)::

    DIR/
      MANIFEST.json            # schema, version, epoch, signature,
                               # per-segment sha256 checksums, plan keys
      seg-node_lo-<tag>.npy    # flat .npy segments, one per tree array
      seg-node_hi-<tag>.npy
      seg-bucket_pts-<tag>.npy
      seg-bucket_gid-<tag>.npy

Write protocol: segments first (fresh per-save ``tag`` so a crashed
re-save can never mix generations), manifest written to a tmp file and
``os.replace``d LAST — a reader that sees a manifest sees a complete,
self-consistent segment set. ``version`` increments on every save into
the directory; the blue/green follower (``snapshot/follower.py``) polls
it to detect a fresh epoch.

Read protocol: schema check, per-segment sha256 verification (streamed
— the verify pass doubles as the page-cache warm for the mmap), then
``np.load(mmap_mode="r")`` and ONE device transfer per segment. No
sort, no reductions, no build compile: loaded answers are byte-identical
to a from-scratch build over the same points because the bytes ARE the
built tree's. A checksum mismatch or schema skew raises a NAMED error
(:class:`SnapshotCorruptError` / :class:`SnapshotSchemaError`) — a
half-read mmap must never serve.

The delta buffer of a mutable engine is deliberately NOT snapshotted:
a snapshot captures one epoch's compacted main tree, and the manifest
records which epoch that is (``epoch``). Replicas converge by adopting
the next epoch's snapshot, not by replaying writes.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
import uuid
from typing import Dict, List, Optional, Tuple

import numpy as np

from kdtree_tpu import obs
from kdtree_tpu.obs import flight

SNAPSHOT_SCHEMA = 1
MANIFEST_NAME = "MANIFEST.json"
# the MortonTree pytree leaves, in tree_flatten order
_SEGMENTS = ("node_lo", "node_hi", "bucket_pts", "bucket_gid")
_HASH_CHUNK = 1 << 22  # 4 MiB streaming-checksum window


class SnapshotError(Exception):
    """Base class for snapshot load/save failures — callers that want
    to fall back to a from-scratch rebuild catch exactly this."""


class SnapshotSchemaError(SnapshotError):
    """The manifest's schema version is not one this code reads."""


class SnapshotCorruptError(SnapshotError):
    """A segment is missing, truncated, or fails its checksum — the
    snapshot must not serve."""


def resolve_dir(path: str) -> str:
    """Resolve a snapshot directory path. Relative paths resolve under
    ``KDTREE_TPU_SNAPSHOT_DIR`` when it is set — the per-run isolation
    hook tests/CI use so snapshot litter can never land in the working
    tree. Absolute paths (and relative ones with the env unset) pass
    through unchanged. The result is ABSOLUTE whenever the base
    applies, so resolving twice (the follower stores a resolved dir
    and load_snapshot resolves again) is idempotent even under a
    relative base — without that, 'snapshots' + 'dir' re-resolved to
    'snapshots/snapshots/dir' and a follower never converged."""
    base = os.environ.get("KDTREE_TPU_SNAPSHOT_DIR")
    if base and not os.path.isabs(path):
        return os.path.abspath(os.path.join(base, path))
    return path


def _manifest_path(dirpath: str) -> str:
    return os.path.join(dirpath, MANIFEST_NAME)


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_HASH_CHUNK)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


class _HashingWriter:
    """File-object shim that hashes every byte as it is written, so the
    save path computes each segment's checksum DURING the write instead
    of re-reading hundreds of MB back per epoch emit. (The load side's
    streamed re-hash stays — there it doubles as the page-cache warm.)
    Not a real file object on purpose: np.save's isfileobj check then
    takes the buffered fp.write path, which is the one that feeds us."""

    def __init__(self, f) -> None:
        self._f = f
        self.h = hashlib.sha256()

    def write(self, data) -> int:
        self.h.update(data)
        return self._f.write(data)


def _count_load_error(reason: str) -> None:
    obs.get_registry().counter(
        "kdtree_snapshot_load_errors_total", labels={"reason": reason}
    ).inc()


def _load_error(exc: SnapshotError, reason: str,
                dirpath: str) -> SnapshotError:
    """Count + flight-record one failed load, then return the exception
    for the caller to raise — every load failure is an incident-shaped
    event (the fallback-to-rebuild path dumps context from here)."""
    _count_load_error(reason)
    flight.record("snapshot.load_error", dir=dirpath, reason=reason,
                  error=str(exc)[:200])
    return exc


def plan_keys_for(tree, k: int, max_batch: int = 1024,
                  min_bucket: Optional[int] = None) -> List[str]:
    """The plan-store keys a server over this snapshot warms on its
    ladder (docs/TUNING.md): one signature per pow2 warmup bucket.
    Advisory manifest metadata — a replica fleet can pre-ship the
    matching plan profiles so even the FIRST batch after a blue/green
    swap dispatches warm."""
    from kdtree_tpu.serve.batcher import MIN_BUCKET, batch_bucket
    from kdtree_tpu.tuning.store import _pow2_ceil, make_signature

    import jax

    max_batch = _pow2_ceil(int(max_batch))
    lo = batch_bucket(1, max_batch, MIN_BUCKET if min_bucket is None
                      else min_bucket)
    buckets = []
    b = lo
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch)
    kk = min(int(k), int(tree.n_real))
    return [
        make_signature(
            q, tree.dim, tree.n_real, kk, tree.bucket_size,
            tree.num_buckets, devices=1, backend=jax.default_backend(),
        ).key
        for q in buckets
    ]


def collect_plan_profiles(
    plan_keys: Optional[List[str]],
) -> Dict[str, dict]:
    """The local plan store's raw profiles for ``plan_keys`` — the
    payload a snapshot PRE-SHIPS to replicas (docs/SERVING.md
    "Snapshots & replica fleets"). Only keys the local store has
    actually settled are included (a manifest must never ship a guess);
    a disabled or unreadable store yields an empty dict. Profiles stay
    version-checked raw dicts, signature included, so the seeding side
    can reconstruct the exact store key."""
    from kdtree_tpu.tuning.store import default_store

    store = default_store()
    out: Dict[str, dict] = {}
    for key in plan_keys or []:
        prof = store.raw_for_key(str(key))
        if prof is not None:
            out[str(key)] = prof
    return out


def seed_plan_store(manifest) -> int:
    """Seed the LOCAL plan store from a manifest's pre-shipped
    ``plan_profiles`` (the other half of :func:`collect_plan_profiles`)
    — called by ``serve --snapshot`` and the blue/green follower BEFORE
    the warmup ladder runs, so adoption compiles warm instead of
    locally re-settling every launch plan. Returns how many profiles
    were written.

    Fill-misses-only: a key the local store already holds is skipped —
    local knowledge (possibly tuned on THIS host) outranks the
    primary's. Malformed entries are skipped silently (advisory
    metadata, the plan-store trust model: a wrong profile can only
    cost speed, and the overflow-retry contract still guards every
    batch)."""
    from kdtree_tpu.tuning.store import PlanSignature, default_store

    profiles = (manifest or {}).get("plan_profiles")
    if not isinstance(profiles, dict) or not profiles:
        return 0
    store = default_store()
    if not store.enabled:
        return 0
    seeded = 0
    for key, prof in profiles.items():
        if not isinstance(prof, dict):
            continue
        sig_d = prof.get("signature")
        if not isinstance(sig_d, dict):
            continue
        try:
            sig = PlanSignature(**{f: sig_d[f]
                                   for f in PlanSignature._fields})
        except (KeyError, TypeError):
            continue
        if sig.key != key:
            continue  # the key must name the profile it claims to
        if store.get_raw(sig) is not None:
            continue
        body = {k: v for k, v in prof.items()
                if k not in ("version", "signature", "updated_unix")}
        if store.put(sig, body):
            seeded += 1
    if seeded:
        obs.get_registry().counter(
            "kdtree_snapshot_plan_seeded_total").inc(seeded)
        flight.record("snapshot.plan_seed", seeded=seeded,
                      shipped=len(profiles))
    return seeded


def read_manifest(dirpath: str) -> Optional[dict]:
    """Parse the manifest, or None when the directory holds none (or a
    torn/unparseable one — the follower treats that as 'nothing new
    yet', and an actual load attempt reports it crisply)."""
    try:
        with open(_manifest_path(dirpath)) as f:
            man = json.load(f)
    except (OSError, ValueError):
        return None
    return man if isinstance(man, dict) else None


def _gen_manifest_name(version: int) -> str:
    return f"MANIFEST-v{int(version):08d}.json"


_GEN_MANIFEST_RE = None


def list_versions(dirpath: str) -> List[int]:
    """Retained generation numbers (ascending) — the versions a
    ``load_snapshot(..., version=N)`` rollback can still reach."""
    import re

    global _GEN_MANIFEST_RE
    if _GEN_MANIFEST_RE is None:
        _GEN_MANIFEST_RE = re.compile(r"^MANIFEST-v(\d{8})\.json$")
    dirpath = resolve_dir(dirpath)
    out = []
    try:
        names = os.listdir(dirpath)
    except OSError:
        return out
    for fname in names:
        m = _GEN_MANIFEST_RE.match(fname)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def _gc_generations(dirpath: str, keep: int) -> int:
    """Retention GC (docs/SERVING.md "Snapshots & replica fleets"):
    keep the newest ``keep`` generation manifests, drop older ones,
    then remove every ``seg-*.npy`` no RETAINED manifest (the live
    ``MANIFEST.json`` included) references — segments are refcounted
    by manifest, so a file shared by two generations survives until
    both are dropped. Returns the number of generations removed.

    Safety against a concurrent follower load: the live manifest and
    every retained generation keep their full segment sets, so any
    reader that saw a retained manifest finds its files. A reader
    mid-load of a JUST-DROPPED generation can race the unlink — it
    then fails the missing-segment/checksum check with a NAMED error
    and retries its poll (the follower's contract); it can never serve
    a half state. ``keep >= 2`` gives followers a full generation of
    slack before that race is even reachable."""
    removed = 0
    keep = max(int(keep), 1)
    versions = list_versions(dirpath)
    for version in versions[:-keep] if len(versions) > keep else []:
        try:
            os.remove(os.path.join(dirpath, _gen_manifest_name(version)))
            removed += 1
        except OSError:
            pass
    referenced = set()
    manifests = [read_manifest(dirpath)]
    for version in list_versions(dirpath):
        try:
            with open(os.path.join(dirpath,
                                   _gen_manifest_name(version))) as f:
                manifests.append(json.load(f))
        except (OSError, ValueError):
            continue
    for man in manifests:
        if not isinstance(man, dict):
            continue
        for seg in (man.get("segments") or {}).values():
            if isinstance(seg, dict) and seg.get("file"):
                referenced.add(str(seg["file"]))
    try:
        names = os.listdir(dirpath)
    except OSError:
        return removed
    for fname in names:
        if (fname.startswith("seg-") and fname.endswith(".npy")
                and fname not in referenced):
            try:
                os.remove(os.path.join(dirpath, fname))
            except OSError:
                pass
    if removed:
        flight.record("snapshot.gc", dir=dirpath, removed=removed,
                      kept=len(list_versions(dirpath)))
        obs.get_registry().counter(
            "kdtree_snapshot_gc_generations_total").inc(removed)
    return removed


def save_snapshot(
    dirpath: str,
    tree,
    epoch: int = 0,
    id_offset: int = 0,
    plan_keys: Optional[List[str]] = None,
    plan_profiles: Optional[Dict[str, dict]] = None,
    meta: Optional[dict] = None,
    keep: int = 1,
) -> dict:
    """Serialize a built Morton serving index into ``dirpath``; returns
    the manifest dict (its ``version`` is the previous manifest's + 1).

    ``keep`` is the retention depth (``--snapshot-keep``): the newest
    ``keep`` generations stay loadable — each save also writes a
    per-generation ``MANIFEST-v*.json``, and the GC drops older
    generations plus any segment no retained manifest references
    (refcounted, see :func:`_gc_generations`). ``keep=1`` is the
    historical behavior: one generation on disk; ``keep=3`` makes
    ``serve --snapshot DIR --snapshot-version N`` a rollback button.

    Only :class:`~kdtree_tpu.ops.morton.MortonTree` is snapshotable —
    it IS the serving representation; adapt other kinds through
    ``serve.lifecycle.tree_for_serving`` first (crisp ``TypeError``
    otherwise, same contract as serving itself)."""
    from kdtree_tpu.ops.morton import MortonTree

    if not isinstance(tree, MortonTree):
        raise TypeError(
            f"snapshots hold the Morton serving index, got "
            f"{type(tree).__name__} — adapt it with "
            "serve.lifecycle.tree_for_serving first"
        )
    dirpath = resolve_dir(dirpath)
    t0 = time.perf_counter()
    os.makedirs(dirpath, exist_ok=True)
    prev = read_manifest(dirpath)
    version = int(prev.get("version", 0)) + 1 if prev else 1
    tag = uuid.uuid4().hex[:8]
    segments: Dict[str, dict] = {}
    total_bytes = 0
    for name in _SEGMENTS:
        arr = np.asarray(getattr(tree, name))
        fname = f"seg-{name}-{tag}.npy"
        fpath = os.path.join(dirpath, fname)
        tmp = f"{fpath}.tmp"
        try:
            with open(tmp, "wb") as f:
                w = _HashingWriter(f)
                np.save(w, arr)
            os.replace(tmp, fpath)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
        nbytes = os.path.getsize(fpath)
        total_bytes += nbytes
        segments[name] = {
            "file": fname,
            "sha256": w.h.hexdigest(),
            "bytes": nbytes,
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
        }
    manifest = {
        "schema": SNAPSHOT_SCHEMA,
        "version": version,
        "epoch": int(epoch),
        "id_offset": int(id_offset),
        "kind": "morton",
        "signature": {
            "n_real": int(tree.n_real),
            "num_levels": int(tree.num_levels),
            "dim": int(tree.dim),
            "num_buckets": int(tree.num_buckets),
            "bucket_size": int(tree.bucket_size),
            "heap_size": int(tree.heap_size),
        },
        "segments": segments,
        "plan_keys": list(plan_keys or []),
        # the pre-shipped warm-plan payload (collect_plan_profiles):
        # replicas seed their store from it before warmup, so adoption
        # compiles warm instead of locally re-tuning (the PR 13 open
        # half — plan_keys used to be advisory key names only)
        "plan_profiles": dict(plan_profiles or {}),
        "created_unix": round(time.time(), 3),
        "meta": dict(meta or {}),
    }
    # generation manifest FIRST, live MANIFEST.json LAST: a reader that
    # sees the live manifest sees a complete retained set, and a crash
    # between the two leaves only an orphan generation file the next
    # save's GC collects
    for target in (os.path.join(dirpath, _gen_manifest_name(version)),
                   _manifest_path(dirpath)):
        tmp = f"{target}.tmp-{tag}"
        try:
            with open(tmp, "w") as f:
                json.dump(manifest, f, indent=2, sort_keys=True)
                f.write("\n")
            os.replace(tmp, target)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
    _gc_generations(dirpath, keep=keep)
    dt = time.perf_counter() - t0
    reg = obs.get_registry()
    reg.counter("kdtree_snapshot_saves_total").inc()
    reg.gauge("kdtree_snapshot_version").set(version)
    reg.gauge("kdtree_snapshot_epoch").set(int(epoch))
    reg.gauge("kdtree_snapshot_bytes").set(total_bytes)
    reg.gauge("kdtree_snapshot_save_seconds").set(round(dt, 6))
    flight.record("snapshot.save", dir=dirpath, version=version,
                  epoch=int(epoch), n=int(tree.n_real),
                  bytes=total_bytes, seconds=round(dt, 3))
    return manifest


def _read_manifest_strict(dirpath: str,
                          version: Optional[int] = None) -> dict:
    mpath = (_manifest_path(dirpath) if version is None
             else os.path.join(dirpath, _gen_manifest_name(version)))
    try:
        with open(mpath) as f:
            man = json.load(f)
    except OSError as e:
        raise _load_error(
            SnapshotError(f"no snapshot manifest at {mpath}: {e}"),
            "missing", dirpath,
        ) from None
    except ValueError as e:
        raise _load_error(
            SnapshotCorruptError(f"manifest {mpath} is not JSON: {e}"),
            "manifest", dirpath,
        ) from None
    if not isinstance(man, dict) or "segments" not in man:
        raise _load_error(
            SnapshotCorruptError(f"manifest {mpath} is not a snapshot "
                                 "manifest (no 'segments')"),
            "manifest", dirpath,
        )
    schema = man.get("schema")
    if schema != SNAPSHOT_SCHEMA:
        raise _load_error(
            SnapshotSchemaError(
                f"snapshot {dirpath} has schema {schema!r}; this build "
                f"reads schema {SNAPSHOT_SCHEMA} — rebuild the snapshot "
                "with a matching kdtree-tpu"
            ),
            "schema", dirpath,
        )
    return man


def load_snapshot(
    dirpath: str, verify: bool = True, version: Optional[int] = None,
) -> Tuple[object, dict]:
    """Load a snapshot into a ready-to-serve
    :class:`~kdtree_tpu.ops.morton.MortonTree`; returns
    ``(tree, manifest)``.

    ``version`` selects a RETAINED generation (``--snapshot-keep``
    kept it; :func:`list_versions` lists them) instead of the live
    manifest — the rollback-by-version read path. A version the GC
    already dropped fails with the named missing-manifest error.

    Every segment is checksum-verified BEFORE any of it is handed to
    the engine (``verify=False`` skips the hash for trusted local
    hand-offs, e.g. the follower re-loading a file set this process
    just wrote and verified), then read through ``np.load(mmap_mode=
    "r")`` and transferred to the device once. Raises the named
    :class:`SnapshotError` subclasses on any inconsistency — never
    returns a partially-read index."""
    import jax.numpy as jnp

    dirpath = resolve_dir(dirpath)
    t0 = time.perf_counter()
    man = _read_manifest_strict(dirpath, version=version)
    sig = man.get("signature", {})
    arrays = {}
    for name in _SEGMENTS:
        seg = man["segments"].get(name)
        if not isinstance(seg, dict) or "file" not in seg:
            raise _load_error(
                SnapshotCorruptError(
                    f"snapshot {dirpath}: manifest lacks segment "
                    f"{name!r}"),
                "manifest", dirpath,
            )
        fpath = os.path.join(dirpath, seg["file"])
        try:
            size = os.path.getsize(fpath)
        except OSError as e:
            raise _load_error(
                SnapshotCorruptError(
                    f"snapshot {dirpath}: segment {seg['file']} "
                    f"unreadable ({e}) — a snapshot is the manifest "
                    "plus its seg-*.npy files and must be copied as a "
                    "set"),
                "segment", dirpath,
            ) from None
        if size != int(seg.get("bytes", -1)):
            raise _load_error(
                SnapshotCorruptError(
                    f"snapshot {dirpath}: segment {seg['file']} is "
                    f"{size} bytes, manifest says {seg.get('bytes')} "
                    "(truncated or torn write)"),
                "checksum", dirpath,
            )
        if verify:
            digest = _sha256_file(fpath)
            if digest != seg.get("sha256"):
                raise _load_error(
                    SnapshotCorruptError(
                        f"snapshot {dirpath}: segment {seg['file']} "
                        f"fails its sha256 check (have {digest[:12]}…, "
                        f"manifest {str(seg.get('sha256'))[:12]}…)"),
                    "checksum", dirpath,
                )
        try:
            arr = np.load(fpath, mmap_mode="r")
        except ValueError as e:
            raise _load_error(
                SnapshotCorruptError(
                    f"snapshot {dirpath}: segment {seg['file']} is not "
                    f"a readable .npy ({e})"),
                "segment", dirpath,
            ) from None
        if list(arr.shape) != list(seg.get("shape", [])) or \
                str(arr.dtype) != seg.get("dtype"):
            raise _load_error(
                SnapshotCorruptError(
                    f"snapshot {dirpath}: segment {seg['file']} has "
                    f"shape {arr.shape}/{arr.dtype}, manifest says "
                    f"{seg.get('shape')}/{seg.get('dtype')}"),
                "segment", dirpath,
            )
        # ONE device transfer per segment; the mmap means the host never
        # holds a second buffered copy alongside it
        arrays[name] = jnp.asarray(arr)
    from kdtree_tpu.ops.morton import MortonTree

    tree = MortonTree(
        node_lo=arrays["node_lo"],
        node_hi=arrays["node_hi"],
        bucket_pts=arrays["bucket_pts"],
        bucket_gid=arrays["bucket_gid"],
        n_real=int(sig.get("n_real", 0)),
        num_levels=int(sig.get("num_levels", 0)),
    )
    if tree.n_real <= 0 or tree.num_buckets != int(
            sig.get("num_buckets", -1)):
        raise _load_error(
            SnapshotCorruptError(
                f"snapshot {dirpath}: signature {sig!r} disagrees with "
                "the loaded arrays"),
            "manifest", dirpath,
        )
    dt = time.perf_counter() - t0
    reg = obs.get_registry()
    reg.counter("kdtree_snapshot_loads_total").inc()
    reg.gauge("kdtree_snapshot_version").set(int(man.get("version", 0)))
    reg.gauge("kdtree_snapshot_epoch").set(int(man.get("epoch", 0)))
    reg.gauge("kdtree_snapshot_load_seconds").set(round(dt, 6))
    flight.record("snapshot.load", dir=dirpath,
                  version=int(man.get("version", 0)),
                  epoch=int(man.get("epoch", 0)), n=int(tree.n_real),
                  seconds=round(dt, 3))
    return tree, man
