"""Index snapshots: versioned on-disk serialization of the built
serving structure, the replica fleet's cold-start and blue/green
primitive (docs/SERVING.md "Snapshots & replica fleets")."""

from kdtree_tpu.snapshot.follower import DEFAULT_POLL_S, SnapshotFollower
from kdtree_tpu.snapshot.store import (
    MANIFEST_NAME,
    SNAPSHOT_SCHEMA,
    SnapshotCorruptError,
    SnapshotError,
    SnapshotSchemaError,
    collect_plan_profiles,
    list_versions,
    load_snapshot,
    plan_keys_for,
    read_manifest,
    resolve_dir,
    save_snapshot,
    seed_plan_store,
)

__all__ = [
    "DEFAULT_POLL_S",
    "MANIFEST_NAME",
    "SNAPSHOT_SCHEMA",
    "SnapshotCorruptError",
    "SnapshotError",
    "SnapshotFollower",
    "SnapshotSchemaError",
    "collect_plan_profiles",
    "list_versions",
    "load_snapshot",
    "plan_keys_for",
    "read_manifest",
    "resolve_dir",
    "save_snapshot",
    "seed_plan_store",
]
