"""Hand-written TPU kernels (Pallas/Mosaic) for the framework's hot loops.

SURVEY.md C18: the reference's bonus tier invites SIMD/GPU kernels
(Project_KDTree.pdf p.5 Task 5; the Makefile already compiles with -mavx,
Makefile:2,6). The TPU equivalents live here:

- :mod:`scan_knn` — the fused bucket-scan + top-k fold for the tiled query
  engine: per-tile DMA streaming of candidate buckets with scalar early
  exit, distances on the VPU, in-register k-extraction. Replaces the XLA
  gather -> top_k -> sort chain, which materializes every candidate block
  in HBM and cannot stop early.

Every kernel has an XLA reference implementation and an identity test
(same algorithm, bit-comparable results) plus the brute-force oracle.

Why there is no radix-sort BUILD kernel (measured decision, round 3): at
the 16M-point headline shape the whole gen+build+query chain is ~0.2 s of
which ~0.1 s is host-dispatch latency, and the ``lax.sort`` that builds
the Morton tree is already faster than a sort-then-gather split (222 ms vs
388 ms wall including dispatch). A Mosaic radix sort would need per-run
variable-length HBM scatter DMAs (unsupported: DMA sizes are static) or
per-row scalar stores (dead slow), to chase <25%% of a dispatch-bound
number. The query scan kernel above was the leverage point instead:
measured 3-4x on the north-star query throughput.
"""

from kdtree_tpu.pallas.scan_knn import scan_tiles_fused

__all__ = ["scan_tiles_fused"]
