"""Fused bucket-scan + top-k Pallas kernel for the tiled query engine.

One grid step = one query tile. The kernel walks the tile's candidate
buckets (lb-ascending, as produced by ``tile_query._frontier``) with:

- **scalar early exit**: stop as soon as the next bucket's box lower bound
  cannot beat the tile's current worst k-th distance — data-dependent
  control flow that costs one SMEM read per step here, but is impossible
  in the XLA formulation (static scan over all C chunks);
- **double-buffered DMA**: bucket ``b``'s coordinates/ids stream
  HBM -> VMEM while bucket ``b-1`` computes;
- **VPU distance blocks**: per axis, a [TQ, B] broadcast-subtract-square
  accumulation (coordinates are stored transposed [D, B] per bucket so the
  block's minor dims are (TQ, B) = clean (8, 128) f32 tiles);
- **conditional in-register fold**: a [TQ, B] block is merged into the
  [TQ, k] best-buffer only when its row-minimum beats some query's current
  k-th (skipped folds cost one vector min + one scalar test); the merge is
  k unrolled min/one-hot-extract passes over a [TQ, B+k] work buffer.

Exactness matches the XLA scan path: identical candidate sets, identical
distance arithmetic, ties broken by scan order (candidates are lb-sorted
by the same frontier). The per-query result buffers come back ascending.

The XLA reference path (``tile_query._scan_tiles``) stays as the identity
oracle and the non-TPU fallback.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _scan_kernel(
    # per-tile SMEM blocks (a scalar-prefetch [T, C] array would need the
    # WHOLE candidate table in the ~1MB SMEM; a (1, C) block per grid step
    # streams in a few hundred bytes instead)
    cand_ref,  # i32[1, 1, C] SMEM block
    lb_ref,  # f32[1, 1, C] SMEM block
    # array inputs
    tqT_ref,  # f32[1, D, TQp] VMEM block (tile queries, transposed)
    ptsT_hbm,  # f32[NBP, D, B] ANY (manual DMA)
    gid_hbm,  # i32[NBP, 1, B] ANY (manual DMA)
    # outputs
    out_d_ref,  # f32[1, TQp, k]
    out_i_ref,  # i32[1, TQp, k]
    # scratch
    pbuf,  # f32[2, D, B]
    gbuf,  # i32[2, 1, B]
    sems,  # DMA sems [2, 2]
    work_d,  # f32[TQp, W]
    work_i,  # i32[TQp, W]
):
    C = cand_ref.shape[2]
    tqp, k = out_d_ref.shape[1], out_d_ref.shape[2]
    D = pbuf.shape[1]
    B = pbuf.shape[2]
    W = work_d.shape[1]

    out_d_ref[0] = jnp.full((tqp, k), jnp.inf, jnp.float32)
    out_i_ref[0] = jnp.full((tqp, k), -1, jnp.int32)
    # constant work-buffer tail (lanes >= B + k never hold candidates)
    work_d[...] = jnp.full((tqp, W), jnp.inf, jnp.float32)
    work_i[...] = jnp.full((tqp, W), -1, jnp.int32)

    def dmas(c, slot):
        b = jnp.maximum(cand_ref[0, 0, c], 0)  # padding never folds; clamp for DMA
        return (
            pltpu.make_async_copy(ptsT_hbm.at[b], pbuf.at[slot], sems.at[slot, 0]),
            pltpu.make_async_copy(gid_hbm.at[b], gbuf.at[slot], sems.at[slot, 1]),
        )

    def start(c, slot):
        cp, cg = dmas(c, slot)
        cp.start()
        cg.start()

    def wait(c, slot):
        cp, cg = dmas(c, slot)
        cp.wait()
        cg.wait()

    start(0, 0)
    lanes = lax.broadcasted_iota(jnp.int32, (tqp, W), 1)

    def cond(c):
        worst = jnp.max(out_d_ref[0, :, k - 1])
        return (c < C) & (lb_ref[0, 0, c] < worst)

    def body(c):
        slot = lax.rem(c, 2)

        @pl.when(c + 1 < C)
        def _():
            start(c + 1, lax.rem(c + 1, 2))

        wait(c, slot)

        acc = jnp.zeros((tqp, B), jnp.float32)
        for d in range(D):
            qd = tqT_ref[0, d, :].reshape(tqp, 1)
            pd = pbuf[slot, d, :].reshape(1, B)
            diff = qd - pd
            acc = acc + diff * diff

        kth = out_d_ref[0, :, k - 1]
        need = jnp.any(jnp.min(acc, axis=1) < kth)

        @pl.when(need)
        def _():
            work_d[:, :B] = acc
            work_i[:, :B] = jnp.broadcast_to(gbuf[slot, 0, :].reshape(1, B), (tqp, B))
            work_d[:, B : B + k] = out_d_ref[0]
            work_i[:, B : B + k] = out_i_ref[0]
            wd = work_d[...]
            wi = work_i[...]
            for j in range(k):
                rm = jnp.min(wd, axis=1, keepdims=True)  # [TQ, 1]
                ml = jnp.min(jnp.where(wd == rm, lanes, W), axis=1, keepdims=True)
                onehot = lanes == ml
                out_d_ref[0, :, j] = rm[:, 0]
                out_i_ref[0, :, j] = jnp.sum(
                    jnp.where(onehot, wi, 0), axis=1, dtype=jnp.int32
                )
                wd = jnp.where(onehot, jnp.inf, wd)

        return c + 1

    c_stop = lax.while_loop(cond, body, jnp.int32(0))

    # the prologue (c=0) or the last body iteration's prefetch (c_stop) may
    # have left a DMA in flight that no iteration waited on; a kernel must
    # not exit with outstanding DMAs
    @pl.when(c_stop < C)
    def _():
        wait(c_stop, lax.rem(c_stop, 2))


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def _scan_tiles_fused_impl(tqT, cand, lb, ptsT, gid3, k: int, interpret: bool):
    T, D, tqp = tqT.shape
    C = cand.shape[1]
    B = ptsT.shape[2]
    W = _round_up(B + k, _LANE)

    return pl.pallas_call(
        _scan_kernel,
        grid=(T,),
        in_specs=[
            # [T, 1, C] with a (1, 1, C) block: the TPU lowering requires
            # the last two block dims to be full (or (8,128)-aligned)
            pl.BlockSpec((1, 1, C), lambda t: (t, 0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, C), lambda t: (t, 0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, D, tqp), lambda t: (t, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec((1, tqp, k), lambda t: (t, 0, 0)),
            pl.BlockSpec((1, tqp, k), lambda t: (t, 0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((T, tqp, k), jnp.float32),
            jax.ShapeDtypeStruct((T, tqp, k), jnp.int32),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, D, B), jnp.float32),
            pltpu.VMEM((2, 1, B), jnp.int32),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.VMEM((tqp, W), jnp.float32),
            pltpu.VMEM((tqp, W), jnp.int32),
        ],
        interpret=interpret,
    )(cand[:, None, :], lb[:, None, :], tqT, ptsT, gid3)


def scan_tiles_fused(
    tree, tq, cand, cand_lb, k: int, interpret: bool | None = None
) -> Tuple[jax.Array, jax.Array]:
    """Drop-in for ``tile_query._scan_tiles`` on TPU.

    tq f32[T, TQ, D]; cand i32[T, C] lb-ascending (-1 pad); cand_lb
    f32[T, C] (+inf at pad). Returns (d2 f32[T, TQ, k], gid i32[T, TQ, k])
    ascending per query.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    T, TQ, D = tq.shape
    k = min(k, tree.n_real)
    tqp = max(TQ, 8)  # sublane floor; padding rows are duplicates, sliced off
    if tqp != TQ:
        tq = jnp.concatenate(
            [tq, jnp.broadcast_to(tq[:, -1:, :], (T, tqp - TQ, D))], axis=1
        )
    tqT = jnp.swapaxes(tq, 1, 2)  # [T, D, TQp]
    ptsT = jnp.swapaxes(tree.bucket_pts, 1, 2)  # [NBP, D, B]
    gid3 = tree.bucket_gid[:, None, :]  # [NBP, 1, B]
    d2, gi = _scan_tiles_fused_impl(tqT, cand, cand_lb, ptsT, gid3, k, interpret)
    return d2[:, :TQ], gi[:, :TQ]
