"""Fused bucket-scan + top-k Pallas kernel for the tiled query engine.

One grid step = one query tile. The kernel walks the tile's candidate
buckets (lb-ascending, as produced by ``tile_query._frontier``) with:

- **scalar early exit**: stop as soon as the next bucket's box lower bound
  cannot beat the tile's current worst k-th distance — data-dependent
  control flow that costs one SMEM read per step here, but is impossible
  in the XLA formulation (static scan over all C chunks);
- **double-buffered DMA**: bucket ``b``'s coordinates/ids stream
  HBM -> VMEM while bucket ``b-1`` computes;
- **VPU distance blocks**: per axis, a [TQ, B] broadcast-subtract-square
  accumulation (coordinates are stored transposed [D, B] per bucket so the
  block's minor dims are (TQ, B) = clean (8, 128) f32 tiles);
- **conditional in-register fold**: a [TQ, B] block is merged into the
  [TQ, k] best-buffer only when its row-minimum beats some query's current
  k-th (skipped folds cost one vector min + one scalar test); the merge is
  k unrolled min/one-hot-extract passes over a [TQ, B+k] work buffer.

Exactness matches the XLA scan path: identical candidate sets, identical
distance arithmetic, ties broken by scan order (candidates are lb-sorted
by the same frontier). The per-query result buffers come back ascending.

The XLA reference path (``tile_query._scan_tiles``) stays as the identity
oracle and the non-TPU fallback.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _scan_kernel(
    # per-tile SMEM blocks (a scalar-prefetch [T, C] array would need the
    # WHOLE candidate table in the ~1MB SMEM; a (1, C) block per grid step
    # streams in a few hundred bytes instead)
    cand_ref,  # i32[1, 1, Cp] SMEM block (Cp = V-padded candidate count)
    lb_ref,  # f32[1, 1, Cp] SMEM block
    # array inputs
    tqT_ref,  # f32[1, D, TQp] VMEM block (tile queries, transposed)
    ptsT_hbm,  # f32[NBP, 1, D*B] ANY (manual DMA; flat [D, B] rows —
    #           lane slices at d*B are 128-aligned for any D, which the
    #           [NBP, D, B] layout is NOT when D isn't sublane-tile-sized)
    gid_hbm,  # i32[NBP, 1, B] ANY (manual DMA)
    # outputs
    out_d_ref,  # f32[1, TQp, k]
    out_i_ref,  # i32[1, TQp, k]
    # scratch
    pbuf,  # f32[2, V, 1, D*B]
    gbuf,  # i32[2, V, 1, B]
    sems,  # DMA sems [2, V, 2]
    work_d,  # f32[TQp, W]  (W >= V*B + k)
    work_i,  # i32[TQp, W]
    *,
    V: int,
):
    """Candidates are walked in GROUPS of V buckets: V DMAs issue together
    and one k-extraction fold covers V*B candidates. Measured at the
    north-star shape with B=128 this was throughput-NEUTRAL (the scan is
    bound by per-candidate DMA/scalar overhead, not the fold — see
    DEFAULT_V), so V defaults to 1; the grouping stays for shapes where
    folds dominate (re-measure before relying on it, especially at larger
    B where fold cost doubles). Early exit checks the group's first
    (lowest) lower bound; in-group padding (cand -1) is masked to +inf
    before the fold."""
    Cp = cand_ref.shape[2]
    G = Cp // V  # number of groups
    tqp, k = out_d_ref.shape[1], out_d_ref.shape[2]
    D = tqT_ref.shape[1]
    B = gbuf.shape[3]
    W = work_d.shape[1]

    out_d_ref[0] = jnp.full((tqp, k), jnp.inf, jnp.float32)
    out_i_ref[0] = jnp.full((tqp, k), -1, jnp.int32)
    # constant work-buffer tail (lanes >= V*B + k never hold candidates)
    work_d[...] = jnp.full((tqp, W), jnp.inf, jnp.float32)
    work_i[...] = jnp.full((tqp, W), -1, jnp.int32)

    def dmas(g, v, slot):
        b = jnp.maximum(cand_ref[0, 0, g * V + v], 0)  # clamp padding for DMA
        return (
            pltpu.make_async_copy(
                ptsT_hbm.at[b], pbuf.at[slot, v], sems.at[slot, v, 0]
            ),
            pltpu.make_async_copy(
                gid_hbm.at[b], gbuf.at[slot, v], sems.at[slot, v, 1]
            ),
        )

    def start_group(g, slot):
        for v in range(V):
            cp, cg = dmas(g, v, slot)
            cp.start()
            cg.start()

    def wait_group(g, slot):
        for v in range(V):
            cp, cg = dmas(g, v, slot)
            cp.wait()
            cg.wait()

    start_group(0, 0)
    lanes = lax.broadcasted_iota(jnp.int32, (tqp, W), 1)

    # the early-exit decision is CARRIED, not read in the cond: jax 0.4.x
    # cannot discharge ref effects in a while cond (loops.py
    # _while_discharge_rule raises NotImplementedError), which kept this
    # kernel un-runnable in CPU interpret mode. Each body iteration decides
    # whether group g+1 can still beat the tile's worst k-th AFTER its own
    # fold — the same iteration set the ref-reading cond produced.
    def cond(carry):
        g, stop = carry
        return (g < G) & jnp.logical_not(stop)

    def body(carry):
        g, _ = carry
        slot = lax.rem(g, 2)

        @pl.when(g + 1 < G)
        def _():
            start_group(g + 1, lax.rem(g + 1, 2))

        wait_group(g, slot)

        best = jnp.full((tqp,), jnp.inf, jnp.float32)
        accs = []
        for v in range(V):
            acc = jnp.zeros((tqp, B), jnp.float32)
            for d in range(D):
                qd = tqT_ref[0, d, :].reshape(tqp, 1)
                pd = pbuf[slot, v, 0, d * B : (d + 1) * B].reshape(1, B)
                diff = qd - pd
                acc = acc + diff * diff
            # in-group padding buckets must never compete
            pad = cand_ref[0, 0, g * V + v] < 0
            acc = jnp.where(pad, jnp.inf, acc)
            accs.append(acc)
            best = jnp.minimum(best, jnp.min(acc, axis=1))

        kth = out_d_ref[0, :, k - 1]
        need = jnp.any(best < kth)

        # work-buffer stores happen ONLY when a fold fires — a skipped
        # bucket group costs just the register accs + one vector min
        @pl.when(need)
        def _():
            for v in range(V):
                work_d[:, v * B : (v + 1) * B] = accs[v]
                work_i[:, v * B : (v + 1) * B] = jnp.broadcast_to(
                    gbuf[slot, v, 0, :].reshape(1, B), (tqp, B)
                )
            work_d[:, V * B : V * B + k] = out_d_ref[0]
            work_i[:, V * B : V * B + k] = out_i_ref[0]
            wd = work_d[...]
            wi = work_i[...]
            for j in range(k):
                rm = jnp.min(wd, axis=1, keepdims=True)  # [TQ, 1]
                ml = jnp.min(jnp.where(wd == rm, lanes, W), axis=1, keepdims=True)
                onehot = lanes == ml
                out_d_ref[0, :, j] = rm[:, 0]
                out_i_ref[0, :, j] = jnp.sum(
                    jnp.where(onehot, wi, 0), axis=1, dtype=jnp.int32
                )
                wd = jnp.where(onehot, jnp.inf, wd)

        # can group g+1 still matter? Read the (possibly just-updated)
        # worst k-th here — the index clamp keeps the final iteration's
        # read in bounds (its stop value is dead: cond's g < G gates it)
        worst = jnp.max(out_d_ref[0, :, k - 1])
        nxt = jnp.minimum((g + 1) * V, Cp - 1)
        return g + 1, jnp.logical_not(lb_ref[0, 0, nxt] < worst)

    stop0 = jnp.logical_not(lb_ref[0, 0, 0] < jnp.inf)
    g_stop, _ = lax.while_loop(cond, body, (jnp.int32(0), stop0))

    # the prologue (g=0) or the last body iteration's prefetch (g_stop) may
    # have left a DMA group in flight that no iteration waited on; a kernel
    # must not exit with outstanding DMAs
    @pl.when(g_stop < G)
    def _():
        wait_group(g_stop, lax.rem(g_stop, 2))


@functools.partial(jax.jit, static_argnames=("k", "V", "interpret"))
def _scan_tiles_fused_impl(tqT, cand, lb, ptsT, gid3, k: int, V: int,
                           interpret: bool):
    T, D, tqp = tqT.shape
    B = gid3.shape[2]
    W = _round_up(V * B + k, _LANE)
    # pad the candidate axis to a multiple of V (-1 / +inf = the standard
    # padding encoding; in-group pads are masked, whole-pad groups never
    # run because their first lb is +inf)
    cpad = (-cand.shape[1]) % V
    if cpad:
        cand = jnp.concatenate(
            [cand, jnp.full((T, cpad), -1, cand.dtype)], axis=1
        )
        lb = jnp.concatenate(
            [lb, jnp.full((T, cpad), jnp.inf, lb.dtype)], axis=1
        )
    Cp = cand.shape[1]

    return pl.pallas_call(
        functools.partial(_scan_kernel, V=V),
        grid=(T,),
        in_specs=[
            # [T, 1, Cp] with a (1, 1, Cp) block: the TPU lowering requires
            # the last two block dims to be full (or (8,128)-aligned)
            pl.BlockSpec((1, 1, Cp), lambda t: (t, 0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, Cp), lambda t: (t, 0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, D, tqp), lambda t: (t, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec((1, tqp, k), lambda t: (t, 0, 0)),
            pl.BlockSpec((1, tqp, k), lambda t: (t, 0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((T, tqp, k), jnp.float32),
            jax.ShapeDtypeStruct((T, tqp, k), jnp.int32),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, V, 1, D * B), jnp.float32),
            pltpu.VMEM((2, V, 1, B), jnp.int32),
            pltpu.SemaphoreType.DMA((2, V, 2)),
            pltpu.VMEM((tqp, W), jnp.float32),
            pltpu.VMEM((tqp, W), jnp.int32),
        ],
        interpret=interpret,
    )(cand[:, None, :], lb[:, None, :], tqT, ptsT, gid3)


DEFAULT_V = 1  # buckets per fold group. Measured at the north-star shape:
               # V in {1, 2, 4, 8} is throughput-neutral (57.8k vs 56.5k
               # q/s) — the scan is bound by per-candidate scalar/DMA
               # overhead with the early exit gated by the tile-max k-th,
               # not by the fold — so keep the simplest configuration; the
               # grouping stays available for shapes where folds dominate.


def scan_tiles_fused(
    tree, tq, cand, cand_lb, k: int, interpret: bool | None = None,
    V: int | None = None,
) -> Tuple[jax.Array, jax.Array]:
    """Drop-in for ``tile_query._scan_tiles`` on TPU.

    tq f32[T, TQ, D]; cand i32[T, C] lb-ascending (-1 pad); cand_lb
    f32[T, C] (+inf at pad). Returns (d2 f32[T, TQ, k], gid i32[T, TQ, k])
    ascending per query. ``V`` groups that many buckets per DMA/fold round.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if V is None:
        V = DEFAULT_V
    T, TQ, D = tq.shape
    k = min(k, tree.n_real)
    tqp = max(TQ, 8)  # sublane floor; padding rows are duplicates, sliced off
    if tqp != TQ:
        tq = jnp.concatenate(
            [tq, jnp.broadcast_to(tq[:, -1:, :], (T, tqp - TQ, D))], axis=1
        )
    tqT = jnp.swapaxes(tq, 1, 2)  # [T, D, TQp]
    nbp, B = tree.bucket_gid.shape
    # flat [NBP, 1, D*B]: the kernel lane-slices at d*B offsets, which is
    # Mosaic-legal only when B is a lane-tile multiple
    assert B % _LANE == 0, f"bucket size must be a multiple of {_LANE}, got {B}"
    ptsT = jnp.swapaxes(tree.bucket_pts, 1, 2).reshape(nbp, 1, D * B)
    gid3 = tree.bucket_gid[:, None, :]  # [NBP, 1, B]
    d2, gi = _scan_tiles_fused_impl(
        tqT, cand, cand_lb, ptsT, gid3, k, V, interpret
    )
    return d2[:, :TQ], gi[:, :TQ]
