"""The query verbs' wire contract, shared by shard server and router.

One module owns request validation and response row shaping for
``/v1/radius``, ``/v1/range`` and ``/v1/count`` so the two HTTP fronts
cannot drift apart — the same single-validator idea as
``approx.parse_recall_target``. Every rejection names what was wrong.

JSON schemas (requests):

- ``/v1/radius``: ``{"queries": [[f32 x D] x q], "r": f | [f x q]}``
  plus the shared optionals (``recall_target``, ``deadline_ms``).
- ``/v1/range``:  ``{"lo": [[f32 x D] x q], "hi": [[f32 x D] x q]}``.
  ``lo > hi`` on any axis is a legitimately EMPTY box, not an error.
- ``/v1/count``:  exactly one of the two shapes above (``"r"`` selects
  the radius form, ``"lo"``/``"hi"`` the box form).

Responses carry ``counts`` always; ``ids`` (global, offset applied,
ascending or (distance, id)-ascending) and ``distances`` (sqrt of the
f32 d2 in float64, the k-NN response convention) only for the
id-materializing verbs; ``truncated`` whenever a bounded-visit answer
is a lower bound rather than exact.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

VERBS = ("radius", "range", "count")
COUNT_FORMS = ("radius", "box")


class VerbParseError(ValueError):
    """Invalid verb request body; ``str(e)`` is the 400 message."""


def _parse_matrix(payload, key: str, dim: int) -> np.ndarray:
    if key not in payload:
        raise VerbParseError(f'body must include "{key}"')
    try:
        arr = np.asarray(payload[key], dtype=np.float32)  # kdt-lint: disable=KDT201 decoded JSON payload is host data, never a device value
    except (TypeError, ValueError):
        raise VerbParseError(f'"{key}" must be a [q, d] number array')
    if arr.ndim != 2 or arr.shape[0] < 1:
        raise VerbParseError(f'"{key}" must be non-empty [q, {dim}], '
                             f"got shape {arr.shape}")
    if arr.shape[1] != dim:
        raise VerbParseError(f'"{key}" rows are {arr.shape[1]}-D but '
                             f"the index is {dim}-D")
    if not np.isfinite(arr).all():
        raise VerbParseError(f'"{key}" contains non-finite values')
    return arr


def parse_radius_body(payload: dict,
                      dim: int) -> Tuple[np.ndarray, np.ndarray]:
    """Validated (queries f32[q, D], r f32[q]). ``r`` may be a scalar
    (shared by all rows) or per-query; r = 0 is the legitimate
    degenerate radius (hits only coincident points)."""
    queries = _parse_matrix(payload, "queries", dim)
    if "r" not in payload:
        raise VerbParseError('body must include "r" (radius, scalar or '
                             "per-query list)")
    try:
        r = np.asarray(payload["r"], dtype=np.float32)  # kdt-lint: disable=KDT201 decoded JSON payload is host data, never a device value
    except (TypeError, ValueError):
        raise VerbParseError('"r" must be a number or a [q] number list')
    if r.ndim not in (0, 1):
        raise VerbParseError('"r" must be a scalar or a [q] list, got '
                             f"shape {r.shape}")
    if r.ndim == 1 and r.shape[0] != queries.shape[0]:
        raise VerbParseError(f'"r" has {r.shape[0]} entries for '
                             f"{queries.shape[0]} queries")
    if not np.isfinite(r).all() or (np.asarray(r) < 0).any():
        raise VerbParseError('"r" must be finite and >= 0')
    return queries, np.broadcast_to(r, (queries.shape[0],)).astype(
        np.float32)


def parse_range_body(payload: dict,
                     dim: int) -> Tuple[np.ndarray, np.ndarray]:
    """Validated (lo f32[q, D], hi f32[q, D])."""
    lo = _parse_matrix(payload, "lo", dim)
    hi = _parse_matrix(payload, "hi", dim)
    if lo.shape != hi.shape:
        raise VerbParseError(f'"lo" {lo.shape} and "hi" {hi.shape} must '
                             "have the same shape")
    return lo, hi


def parse_count_body(
    payload: dict, dim: int,
) -> Tuple[str, np.ndarray, Optional[np.ndarray], Optional[np.ndarray],
           Optional[np.ndarray]]:
    """Validated (form, queries|lo, r|None, lo|None, hi|None): the count
    verb is radius-form or box-form, selected by which keys are present
    (exactly one form, never both)."""
    has_r = "r" in payload or "queries" in payload
    has_box = "lo" in payload or "hi" in payload
    if has_r == has_box:
        raise VerbParseError(
            'count takes exactly one form: {"queries", "r"} (within '
            'radius) or {"lo", "hi"} (within box)')
    if has_r:
        queries, r = parse_radius_body(payload, dim)
        return "radius", queries, r, None, None
    lo, hi = parse_range_body(payload, dim)
    return "box", lo, None, lo, hi


def globalize_ids(ids: np.ndarray, id_offset: int) -> np.ndarray:
    """Shard-local gids -> global ids (padding stays -1); int64 like
    the k-NN response so deep shards can't wrap the i32 gid table."""
    ids = ids.astype(np.int64)
    if id_offset:
        ids = np.where(ids >= 0, ids + id_offset, -1)
    return ids


def radius_rows_json(d2: np.ndarray, ids: np.ndarray,
                     counts: np.ndarray, id_offset: int):
    """Variable-length response rows for the radius verb: per query,
    the hit ids ((distance, id)-ascending, padding stripped) and their
    Euclidean distances (sqrt of the f32 d2 in float64, the k-NN
    convention — identical arithmetic on every shard keeps the
    router's dedup-union merge byte-identical)."""
    gids = globalize_ids(ids, id_offset)
    dist = np.sqrt(d2.astype(np.float64))
    out_ids, out_d = [], []
    for q in range(ids.shape[0]):
        n = int(counts[q])
        out_ids.append(gids[q, :n].tolist())
        out_d.append(dist[q, :n].tolist())
    return out_ids, out_d


def range_rows_json(ids: np.ndarray, counts: np.ndarray,
                    id_offset: int):
    """Variable-length response rows for the range verb: per query,
    the contained ids ascending, padding stripped."""
    gids = globalize_ids(ids, id_offset)
    return [gids[q, :int(counts[q])].tolist()
            for q in range(ids.shape[0])]
