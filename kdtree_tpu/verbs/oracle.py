"""Brute-force oracles for the query verbs.

These are the exactness referees: every verb answer — from the device
kernels, through MutableEngine overlays, through the multi-shard router
merge — must be byte-identical (counts: exactly equal) to the oracle
over the same point set. To make byte-identity achievable rather than
aspirational, the oracle computes squared distances with the SAME f32
arithmetic as the device fold (``_block_d2_exact``: diff then
sum-of-squares, f32 throughout) and normalizes rows to the same
canonical forms (``canonical_radius_rows`` / ``canonical_range_rows``).

Oracles accept the flat padded storage the serving engines already
hold (+inf padding rows, gid -1) — padding and tombstone holes
self-exclude via the gid mask, never via distance screening.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from kdtree_tpu.ops.bruteforce import _block_d2_exact

# JITTED, like the k-NN oracle's scan: under jit, XLA:CPU fuses the
# diff/square/reduce chain and LLVM may contract mul+add into fma —
# the device fold compiles the same way, so the jitted panel is
# bit-identical to it, while an EAGER _block_d2_exact (one kernel per
# op, no cross-op contraction) can differ by 1 ulp. The byte-identity
# contract is defined over the jitted arithmetic.
_jit_block_d2 = jax.jit(_block_d2_exact)
from kdtree_tpu.verbs.device import (
    VerbResult,
    canonical_radius_rows,
    canonical_range_rows,
)

_ORACLE_TILE = 1 << 13  # points per distance block (bounds the [Q, N] panel)


def _gid_mask(points: np.ndarray, gid) -> np.ndarray:
    if gid is None:
        return np.arange(points.shape[0], dtype=np.int32)
    return np.asarray(gid, dtype=np.int32)  # kdt-lint: disable=KDT201 oracle reference code: host brute force by definition


def _pad_rows(rows, fill, dtype):
    m = max((len(r) for r in rows), default=0)
    m = max(m, 1)
    out = np.full((len(rows), m), fill, dtype)
    for i, r in enumerate(rows):
        out[i, :len(r)] = r
    return out


def radius_oracle(points, queries, r, *, gid=None,
                  with_ids: bool = True) -> VerbResult:
    """Exhaustive radius answer: every live point with d2 <= r^2 in
    f32, using the device fold's exact distance arithmetic."""
    points = np.asarray(points, dtype=np.float32)  # kdt-lint: disable=KDT201 oracle reference code: host brute force by definition
    queries = np.asarray(queries, dtype=np.float32)  # kdt-lint: disable=KDT201 oracle reference code: host brute force by definition
    Q = queries.shape[0]
    gid = _gid_mask(points, gid)
    r = np.broadcast_to(np.asarray(r, dtype=np.float32), (Q,))  # kdt-lint: disable=KDT201 oracle reference code: host brute force by definition
    r2 = (r * r).astype(np.float32)

    counts = np.zeros(Q, np.int64)
    rows_d = [[] for _ in range(Q)] if with_ids else None
    rows_i = [[] for _ in range(Q)] if with_ids else None
    qd = jnp.asarray(queries)
    for s in range(0, points.shape[0], _ORACLE_TILE):
        e = min(s + _ORACLE_TILE, points.shape[0])
        d2 = np.asarray(_jit_block_d2(qd, jnp.asarray(points[s:e])))  # kdt-lint: disable=KDT201 oracle is a host-side referee by definition
        live = gid[s:e] >= 0
        hit = (d2 <= r2[:, None]) & live[None, :]
        counts += hit.sum(axis=1)
        if with_ids:
            for q in range(Q):
                idx = np.nonzero(hit[q])[0]
                rows_d[q].append(d2[q, idx])
                rows_i[q].append(gid[s:e][idx])
    if not with_ids:
        return VerbResult(counts, None, None, False, 0)
    d2p = _pad_rows([np.concatenate(r) for r in rows_d], np.inf,
                    np.float32)
    idp = _pad_rows([np.concatenate(r) for r in rows_i], -1, np.int32)
    d2c, idc = canonical_radius_rows(d2p, idp)
    return VerbResult(counts, d2c, idc, False, 0)


def range_oracle(points, box_lo, box_hi, *, gid=None,
                 with_ids: bool = True) -> VerbResult:
    """Exhaustive box-containment answer (inclusive faces). Pure f32
    comparisons — no arithmetic, so exactness is trivial."""
    points = np.asarray(points, dtype=np.float32)  # kdt-lint: disable=KDT201 oracle reference code: host brute force by definition
    box_lo = np.asarray(box_lo, dtype=np.float32)  # kdt-lint: disable=KDT201 oracle reference code: host brute force by definition
    box_hi = np.asarray(box_hi, dtype=np.float32)  # kdt-lint: disable=KDT201 oracle reference code: host brute force by definition
    Q = box_lo.shape[0]
    gid = _gid_mask(points, gid)
    live = gid >= 0

    counts = np.zeros(Q, np.int64)
    rows = [] if with_ids else None
    for q in range(Q):
        inside = live.copy()
        for d in range(points.shape[1]):
            inside &= (points[:, d] >= box_lo[q, d]) & \
                (points[:, d] <= box_hi[q, d])
        idx = np.nonzero(inside)[0]
        counts[q] = idx.size
        if with_ids:
            rows.append(gid[idx])
    if not with_ids:
        return VerbResult(counts, None, None, False, 0)
    idp = _pad_rows(rows, -1, np.int32)
    return VerbResult(counts, None, canonical_range_rows(idp), False, 0)


def radius_count_oracle(points, queries, r, *, gid=None) -> np.ndarray:
    return radius_oracle(points, queries, r, gid=gid,
                         with_ids=False).counts


def range_count_oracle(points, box_lo, box_hi, *, gid=None) -> np.ndarray:
    return range_oracle(points, box_lo, box_hi, gid=gid,
                        with_ids=False).counts
