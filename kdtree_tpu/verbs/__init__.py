"""Query verbs beyond k-NN: exact radius, box-range, and count.

Same exactness contract as the k-NN stack: tree-pruned device kernels
(``device``) pinned byte-identical to brute-force oracles (``oracle``),
overflow detected and retried rather than silently truncated, and
bounded-visit answers flagged as sound lower bounds. ``wire`` holds the
HTTP request/response contract shared by shard server and router.
"""

from kdtree_tpu.verbs.device import (
    VerbResult,
    canonical_radius_rows,
    canonical_range_rows,
    radius_search,
    range_search,
)
from kdtree_tpu.verbs.oracle import (
    radius_count_oracle,
    radius_oracle,
    range_count_oracle,
    range_oracle,
)
from kdtree_tpu.verbs.wire import (
    VERBS,
    VerbParseError,
    parse_count_body,
    parse_radius_body,
    parse_range_body,
)

__all__ = [
    "VerbResult",
    "canonical_radius_rows",
    "canonical_range_rows",
    "radius_search",
    "range_search",
    "radius_oracle",
    "range_oracle",
    "radius_count_oracle",
    "range_count_oracle",
    "VERBS",
    "VerbParseError",
    "parse_radius_body",
    "parse_range_body",
    "parse_count_body",
]
