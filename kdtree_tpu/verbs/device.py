"""Tree-pruned device kernels for the query verbs (radius / range / count).

The k-NN tile engine (ops/tile_query.py) already computes the one
geometric fact every spatial verb needs: the exact box-to-box lower
bound of |q - p|^2 between a tile of queries and a tree node
(``_gathered_box_lb``), ranked lb-ascending by the level-synchronous
frontier (``_frontier``). The verbs reuse that frontier unchanged —
only the *bound* and the *fold* differ per verb:

- **radius** (all points with d(q, p) <= r): collect every bucket whose
  lower bound vs the tile's covering box is <= the tile's largest r^2.
  ``lb(node, tile box) <= lb(node, q) <= d2(q, p)`` for every q in the
  tile and p in the node, so a pruned bucket cannot contain a hit for
  any query it covers.
- **range** (axis-aligned box containment): the same frontier with the
  union of the tile's query boxes as the "tile box" and bound 0 — a
  node survives iff its box is NOT disjoint from the union box
  (disjointness <=> lb > 0), a superset of the nodes any single query
  box intersects.
- **count**: either traversal with the id fold stripped — per-query
  cardinalities only, no id buffers on the device or the wire.

Exactness contract: identical to k-NN. Candidate overflow (more buckets
pass the bound than the frontier cap holds) and hit overflow (more hits
than the per-query result buffer holds) are both *detected* on device
and *retried* by the host driver with doubled capacity — overflow is
the only incompleteness signal, never silent truncation.

Bounded-visit truncation (PR 14's ``visit_cap``) slices the
lb-ascending candidate list exactly like the k-NN path does. A
visited-prefix answer is a SUBSET of the true hit set for every query
in the tile, so a truncated count / radius set is a sound LOWER BOUND —
the verbs' analog of the k-NN recall contract (flagged through the same
``gear``/``recall_target`` plumbing by the serving layer).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kdtree_tpu.ops.morton import MortonTree, default_bits
from kdtree_tpu.ops.tile_query import _frontier, _sort_queries
from kdtree_tpu.tuning.store import _pow2_ceil

DEFAULT_TILE = 64  # queries per tile: verbs carry per-query bounds, so
# smaller tiles keep the tile-box over-approximation (max r^2 / union
# box) tight; pow2 like the k-NN tiles
DEFAULT_CAP = 64  # candidate buckets per tile (doubles on frontier overflow)
DEFAULT_HITS = 128  # per-query hit-buffer lanes (doubles on hit overflow)
_SCAN_V = 4  # buckets folded per scan chunk (v * bucket_size distance lanes)
_MAX_Q = 1 << 15  # queries per device program; larger sets stream in slices

# the int32 "no hit" sentinel for the range fold: real gids are < 2^31
# (guarded at build), so the sentinel always sorts last
_ID_INF = np.int32(2**31 - 1)


class VerbResult(NamedTuple):
    """One verb answer over a query batch, host-materialized.

    ``counts`` is exact (or a sound lower bound when ``truncated``).
    ``d2``/``ids`` are None for count-only calls; otherwise rows are
    canonically (d2, id)-ascending for radius and id-ascending for
    range, padded to the common width with (+inf, -1).
    """

    counts: np.ndarray  # i64[Q]
    d2: Optional[np.ndarray]  # f32[Q, m] | None
    ids: Optional[np.ndarray]  # i32[Q, m] | None
    truncated: bool  # visit_cap actually cut a tile's candidate list
    retries: int  # overflow-retry doublings the driver paid


def canonical_radius_rows(d2: np.ndarray, ids: np.ndarray):
    """Row-wise canonical (d2, id)-ascending order, (+inf, -1) padding
    last — the byte-identity normal form shared by the device driver,
    the brute-force oracle, and the router's dedup-union merge. Two
    stable argsorts compose into a lexsort (secondary key first)."""
    d2 = np.where(ids < 0, np.inf, d2)
    by_id = np.argsort(np.where(ids < 0, _ID_INF, ids), axis=1,
                       kind="stable")
    d2 = np.take_along_axis(d2, by_id, axis=1)
    ids = np.take_along_axis(ids, by_id, axis=1)
    by_d = np.argsort(d2, axis=1, kind="stable")
    return (np.take_along_axis(d2, by_d, axis=1),
            np.take_along_axis(ids, by_d, axis=1))


def canonical_range_rows(ids: np.ndarray) -> np.ndarray:
    """Row-wise id-ascending order with -1 padding last — the range
    verb's normal form (containment has no distances)."""
    ids = np.sort(np.where(ids < 0, _ID_INF, ids), axis=1, kind="stable")
    return np.where(ids == _ID_INF, -1, ids)


def merge_results(kind: str, a: VerbResult, b: VerbResult) -> VerbResult:
    """Row-wise union of two :class:`VerbResult`\\ s over the same query
    batch whose underlying point sets are DISJOINT (the mutable overlay:
    masked main storage vs the delta buffer) — counts add, id rows
    concatenate and re-canonicalize. ``kind`` is "radius" or "range"."""
    counts = a.counts + b.counts
    truncated = a.truncated or b.truncated
    retries = a.retries + b.retries
    if a.ids is None:
        return VerbResult(counts, None, None, truncated, retries)
    ids = np.concatenate([a.ids, b.ids], axis=1)
    if kind == "radius":
        d2 = np.concatenate([a.d2, b.d2], axis=1)
        d2, ids = canonical_radius_rows(d2, ids)
        return VerbResult(counts, d2, ids, truncated, retries)
    return VerbResult(counts, None, canonical_range_rows(ids),
                      truncated, retries)


def trim_result(res: VerbResult) -> VerbResult:
    """Drop all-padding trailing columns (rows stay canonical — padding
    sorts last) so overlay-widened buffers leave at hit width."""
    if res.ids is None:
        return res
    m = max(int(res.counts.max(initial=0)), 1)
    if m >= res.ids.shape[1]:
        return res
    return VerbResult(res.counts,
                      None if res.d2 is None else res.d2[:, :m],
                      res.ids[:, :m], res.truncated, res.retries)


def _chunked(cand, cand_lb, v: int):
    """Pad the candidate list to a multiple of ``v`` and expose it as
    scan chunks [C//v, T, v] (+lb of each chunk's first, unused here but
    kept shape-compatible with the k-NN scan)."""
    T, C = cand.shape
    cpad = (-C) % v
    if cpad:
        cand = jnp.concatenate(
            [cand, jnp.full((T, cpad), -1, jnp.int32)], axis=1)
        C += cpad
    return jnp.swapaxes(cand.reshape(T, C // v, v), 0, 1)


def _gather_chunk(tree, cb):
    """One chunk's flattened bucket points + masked gids:
    cb i32[T, v] -> (pts f32[T, v*B, D], gids i32[T, v*B])."""
    B = tree.bucket_size
    sel = jnp.maximum(cb, 0)
    pts = tree.bucket_pts[sel]  # [T, v, B, D]
    gids = jnp.where((cb >= 0)[:, :, None], tree.bucket_gid[sel], -1)
    T, v = cb.shape
    return pts.reshape(T, v * B, -1), gids.reshape(T, v * B)


def _truncate(cand, cand_lb, visit_cap):
    """Slice the lb-ascending candidate list to ``visit_cap`` (the exact
    analog of the k-NN bounded-visit slice) and report, per tile,
    whether anything finite was actually cut."""
    if visit_cap is None or visit_cap >= cand.shape[1]:
        return cand, cand_lb, jnp.zeros(cand.shape[0], bool)
    cut = jnp.sum(jnp.isfinite(cand_lb), axis=1) > visit_cap
    return cand[:, :visit_cap], cand_lb[:, :visit_cap], cut


@functools.partial(
    jax.jit,
    static_argnames=("cap", "m", "visit_cap", "count_only", "v"),
)
def _radius_tiles(tree, tq, r2, cap: int, m: int,
                  visit_cap: int | None, count_only: bool, v: int):
    """Radius over tiles: tq f32[T, TQ, D], r2 f32[T, TQ] (negative =
    padding row, never hits). Returns (counts i32[T, TQ], best_d
    f32[T, TQ, m], best_i i32[T, TQ, m], frontier overflow any,
    hit overflow any, truncated any)."""
    T, TQ, D = tq.shape
    box_lo = jnp.min(tq, axis=1)
    box_hi = jnp.max(tq, axis=1)
    bound = jnp.max(r2, axis=1)  # covers every query the tile holds
    cand, cand_lb, overflow = _frontier(tree, box_lo, box_hi, bound, cap)
    cand, cand_lb, cut = _truncate(cand, cand_lb, visit_cap)
    chunks = _chunked(cand, cand_lb, v)

    def step(carry, cb):
        counts, best_d, best_i = carry
        pts, gids = _gather_chunk(tree, cb)
        diff = tq[:, :, None, :] - pts[:, None, :, :]
        d2 = jnp.sum(diff * diff, axis=-1)  # [T, TQ, v*B]
        hit = (gids[:, None, :] >= 0) & (d2 <= r2[:, :, None])
        counts = counts + jnp.sum(hit, axis=-1, dtype=jnp.int32)
        if not count_only:
            key = jnp.where(hit, d2, jnp.inf)
            all_d = jnp.concatenate([best_d, key], axis=-1)
            all_i = jnp.concatenate(
                [best_i, jnp.broadcast_to(gids[:, None, :], key.shape)],
                axis=-1)
            neg, sel = lax.top_k(-all_d, m)
            best_d = -neg
            best_i = jnp.take_along_axis(all_i, sel, axis=-1)
        return (counts, best_d, best_i), None

    width = 0 if count_only else m
    init = (
        jnp.zeros((T, TQ), jnp.int32),
        jnp.full((T, TQ, width), jnp.inf, jnp.float32),
        jnp.full((T, TQ, width), -1, jnp.int32),
    )
    (counts, best_d, best_i), _ = lax.scan(step, init, chunks)
    best_i = jnp.where(jnp.isfinite(best_d), best_i, -1)
    return (counts, best_d, best_i, jnp.any(overflow), jnp.any(cut))


@functools.partial(
    jax.jit,
    static_argnames=("cap", "m", "visit_cap", "count_only", "v"),
)
def _range_tiles(tree, qlo, qhi, cap: int, m: int,
                 visit_cap: int | None, count_only: bool, v: int):
    """Box containment over tiles: qlo/qhi f32[T, TQ, D] per-query
    boxes (padding rows carry the empty box lo=+inf/hi=-inf). The tile
    box is the UNION of its query boxes; bound 0 keeps exactly the
    nodes not disjoint from it."""
    T, TQ, D = qlo.shape
    box_lo = jnp.min(qlo, axis=1)
    box_hi = jnp.max(qhi, axis=1)
    bound = jnp.zeros(T, jnp.float32)
    cand, cand_lb, overflow = _frontier(tree, box_lo, box_hi, bound, cap)
    cand, cand_lb, cut = _truncate(cand, cand_lb, visit_cap)
    chunks = _chunked(cand, cand_lb, v)

    def step(carry, cb):
        counts, best_i = carry
        pts, gids = _gather_chunk(tree, cb)
        hit = gids[:, None, :] >= 0  # [T, TQ, v*B] after broadcast
        hit = jnp.broadcast_to(hit, (T, TQ, pts.shape[1]))
        # per-axis containment, gathered one axis at a time like
        # _gathered_box_lb (no [T, TQ, W, D] intermediate)
        for d in range(D):
            pd = pts[:, None, :, d]
            hit = hit & (pd >= qlo[:, :, d:d + 1]) & \
                (pd <= qhi[:, :, d:d + 1])
        counts = counts + jnp.sum(hit, axis=-1, dtype=jnp.int32)
        if not count_only:
            key = jnp.where(hit, jnp.broadcast_to(gids[:, None, :],
                                                  hit.shape), _ID_INF)
            all_i = jnp.concatenate([best_i, key], axis=-1)
            neg, _ = lax.top_k(-all_i, m)
            best_i = -neg  # the m SMALLEST ids, ascending
        return (counts, best_i), None

    width = 0 if count_only else m
    init = (
        jnp.zeros((T, TQ), jnp.int32),
        jnp.full((T, TQ, width), _ID_INF, jnp.int32),
    )
    (counts, best_i), _ = lax.scan(step, init, chunks)
    best_i = jnp.where(best_i == _ID_INF, -1, best_i)
    return counts, best_i, jnp.any(overflow), jnp.any(cut)


def _tile_for(q: int, tile: int | None) -> int:
    t = DEFAULT_TILE if tile is None else int(tile)
    return max(1, min(_pow2_ceil(t), _pow2_ceil(max(q, 1))))


def _cap_ceiling(tree) -> int:
    return _pow2_ceil(tree.num_buckets)


def _slices(q: int):
    for s in range(0, q, _MAX_Q):
        yield s, min(s + _MAX_Q, q)


def radius_search(
    tree: MortonTree,
    queries,
    r,
    *,
    visit_cap: int | None = None,
    with_ids: bool = True,
    tile: int | None = None,
    cap: int | None = None,
    max_hits: int | None = None,
) -> VerbResult:
    """All points within Euclidean distance ``r`` of each query
    (inclusive: d2 <= r^2 in f32, the same arithmetic the oracle uses).

    ``r`` is a scalar or per-query [Q] array. ``with_ids=False`` is the
    count verb: per-query cardinalities only, no id buffers anywhere.
    ``visit_cap`` truncates the lb-ascending candidate list per tile —
    the answer is then a flagged lower bound (``truncated``).
    """
    queries = np.asarray(queries, dtype=np.float32)  # kdt-lint: disable=KDT201 verb API boundary: normalizes caller-provided host rows (HTTP JSON, oracles)
    Q, D = queries.shape
    r = np.broadcast_to(np.asarray(r, dtype=np.float32), (Q,))  # kdt-lint: disable=KDT201 verb API boundary: r is a host scalar or per-query host list
    r2 = (r * r).astype(np.float32)

    parts = [
        _radius_slice(tree, queries[s:e], r2[s:e], visit_cap, with_ids,
                      tile, cap, max_hits)
        for s, e in _slices(Q)
    ]
    return _concat_results(parts, with_dists=with_ids)


def _radius_slice(tree, queries, r2, visit_cap, with_ids, tile, cap,
                  max_hits) -> VerbResult:
    Q, D = queries.shape
    t = _tile_for(Q, tile)
    qpad = (-Q) % t
    sq, order = _sort_queries(jnp.asarray(queries), default_bits(D), qpad)
    # padding duplicates the last query; a NEGATIVE r2 makes those rows
    # hit nothing (d2 <= r2 < 0 is impossible)
    r2p = np.concatenate([r2, np.full(qpad, -1.0, np.float32)])
    order_h = np.asarray(order)  # kdt-lint: disable=KDT201 one [Q]-sized permutation fetch per verb call, amortized over the whole batch
    r2s = jnp.asarray(r2p[order_h]).reshape(-1, t)
    tq = sq.reshape(-1, t, D)

    c = min(DEFAULT_CAP if cap is None else _pow2_ceil(int(cap)),
            _cap_ceiling(tree))
    m = _pow2_ceil(DEFAULT_HITS if max_hits is None else int(max_hits))
    retries = 0
    while True:
        counts, bd, bi, ovf, cut = _radius_tiles(
            tree, tq, r2s, c, m if with_ids else 0, visit_cap,
            not with_ids, _SCAN_V)
        counts_h = np.asarray(counts).reshape(-1)  # kdt-lint: disable=KDT201 driver boundary: per-query counts decide the overflow retry and ARE the count verb's answer
        if visit_cap is None and bool(ovf) and c < _cap_ceiling(tree):  # kdt-lint: disable=KDT201 driver-level overflow flag fetch, the retry contract's only signal
            c = min(c * 2, _cap_ceiling(tree))
            retries += 1
            continue
        if with_ids and int(counts_h.max(initial=0)) > m:  # kdt-lint: disable=KDT201 retry sizing over the already-fetched host counts
            # counts are exact regardless of m, so ONE retry sized to
            # the measured maximum always suffices
            m = _pow2_ceil(int(counts_h.max()))  # kdt-lint: disable=KDT201 retry sizing over the already-fetched host counts
            retries += 1
            continue
        break
    truncated = bool(cut)  # kdt-lint: disable=KDT201 one scalar truncation flag per verb call, rides the response contract
    counts_out = np.zeros(Q + qpad, np.int64)
    counts_out[order_h] = counts_h
    if not with_ids:
        return VerbResult(counts_out[:Q], None, None, truncated, retries)
    d2s = np.asarray(bd).reshape(len(order_h), -1)  # kdt-lint: disable=KDT201 response boundary: radius hits are host-materialized to answer the caller
    idss = np.asarray(bi).reshape(len(order_h), -1)  # kdt-lint: disable=KDT201 response boundary: radius hits are host-materialized to answer the caller
    d2_out = np.empty_like(d2s)
    ids_out = np.empty_like(idss)
    d2_out[order_h] = d2s
    ids_out[order_h] = idss
    d2c, idc = canonical_radius_rows(d2_out[:Q], ids_out[:Q])
    return VerbResult(counts_out[:Q], d2c, idc, truncated, retries)


def range_search(
    tree: MortonTree,
    box_lo,
    box_hi,
    *,
    visit_cap: int | None = None,
    with_ids: bool = True,
    tile: int | None = None,
    cap: int | None = None,
    max_hits: int | None = None,
) -> VerbResult:
    """All points inside each axis-aligned box [box_lo, box_hi]
    (inclusive on both faces). Boxes where lo > hi on any axis are
    legitimately empty. Returns ids ascending per query (containment
    has no distances); ``with_ids=False`` is the count form."""
    box_lo = np.asarray(box_lo, dtype=np.float32)  # kdt-lint: disable=KDT201 verb API boundary: normalizes caller-provided host rows (HTTP JSON, oracles)
    box_hi = np.asarray(box_hi, dtype=np.float32)  # kdt-lint: disable=KDT201 verb API boundary: normalizes caller-provided host rows (HTTP JSON, oracles)
    Q, D = box_lo.shape
    parts = [
        _range_slice(tree, box_lo[s:e], box_hi[s:e], visit_cap, with_ids,
                     tile, cap, max_hits)
        for s, e in _slices(Q)
    ]
    return _concat_results(parts, with_dists=False)


def _range_slice(tree, box_lo, box_hi, visit_cap, with_ids, tile, cap,
                 max_hits) -> VerbResult:
    Q, D = box_lo.shape
    t = _tile_for(Q, tile)
    qpad = (-Q) % t
    if qpad:
        # pad with the EMPTY box: +inf lo / -inf hi contains nothing and
        # cannot widen the tile's union box
        box_lo = np.concatenate(
            [box_lo, np.full((qpad, D), np.inf, np.float32)])
        box_hi = np.concatenate(
            [box_hi, np.full((qpad, D), -np.inf, np.float32)])
    qlo = jnp.asarray(box_lo).reshape(-1, t, D)
    qhi = jnp.asarray(box_hi).reshape(-1, t, D)

    c = min(DEFAULT_CAP if cap is None else _pow2_ceil(int(cap)),
            _cap_ceiling(tree))
    m = _pow2_ceil(DEFAULT_HITS if max_hits is None else int(max_hits))
    retries = 0
    while True:
        out = _range_tiles(tree, qlo, qhi, c, m if with_ids else 0,
                           visit_cap, not with_ids, _SCAN_V)
        counts, bi, ovf, cut = out
        counts_h = np.asarray(counts).reshape(-1)  # kdt-lint: disable=KDT201 driver boundary: per-query counts decide the overflow retry and ARE the count verb's answer
        if visit_cap is None and bool(ovf) and c < _cap_ceiling(tree):  # kdt-lint: disable=KDT201 driver-level overflow flag fetch, the retry contract's only signal
            c = min(c * 2, _cap_ceiling(tree))
            retries += 1
            continue
        if with_ids and int(counts_h.max(initial=0)) > m:  # kdt-lint: disable=KDT201 retry sizing over the already-fetched host counts
            m = _pow2_ceil(int(counts_h.max()))  # kdt-lint: disable=KDT201 retry sizing over the already-fetched host counts
            retries += 1
            continue
        break
    truncated = bool(cut)  # kdt-lint: disable=KDT201 one scalar truncation flag per verb call, rides the response contract
    counts_out = counts_h[:Q].astype(np.int64)
    if not with_ids:
        return VerbResult(counts_out, None, None, truncated, retries)
    ids = np.asarray(bi).reshape(len(counts_h), -1)[:Q]  # kdt-lint: disable=KDT201 response boundary: range hits are host-materialized to answer the caller
    return VerbResult(counts_out, None, canonical_range_rows(ids),
                      truncated, retries)


def _concat_results(parts, with_dists: bool) -> VerbResult:
    if len(parts) == 1:
        return parts[0]
    counts = np.concatenate([p.counts for p in parts])
    truncated = any(p.truncated for p in parts)
    retries = sum(p.retries for p in parts)
    if parts[0].ids is None:
        return VerbResult(counts, None, None, truncated, retries)
    m = max(p.ids.shape[1] for p in parts)

    def widen(a, fill, dtype):
        return np.concatenate([
            np.concatenate([x, np.full((x.shape[0], m - x.shape[1]),
                                       fill, dtype)], axis=1)
            for x in a
        ])

    ids = widen([p.ids for p in parts], -1, np.int32)
    d2 = (widen([p.d2 for p in parts], np.inf, np.float32)
          if with_dists else None)
    return VerbResult(counts, d2, ids, truncated, retries)
