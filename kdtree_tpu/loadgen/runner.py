"""The open-loop driver: replay a schedule, emit a capacity block.

The runner takes a precomputed :class:`~kdtree_tpu.loadgen.schedule.
Schedule` and a live target (a ``kdtree-tpu serve`` shard or a
``route`` front) and does exactly three things:

1. **Dispatch on schedule.** A scheduler walks the arrivals and hands
   each one to a worker pool *at its intended time* — it never waits
   for a response. The pool is sized by ``max_inflight``; if every
   worker is busy the arrival queues client-side, and because latency
   is measured from the **intended** send time, that wait is charged to
   the measurement, not hidden from it (the report carries the send-lag
   p99 so a client-saturated run is self-describing).
2. **Classify.** Each response lands in its step's accumulator:
   ok / shed (429) / degraded / partial / error (5xx, protocol) /
   timeout, plus the intended-latency sample. Goodput is 200-answers
   per second of step time.
3. **Summarize.** Per step: client-side p50/p95/p99 intended latency,
   goodput, shed/degraded/partial/error fractions. Across steps: the
   **knee** — the highest offered rate whose step met the latency SLO
   at the configured quantile with an acceptable bad fraction. A final
   ``/metrics`` scrape folds the server's own write-path evidence
   (``kdtree_write_latency_ms``, the epoch-rebuild p99 delta, the
   epoch counter) into the block, so one artifact carries both sides
   of the run.

Every request carries ``X-Loadgen-Rate`` (the step's offered rate) —
the serving process mirrors it into a gauge and a flight event, so an
SLO PAGE that fires mid-run names the offered rate in its incident
dump. Step transitions and the knee verdict land in this process's own
flight ring too.

Stdlib + numpy only — no jax; the client must not perturb the machine
it measures.
"""

from __future__ import annotations

import http.client
import json
import queue
import threading
import time
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlparse

import numpy as np

from kdtree_tpu.obs import flight

CAPACITY_VERSION = 1
DEFAULT_SLO_MS = 250.0  # matches the request-p99-latency serving SLO
DEFAULT_SLO_QUANTILE = 0.99
DEFAULT_MAX_BAD_FRAC = 0.05
DEFAULT_MAX_INFLIGHT = 64
DEFAULT_TIMEOUT_S = 10.0
# relative band the capacity-headroom model's predicted rate must land
# within of the loadgen-measured knee (same posture as trend's
# DEFAULT_BAND): a model off by more than this is not a model
DEFAULT_KNEE_BAND = 0.5

__all__ = ["discover", "run_load", "compute_knee", "scrape_server_block",
           "scrape_pool_counters", "scrape_cost_classes",
           "CAPACITY_VERSION", "DEFAULT_KNEE_BAND"]


def _host_port(target: str) -> Tuple[str, int]:
    parsed = urlparse(target if "//" in target else f"http://{target}")
    if not parsed.hostname or not parsed.port:
        raise ValueError(
            f"target {target!r} must be http://host:port"
        )
    return parsed.hostname, parsed.port


def _request(
    target: str, method: str, path: str, body: Optional[dict],
    timeout_s: float, headers: Optional[Dict[str, str]] = None,
) -> Tuple[int, Optional[dict]]:
    """One one-shot HTTP exchange; (status, parsed JSON | None). Raises
    OSError/http.client.HTTPException on transport failure — the caller
    decides whether that is an outcome or a fatal. Used by the control
    plane (discovery); the measured load path uses per-worker
    keep-alive connections (:class:`_WorkerConn`)."""
    host, port = _host_port(target)
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        payload = None if body is None else json.dumps(body).encode()
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(headers or {})
        conn.request(method, path, body=payload, headers=hdrs)
        resp = conn.getresponse()
        raw = resp.read()
        try:
            parsed = json.loads(raw) if raw else None
        except ValueError:
            parsed = None
        return resp.status, parsed
    finally:
        conn.close()


# reuse a worker's keep-alive connection only while comfortably inside
# the serve handlers' 5 s idle socket timeout: a connection the server
# already closed would turn the first request after an idle spell into
# a spurious connection-reset "error" in the measurement
_CONN_IDLE_REUSE_S = 2.0


class _WorkerConn:
    """One worker thread's persistent HTTP connection to the target.

    The measured path must not pay a TCP handshake per request (at
    sustained ladder rates that both depresses the measured quantiles —
    the knee would partly measure the generator — and churns one
    ephemeral port per request). Stale or failed connections are closed
    and reopened; a request that failed on the wire is NOT retried —
    the failure is the measurement."""

    __slots__ = ("host", "port", "timeout_s", "conn", "last")

    def __init__(self, target: str, timeout_s: float) -> None:
        self.host, self.port = _host_port(target)
        self.timeout_s = timeout_s
        self.conn = None
        self.last = 0.0

    def request(self, path: str, body: dict,
                headers: Dict[str, str]) -> Tuple[int, Optional[dict]]:
        now = time.monotonic()
        if self.conn is None or now - self.last > _CONN_IDLE_REUSE_S:
            self.close()
            self.conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout_s)
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(headers)
        try:
            self.conn.request("POST", path, body=json.dumps(body).encode(),
                              headers=hdrs)
            resp = self.conn.getresponse()
            raw = resp.read()
        except BaseException:
            self.close()  # never leave a half-read connection for reuse
            raise
        self.last = time.monotonic()
        if resp.will_close:
            self.close()
        try:
            parsed = json.loads(raw) if raw else None
        except ValueError:
            parsed = None
        return resp.status, parsed

    def close(self) -> None:
        if self.conn is not None:
            try:
                self.conn.close()
            except Exception:
                pass
            self.conn = None


def _write_base_of(detail: Dict) -> int:
    """The first id fresh upserts can mint against one shard without
    colliding with its served rows. A spatially-partitioned shard
    serves GLOBAL morton-rank ids at ``id_offset`` 0 — its occupied
    span is the ``spatial.id_range``, not ``[0, n)`` (offset + n would
    collide with a sibling shard's ids)."""
    spatial = detail.get("spatial")
    if isinstance(spatial, dict):
        id_range = spatial.get("id_range")
        try:
            return int(id_range[1])
        except (TypeError, ValueError, IndexError):
            pass
    return int(detail.get("id_offset", 0)) + int(detail.get("n", 0))


def _leaf_details(entry: Dict) -> List[Dict]:
    """The data-bearing leaf healthz details under one router shard
    entry. A plain shard's own detail carries ``dim`` directly; a
    replica set's primary may be ejected, so the first serving
    replica's detail stands in; and under two-level routing the entry
    is a CHILD ROUTER whose detail is its own aggregated breakdown —
    recurse, so a parent target sums n over the whole tree."""
    detail = entry.get("detail") or {}
    if "dim" in detail:
        return [detail]
    for rep in entry.get("replicas") or []:
        rdetail = rep.get("detail") or {}
        if "dim" in rdetail:
            return [rdetail]
    leaves: List[Dict] = []
    for sub in detail.get("shards") or []:
        leaves.extend(_leaf_details(sub))
    return leaves


def discover(
    target: str, timeout_s: float = 5.0, retries: int = 60,
    retry_sleep_s: float = 0.5,
) -> Dict:
    """Read the target's ``/healthz`` until it answers ready and derive
    the schedule facts: ``dim``, total ``n``, ``k_max``, and
    ``write_base`` (the first id fresh upserts can mint without
    colliding with served rows). Handles both shapes: a shard's flat
    body and the router's aggregated ``shards`` breakdown (per-shard
    detail = that shard's own healthz body)."""
    last = None
    for _ in range(max(int(retries), 1)):
        try:
            status, body = _request(target, "GET", "/healthz", None,
                                    timeout_s)
        except (OSError, http.client.HTTPException) as e:
            last = repr(e)
            time.sleep(retry_sleep_s)
            continue
        if status == 200 and isinstance(body, dict):
            if "dim" in body:
                return {
                    "dim": int(body["dim"]),
                    "n": int(body.get("n", 0)),
                    "k_max": int(body.get("k_max", 1)),
                    "write_base": _write_base_of(body),
                }
            if "shards" in body:
                dims, kmaxs, bases, total = [], [], [0], 0
                for s in body["shards"]:
                    for detail in _leaf_details(s):
                        dims.append(int(detail["dim"]))
                        kmaxs.append(int(detail.get("k_max", 1)))
                        total += int(detail.get("n", 0))
                        bases.append(_write_base_of(detail))
                if dims:
                    return {
                        "dim": dims[0],
                        "n": total,
                        "k_max": min(kmaxs),
                        "write_base": max(bases),
                    }
        last = f"healthz answered {status}"
        time.sleep(retry_sleep_s)
    raise RuntimeError(
        f"target {target} never reported ready: {last}"
    )


# --------------------------------------------------------------------------
# per-step accounting
# --------------------------------------------------------------------------


class _StepAcc:
    """One rate step's outcome ledger (appended under the runner lock —
    the lock guards list/int updates only, never I/O)."""

    __slots__ = ("rate", "intended", "sent", "latencies_ms",
                 "send_lag_ms", "counts", "gears", "fanout", "slowest",
                 "verbs")

    def __init__(self, rate: float) -> None:
        self.rate = float(rate)
        self.intended = 0
        self.sent = 0
        self.latencies_ms: List[float] = []
        self.send_lag_ms: List[float] = []
        self.counts = {
            "ok": 0, "shed": 0, "degraded": 0, "partial": 0,
            "errors": 0, "timeouts": 0, "writes_ok": 0,
        }
        # answered-query gear distribution (docs/SERVING.md
        # "Degradation ladder"): "exact", "approx:<t>", or
        # "brute-deadline" — the response's gear token, so a capacity
        # step says WHICH gear its goodput was measured at
        self.gears: Dict[str, int] = {}
        # per-answered-query fan-out samples (contacted / total from a
        # router response's shards block; empty against a plain shard
        # target) — the selective fan-out evidence (docs/SERVING.md
        # "Spatial sharding & selective fan-out")
        self.fanout: List[float] = []
        # (latency_ms, request id) of the step's slowest exchange: the
        # id doubles as the TRACE id server-side, so the capacity block
        # names the exact trace to pull a waterfall for (kdtree-tpu
        # trace --id <it> --target <router>)
        self.slowest: Optional[Tuple[float, str]] = None
        # per-read-verb ledger (docs/SERVING.md "Query verbs"),
        # populated only when the schedule carries a verb mix: verb →
        # {"lat": [...], "ok": n, "sent": n, "bad": n} — the per-verb
        # latency/goodput columns and the per-verb knees come from here
        self.verbs: Dict[str, Dict] = {}


def _classify(op: str, status: int, body: Optional[dict]) -> List[str]:
    """Outcome tags for one completed exchange (a 200 can be both ok
    and degraded/partial — the fractions are independent signals)."""
    if status == 429:
        return ["shed"]
    if status != 200:
        return ["errors"]
    tags = ["ok"]
    if op != "query":
        tags.append("writes_ok")
        return tags
    degraded = (body or {}).get("degraded")
    if isinstance(degraded, str):
        tags.append("partial" if degraded.startswith("partial")
                    else "degraded")
    return tags


def _gear_of(op: str, status: int, body: Optional[dict]) -> Optional[str]:
    """The answering gear of one completed QUERY exchange — the
    response's gear token, "exact" when a 200 carries none. None for
    writes and failures (they have no gear)."""
    if op != "query" or status != 200:
        return None
    gear = (body or {}).get("gear")
    return gear if isinstance(gear, str) else "exact"


def _fanout_of(op: str, status: int,
               body: Optional[dict]) -> Optional[float]:
    """Contacted-shard fraction of one answered QUERY exchange — the
    router's ``shards`` block (contacted / total). None for plain
    shard targets (no block), writes, and failures. Pre-selective
    routers carry no ``contacted`` key; their ``answered`` stands in
    (contacted == answered under full scatter)."""
    if op != "query" or status != 200:
        return None
    shards = (body or {}).get("shards")
    if not isinstance(shards, dict):
        return None
    total = shards.get("total")
    contacted = shards.get("contacted", shards.get("answered"))
    if not isinstance(total, int) or not isinstance(contacted, int) \
            or total < 1:
        return None
    return contacted / total


def _quantiles_ms(vals: List[float]) -> Dict[str, Optional[float]]:
    if not vals:
        return {"p50_ms": None, "p95_ms": None, "p99_ms": None}
    arr = np.asarray(vals, dtype=np.float64)
    p50, p95, p99 = np.percentile(arr, [50.0, 95.0, 99.0])
    return {"p50_ms": round(float(p50), 3), "p95_ms": round(float(p95), 3),
            "p99_ms": round(float(p99), 3)}


def compute_knee(
    steps: List[dict],
    slo_ms: float = DEFAULT_SLO_MS,
    slo_quantile: float = DEFAULT_SLO_QUANTILE,
    max_bad_frac: float = DEFAULT_MAX_BAD_FRAC,
) -> float:
    """The capacity verdict: the highest offered rate whose step met
    the SLO — quantile latency within ``slo_ms`` AND
    (shed + errors + timeouts) / sent within ``max_bad_frac``. 0.0 when
    no step qualified (the service has no measured capacity at this
    ladder — itself a finding, not an absence of data).

    Only the quantiles the steps actually report are judgeable; an
    unsupported value must be an error, not a silent fall-back to p99
    that contradicts the ``slo_quantile`` the artifact publishes."""
    qkey = {0.5: "p50_ms", 0.95: "p95_ms", 0.99: "p99_ms"}.get(
        round(float(slo_quantile), 4)
    )
    if qkey is None:
        raise ValueError(
            f"slo_quantile must be one of 0.5 / 0.95 / 0.99 (the "
            f"reported step quantiles), got {slo_quantile}"
        )
    knee = 0.0
    for s in steps:
        if not s.get("sent"):
            continue
        lat = s.get(qkey)
        if lat is None or lat > slo_ms:
            continue
        if s.get("bad_frac", 1.0) > max_bad_frac:
            continue
        knee = max(knee, float(s["rate"]))
    return knee


# --------------------------------------------------------------------------
# server-side evidence scrape
# --------------------------------------------------------------------------


def _parse_prom_lines(text: str) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        key, _, val = line.rpartition(" ")
        try:
            out[key] = float(val)
        except ValueError:
            continue
    return out


def _sum_series(parsed: Dict[str, float], family: str,
                must_contain: str = "") -> Optional[float]:
    """Sum every series of ``family`` whose key contains
    ``must_contain`` (matches across extra labels — a federated router
    scrape adds ``shard=...``)."""
    vals = [
        v for k, v in parsed.items()
        if (k == family or k.startswith(family + "{"))
        and must_contain in k
    ]
    return sum(vals) if vals else None


def _max_series(parsed: Dict[str, float], family: str) -> Optional[float]:
    """Max over a family's series — for stateful gauges like the epoch,
    where a federated scrape holds one series per shard/replica and a
    SUM would publish a meaningless total (6 replicas at epoch 1 are
    not 'epoch 6')."""
    vals = [
        v for k, v in parsed.items()
        if k == family or k.startswith(family + "{")
    ]
    return max(vals) if vals else None


def scrape_pool_counters(
        target: str, timeout_s: float = 2.0
) -> Optional[Tuple[float, float]]:
    """One ``/metrics`` scrape distilled to the router's connection-pool
    counters: ``(hits, misses)`` summed across series. None ONLY when
    the scrape itself failed; a 200 exposition without either family
    reads as ``(0, 0)`` — the registry exports counters lazily, so a
    pre-traffic router legitimately shows neither family at snapshot 0
    and the first window's deltas must still anchor there. A target
    that NEVER exports the families (a plain shard, a ``--no-pool``
    router) nets a zero delta across every window, and ``_reuse_frac``
    maps that to None: absent evidence, never a fake zero."""
    try:
        host, port = _host_port(target)
        conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
        try:
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            status, text = resp.status, resp.read().decode(
                "utf-8", "replace")
        finally:
            conn.close()
        if status != 200:
            return None
        parsed = _parse_prom_lines(text)
        hits = _sum_series(parsed, "kdtree_router_pool_hits_total")
        misses = _sum_series(parsed, "kdtree_router_pool_misses_total")
        return (hits or 0.0, misses or 0.0)
    except (OSError, http.client.HTTPException, ValueError):
        return None


def _reuse_frac(
        start: Optional[Tuple[float, float]],
        end: Optional[Tuple[float, float]],
) -> Optional[float]:
    """Connection-reuse fraction over a [start, end) counter window:
    hits / (hits + misses) of the DELTAS. None when either snapshot is
    missing or nothing was leased in the window."""
    if start is None or end is None:
        return None
    hits = end[0] - start[0]
    misses = end[1] - start[1]
    attempts = hits + misses
    if attempts <= 0:
        return None
    return round(hits / attempts, 4)


def scrape_server_block(target: str,
                        timeout_s: float = 5.0) -> Optional[Dict]:
    """One ``/metrics`` scrape distilled to the write-path evidence the
    capacity block publishes: per-op ``kdtree_write_latency_ms``
    count/mean, the epoch-rebuild p99 delta, and the epoch. Falls back
    to the router's federated scrape when the plain exposition has no
    write families (the shards hold them). None when the scrape failed
    — the client-side curve stands on its own."""
    for path in ("/metrics", "/metrics?federate=1"):
        try:
            host, port = _host_port(target)
            conn = http.client.HTTPConnection(host, port,
                                              timeout=timeout_s)
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                status, text = resp.status, resp.read().decode(
                    "utf-8", "replace")
            finally:
                conn.close()
            if status != 200:
                return None
            parsed = _parse_prom_lines(text)
            writes = {}
            for op in ("upsert", "delete"):
                count = _sum_series(parsed, "kdtree_write_latency_ms_count",
                                    f'op="{op}"')
                total = _sum_series(parsed, "kdtree_write_latency_ms_sum",
                                    f'op="{op}"')
                if count:
                    writes[op] = {
                        "count": int(count),
                        "mean_ms": round((total or 0.0) / count, 3),
                    }
            if not writes and path == "/metrics":
                continue  # router front: the shards hold the families
            # max, not sum: per-shard/replica series of these are each
            # a whole statement about one process — the fleet summary
            # is the worst delta and the furthest epoch
            delta = _max_series(parsed,
                                "kdtree_mutable_rebuild_p99_delta_ms")
            epoch = _max_series(parsed, "kdtree_epoch")
            return {
                "write_latency_ms": writes,
                "rebuild_p99_delta_ms": (None if delta is None
                                         else round(delta, 3)),
                "epoch": None if epoch is None else int(epoch),
            }
        except (OSError, http.client.HTTPException, ValueError):
            return None
    return None


def scrape_cost_classes(
        target: str, timeout_s: float = 2.0,
) -> Optional[Dict[str, Dict[str, float]]]:
    """One ``/metrics`` scrape distilled to the cost ledger's per-class
    cumulative ``{requests, device_ms}`` counters, keyed
    ``"verb/gear/outcome"`` and summed across any federation labels.
    Falls back to the router's federated scrape when the plain
    exposition carries no cost families (the shards hold them). None
    when the scrape itself failed; a reachable pre-traffic target reads
    as ``{}`` so the first window's deltas can still anchor there."""
    for path in ("/metrics", "/metrics?federate=1"):
        try:
            host, port = _host_port(target)
            conn = http.client.HTTPConnection(host, port,
                                              timeout=timeout_s)
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                status, text = resp.status, resp.read().decode(
                    "utf-8", "replace")
            finally:
                conn.close()
        except (OSError, http.client.HTTPException, ValueError):
            return None
        if status != 200:
            return None
        classes = _parse_cost_classes(text)
        if classes or path != "/metrics":
            return classes
        # plain scrape carried no cost families — try the router's
        # federated exposition before concluding "no traffic yet"
    return classes


def _parse_cost_classes(text: str) -> Dict[str, Dict[str, float]]:
    """Distill one exposition's cost counters to per-class cumulative
    ``{requests, device_ms}``, keyed ``"verb/gear/outcome"`` and summed
    across any extra (federation) labels."""
    classes: Dict[str, Dict[str, float]] = {}
    fields = {"kdtree_cost_requests_total": "requests",
              "kdtree_cost_device_ms_total": "device_ms"}
    for key, val in _parse_prom_lines(text).items():
        field = fields.get(key.split("{", 1)[0])
        if field is None or "{" not in key:
            continue
        labels = {}
        for part in key.split("{", 1)[1].rstrip("}").split(","):
            if "=" in part:
                lk, lv = part.split("=", 1)
                labels[lk] = lv.strip('"')
        ck = "/".join((labels.get("verb", "?"),
                       labels.get("gear", "?"),
                       labels.get("outcome", "?")))
        ent = classes.setdefault(
            ck, {"requests": 0.0, "device_ms": 0.0})
        ent[field] += val
    return classes


def _cost_delta(
        start: Optional[Dict[str, Dict[str, float]]],
        end: Optional[Dict[str, Dict[str, float]]],
) -> Optional[Dict[str, Dict[str, float]]]:
    """Per-class ``{requests, device_ms, cost_ms}`` deltas over a
    [start, end) boundary window. None when either snapshot is missing
    or no request landed in the window — absent evidence, never a fake
    zero-cost class."""
    if start is None or end is None:
        return None
    out: Dict[str, Dict[str, float]] = {}
    for ck, ent in end.items():
        base = start.get(ck, {})
        req = ent.get("requests", 0.0) - base.get("requests", 0.0)
        dev = ent.get("device_ms", 0.0) - base.get("device_ms", 0.0)
        if req > 0:
            out[ck] = {"requests": int(round(req)),
                       "device_ms": round(dev, 3),
                       "cost_ms": round(dev / req, 4)}
    return out or None


# --------------------------------------------------------------------------
# the runner
# --------------------------------------------------------------------------


def run_load(
    target: str,
    schedule,
    k: int = 4,
    slo_ms: float = DEFAULT_SLO_MS,
    slo_quantile: float = DEFAULT_SLO_QUANTILE,
    max_bad_frac: float = DEFAULT_MAX_BAD_FRAC,
    max_inflight: int = DEFAULT_MAX_INFLIGHT,
    timeout_s: float = DEFAULT_TIMEOUT_S,
    scrape: bool = True,
    on_step=None,
    verb_radius: float = 0.1,
    knee_band: float = DEFAULT_KNEE_BAND,
) -> Dict:
    """Replay ``schedule`` against ``target``; return the full report
    (see the module docstring for the measurement contract). ``on_step``
    is an optional callback ``(step_index, rate)`` fired at each ladder
    transition — the CLI's progress line. ``verb_radius`` is the search
    radius (and range-box half-width) non-knn query verbs carry, in the
    unit-cube coordinates the schedule draws queries from — it pins
    verb selectivity so two runs at the same mix measure the same
    work."""
    # per-verb accounting only when the schedule mixes verbs: an
    # unmixed run's artifact stays byte-identical to pre-verb loadgen
    track_verbs = bool(getattr(schedule, "verb_mix", None))
    accs = [_StepAcc(r) for r in schedule.rates]
    for a in schedule.arrivals:
        accs[a.step].intended += 1
    lock = threading.Lock()
    work: "queue.Queue" = queue.Queue()

    # connection-reuse evidence: pool-counter snapshots at each step
    # boundary (docs/SERVING.md "Scaling the router"). The boundary
    # scrapes run on their own daemon threads so the open-loop
    # dispatcher never blocks on a GET; snapshot 0 and the final one
    # bracket the run synchronously (outside the measured window).
    # Attribution at a boundary is approximate by design — responses
    # from step N may still land after step N+1 opened — which is fine
    # for a fraction that moves by tens of points between the pooled
    # and --no-pool arms.
    pool_snaps: Dict[int, Tuple[float, float]] = {}
    # cost-ledger snapshots at the same boundaries: per-step per-class
    # cost columns and the run-wide predicted-knee check both difference
    # these (docs/OBSERVABILITY.md "Cost accounting & capacity headroom")
    cost_snaps: Dict[int, Dict[str, Dict[str, float]]] = {}
    snap_threads: List[threading.Thread] = []

    def snap_boundary(step: int) -> None:
        got = scrape_pool_counters(target)
        costs = scrape_cost_classes(target)
        with lock:
            if got is not None:
                pool_snaps[step] = got
            if costs is not None:
                cost_snaps[step] = costs

    if scrape:
        snap_boundary(0)
    t0 = time.monotonic()

    def record(arrival, intended: float, tags: List[str],
               done: float, actual_send: float,
               gear: Optional[str] = None,
               fanout: Optional[float] = None,
               req_id: str = "") -> None:
        acc = accs[arrival.step]
        lat_ms = (done - intended) * 1e3
        with lock:
            acc.sent += 1
            if req_id and (acc.slowest is None
                           or lat_ms > acc.slowest[0]):
                acc.slowest = (lat_ms, req_id)
            acc.latencies_ms.append(lat_ms)
            acc.send_lag_ms.append(
                max(actual_send - intended, 0.0) * 1e3)
            for tag in tags:
                acc.counts[tag] += 1
            if gear is not None:
                acc.gears[gear] = acc.gears.get(gear, 0) + 1
            if fanout is not None:
                acc.fanout.append(fanout)
            if track_verbs and arrival.op == "query":
                verb = getattr(arrival, "verb", "knn") or "knn"
                led = acc.verbs.setdefault(
                    verb, {"lat": [], "ok": 0, "sent": 0, "bad": 0})
                led["sent"] += 1
                led["lat"].append(lat_ms)
                if "ok" in tags:
                    led["ok"] += 1
                if any(tag in ("shed", "errors", "timeouts")
                       for tag in tags):
                    led["bad"] += 1

    def do_request(conn: _WorkerConn, arrival, intended: float,
                   seq: int) -> None:
        actual_send = time.monotonic()
        headers = {
            "X-Loadgen-Rate": f"{schedule.rates[arrival.step]:g}",
            # unique per arrival: an incident dump must correlate ONE
            # slow exchange to its server-side span, not a whole step
            "X-Request-Id": f"lg{schedule.seed}-{arrival.step}-{seq}",
        }
        if arrival.op == "query":
            verb = getattr(arrival, "verb", "knn") or "knn"
            point = arrival.point.tolist()
            if verb == "radius":
                path, body = "/v1/radius", {
                    "queries": [point], "r": float(verb_radius)}
            elif verb == "count":
                path, body = "/v1/count", {
                    "queries": [point], "r": float(verb_radius)}
            elif verb == "range":
                lo = (arrival.point - verb_radius).tolist()
                hi = (arrival.point + verb_radius).tolist()
                path, body = "/v1/range", {"lo": [lo], "hi": [hi]}
            else:
                path, body = "/v1/knn", {
                    "queries": [point], "k": int(k)}
            if getattr(arrival, "recall", None) is not None:
                body["recall_target"] = float(arrival.recall)
        elif arrival.op == "upsert":
            path, body = "/v1/upsert", {
                "ids": [int(arrival.gid)],
                "points": [arrival.point.tolist()]}
        else:
            path, body = "/v1/delete", {"ids": [int(arrival.gid)]}
        gear = fanout = None
        try:
            status, resp = conn.request(path, body, headers)
            tags = _classify(arrival.op, status, resp)
            gear = _gear_of(arrival.op, status, resp)
            fanout = _fanout_of(arrival.op, status, resp)
        except TimeoutError:
            # socket.timeout IS TimeoutError: the request outlived its
            # client budget — the open-loop analog of a deadline miss
            tags = ["timeouts"]
        except (http.client.HTTPException, OSError):
            tags = ["errors"]
        record(arrival, intended, tags, time.monotonic(), actual_send,
               gear, fanout, req_id=headers["X-Request-Id"])

    def worker() -> None:
        conn = _WorkerConn(target, timeout_s)
        try:
            while True:
                item = work.get()
                if item is None:
                    return
                do_request(conn, *item)
        finally:
            conn.close()

    n_workers = max(int(max_inflight), 1)
    threads = [
        threading.Thread(target=worker, name=f"kdtree-loadgen-{i}")
        for i in range(n_workers)
    ]
    for t in threads:
        t.start()

    current_step = -1
    try:
        for seq, arrival in enumerate(schedule.arrivals):
            if arrival.step != current_step:
                if scrape and arrival.step > 0:
                    st = threading.Thread(
                        target=snap_boundary, args=(arrival.step,),
                        name="kdtree-loadgen-poolsnap", daemon=True)
                    st.start()
                    snap_threads.append(st)
                current_step = arrival.step
                rate = schedule.rates[current_step]
                flight.record("loadgen.step", step=current_step,
                              rate=rate, target=target)
                if on_step is not None:
                    on_step(current_step, rate)
            intended = t0 + arrival.t
            delay = intended - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            # enqueue and move on: the schedule NEVER waits for a
            # response — that is the open-loop contract
            work.put((arrival, intended, seq))
    finally:
        for _ in threads:
            work.put(None)
        for t in threads:
            t.join()

    if scrape:
        for st in snap_threads:
            st.join(timeout=5.0)
        snap_boundary(len(accs))

    steps = []
    for si, acc in enumerate(accs):
        sent = acc.sent
        bad = (acc.counts["shed"] + acc.counts["errors"]
               + acc.counts["timeouts"])
        row = {
            "rate": acc.rate,
            "seconds": schedule.step_seconds,
            "intended": acc.intended,
            "sent": sent,
            "goodput_rps": round(acc.counts["ok"]
                                 / schedule.step_seconds, 3),
            "bad_frac": round(bad / sent, 5) if sent else None,
            "shed_frac": round(acc.counts["shed"] / sent, 5)
            if sent else None,
            "degraded_frac": round(acc.counts["degraded"] / sent, 5)
            if sent else None,
            "partial_frac": round(acc.counts["partial"] / sent, 5)
            if sent else None,
            **{key: acc.counts[key] for key in
               ("ok", "shed", "degraded", "partial", "errors",
                "timeouts", "writes_ok")},
            **_quantiles_ms(acc.latencies_ms),
            "send_lag_p99_ms": _quantiles_ms(acc.send_lag_ms)["p99_ms"],
            # the gear distribution the step's answered queries were
            # served at — a capacity point is only comparable to
            # another measured at the same gears
            "gears": dict(sorted(acc.gears.items())),
            # mean contacted-shard fraction of the step's answered
            # routed queries (None against a plain shard target): the
            # selective fan-out evidence the trend gate's
            # fanout-growth rule watches
            "fanout_frac": (round(float(np.mean(acc.fanout)), 4)
                            if acc.fanout else None),
            # the step's slowest exchange by request id — the id IS the
            # server-side trace id, so this names the waterfall to pull
            # (kdtree-tpu trace --id <it>) for the step's worst tail
            "slowest_trace_id": (acc.slowest[1] if acc.slowest
                                 else None),
            "slowest_ms": (round(acc.slowest[0], 3) if acc.slowest
                           else None),
            # connection-reuse fraction of the step's shard attempts
            # (pool hits / leases, from the target's own counters);
            # None against a pool-less target or when a boundary
            # scrape was lost — absent evidence, never a fake zero
            "conn_reuse_frac": _reuse_frac(pool_snaps.get(si),
                                           pool_snaps.get(si + 1)),
            # per-class cost columns for the step's boundary window
            # (additive key; None when a boundary scrape was lost):
            # knees measured at different class mixes are
            # incommensurable, and this is the evidence trend's
            # cost-growth rule compares mixes with
            "costs": _cost_delta(cost_snaps.get(si),
                                 cost_snaps.get(si + 1)),
        }
        if track_verbs:
            # per-verb latency/goodput columns (additive key — only
            # mixed runs carry it, and trend treats runs at differing
            # verb mixes as incommensurable): a mixed step's aggregate
            # quantiles blend verbs with different unit costs, so the
            # per-verb split is what a knee regression localizes with
            row["verbs"] = {
                verb: {
                    "sent": led["sent"],
                    "ok": led["ok"],
                    "goodput_rps": round(
                        led["ok"] / schedule.step_seconds, 3),
                    "bad_frac": (round(led["bad"] / led["sent"], 5)
                                 if led["sent"] else None),
                    **_quantiles_ms(led["lat"]),
                }
                for verb, led in sorted(acc.verbs.items())
            }
        steps.append(row)
    knee = compute_knee(steps, slo_ms=slo_ms, slo_quantile=slo_quantile,
                        max_bad_frac=max_bad_frac)
    verb_block = None
    if track_verbs:
        # per-verb knee: the highest OFFERED (total) ladder rate whose
        # step met the SLO judged on that verb's own samples — the
        # capacity verdict per read verb, same bar as the aggregate
        verb_block = {}
        all_verbs = sorted({v for acc in accs for v in acc.verbs})
        for verb in all_verbs:
            vsteps = []
            for acc in accs:
                led = acc.verbs.get(verb)
                if not led or not led["sent"]:
                    continue
                vsteps.append({
                    "rate": acc.rate,
                    "sent": led["sent"],
                    "bad_frac": round(led["bad"] / led["sent"], 5),
                    **_quantiles_ms(led["lat"]),
                })
            verb_block[verb] = {
                "knee_rate": compute_knee(
                    vsteps, slo_ms=slo_ms, slo_quantile=slo_quantile,
                    max_bad_frac=max_bad_frac),
            }
    server_block = scrape_server_block(target) if scrape else None
    all_fanout = [f for acc in accs for f in acc.fanout]
    capacity = {
        "capacity_version": CAPACITY_VERSION,
        "offered_unit": "req/s",
        "slo_ms": float(slo_ms),
        "slo_quantile": float(slo_quantile),
        "max_bad_frac": float(max_bad_frac),
        "knee_rate": knee,
        # run-level mean fan-out fraction (additive key, same
        # versioning posture as the per-step gears): a regression back
        # toward full scatter fails trend like a throughput cliff
        "fanout_frac": (round(float(np.mean(all_fanout)), 4)
                        if all_fanout else None),
        # run-level connection-reuse fraction over the whole ladder
        # (additive key, same versioning posture as fanout_frac): the
        # pooled-vs---no-pool A/B's second axis next to the knee
        "conn_reuse_frac": _reuse_frac(
            pool_snaps.get(0), pool_snaps.get(len(accs))),
        "steps": steps,
        "server": server_block,
    }
    if verb_block is not None:
        # additive key, same versioning posture as fanout_frac: the
        # per-verb capacity verdicts next to the aggregate knee
        capacity["verbs"] = verb_block
    # the capacity-headroom model's A/B (additive key): predicted
    # sustainable rate from the run-wide measured cost-per-query
    # (device budget 1000 ms/s — one serial batch worker) against the
    # knee the ladder actually measured. within_band is the CI verdict.
    run_costs = _cost_delta(cost_snaps.get(0), cost_snaps.get(len(accs)))
    if run_costs:
        total_req = sum(e["requests"] for e in run_costs.values())
        total_dev = sum(e["device_ms"] for e in run_costs.values())
        if total_req > 0 and total_dev > 0:
            cpq = total_dev / total_req
            predicted = 1000.0 / cpq
            capacity["predicted"] = {
                "cost_per_query_ms": round(cpq, 4),
                "predicted_rate": round(predicted, 3),
                "knee_rate": knee,
                "band": float(knee_band),
                "within_band": (abs(predicted - knee) <= knee_band * knee
                                if knee > 0 else None),
                "classes": run_costs,
            }
    flight.record("loadgen.knee", knee_rate=knee, slo_ms=float(slo_ms),
                  steps=len(steps), target=target)
    return {
        "loadgen_version": 1,
        "target": target,
        "schedule": schedule.describe(),
        "k": int(k),
        "capacity": capacity,
    }
