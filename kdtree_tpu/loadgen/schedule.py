"""Open-loop arrival schedules: seeded, precomputed, response-blind.

The whole point of an open-loop generator is that arrival times are a
function of the *offered* rate and the seed — never of how the service
responded. This module makes that property structural instead of
behavioral: the complete schedule (every arrival's time offset, op, and
payload) is computed **before the first request is sent**, from one
seeded ``numpy`` generator. The runner then merely replays it. Two runs
with the same seed produce byte-identical schedules; a service that
slows down cannot slow the schedule down with it — latency measured
from the intended send time therefore includes every second of queueing
the service caused (the coordinated-omission correction, built in
rather than patched on).

Shapes:

- **steps** (default): a rate ladder — each entry of ``rates`` holds
  for ``step_seconds`` of homogeneous Poisson arrivals. This is the
  capacity-sweep shape: one latency-vs-offered-load curve point per
  rung.
- **diurnal**: the same ladder, with each rung's rate sinusoidally
  modulated (``rate * (1 + amp * sin)``) via Lewis-Shedler thinning —
  still exactly reproducible from the seed, still open-loop.

Op mix: each arrival independently draws query/upsert/delete by the
configured weights. Upserts mint fresh ids above ``write_base`` (past
the served index, so they never collide with existing rows); deletes
target an id some *earlier* arrival in the schedule upserted — chosen
at build time, so even the delete targets are response-independent. A
delete drawn before any upsert exists becomes an upsert (there is
nothing of ours to delete yet).

Query geometry is Zipf-skewed over spatial regions: ``regions`` seeded
centers in the unit cube, region ranks weighted ``1/rank^s``, query
points jittered around the drawn center. Real query traffic is never
uniform — hot regions are what make cache/plan behavior and per-bucket
load interesting under load.

Stdlib + numpy only; deliberately no jax import (the generator is a
client process).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Arrival", "MixSpec", "Schedule", "build_schedule",
           "parse_mix", "parse_recall_mix", "parse_verb_mix"]

OPS = ("query", "upsert", "delete")
# the read verbs a QUERY arrival can carry (docs/SERVING.md "Query
# verbs"); "knn" is the default and the only verb pre-verb schedules
# ever drew
QUERY_VERBS = ("knn", "radius", "range", "count")
DEFAULT_REGIONS = 64
DEFAULT_ZIPF_S = 1.1
_JITTER_STD = 0.05  # query scatter around its region center (unit cube)


class MixSpec:
    """Operation weights, normalized. ``MixSpec(query=1.0)`` is a pure
    read load; the default serving mix is read-heavy with a real write
    tail."""

    __slots__ = ("query", "upsert", "delete")

    def __init__(self, query: float = 0.9, upsert: float = 0.08,
                 delete: float = 0.02) -> None:
        weights = {"query": float(query), "upsert": float(upsert),
                   "delete": float(delete)}
        if any(w < 0 for w in weights.values()):
            raise ValueError(f"mix weights must be >= 0, got {weights}")
        total = sum(weights.values())
        if total <= 0:
            raise ValueError("mix weights must not all be zero")
        self.query = weights["query"] / total
        self.upsert = weights["upsert"] / total
        self.delete = weights["delete"] / total

    def probs(self) -> List[float]:
        return [self.query, self.upsert, self.delete]

    def describe(self) -> Dict[str, float]:
        return {"query": self.query, "upsert": self.upsert,
                "delete": self.delete}


def parse_mix(raw: str) -> MixSpec:
    """``"query:0.9,upsert:0.08,delete:0.02"`` → :class:`MixSpec`.
    Unknown op names are an error — a typo'd ``upsrt`` silently running
    a pure-read load would make a write-path drill vacuously green (the
    fault-spec grammar's lesson, applied here)."""
    weights = {"query": 0.0, "upsert": 0.0, "delete": 0.0}
    for clause in raw.split(","):
        clause = clause.strip()
        if not clause:
            continue
        if ":" not in clause:
            raise ValueError(
                f"bad mix clause {clause!r}: expected op:weight"
            )
        op, val = (part.strip() for part in clause.split(":", 1))
        if op not in OPS:
            raise ValueError(
                f"unknown mix op {op!r}: expected one of {', '.join(OPS)}"
            )
        try:
            weights[op] = float(val)
        except ValueError:
            raise ValueError(
                f"bad mix weight {val!r} in {clause!r}: must be a number"
            ) from None
    return MixSpec(**weights)


def parse_recall_mix(raw: Optional[str]):
    """``--recall-target`` → ``[(target | None, weight), ...]``.

    Accepts a single value (``"0.99"`` — every query carries it;
    ``"exact"``/``"1"`` — the pure-exact default) or a weighted mix
    (``"exact:0.5,0.99:0.3,0.9:0.2"``) so capacity curves can be
    driven per gear. Weights normalize; a typo'd target is an error,
    never a silently-exact run (the fault-spec grammar's lesson)."""
    if raw is None or not raw.strip():
        return None

    def one_target(tok: str) -> Optional[float]:
        tok = tok.strip()
        if tok.lower() in ("exact", "1", "1.0"):
            return None
        try:
            t = float(tok)
        except ValueError:
            raise ValueError(
                f"bad recall target {tok!r}: expected 'exact' or a "
                "number in (0, 1)"
            ) from None
        if not (0.0 < t < 1.0):
            raise ValueError(
                f"recall target {t:g} must be in (0, 1) — use 'exact' "
                "for 1.0"
            )
        return t

    if ":" not in raw:
        target = one_target(raw)
        return None if target is None else [(target, 1.0)]
    out = []
    for clause in raw.split(","):
        clause = clause.strip()
        if not clause:
            continue
        tok, _, w = clause.rpartition(":")
        try:
            weight = float(w)
        except ValueError:
            raise ValueError(
                f"bad recall-mix weight {w!r} in {clause!r}: must be a "
                "number"
            ) from None
        if weight < 0:
            raise ValueError(f"recall-mix weight {weight:g} in "
                             f"{clause!r} must be >= 0")
        out.append((one_target(tok), weight))
    total = sum(w for _, w in out)
    if total <= 0:
        raise ValueError("recall-mix weights must not all be zero")
    return [(t, w / total) for t, w in out]


def parse_verb_mix(raw: Optional[str]):
    """``--verb-mix`` → ``[(verb, weight), ...]`` or None (pure knn).

    ``"knn:0.7,radius:0.2,count:0.1"`` draws each QUERY arrival's read
    verb by the normalized weights — still seeded, still
    response-blind, and the extra rng draw happens only when a mix is
    configured, so an unmixed schedule stays byte-identical to what
    pre-verb loadgen built from the same seed. Unknown verb names are
    an error, never a silently-pure-knn run (the fault-spec grammar's
    lesson)."""
    if raw is None or not raw.strip():
        return None
    weights: Dict[str, float] = {}
    for clause in raw.split(","):
        clause = clause.strip()
        if not clause:
            continue
        if ":" not in clause:
            raise ValueError(
                f"bad verb-mix clause {clause!r}: expected verb:weight"
            )
        verb, val = (part.strip() for part in clause.split(":", 1))
        if verb not in QUERY_VERBS:
            raise ValueError(
                f"unknown verb {verb!r}: expected one of "
                f"{', '.join(QUERY_VERBS)}"
            )
        try:
            weight = float(val)
        except ValueError:
            raise ValueError(
                f"bad verb-mix weight {val!r} in {clause!r}: must be a "
                "number"
            ) from None
        if weight < 0:
            raise ValueError(f"verb-mix weight {weight:g} in "
                             f"{clause!r} must be >= 0")
        weights[verb] = weights.get(verb, 0.0) + weight
    total = sum(weights.values())
    if total <= 0:
        raise ValueError("verb-mix weights must not all be zero")
    return [(v, weights[v] / total) for v in QUERY_VERBS
            if v in weights]


class Arrival:
    """One scheduled request: when (offset seconds from run start),
    what (op + payload + the query's recall target, None = exact, and
    its read verb — knn/radius/range/count), and which rate step it
    belongs to."""

    __slots__ = ("t", "step", "op", "point", "gid", "recall", "verb")

    def __init__(self, t: float, step: int, op: str,
                 point: Optional[np.ndarray] = None,
                 gid: Optional[int] = None,
                 recall: Optional[float] = None,
                 verb: str = "knn") -> None:
        self.t = float(t)
        self.step = int(step)
        self.op = op
        self.point = point
        self.gid = gid
        self.recall = recall
        self.verb = verb

    def key(self):
        """Comparable identity for determinism tests: timing, step, op,
        payload — everything the runner acts on."""
        return (
            round(self.t, 9), self.step, self.op, self.gid,
            None if self.point is None
            else tuple(round(float(x), 9) for x in self.point),
            self.recall, self.verb,
        )


class Schedule:
    """A fully materialized open-loop schedule plus its build facts."""

    def __init__(self, arrivals: List[Arrival], rates: List[float],
                 step_seconds: float, seed: int, mix: MixSpec,
                 dim: int, write_base: int, shape: str,
                 recall_mix=None, verb_mix=None) -> None:
        self.arrivals = arrivals
        self.rates = [float(r) for r in rates]
        self.step_seconds = float(step_seconds)
        self.seed = int(seed)
        self.mix = mix
        self.dim = int(dim)
        self.write_base = int(write_base)
        self.shape = shape
        self.recall_mix = recall_mix
        self.verb_mix = verb_mix

    @property
    def duration_s(self) -> float:
        return self.step_seconds * len(self.rates)

    def keys(self):
        return [a.key() for a in self.arrivals]

    def describe(self) -> Dict:
        ops = {op: 0 for op in OPS}
        for a in self.arrivals:
            ops[a.op] += 1
        out = {
            "arrivals": len(self.arrivals),
            "rates": self.rates,
            "step_seconds": self.step_seconds,
            "seed": self.seed,
            "shape": self.shape,
            "mix": self.mix.describe(),
            "ops": ops,
            "dim": self.dim,
            "write_base": self.write_base,
        }
        if self.recall_mix:
            out["recall_mix"] = [
                ["exact" if t is None else t, w]
                for t, w in self.recall_mix
            ]
        if self.verb_mix:
            out["verb_mix"] = [[v, w] for v, w in self.verb_mix]
            verbs = {v: 0 for v, _ in self.verb_mix}
            for a in self.arrivals:
                if a.op == "query":
                    verbs[a.verb] = verbs.get(a.verb, 0) + 1
            out["verbs"] = verbs
        return out


def _zipf_weights(regions: int, s: float) -> np.ndarray:
    ranks = np.arange(1, regions + 1, dtype=np.float64)
    w = 1.0 / np.power(ranks, s)
    return w / w.sum()


def build_schedule(
    rates: Sequence[float],
    step_seconds: float,
    seed: int,
    dim: int,
    mix: Optional[MixSpec] = None,
    regions: int = DEFAULT_REGIONS,
    zipf_s: float = DEFAULT_ZIPF_S,
    shape: str = "steps",
    diurnal_amp: float = 0.3,
    write_base: int = 10_000_000,
    recall_mix=None,
    verb_mix=None,
) -> Schedule:
    """Materialize the whole schedule from the seed — see the module
    docstring for the open-loop rationale.

    ``rates`` are offered request rates (req/s) per ladder step;
    ``write_base`` is the first id upserts mint (pick it above the
    served index's id range so writes never collide with real rows —
    the CLI derives it from ``/healthz``). ``recall_mix`` (from
    :func:`parse_recall_mix`) draws each QUERY arrival's
    ``recall_target`` from a weighted set — still seeded, still
    response-blind — so capacity curves can be driven per serving
    gear; ``None`` keeps every query exact. ``verb_mix`` (from
    :func:`parse_verb_mix`) likewise draws each query arrival's read
    verb (knn/radius/range/count); ``None`` keeps every query a knn
    lookup AND skips the draw entirely, so unmixed schedules stay
    byte-identical to pre-verb ones from the same seed."""
    if not rates or any(r <= 0 for r in rates):
        raise ValueError(f"rates must be positive, got {list(rates)}")
    if step_seconds <= 0:
        raise ValueError(f"step_seconds must be > 0, got {step_seconds}")
    if dim < 1:
        raise ValueError(f"dim must be >= 1, got {dim}")
    if regions < 1:
        raise ValueError(f"regions must be >= 1, got {regions}")
    if shape not in ("steps", "diurnal"):
        raise ValueError(f"shape must be 'steps' or 'diurnal', got {shape!r}")
    if not (0.0 <= diurnal_amp < 1.0):
        raise ValueError(f"diurnal amp must be in [0, 1), got {diurnal_amp}")
    mix = mix if mix is not None else MixSpec()
    rng = np.random.default_rng(int(seed))
    centers = rng.random((regions, dim))
    region_p = _zipf_weights(regions, zipf_s)
    probs = mix.probs()
    recall_targets = recall_probs = None
    if recall_mix:
        recall_targets = [t for t, _ in recall_mix]
        recall_probs = [w for _, w in recall_mix]
    verb_names = verb_probs = None
    if verb_mix:
        verb_names = [v for v, _ in verb_mix]
        verb_probs = [w for _, w in verb_mix]

    arrivals: List[Arrival] = []
    upserted: List[int] = []  # gids minted so far, in schedule order
    next_gid = int(write_base)
    for step, rate in enumerate(rates):
        t0 = step * step_seconds
        t1 = t0 + step_seconds
        # homogeneous Poisson at the envelope rate; diurnal thins it
        # down to the modulated instantaneous rate (Lewis-Shedler)
        env_rate = rate * (1.0 + diurnal_amp) if shape == "diurnal" \
            else rate
        t = t0
        while True:
            t += float(rng.exponential(1.0 / env_rate))
            if t >= t1:
                break
            if shape == "diurnal":
                inst = rate * (
                    1.0 + diurnal_amp
                    * np.sin(2.0 * np.pi * (t - t0) / step_seconds)
                )
                if rng.random() * env_rate > max(inst, 0.0):
                    continue  # thinned: this envelope arrival never fires
            op = OPS[int(rng.choice(3, p=probs))]
            if op == "delete" and not upserted:
                # nothing of ours exists to delete yet; minting a fresh
                # row keeps the write fraction honest instead of
                # silently shrinking it
                op = "upsert"
            if op == "query":
                center = centers[int(rng.choice(regions, p=region_p))]
                point = np.clip(
                    center + rng.normal(0.0, _JITTER_STD, dim), 0.0, 1.0
                ).astype(np.float32)
                recall = None
                if recall_targets is not None:
                    recall = recall_targets[
                        int(rng.choice(len(recall_targets),
                                       p=recall_probs))
                    ]
                verb = "knn"
                if verb_names is not None:
                    verb = verb_names[
                        int(rng.choice(len(verb_names), p=verb_probs))
                    ]
                arrivals.append(Arrival(t, step, "query", point=point,
                                        recall=recall, verb=verb))
            elif op == "upsert":
                gid = next_gid
                next_gid += 1
                upserted.append(gid)
                point = rng.random(dim).astype(np.float32)
                arrivals.append(
                    Arrival(t, step, "upsert", point=point, gid=gid)
                )
            else:
                # target an id an EARLIER arrival upserted — decided at
                # build time, so delete targets are response-blind too
                pick = int(rng.integers(len(upserted)))
                gid = upserted.pop(pick)
                arrivals.append(Arrival(t, step, "delete", gid=gid))
    return Schedule(arrivals, list(rates), step_seconds, seed, mix, dim,
                    write_base, shape, recall_mix=recall_mix,
                    verb_mix=verb_mix)
