"""kdtree_tpu.loadgen — the production load harness.

Everything before this subsystem measured the serving stack closed-loop:
one request in flight, throughput = 1/latency, and a queue that can
never form. Production traffic is the opposite — arrivals come from the
*world*, not from the previous response — and the difference is exactly
the regime where SLOs, shedding, hedging, and the mutable write path
earn their keep. This package drives the serve/route HTTP API the way
production would:

- :mod:`~kdtree_tpu.loadgen.schedule` — a **precomputed, seeded**
  arrival schedule: Poisson arrivals at each rung of a rate ladder
  (optionally diurnally modulated), a configurable query/upsert/delete
  mix, and Zipf-skewed query geometry over spatial regions. The entire
  schedule exists before the first request is sent, which is the
  open-loop guarantee in mechanical form: response latency *cannot*
  influence when the next request fires (no coordinated omission).
- :mod:`~kdtree_tpu.loadgen.runner` — the driver: dispatches the
  schedule against a live ``serve``/``route`` process, measures latency
  from each arrival's **intended** send time (queueing the service
  caused is charged to the service, even if the client itself fell
  behind), classifies outcomes (ok/shed/degraded/partial/error/
  timeout), scrapes the target's ``/metrics`` for the new write-path
  histograms, and emits a ``capacity`` block: one curve point per rate
  step plus the **knee** — the highest offered rate that still meets
  the latency SLO with an acceptable bad fraction.

The capacity block rides in the telemetry sidecar
(``kdtree-tpu --metrics-out ... loadgen``) and in the standalone
``--out`` artifact; ``kdtree-tpu trend`` diffs knee rates across rounds
so a capacity regression fails CI exactly like a single-shot throughput
drop (docs/OBSERVABILITY.md "Load harness & capacity curves").

Host-only: this package never imports jax — the load generator is a
client, and it must cost the machine nothing the service under test
would notice.
"""

from kdtree_tpu.loadgen.schedule import (
    Arrival,
    MixSpec,
    Schedule,
    build_schedule,
)

__all__ = ["Arrival", "MixSpec", "Schedule", "build_schedule"]
