"""Exact brute-force k-NN — the correctness oracle and the MXU-friendly path.

The reference has no oracle (its own low-D output is wrong due to the sort
off-by-one at ``kdtree_sequential.cpp:46-48`` — see SURVEY.md §3.5), so brute
force is the ground truth for every test in this framework.

Numerics (verified on a real v5e chip): the textbook ``|q|^2 + |p|^2 - 2 q.p``
matmul form is unusable as an oracle in low D — TPU matmuls default to
bf16-precision passes, and even at ``Precision.HIGHEST`` the form cancels
catastrophically when the true distance is tiny relative to |q||p| (~1e4 for
this problem's [-100,100) coordinates): nearest-neighbor distances come back
as 0.0. So:

- ``method='exact'`` (default for D <= 32): direct ``(q - p)^2`` blocks on the
  VPU — bit-faithful to the reference's accumulation
  (``kdtree_sequential.cpp:14-25``), bandwidth-bound.
- ``method='matmul'`` (default for D > 32): HIGHEST-precision matmul on the
  MXU as a COARSE ranking, followed by exact rescoring of the top k+slack
  candidates per tile (clustered high-D data puts |x|^2 up to ~1e6 against
  d^2 of a few hundred — the identity alone is off by ~0.1 absolute). The
  refine pass makes returned distances exact; the *selection* is exact up to
  the slack margin (a true neighbor coarse-ranked below k+REFINE_SLACK
  within its tile would be missed — astronomically unlikely but not
  impossible). **The oracle claim above is for method='exact'**;
  ``knn_exact_d2`` is the strict oracle used by the test suite.

Both stream point tiles through a ``lax.scan`` carrying a running top-k, so N
is bounded by HBM, not by a [Q, N] matrix.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

EXACT_DIM_MAX = 32  # above this, 'auto' switches to the matmul form
REFINE_SLACK = 8  # extra coarse candidates kept for exact rescoring (matmul)


def _block_d2_exact(queries: jax.Array, ptile: jax.Array) -> jax.Array:
    """[Q, T] squared distances via direct subtraction (VPU, exact in f32)."""
    diff = queries[:, None, :] - ptile[None, :, :]
    return jnp.sum(diff * diff, axis=-1)


def _block_d2_matmul(queries: jax.Array, ptile: jax.Array) -> jax.Array:
    """[Q, T] squared distances via the matmul identity (MXU, high-D only)."""
    qn = jnp.sum(queries * queries, axis=1, keepdims=True)
    pn = jnp.sum(ptile * ptile, axis=1)
    cross = jax.numpy.matmul(queries, ptile.T, precision=lax.Precision.HIGHEST)
    return jnp.maximum(qn + pn[None, :] - 2.0 * cross, 0.0)


@functools.partial(jax.jit, static_argnames=("k", "tile", "method", "axis_name"))
def _knn_scan(points, queries, k: int, tile: int, method: str,
              axis_name: str | None = None):
    """Streaming top-k scan. With ``axis_name`` set, each block's distances
    are PARTIAL sums (the caller holds a feature-axis column shard) and one
    psum over the mesh completes them — the D-sharded TP analog
    (kdtree_tpu.parallel.dsharded) reuses this exact skeleton; only
    method='exact' composes with partial sums (the matmul refine pass
    rescans columns it doesn't hold)."""
    assert axis_name is None or method == "exact"
    n, d = points.shape
    q = queries.shape[0]
    block = _block_d2_exact if method == "exact" else _block_d2_matmul

    pad = (-n) % tile
    if pad:
        points = jnp.concatenate(
            [points, jnp.zeros((pad, d), points.dtype)], axis=0
        )
    ntiles = points.shape[0] // tile
    tiles = points.reshape(ntiles, tile, d)

    def step(carry, ptile):
        best_d, best_i, base = carry
        real = base + jnp.arange(tile) < n  # positional mask, not data-dependent
        d2_blk = block(queries, ptile)
        if axis_name is not None:
            d2_blk = lax.psum(d2_blk, axis_name)
        d2 = jnp.where(real[None, :], d2_blk, jnp.inf)
        # the matmul identity qn+pn-2q.p cancels catastrophically when |x|^2
        # >> d^2 (clustered data far from the origin: f32 absolute error
        # ~eps*|x|^2 can exceed the NN distance). So the MXU pass is only a
        # COARSE ranking: keep k+slack candidates and rescore them with the
        # exact subtraction form (cheap: [Q, kk, D]); the slack absorbs
        # coarse-ranking inversions at the cut.
        kk = min(k if method == "exact" else k + REFINE_SLACK, tile)
        neg, idx = lax.top_k(-d2, kk)
        sel_d = -neg
        if method != "exact":
            pe = ptile[idx]  # [Q, kk, D]
            diff = queries[:, None, :] - pe
            d2e = jnp.sum(diff * diff, axis=-1)
            sel_d = jnp.where(jnp.isinf(sel_d), jnp.inf, d2e)
        cand_d = jnp.concatenate([best_d, sel_d], axis=1)
        cand_i = jnp.concatenate([best_i, idx.astype(jnp.int32) + base], axis=1)
        neg2, sel = lax.top_k(-cand_d, k)
        return (-neg2, jnp.take_along_axis(cand_i, sel, axis=1), base + tile), None

    init = (
        jnp.full((q, k), jnp.inf, points.dtype),
        jnp.full((q, k), -1, jnp.int32),
        jnp.int32(0),
    )
    (best_d, best_i, _), _ = lax.scan(step, init, tiles)
    return best_d, best_i


def knn(
    points: jax.Array,
    queries: jax.Array,
    k: int = 1,
    method: str = "auto",
    tile: int = 1 << 17,
) -> Tuple[jax.Array, jax.Array]:
    """Exact k-NN by streaming brute force.

    Args:
      points:  f32[N, D]
      queries: f32[Q, D]
      k: neighbors per query (clamped to N).
      method: 'exact' | 'matmul' | 'auto' (exact for D <= 32, else matmul).
      tile: point-tile size per scan step (bounds the [Q, tile] block).

    Returns:
      (dists_sq f32[Q, k], indices i32[Q, k]) ascending by distance. Squared
      Euclidean, like the reference's ``distance_squared``
      (``kdtree_sequential.cpp:14-25``); ``sqrt`` at the protocol edge
      (``Node.cpp:36-38``).
    """
    n, d = points.shape
    k = min(k, n)
    if method == "auto":
        method = "exact" if d <= EXACT_DIM_MAX else "matmul"
    tile = min(tile, max(k, ((n + 127) // 128) * 128))
    from kdtree_tpu import obs

    if not obs.is_tracer(queries):
        obs.count_query("bruteforce", queries.shape[0])
    return _knn_scan(points, queries, k, tile, method)


def knn_exact_d2(points, queries, k: int = 1):
    """Non-tiled direct-subtraction oracle (test-sized problems)."""
    d2 = _block_d2_exact(queries, points)
    neg, idx = lax.top_k(-d2, min(k, points.shape[0]))
    return -neg, idx.astype(jnp.int32)
