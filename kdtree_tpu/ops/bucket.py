"""Bucketed k-d tree: leaf buckets instead of single-point leaves.

The reference recurses to single-point leaves (``kdtree_sequential.cpp:35``),
which is the wrong shape for a vector machine: querying becomes a long,
divergent pointer chase. The classic fix — and the idiomatic TPU one — is to
stop splitting once a segment fits a **bucket** of ~128 points (one VPU lane
row), and scan buckets vectorized at query time:

- build does only ``ceil(log2(N / B))`` sorted levels instead of
  ``ceil(log2 N)`` (~25%% fewer sorts at 16M/B=128);
- traversal per query becomes ~depth node hops plus a handful of
  [B, D]-shaped dense distance blocks — VPU work instead of serialized
  gathers. Measured on a v5e chip at 16M x 3D, k=16: ~27x the query
  throughput of the single-point-leaf tree.

Exactness is preserved: internal nodes still hold their median point exactly
like the reference (their distance is tested on visit), buckets hold the
remaining segment points, and the same plane-distance prune bounds apply to
bucket visits. Results are validated against the brute-force oracle.

Storage (all pytree leaves, device-resident):
  node_coords f32[H, D]  internal node point coordinates (inf where absent)
  node_gid    i32[H]     internal node point ids (-1 where absent)
  node_bucket i32[H]     bucket index for bucket-leaf heap slots, else -1
  bucket_pts  f32[NB, B, D]  bucket contents (inf-padded)
  bucket_gid  i32[NB, B]     bucket point ids (-1 padding)
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kdtree_tpu import obs

DEFAULT_BUCKET = 128


@jax.tree_util.register_pytree_node_class
class BucketKDTree:
    def __init__(self, node_coords, node_gid, node_bucket, bucket_pts, bucket_gid,
                 n_real, num_levels):
        self.node_coords = node_coords
        self.node_gid = node_gid
        self.node_bucket = node_bucket
        self.bucket_pts = bucket_pts
        self.bucket_gid = bucket_gid
        self.n_real = n_real
        self.num_levels = num_levels  # internal levels (max traversal depth)

    @property
    def dim(self) -> int:
        return self.node_coords.shape[1]

    @property
    def heap_size(self) -> int:
        return self.node_coords.shape[0]

    @property
    def bucket_size(self) -> int:
        return self.bucket_pts.shape[1]

    def tree_flatten(self):
        return (
            (self.node_coords, self.node_gid, self.node_bucket,
             self.bucket_pts, self.bucket_gid),
            (self.n_real, self.num_levels),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    def __repr__(self):
        return (
            f"BucketKDTree(n={self.n_real}, heap={self.heap_size}, "
            f"buckets={self.bucket_pts.shape[0]}x{self.bucket_size})"
        )


@dataclass(frozen=True)
class BucketSpec:
    """Static structure of a bucketed tree over n points, bucket cap b."""

    n: int
    bucket_cap: int
    num_levels: int
    heap_size: int
    num_buckets: int
    consume_level: np.ndarray  # i32[N]; num_levels where never consumed
    med_nodes: np.ndarray  # i32[M] heap ids of internal nodes
    med_pos: np.ndarray  # i32[M] their (final) permutation positions
    bucket_node: np.ndarray  # i32[NB] heap id of each bucket leaf
    bucket_start: np.ndarray  # i32[NB] position range start
    bucket_len: np.ndarray  # i32[NB]


@functools.lru_cache(maxsize=16)
def bucket_spec(n: int, bucket_cap: int = DEFAULT_BUCKET) -> BucketSpec:
    """Same recursion arithmetic as ``tree_spec`` (reference split at
    ``kdtree_sequential.cpp:51-56``) but segments with <= bucket_cap points
    become leaf buckets instead of recursing."""
    if n <= 0:
        raise ValueError(f"n must be positive, got {n}")
    if bucket_cap < 2:
        # a size-2 segment has no right child; phase-A descent would walk
        # empty heap slots (see ADVICE r1) — disallow rather than rely on
        # index clamping
        raise ValueError(f"bucket_cap must be >= 2, got {bucket_cap}")
    segs = [(0, n, 0)]
    med_levels, med_nodes, med_pos = [], [], []
    buckets = []
    level = 0
    max_node = 0
    while segs:
        nxt = []
        for s, c, node in segs:
            max_node = max(max_node, node)
            if c <= bucket_cap:
                buckets.append((node, s, c))
                continue
            m = c // 2
            med_levels.append(level)
            med_nodes.append(node)
            med_pos.append(s + m)
            nxt.append((s, m, 2 * node + 1))
            if c - m - 1 > 0:
                nxt.append((s + m + 1, c - m - 1, 2 * node + 2))
        segs = nxt
        level += 1
    num_levels = (max(med_levels) + 1) if med_levels else 0
    consume = np.full(n, num_levels, np.int32)  # bucket points: never consumed
    if med_pos:
        consume[np.array(med_pos, np.int64)] = np.array(med_levels, np.int32)
    bucket_node = np.array([b[0] for b in buckets], np.int32)
    bucket_start = np.array([b[1] for b in buckets], np.int32)
    bucket_len = np.array([b[2] for b in buckets], np.int32)
    return BucketSpec(
        n=n,
        bucket_cap=bucket_cap,
        num_levels=num_levels,
        heap_size=max_node + 1,
        num_buckets=len(buckets),
        consume_level=consume,
        med_nodes=np.array(med_nodes, np.int32),
        med_pos=np.array(med_pos, np.int32),
        bucket_node=bucket_node,
        bucket_start=bucket_start,
        bucket_len=bucket_len,
    )


@functools.lru_cache(maxsize=16)
def _bucket_arrays(n: int, d: int, bucket_cap: int):
    spec = bucket_spec(n, bucket_cap)
    return (
        jnp.asarray(spec.consume_level),
        jnp.asarray(spec.med_nodes),
        jnp.asarray(spec.med_pos),
        jnp.asarray(spec.bucket_node),
        jnp.asarray(spec.bucket_start),
        jnp.asarray(spec.bucket_len),
    )


def _extract_bucket_tree(
    points, perm, med_nodes, med_pos, bucket_node, bucket_start, bucket_len,
    *, num_levels: int, heap_size: int, bucket_cap: int,
) -> BucketKDTree:
    """Assemble the BucketKDTree from the final position->pid permutation."""
    n, d = points.shape
    # internal nodes
    node_gid = jnp.full(heap_size, -1, jnp.int32).at[med_nodes].set(perm[med_pos])
    node_coords = jnp.full((heap_size, d), jnp.inf, points.dtype)
    node_coords = node_coords.at[med_nodes].set(points[perm[med_pos]])
    # bucket leaves
    node_bucket = jnp.full(heap_size, -1, jnp.int32)
    node_bucket = node_bucket.at[bucket_node].set(
        jnp.arange(bucket_node.shape[0], dtype=jnp.int32)
    )
    offs = jnp.arange(bucket_cap, dtype=jnp.int32)
    pos = bucket_start[:, None] + offs[None, :]  # [NB, B]
    valid = offs[None, :] < bucket_len[:, None]
    gid = jnp.where(valid, perm[jnp.minimum(pos, n - 1)], -1)
    bpts = jnp.where(
        valid[:, :, None], points[jnp.maximum(gid, 0)], jnp.inf
    )
    return BucketKDTree(
        node_coords=node_coords,
        node_gid=node_gid,
        node_bucket=node_bucket,
        bucket_pts=bpts,
        bucket_gid=gid,
        n_real=n,
        num_levels=num_levels,
    )


def build_bucket_impl(
    points, consume, med_nodes, med_pos, bucket_node, bucket_start, bucket_len,
    *, num_levels: int, heap_size: int, bucket_cap: int,
) -> BucketKDTree:
    n, d = points.shape

    def level_step(lvl, perm):
        dead = (consume < lvl).astype(jnp.int32)
        csum = jnp.cumsum(dead)
        segkey = 2 * csum - dead
        axis = jnp.mod(lvl, d)
        coord = points[perm, axis]
        _, _, perm = lax.sort((segkey, coord, perm), num_keys=3, is_stable=True)
        return perm

    perm = lax.fori_loop(0, num_levels, level_step, jnp.arange(n, dtype=jnp.int32))
    return _extract_bucket_tree(
        points, perm, med_nodes, med_pos, bucket_node, bucket_start, bucket_len,
        num_levels=num_levels, heap_size=heap_size, bucket_cap=bucket_cap,
    )


def build_bucket_presort_impl(
    points, consume, med_nodes, med_pos, bucket_node, bucket_start, bucket_len,
    *, num_levels: int, heap_size: int, bucket_cap: int,
) -> BucketKDTree:
    """Presort-strategy bucket build: ~10 scan passes per level instead of a
    full ``lax.sort`` per level (see :mod:`kdtree_tpu.ops.build_presort`).

    Produces a tree bit-identical to :func:`build_bucket` (tested): both order
    bucket contents by (last-level axis coordinate, id) — the sort build
    because its final level sorts by that axis, the presort build because
    ``lists[a]`` maintains exactly that order per segment.
    """
    from kdtree_tpu.ops.build_presort import presort_lists

    n, d = points.shape
    if num_levels == 0:
        final = jnp.arange(n, dtype=jnp.int32)
    else:
        lists = presort_lists(points, consume, num_levels=num_levels)
        final = lists[(num_levels - 1) % d]
    return _extract_bucket_tree(
        points, final, med_nodes, med_pos, bucket_node, bucket_start, bucket_len,
        num_levels=num_levels, heap_size=heap_size, bucket_cap=bucket_cap,
    )


@functools.partial(
    jax.jit, static_argnames=("num_levels", "heap_size", "bucket_cap", "strategy")
)
def _build_bucket_jit(points, consume, med_nodes, med_pos, bucket_node,
                      bucket_start, bucket_len, num_levels, heap_size, bucket_cap,
                      strategy="sort"):
    impl = build_bucket_presort_impl if strategy == "presort" else build_bucket_impl
    return impl(
        points, consume, med_nodes, med_pos, bucket_node, bucket_start,
        bucket_len, num_levels=num_levels, heap_size=heap_size,
        bucket_cap=bucket_cap,
    )


def build_bucket(
    points: jax.Array, bucket_cap: int = DEFAULT_BUCKET, strategy: str = "auto"
) -> BucketKDTree:
    """Build a bucketed tree (jitted; structure arrays are runtime inputs).

    ``strategy``: "sort" (one stable lax.sort per level) or "presort" (per-axis
    presorted lists + scan repartition, which keeps D sorted id lists so it
    only makes sense for small D). "auto" picks by D. Identical trees either
    way. Measured on the real v5e chip at 16M x 3D the sort strategy wins
    (~5.8s vs presort's scatter-bound ~49s), so auto currently always
    resolves to "sort"; the knob stays because the presort path is the
    scaffold for the Pallas partition kernel.
    """
    n, d = points.shape
    if strategy == "auto":
        strategy = "sort"
    if not obs.is_tracer(points):
        obs.count_build("bucket", n)
    spec = bucket_spec(n, bucket_cap)
    arrs = _bucket_arrays(n, d, bucket_cap)
    return _build_bucket_jit(
        points, *arrs, spec.num_levels, spec.heap_size, spec.bucket_cap,
        strategy=strategy,
    )


# ---------------------------------------------------------------------------
# query
# ---------------------------------------------------------------------------


def _bucket_scan_merge(tree, q, bkt, enabled, best_d, best_i):
    """Dense single-bucket scan merged into the k-buffer via the shared
    helper. ``enabled`` masks the whole update."""
    from kdtree_tpu.ops.topk import merge_topk

    bpts = tree.bucket_pts[jnp.maximum(bkt, 0)]  # [B, D]
    bgid = tree.bucket_gid[jnp.maximum(bkt, 0)]
    bd = q[None, :] - bpts
    bd2 = jnp.sum(bd * bd, axis=1)  # [B] (inf at padding)
    bd2 = jnp.where(enabled, bd2, jnp.inf)
    return merge_topk(best_d, best_i, bd2, bgid, enabled)


def _bucket_knn_one(tree: BucketKDTree, k: int, q):
    """Two-phase exact k-NN.

    Phase A descends straight to the query's home bucket — a cheap
    fixed-bound loop with no stack and no bucket traffic — collecting the
    internal median points on the path, then scans the home bucket once.
    That fills the k-buffer with tight candidates, so phase B (the classic
    stack-based prune-and-backtrack, as in ``kdtree_sequential.cpp:75-136``)
    prunes almost everything. Phase B skips home-path ancestors and the home
    bucket via heap-index arithmetic (ancestor test: (hb+1) >> dl == node+1)
    so no candidate is counted twice.
    """
    heap_size = tree.heap_size
    d = tree.dim
    max_depth = tree.num_levels
    stack_cap = max_depth + 2

    best_d = jnp.full(k, jnp.inf, jnp.float32)
    best_i = jnp.full(k, -1, jnp.int32)

    # ---- phase A: descend to the home bucket ----
    def descend_cond(state):
        node, _, _ = state
        return tree.node_bucket[jnp.minimum(node, heap_size - 1)] < 0

    def descend_body(state):
        node, best_d, best_i = state
        p = tree.node_coords[node]
        gid = tree.node_gid[node]
        diff = q - p
        d2 = jnp.sum(diff * diff)
        worst = jnp.max(best_d)
        wi = jnp.argmax(best_d)
        take = (gid >= 0) & (d2 < worst)
        best_d = jnp.where(take, best_d.at[wi].set(d2), best_d)
        best_i = jnp.where(take, best_i.at[wi].set(gid), best_i)
        level = 31 - lax.clz(node + 1)
        ax = jnp.mod(level, d)
        go_right = (q[ax] >= p[ax]).astype(jnp.int32)
        return 2 * node + 1 + go_right, best_d, best_i

    home, best_d, best_i = lax.while_loop(
        descend_cond, descend_body, (jnp.int32(0), best_d, best_i)
    )
    home_bkt = tree.node_bucket[jnp.minimum(home, heap_size - 1)]
    best_d, best_i = _bucket_scan_merge(tree, q, home_bkt, home_bkt >= 0, best_d, best_i)

    # ---- phase B: collect-then-scan backtracking ----
    # The traversal loop body stays tiny (a few scalar gathers per lane):
    # candidate buckets that survive pruning are *collected* into a V-slot
    # list; each time the list fills (or the stack drains) ONE dense
    # [V, B, D] scan + top-k merge processes them. Bucket HBM traffic and
    # sorting leave the serial loop entirely — on a v5e chip this is ~10x
    # the naive scan-inside-the-loop query throughput.
    home_lvl = 31 - lax.clz(home + 1)
    V = 8  # buckets per dense-scan round

    stack_n = jnp.zeros(stack_cap, jnp.int32)
    stack_b = jnp.zeros(stack_cap, jnp.float32)
    sp = jnp.int32(1)  # root pre-pushed with bound 0
    B = tree.bucket_size

    def outer_cond(state):
        return state[2] > 0

    def outer_body(state):
        stack_n, stack_b, sp, best_d, best_i = state
        blist = jnp.full(V, -1, jnp.int32)

        def inner_cond(s):
            _, _, sp, _, _, _, bcnt = s
            return (sp > 0) & (bcnt < V)

        def inner_body(s):
            stack_n, stack_b, sp, best_d, best_i, blist, bcnt = s
            top = sp - 1
            node = stack_n[top]
            bound = stack_b[top]
            worst = jnp.max(best_d)
            nc = jnp.minimum(node, heap_size - 1)
            bkt = tree.node_bucket[nc]
            gid = tree.node_gid[nc]
            occupied = (node < heap_size) & ((gid >= 0) | (bkt >= 0))
            visit = occupied & (bound < worst)
            is_bucket = visit & (bkt >= 0)
            is_internal = visit & (bkt < 0)

            # skip anything phase A already counted
            level = 31 - lax.clz(node + 1)
            dl = home_lvl - level
            on_home_path = (dl >= 0) & ((home + 1) >> jnp.maximum(dl, 0) == node + 1)

            p = tree.node_coords[nc]
            diff = q - p
            d2 = jnp.sum(diff * diff)
            wi = jnp.argmax(best_d)
            take = is_internal & (d2 < worst) & ~on_home_path
            best_d = jnp.where(take, best_d.at[wi].set(d2), best_d)
            best_i = jnp.where(take, best_i.at[wi].set(gid), best_i)

            ax = jnp.mod(level, d)
            delta = q[ax] - p[ax]
            go_right = (delta >= 0).astype(jnp.int32)
            near = 2 * node + 1 + go_right
            far = 2 * node + 2 - go_right
            pushed_n = stack_n.at[top].set(far).at[top + 1].set(near)
            pushed_b = stack_b.at[top].set(delta * delta).at[top + 1].set(
                jnp.float32(0)
            )
            stack_n = jnp.where(is_internal, pushed_n, stack_n)
            stack_b = jnp.where(is_internal, pushed_b, stack_b)
            sp = jnp.where(is_internal, sp + 1, sp - 1)

            collect = is_bucket & (bkt != home_bkt)
            blist = jnp.where(collect, blist.at[bcnt].set(bkt), blist)
            bcnt = jnp.where(collect, bcnt + 1, bcnt)
            return stack_n, stack_b, sp, best_d, best_i, blist, bcnt

        stack_n, stack_b, sp, best_d, best_i, blist, bcnt = lax.while_loop(
            inner_cond, inner_body,
            (stack_n, stack_b, sp, best_d, best_i, blist, jnp.int32(0)),
        )

        # dense scan of the collected buckets: [V, B, D] block + one top-k
        from kdtree_tpu.ops.topk import scan_bucket_block

        best_d, best_i = scan_bucket_block(
            q, tree.bucket_pts, tree.bucket_gid, blist, bcnt, best_d, best_i
        )
        return stack_n, stack_b, sp, best_d, best_i

    init = (stack_n, stack_b, sp, best_d, best_i)
    _, _, _, best_d, best_i = lax.while_loop(outer_cond, outer_body, init)
    best_d, best_i = lax.sort((best_d, best_i), num_keys=2, is_stable=True)
    return best_d, best_i


@functools.partial(jax.jit, static_argnames=("k", "chunk"))
def _bucket_knn_batch(tree, queries, k: int, chunk: int):
    nq = queries.shape[0]
    pad = (-nq) % chunk
    if pad:
        queries = jnp.concatenate(
            [queries, jnp.zeros((pad, queries.shape[1]), queries.dtype)], axis=0
        )
    chunks = queries.reshape(-1, chunk, queries.shape[1])

    def one_chunk(_, qs):
        out = jax.vmap(lambda q: _bucket_knn_one(tree, k, q))(qs)
        return None, out

    _, (d2, idx) = lax.scan(one_chunk, None, chunks)
    d2 = d2.reshape(-1, k)[:nq]
    idx = idx.reshape(-1, k)[:nq]
    return d2, idx


def bucket_knn(
    tree: BucketKDTree, queries: jax.Array, k: int = 1, chunk: int = 16384
) -> Tuple[jax.Array, jax.Array]:
    """Exact k-NN against a bucketed tree.

    Large query batches are processed in fixed-size chunks under a scan —
    bounded memory regardless of Q (a single 1M-lane vmapped while_loop
    crashed the TPU worker; chunking also keeps lockstep divergence local).
    """
    k = min(k, tree.n_real)
    if not obs.is_tracer(queries):
        obs.count_query("bucket", queries.shape[0])
    return _bucket_knn_batch(tree, queries, k, min(chunk, max(queries.shape[0], 1)))
