from kdtree_tpu.ops import bruteforce
from kdtree_tpu.ops.build import build, build_jit, validate_invariants
from kdtree_tpu.ops.generate import (
    generate_problem,
    generate_points_rowwise,
    generate_points_shard,
)
from kdtree_tpu.ops.query import knn, nearest_neighbor

__all__ = [
    "bruteforce",
    "build",
    "build_jit",
    "validate_invariants",
    "generate_problem",
    "generate_points_rowwise",
    "generate_points_shard",
    "knn",
    "nearest_neighbor",
]
