"""Presort build: O(N) work per level instead of a comparison sort per level.

The sort-based build (:mod:`kdtree_tpu.ops.build`) pays a full stable
``lax.sort`` per level — O(N log N) x depth. This implementation uses the
classic parallel k-d construction strategy instead (cf. GPU builders such as
Wehr & Radkowski's adaptive split-and-sort — PAPERS.md): sort the point ids
**once per axis** up front, then maintain, for every axis, the invariant

    list_a = point ids ordered segment-major, coord_a-minor,

where "segments" are the same static position structure the sort-based build
uses (``TreeSpec``: exact-median splits make every boundary static, holes at
consumed medians persist). Splitting a level then needs NO sort:

1. position-space classification (shared by all axes, static structure +
   dynamic level, all plain cummax/cumsum scans):
   - ``H[p]``: nearest hole at-or-left  -> segment start = H+1
   - ``M[p]`` / ``Q[p]``: nearest dying position left / right -> the
     segment's median position
   - side(p): left / dies-now / right / already-dead
2. the split-axis list maps sides from positions to point ids (one scatter);
3. every axis list stably repartitions [left | hole | right] inside each
   segment with two segmented cumsums and one scatter — coordinate order is
   preserved within the children, restoring the invariant.

Consumed points sit at their static hole position in EVERY list, so the final
node extraction is one gather, same as the sort-based build. The resulting
tree is bit-identical to the sort-based build (tested), since both order
segments by (coord, id).

Work per level: ~10 elementwise/scan passes over N per axis versus a full
sort — asymptotically better, but the passes are dominated by 16M-wide random
gathers and scatters, which XLA:TPU serializes. Measured on the real v5e chip
at 16M x 3D this loses badly to the sort build (~49s vs ~8.5s), so the sort
strategy is the production path; this module remains as (a) the correctness
scaffold for the Pallas partition kernel, which implements the same
repartition with explicit VMEM tiles instead of scatters, and (b) the faster
option on CPU backends where scatters are cheap.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from kdtree_tpu.models.tree import KDTree, tree_spec
from kdtree_tpu.ops.build import spec_arrays

# side codes
_LEFT, _DIES, _RIGHT, _STAY = 0, 1, 2, 3


def presort_lists(points: jax.Array, consume: jax.Array, *, num_levels: int) -> jax.Array:
    """Run the presort level loop; returns the per-axis lists i32[D, N].

    ``consume[p]`` is the level at which position p's point is consumed as a
    node median (>= num_levels for positions that never die — e.g. bucket-leaf
    points, see :func:`kdtree_tpu.ops.bucket.build_bucket_presort`). Segments
    with no dying median at a level ("frozen" bucket segments) are left in
    place, preserving the invariant.
    """
    n, d = points.shape
    iota = jnp.arange(n, dtype=jnp.int32)

    # the only comparison sorts: one stable (coord, id) ordering per axis
    def sort_axis(col):
        _, pid = lax.sort((col, iota), num_keys=1, is_stable=True)
        return pid

    lists = jax.vmap(sort_axis, in_axes=1)(points)  # i32[D, N]

    def level_step(lvl, lists):
        # ---- position-space structure for this level (axis-independent) ----
        hole = consume < lvl
        dying = consume == lvl
        H = lax.cummax(jnp.where(hole, iota, -1))
        M = lax.cummax(jnp.where(dying, iota, -1))
        valid = consume <= lvl
        Q = lax.cummin(jnp.where(valid, iota, n)[::-1])[::-1]
        cq = consume[jnp.minimum(Q, n - 1)]
        seg_start = H + 1
        # the segment median is right of p while p is in the left half
        med = jnp.where(cq == lvl, Q, M)
        # frozen segment (no dying median this level, e.g. a finished bucket):
        # nearest dying-left lies before the segment start -> stay put
        side_pos = jnp.where(
            hole,
            _STAY,
            jnp.where(
                dying,
                _DIES,
                jnp.where(
                    cq == lvl, _LEFT, jnp.where(M >= seg_start, _RIGHT, _STAY)
                ),
            ),
        )

        # ---- map sides from positions to points via the split-axis list ----
        a = jnp.mod(lvl, d)
        alist = lax.dynamic_index_in_dim(lists, a, axis=0, keepdims=False)
        side_of_pid = jnp.zeros(n, jnp.int32).at[alist].set(side_pos)

        # ---- stable 3-way repartition of every axis list ------------------
        def repartition(lst):
            side = side_of_pid[lst]
            left = (side == _LEFT).astype(jnp.int32)
            right = (side == _RIGHT).astype(jnp.int32)
            exl = jnp.cumsum(left) - left  # exclusive
            exr = jnp.cumsum(right) - right
            rank_l = exl - exl[seg_start]
            rank_r = exr - exr[seg_start]
            new_pos = jnp.where(
                side == _LEFT,
                seg_start + rank_l,
                jnp.where(
                    side == _DIES,
                    med,
                    jnp.where(side == _RIGHT, med + 1 + rank_r, iota),
                ),
            )
            return jnp.zeros(n, jnp.int32).at[new_pos].set(lst)

        return jax.vmap(repartition)(lists)

    return lax.fori_loop(0, num_levels, level_step, lists)


def build_presort_impl(
    points: jax.Array,
    consume: jax.Array,
    all_nodes: jax.Array,
    all_medpos: jax.Array,
    node_axes: jax.Array,
    *,
    num_levels: int,
) -> KDTree:
    n, d = points.shape
    heap_size = node_axes.shape[0]
    lists = presort_lists(points, consume, num_levels=num_levels)

    # consumed points sit at their hole in every list; use list 0
    final = lists[0]
    node_point = jnp.full(heap_size, -1, dtype=jnp.int32)
    node_point = node_point.at[all_nodes].set(final[all_medpos])
    gathered = points[jnp.maximum(node_point, 0), node_axes]
    split_val = jnp.where(node_point >= 0, gathered, jnp.float32(0))
    return KDTree(points=points, node_point=node_point, split_val=split_val)


@functools.partial(jax.jit, static_argnames=("num_levels",))
def _build_presort_jit(points, consume, all_nodes, all_medpos, node_axes, num_levels):
    return build_presort_impl(
        points, consume, all_nodes, all_medpos, node_axes, num_levels=num_levels
    )


def build_presort(points: jax.Array) -> KDTree:
    """Jitted presort build; drop-in replacement for ``build_jit`` (identical
    trees — but see the module docstring: slower than build_jit on TPU)."""
    n, d = points.shape
    spec = tree_spec(n)
    consume, all_nodes, all_medpos, node_axes = spec_arrays(n, d)
    return _build_presort_jit(
        points, consume, all_nodes, all_medpos, node_axes, spec.num_levels
    )
