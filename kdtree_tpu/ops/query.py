"""Batched exact (k-)nearest-neighbor queries over the implicit tree.

The reference answers each query with host recursion
(``nearest``, ``kdtree_sequential.cpp:75-136``): descend into the near child,
then visit the far child only if the splitting-plane distance beats the best
distance found so far. Host recursion can't live under ``jit``, so here the
traversal is an **iterative DFS with an explicit bounded stack** inside a
``lax.while_loop`` (the depth bound is static — ``TreeSpec.num_levels``), and
the whole thing is ``vmap``-ped over the query batch: XLA runs all lanes in
lockstep until every query's stack drains.

Pruning is done at *pop* time: the far child is pushed together with its
splitting-plane bound ``d_axis^2``, and re-tested against the *current* k-th
best when popped. That is never weaker than the reference's recursive test at
``kdtree_sequential.cpp:118`` (the best distance can only have shrunk since the
push), so the result is exact.

Generalization over the reference: k neighbors (buffer insertion against the
running k-th best) instead of 1, and the point *index* is returned, which the
reference's MPI reduce famously loses (``kdtree_mpi.cpp:253``).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from kdtree_tpu.models.tree import KDTree, tree_spec


def _knn_one(get_node, heap_size: int, d: int, max_depth: int, k: int, q):
    """Exact k-NN for a single query; shapes static, vmap-friendly.

    ``get_node(node) -> (coords f32[D], id i32, traversable bool)`` abstracts
    the tree storage: the classic tree gathers ``points[node_point[node]]``,
    the global (distributed-build) tree reads a node-coordinate heap directly.
    ``traversable`` means the node's subtree may contain real points (for the
    classic tree that's just "slot occupied"; the global tree keeps +inf
    padding sentinels as non-takeable nodes whose *left* subtrees still hold
    real points). ``id < 0`` means the node's own point must not be taken.
    """
    stack_cap = max_depth + 2  # one far-sibling per level + the live path head

    stack_n = jnp.zeros(stack_cap, jnp.int32)
    stack_b = jnp.zeros(stack_cap, jnp.float32)
    sp = jnp.int32(1)  # root pre-pushed with bound 0
    best_d = jnp.full(k, jnp.inf, jnp.float32)
    best_i = jnp.full(k, -1, jnp.int32)

    def cond(state):
        return state[2] > 0

    def body(state):
        stack_n, stack_b, sp, best_d, best_i = state
        top = sp - 1
        node = stack_n[top]
        bound = stack_b[top]

        worst = jnp.max(best_d)
        node_c = jnp.minimum(node, heap_size - 1)
        p, pidx, traversable = get_node(node_c)
        visit = (node < heap_size) & traversable & (bound < worst)

        diff = q - p
        d2 = jnp.sum(diff * diff)

        # insert into the k-buffer, replacing the current worst
        wi = jnp.argmax(best_d)
        take = visit & (d2 < worst) & (pidx >= 0)
        best_d = jnp.where(take, best_d.at[wi].set(d2), best_d)
        best_i = jnp.where(take, best_i.at[wi].set(pidx), best_i)

        # cyclic axis = level % D, level from the heap index (clz trick)
        level = 31 - lax.clz(node + 1)
        ax = jnp.mod(level, d)
        delta = q[ax] - p[ax]
        go_right = (delta >= 0).astype(jnp.int32)  # kdtree_sequential.cpp:99-107
        near = 2 * node + 1 + go_right
        far = 2 * node + 2 - go_right

        # pop 1, push far (with its plane bound) then near (always visited)
        pushed_n = stack_n.at[top].set(far).at[top + 1].set(near)
        pushed_b = stack_b.at[top].set(delta * delta).at[top + 1].set(jnp.float32(0))
        stack_n = jnp.where(visit, pushed_n, stack_n)
        stack_b = jnp.where(visit, pushed_b, stack_b)
        sp = jnp.where(visit, sp + 1, sp - 1)

        return stack_n, stack_b, sp, best_d, best_i

    init = (stack_n, stack_b, sp, best_d, best_i)
    _, _, _, best_d, best_i = lax.while_loop(cond, body, init)
    # ascending by (distance, id) for determinism under ties
    best_d, best_i = lax.sort((best_d, best_i), num_keys=2, is_stable=True)
    return best_d, best_i


@functools.partial(jax.jit, static_argnames=("k", "max_depth"))
def _knn_batch(node_point, points, queries, k: int, max_depth: int):
    heap_size = node_point.shape[0]
    d = points.shape[1]

    def get_node(node):
        pidx = node_point[node]
        return points[jnp.maximum(pidx, 0)], pidx, pidx >= 0

    return jax.vmap(
        lambda q: _knn_one(get_node, heap_size, d, max_depth, k, q)
    )(queries)


@functools.partial(jax.jit, static_argnames=("k", "max_depth"))
def _knn_batch_nodes(node_coords, node_gid, node_traversable, queries, k: int,
                     max_depth: int):
    """k-NN over a node-coordinate heap (global-tree storage): node i's point
    coordinates live at node_coords[i], its global point id at node_gid[i]
    (-1 = padding sentinel or empty slot), and node_traversable[i] says
    whether the subtree can contain real points (static reachability)."""
    heap_size, d = node_coords.shape

    def get_node(node):
        return node_coords[node], node_gid[node], node_traversable[node]

    return jax.vmap(
        lambda q: _knn_one(get_node, heap_size, d, max_depth, k, q)
    )(queries)


def knn(tree: KDTree, queries: jax.Array, k: int = 1) -> Tuple[jax.Array, jax.Array]:
    """Exact k-NN for a batch of queries.

    Args:
      tree: built :class:`KDTree`.
      queries: f32[Q, D].
      k: neighbors per query (clamped to N).

    Returns:
      (dists_sq f32[Q, k], indices i32[Q, k]) ascending by distance. Squared
      distances, like the reference's internal metric; ``sqrt`` at the edge.
    """
    k = min(k, tree.n)
    max_depth = tree_spec(tree.n).num_levels
    return _knn_batch(tree.node_point, tree.points, queries, k, max_depth)


def nearest_neighbor(tree: KDTree, queries: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """1-NN convenience wrapper (the reference's only query mode).

    Returns (dist_sq f32[Q], index i32[Q]).
    """
    d2, idx = knn(tree, queries, k=1)
    return d2[:, 0], idx[:, 0]
