"""Shared k-buffer merge for bucketed tree queries.

Both bucket and Morton queries collect V candidate buckets, compute a dense
[V*B] distance block, and fold it into a per-query k-buffer. The merge's
invariants are subtle enough to keep in ONE place (cf. round-2 review):
+inf-masked lanes must never displace real candidates, and -1 padding ids
must lose ties to real ids, which the 2-key stable sort guarantees because
(inf, -1) sorts after (inf, real>=0) never happens — -1 < real, but only
distances decide unless equal, and equal-inf entries are all discardable.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def merge_topk(best_d, best_i, cand_d2, cand_gid, enabled):
    """Fold flat candidates (d2 f32[M], gid i32[M]) into the sorted-ascending
    k-buffer (best_d f32[k], best_i i32[k]); no-op when ``enabled`` is false.
    """
    k = best_d.shape[0]
    m = cand_d2.shape[0]
    kk = min(k, m)
    neg, sel = lax.top_k(-cand_d2, kk)
    all_d = jnp.concatenate([best_d, -neg])
    all_i = jnp.concatenate([best_i, cand_gid[sel]])
    all_d, all_i = lax.sort((all_d, all_i), num_keys=2, is_stable=True)
    best_d = jnp.where(enabled, all_d[:k], best_d)
    best_i = jnp.where(enabled, all_i[:k], best_i)
    return best_d, best_i


def scan_bucket_block(q, bucket_pts, bucket_gid, blist, bcnt, best_d, best_i):
    """Dense-scan the collected bucket list and merge into the k-buffer.

    q f32[D]; bucket_pts f32[NB, B, D] (+inf padding); bucket_gid i32[NB, B]
    (-1 padding); blist i32[V] bucket indices (-1 = empty slot); bcnt i32.
    """
    bsel = jnp.maximum(blist, 0)
    pts_v = bucket_pts[bsel]  # [V, B, D]
    gid_v = bucket_gid[bsel]  # [V, B]
    dv = q[None, None, :] - pts_v
    d2_v = jnp.sum(dv * dv, axis=-1)  # [V, B] (inf at padding)
    d2_v = jnp.where((blist >= 0)[:, None], d2_v, jnp.inf).reshape(-1)
    return merge_topk(best_d, best_i, d2_v, gid_v.reshape(-1), bcnt > 0)
