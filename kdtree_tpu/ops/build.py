"""Level-synchronous k-d tree construction.

The reference builds depth-first with one ``std::sort`` per node
(``kdtree_sequential.cpp:30-70``; O(N log^2 N) work, sequential). The TPU
re-expression processes **all segments of a level at once** with a single
``lax.sort`` over composite keys — the per-subtree OpenMP task parallelism the
course spec asked for (SURVEY.md C16) falls out as plain data parallelism, and
XLA maps it onto the chip.

Per level:
  1. ``segkey[p] = 2 * cumsum(dead)[p] - dead[p]`` — a monotone i32 that is
     constant within each live segment and unique for every dead (already
     consumed) position, so a stable sort by (segkey, coord, id) sorts within
     segments while leaving consumed medians pinned in place.
  2. one stable ``lax.sort`` of (segkey, axis coordinate, permutation).
  3. mark this level's (static) median positions dead.

The median positions and heap node ids per level are static functions of N
(``TreeSpec``), because the reference's exact-median split arithmetic
(``kdtree_sequential.cpp:51-56``) fixes every segment size in advance — that
choice is what makes the whole build jit-compile with static shapes, and we
keep it.

Note: the reference's sort call excludes the last element of each sub-range
(``kdtree_sequential.cpp:46-48``), a bug that corrupts low-D answers
(SURVEY.md §3.5). This build sorts full segments — the corrected semantics.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kdtree_tpu import obs
from kdtree_tpu.models.tree import KDTree, TreeSpec, node_levels, tree_spec

# The static structure arrays are O(N); embedding them as HLO constants bloats
# the program (a 16M-point build produced a >100 MB module that the remote
# TPU compiler rejected outright). So they are *runtime arguments* everywhere:
# spec_arrays() materializes them once per (n, d) on the default device, and
# the jitted/sharded builds thread them through as inputs.


@functools.lru_cache(maxsize=16)
def _position_arrays(n: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    spec = tree_spec(n)
    return (
        jnp.asarray(spec.consume_level),
        jnp.asarray(spec.all_nodes),
        jnp.asarray(spec.all_medpos),
    )


@functools.lru_cache(maxsize=32)
def _node_axes(heap_size: int, d: int) -> jax.Array:
    return jnp.asarray(node_levels(heap_size) % d)


def spec_arrays(n: int, d: int) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Device-resident structure arrays for a tree over n points in d dims:
    (consume_level i32[N], all_nodes i32[N], all_medpos i32[N],
    node_axes i32[H]). The O(N) position arrays are d-independent and cached
    per n; only the small node_axes array is per (heap_size, d)."""
    consume, all_nodes, all_medpos = _position_arrays(n)
    return consume, all_nodes, all_medpos, _node_axes(tree_spec(n).heap_size, d)


def build_impl(
    points: jax.Array,
    consume: jax.Array,
    all_nodes: jax.Array,
    all_medpos: jax.Array,
    node_axes: jax.Array,
    *,
    num_levels: int,
) -> KDTree:
    """Pure traceable build; structure arrays are inputs, not constants."""
    n, d = points.shape
    heap_size = node_axes.shape[0]

    # The dead set lives in *position* space and positions never move once
    # consumed, so deadness at level l is `consume < l` — no per-level scatter.
    # That lets the level loop be a fori_loop with a single lax.sort in the
    # compiled program: compile time is O(1) in tree depth (an unrolled loop
    # at 1M points took ~3min of XLA compile; this takes seconds).
    def level_step(lvl, perm):
        dead = (consume < lvl).astype(jnp.int32)
        csum = jnp.cumsum(dead)
        segkey = 2 * csum - dead
        axis = jnp.mod(lvl, d)
        coord = points[perm, axis]
        # Stable 3-key sort: segment id, coordinate, then original index —
        # the (coord, id) composite makes exact-median selection deterministic
        # under f32 ties (SURVEY.md §7 "hard parts").
        _, _, perm = lax.sort((segkey, coord, perm), num_keys=3, is_stable=True)
        return perm

    perm = lax.fori_loop(0, num_levels, level_step, jnp.arange(n, dtype=jnp.int32))

    # Consumed positions never move again, so one gather over the final
    # permutation recovers every node's point.
    node_point = jnp.full(heap_size, -1, dtype=jnp.int32)
    node_point = node_point.at[all_nodes].set(perm[all_medpos])

    gathered = points[jnp.maximum(node_point, 0), node_axes]
    split_val = jnp.where(node_point >= 0, gathered, jnp.float32(0))

    return KDTree(points=points, node_point=node_point, split_val=split_val)


def build(points: jax.Array, spec: TreeSpec | None = None) -> KDTree:
    """Build the implicit-array k-d tree over ``points`` (f32[N, D]).

    Traceable under jit/shard_map. NOTE: when traced, the structure arrays
    become program constants — fine for small/medium N; for large N prefer
    :func:`build_jit`, which passes them as runtime arguments.
    """
    n, d = points.shape
    if spec is None:
        spec = tree_spec(n)
    assert spec.n == n
    consume, all_nodes, all_medpos, node_axes = spec_arrays(n, d)
    return build_impl(
        points, consume, all_nodes, all_medpos, node_axes, num_levels=spec.num_levels
    )


@functools.partial(jax.jit, static_argnames=("num_levels",))
def _build_jit_impl(points, consume, all_nodes, all_medpos, node_axes, num_levels):
    return build_impl(
        points, consume, all_nodes, all_medpos, node_axes, num_levels=num_levels
    )


def build_jit(points: jax.Array) -> KDTree:
    """Jitted build; structure arrays enter as device inputs (no giant HLO
    constants), cached per (N, D)."""
    n, d = points.shape
    spec = tree_spec(n)
    consume, all_nodes, all_medpos, node_axes = spec_arrays(n, d)
    if not obs.is_tracer(points):
        obs.count_build("tree", n)
    return _build_jit_impl(
        points, consume, all_nodes, all_medpos, node_axes, spec.num_levels
    )


# ---------------------------------------------------------------------------
# Host-side validation (test / debug utility — the working replacement for the
# reference's dead tree printers, Utility.cpp:21-63).
# ---------------------------------------------------------------------------


def validate_invariants(tree: KDTree) -> None:
    """Assert the k-d invariant on every node, host-side with NumPy.

    For node i at level l with axis a = l % D: every point in the left subtree
    has coord[a] <= split_val[i] and every point in the right subtree has
    coord[a] >= split_val[i]. (Ties may land on either side of the median under
    the deterministic (coord, id) composite sort, so the right-side comparison
    is >=; the reference's unstable std::sort has the same latitude.)

    Also checks that node_point is a permutation: every point appears exactly
    once.

    Fully vectorized (one bottom-up subtree-min/max sweep over the heap plus
    one check per level), O(H * D) time and memory — validates a 1M-point
    tree in seconds where the old per-node DFS was O(heap * subtree). The
    working replacement for the reference's dead printers (Utility.cpp:21-63).
    """
    pts = np.asarray(tree.points)  # kdt-lint: disable=KDT201 host-side debug validator — fetching the tree IS the job
    npnt = np.asarray(tree.node_point)  # kdt-lint: disable=KDT201 host-side debug validator
    sval = np.asarray(tree.split_val)  # kdt-lint: disable=KDT201 host-side debug validator
    d = pts.shape[1]
    # heap_size is max occupied node + 1; pad to a full heap so every level
    # slice below is complete (padding slots are simply unoccupied)
    num_levels = tree.heap_size.bit_length()
    h = (1 << num_levels) - 1
    npnt = np.concatenate([npnt, np.full(h - tree.heap_size, -1, npnt.dtype)])
    sval = np.concatenate([sval, np.zeros(h - tree.heap_size, sval.dtype)])

    used = npnt[npnt >= 0]
    assert used.size == tree.n, f"{used.size} nodes for {tree.n} points"
    assert np.array_equal(np.sort(used), np.arange(tree.n)), "node_point is not a permutation"

    # bottom-up subtree coordinate ranges: submin/submax[i, a] over subtree(i)
    occupied = npnt >= 0
    own = pts[np.maximum(npnt, 0)]
    submin = np.where(occupied[:, None], own, np.inf)
    submax = np.where(occupied[:, None], own, -np.inf)
    for lvl in range(num_levels - 2, -1, -1):
        lo, hi = (1 << lvl) - 1, (1 << (lvl + 1)) - 1
        c = np.s_[2 * lo + 1 : 2 * hi + 1]  # both children levels, contiguous
        kid_min = np.minimum(submin[c][0::2], submin[c][1::2])
        kid_max = np.maximum(submax[c][0::2], submax[c][1::2])
        submin[lo:hi] = np.minimum(submin[lo:hi], kid_min)
        submax[lo:hi] = np.maximum(submax[lo:hi], kid_max)

    for lvl in range(num_levels):
        lo, hi = (1 << lvl) - 1, min((1 << (lvl + 1)) - 1, h)
        a = lvl % d
        occ = occupied[lo:hi]
        if not occ.any():
            continue
        ids = np.nonzero(occ)[0] + lo
        assert np.array_equal(
            sval[ids], pts[npnt[ids], a]
        ), f"split_val mismatch at level {lvl}"
        left, right = 2 * ids + 1, 2 * ids + 2
        inb = left < h  # leaves of a full heap have no child slots
        if inb.any():
            li, ri, si = left[inb], right[inb], sval[ids[inb]]
            bad_l = submax[li, a] > si
            assert not bad_l.any(), f"left violation at node {li[bad_l][:5]}"
            bad_r = submin[ri, a] < si
            assert not bad_r.any(), f"right violation at node {ri[bad_r][:5]}"
