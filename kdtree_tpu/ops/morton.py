"""Morton-order bucket tree: the TPU-native spatial index.

The reference's build is "recursively sort each segment by a cycling axis"
(``kdtree_sequential.cpp:30-70``) — inherently one pass per tree level, ~24
full-array sorts at 16M points even after level-synchronous batching
(:mod:`kdtree_tpu.ops.build`). A TPU wants the opposite shape: ONE big sort
and then only dense reductions. This is the classic linear-BVH construction
(cf. Karras-style LBVH builders in PAPERS.md/SNIPPETS.md, re-expressed in
XLA ops):

1. quantize each axis to ``bits`` integer cells and interleave into a Morton
   code — spatially close points get numerically close codes;
2. ONE stable ``lax.sort`` by (code, id), carrying the coordinate columns as
   sort payload (measured: payload carry is ~free next to the key compare,
   and it avoids a 16M random gather afterwards);
3. cut the sorted order into fixed-size buckets of B points (B ~ one VPU
   tile); bucket AABBs via masked min/max reductions;
4. an implicit complete binary tree over the (pow2-padded) buckets, parent
   AABB = union of children — log2 levels of shrinking reductions, ~2x the
   leaf-AABB bytes in total traffic.

Build cost at 16M x 3D is one sort + a few dense passes — measured ~0.4s on
a v5e chip vs ~5.8s for the level-synchronous sort build and ~122s for the
reference on a Xeon core.

Queries stay EXACT: the AABB distance

    lb(q, node) = sum_a max(lo[a] - q[a], q[a] - hi[a], 0)^2

is a true lower bound on the distance to any point in the node's subtree
(tighter than the k-d splitting-plane bound the reference prunes with,
``kdtree_sequential.cpp:118``), so best-first DFS with "visit iff lb < worst
of the current k-buffer" can never miss a true neighbor. Leaf visits are
dense [B, D] distance blocks — VPU work, batched V buckets at a time like
:func:`kdtree_tpu.ops.bucket.bucket_knn`'s phase B.

The tree differs structurally from the reference's median-split k-d tree
(that one is kept, bit-exact, in :mod:`kdtree_tpu.ops.build` /
:mod:`kdtree_tpu.ops.bucket` for parity testing); results agree because both
are exact — validated against the brute-force oracle.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from kdtree_tpu import obs
from kdtree_tpu.ops.topk import scan_bucket_block
from kdtree_tpu.utils.guards import check_rows_fit_i32

# bucket-occupancy histogram bounds (points per bucket) — spans both the
# single-chip default cap (256) and the forest cap (128); the +Inf bucket
# catches anything a future cap raises
_OCC_BUCKETS = (0, 8, 16, 32, 64, 96, 128, 192, 256, 512)

DEFAULT_BUCKET = 256  # two 128-lane vregs per bucket row. Measured at the
# north-star query shape (16M pts, 1M k=16 queries, fused Pallas scan):
# 256 beats 128 by 1.54x (87k vs 57k q/s — fewer, larger DMAs against the
# same total bytes) and 512 regresses 4.5x (per-bucket fold cost dominates).
_QUERY_COLLECT = 8  # buckets per dense-scan round in the query loop


@jax.tree_util.register_pytree_node_class
class MortonTree:
    """Implicit complete AABB tree over Morton-sorted point buckets.

    Storage (pytree leaves, device-resident):
      node_lo / node_hi  f32[H, D]   heap-indexed AABBs; node i has children
                                     2i+1 / 2i+2; leaves are the last NBP
                                     slots and map to bucket (i - (NBP-1))
      bucket_pts         f32[NBP, B, D]  bucket contents (+inf padding)
      bucket_gid         i32[NBP, B]     original point ids (-1 padding)
    Static aux: n_real, num_levels (= log2 NBP, max traversal depth).
    """

    def __init__(self, node_lo, node_hi, bucket_pts, bucket_gid, n_real, num_levels):
        self.node_lo = node_lo
        self.node_hi = node_hi
        self.bucket_pts = bucket_pts
        self.bucket_gid = bucket_gid
        self.n_real = n_real
        self.num_levels = num_levels

    @property
    def dim(self) -> int:
        return self.bucket_pts.shape[2]

    @property
    def num_buckets(self) -> int:
        return self.bucket_pts.shape[0]

    @property
    def bucket_size(self) -> int:
        return self.bucket_pts.shape[1]

    @property
    def heap_size(self) -> int:
        return self.node_lo.shape[0]

    def tree_flatten(self):
        return (
            (self.node_lo, self.node_hi, self.bucket_pts, self.bucket_gid),
            (self.n_real, self.num_levels),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    def __repr__(self):
        return (
            f"MortonTree(n={self.n_real}, buckets={self.num_buckets}x"
            f"{self.bucket_size}, dim={self.dim})"
        )


def default_bits(dim: int) -> int:
    """The shared quantization-bit rule: the most bits per axis that still
    fit a u32 interleaved code for this dimensionality, capped at 16. One
    definition — a tree built with one rule and queried through a planner
    using another would silently mismatch Hilbert sort vs tree geometry."""
    return max(1, min(32 // max(dim, 1), 16))


def morton_codes(
    points: jax.Array, bits: int, lo: jax.Array | None = None,
    hi: jax.Array | None = None,
) -> jax.Array:
    """u32 Morton (Z-order) codes; ``bits`` quantization bits per axis.

    Normalization defaults to the data's own per-axis min/max so clustered
    inputs (the 128-D grading generator's Gaussian blobs analog) still spread
    over the full code range. Pass explicit ``lo``/``hi`` (broadcastable to
    [D]) when several devices must quantize on the SAME grid — e.g. the
    sample-sort splitters of the global Morton engine, where codes from
    different devices are compared against shared splitters.
    """
    n, d = points.shape
    finite = jnp.isfinite(points)
    if lo is None:
        lo = jnp.min(jnp.where(finite, points, jnp.inf), axis=0)
    else:
        lo = jnp.broadcast_to(jnp.asarray(lo, points.dtype), (d,))
    if hi is None:
        hi = jnp.max(jnp.where(finite, points, -jnp.inf), axis=0)
    else:
        hi = jnp.broadcast_to(jnp.asarray(hi, points.dtype), (d,))
    scale = jnp.where(hi > lo, (hi - lo), jnp.float32(1))
    t = (points - lo) / scale * (1 << bits)
    # +inf padding rows (sharded callers pad blocks with inf sentinels) land
    # in the top cell so they sort to the end; NaN-safe via the finite test
    t = jnp.where(jnp.all(finite, axis=1)[:, None], t, jnp.float32(1 << bits))
    # clip BEFORE the cast: float->uint32 of out-of-range values (possible
    # when an explicit lo/hi grid is narrower than the data) is
    # implementation-defined in XLA, so clamp while still in float
    cells = jnp.clip(t, 0.0, float((1 << bits) - 1)).astype(jnp.uint32)
    code = jnp.zeros(n, jnp.uint32)
    for b in range(bits):  # static unroll: bits*d or-shift ops
        for a in range(d):
            # u32 shifts >= 32 are implementation-defined in XLA; axes whose
            # interleave slot falls outside the code simply don't contribute
            # (correctness is unaffected — any point order yields a valid
            # tree — only locality degrades, and only for d > 32)
            if b * d + a < 32:
                code = code | (((cells[:, a] >> b) & 1) << (b * d + a))
    return code


@functools.lru_cache(maxsize=32)
def _tree_shape(n: int, bucket_cap: int) -> Tuple[int, int, int]:
    """(num_buckets_padded, heap_size, num_levels) for n points."""
    nb = max(1, -(-n // bucket_cap))
    nbp = 1 << (nb - 1).bit_length()
    return nbp, 2 * nbp - 1, (nb - 1).bit_length()


def build_morton_impl(points: jax.Array, *, bucket_cap: int, bits: int) -> MortonTree:
    n, d = points.shape
    check_rows_fit_i32(n, "point set")  # gids below are int32
    nbp, heap_size, num_levels = _tree_shape(n, bucket_cap)
    code = morton_codes(points, bits)
    gid = jnp.arange(n, dtype=jnp.int32)
    # one sort; coordinate columns ride as payload (stable => gid tie-break)
    ops = lax.sort(
        (code, gid, *(points[:, a] for a in range(d))), num_keys=1, is_stable=True
    )
    sgid = ops[1]
    cols = ops[2:]

    pad = nbp * bucket_cap - n
    sgid = jnp.concatenate([sgid, jnp.full(pad, -1, jnp.int32)])
    spts = jnp.stack(
        [jnp.concatenate([c, jnp.full(pad, jnp.inf, c.dtype)]) for c in cols], axis=1
    )

    bucket_pts = spts.reshape(nbp, bucket_cap, d)
    bucket_gid = sgid.reshape(nbp, bucket_cap)
    valid = (bucket_gid >= 0)[:, :, None]

    # leaf AABBs (masked so padding rows never loosen a bound)
    leaf_lo = jnp.min(jnp.where(valid, bucket_pts, jnp.inf), axis=1)
    leaf_hi = jnp.max(jnp.where(valid, bucket_pts, -jnp.inf), axis=1)

    # implicit complete tree, bottom-up; level arrays halve each round
    levels_lo = [leaf_lo]
    levels_hi = [leaf_hi]
    while levels_lo[0].shape[0] > 1:
        lo2 = levels_lo[0].reshape(-1, 2, d)
        hi2 = levels_hi[0].reshape(-1, 2, d)
        levels_lo.insert(0, jnp.min(lo2, axis=1))
        levels_hi.insert(0, jnp.max(hi2, axis=1))
    node_lo = jnp.concatenate(levels_lo, axis=0)
    node_hi = jnp.concatenate(levels_hi, axis=0)
    return MortonTree(
        node_lo=node_lo,
        node_hi=node_hi,
        bucket_pts=bucket_pts,
        bucket_gid=bucket_gid,
        n_real=n,
        num_levels=num_levels,
    )


@functools.partial(jax.jit, static_argnames=("bucket_cap", "bits"))
def _build_morton_jit(points, bucket_cap, bits):
    return build_morton_impl(points, bucket_cap=bucket_cap, bits=bits)


# Measured single-chip capacity cliff (v5e, 16 GiB HBM): the 2^27 x 3D build
# works (114.6M pts/s); 2^28 crashes the XLA compile — and a crashed remote
# compile can wedge the device tunnel for HOURS (round 3 lost its driver
# bench window to exactly this). The build's peak working set is ~3 live
# copies of the (d+2)-column sort operand (input columns + sort output +
# the padded bucket/heap arrays), so the guard is bytes-based, not an n
# constant: 3*(d+2)*4 bytes/point. At the measured cliff (2^27 x 3D ~ 8.1
# GiB OK, 2^28 x 3D ~ 16.1 GiB crash) a 12 GiB budget separates the two
# with margin. Override with KDTREE_TPU_MAX_BUILD_BYTES for chips with
# more HBM.
_MAX_BUILD_BYTES = 12 << 30


class BuildCapacityError(ValueError):
    """A single-chip build would exceed the device HBM budget.

    A distinct type so the CLI can turn exactly this condition into a crisp
    stderr + exit-code failure (C10) without masking unrelated ValueErrors,
    and so routing layers can fall back to a non-materializing path."""


def check_build_capacity(n: int, d: int, backend: str | None = None,
                         budget: int | None = None) -> None:
    """Raise BuildCapacityError (instead of letting XLA compile-crash) when a
    single-chip Morton build would exceed the device memory budget."""
    import os

    if backend is None:
        backend = jax.default_backend()
    if backend != "tpu":
        return  # CPU/GPU hosts page; only the TPU compile hard-crashes
    if budget is None:
        raw = os.environ.get("KDTREE_TPU_MAX_BUILD_BYTES")
        try:
            budget = int(raw) if raw is not None else _MAX_BUILD_BYTES
        except ValueError:
            raise ValueError(
                f"KDTREE_TPU_MAX_BUILD_BYTES must be an integer byte count, "
                f"got {raw!r} (e.g. 17179869184 for 16 GiB)"
            ) from None
    need = 3 * n * (d + 2) * 4
    if need > budget:
        raise BuildCapacityError(
            f"single-chip Morton build of n={n}, d={d} needs ~{need >> 30} "
            f"GiB working set (> {budget >> 30} GiB budget); shard it with "
            "the global-morton engine (build_global_morton) instead, or "
            "raise KDTREE_TPU_MAX_BUILD_BYTES if this chip has more HBM"
        )


def build_morton(
    points: jax.Array, bucket_cap: int = DEFAULT_BUCKET, bits: int | None = None
) -> MortonTree:
    """Build the Morton bucket tree (jitted). ``bits`` defaults to the most
    that fit a u32 code for this dimensionality (10 at D=3)."""
    n, d = points.shape
    check_build_capacity(n, d)
    if bits is None:
        bits = default_bits(d)
    else:
        # user-supplied bits are clamped by the same rule: more than
        # default_bits(d) cannot fit the u32 interleaved code anyway
        bits = max(1, min(bits, default_bits(d)))
    tree = _build_morton_jit(points, bucket_cap, bits)
    if not obs.is_tracer(points):
        obs.count_build("morton", n)
        if obs.enabled() and not obs.is_tracer(tree.bucket_gid):
            # enabled-gated occupancy: dispatch a tiny [NBP] reduction now
            # (async, ~free) and DEFER the host fetch to report time so no
            # sync lands inside the build hot path
            import numpy as _np

            occ_dev = jnp.sum((tree.bucket_gid >= 0).astype(jnp.int32), axis=1)
            hist = obs.get_registry().histogram(
                "kdtree_bucket_occupancy", buckets=_OCC_BUCKETS
            )
            obs.defer(lambda: hist.observe_array(_np.asarray(occ_dev)))
    return tree


def morton_view(
    points: jax.Array,
    gid: jax.Array | None = None,
    n_real: int | None = None,
    bucket_cap: int = DEFAULT_BUCKET,
    bits: int | None = None,
) -> MortonTree:
    """A Morton bucket tree over another index's point storage — the
    dense-serving view that lets ANY checkpointed tree type answer big
    query batches with the tiled engine (the same per-device trick
    ``parallel.global_morton._local_forest_jit`` applies across a device
    axis, single-tree form).

    ``gid`` maps row positions to the source index's original point ids
    (required when ``points`` is padded storage, e.g. a BucketKDTree's
    flattened buckets: +inf rows build into inf-leaves the tiled scan
    prunes, and their slots map to id -1). ``n_real`` overrides the real
    point count for density planning when ``points`` includes padding;
    with ``gid`` given and ``n_real`` omitted it is derived as the count
    of real ids (one host sync) — defaulting to the padded row count
    would silently break the downstream ``k = min(k, n_real)`` clamp.
    """
    if gid is not None and n_real is None:
        n_real = int((gid >= 0).sum())
    tree = build_morton(points, bucket_cap=bucket_cap, bits=bits)
    if gid is not None:
        bg = jnp.where(
            tree.bucket_gid >= 0, gid[jnp.maximum(tree.bucket_gid, 0)], -1
        )
        tree = MortonTree(
            tree.node_lo, tree.node_hi, tree.bucket_pts, bg,
            n_real=n_real if n_real is not None else tree.n_real,
            num_levels=tree.num_levels,
        )
    elif n_real is not None and n_real != tree.n_real:
        tree = MortonTree(
            tree.node_lo, tree.node_hi, tree.bucket_pts, tree.bucket_gid,
            n_real=n_real, num_levels=tree.num_levels,
        )
    return tree


# cached on the owner after the first BuildCapacityError: an over-budget
# checkpoint's failure is a property of its shape, so retrying it on every
# dense batch would re-materialize make_inputs()' flattened bucket-points
# copy (the very allocation the budget guard exists to prevent) just to
# raise again. A distinct sentinel (not None) so "never tried" and
# "tried and over budget" stay distinguishable.
_BUDGET_EXCEEDED = object()


def serving_view(owner, make_inputs, cache_attr: str = "_morton_view"):
    """Cache-or-build a dense-serving :func:`morton_view` on ``owner``.

    The shared shape of every "serve a checkpointed index with the tiled
    engine" trick (classic/bucket trees in the CLI, the mesh-free forest
    path): build the view once from ``make_inputs() ->`` ``morton_view``
    kwargs, cache it on the object, and return ``None`` when the view
    would exceed the single-chip HBM budget (``BuildCapacityError``) so
    the caller falls back to its memory-lean engine instead of surfacing
    a confusing rebuild error for a query that used to work. The
    over-budget outcome is cached too: later batches return None without
    re-running ``make_inputs`` (whose flattened copy is the expensive
    part)."""
    view = getattr(owner, cache_attr, None)
    if view is _BUDGET_EXCEEDED:
        return None
    if view is not None:
        return view
    try:
        view = morton_view(**make_inputs())
    except BuildCapacityError:
        setattr(owner, cache_attr, _BUDGET_EXCEEDED)
        return None
    setattr(owner, cache_attr, view)
    return view


# ---------------------------------------------------------------------------
# query
# ---------------------------------------------------------------------------


def _bbox_d2(q, lo, hi):
    """Exact lower bound on |q - p|^2 over any p inside [lo, hi]."""
    gap = jnp.maximum(jnp.maximum(lo - q, q - hi), 0.0)
    return jnp.sum(gap * gap)


def _morton_knn_one(tree: MortonTree, k: int, q):
    nbp = tree.num_buckets
    first_leaf = nbp - 1
    B = tree.bucket_size
    V = _QUERY_COLLECT
    # worst case the stack holds both children at every level
    stack_cap = 2 * tree.num_levels + 2

    best_d = jnp.full(k, jnp.inf, jnp.float32)
    best_i = jnp.full(k, -1, jnp.int32)

    stack_n = jnp.zeros(stack_cap, jnp.int32)
    stack_b = jnp.zeros(stack_cap, jnp.float32)
    sp = jnp.int32(1)  # root pre-pushed with bound 0

    def outer_cond(state):
        return state[2] > 0

    def outer_body(state):
        stack_n, stack_b, sp, best_d, best_i = state
        blist = jnp.full(V, -1, jnp.int32)

        def inner_cond(s):
            _, _, sp, _, _, _, bcnt = s
            return (sp > 0) & (bcnt < V)

        def inner_body(s):
            stack_n, stack_b, sp, best_d, best_i, blist, bcnt = s
            top = sp - 1
            node = stack_n[top]
            bound = stack_b[top]
            worst = jnp.max(best_d)
            visit = bound < worst
            is_leaf = visit & (node >= first_leaf)
            is_internal = visit & (node < first_leaf)
            sp = sp - 1  # pop

            # internal: push children ordered near-last (visited first),
            # each only if its own bound already beats the current worst
            c1 = 2 * node + 1
            c2 = 2 * node + 2
            ci = jnp.minimum(jnp.stack([c1, c2]), tree.heap_size - 1)
            bd = jax.vmap(lambda i: _bbox_d2(q, tree.node_lo[i], tree.node_hi[i]))(ci)
            swap = bd[0] < bd[1]  # push nearer child last
            first_c = jnp.where(swap, c2, c1)
            first_b = jnp.where(swap, bd[1], bd[0])
            second_c = jnp.where(swap, c1, c2)
            second_b = jnp.where(swap, bd[0], bd[1])
            push1 = is_internal & (first_b < worst)
            stack_n = jnp.where(push1, stack_n.at[sp].set(first_c), stack_n)
            stack_b = jnp.where(push1, stack_b.at[sp].set(first_b), stack_b)
            sp = jnp.where(push1, sp + 1, sp)
            push2 = is_internal & (second_b < worst)
            stack_n = jnp.where(push2, stack_n.at[sp].set(second_c), stack_n)
            stack_b = jnp.where(push2, stack_b.at[sp].set(second_b), stack_b)
            sp = jnp.where(push2, sp + 1, sp)

            collect = is_leaf
            blist = jnp.where(collect, blist.at[bcnt].set(node - first_leaf), blist)
            bcnt = jnp.where(collect, bcnt + 1, bcnt)
            return stack_n, stack_b, sp, best_d, best_i, blist, bcnt

        stack_n, stack_b, sp, best_d, best_i, blist, bcnt = lax.while_loop(
            inner_cond, inner_body,
            (stack_n, stack_b, sp, best_d, best_i, blist, jnp.int32(0)),
        )
        best_d, best_i = scan_bucket_block(
            q, tree.bucket_pts, tree.bucket_gid, blist, bcnt, best_d, best_i
        )
        return stack_n, stack_b, sp, best_d, best_i

    init = (stack_n, stack_b, sp, best_d, best_i)
    _, _, _, best_d, best_i = lax.while_loop(outer_cond, outer_body, init)
    return lax.sort((best_d, best_i), num_keys=2, is_stable=True)


@functools.partial(jax.jit, static_argnames=("k", "chunk"))
def _morton_knn_batch(tree, queries, k: int, chunk: int):
    nq = queries.shape[0]
    pad = (-nq) % chunk
    if pad:
        queries = jnp.concatenate(
            [queries, jnp.zeros((pad, queries.shape[1]), queries.dtype)], axis=0
        )
    chunks = queries.reshape(-1, chunk, queries.shape[1])

    def one_chunk(_, qs):
        return None, jax.vmap(lambda q: _morton_knn_one(tree, k, q))(qs)

    _, (d2, idx) = lax.scan(one_chunk, None, chunks)
    return d2.reshape(-1, k)[:nq], idx.reshape(-1, k)[:nq]


def morton_knn(
    tree: MortonTree, queries: jax.Array, k: int = 1, chunk: int = 4096
) -> Tuple[jax.Array, jax.Array]:
    """Exact k-NN against a Morton bucket tree (per-query best-first DFS).

    Returns (dists_sq f32[Q, k], indices i32[Q, k]) ascending. Queries run
    in fixed-size chunks, one device program per chunk: bounded memory,
    local lockstep divergence, and no single program long enough to trip
    an execution watchdog. The chunk loop is ASYNC by construction —
    no host fetch between dispatches, so the per-chunk programs queue
    back-to-back on device and the single sync happens at the caller's
    first use of the concatenated result (the driver bench's
    ``sparse DFS`` extra and ``scripts/measure_sparse_dfs.py`` record
    the measured q/s and the per-chunk-synced contrast). For large Q
    prefer :func:`kdtree_tpu.ops.tile_query.morton_knn_tiled` (dense,
    orders of magnitude faster at scale); this DFS engine wins for
    small/sparse batches.
    """
    k = min(k, tree.n_real)
    q = queries.shape[0]
    if not obs.is_tracer(queries):
        obs.count_query("morton", q)
    chunk = min(chunk, max(q, 1))
    if q <= chunk:
        return _morton_knn_batch(tree, queries, k, chunk)
    # pad to a chunk multiple so every slice reuses ONE compiled program
    # (a ragged tail shape would recompile the whole DFS kernel)
    pad = (-q) % chunk
    if pad:
        queries = jnp.concatenate(
            [queries, jnp.broadcast_to(queries[-1], (pad, queries.shape[1]))],
            axis=0,
        )
    parts = [
        _morton_knn_batch(tree, queries[i : i + chunk], k, chunk)
        for i in range(0, queries.shape[0], chunk)
    ]
    return (
        jnp.concatenate([p[0] for p in parts], axis=0)[:q],
        jnp.concatenate([p[1] for p in parts], axis=0)[:q],
    )
