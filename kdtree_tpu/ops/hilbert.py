"""Hilbert-curve codes: the jump-free space-filling order.

Morton (Z-order) codes are cheap but discontinuous — the Z-curve teleports
across the domain at high-bit boundaries, so a window of consecutive codes
can span almost the whole space. That breaks the tiled query engine
(:mod:`kdtree_tpu.ops.tile_query`), whose whole premise is "consecutive
sorted queries are spatial neighbors": a tile straddling a Z-jump gets a
domain-sized AABB and has to scan every bucket (measured: p99 candidate
count 2051 vs median 76 on uniform data).

The Hilbert curve has no jumps: consecutive cells along the curve are
always face-adjacent, so ANY contiguous window of the sorted order is a
connected region with diameter ~ (window/total)^(1/D). Encoding uses
Skilling's transpose algorithm (public domain, Skilling 2004 "Programming
the Hilbert curve"): per-axis cell coordinates are transformed in place by
``bits`` rounds of conditional exchange/invert against axis 0, then
Gray-decoded — all u32 bit ops, vectorized over N points, statically
unrolled over ``bits * D`` rounds (no data-dependent control flow).

The curve property is pinned by tests: enumerating every cell of a small
grid and sorting by code must walk cells with L1 steps of exactly 1
(``tests/test_hilbert.py``) — a convention-independent correctness oracle.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _quantize(points: jax.Array, bits: int, lo, hi) -> list[jax.Array]:
    """Per-axis u32 cell coords in [0, 2^bits); same conventions as
    :func:`kdtree_tpu.ops.morton.morton_codes` (data-derived bounds by
    default, non-finite rows to the top cell, float-side clip)."""
    n, d = points.shape
    finite = jnp.isfinite(points)
    if lo is None:
        lo = jnp.min(jnp.where(finite, points, jnp.inf), axis=0)
    else:
        lo = jnp.broadcast_to(jnp.asarray(lo, points.dtype), (d,))
    if hi is None:
        hi = jnp.max(jnp.where(finite, points, -jnp.inf), axis=0)
    else:
        hi = jnp.broadcast_to(jnp.asarray(hi, points.dtype), (d,))
    scale = jnp.where(hi > lo, (hi - lo), jnp.asarray(1, points.dtype))
    t = (points - lo) / scale * (1 << bits)
    t = jnp.where(jnp.all(finite, axis=1)[:, None], t, jnp.float32(1 << bits))
    cells = jnp.clip(t, 0.0, float((1 << bits) - 1)).astype(jnp.uint32)
    return [cells[:, a] for a in range(d)]


def hilbert_codes(
    points: jax.Array,
    bits: int,
    lo: jax.Array | None = None,
    hi: jax.Array | None = None,
) -> jax.Array:
    """u32 Hilbert indices; ``bits`` quantization bits per axis.

    Requires ``bits * D <= 32`` (callers clamp bits the same way the Morton
    path does). Higher code = later on the curve; consecutive codes are
    face-adjacent cells.
    """
    n, d = points.shape
    if bits * d > 32:
        # order by the leading axes only (same graceful degradation as
        # morton_codes for D > 32: ordering quality drops, correctness of
        # consumers never depends on WHICH order, only that one exists)
        # kdt-lint: disable=KDT301 inverse map (how many AXES fit a u32 at
        # this bits), not the bits-per-axis rule default_bits owns
        d = max(32 // max(bits, 1), 1)
        points = points[:, :d]
    x = _quantize(points, bits, lo, hi)

    if d == 1:
        return x[0]

    # Skilling: axes -> transposed Hilbert (in place, MSB down)
    q = 1 << (bits - 1)
    while q > 1:
        p = jnp.uint32(q - 1)
        for i in range(d):
            high = (x[i] & q) != 0
            # invert low bits of x[0]      OR exchange low bits x[0]<->x[i]
            t = (x[0] ^ x[i]) & p
            x0_inv = x[0] ^ p
            x[0] = jnp.where(high, x0_inv, x[0] ^ t)
            if i:
                x[i] = jnp.where(high, x[i], x[i] ^ t)
        q >>= 1

    # Gray decode
    for i in range(1, d):
        x[i] = x[i] ^ x[i - 1]
    t = jnp.zeros(n, jnp.uint32)
    q = 1 << (bits - 1)
    while q > 1:
        t = jnp.where((x[d - 1] & q) != 0, t ^ jnp.uint32(q - 1), t)
        q >>= 1
    for i in range(d):
        x[i] = x[i] ^ t

    # interleave transposed bits: index bit (b*D-1) is bit (bits-1) of x[0]
    code = jnp.zeros(n, jnp.uint32)
    for b in range(bits):
        for i in range(d):
            pos = (bits - 1 - b) * d + (d - 1 - i)
            code = code | (((x[i] >> (bits - 1 - b)) & 1) << pos)
    return code
