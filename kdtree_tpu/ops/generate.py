"""Problem generation.

The reference generates the point cloud host-side with ``std::mt19937`` +
``uniform_real_distribution<float>(-100, 100)`` (``Utility.cpp:6-18``), with
queries as the last ``num_queries`` rows (``kdtree_sequential.cpp:157``). Its
MPI variant regenerates only the local shard via ``random.discard``
(``kdtree_mpi.cpp:19-41``) — a communication-avoidance trick.

The TPU-native path uses JAX's counter-based threefry PRNG: generation happens
on device, and shard-local generation is free — each device fills its own rows
of the same deterministic global array, the counter-based analog of the
reference's ``discard`` trick. Bit-exact replay of the reference's mt19937
stream (for golden parity against the reference binary) lives in
:mod:`kdtree_tpu.native` instead.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

COORD_MIN = -100.0  # Utility.cpp:8
COORD_MAX = 100.0


def generate_problem(
    seed: int, dim: int, num_points: int, num_queries: int = 10, dtype=jnp.float32
) -> Tuple[jax.Array, jax.Array]:
    """Generate ``(points[num_points, dim], queries[num_queries, dim])``.

    Same contract as the reference (seeded, uniform in [-100, 100), queries
    drawn after/apart from the points) but with the threefry PRNG so the same
    seed gives the same problem on any device count or mesh layout.
    """
    kp, kq = jax.random.split(jax.random.key(seed), 2)
    points = jax.random.uniform(
        kp, (num_points, dim), dtype=dtype, minval=COORD_MIN, maxval=COORD_MAX
    )
    queries = jax.random.uniform(
        kq, (num_queries, dim), dtype=dtype, minval=COORD_MIN, maxval=COORD_MAX
    )
    return points, queries


def generate_queries(
    seed: int, dim: int, num_queries: int = 10, dtype=jnp.float32
) -> jax.Array:
    """Only the query block of :func:`generate_problem` — bit-identical to its
    second return value, without materializing the N points (the query key is
    independent of num_points by construction)."""
    _, kq = jax.random.split(jax.random.key(seed), 2)
    return jax.random.uniform(
        kq, (num_queries, dim), dtype=dtype, minval=COORD_MIN, maxval=COORD_MAX
    )


def generate_clustered(
    seed: int,
    dim: int,
    num_points: int,
    num_queries: int = 10,
    num_clusters: int = 8,
    stddev: float = 2.0,
    dtype=jnp.float32,
) -> Tuple[jax.Array, jax.Array]:
    """Gaussian-mixture problem: the load-imbalance stress configuration
    (BASELINE.json configs[4]; the course grades on the 128-D shape,
    ``Utility.cpp:98-99``, where clustering is what makes median splits and
    sample-sort partitions uneven).

    ``num_clusters`` centers are drawn uniformly from the generator domain;
    each point/query is a center plus isotropic N(0, stddev²) noise —
    tightly clustered relative to the [-100, 100) domain, so spatial
    density varies by orders of magnitude. Queries come from the same
    mixture (the adversarial case: every query lands in a dense region).
    """
    kc, ka, kn, kqa, kqn = jax.random.split(jax.random.key(seed), 5)
    centers = jax.random.uniform(
        kc, (num_clusters, dim), dtype=dtype, minval=COORD_MIN, maxval=COORD_MAX
    )
    assign = jax.random.randint(ka, (num_points,), 0, num_clusters)
    points = centers[assign] + stddev * jax.random.normal(
        kn, (num_points, dim), dtype=dtype
    )
    qassign = jax.random.randint(kqa, (num_queries,), 0, num_clusters)
    queries = centers[qassign] + stddev * jax.random.normal(
        kqn, (num_queries, dim), dtype=dtype
    )
    return points, queries


def generate_points_shard(
    seed: int, dim: int, shard_start: int, shard_rows: int, dtype=jnp.float32
) -> jax.Array:
    """Generate rows ``[shard_start, shard_start + shard_rows)`` of the global
    point array, without generating the rest.

    The counter-based equivalent of the reference's ``random.discard`` skip
    (``kdtree_mpi.cpp:24,32``): each row's bits depend only on (seed, row), so
    any shard can be produced independently and the union over shards is
    bit-identical to :func:`generate_points_rowwise` (NOT to
    :func:`generate_problem`, which draws the whole (N, D) block from one key
    in a single call and therefore produces different bits).

    ``seed`` and ``shard_start`` may be traced values (``shard_rows`` must be
    static) — this is what lets every sharded engine generate its own rows
    inside one jitted SPMD program.
    """
    kp, _ = jax.random.split(jax.random.key(seed), 2)
    row_keys = jax.vmap(lambda r: jax.random.fold_in(kp, r))(
        shard_start + jnp.arange(shard_rows)
    )
    return jax.vmap(
        lambda k: jax.random.uniform(k, (dim,), dtype=dtype, minval=COORD_MIN, maxval=COORD_MAX)
    )(row_keys)


def generate_points_rowwise(seed: int, dim: int, num_points: int, dtype=jnp.float32) -> jax.Array:
    """Whole-array variant of :func:`generate_points_shard` (rows 0..N).

    Use this (not :func:`generate_problem`) when single-device output must be
    bit-identical to multi-device shard-local generation.
    """
    return generate_points_shard(seed, dim, 0, num_points, dtype=dtype)


def generate_points_shard_clustered(
    seed: int, dim: int, shard_start: int, shard_rows: int,
    num_clusters: int = 8, stddev: float = 2.0, dtype=jnp.float32,
) -> jax.Array:
    """Shard-window clustered generation: the Gaussian-mixture stress
    distribution (:func:`generate_clustered`'s shape) as a counter-based
    row stream, so the scale engines can ingest SKEWED data without ever
    materializing [N, D] (VERDICT r3 item 6 — the fit test needs clustered
    data to actually flow through the sample-sort/mirror exchanges).

    Every row's bits depend only on (seed, row): cluster centers come from
    the seed key alone (identical on every device, no communication) and
    each row folds its global index in for (assignment, noise) — shard
    windows compose bit-identically to the rows 0..N stream, exactly like
    :func:`generate_points_shard`.
    """
    kc, kr = jax.random.split(jax.random.key(seed), 2)
    centers = jax.random.uniform(
        kc, (num_clusters, dim), dtype=dtype, minval=COORD_MIN, maxval=COORD_MAX
    )
    row_keys = jax.vmap(lambda r: jax.random.fold_in(kr, r))(
        shard_start + jnp.arange(shard_rows)
    )

    def one_row(k):
        ka, kn = jax.random.split(k, 2)
        c = jax.random.randint(ka, (), 0, num_clusters)
        return centers[c] + stddev * jax.random.normal(kn, (dim,), dtype=dtype)

    return jax.vmap(one_row)(row_keys)
