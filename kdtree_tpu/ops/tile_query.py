"""Tiled batched k-NN: the TPU-native answer to large query batches.

The per-query best-first DFS (:func:`kdtree_tpu.ops.morton.morton_knn`) is
exact but SIMD-hostile at scale: every query walks its own stack under a
``while_loop`` (divergent lanes, serialized scalar gathers), which measures
~15-25 ms per query batch-step on a v5e chip — unusable at the north star's
10M queries (BASELINE.json). The reference has the same shape per query
(`kdtree_sequential.cpp:75-136`) and only ever answers 10.

This module replaces control flow with dense math, the way a TPU wants it:

1. **Sort queries by Hilbert code** — one small sort; afterwards consecutive
   queries are spatial neighbors (Hilbert, not Morton: the Z-curve's jumps
   produce domain-spanning tiles — see :mod:`kdtree_tpu.ops.hilbert`).
2. **Cut into tiles of TQ queries**; a tile's AABB is tight because of (1).
3. **Seed pass**: beam-descend the bucket-AABB heap once PER TILE (not per
   query) keeping the S closest buckets by box-to-box lower bound; scan
   those S*B points densely → a valid k-th-distance upper bound per query
   (any bucket's points give an upper bound; exactness never depends on the
   beam being right).
4. **Collect pass**: re-descend with the tile bound
   ``B_tile = max_q kth(q)``, keeping EVERY node whose box lower bound is
   <= B_tile (capacity ``cmax``, overflow-flagged — the caller retries with
   a larger cap, same contract as the sample-sort slack). Correctness: for
   any true neighbor p of q in the tile, ``lb(bucket(p), tile_box) <=
   lb(bucket(p), q) <= d2(q,p) <= kth_true(q) <= kth_seed(q) <= B_tile`` —
   so every bucket that can matter is collected.
5. **Dense scan**: for each tile, stream its candidate buckets in chunks of
   V and fold ``[TQ, V*B]`` distance blocks into per-query k-buffers — pure
   VPU work, no divergence, no scalar gathers. (This phase is the Pallas
   fusion target: one kernel = DMA bucket block -> distances -> top-k fold.)

Both descents and both scans are one code path each; every step is static-
shaped and jit-compiles once per (tree shape, Q, k) config. Results are
exact (oracle-tested) — same contract as ``morton_knn``, with ids.
"""

from __future__ import annotations

import collections
import functools
import os
from typing import Callable, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kdtree_tpu import obs
from kdtree_tpu.ops.hilbert import hilbert_codes
from kdtree_tpu.ops.morton import MortonTree, default_bits

DEFAULT_TILE = 256
DEFAULT_CMAX = 128
DEFAULT_SEEDS = 8
_SCAN_V = 8  # buckets per dense-scan fold (the "candidate pad" knob: the
# candidate axis pads to a multiple of it; plan/tuner override via scan_v)
_PALLAS_V = 1  # fused-kernel fold group (pallas/scan_knn.DEFAULT_V: DMA
# latency dominates there, so grouping was measured throughput-neutral)
_SCAN_ROWS = 8192  # queries per scan block on the WIDE fold path (bounds
# the [TB, TQ, V*B] block)
_SCAN_ELEMS = 1 << 16  # fold-op element target on the NARROW path: tb is
# sized so each chunk op stays ~this many elements — small enough that the
# per-block early exit has real granularity, big enough that XLA:CPU's
# fixed per-op cost doesn't dominate (tb=2 at tile=128/B=256 measured 2.2x
# over tb=64 on the profile shape; tb=1 was within noise of tb=2)
_NARROW_TILE_MIN = 64  # heuristic: tiles this wide take the narrow path
# (v=1 + early exit); smaller tiles keep the wide top_k fold — narrow
# chunks at tiny TQ degenerate into op-overhead (measured 741 -> 572 q/s
# at the 1M/tile=8 bench shape, vs 1.5-2x FASTER at tile>=64 shapes)
_EXTRACT_K_MAX = 32  # largest k the unrolled argmin-extract fold compiles
# for; beyond it every width falls back to the top_k formulation
_EXTRACT_W_MAX = 640  # widest [carry | chunk] buffer the extract fold is
# allowed: measured on this container's XLA:CPU, k unrolled argmin passes
# beat nothing at W=2056 (525 ms vs top_k's 102 ms per [64,128,2048]
# block) but are competitive at W<=~512 — and they are TRACED, so the
# narrow path's busy_frac is honest where top_k's custom call reads as
# device idle
_BATCH_Q = 1 << 16  # queries per device program (watchdog + memory bound);
# measured at the 10M-query north-star shape with async dispatch: 2^16 ->
# 365k q/s, 2^17 -> 333k, 2^18 -> 291k — bigger programs don't amortize
# anything further once dispatch is async, they just coarsen retries
DEFAULT_LOOKAHEAD = 8  # batches the pipelined driver keeps in flight before
# it blocks on the oldest batch's overflow flag: enough queue depth that the
# device never drains between programs, small enough that in-flight output
# buffers stay bounded at the 10M-query shape (~150 batches would otherwise
# all be resident at once). KDTREE_TPU_TILE_LOOKAHEAD overrides.


def _gathered_box_lb(tree, box_lo, box_hi, ids):
    """Exact lower bound of |q - p|^2 over q in tile box, p in node ``ids``'
    box. box_lo/box_hi f32[T, D]; ids i32[T, C] -> f32[T, C].

    Gathers per AXIS (D one-dimensional gathers producing [T, C]) instead of
    one [T, C, D] row gather: XLA lays [rows, D] gather results out as
    (8, 128) tiles with the minor D=3 dim padded to 128 — a measured 42.7x
    memory blowup that OOMed a 16 GB chip at [4096, 4096, 3]. [T, C] blocks
    tile cleanly.
    """
    lb = jnp.zeros(ids.shape, jnp.float32)
    for d in range(box_lo.shape[1]):
        lo_d = tree.node_lo[:, d][ids]
        hi_d = tree.node_hi[:, d][ids]
        gap = jnp.maximum(
            jnp.maximum(lo_d - box_hi[:, d : d + 1], box_lo[:, d : d + 1] - hi_d),
            0.0,
        )
        lb = lb + gap * gap
    return lb


def _frontier(tree: MortonTree, box_lo, box_hi, bound, cap: int):
    """Level-synchronous frontier descent over the implicit AABB heap.

    Keeps the <=cap nodes with smallest box-to-box lower bound at every
    level, pruning nodes with lb > bound (monotone: parent lb <= child lb,
    so a pruned subtree can never matter). With ``bound = +inf`` this is a
    best-cap beam (seed mode); with a finite bound it is exact collection,
    and ``overflow[t]`` reports that more than cap nodes passed the bound
    at some level for tile t (caller must retry with a larger cap).

    Returns (bucket ids i32[T, cap] lb-ascending with -1 padding,
    their lower bounds f32[T, cap] (+inf at padding), overflow bool[T]).
    """
    T = box_lo.shape[0]
    L = tree.num_levels
    nbp = tree.num_buckets
    first_leaf = nbp - 1
    s = min(max(cap.bit_length() - 1, 0), L)  # start level: 2^s <= cap
    m = 1 << s

    ids = jnp.broadcast_to(jnp.arange(m, dtype=jnp.int32) + (m - 1), (T, m))
    lb = _gathered_box_lb(tree, box_lo, box_hi, ids)
    # empty/padding nodes have [+inf, -inf] boxes -> lb = +inf -> excluded
    lb = jnp.where(lb <= bound[:, None], lb, jnp.inf)
    overflow = jnp.sum(jnp.isfinite(lb), axis=1) > cap
    if m < cap:
        ids = jnp.concatenate(
            [ids, jnp.zeros((T, cap - m), jnp.int32)], axis=1
        )
        lb = jnp.concatenate([lb, jnp.full((T, cap - m), jnp.inf)], axis=1)
    # top_k(-lb) keeps the cap-smallest lbs in ascending order — same kept
    # set as a full sort-truncate at ~2.5x less stage time (measured, the
    # r3 "top_k frontier" candidate; kept-set identity asserted in
    # scripts/profile_stages.py's A/B). Tie choice at the cap edge cannot
    # affect exactness: if more than cap nodes pass the bound, overflow is
    # already True and the caller retries with a bigger cap.
    neg, sel = lax.top_k(-lb, cap)
    lb, ids = -neg, jnp.take_along_axis(ids, sel, axis=1)

    for _ in range(s, L):
        alive = jnp.isfinite(lb)
        cids = jnp.concatenate([2 * ids + 1, 2 * ids + 2], axis=1)
        calive = jnp.concatenate([alive, alive], axis=1)
        safe = jnp.clip(cids, 0, tree.heap_size - 1)
        clb = _gathered_box_lb(tree, box_lo, box_hi, safe)
        clb = jnp.where(calive & (clb <= bound[:, None]), clb, jnp.inf)
        overflow = overflow | (jnp.sum(jnp.isfinite(clb), axis=1) > cap)
        neg, sel = lax.top_k(-clb, cap)
        lb, ids = -neg, jnp.take_along_axis(cids, sel, axis=1)

    bucket = jnp.where(jnp.isfinite(lb), ids - first_leaf, -1)
    return bucket, lb, overflow


def _fold_block(best_d, best_i, d2, gids, k: int):
    """Merge a [..., W] candidate block into the ascending [..., k] best
    buffers — exactly (the k smallest of carry ∪ block, ascending).

    The formulation is chosen at trace time from (k, width) — same
    selected set either way, measured on this container's XLA:CPU:

    - **narrow** (k <= _EXTRACT_K_MAX and |carry|+W <= _EXTRACT_W_MAX):
      k unrolled argmin/extract passes over the [..., k + W] work buffer
      (the Pallas kernel's fold, in XLA). Entirely traced elementwise/
      reduce fusions — ``lax.top_k`` on the CPU runtime is a custom call
      that executes OUTSIDE the traced op slices (PR 6 profile: untraced
      ~80 ms holes per chunk were the single largest device-idle source
      at 50.7% busy), so the narrow path is what makes a >90% busy_frac
      honest rather than unmeasurable.
    - **wide**: one ``lax.top_k`` over the chunk, then a stable sort of
      the [..., 2k] merge buffer (the pre-PR-6 fold). At W ~ v*B = 2048
      the custom call is 5x FASTER than k traced extract passes — wide
      chunks keep it for raw throughput; the (tile, cmax, v, tb) sweep
      decides per shape which regime wins (docs/TUNING.md "Raw speed").

    Ties at equal distance resolve to the lowest lane (argmin's
    first-index rule; top_k and the stable sort preserve lane order);
    the carry occupies the leading lanes, so an incumbent id always beats
    an equal newcomer — deterministic regardless of chunk arrival order.
    """
    W = best_d.shape[-1] + d2.shape[-1]
    if k <= _EXTRACT_K_MAX and W <= _EXTRACT_W_MAX:
        all_d = jnp.concatenate([best_d, d2], axis=-1)
        all_i = jnp.concatenate([best_i, gids], axis=-1)
        lanes = lax.broadcasted_iota(jnp.int32, all_d.shape, all_d.ndim - 1)
        out_d, out_i = [], []
        for _ in range(k):
            am = jnp.argmin(all_d, axis=-1, keepdims=True)
            out_d.append(jnp.take_along_axis(all_d, am, axis=-1)[..., 0])
            out_i.append(jnp.take_along_axis(all_i, am, axis=-1)[..., 0])
            all_d = jnp.where(lanes == am, jnp.inf, all_d)
        return jnp.stack(out_d, axis=-1), jnp.stack(out_i, axis=-1)
    if d2.shape[-1] >= k:
        # chunk-side top_k first: selection runs over W instead of W + k
        neg, sel = lax.top_k(-d2, k)
        d2, gids = -neg, jnp.take_along_axis(gids, sel, axis=-1)
    all_d = jnp.concatenate([best_d, d2], axis=-1)
    all_i = jnp.concatenate([best_i, gids], axis=-1)
    # distance-only stable sort (num_keys=1): lane order breaks ties, so
    # the leading carry lanes win — see the incumbent rule above (a
    # 2-key sort would let a later equal-distance candidate with a lower
    # gid displace a held incumbent, making ids depend on which chunks
    # the early exit skipped)
    all_d, all_i = lax.sort((all_d, all_i), num_keys=1, is_stable=True)
    return all_d[..., :k], all_i[..., :k]


def _scan_tiles(tree: MortonTree, tq, cand, cand_lb, k: int, v: int, tb: int):
    """Dense-scan each tile's candidate buckets into per-query k-buffers.

    tq f32[T, TQ, D]; cand i32[T, C] lb-ascending (-1 pad); cand_lb
    f32[T, C] (+inf at pad). Returns (d2 f32[T, TQ, k], gid i32[T, TQ, k])
    ascending. Tiles stream through in blocks of ``tb`` and buckets in
    chunks of ``v`` so intermediates stay [tb, TQ, v*B].

    Each chunk is gated by the Pallas kernel's early-exit rule, ported to
    the portable path via a real ``lax.cond`` branch (the chunk scan is a
    sequential ``lax.scan``, so the false branch genuinely skips the
    distance block AND the fold): candidates are lb-ascending per tile, so
    once chunk c's first lower bound can no longer beat any query's
    current k-th in any of the block's tiles, neither can any later chunk
    entry of those tiles. Exact — ``lb(bucket, tile box) <= d2(q, p)`` for
    every q in the tile and p in the bucket, so a skipped chunk could
    never have displaced a held neighbor (equal-distance ties keep the
    incumbent, see ``_fold_block``). ``tb`` sets the exit granularity:
    one straggler tile keeps its whole block's chunks alive, so smaller
    blocks prune more but pay more per-iteration overhead — a measured
    trade the tuner sweeps (docs/TUNING.md "Raw speed").
    """
    T, TQ, D = tq.shape
    C = cand.shape[1]
    B = tree.bucket_size

    cpad = (-C) % v
    if cpad:
        cand = jnp.concatenate([cand, jnp.full((T, cpad), -1, jnp.int32)], axis=1)
        cand_lb = jnp.concatenate(
            [cand_lb, jnp.full((T, cpad), jnp.inf, jnp.float32)], axis=1
        )
        C += cpad
    tpad = (-T) % tb
    if tpad:
        tq = jnp.concatenate([tq, jnp.zeros((tpad, TQ, D), tq.dtype)], axis=0)
        cand = jnp.concatenate([cand, jnp.full((tpad, C), -1, jnp.int32)], axis=0)
        cand_lb = jnp.concatenate(
            [cand_lb, jnp.full((tpad, C), jnp.inf, jnp.float32)], axis=0
        )

    tq_b = tq.reshape(-1, tb, TQ, D)
    cand_b = cand.reshape(-1, tb, C // v, v)
    # chunk lower bound = its first candidate's (lb-ascending per tile);
    # padded tiles/chunks carry +inf and therefore never fold
    lb_b = cand_lb.reshape(-1, tb, C // v, v)[..., 0]

    def block_fn(args):
        tqb, candb, lbb = args  # [tb, TQ, D], [tb, C//v, v], [tb, C//v]

        def chunk(carry, xs):
            best_d, best_i = carry
            cb, lb0 = xs  # i32[tb, v], f32[tb]

            def fold(c):
                bd, bi = c
                sel = jnp.maximum(cb, 0)
                pts = tree.bucket_pts[sel].reshape(tb, 1, v * B, D)
                gids = jnp.where((cb >= 0)[:, :, None], tree.bucket_gid[sel], -1)
                gids = gids.reshape(tb, 1, v * B)
                diff = tqb[:, :, None, :] - pts
                d2 = jnp.sum(diff * diff, axis=-1)  # [tb, TQ, v*B]
                # invalid buckets -> inf rows; padding rows inside real
                # buckets are +inf coords and come out inf on their own
                bad = jnp.repeat(cb < 0, B, axis=1)[:, None, :]
                d2 = jnp.where(bad, jnp.inf, d2)
                gids = jnp.broadcast_to(gids, d2.shape)
                return _fold_block(bd, bi, d2, gids, k)

            alive = lb0 < jnp.max(best_d[..., k - 1], axis=1)  # [tb]
            return lax.cond(jnp.any(alive), fold, lambda c: c,
                            (best_d, best_i)), None

        init = (
            jnp.full((tb, TQ, k), jnp.inf, jnp.float32),
            jnp.full((tb, TQ, k), -1, jnp.int32),
        )
        (bd, bi), _ = lax.scan(
            chunk, init,
            (jnp.swapaxes(candb, 0, 1), jnp.swapaxes(lbb, 0, 1)),
        )
        return bd, bi

    d2, gid = lax.map(block_fn, (tq_b, cand_b, lb_b))
    d2 = d2.reshape(-1, TQ, k)[:T]
    gid = gid.reshape(-1, TQ, k)[:T]
    return d2, gid


@functools.partial(jax.jit, static_argnames=("bits", "qpad"))
def _sort_queries(queries, bits: int, qpad: int):
    """Hilbert-sort the (padded) query set once, globally.

    Hilbert, not Morton: a Z-curve window straddling a high-bit boundary
    spans the whole domain (measured p99 tile candidate count 2051 vs median
    76), while any Hilbert window is a connected region. Padding duplicates
    the last query (harmless real coordinates; results are sliced away).
    """
    Q, D = queries.shape
    if qpad:
        queries = jnp.concatenate(
            [queries, jnp.broadcast_to(queries[-1], (qpad, D))], axis=0
        )
    Qp = queries.shape[0]
    qcode = hilbert_codes(queries, bits)
    order = lax.sort(
        (qcode, jnp.arange(Qp, dtype=jnp.int32)), num_keys=1, is_stable=True
    )[1]
    return queries[order], order


def _tiled_batch_core(
    tree, sq, k: int, tile: int, cmax: int, seeds: int, v: int, tb: int,
    use_pallas: bool = False, visit_cap: int | None = None,
):
    """Seed + collect + scan for ONE batch of sorted queries (trace-level
    body, shared by the jitted single-tree wrapper below and the SPMD
    per-shard program in :mod:`kdtree_tpu.parallel.global_morton`).

    Kept deliberately bounded (caller slices the sorted order into batches):
    one giant fused program at 10M queries runs for minutes and trips the
    device runtime's execution watchdog — many sub-second programs do not,
    and per-batch overflow retries only recompute the affected slice.
    """
    tq = sq.reshape(-1, tile, sq.shape[1])
    box_lo = jnp.min(tq, axis=1)
    box_hi = jnp.max(tq, axis=1)
    T = tq.shape[0]

    inf_bound = jnp.full(T, jnp.inf, jnp.float32)
    seed_cand, seed_lb, _ = _frontier(tree, box_lo, box_hi, inf_bound, seeds)
    if use_pallas:
        from kdtree_tpu.pallas.scan_knn import scan_tiles_fused

        sd, _ = scan_tiles_fused(tree, tq, seed_cand, seed_lb, k, V=v)
    else:
        sd, _ = _scan_tiles(tree, tq, seed_cand, seed_lb, k, v, tb)
    tile_bound = jnp.max(sd[..., k - 1], axis=1)  # [T]

    cand, cand_lb, overflow = _frontier(tree, box_lo, box_hi, tile_bound, cmax)
    if visit_cap is not None and visit_cap < cand.shape[1]:
        # bounded-visit (approximate) mode: the collect pass already
        # ranked every relevant bucket lb-ascending, so approximation is
        # a TRUNCATION of that list, not a different traversal
        # (kdtree_tpu/approx/search.py). Truncations of one fixed
        # ranking are nested — visit_cap M's bucket set is a subset of
        # M' > M's — which is what makes recall@k monotone in the cap,
        # and visit_cap >= C makes the slice a no-op: the program IS the
        # exact program, byte for byte (both test-pinned).
        cand = cand[:, :visit_cap]
        cand_lb = cand_lb[:, :visit_cap]
    if use_pallas:
        fd, fi = scan_tiles_fused(tree, tq, cand, cand_lb, k, V=v)
    else:
        fd, fi = _scan_tiles(tree, tq, cand, cand_lb, k, v, tb)
    q = tq.shape[0] * tile
    # collect-pass candidate-bucket count: a trivial [T, C] reduction the
    # compiler fuses; the driver fetches it (telemetry-gated) alongside the
    # overflow flags to report tile-query prune rate
    ncand = jnp.sum((cand >= 0).astype(jnp.int32))
    return fd.reshape(q, k), fi.reshape(q, k), jnp.any(overflow), ncand


@functools.partial(
    jax.jit,
    static_argnames=("k", "qbatch", "tile", "cmax", "seeds", "v", "tb",
                     "use_pallas", "visit_cap"),
)
def _tiled_batch(
    tree, sq, b0, k: int, qbatch: int, tile: int, cmax: int, seeds: int,
    v: int, tb: int, use_pallas: bool = False,
    visit_cap: int | None = None,
):
    """One batch = ONE device program: the batch's query slice is a
    ``dynamic_slice`` on the traced offset ``b0`` INSIDE the program, so
    the driver's dispatch loop launches exactly one program per batch
    (the old eager ``lax.slice_in_dim`` was a second per-batch program —
    and, offsets being static, a fresh tiny compile per distinct offset
    at the ~150-batch north-star shape)."""
    sqb = lax.dynamic_slice_in_dim(sq, b0, qbatch, axis=0)
    return _tiled_batch_core(tree, sqb, k, tile, cmax, seeds, v, tb,
                             use_pallas, visit_cap)


@functools.partial(jax.jit, static_argnames=("qreal",))
def _unsort(order, d2, gi, qreal: int):
    out_d = jnp.zeros(d2.shape, jnp.float32).at[order].set(d2)
    out_i = jnp.zeros(gi.shape, jnp.int32).at[order].set(gi)
    return out_d[:qreal], out_i[:qreal]


def _auto_tile(Q, n, k, D, nbp, B, cmax, use_pallas=False):
    """Density-sized tiles: expected candidate buckets per tile is
    ``((TQ/Q)^(1/D) + 2 (k/n)^(1/D))^D * nbp`` (tile extent + twice the
    k-th-neighbor radius, as domain fractions, assuming comparable query
    and point clouds), with an empirical x8 safety from measured p99 vs
    the uniform model.

    XLA path: pick the largest power-of-2 tile whose estimate fits cmax
    (the dense scan pays for every candidate slot, so keep C small).

    Pallas path: the kernel's early exit makes extra candidate SLOTS nearly
    free while per-bucket DMA latency dominates, so bigger tiles win
    outright (total bucket DMAs ~ (a + b/tile^(1/D))^D decreases in tile):
    pick the largest tile <= 128 whose estimate stays under 768 slots
    (3/4 of the 1024-slot candidate budget) and size cmax to 2x the
    estimate — measured at the 16M/1M/k=16 north-star shape this is 3x
    faster than the small-tile choice, and the margin avoids the
    overflow-retry recompile cliff. The 128 ceiling is itself measured:
    the kernel's k-extraction fold is O(TQ * W) per fired bucket, so past
    TQ=128 the fold cost outgrows the DMA savings (same shape, v5e:
    tile 64/128/256/512 -> 111/125/79/48 k q/s)."""
    def est(tq):
        return (
            ((tq / Q) ** (1.0 / D) + 2.0 * (k / max(n, 1)) ** (1.0 / D)) ** D
            * nbp
            * 8.0
        )
    if use_pallas:
        tq = 128
        while tq > 8 and est(tq) > 768:
            tq //= 2
        need = max(cmax, est(tq) * 2.0)
        c = 128
        while c < min(4096, nbp) and c < need:
            c *= 2
        return tq, min(c, nbp)
    tq = 1024
    while tq > 4 and est(tq) > 0.75 * cmax:
        tq //= 2
    if est(tq) > 0.75 * cmax:
        need = est(tq) * 1.5
        while cmax < min(4096, nbp) and cmax < need:
            cmax *= 2
    return tq, min(cmax, nbp)


def dense_lowd(q: int, n: int, dim: int) -> bool:
    """The measured tiled-engine crossover (v5e, round 3): dense low-D
    batches win 4x on the tiled Pallas engine; sparse batches invert
    (each sparse tile's box covers most buckets). Shared by the CLI auto
    engine choice, checkpoint-query dispatch, and the SPMD forest query
    routing."""
    return q >= 512 and q * 64 >= n and dim <= 6


class TiledPlan(NamedTuple):
    """Static launch configuration for a tiled-query run, shared by the
    single-tree driver below and the SPMD forest driver
    (:func:`kdtree_tpu.parallel.global_morton.global_morton_query_tiled`).

    ``source`` records where the knobs came from: ``"warm"`` (plan-store
    hit — the batch driver skips the synchronous first-batch cap-settling
    probe), ``"heuristic"`` (the static density model), or ``"explicit"``
    (caller-forced; never recorded back to the store)."""

    tile: int
    cmax: int
    seeds: int
    v: int
    tb: int
    bits: int
    qbatch: int
    use_pallas: bool
    source: str = "heuristic"
    # the plan-store signature this plan was looked up under (None for
    # explicit plans) — carried here so feedback_for records under EXACTLY
    # the key lookup consulted; re-deriving it at each call site invited
    # silent argument-order drift that would de-sync lookup from recording
    sig: object = None


def _opt_knob(x) -> int | None:
    """Validate an optional block-shape knob read from a plan profile:
    profiles are advisory, so anything but a positive int reads as
    'not recorded' rather than an error."""
    if isinstance(x, int) and not isinstance(x, bool) and x >= 1:
        return x
    return None


def plan_tiled(
    Q: int, D: int, n_real: int, nbp: int, B: int, k: int,
    tile: int | None = None, cmax: int = DEFAULT_CMAX,
    seeds: int = DEFAULT_SEEDS, use_pallas: bool | None = None,
    devices: int = 1, scan_v: int | None = None, scan_tb: int | None = None,
) -> TiledPlan:
    """Resolve the static knobs of a tiled run from the problem shape.

    ``tile=None`` picks the launch configuration automatically: first from
    the persistent plan store (:mod:`kdtree_tpu.tuning` — a previous run's
    settled tile/cmax/seeds (and, when a sweep recorded them, the
    block-shape knobs ``v``/``tb``) for this quantized problem signature,
    in which case the caller-supplied ``cmax``/``seeds`` starting hints
    are superseded), then from the static density heuristic on a miss.
    ``devices`` is the per-shard plan context (forest drivers pass their
    shard count so a P=8 shard plan never collides with a single-chip
    one). ``use_pallas=None`` enables the fused Mosaic kernel on TPU
    backends and the XLA scan elsewhere (tests force use_pallas=True,
    which interprets off-TPU). ``scan_v``/``scan_tb`` force the scan
    block shape (buckets per fold chunk / tiles per scan block — the
    fused-kernel fold group on the Pallas path) — explicit overrides,
    used by the tuner sweep; exactness never depends on either.
    """
    forced_engine = use_pallas is not None
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    source = "explicit"
    sig = None
    # the store is consulted/recorded only for FULLY auto plans: a caller
    # hinting cmax or seeds or forcing the scan engine or block shape
    # (even with tile unset) is a one-off override, and recording its
    # settled knobs would lock the override into every future auto run of
    # the shape (feedback never shrinks a cap, and a forced-engine profile
    # would evict the default engine's warm plan under the shared key)
    auto = (tile is None and cmax == DEFAULT_CMAX
            and seeds == DEFAULT_SEEDS and not forced_engine
            and scan_v is None and scan_tb is None)
    v, tb = scan_v, scan_tb
    if auto:
        from kdtree_tpu import tuning

        sig = tuning.make_signature(Q, D, n_real, k, B, nbp,
                                    devices=devices)
        prof = tuning.lookup(sig, use_pallas=use_pallas)
        if prof is not None:
            tile, cmax = int(prof["tile"]), int(prof["cmax"])
            seeds = int(prof.get("seeds", seeds))
            v = _opt_knob(prof.get("v"))
            tb = _opt_knob(prof.get("tb"))
            source = "warm"
        else:
            tile, cmax = _auto_tile(Q, n_real, k, D, nbp, B, cmax,
                                    use_pallas)
            source = "heuristic"
    elif tile is None:
        tile, cmax = _auto_tile(Q, n_real, k, D, nbp, B, cmax, use_pallas)
    if min(tile, max(Q, 1)) != tile and source == "warm":
        # the clamp is about to change the tile a warm profile's block
        # knobs were swept at — knobs measured at one tile width pinned
        # onto another hard-code the wrong fold regime (same invariant
        # the tuner's _prev_block_knobs enforces); fall back to the
        # shape heuristic for them
        v, tb = scan_v, scan_tb
    tile = min(tile, max(Q, 1))
    seeds = min(seeds, nbp)
    if k > (seeds * B) // 2:
        # seed buckets must be able to bound the k-th distance; fall back to
        # collecting everything (exact, still dense) for oversized k
        cmax = nbp
    cmax = min(cmax, nbp)
    bits = default_bits(D)
    # the fold selects from [carry | chunk], so any v >= 1 is exact (the
    # old top_k-from-chunk-alone formulation needed v*B >= k; the carry-
    # inclusive fold does not). Heuristic regime choice (docs/TUNING.md
    # "Raw speed"): wide tiles take the NARROW scan (v=1 single-bucket
    # chunks — per-bucket early exit, traced extract fold) because their
    # per-op arrays stay large enough to amortize XLA:CPU's fixed op cost;
    # small tiles keep the WIDE v chunks and the top_k fold, where the
    # measured crossover flips (see _NARROW_TILE_MIN). The tuner sweep
    # overrides both per shape via the plan store.
    if v is None:
        if use_pallas:
            v = _PALLAS_V
        elif tile >= _NARROW_TILE_MIN and k <= _EXTRACT_K_MAX \
                and B + k <= _EXTRACT_W_MAX:
            v = 1
        else:
            # the regime is decided by _fold_block's WIDTH gate, so a
            # small bucket size could let _SCAN_V chunks slip under it
            # and run the narrow extract at tiny tiles — the measured
            # regression the branch exists to avoid. Widen v until the
            # chunk is genuinely wide.
            v = _SCAN_V
            while v * B + k <= _EXTRACT_W_MAX:
                v *= 2
    v = max(int(v), 1)
    # batches bound each device program's runtime (watchdog) and memory;
    # the global Hilbert sort happens ONCE, so batch slices stay coherent.
    # Small Q must not pad up to the full batch quantum (Q=1024 padded to
    # 2^16 would scan 64x more rows than asked) — cap at Q tile-rounded
    qbatch = max(_BATCH_Q // tile, 1) * tile
    qbatch = min(qbatch, -(-max(Q, 1) // tile) * tile)
    if tb is None:
        # same gate as _fold_block's narrow path: k must also fit the
        # unrolled extract (k > _EXTRACT_K_MAX runs the WIDE fold even at
        # narrow widths, where element-target-sized tiny blocks would
        # just pay per-op overhead)
        if k <= _EXTRACT_K_MAX and v * B + k <= _EXTRACT_W_MAX:
            # narrow scan: size blocks to the fold-op element target so
            # the early exit keeps per-block granularity without XLA:CPU
            # op overhead dominating
            tb = max(1, _SCAN_ELEMS // max(tile * (v * B + k), 1))
        else:
            tb = max(1, _SCAN_ROWS // tile)
    # a block wider than the batch's tile count only pads dead tiles
    tb = max(1, min(int(tb), -(-qbatch // tile)))
    return TiledPlan(tile, cmax, seeds, v, tb, bits, qbatch, use_pallas,
                     source, sig)


def _resolve_lookahead(lookahead: int | None) -> int:
    if lookahead is not None:
        return max(int(lookahead), 1)
    raw = os.environ.get("KDTREE_TPU_TILE_LOOKAHEAD")
    if raw:
        try:
            return max(int(raw), 1)
        except ValueError:
            pass
    return DEFAULT_LOOKAHEAD


def drive_batches(
    run_batch: Callable[[int, int], tuple],
    offsets: Sequence[int],
    cmax: int,
    nbp: int,
    scan_units_per_batch: int | None = None,
    settle_first: bool = True,
    feedback=None,
    lookahead: int | None = None,
) -> Tuple[jax.Array, jax.Array]:
    """Pipelined async batch dispatch with overflow-retry, shared by every
    tiled driver. ``run_batch(offset, cap) -> (d2, gid, overflow[,
    ncand])`` must be a jitted program; the optional 4th output is the
    batch's candidate-bucket count (an i32 scalar), which — together with
    ``scan_units_per_batch`` = tiles-per-batch x shards, the number of
    (tile, local-tree) pairs whose frontier could have kept up to ``nbp``
    buckets each — lets the driver report the tile-query prune rate
    (``1 - candidates / (scan_units * nbp)``). The candidate fetch is one
    extra stacked host read gated on ``obs.enabled()``, so
    metrics-disabled runs pay nothing.

    Settles the cap on the FIRST batch synchronously (``settle_first``): a
    tile geometry that overflows cap C in one batch tends to overflow it
    in similar batches too, so systematic undersizing costs one doubling
    round here instead of a re-run of every batch. A WARM plan
    (``plan.source == "warm"`` — the cap already settled in a previous
    run and came back from the plan store) passes ``settle_first=False``
    and skips the probe entirely.

    Dispatch then runs as a bounded pipeline: up to ``lookahead`` batches
    stay in flight; once the window is full, the OLDEST batch is retired
    (one scalar overflow-flag fetch — the device still has a full window
    of programs queued behind it, so the host wait overlaps execution
    instead of draining it, and the next batch's host-side prep overlaps
    the in-flight batches' device time). A retired batch that flags
    overflow retries immediately at the grown cap — invalidating ONLY
    itself, never the in-flight lookahead (each re-dispatch counts once
    in the retry counter; a younger in-flight batch dispatched at the
    stale cap is checked — and, if needed, retried — when ITS turn
    comes). The tail window (and any run short enough to fit entirely in
    the window, which includes every pre-pipeline call site) drains with
    ONE stacked flag fetch plus doubling rounds — exactly the old
    all-async behavior, because a per-batch fetch with an EMPTY pipeline
    behind it serializes host and device (measured ~8x at the 10M-query
    north-star shape). A clean flag at a smaller cap is still exact —
    overflow is the only incompleteness signal.
    """
    from kdtree_tpu.obs import flight

    reg = obs.get_registry()
    retries = reg.counter("kdtree_tile_overflow_retries_total")
    nretries = 0
    bcmax = cmax
    n = len(offsets)
    window = _resolve_lookahead(lookahead)
    batches: list = [None] * n
    caps = [0] * n

    def dispatch(i: int, cap: int):
        # the "tile.dispatch" TraceAnnotation is the device-timeline
        # anchor (obs/timeline.py): in a profiler capture, the gap
        # between this annotation and the first op slice that follows is
        # the dispatch-to-execution lag, and each dispatch-to-next-
        # dispatch window gets a device busy/idle breakdown. Outside a
        # capture the annotation is a ~ns no-op.
        with jax.profiler.TraceAnnotation("tile.dispatch", batch=i,
                                          cap=cap):
            batches[i] = run_batch(offsets[i], cap)
            caps[i] = cap

    def retire(i: int) -> None:
        """Block on batch ``i``'s overflow flag; retry it (alone) at the
        grown cap until clean or the cap ceiling."""
        nonlocal bcmax, nretries
        while True:
            # the annotation wraps ONLY the blocking flag fetch — a retry
            # re-dispatch runs outside it so the timeline's stage split
            # books it as prep (its own tile.dispatch), keeping retire_us
            # = flag-fetch wait exactly as documented
            with jax.profiler.TraceAnnotation("tile.retire", batch=i):
                # kdt-lint: disable=KDT201 pipelined retirement: one
                # scalar flag fetch per batch, taken only while a full
                # lookahead window of programs is queued behind it — the
                # host wait overlaps device execution, it never drains it
                done = not bool(np.asarray(batches[i][2])) \
                    or caps[i] >= nbp
            if done:
                return
            if caps[i] >= bcmax:
                bcmax = min(bcmax * 2, nbp)
            retries.inc()
            nretries += 1
            flight.record("tile.overflow_retry", cap=bcmax, batches=1)
            dispatch(i, bcmax)

    start = 0
    inflight: collections.deque = collections.deque()
    # offsets must be non-empty: every caller guards Q == 0 upstream, and
    # the result assembly below indexes batches[0] unconditionally
    if settle_first:
        dispatch(0, bcmax)
        # the deliberate cap-settling probe: one synchronous flag fetch
        # on the FIRST batch settles a systematic undersize before ~150
        # async batches dispatch at the wrong cap
        while bool(np.asarray(batches[0][2])) and bcmax < nbp:
            bcmax = min(bcmax * 2, nbp)
            retries.inc()
            nretries += 1
            dispatch(0, bcmax)
        start = 1
        # the settled batch still joins the pipeline: cold and warm runs
        # must execute the SAME program set, and excluding batch 0 here
        # made a cold run's drain stack one flag NARROWER than a warm
        # run's — so the first warm run recompiled the drain fetch
        # inside what should be a steady-state (capture-clean) window.
        # Its re-checked flag is already resident and clean; the extra
        # fetch is the price of program-set parity.
        inflight.append(0)
    for i in range(start, n):
        if len(inflight) >= window:
            retire(inflight.popleft())
        dispatch(i, bcmax)
        inflight.append(i)
    # drain the tail window: one stacked fetch over the (<= lookahead)
    # still-in-flight batches, then doubling rounds for stragglers
    while inflight:
        idx = list(inflight)
        inflight.clear()
        with jax.profiler.TraceAnnotation("tile.drain", batches=len(idx)):
            # kdt-lint: disable=KDT201 ONE stacked overflow-flag fetch for
            # the tail window after every batch dispatched async; overflow
            # is the only exactness signal, so this sync is the contract
            flags = np.asarray(jnp.stack([batches[i][2] for i in idx]))
        # a batch whose LAST dispatch already ran at the nbp ceiling is
        # final: overflow there is impossible by construction (every
        # bucket fits), so a still-set flag is a bug upstream and
        # retrying it would loop forever. The filter is per-batch caps,
        # NOT bcmax — retiring an earlier straggler may have grown bcmax
        # to the ceiling while tail batches were still in flight at a
        # stale smaller cap, and those must retry or their overflowed
        # (incomplete) results would be returned.
        bad = [idx[j] for j in np.nonzero(flags)[0] if caps[idx[j]] < nbp]
        if not bad:
            break
        if max(caps[i] for i in bad) >= bcmax:
            # a failure at the CURRENT cap starts a doubling round; a
            # batch that failed at a stale smaller cap first retries at
            # today's bcmax (same rule as retire())
            bcmax = min(bcmax * 2, nbp)
        flight.record("tile.overflow_retry", cap=bcmax, batches=len(bad))
        for i in bad:
            retries.inc()
            nretries += 1
            dispatch(i, bcmax)
            inflight.append(i)
    reg.counter("kdtree_tile_batches_total").inc(len(offsets))
    if obs.enabled() and len(batches[0]) > 3:
        # stack the per-batch candidate counts on device (async) and DEFER
        # the fetch to report time — no sync added to the dispatch loop
        ncand_dev = jnp.stack([b[3] for b in batches])
        units = (scan_units_per_batch or 0) * len(offsets)

        def _flush_candidates(reg=reg, ncand_dev=ncand_dev, units=units,
                              nbp=nbp, feedback=feedback):
            ncand = int(np.asarray(ncand_dev).sum())
            reg.counter("kdtree_tile_candidates_total").inc(ncand)
            rate = None
            if units:
                reg.counter("kdtree_tile_scan_units_total").inc(units)
                denom = units * nbp
                if denom > 0:
                    rate = 1.0 - ncand / denom
                    reg.gauge("kdtree_tile_prune_rate").set(rate)
            if feedback is not None:
                # hand THIS run's rate to the plan-store enrichment
                # directly — reading the process-global gauge back would
                # cross-contaminate signatures when several differently
                # shaped runs flush together
                feedback.record_stats(prune_rate=rate)

        obs.defer(_flush_candidates)
    if feedback is not None:
        # the settled cap and this run's retry count are host-side facts by
        # now (the retry loop fetched the flags); recording them closes the
        # auto-tune loop — the next same-shaped run starts here
        feedback.settled(cmax=bcmax, retries=nretries)
    # one flight-recorder event per DRIVE (not per batch): an incident
    # dump shows each tiled run's dispatch count, settled cap, and retry
    # reality without per-batch ring pressure
    flight.record("tile.drive", batches=len(offsets), cmax=bcmax,
                  retries=nretries)
    parts_d = [b[0] for b in batches]
    parts_i = [b[1] for b in batches]
    d2 = jnp.concatenate(parts_d, axis=0) if len(parts_d) > 1 else parts_d[0]
    gi = jnp.concatenate(parts_i, axis=0) if len(parts_i) > 1 else parts_i[0]
    return d2, gi


def morton_knn_tiled(
    tree: MortonTree,
    queries: jax.Array,
    k: int = 1,
    tile: int | None = None,
    cmax: int = DEFAULT_CMAX,
    seeds: int = DEFAULT_SEEDS,
    use_pallas: bool | None = None,
    plan: TiledPlan | None = None,
    scan_v: int | None = None,
    scan_tb: int | None = None,
    visit_cap: int | None = None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact batched k-NN via Hilbert-sorted query tiles and dense scans.

    Same contract as :func:`kdtree_tpu.ops.morton.morton_knn` (d2 f32[Q, k],
    ids i32[Q, k], ascending), built for large Q. ``tile=None`` plans
    automatically — from the persistent plan store when a previous run
    settled this problem shape (:mod:`kdtree_tpu.tuning`; a warm plan
    skips the first-batch cap probe entirely), from query/point density
    otherwise — and records the settled configuration back. ``cmax``
    doubles automatically (up to the bucket count) when a tile's
    candidate set overflows — geometry-driven, rare for sane tiles.
    ``use_pallas=None`` enables the fused scan kernel
    (:mod:`kdtree_tpu.pallas.scan_knn`) on TPU backends and uses the XLA
    scan elsewhere. A caller that already resolved a plan (the serving
    batcher inspects ``plan.source`` for its warm/cold metrics before
    dispatching) passes it via ``plan`` so the store is consulted — and
    its hit/miss counters advanced — exactly once; the tile/cmax/seeds/
    use_pallas knob arguments are ignored then.

    ``visit_cap`` (docs/SERVING.md "Degradation ladder") bounds the
    dense scan to the ``visit_cap`` nearest candidate buckets per tile
    (by box lower bound) — the bounded-visit APPROXIMATE mode
    :mod:`kdtree_tpu.approx` resolves from a recall target. ``None``
    (the default) is the exact path, unchanged; a cap at least as wide
    as the collected candidate list is byte-identical to it. Approx
    runs never feed the plan store (a truncated run's stats would
    contaminate the exact shape's profile).
    """
    Q, D = queries.shape
    k = min(k, tree.n_real)
    if Q == 0:
        return (
            jnp.zeros((0, k), jnp.float32),
            jnp.zeros((0, k), jnp.int32),
        )
    obs.count_query("tiled", Q)
    if plan is None:
        plan = plan_tiled(
            Q, D, tree.n_real, tree.num_buckets, tree.bucket_size, k,
            tile, cmax, seeds, use_pallas, scan_v=scan_v, scan_tb=scan_tb,
        )
    from kdtree_tpu import tuning

    # approx (bounded-visit) runs are excluded from the auto-tune loop:
    # their settled caps and prune stats describe a deliberately
    # truncated scan, and recording them would warm-start the EXACT
    # path of this shape from approximate evidence
    feedback = None if visit_cap is not None else tuning.feedback_for(plan)
    if visit_cap is not None:
        visit_cap = max(int(visit_cap), 1)
        obs.get_registry().counter("kdtree_approx_queries_total").inc(Q)
    qpad = (-Q) % plan.qbatch
    with obs.span("query.tiled", sync=False, q=Q, k=k):
        sq, order = _sort_queries(queries, plan.bits, qpad)
        Qp = sq.shape[0]

        def run_batch(b0: int, cap: int):
            return _tiled_batch(
                tree, sq, b0, k, plan.qbatch, plan.tile, cap, plan.seeds,
                plan.v, plan.tb, plan.use_pallas, visit_cap,
            )

        offsets = list(range(0, Qp, plan.qbatch))
        d2, gi = drive_batches(
            run_batch, offsets, plan.cmax, tree.num_buckets,
            scan_units_per_batch=plan.qbatch // plan.tile,
            settle_first=plan.source != "warm",
            feedback=feedback,
        )
        return _unsort(order, d2, gi, Q)
