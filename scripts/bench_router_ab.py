"""Router scale-out A/B bench: the committed BENCH_router_*.json recipe.

Stands up an N-shard spatially-partitioned fleet IN-PROCESS (the same
``plan_partition`` + ``morton_view`` + ``make_server`` stack the serve
tests use, minus the disk round-trip), launches the router topology
under test as real ``kdtree-tpu route`` subprocesses, and drives it
with the open-loop ``loadgen`` harness — so the artifact this writes is
a first-class `kdtree-tpu trend` input, `capacity.ab` block included.

The four committed arms (docs/SERVING.md "Measuring it: the A/B loop").
In each pair the CANDIDATE (the arm carrying ``--ab-baseline``) is the
configuration the repo recommends at that scale, so the trend
``knee-drop`` gate re-judges the recommendation on every regeneration:

  # 16 shards, pooling isolated: fresh baseline, pooled candidate
  python scripts/bench_router_ab.py --shards 16 --pts-per-shard 512 \
      --cloud uniform --arm fresh --rates 10,20,30,40,50,60,90,120 \
      --step-seconds 4 --slo-ms 250 --slo-quantile 0.95 \
      --deadline-ms 2000 --hedge-ms 150 \
      --out BENCH_router_fresh16.json
  python scripts/bench_router_ab.py --shards 16 --pts-per-shard 512 \
      --cloud uniform --arm pooled --rates 10,20,30,40,50,60,90,120 \
      --step-seconds 4 --slo-ms 250 --slo-quantile 0.95 \
      --deadline-ms 2000 --hedge-ms 150 \
      --ab-baseline BENCH_router_fresh16.json \
      --out BENCH_router_pooled16.json

  # 64 shards, topology: two-level baseline, flat pooled candidate.
  # On a single-core host the two-level tree DOUBLES the router-path
  # work per request with no extra hardware to absorb it, so flat wins
  # and is the committed recommendation at this scale; the hier arm is
  # kept as the measured baseline so the day multi-host routing makes
  # the tree pay for itself, flipping the pair is a one-line change.
  python scripts/bench_router_ab.py --shards 64 --pts-per-shard 512 \
      --cloud uniform --arm hier --children 4 --rates 2,4,6,8,12,16,24 \
      --step-seconds 4 --slo-ms 250 --slo-quantile 0.95 \
      --deadline-ms 4000 --hedge-ms 1500 \
      --out BENCH_router_hier64.json
  python scripts/bench_router_ab.py --shards 64 --pts-per-shard 512 \
      --cloud uniform --arm flat --rates 2,4,6,8,12,16,24 \
      --step-seconds 4 --slo-ms 250 --slo-quantile 0.95 \
      --deadline-ms 4000 --hedge-ms 1500 \
      --ab-baseline BENCH_router_hier64.json \
      --out BENCH_router_flat64.json

The 64-shard pair judges at p95 with a 1500 ms hedge floor: the shard
host is ONE process sharing ONE core with both routers and the load
generator, so every few seconds the scheduler parks it for ~1.5 s and
a short step's p99 (~40 samples) is hostage to whether that stall
landed inside it.  The hedge is what rescues the stalled requests
(their latency clusters at exactly hedge + RTT in every arm, pooled
or fresh), and p95 is the quantile with enough samples to rank the
arms instead of ranking the stalls.

Everything shares one machine (CI runners and this container are
single-digit cores), so the backend fleet cost is identical across
arms and the measured delta is the router-path difference — exactly
what the A/B claims. The cloud lives in the UNIT CUBE because loadgen
draws its Zipf-region query points there.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import re
import signal
import subprocess
import sys
import time
import urllib.request

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

_READY_RE = re.compile(r"^ready: .* on port (\d+)$", re.M)


def build_fleet(shards: int, pts_per_shard: int, seed: int,
                cloud: str = "clustered"):
    """N in-process shard servers over one clustered unit-cube cloud,
    partitioned by ``plan_partition`` with global morton-rank gids."""
    import jax.numpy as jnp

    from kdtree_tpu.obs import slo as obs_slo
    from kdtree_tpu.ops.morton import morton_view
    from kdtree_tpu.serve import lifecycle
    from kdtree_tpu.serve import server as srv
    from kdtree_tpu.serve import spatial as sp

    rng = np.random.default_rng(seed)
    if cloud == "uniform":
        # dense cube coverage: loadgen's region queries land near data
        # everywhere, so k-NN balls stay small and per-level box
        # pruning — the thing the topology A/B exercises — is sharp
        pts = rng.random((shards * pts_per_shard, 3)).astype(np.float32)
    else:
        n_centers = min(shards, 16)
        centers = rng.random((n_centers, 3))
        pts = np.concatenate([
            c + rng.normal(0.0, 0.02,
                           (shards * pts_per_shard // n_centers, 3))
            for c in centers
        ]).astype(np.float32)
        pts = np.clip(pts, 0.0, 1.0)
    plan = sp.plan_partition(pts, shards)
    order = plan["order"]
    servers, urls = [], []
    for i, ((s, e), (c0, c1)) in enumerate(
            zip(plan["bounds"], plan["code_ranges"])):
        tree = morton_view(
            jnp.asarray(pts[order[s:e]]),
            gid=jnp.asarray(np.arange(s, e, dtype=np.int32)),
            n_real=int(e - s),
        )
        state = lifecycle.build_state(
            tree=tree, k=8, max_batch=32, max_delta_rows=64,
            # the serve-side SLO ladder is pinned OFF (empty specs) for
            # every arm: all N in-process shards share ONE history
            # ring, so a single over-the-knee step would page every
            # shard's healthz at once and the routers would mass-eject
            # the fleet — an artifact of single-process hosting, not a
            # property of either router arm under test
            slo_engine=obs_slo.SloEngine(specs=[]),
            meta={"spatial": {
                "grid": plan["grid"].to_json(),
                "code_range": [int(c0), int(c1)],
                "id_range": [int(s), int(e)],
                "shard": i, "shards": shards,
            }},
        )
        httpd = srv.make_server(state, port=0)
        httpd.start(warmup_buckets=[8])
        servers.append(httpd)
        urls.append(f"http://127.0.0.1:{httpd.server_address[1]}")
        if (i + 1) % 16 == 0:
            print(f"  fleet: {i + 1}/{shards} shards up",
                  file=sys.stderr)
    return servers, urls


def spawn_router(shard_urls, extra, log_path, timeout_s=60.0):
    """One ``kdtree-tpu route`` subprocess; returns (Popen, url)."""
    cmd = [sys.executable, "-m", "kdtree_tpu", "route"]
    for u in shard_urls:
        cmd += ["--shard", u]
    cmd += ["--port", "0"] + list(extra)
    log = open(log_path, "w")
    proc = subprocess.Popen(cmd, stderr=log, stdout=subprocess.DEVNULL,
                            env={**os.environ, "JAX_PLATFORMS": "cpu"})
    deadline = time.monotonic() + timeout_s
    port = None
    while time.monotonic() < deadline:
        with open(log_path) as f:
            m = _READY_RE.search(f.read())
        if m:
            port = m.group(1)
            break
        if proc.poll() is not None:
            raise RuntimeError(
                f"router died during startup; see {log_path}")
        time.sleep(0.2)
    if port is None:
        raise RuntimeError(f"router never became ready; see {log_path}")
    return proc, f"http://127.0.0.1:{port}"


def wait_topology(url, n, timeout_s=120.0):
    """Block until the router's health probes have learned a box for
    every shard (pruning is live) — otherwise the first ladder steps
    measure full scatter and the A/B compares different fan-outs."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(f"{url}/debug/shards",
                                        timeout=10) as r:
                rep = json.loads(r.read())["shards"]
            if len(rep) == n and all(
                    "box" in (s.get("detail") or {}) for s in rep):
                return
        except OSError:
            pass
        time.sleep(0.3)
    raise RuntimeError(f"topology never learned at {url}")


def stop(proc):
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--shards", type=int, default=16)
    ap.add_argument("--pts-per-shard", type=int, default=256)
    ap.add_argument("--arm", required=True,
                    choices=("fresh", "pooled", "flat", "hier"))
    ap.add_argument("--children", type=int, default=4,
                    help="child routers for --arm hier")
    ap.add_argument("--cloud", choices=("clustered", "uniform"),
                    default="clustered")
    ap.add_argument("--rates", default="40,80,120,160,200")
    ap.add_argument("--step-seconds", type=float, default=5.0)
    ap.add_argument("--slo-ms", type=float, default=150.0)
    ap.add_argument("--slo-quantile", type=float, default=0.99,
                    help="0.95 is the robust choice when the shard "
                         "host shares one core with the harness: a "
                         "single GC/scheduler stall in a short step "
                         "taints p99 with ~40 samples")
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--seed", type=int, default=23)
    ap.add_argument("--deadline-ms", type=float, default=2000.0)
    ap.add_argument("--hedge-ms", type=float, default=150.0,
                    help="hedge-delay floor for every router level; "
                         "the 50 ms default assumes multi-host tails, "
                         "and on a shared-core bench it turns queueing "
                         "into hedge storms")
    ap.add_argument("--ab-baseline", default=None)
    ap.add_argument("--variant", default=None,
                    help="capacity.variant label (default: the arm)")
    ap.add_argument("--out", required=True)
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    print(f"bench_router_ab: {args.shards} shards x "
          f"{args.pts_per_shard} pts, arm={args.arm}", file=sys.stderr)
    servers, urls = build_fleet(args.shards, args.pts_per_shard,
                                args.seed, cloud=args.cloud)
    # breakers pinned far out and health probes slowed for EVERY arm:
    # past the knee a single-host bench saturates, and a breaker storm
    # (open -> quorum 503 -> reset -> re-trip) turns the over-the-knee
    # steps into an error-rate measurement instead of a latency one.
    # The A/B compares router data paths, not ejection policy.
    # --no-slo for the same reason the serve-side ladder is pinned off
    # above: a PAGE is sticky for the whole burn window, so one
    # over-the-knee ladder step would leave child routers ejected (and
    # requests erroring) through every later step.
    route_common = ["--deadline-ms", str(args.deadline_ms),
                    "--retries", "0",
                    "--breaker-failures", "1000000",
                    "--health-period-s", "2.0",
                    "--hedge-ms", str(args.hedge_ms),
                    "--no-slo"]
    procs = []
    try:
        if args.arm == "hier":
            child_urls = []
            per = (len(urls) + args.children - 1) // args.children
            for ci in range(args.children):
                sub = urls[ci * per:(ci + 1) * per]
                if not sub:
                    continue
                proc, curl = spawn_router(
                    sub, route_common, f"bench_child{ci}.log")
                procs.append(proc)
                wait_topology(curl, len(sub))
                child_urls.append(curl)
            top, target = spawn_router(
                child_urls, route_common + ["--parent"],
                "bench_parent.log")
            procs.append(top)
            wait_topology(target, len(child_urls))
        else:
            extra = list(route_common)
            if args.arm == "fresh":
                extra.append("--no-pool")
            top, target = spawn_router(urls, extra, "bench_router.log")
            procs.append(top)
            wait_topology(target, len(urls))

        from kdtree_tpu.utils import cli

        lg = ["loadgen", "--target", target,
              "--rates", args.rates,
              "--step-seconds", str(args.step_seconds),
              "--slo-ms", str(args.slo_ms),
              "--slo-quantile", str(args.slo_quantile),
              "--mix", "query:1", "--k", str(args.k),
              "--seed", str(args.seed),
              "--variant", args.variant or args.arm,
              "--out", args.out]
        if args.ab_baseline:
            lg += ["--ab-baseline", args.ab_baseline]
        cli.main(lg)
    finally:
        for proc in reversed(procs):
            with contextlib.suppress(OSError):
                stop(proc)
        for httpd in servers:
            httpd.stop()
    with open(args.out) as f:
        cap = json.load(f)["capacity"]
    print(json.dumps({
        "arm": args.arm, "shards": args.shards,
        "knee_rate": cap["knee_rate"],
        "conn_reuse_frac": cap.get("conn_reuse_frac"),
        "ab": cap.get("ab"),
    }, indent=2), file=sys.stderr)


if __name__ == "__main__":
    main()
