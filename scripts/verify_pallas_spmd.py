"""Verify the Pallas kernel inside the SPMD serving path on the real chip
(VERDICT r4 item 3 / next-round #3).

On TPU backends ``plan_tiled`` flips ``use_pallas=True``
(``ops/tile_query.py``), so the FIRST real-TPU dense forest query takes a
code path — Mosaic kernel inside ``shard_map`` — that off-TPU tests only
exercise in interpret mode. This is a thin CLI over the same
``bench.bench_spmd_pallas`` measurement the driver bench records, for
one-off runs outside a full bench sweep.

Usage: python scripts/verify_pallas_spmd.py [--n 22] [--q 16] [--k 16]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=22, help="log2 points")
    ap.add_argument("--q", type=int, default=16, help="log2 queries")
    ap.add_argument("--k", type=int, default=16)
    args = ap.parse_args()

    import bench
    import kdtree_tpu as kt

    backend = jax.default_backend()
    n, q, k = 1 << args.n, 1 << args.q, args.k
    dt, use_pallas, ok = bench.bench_spmd_pallas(kt, n, 3, q, k)
    if backend == "tpu" and not use_pallas:
        print(json.dumps({"ok": False, "reason": "plan did not select the "
                          "Pallas kernel on a TPU backend"}))
        sys.exit(1)
    print(json.dumps({
        "ok": bool(ok),
        "backend": backend,
        "use_pallas": bool(use_pallas),
        "n": n, "q": q, "k": k,
        "q_per_s": round(q / dt),
        "note": "Mosaic kernel under shard_map (1-device mesh), "
                "oracle-checked" if ok else "MISMATCH vs oracle",
    }))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
