"""Measure the DFS engine's chunk loop at the sparse 64k-query shape on
the real chip (VERDICT r3 item 8 / r4 item 9).

Code analysis says ``morton_knn``'s chunk loop is already async — each
``_morton_knn_batch`` dispatch returns without a host fetch, so the ~16
device programs queue back-to-back and the single sync happens at the
final concatenate. The async leg reuses ``bench.bench_sparse_dfs`` (the
same measurement the driver bench records); this script adds the
per-chunk-SYNCED contrast run that quantifies what the async dispatch
saves.

Run on the real chip; one JSON line out.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np


def main():
    n, dim, q, k, chunk = 1 << 24, 3, 1 << 16, 16, 4096
    backend = jax.default_backend()

    import bench
    import kdtree_tpu as kt
    from kdtree_tpu.ops.generate import generate_points_rowwise, generate_queries
    from kdtree_tpu.ops.morton import _morton_knn_batch, build_morton

    pts = generate_points_rowwise(3, dim, n)
    tree = build_morton(pts)
    jax.block_until_ready(tree.bucket_pts)

    t_async, ok = bench.bench_sparse_dfs(kt, tree, pts, q, k)

    qs = generate_queries(55, dim, q)
    np.asarray(_morton_knn_batch(tree, qs[:chunk], k, chunk)[0][:1])  # warmup
    t0 = time.perf_counter()
    for i in range(0, q, chunk):
        d2c, _ = _morton_knn_batch(tree, qs[i : i + chunk], k, chunk)
        np.asarray(d2c[:1])  # forced per-chunk host sync (the contrast)
    t_sync = time.perf_counter() - t0

    print(json.dumps({
        "ok": bool(ok),
        "backend": backend, "n": n, "q": q, "k": k, "chunk": chunk,
        "async_s": round(t_async, 4),
        "per_chunk_sync_s": round(t_sync, 4),
        "async_q_per_s": round(q / t_async),
        "sync_overhead_x": round(t_sync / t_async, 2),
        "loop_is_async": bool(t_async <= t_sync * 1.02),
    }))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
