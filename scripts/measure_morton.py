"""Morton chain timing at 16M x 3D on the real chip (one-off profiling aid)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

import kdtree_tpu as kt


def sync(out):
    jax.tree.map(lambda x: np.asarray(x.ravel()[:4]) if hasattr(x, "shape") else x, out)


def timeit(label, fn, reps=3):
    sync(fn(999))
    ts = []
    for seed in range(1, reps + 1):
        t0 = time.perf_counter()
        sync(fn(seed))
        ts.append(time.perf_counter() - t0)
    print(f"{label}: best {min(ts):.3f}s  all {[round(t, 3) for t in ts]}", flush=True)
    return min(ts)


def main():
    n, dim, nq = 1 << 24, 3, 10
    print(f"platform={jax.devices()[0].platform} n={n}", flush=True)

    def gen(seed):
        return kt.generate_problem(seed=seed, dim=dim, num_points=n, num_queries=nq)

    for cap in (128, 256):
        def chain(seed, cap=cap):
            pts, qs = gen(seed)
            tree = kt.build_morton(pts, bucket_cap=cap)
            return kt.morton_knn(tree, qs, k=1)[0]

        timeit(f"gen+build_morton(cap={cap})+10NN", chain)

    # oracle sanity at 16M on the chip
    pts, qs = gen(7)
    tree = kt.build_morton(pts)
    d2, _ = kt.morton_knn(tree, qs, k=1)
    bf, _ = kt.bruteforce.knn_exact_d2(pts, qs, k=1)
    ok = np.allclose(np.asarray(d2)[:, 0], np.asarray(bf)[:, 0], rtol=1e-5)
    print("oracle check:", "OK" if ok else "FAIL", flush=True)

    # query throughput: 1M queries k=16
    qbig = kt.generate_problem(seed=11, dim=dim, num_points=1 << 20, num_queries=1)[0]

    def qchain(seed):
        return kt.morton_knn(tree, qbig + seed * 0.001, k=16)[0]

    t = timeit("1M queries k=16 (morton)", qchain)
    print(f"query throughput: {(1 << 20) / t / 1e6:.2f}M q/s", flush=True)


if __name__ == "__main__":
    main()
