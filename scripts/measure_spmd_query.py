"""Forest serving strategies on the 8-virtual-device CPU mesh: SPMD
shard_map vs the mesh-free flat view vs the old sequential per-tree loop
(VERDICT r3 item 2's comparison, extended for the round-5 flat view).

The virtual mesh shares ONE host's cores, so wall-clock here measures
total WORK, not parallel speedup: the flat view does the least work (one
frontier + one candidate set over all rows) and wins on a shared core,
while SPMD's per-device programs win wall-clock only when P real chips
run them concurrently. The round-4 "5.7x SPMD vs sequential" number
compared against the per-tree loop (P frontiers, P full-Q scans) — that
loop is now only the HBM-overflow fallback; the flat view replaced it as
the mesh-free default (measured 7.7x over the loop at the test shape).

Run alone (no concurrent pytest — host contention corrupts timings).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from kdtree_tpu.ops.generate import generate_queries
from kdtree_tpu.parallel.global_morton import (
    _query_tiled_meshfree, _query_tiled_spmd, build_global_morton,
)
from kdtree_tpu.parallel.mesh import make_mesh


def fetch(x):
    return np.asarray(x[0].ravel()[:1])


def timed(fn, qs_warm, qs):
    fetch(fn(qs_warm))  # compile
    t0 = time.perf_counter()
    out = fn(qs)
    fetch(out)
    return time.perf_counter() - t0, out


def main():
    import argparse

    ap = argparse.ArgumentParser()
    # defaults sized for the 1-core CI host (the 2^20/2^16 shape runs >50min
    # there); pass --n 20 --q 16 on a real multi-core box
    ap.add_argument("--n", type=int, default=19)
    ap.add_argument("--q", type=int, default=14)
    args = ap.parse_args()
    n, dim, k, p = 1 << args.n, 3, 16, 8
    Q = 1 << args.q
    mesh = make_mesh(p)
    forest = build_global_morton(3, dim, n, mesh=mesh)
    qs = generate_queries(11, dim, Q)
    qs2 = generate_queries(12, dim, Q)

    dt_spmd, out_s = timed(
        lambda q: _query_tiled_spmd(forest, q, k, mesh), qs2, qs)

    # the SPMD path never touches the _dense_view cache, so the same
    # forest object serves the flat-view measurement
    dt_view, out_v = timed(
        lambda q: _query_tiled_meshfree(forest, q, k), qs2, qs)

    # the old per-tree loop = today's HBM-overflow fallback; force it by
    # making the capacity check refuse the flat view
    import kdtree_tpu.ops.morton as morton_mod

    f_seq = build_global_morton(3, dim, n, mesh=mesh)
    real_check = morton_mod.check_build_capacity

    def refuse(*a, **kw):
        raise morton_mod.BuildCapacityError("forced: measuring the fallback")

    morton_mod.check_build_capacity = refuse
    try:
        dt_seq, out_q = timed(
            lambda q: _query_tiled_meshfree(f_seq, q, k), qs2, qs)
    finally:
        morton_mod.check_build_capacity = real_check
    # sentinel: if the patched guard ever stops being consulted (e.g. the
    # call-time import gets hoisted), this row would silently re-time the
    # flat view and publish a wrong number — fail loudly instead
    assert getattr(f_seq, "_dense_view", None) is None, (
        "fallback measurement actually took the flat-view path"
    )

    for other in (out_v, out_q):
        np.testing.assert_allclose(
            np.asarray(out_s[0]), np.asarray(other[0]), rtol=1e-6)
    print(f"n={n} Q={Q} k={k} P={p} (CPU virtual mesh — wall-clock here "
          "tracks total work, not parallel speedup)")
    print(f"SPMD shard_map tiled     : {dt_spmd:.2f}s = {Q/dt_spmd:,.0f} q/s")
    print(f"mesh-free flat view      : {dt_view:.2f}s = {Q/dt_view:,.0f} q/s")
    print(f"per-tree loop (fallback) : {dt_seq:.2f}s = {Q/dt_seq:,.0f} q/s")
    print(f"flat view vs loop: {dt_seq/dt_view:.2f}x   "
          f"SPMD vs loop: {dt_seq/dt_spmd:.2f}x (answers identical)")


if __name__ == "__main__":
    main()
