"""SPMD vs sequential tiled forest query on the 8-virtual-device CPU mesh
(VERDICT r3 item 2's comparison; the virtual mesh shares one host's cores,
so the interesting number is work SAVED — each SPMD device scans ~N/P
points once, while the sequential path scans all P trees at full Q).

Run alone (no concurrent pytest — host contention corrupts timings).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from kdtree_tpu.ops.generate import generate_queries
from kdtree_tpu.parallel.global_morton import (
    _query_tiled_meshfree, _query_tiled_spmd, build_global_morton,
)
from kdtree_tpu.parallel.mesh import make_mesh


def fetch(x):
    return np.asarray(x[0].ravel()[:1])


def main():
    import argparse

    ap = argparse.ArgumentParser()
    # defaults sized for the 1-core CI host (the 2^20/2^16 shape runs >50min
    # there); pass --n 20 --q 16 on a real multi-core box
    ap.add_argument("--n", type=int, default=19)
    ap.add_argument("--q", type=int, default=14)
    args = ap.parse_args()
    n, dim, k, p = 1 << args.n, 3, 16, 8
    Q = 1 << args.q
    mesh = make_mesh(p)
    forest = build_global_morton(3, dim, n, mesh=mesh)
    qs = generate_queries(11, dim, Q)
    qs2 = generate_queries(12, dim, Q)

    out_s = _query_tiled_spmd(forest, qs2, k, mesh)  # compile
    fetch(out_s)
    t0 = time.perf_counter()
    out_s = _query_tiled_spmd(forest, qs, k, mesh)
    fetch(out_s)
    dt_spmd = time.perf_counter() - t0

    out_m = _query_tiled_meshfree(forest, qs2, k)  # compile
    fetch(out_m)
    t0 = time.perf_counter()
    out_m = _query_tiled_meshfree(forest, qs, k)
    fetch(out_m)
    dt_seq = time.perf_counter() - t0

    np.testing.assert_allclose(
        np.asarray(out_s[0]), np.asarray(out_m[0]), rtol=1e-6
    )
    print(f"n={n} Q={Q} k={k} P={p} (CPU virtual mesh)")
    print(f"SPMD shard_map tiled: {dt_spmd:.2f}s = {Q/dt_spmd:,.0f} q/s")
    print(f"sequential per-tree : {dt_seq:.2f}s = {Q/dt_seq:,.0f} q/s")
    print(f"speedup: {dt_seq/dt_spmd:.2f}x (answers identical)")


if __name__ == "__main__":
    main()
