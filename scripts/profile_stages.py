"""Stage-level profile of the Morton build + tiled query with an HBM
roofline (VERDICT r3 item 3): decomposes the two hot paths into jitted
stages — the tree always passed as a jit ARGUMENT, never closed over
(closing over a 400MB tree bakes it into the HLO as constants and crashes
the remote compile with HTTP 413) — times each on the real chip, and
reports achieved HBM bytes/s against the chip's peak so "fast" is stated
relative to the hardware ceiling, not a 15-year-old Xeon core.

Byte accounting is exact for the build stages (pure streaming reads/
writes) and a documented upper bound for the query stages (the frontier's
gather traffic and the scan's per-candidate DMA; the Pallas kernel's
early exit makes true scan traffic strictly less than the candidate
bound, so achieved-of-peak there is a LOWER bound on efficiency).

Usage: python scripts/profile_stages.py [--n 24] [--q 16] [--cpu]
  --n: log2 points (default 24 = 16M, the headline shape)
  --q: log2 queries per measured batch (default 16 = one tile_query batch)
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# v5e: 16 GiB HBM @ ~819 GB/s, 1 TensorCore. The roofline denominator.
HBM_PEAK_GBS = {"tpu": 819.0, "cpu": 50.0}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=24)
    ap.add_argument("--q", type=int, default=16)
    ap.add_argument("--k", type=int, default=16)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    import kdtree_tpu as kt
    from kdtree_tpu import obs
    from kdtree_tpu.ops.morton import morton_codes
    from kdtree_tpu.ops import tile_query as tq
    from kdtree_tpu.ops.tile_query import (
        _frontier, _scan_tiles, _sort_queries, plan_tiled,
    )

    # telemetry sidecar alongside the stage table (shared contract with
    # bench.py via obs.sidecar_path: env override, =none disables)
    metrics_out = obs.sidecar_path("profile_telemetry.json")
    if metrics_out:
        from kdtree_tpu.obs import jaxrt

        obs.configure(metrics_out=metrics_out)
        jaxrt.probe_devices()

    platform = jax.devices()[0].platform
    peak = HBM_PEAK_GBS.get(platform, 100.0)
    n, Q, k, D = 1 << args.n, 1 << args.q, args.k, 3

    def fetch(x):
        return np.asarray(jax.tree.leaves(x)[0].ravel()[:1])

    def timeit(label, fn, *fargs, nbytes=None, reps=5):
        fetch(fn(*fargs))  # compile + warm
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fetch(fn(*fargs))
            ts.append(time.perf_counter() - t0)
        dt = min(ts)
        gbs = (nbytes / dt / 1e9) if nbytes else None
        pct = f" {gbs:7.1f} GB/s = {100*gbs/peak:5.1f}% of {platform} peak" if gbs else ""
        print(f"{label:34s} {dt*1e3:9.2f} ms{pct}")
        return dt

    print(f"platform={platform} n=2^{args.n} Q=2^{args.q} k={k} "
          f"(peak {peak:.0f} GB/s)")

    # ---- build stages ----------------------------------------------------
    pts, _ = kt.generate_problem(seed=1, dim=D, num_points=n, num_queries=1)
    bits = 10

    codes_j = jax.jit(functools.partial(morton_codes, bits=bits))
    # stage bytes: read [n,3] f32, write u32 codes
    timeit("build: morton codes", codes_j, pts, nbytes=n * 16)

    code = codes_j(pts)
    gid = jnp.arange(n, dtype=jnp.int32)

    @jax.jit
    def sort_stage(code, gid, pts):
        return lax.sort((code, gid, *(pts[:, a] for a in range(D))),
                        num_keys=1, is_stable=True)

    # 5 u32/f32 columns in + out
    timeit("build: 5-col one-shot sort", sort_stage, code, gid, pts,
           nbytes=2 * n * 20)

    full_build = jax.jit(lambda p: kt.build_morton(p))
    timeit("build: full (codes+sort+AABB)", full_build, pts,
           nbytes=2 * n * 20 + n * 16 + 2 * n * 16)

    tree = kt.build_morton(pts)
    nbp, B = tree.num_buckets, tree.bucket_size

    # ---- query stages ----------------------------------------------------
    from kdtree_tpu.ops.generate import generate_queries

    queries = generate_queries(7, D, Q)
    plan = plan_tiled(Q, D, n, nbp, B, k)
    print(f"plan: tile={plan.tile} cmax={plan.cmax} seeds={plan.seeds} "
          f"pallas={plan.use_pallas}")

    sort_q = jax.jit(functools.partial(_sort_queries, bits=plan.bits, qpad=0))
    timeit("query: hilbert sort", sort_q, queries, nbytes=2 * Q * 16)
    sq, order = sort_q(queries)

    tile = plan.tile
    tq3 = sq.reshape(-1, tile, D)
    box_lo, box_hi = jnp.min(tq3, axis=1), jnp.max(tq3, axis=1)
    T = tq3.shape[0]
    inf_bound = jnp.full(T, jnp.inf, jnp.float32)

    fr_seed = jax.jit(functools.partial(_frontier, cap=plan.seeds))
    # frontier traffic bound: per level, gather 2*cap node boxes (2 arrays x
    # D axes x 4B) per tile
    fr_bytes = T * tree.num_levels * 2 * plan.seeds * 2 * D * 4
    timeit("query: seed frontier", fr_seed, tree, box_lo, box_hi, inf_bound,
           nbytes=fr_bytes)
    seed_cand, seed_lb, _ = fr_seed(tree, box_lo, box_hi, inf_bound)

    if plan.use_pallas:
        from kdtree_tpu.pallas.scan_knn import scan_tiles_fused

        scan = jax.jit(functools.partial(scan_tiles_fused, k=k))
        scan_args = (tree, tq3, seed_cand, seed_lb)
    else:
        scan = jax.jit(functools.partial(
            _scan_tiles, k=k, v=plan.v, tb=plan.tb))
        scan_args = (tree, tq3, seed_cand, seed_lb)
    # candidate-bound DMA traffic: every finite candidate bucket's coords+ids
    seed_bytes = int(np.asarray((seed_cand >= 0).sum())) * B * (D + 1) * 4
    timeit("query: seed scan", scan, *scan_args, nbytes=seed_bytes)

    sd = scan(*scan_args)[0]
    tile_bound = jnp.max(sd[..., k - 1], axis=1)
    fr_col = jax.jit(functools.partial(_frontier, cap=plan.cmax))
    fr2_bytes = T * tree.num_levels * 2 * plan.cmax * 2 * D * 4
    timeit("query: collect frontier", fr_col, tree, box_lo, box_hi,
           tile_bound, nbytes=fr2_bytes)
    cand, cand_lb, _ = fr_col(tree, box_lo, box_hi, tile_bound)
    cb = int(np.asarray((cand >= 0).sum())) * B * (D + 1) * 4
    if plan.use_pallas:
        timeit("query: collect scan (candidate-bound bytes)", scan, tree,
               tq3, cand, cand_lb, nbytes=cb)
    else:
        timeit("query: collect scan (candidate-bound bytes)", scan, tree,
               tq3, cand, cand_lb, nbytes=cb)
    print(f"candidates/tile: seed={plan.seeds} collect "
          f"mean={float(np.asarray((cand >= 0).sum(axis=1).mean())):.1f} "
          f"max={int(np.asarray((cand >= 0).sum(axis=1).max()))} "
          f"(cap {plan.cmax})")

    # --- A/B: SORT frontier variant (the pre-r5 library form) -----------
    # The library _frontier switched to top_k(-lb) in round 5 (kept sets
    # identical, ~2.5x faster stage time on CPU); this contrast re-measures
    # the old full-2C-sort form so the A/B stays two-sided on every
    # platform the script runs on.
    from kdtree_tpu.ops.tile_query import _gathered_box_lb

    def _frontier_sort(tree, box_lo, box_hi, bound, cap: int):
        T = box_lo.shape[0]
        L = tree.num_levels
        nbp = tree.num_buckets
        first_leaf = nbp - 1
        s = min(max(cap.bit_length() - 1, 0), L)
        m = 1 << s
        ids = jnp.broadcast_to(jnp.arange(m, dtype=jnp.int32) + (m - 1), (T, m))
        lb = _gathered_box_lb(tree, box_lo, box_hi, ids)
        lb = jnp.where(lb <= bound[:, None], lb, jnp.inf)
        overflow = jnp.sum(jnp.isfinite(lb), axis=1) > cap
        if m < cap:
            ids = jnp.concatenate([ids, jnp.zeros((T, cap - m), jnp.int32)], axis=1)
            lb = jnp.concatenate([lb, jnp.full((T, cap - m), jnp.inf)], axis=1)
        lb, ids = lax.sort((lb, ids), num_keys=1, is_stable=True)
        ids, lb = ids[:, :cap], lb[:, :cap]
        for _ in range(s, L):
            alive = jnp.isfinite(lb)
            cids = jnp.concatenate([2 * ids + 1, 2 * ids + 2], axis=1)
            calive = jnp.concatenate([alive, alive], axis=1)
            safe = jnp.clip(cids, 0, tree.heap_size - 1)
            clb = _gathered_box_lb(tree, box_lo, box_hi, safe)
            clb = jnp.where(calive & (clb <= bound[:, None]), clb, jnp.inf)
            overflow = overflow | (jnp.sum(jnp.isfinite(clb), axis=1) > cap)
            clb, cids = lax.sort((clb, cids), num_keys=1, is_stable=True)
            ids, lb = cids[:, :cap], clb[:, :cap]
        bucket = jnp.where(jnp.isfinite(lb), ids - first_leaf, -1)
        return bucket, lb, overflow

    frs = jax.jit(functools.partial(_frontier_sort, cap=plan.cmax))
    timeit("query: collect frontier (sort A/B)", frs, tree, box_lo, box_hi,
           tile_bound, nbytes=fr2_bytes)
    ck, _, _ = frs(tree, box_lo, box_hi, tile_bound)
    same = bool(np.asarray(
        (jnp.sort(jnp.where(cand < 0, 1 << 30, cand), axis=1)
         == jnp.sort(jnp.where(ck < 0, 1 << 30, ck), axis=1)).all()
    ))
    print(f"sort frontier kept sets identical to top_k frontier: {same}")

    # host-side batch driver (jits internally); timed as-is
    fetch(tq.morton_knn_tiled(tree, queries, k=k))
    t0 = time.perf_counter()
    fetch(tq.morton_knn_tiled(tree, queries, k=k))
    dt = time.perf_counter() - t0
    print(f"{'query: full tiled pipeline':34s} {dt*1e3:9.2f} ms "
          f"({Q/dt:,.0f} q/s)")

    if metrics_out:
        # guarded: the stage table above already printed — failed telemetry
        # must not turn a successful profile into a crash
        if obs.finalize_guarded(
            extra={"platform": platform, "n": n, "q": Q, "k": k}
        ) is not None:
            print(f"telemetry sidecar written to {metrics_out}",
                  file=sys.stderr)


if __name__ == "__main__":
    main()
