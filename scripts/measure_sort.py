"""Per-level sort-variant microbenchmark at 16M (one-off profiling aid).

The build's per-level cost is one stable lax.sort over composite keys; this
compares key/payload packings to pick the cheapest on real hardware.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def sync(out):
    jax.tree.map(lambda x: np.asarray(x.ravel()[:4]) if hasattr(x, "shape") else x, out)


def timeit(label, fn, *args, reps=3):
    f = jax.jit(fn)
    sync(f(*args))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        sync(f(*args))
        ts.append(time.perf_counter() - t0)
    print(f"{label}: best {min(ts)*1000:.1f}ms  all {[round(t*1000) for t in ts]}", flush=True)


def main():
    n = 1 << 24
    print(f"platform={jax.devices()[0].platform} n={n}", flush=True)
    key = jax.random.key(0)
    coord = jax.random.uniform(key, (n,), jnp.float32, -100, 100)
    xyz = jax.random.uniform(key, (n, 3), jnp.float32, -100, 100)
    segkey = (jnp.arange(n, dtype=jnp.int32) >> 12) * 2
    perm = jnp.arange(n, dtype=jnp.int32)
    consume = jnp.asarray(np.random.default_rng(0).integers(0, 24, n, np.int32))

    def sort3(segkey, coord, perm):
        return lax.sort((segkey, coord, perm), num_keys=3, is_stable=True)[2]

    def fkey(coord):
        b = lax.bitcast_convert_type(coord, jnp.uint32)
        return jnp.where(b >> 31 != 0, ~b, b | jnp.uint32(0x80000000))

    def sort_u64(segkey, coord, perm):
        packed = (segkey.astype(jnp.uint64) << 32) | fkey(coord).astype(jnp.uint64)
        return lax.sort((packed, perm), num_keys=1, is_stable=True)[1]

    def sort_u64_payload(segkey, xyz, perm):
        packed = (segkey.astype(jnp.uint64) << 32) | fkey(xyz[:, 0]).astype(jnp.uint64)
        out = lax.sort(
            (packed, xyz[:, 0], xyz[:, 1], xyz[:, 2], perm), num_keys=1, is_stable=True
        )
        return out[4]

    def sort2_u32(segkey, coord, perm):
        return lax.sort((segkey, fkey(coord), perm), num_keys=2, is_stable=True)[2]

    def gather_axis(perm, xyz):
        return xyz[perm, 1]

    def level_scans(consume):
        lvl = 12
        dead = (consume < lvl).astype(jnp.int32)
        csum = jnp.cumsum(dead)
        return 2 * csum - dead

    timeit("sort 3-key (i32,f32,i32)", sort3, segkey, coord, perm)
    timeit("sort 1-key u64 + i32 payload", sort_u64, segkey, coord, perm)
    timeit("sort 1-key u64 + xyz+id payload", sort_u64_payload, segkey, xyz, perm)
    timeit("sort 2-key (i32,u32) + i32", sort2_u32, segkey, coord, perm)
    timeit("gather coords[perm]", gather_axis, perm, xyz)
    timeit("segkey scans", level_scans, consume)
    timeit("top_k 16 of 16M", lambda c: lax.top_k(c, 16)[0], coord)


if __name__ == "__main__":
    main()
