"""Measure build/query path candidates on the real chip at 16M x 3D.

Not a test — a one-off profiling aid for picking the headline bench chain.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

import kdtree_tpu as kt
from kdtree_tpu.ops.build_presort import build_presort
from kdtree_tpu.ops.bucket import build_bucket, bucket_knn


def sync(out):
    # fetch only a few elements per leaf: forces the producer computation to
    # finish without paying a 100+MB device->host transfer over the tunnel
    jax.tree.map(
        lambda x: np.asarray(x.ravel()[:4]) if hasattr(x, "shape") else x, out
    )


def timeit(label, fn, reps=3):
    # warmup/compile
    sync(fn(999))
    ts = []
    for seed in range(1, reps + 1):
        t0 = time.perf_counter()
        sync(fn(seed))
        ts.append(time.perf_counter() - t0)
    print(f"{label}: best {min(ts):.3f}s  all {[round(t, 3) for t in ts]}", flush=True)
    return min(ts)


def main():
    n, dim, nq = 1 << 24, 3, 10
    print(f"platform={jax.devices()[0].platform} n={n} dim={dim}", flush=True)

    def gen(seed):
        return kt.generate_problem(seed=seed, dim=dim, num_points=n, num_queries=nq)

    timeit("gen only", lambda s: gen(s)[0])

    def chain_sort(seed):
        pts, qs = gen(seed)
        tree = kt.build_jit(pts)
        return kt.nearest_neighbor(tree, qs)[0]

    def chain_presort(seed):
        pts, qs = gen(seed)
        tree = build_presort(pts)
        return kt.nearest_neighbor(tree, qs)[0]

    def chain_bucket(seed):
        pts, qs = gen(seed)
        tree = build_bucket(pts)
        return bucket_knn(tree, qs, k=1)[0]

    timeit("gen+build_jit+10NN", chain_sort)
    timeit("gen+build_presort+10NN", chain_presort)
    timeit("gen+build_bucket+10NN", chain_bucket)

    # build-only splits
    def build_only(builder):
        pts_cache = {}

        def f(seed):
            if seed not in pts_cache:
                pts_cache[seed] = gen(seed)[0]
                np.asarray(pts_cache[seed][:1])
            return builder(pts_cache[seed])

        return f

    timeit("build_jit only", build_only(lambda p: kt.build_jit(p).node_point))
    timeit("build_presort only", build_only(lambda p: build_presort(p).node_point))
    timeit("build_bucket only", build_only(lambda p: build_bucket(p).node_gid))


if __name__ == "__main__":
    main()
