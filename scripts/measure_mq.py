"""Morton query-throughput probe with conservative chunking (one-off)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

import kdtree_tpu as kt


def sync(out):
    jax.tree.map(lambda x: np.asarray(x.ravel()[:4]) if hasattr(x, "shape") else x, out)


def main():
    n, dim = 1 << 24, 3
    chunk = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    nq = int(sys.argv[2]) if len(sys.argv) > 2 else 1 << 18
    pts, _ = kt.generate_problem(seed=7, dim=dim, num_points=n, num_queries=1)
    tree = kt.build_morton(pts, bucket_cap=128)
    qs = kt.generate_problem(seed=11, dim=dim, num_points=nq, num_queries=1)[0]
    sync(kt.morton_knn(tree, qs, k=16, chunk=chunk)[0])
    ts = []
    for i in (1, 2):
        t0 = time.perf_counter()
        sync(kt.morton_knn(tree, qs + 0.001 * i, k=16, chunk=chunk)[0])
        ts.append(time.perf_counter() - t0)
    t = min(ts)
    print(f"chunk={chunk} nq={nq}: {t:.3f}s = {nq / t / 1e6:.2f}M q/s", flush=True)


if __name__ == "__main__":
    main()
