"""The runtime lock-order sanitizer (kdtree_tpu/analysis/lockwatch.py).

Unit coverage for the watcher semantics (order graph, cycle fail-fast,
reentrancy, hold budget, artifact schema), plus the two HISTORICAL
deadlocks re-pinned under ``KDTREE_TPU_LOCKWATCH=1`` — the satellite
contract of ISSUE 11:

- SIGUSR2 firing inside ``FlightRecorder.record()``'s critical section
  (the PR 5 deadlock; the RLock fix must hold under instrumentation);
- a breaker transition concurrent with ``allow()`` (the PR 9 stall; the
  transition's file I/O must run OUTSIDE the breaker lock, which the
  hold-budget tracking now proves mechanically).

No jax API anywhere (package import aside): tier-1-cheap.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time

import pytest

from kdtree_tpu.analysis import lockwatch


@pytest.fixture
def watched(monkeypatch, tmp_path):
    """Lockwatch ON with an isolated artifact dir and a fresh graph.

    The watcher is a process singleton shared with an env-enabled
    tier-1 run, and its atexit artifact is the CI gate's input — so the
    pre-test graph is stashed and MERGED BACK after, rather than wiped:
    evidence (edges, hold violations) accumulated by every other test
    must survive this file's isolation."""
    monkeypatch.setenv(lockwatch.ENV_ENABLE, "1")
    monkeypatch.setenv(lockwatch.ENV_DIR, str(tmp_path))
    monkeypatch.delenv(lockwatch.ENV_STRICT, raising=False)
    w = lockwatch.watcher()
    saved = w.export_state()
    w.reset()
    yield w
    w.reset()
    w.merge_state(saved)


# ---------------------------------------------------------------------------
# factory semantics
# ---------------------------------------------------------------------------


def test_disabled_factories_return_plain_stdlib(monkeypatch):
    monkeypatch.delenv(lockwatch.ENV_ENABLE, raising=False)
    assert type(lockwatch.make_lock("x")) is type(threading.Lock())
    assert isinstance(lockwatch.make_rlock("x"), type(threading.RLock()))
    assert isinstance(lockwatch.make_condition("x"), threading.Condition)


def test_enabled_factories_instrument(watched):
    lk = lockwatch.make_lock("t.lock")
    assert isinstance(lk, lockwatch.WatchedLock)
    with lk:
        assert lk.locked()
    assert not lk.locked()
    rk = lockwatch.make_rlock("t.rlock")
    assert isinstance(rk, lockwatch.WatchedRLock)


def test_order_graph_records_edges_and_counts(watched):
    a = lockwatch.make_lock("t.a")
    b = lockwatch.make_lock("t.b")
    for _ in range(3):
        with a:
            with b:
                pass
    rep = watched.report()
    edges = {(e["from"], e["to"]): e for e in rep["edges"]}
    assert edges[("t.a", "t.b")]["count"] == 3
    assert edges[("t.a", "t.b")]["stack"]  # provenance for the artifact
    assert rep["cycles"] == []


def test_lock_order_inversion_raises_and_records(watched):
    a = lockwatch.make_lock("t.a")
    b = lockwatch.make_lock("t.b")
    with a:
        with b:
            pass
    err = []

    def inverted():
        try:
            with b:
                with a:
                    pass
        except lockwatch.LockOrderError as e:
            err.append(e)

    t = threading.Thread(target=inverted)
    t.start()
    t.join()
    assert err, "the inverted acquisition must fail fast"
    assert "t.a" in str(err[0]) and "t.b" in str(err[0])
    assert watched.cycles()  # recorded for the artifact/CI gate


def test_nonreentrant_self_reacquire_raises(watched):
    # the PR 5 deadlock in miniature: same thread, same plain lock —
    # without the sanitizer this blocks forever, with it it raises
    lk = lockwatch.make_lock("t.self")
    lk.acquire()
    with pytest.raises(lockwatch.LockOrderError, match="re-acquired"):
        lk.acquire()
    lk.release()


def test_rlock_reentrancy_is_clean(watched):
    rk = lockwatch.make_rlock("t.ring")
    with rk:
        with rk:
            with rk:
                pass
    assert watched.cycles() == []
    # one held entry per instance: no self-edges were minted
    assert all(e["from"] != e["to"] for e in watched.report()["edges"])


def test_nested_rlock_reacquire_with_intervening_lock_is_clean(watched):
    # `with R: with A: with R:` cannot deadlock (the thread owns R) and
    # orders against nothing — the re-acquire must mint NO reversed
    # A -> R edge against the real R -> A one (which would read as an
    # inversion and fail the CI gate on a legal pattern)
    r = lockwatch.make_rlock("t.outer")
    a = lockwatch.make_lock("t.mid")
    with r:
        with a:
            with r:
                pass
    assert watched.cycles() == []
    edges = {(e["from"], e["to"]) for e in watched.report()["edges"]}
    assert ("t.mid", "t.outer") not in edges


def test_same_name_different_instances_do_not_false_cycle(watched):
    # two locks sharing a ROLE (e.g. two shards' route.shard) nested is
    # not an inversion of the role against itself
    a1 = lockwatch.make_lock("t.shard")
    a2 = lockwatch.make_lock("t.shard")
    with a1:
        with a2:
            pass
    assert watched.cycles() == []


def test_io_hold_past_budget_is_recorded(watched, monkeypatch, tmp_path):
    monkeypatch.setenv(lockwatch.ENV_HOLD_MS, "1")
    lk = lockwatch.make_lock("t.io")
    with lk:
        (tmp_path / "x").write_text("x")  # audit: open -> did_io
        time.sleep(0.01)
    v = [x for x in watched.violations() if x["lock"] == "t.io"]
    assert v and v[0]["held_ms"] > 1.0 and v[0]["io"] is True


def test_io_free_hold_is_not_a_violation(watched, monkeypatch):
    monkeypatch.setenv(lockwatch.ENV_HOLD_MS, "1")
    lk = lockwatch.make_lock("t.cpu")
    with lk:
        time.sleep(0.01)  # long hold, but no I/O: compute is legal
    assert not [x for x in watched.violations() if x["lock"] == "t.cpu"]


def test_strict_mode_raises_deferred_at_next_acquire(watched, monkeypatch,
                                                     tmp_path):
    # the strict raise is DEFERRED to the thread's next blocking
    # acquire: raising from release would fire inside __exit__ (masking
    # the with-body's own exception) or inside Condition.wait's
    # release-save (corrupting the waiter list)
    monkeypatch.setenv(lockwatch.ENV_HOLD_MS, "1")
    monkeypatch.setenv(lockwatch.ENV_STRICT, "1")
    lk = lockwatch.make_lock("t.strict")
    with lk:  # must exit cleanly even though the hold violates
        (tmp_path / "y").write_text("y")
        time.sleep(0.01)
    assert not lk.locked()
    with pytest.raises(lockwatch.LockHoldError, match="while performing"):
        lk.acquire()
    # the pending error is consumed: the retry proceeds normally
    with lk:
        pass


def test_strict_mode_does_not_mask_with_body_exception(watched,
                                                       monkeypatch,
                                                       tmp_path):
    monkeypatch.setenv(lockwatch.ENV_HOLD_MS, "1")
    monkeypatch.setenv(lockwatch.ENV_STRICT, "1")
    lk = lockwatch.make_lock("t.strict2")
    with pytest.raises(ValueError, match="the real failure"):
        with lk:
            (tmp_path / "z").write_text("z")
            time.sleep(0.01)
            raise ValueError("the real failure")
    # the hold violation still surfaces — at the next acquire
    with pytest.raises(lockwatch.LockHoldError):
        lk.acquire()


def test_condition_wait_notify_roundtrip(watched):
    cond = lockwatch.make_condition("t.cond")
    got = []

    def waiter():
        with cond:
            cond.wait(timeout=10)
            got.append(1)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cond:
        cond.notify_all()
    t.join(timeout=10)
    assert got == [1]
    assert watched.cycles() == []


def test_strict_mode_survives_condition_wait(watched, monkeypatch,
                                             tmp_path):
    # a hold violation noticed by wait()'s release-save must NOT raise
    # from the internal re-acquire (that would leave the condition lock
    # un-owned behind wait's back, corrupt the count, and ghost the
    # waiter) — it defers to the thread's next user-initiated acquire
    monkeypatch.setenv(lockwatch.ENV_HOLD_MS, "1")
    monkeypatch.setenv(lockwatch.ENV_STRICT, "1")
    cond = lockwatch.make_condition("t.strictcond")
    with cond:
        (tmp_path / "w").write_text("w")
        time.sleep(0.01)
        cond.wait(timeout=0.05)  # release-save sees the violation
        assert cond._lock._count == 1  # depth restored, not corrupted
    with pytest.raises(lockwatch.LockHoldError):
        cond.acquire()


def test_condition_wait_releases_recursive_holds(watched):
    # the stdlib Condition defaults to an RLock; the watched variant
    # must match — a wait() while the lock is held RECURSIVELY releases
    # every level (via _release_save) so the notifier can get in, then
    # restores the full depth
    cond = lockwatch.make_condition("t.rcond")
    got = []

    def waiter():
        cond.acquire()
        cond.acquire()  # recursive hold
        cond.wait(timeout=10)
        got.append(cond._lock._count)  # depth restored after wait
        cond.release()
        cond.release()

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cond:  # acquirable because wait released BOTH levels
        cond.notify_all()
    t.join(timeout=10)
    assert got == [2]
    assert watched.cycles() == []


def test_rlock_release_race_leaves_no_stranded_entries(watched):
    # regression for the release-race: still-held must be read BEFORE
    # the inner release, or a contender re-acquiring in the gap strands
    # the releasing thread's held entry — whose ghost then mints a
    # false "t.race -> t.probe*" edge from that thread's next acquire
    shared = lockwatch.make_rlock("t.race")
    probes = [lockwatch.make_lock(f"t.probe{i}") for i in range(2)]

    def churn(i):
        for _ in range(2000):
            with shared:
                pass
        with probes[i]:  # held stack must be empty by now
            pass

    threads = [threading.Thread(target=churn, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    bad = [e for e in watched.report()["edges"] if e["from"] == "t.race"]
    assert not bad, f"stranded held entry minted false edges: {bad}"


def test_dump_artifact_schema(watched, tmp_path):
    a = lockwatch.make_lock("t.a")
    b = lockwatch.make_lock("t.b")
    with a:
        with b:
            pass
    path = lockwatch.dump(str(tmp_path / "graph.json"))
    doc = json.load(open(path))
    assert doc["lockwatch_version"] == lockwatch.LOCKWATCH_VERSION
    assert doc["pid"] == os.getpid()
    assert doc["locks"]["t.a"] >= 1
    assert {"from": "t.a", "to": "t.b"}.items() <= doc["edges"][0].items()
    assert doc["cycles"] == [] and isinstance(doc["violations"], list)


def test_default_dump_path_is_pid_suffixed(watched, tmp_path):
    lk = lockwatch.make_lock("t.a")
    with lk:  # never leave a held entry stranded on the main thread:
        pass  # it would mint false edges into the process artifact
    path = lockwatch.dump()
    assert path == str(tmp_path / f"lockwatch-graph-{os.getpid()}.json")
    assert json.load(open(path))["locks"]


# ---------------------------------------------------------------------------
# regression: SIGUSR2 inside FlightRecorder.record()'s critical section
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not hasattr(signal, "SIGUSR2"),
                    reason="platform has no SIGUSR2")
def test_sigusr2_inside_record_critical_section_under_lockwatch(
    watched, tmp_path,
):
    """The PR 5 deadlock, re-pinned under the sanitizer: the handler
    fires while the MAIN thread sits inside the ring's critical section
    and dumps the ring — the reentrant acquire must succeed (no wedge,
    no LockOrderError) and the dump must be parseable. The plain-Lock
    variant of this exact shape is the KDT401 true-positive fixture in
    tests/test_analysis.py."""
    from kdtree_tpu.obs.flight import FlightRecorder

    rec = FlightRecorder(capacity=64)  # built with lockwatch ON
    assert isinstance(rec._lock, lockwatch.WatchedRLock)
    for i in range(5):
        rec.record("warmup", i=i)
    dump_path = tmp_path / "flight-sig.json"
    fired = []

    def _on_sig(signum, frame):
        fired.append(rec.dump(str(dump_path), reason="in-critical-section"))

    old = signal.signal(signal.SIGUSR2, _on_sig)
    try:
        with rec._lock:  # the middle of record()'s critical section
            os.kill(os.getpid(), signal.SIGUSR2)
            # the handler runs between bytecodes of THIS loop, while
            # the lock is held — give it a bytecode to land on
            for _ in range(1000):
                if fired:
                    break
    finally:
        signal.signal(signal.SIGUSR2, old)
    assert fired, "handler never ran"
    doc = json.load(open(dump_path))
    assert doc["reason"] == "in-critical-section"
    assert len(doc["events"]) == 5
    assert watched.cycles() == [], "handler reentry must not read as a cycle"


# ---------------------------------------------------------------------------
# regression: breaker transition concurrent with allow()
# ---------------------------------------------------------------------------


def test_breaker_transition_io_runs_outside_lock_under_lockwatch(
    watched, monkeypatch, tmp_path,
):
    """The PR 9 stall, re-pinned mechanically: the open-transition
    reporter writes a file (slow, past the hold budget) while other
    threads hammer allow(). The hold-budget tracking must see ZERO
    I/O-under-lock violations on route.breaker — proof the reporter
    runs outside the lock — and no ordering cycles. The under-the-lock
    variant is the KDT402 true-positive fixture in
    tests/test_analysis.py."""
    from kdtree_tpu.serve.router import CircuitBreaker

    monkeypatch.setenv(lockwatch.ENV_HOLD_MS, "5")
    dump_file = tmp_path / "breaker-dump.json"

    def slow_reporter(old, new):
        dump_file.write_text(json.dumps({"from": old, "to": new}))
        time.sleep(0.02)  # well past the 5 ms budget

    br = CircuitBreaker(failures=2, reset_s=0.05,
                        on_transition=slow_reporter)
    assert isinstance(br._lock, lockwatch.WatchedLock)
    stop = threading.Event()
    errors = []

    def hammer():
        try:
            while not stop.is_set():
                br.allow()
        except Exception as e:  # LockOrderError included
            errors.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(6):  # closed -> open -> half-open -> ... churn
            br.record_failure()
            br.record_failure()
            time.sleep(0.06)
            br.allow()
            br.record_success()
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errors
    assert dump_file.exists()  # transitions really did file I/O
    assert not [v for v in watched.violations()
                if v["lock"] == "route.breaker"], (
        "transition I/O leaked inside the breaker lock"
    )
    assert watched.cycles() == []


# ---------------------------------------------------------------------------
# the product stack under the sanitizer
# ---------------------------------------------------------------------------


def test_admission_queue_under_lockwatch(watched):
    """The admission queue's Condition is watched end to end: submit /
    pop_wait across threads, flight events recorded under the held
    condition — the serve.admission -> obs.* edges must be acyclic."""
    import numpy as np

    from kdtree_tpu.serve.admission import AdmissionQueue, PendingRequest

    q = AdmissionQueue(max_rows=8)
    popped = []

    def worker():
        while True:
            req = q.pop_wait(2.0)
            if req is None:
                return
            popped.append(req)
            req.fulfill(None, None)

    t = threading.Thread(target=worker)
    t.start()
    reqs = [PendingRequest(np.zeros((1, 3), np.float32), 1)
            for _ in range(4)]
    for r in reqs:
        q.submit(r)
    for r in reqs:
        assert r.event.wait(5)
    q.close()
    t.join(timeout=10)
    assert len(popped) == 4
    assert watched.cycles() == []
