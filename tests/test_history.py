"""Metric-history ring (obs/history.py): bounded snapshots, windowed
queries (counter delta/rate, gauge stats, histogram quantile / frac_le),
the background sampler, the kill switch, the flight-companion dump, and
the per-sample cost bound backing the <2% serving-overhead claim."""

import json
import time

import pytest

from kdtree_tpu.obs import history as hist
from kdtree_tpu.obs.registry import MetricsRegistry


def _reg_with_traffic():
    reg = MetricsRegistry()
    reg.counter("t_total", labels={"status": "ok"})
    reg.counter("t_total", labels={"status": "shed"})
    reg.gauge("g_frac")
    reg.histogram("lat_seconds", buckets=(0.1, 0.25, 0.5),
                  labels={"phase": "total"})
    return reg


# ---------------------------------------------------------------------------
# ring semantics
# ---------------------------------------------------------------------------


def test_ring_is_bounded_and_counts_dropped():
    h = hist.MetricHistory(capacity=4)
    reg = MetricsRegistry()
    for i in range(10):
        h.record(reg.snapshot(), ts=float(i))
    st = h.stats()
    assert st["samples"] == 4 and st["dropped"] == 6
    assert [s["ts"] for s in h.samples()] == [6.0, 7.0, 8.0, 9.0]
    # seq is monotone across the wrap
    assert [s["seq"] for s in h.samples()] == [6, 7, 8, 9]


def test_capacity_floor():
    with pytest.raises(ValueError):
        hist.MetricHistory(capacity=1)


def test_record_never_raises_on_garbage():
    h = hist.MetricHistory(capacity=4)
    h.record(None)          # type: ignore[arg-type]
    h.record({"counters": object()})
    # garbage either lands as an inert sample or is dropped — no raise
    assert h.stats()["samples"] <= 2


def test_window_filters_by_timestamp():
    h = hist.MetricHistory(capacity=16)
    reg = MetricsRegistry()
    for i in range(8):
        h.record(reg.snapshot(), ts=100.0 + i)
    assert len(h.samples(window_s=3.5, now=107.0)) == 4  # ts 103.5..107


# ---------------------------------------------------------------------------
# windowed queries
# ---------------------------------------------------------------------------


def test_counter_delta_and_rate_sum_label_sets():
    reg = _reg_with_traffic()
    h = hist.MetricHistory(capacity=8)
    h.record(reg.snapshot(), ts=100.0)
    reg.counter("t_total", labels={"status": "ok"}).inc(30)
    reg.counter("t_total", labels={"status": "shed"}).inc(10)
    h.record(reg.snapshot(), ts=102.0)
    assert h.counter_delta("t_total", 10, now=102.0) == 40.0
    assert h.counter_delta('t_total{status="shed"}', 10, now=102.0) == 10.0
    assert h.counter_rate("t_total", 10, now=102.0) == pytest.approx(20.0)
    # absent series / too few samples -> None, never a crash
    assert h.counter_delta("nope_total", 10, now=102.0) is None
    assert h.counter_delta("t_total", 0.5, now=102.0) is None


def test_gauge_stats_window():
    reg = _reg_with_traffic()
    h = hist.MetricHistory(capacity=8)
    for i, v in enumerate((0.9, 0.4, 0.2)):
        reg.gauge("g_frac").set(v)
        h.record(reg.snapshot(), ts=100.0 + i)
    st = h.gauge_stats("g_frac", 1.5, now=102.0)  # last two samples
    assert st["n"] == 2 and st["last"] == 0.2
    assert st["min"] == 0.2 and st["max"] == 0.4
    assert h.gauge_stats("absent", 10, now=102.0) is None


def test_histogram_windowed_quantile_and_frac_le():
    reg = _reg_with_traffic()
    h = hist.MetricHistory(capacity=8)
    lat = reg.histogram("lat_seconds", buckets=(0.1, 0.25, 0.5),
                        labels={"phase": "total"})
    # pre-window noise the window math must subtract out
    for _ in range(1000):
        lat.observe(0.4)
    h.record(reg.snapshot(), ts=100.0)
    for _ in range(90):
        lat.observe(0.05)
    for _ in range(10):
        lat.observe(0.4)
    h.record(reg.snapshot(), ts=101.0)
    key = 'lat_seconds{phase="total"}'
    le, total = h.frac_le(key, 0.25, 10, now=101.0)
    assert (le, total) == (90.0, 100.0)
    # p50 falls in the first bucket, p99 interpolates inside (0.25, 0.5]
    assert 0.0 < h.quantile(key, 0.50, 10, now=101.0) <= 0.1
    assert 0.25 < h.quantile(key, 0.99, 10, now=101.0) <= 0.5
    assert h.quantile("absent", 0.5, 10, now=101.0) is None


def test_frac_le_between_buckets_rounds_against_the_slo():
    """A threshold BETWEEN bucket bounds must count the in-between
    observations as violations (largest upper <= bound), never as good:
    rounding the other way hides a real latency burn between buckets
    from the SLO engine."""
    reg = MetricsRegistry()
    h = hist.MetricHistory(capacity=4)
    lat = reg.histogram("lat_seconds", buckets=(0.1, 0.25, 0.5),
                        labels={"phase": "total"})
    h.record(reg.snapshot(), ts=100.0)
    for _ in range(50):
        lat.observe(0.05)   # <= 0.1: genuinely good
    for _ in range(50):
        lat.observe(0.35)   # in (0.25, 0.5]: above a 0.3 threshold
    h.record(reg.snapshot(), ts=101.0)
    key = 'lat_seconds{phase="total"}'
    le, total = h.frac_le(key, 0.3, 10, now=101.0)  # bound between buckets
    assert (le, total) == (50.0, 100.0)  # counts only <= 0.25 as good
    # a bound below every bucket counts nothing as good, same reasoning
    assert h.frac_le(key, 0.01, 10, now=101.0) == (0.0, 100.0)


def test_mark_series_bounded():
    h = hist.MetricHistory(capacity=4)
    h.mark("slo_page")
    h.mark("slo_page")
    for i in range(200):
        # names past the cap are dropped, not stored (cardinality bound)
        h.mark(f"flood-{i}")
    rep = h.report()
    assert rep["marks"]["slo_page"]["count"] == 2.0
    assert len(rep["marks"]) <= 64


# ---------------------------------------------------------------------------
# report / dump
# ---------------------------------------------------------------------------


def test_report_shape_and_limit():
    reg = _reg_with_traffic()
    h = hist.MetricHistory(capacity=16)
    for i in range(6):
        h.record(reg.snapshot(), ts=100.0 + i)
    rep = h.report(limit=2)
    assert rep["history_version"] == hist.HISTORY_VERSION
    assert rep["samples"] == 6 and len(rep["events"]) == 2
    assert rep["events"][-1]["ts"] == 105.0  # newest last


def test_dump_is_atomic_and_parseable(tmp_path):
    reg = _reg_with_traffic()
    h = hist.MetricHistory(capacity=4)
    h.record(reg.snapshot())
    path = h.dump(str(tmp_path / "hist.json"))
    rep = json.loads(open(path).read())
    assert rep["samples"] == 1 and rep["events"]


def test_flight_auto_dump_writes_history_companion(tmp_path, monkeypatch):
    """An incident that earns a flight dump also drops the history ring
    alongside it (history-<reason>.json) — the trending-into-it view."""
    from kdtree_tpu.obs import flight

    monkeypatch.setenv("KDTREE_TPU_FLIGHT_DIR", str(tmp_path))
    hist.sample()  # ensure the process ring has something to say
    path = flight.auto_dump("hist-companion-test", force=True)
    assert path is not None
    companion = tmp_path / "history-hist-companion-test.json"
    assert companion.exists()
    rep = json.loads(companion.read_text())
    assert rep["history_version"] == hist.HISTORY_VERSION


# ---------------------------------------------------------------------------
# sampler
# ---------------------------------------------------------------------------


def test_sampler_thread_samples_and_stops():
    reg = _reg_with_traffic()
    h = hist.MetricHistory(capacity=64)
    ticks = []
    s = hist.Sampler(period_s=0.01, history=h, registry=reg,
                     on_sample=lambda: ticks.append(1))
    s.start()
    time.sleep(0.15)
    s.stop()
    n = h.stats()["samples"]
    assert n >= 3
    assert len(ticks) >= 3
    time.sleep(0.05)
    assert h.stats()["samples"] == n  # stopped means stopped
    s.stop()  # idempotent


def test_sampler_survives_raising_hook():
    reg = _reg_with_traffic()
    h = hist.MetricHistory(capacity=64)

    def boom():
        raise RuntimeError("hook bug")

    s = hist.Sampler(period_s=0.01, history=h, registry=reg, on_sample=boom)
    s.start()
    time.sleep(0.08)
    s.stop()
    assert h.stats()["samples"] >= 2  # the hook's bug never killed the loop


def test_kill_switch_disables_module_recording(monkeypatch):
    monkeypatch.setattr(hist, "_DISABLED", True)
    before = hist.get_history().stats()["samples"]
    hist.sample()
    assert hist.get_history().stats()["samples"] == before


def test_env_knobs_defaulted_on_garbage(monkeypatch):
    monkeypatch.setenv("KDTREE_TPU_HISTORY_SAMPLES", "banana")
    assert hist._env_capacity() == hist.DEFAULT_CAPACITY
    monkeypatch.setenv("KDTREE_TPU_HISTORY_PERIOD_S", "-3")
    assert hist.default_period() == hist.DEFAULT_PERIOD_S
    monkeypatch.setenv("KDTREE_TPU_HISTORY_PERIOD_S", "0.25")
    assert hist.default_period() == 0.25


# ---------------------------------------------------------------------------
# cost: the <2% serving bar, mechanically
# ---------------------------------------------------------------------------


def test_per_sample_cost_stays_small():
    """Same method as the flight recorder's per-event bound: measure the
    unit cost and hold it far under budget. A serving-sized registry
    (~50 series) snapshots in well under 5 ms; at the default 1 Hz
    period that is <0.5% of one core — the A/B partner is
    KDTREE_TPU_HISTORY=0."""
    reg = MetricsRegistry()
    for i in range(8):
        for status in ("ok", "shed", "error", "degraded"):
            reg.counter("t_total", labels={"status": status, "b": str(i)})
        reg.histogram("lat_seconds", labels={"phase": str(i)})
    h = hist.MetricHistory(capacity=256)
    h.sample(reg)  # warm any lazy paths
    n = 200
    t0 = time.perf_counter()
    for _ in range(n):
        h.sample(reg)
    per_sample = (time.perf_counter() - t0) / n
    assert per_sample < 5e-3, f"{per_sample * 1e3:.2f} ms/sample"


def test_sample_records_its_own_counter():
    reg = MetricsRegistry()
    h = hist.MetricHistory(capacity=8)
    h.sample(reg)
    assert reg.snapshot()["counters"]["kdtree_history_samples_total"] == 1.0
