"""Device-probe branches of bench.py (the wedged-tunnel guard).

Three-way contract: fast init error -> crisp FAILED line; first wedge ->
CPU re-exec (exec hop validated manually against a real wedged tunnel —
too slow for CI); second wedge -> crisp FAILED. These tests pin the two
FAILED branches and the timeout detection in subprocesses.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]


def _run(code, env_extra, timeout=120):
    env = dict(os.environ)
    env.update(env_extra)
    return subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=timeout, cwd=REPO, env=env)


def _failed_line(stdout):
    line = stdout.strip().splitlines()[-1]
    rep = json.loads(line)
    assert rep["value"] == 0 and rep["metric"].startswith("FAILED"), rep
    return rep


def test_fast_init_error_fails_crisply():
    """A real init error (unknown platform) must NOT fall back to CPU —
    honest CPU numbers would mask the misconfiguration."""
    res = _run("import bench; bench.main()",
               {"JAX_PLATFORMS": "bogus", "BENCH_DEVICE_PROBE_S": "30"})
    assert res.returncode == 2, (res.stdout, res.stderr[-500:])
    rep = _failed_line(res.stdout)
    assert "bogus" in rep["metric"]
    assert "falling back" not in res.stderr


def test_second_wedge_fails_crisply():
    """With the fallback guard already set (= we ARE the fallback process),
    a hanging device init produces the FAILED line, not another exec."""
    code = (
        "import time, bench\n"
        "bench.jax.devices = lambda *a: time.sleep(3600)\n"
        "bench.main()\n"
    )
    res = _run(code, {"BENCH_TUNNEL_FALLBACK": "1",
                      "BENCH_DEVICE_PROBE_S": "2",
                      "JAX_PLATFORMS": "cpu"})
    assert res.returncode == 2, (res.stdout, res.stderr[-500:])
    rep = _failed_line(res.stdout)
    assert "did not complete" in rep["metric"]


def test_transient_init_error_healed_by_bounded_retry():
    """A fast init error that clears on the second attempt (transient
    tunnel hiccup) must be retried — KDTREE_TPU_DEVICE_INIT_RETRIES
    bounds the attempts — and every attempt must land in the flight ring
    with its reason."""
    code = (
        "import bench\n"
        "calls = {'n': 0}\n"
        "def flaky():\n"
        "    calls['n'] += 1\n"
        "    if calls['n'] == 1:\n"
        "        raise RuntimeError('transient tunnel hiccup')\n"
        "    return ['dev']\n"
        "bench.jax.devices = flaky\n"
        "init_s = bench._device_probe(30.0)\n"
        "from kdtree_tpu.obs import flight\n"
        "ev = [e for e in flight.recorder().snapshot()\n"
        "      if e['type'] == 'bench.device_init']\n"
        "assert [e['outcome'] for e in ev] == ['error', 'ok'], ev\n"
        "assert 'hiccup' in ev[0]['reason'], ev\n"
        "print('HEALED', calls['n'], init_s >= 0)\n"
    )
    res = _run(code, {"KDTREE_TPU_DEVICE_INIT_RETRIES": "2",
                      "JAX_PLATFORMS": "cpu"})
    assert res.returncode == 0, (res.stdout, res.stderr[-800:])
    assert "HEALED 2 True" in res.stdout


def test_exhausted_retries_still_fail_crisply():
    """Retries are BOUNDED: a persistent init error exhausts them and
    fails with the standard metric line, never silent CPU numbers."""
    code = (
        "import bench\n"
        "calls = {'n': 0}\n"
        "def broken():\n"
        "    calls['n'] += 1\n"
        "    raise RuntimeError('bad credentials')\n"
        "bench.jax.devices = broken\n"
        "bench._device_probe(30.0)\n"
    )
    res = _run(code, {"KDTREE_TPU_DEVICE_INIT_RETRIES": "1",
                      "JAX_PLATFORMS": "cpu"})
    assert res.returncode == 2, (res.stdout, res.stderr[-500:])
    rep = _failed_line(res.stdout)
    assert "bad credentials" in rep["metric"]
