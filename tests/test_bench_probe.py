"""Device-probe branches of bench.py (the wedged-tunnel guard).

Three-way contract: fast init error -> crisp FAILED line; first wedge ->
CPU re-exec (exec hop validated manually against a real wedged tunnel —
too slow for CI); second wedge -> crisp FAILED. These tests pin the two
FAILED branches and the timeout detection in subprocesses.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]


def _run(code, env_extra, timeout=120):
    env = dict(os.environ)
    env.update(env_extra)
    return subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=timeout, cwd=REPO, env=env)


def _failed_line(stdout):
    line = stdout.strip().splitlines()[-1]
    rep = json.loads(line)
    assert rep["value"] == 0 and rep["metric"].startswith("FAILED"), rep
    return rep


def test_fast_init_error_fails_crisply():
    """A real init error (unknown platform) must NOT fall back to CPU —
    honest CPU numbers would mask the misconfiguration."""
    res = _run("import bench; bench.main()",
               {"JAX_PLATFORMS": "bogus", "BENCH_DEVICE_PROBE_S": "30"})
    assert res.returncode == 2, (res.stdout, res.stderr[-500:])
    rep = _failed_line(res.stdout)
    assert "bogus" in rep["metric"]
    assert "falling back" not in res.stderr


def test_second_wedge_fails_crisply():
    """With the fallback guard already set (= we ARE the fallback process),
    a hanging device init produces the FAILED line, not another exec."""
    code = (
        "import time, bench\n"
        "bench.jax.devices = lambda *a: time.sleep(3600)\n"
        "bench.main()\n"
    )
    res = _run(code, {"BENCH_TUNNEL_FALLBACK": "1",
                      "BENCH_DEVICE_PROBE_S": "2",
                      "JAX_PLATFORMS": "cpu"})
    assert res.returncode == 2, (res.stdout, res.stderr[-500:])
    rep = _failed_line(res.stdout)
    assert "did not complete" in rep["metric"]
