"""Morton bucket tree: exactness vs the brute-force oracle (SURVEY.md §4
test plan item 1 — the oracle is the only trustworthy reference, §3.5)."""

import jax.numpy as jnp
import numpy as np
import pytest

from kdtree_tpu import build_morton, generate_problem, morton_knn
from kdtree_tpu.ops import bruteforce
from kdtree_tpu.ops.morton import morton_codes


@pytest.mark.parametrize(
    "n,d,k,cap",
    [
        (100, 3, 1, 8),
        (1000, 3, 16, 16),
        (2048, 3, 4, 128),
        (777, 5, 3, 32),
        (50, 2, 1, 128),
        (4096, 3, 1, 128),
        (1000, 8, 4, 64),
        (333, 1, 2, 16),
    ],
)
def test_morton_knn_matches_bruteforce(n, d, k, cap):
    pts, qs = generate_problem(seed=n * 31 + d, dim=d, num_points=n, num_queries=10)
    tree = build_morton(pts, bucket_cap=cap)
    d2, idx = morton_knn(tree, qs, k=k)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=k)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf), rtol=1e-6)
    # returned indices must reproduce the returned distances
    gather = np.sum(
        (np.asarray(qs)[:, None, :] - np.asarray(pts)[np.asarray(idx)]) ** 2, axis=-1
    )
    np.testing.assert_allclose(gather, np.asarray(d2), rtol=1e-6)
    assert (np.asarray(idx) >= 0).all()


def test_single_bucket_tree():
    pts, qs = generate_problem(seed=9, dim=3, num_points=50, num_queries=5)
    tree = build_morton(pts, bucket_cap=128)
    assert tree.num_buckets == 1 and tree.num_levels == 0
    d2, _ = morton_knn(tree, qs, k=2)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=2)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf), rtol=1e-6)


def test_duplicate_points():
    pts = jnp.zeros((300, 3), jnp.float32)
    tree = build_morton(pts, bucket_cap=64)
    d2, idx = morton_knn(tree, jnp.ones((2, 3)), k=4)
    np.testing.assert_allclose(np.asarray(d2), 3.0, rtol=1e-6)
    assert (np.asarray(idx) >= 0).all()


def test_clustered_points_stay_exact():
    """Morton quantization must not break exactness on skewed distributions
    (the load-imbalance axis the course graded, Utility.cpp:98-99)."""
    rng = np.random.default_rng(0)
    centers = rng.uniform(-100, 100, (4, 3))
    pts = jnp.asarray(
        (centers[rng.integers(0, 4, 3000)] + rng.normal(0, 0.01, (3000, 3))).astype(
            np.float32
        )
    )
    qs = jnp.asarray(rng.uniform(-100, 100, (10, 3)).astype(np.float32))
    tree = build_morton(pts, bucket_cap=32)
    d2, _ = morton_knn(tree, qs, k=8)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=8)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf), rtol=1e-6)


def test_k_larger_than_n():
    pts, qs = generate_problem(seed=3, dim=3, num_points=10, num_queries=3)
    d2, idx = morton_knn(build_morton(pts, bucket_cap=4), qs, k=50)
    assert d2.shape == (3, 10)  # clamped to n
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=10)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf), rtol=1e-6)


def test_morton_codes_locality():
    """Codes must be monotone per axis cell and interleave all axes."""
    pts = jnp.asarray(
        np.array([[0.0, 0.0], [1.0, 0.0], [0.0, 1.0], [1.0, 1.0]], np.float32)
    )
    codes = np.asarray(morton_codes(pts, bits=1))
    assert sorted(codes.tolist()) == [0, 1, 2, 3]
    assert codes[0] == 0 and codes[3] == 3


def test_non_pow2_and_tiny():
    for n in (1, 2, 3, 129, 1025):
        pts, qs = generate_problem(seed=n, dim=3, num_points=n, num_queries=4)
        d2, _ = morton_knn(build_morton(pts, bucket_cap=128), qs, k=1)
        bf, _ = bruteforce.knn_exact_d2(pts, qs, k=1)
        np.testing.assert_allclose(np.asarray(d2), np.asarray(bf), rtol=1e-6)


def test_morton_codes_explicit_grid_out_of_range():
    """Points outside an explicit lo/hi grid must clamp to the edge cells
    (float->uint32 of out-of-range values is implementation-defined in XLA,
    so the clip has to happen float-side)."""
    pts = jnp.asarray([[-150.0], [-100.0], [0.0], [100.0], [250.0]])
    codes = np.asarray(morton_codes(pts, bits=8, lo=-100.0, hi=100.0))
    assert codes[0] == codes[1] == 0  # below-grid clamps to cell 0
    assert codes[4] == (1 << 8) - 1  # above-grid clamps to the top cell
    assert codes[0] <= codes[2] <= codes[4]


def test_build_capacity_guard():
    """VERDICT r3 weak #6: the measured single-chip capacity cliff (2^27 x 3D
    builds, 2^28 crashes the remote compile AND can wedge the device tunnel
    for hours) must be a crisp ValueError, not a compile crash. CPU/GPU are
    exempt (they page instead of crashing)."""
    import pytest

    from kdtree_tpu.ops.morton import check_build_capacity

    # the measured cliff: 2^27 x 3D fits the default budget, 2^28 does not
    check_build_capacity(1 << 27, 3, backend="tpu")
    with pytest.raises(ValueError, match="global-morton"):
        check_build_capacity(1 << 28, 3, backend="tpu")
    # bytes-based, not an n constant: high-D hits the wall much earlier
    with pytest.raises(ValueError, match="GiB"):
        check_build_capacity(1 << 27, 128, backend="tpu")
    check_build_capacity(500000, 128, backend="tpu")  # the harness config fits
    # non-TPU backends never raise
    check_build_capacity(1 << 30, 128, backend="cpu")
    # budget override
    check_build_capacity(1 << 28, 3, backend="tpu", budget=1 << 40)
