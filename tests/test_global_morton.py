"""Global Morton forest (sample-sort all_to_all partition) on the virtual
8-device CPU mesh — the --oversubscribe analog (SURVEY.md §4 item 4)."""

import numpy as np
import pytest

from kdtree_tpu.ops import bruteforce
from kdtree_tpu.ops.generate import (
    generate_points_rowwise,
    generate_points_shard,
    generate_queries,
)
from kdtree_tpu.parallel.global_morton import global_morton_knn
from kdtree_tpu.parallel.mesh import make_mesh


def _oracle(seed, dim, n, nq, k):
    pts = generate_points_rowwise(seed, dim, n)
    qs = generate_queries(seed + 7777, dim, nq)
    bf_d2, bf_i = bruteforce.knn_exact_d2(pts, qs, k=k)
    return pts, qs, bf_d2, bf_i


@pytest.mark.parametrize("p", [1, 2, 4, 8])
@pytest.mark.parametrize("n,dim,k", [(2048, 3, 4), (1000, 2, 1), (1037, 3, 3),
                                     (1500, 8, 4)])
def test_matches_bruteforce_any_device_count(p, n, dim, k):
    # the 8-D case covers BASELINE.json configs[2]'s dimension: 4 Morton
    # bits/axis — much coarser codes, different splitter behavior
    pts, qs, bf_d2, _ = _oracle(31, dim, n, 8, k)
    d2, gi = global_morton_knn(31, dim, n, qs, k=k, mesh=make_mesh(p))
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-5)
    # ids must reproduce the distances against the independently generated set
    gather = np.sum(
        (np.asarray(qs)[:, None, :] - np.asarray(pts)[np.asarray(gi)]) ** 2, axis=-1
    )
    np.testing.assert_allclose(gather, np.asarray(d2), rtol=1e-5)


def test_device_count_invariance():
    """Same seed => same answers on 1, 2, 4, 8 devices (the determinism
    guarantee the reference gets from its discard trick)."""
    qs = generate_queries(99, 3, 6)
    outs = [
        np.asarray(global_morton_knn(5, 3, 1500, qs, k=3, mesh=make_mesh(p))[0])
        for p in (1, 2, 4, 8)
    ]
    for o in outs[1:]:
        np.testing.assert_allclose(o, outs[0], rtol=1e-6)


@pytest.mark.parametrize("seed", [0, 13, 31])
def test_non_divisible_n(seed):
    """N not divisible by P: past-N rows must never contaminate answers."""
    n, dim, k = 1037, 3, 5
    pts, qs, bf_d2, _ = _oracle(seed, dim, n, 8, k)
    d2, gi = global_morton_knn(seed, dim, n, qs, k=k, mesh=make_mesh(8))
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-5)
    assert int(np.asarray(gi).max()) < n


@pytest.mark.parametrize("seed", [0, 13])
def test_phantom_rows_adversarial(seed):
    """Round-2 judge/advisor repro: queries placed EXACTLY at the phantom
    rows' coordinates (rows [n, p*rows) that the ceil-padding shard generates
    past num_points). With the pre-fix code a phantom wins the k-buffer at
    distance 0 and the post-hoc filter turns it into (inf, -1), evicting a
    true neighbor; the fixed code must match the brute-force oracle over the
    first n rows exactly."""
    n, dim, k, p = 1037, 3, 5, 8
    rows = -(-n // p)  # 130 -> 3 phantom rows 1037..1039
    phantom = generate_points_shard(seed, dim, n, p * rows - n)
    pts = generate_points_rowwise(seed, dim, n)
    bf_d2, bf_i = bruteforce.knn_exact_d2(pts, phantom, k=k)
    d2, gi = global_morton_knn(seed, dim, n, phantom, k=k, mesh=make_mesh(p))
    assert np.all(np.isfinite(np.asarray(d2))), "phantom row leaked as inf"
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-5)
    assert int(np.asarray(gi).max()) < n
    assert int(np.asarray(gi).min()) >= 0


def test_clustered_load_imbalance():
    """Sample-sort splitters must keep clustered data balanced enough to fit
    the slack capacity (the course's grading dimension, Utility.cpp:98-99).
    Overflow handling directly: tiny slack must raise, not silently drop
    points. (The FIT at default slack is test_clustered_fit_default_slack.)"""
    qs = generate_queries(1, 3, 4)
    with pytest.raises(RuntimeError, match="overflow"):
        global_morton_knn(1, 3, 4096, qs, k=1, mesh=make_mesh(8), slack=0.05)


@pytest.mark.parametrize("seed,dim", [(5, 3), (17, 3), (5, 8)])
def test_clustered_fit_default_slack(seed, dim):
    """VERDICT r3 item 6: genuinely SKEWED data (8-center Gaussian mixture,
    stddev 2 over a 200-wide domain — density varies by orders of magnitude)
    must flow through the sample-sort exchange at DEFAULT slack with no
    overflow, balanced per-device occupancy, and exact answers. The 8-D
    case (VERDICT r4 missing #4) stresses the coarse 4-bits/axis codes of
    BASELINE.json configs[2]'s dimension."""
    from kdtree_tpu.ops.generate import generate_points_shard_clustered
    from kdtree_tpu.parallel.global_morton import (
        build_global_morton, global_morton_query,
    )

    n, k, p = 1 << 15, 4, 8
    mesh = make_mesh(p)
    # default slack: a RuntimeError here means the splitters don't absorb
    # realistic clustering and the slack default needs retuning
    forest = build_global_morton(seed, dim, n, mesh=mesh,
                                 distribution="clustered")
    occ = np.asarray((forest.bucket_gid >= 0).sum(axis=(1, 2)))
    assert occ.sum() == n
    assert occ.max() <= 1.8 * occ.mean(), f"imbalanced occupancy: {occ}"

    pts = generate_points_shard_clustered(seed, dim, 0, n)
    qs = pts[:32] + 0.05  # queries inside the dense regions (adversarial)
    d2, gi = global_morton_query(forest, qs, k=k, mesh=mesh)
    bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=k)
    # clustered near-duplicate distances are ~1e-2 squared: f32 summation
    # order between engine and oracle differs at ~1e-4 relative
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2),
                               rtol=1e-3, atol=1e-5)
    assert int(np.asarray(gi).min()) >= 0


def test_occupancy_recorded_and_drives_tile_planning(tmp_path):
    """VERDICT r4 weak #6 / item 7: builds record the worst shard's REAL
    occupancy in aux (clustered partitions can deviate from ceil(N/P) — the
    deviation slack absorbs), tile planning consumes it, the value survives
    a checkpoint round trip, and pre-r5 checkpoints without the aux field
    fall back to the estimate."""
    from kdtree_tpu.ops.generate import generate_points_shard_clustered
    from kdtree_tpu.parallel.global_morton import (
        GlobalMortonForest, _shard_n_real, build_global_morton,
        global_morton_query_tiled,
    )
    from kdtree_tpu.utils.checkpoint import load_tree, save_tree

    n, dim, k, p = 1 << 13, 3, 4, 8
    mesh = make_mesh(p)
    forest = build_global_morton(5, dim, n, mesh=mesh,
                                 distribution="clustered")
    occ = np.asarray((forest.bucket_gid >= 0).sum(axis=(1, 2)))
    assert forest.occ_max == int(occ.max())
    # planning consumes occupancy quantized up in est/16 steps (cache-
    # stable static jit args across same-shaped rebuilds)
    est = -(-n // p)
    step = max(1, est // 16)
    occ_q = -(-int(occ.max()) // step) * step
    assert _shard_n_real(forest, k) == max(occ_q, k)
    assert occ_q >= int(occ.max()) and occ_q - int(occ.max()) < step

    path = str(tmp_path / "f.npz")
    save_tree(path, forest)
    loaded, _ = load_tree(path)
    assert loaded.occ_max == forest.occ_max

    # a pre-r5 checkpoint deserializes with 4-tuple aux: occ_max reads 0 and
    # planning falls back to the ceil(N/P) estimate (never crashes)
    children, aux = GlobalMortonForest.tree_flatten(forest)
    legacy = GlobalMortonForest.tree_unflatten(aux[:4], children)
    assert legacy.occ_max == 0
    assert _shard_n_real(legacy, k) == max(-(-n // p), k)

    # occupancy-sized planning keeps the dense tiled SPMD route exact on
    # exactly the skewed stream the estimate used to undersize
    pts = generate_points_shard_clustered(5, dim, 0, n)
    qs = pts[:1024] + 0.05
    d2, _ = global_morton_query_tiled(forest, qs, k=k, mesh=mesh)
    bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=k)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2),
                               rtol=1e-3, atol=1e-5)


def test_clustered_shard_windows_compose():
    """The clustered row stream is counter-based: shard windows must be
    bit-identical to the rows-0..N stream (device-count invariance)."""
    from kdtree_tpu.ops.generate import generate_points_shard_clustered

    full = np.asarray(generate_points_shard_clustered(9, 3, 0, 1000))
    a = np.asarray(generate_points_shard_clustered(9, 3, 0, 400))
    b = np.asarray(generate_points_shard_clustered(9, 3, 400, 600))
    np.testing.assert_array_equal(np.concatenate([a, b]), full)


def test_ingest_user_points_matches_oracle(tmp_path):
    """VERDICT r4 missing #3: the scale engine must ingest USER data, not
    only seeded streams. Rows stream host -> mesh from a memmapped .npy one
    shard-block at a time (bigger than any single shard), then the standard
    sample-sort partition; answers and ids must match the oracle over the
    original row order. Anisotropic axis scales stress the shared
    quantization grid (the generative path's fixed COORD_MIN/MAX grid does
    not apply to user data)."""
    import jax.numpy as jnp

    from kdtree_tpu.parallel.global_morton import (
        build_global_morton_from_points, global_morton_query,
    )

    rng = np.random.default_rng(3)
    n, dim, k, p = 49_999, 3, 4, 8  # non-divisible: last shard padded
    pts = (rng.normal(size=(n, dim)) *
           np.array([5.0, 50.0, 0.5])).astype(np.float32)
    f = tmp_path / "pts.npy"
    np.save(f, pts)
    mm = np.load(f, mmap_mode="r")

    mesh = make_mesh(p)
    forest = build_global_morton_from_points(mm, mesh=mesh)
    assert forest.num_points == n
    occ = np.asarray((forest.bucket_gid >= 0).sum(axis=(1, 2)))
    assert occ.sum() == n and forest.occ_max == int(occ.max())

    qs = jnp.asarray(pts[::3500] + 0.01)
    d2, gi = global_morton_query(forest, qs, k=k, mesh=mesh)
    bf_d2, _ = bruteforce.knn_exact_d2(jnp.asarray(pts), qs, k=k)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2),
                               rtol=1e-4, atol=1e-6)
    # ids must address the ORIGINAL file rows
    gi_np = np.asarray(gi)
    assert gi_np.min() >= 0 and gi_np.max() < n
    gather = np.sum(
        (np.asarray(qs)[:, None, :] - pts[gi_np]) ** 2, axis=-1)
    np.testing.assert_allclose(gather, np.asarray(d2), rtol=1e-4, atol=1e-6)

    # non-finite rows fail crisply, naming the offending block
    bad = pts.copy()
    bad[12345, 0] = np.nan
    with pytest.raises(ValueError, match="non-finite"):
        build_global_morton_from_points(bad, mesh=mesh)


def test_ingest_presharded_files(tmp_path):
    """The second ingest route (VERDICT r4 missing #3's alternative):
    per-device files map onto devices verbatim with NO exchange — correct
    for ANY partition because the forest query merges every shard, and
    exactly right for spatially-partitioned exports (each file one
    region) that the sample-sort exchange would concentrate onto one
    destination. Uneven file lengths pad; ids address the files'
    concatenation in argument order."""
    import jax.numpy as jnp

    from kdtree_tpu.parallel.global_morton import (
        build_global_morton_from_shard_files, global_morton_query,
    )

    rng = np.random.default_rng(6)
    n, dim, k, p = 12_000, 3, 4, 4
    pts = rng.normal(size=(n, dim)).astype(np.float32) * 20.0
    # spatially partition by x-quantile into UNEVEN files (worst case for
    # the exchange; a no-op here)
    order = np.argsort(pts[:, 0])
    cuts = [0, 2000, 5000, 9500, n]
    paths, parts = [], []
    for i in range(p):
        part = pts[order[cuts[i] : cuts[i + 1]]]
        f = tmp_path / f"part-{i}.npy"
        np.save(f, part)
        paths.append(str(f))
        parts.append(part)
    cat = np.concatenate(parts)  # global ids address THIS order

    forest = build_global_morton_from_shard_files(paths)
    assert forest.num_points == n and forest.devices == p
    occ = np.asarray((forest.bucket_gid >= 0).sum(axis=(1, 2)))
    np.testing.assert_array_equal(occ, np.diff(cuts))
    assert forest.occ_max == int(occ.max())

    qs = jnp.asarray(cat[::1500] + 0.01)
    d2, gi = global_morton_query(forest, qs, k=k, mesh=make_mesh(p))
    bf_d2, _ = bruteforce.knn_exact_d2(jnp.asarray(cat), qs, k=k)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2),
                               rtol=1e-4, atol=1e-6)
    gi_np = np.asarray(gi)
    gather = np.sum((np.asarray(qs)[:, None, :] - cat[gi_np]) ** 2, axis=-1)
    np.testing.assert_allclose(gather, np.asarray(d2), rtol=1e-4, atol=1e-6)

    # mismatched dims across files fail crisply
    np.save(tmp_path / "bad-0.npy", parts[0])
    np.save(tmp_path / "bad-1.npy", rng.normal(size=(50, 5)).astype(np.float32))
    with pytest.raises(ValueError, match="-D but earlier shards"):
        build_global_morton_from_shard_files(
            [str(tmp_path / "bad-0.npy"), str(tmp_path / "bad-1.npy")])


def test_meshfree_dense_serving_uses_flat_view(monkeypatch):
    """Round-5 perf lever: a forest checkpoint served WITHOUT a matching
    mesh (the 1-chip deployment shape) answers dense batches through ONE
    flattened Morton view over all shards' rows — exact, global ids,
    cached — instead of P sequential tiled runs; and when the view cannot
    fit the HBM budget, the bounded sequential loop still answers with
    identical results."""
    from kdtree_tpu.ops.generate import generate_points_shard
    from kdtree_tpu.parallel.global_morton import (
        build_global_morton, global_morton_query_tiled,
    )

    n, dim, k, p = 1 << 13, 3, 4, 8
    forest = build_global_morton(21, dim, n, mesh=make_mesh(p))
    pts = generate_points_shard(21, dim, 0, n)
    qs = pts[:1024] + 0.02  # dense: Q >= 512 and Q*64 >= N

    # mesh of 1 != forest.devices -> the mesh-free serving path
    d2, gi = global_morton_query_tiled(forest, qs, k=k, mesh=make_mesh(1))
    assert getattr(forest, "_dense_view", None) is not None
    assert forest._dense_view.n_real == n
    bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=k)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2),
                               rtol=1e-4, atol=1e-6)
    gi_np = np.asarray(gi)
    assert gi_np.min() >= 0 and gi_np.max() < n

    # HBM-budget fallback: the sequential per-shard loop answers identically
    from kdtree_tpu.ops import morton as morton_mod

    forest2 = build_global_morton(21, dim, n, mesh=make_mesh(p))

    def boom(*a, **kw):
        raise morton_mod.BuildCapacityError("forced: view too big for test")

    monkeypatch.setattr(morton_mod, "check_build_capacity", boom)
    d2s, _ = global_morton_query_tiled(forest2, qs, k=k, mesh=make_mesh(1))
    monkeypatch.undo()
    # the over-budget outcome is CACHED (round-5 advisor fix): later dense
    # batches must not re-materialize the flattened view just to fail again
    assert getattr(forest2, "_dense_view", None) is morton_mod._BUDGET_EXCEEDED
    np.testing.assert_allclose(np.asarray(d2s), np.asarray(d2), rtol=1e-6)


def test_ingest_sorted_input_fits_default_slack():
    """Code-review r5 repro: a spatially SORTED input file (np.sort output,
    scan order, tiled exports) must flow through the ingest exchange at
    DEFAULT slack. Contiguous splitting would make source i the i-th global
    quantile and overflow (nearly all of a source's rows route to one
    destination); the block-cyclic streaming gives every device a ~uniform
    sample of the file, so sort order is irrelevant — and answers stay
    exact with ids into the ORIGINAL (sorted) row order."""
    import jax.numpy as jnp

    from kdtree_tpu.parallel.global_morton import (
        build_global_morton_from_points, global_morton_query,
    )

    rng = np.random.default_rng(4)
    n, dim, k, p = 40_000, 3, 4, 8
    pts = rng.normal(size=(n, dim)).astype(np.float32) * 10.0
    pts = pts[np.argsort(pts[:, 0])]  # worst case for contiguous splits

    mesh = make_mesh(p)
    forest = build_global_morton_from_points(pts, mesh=mesh)  # default slack
    occ = np.asarray((forest.bucket_gid >= 0).sum(axis=(1, 2)))
    assert occ.sum() == n

    qs = jnp.asarray(pts[::3000] + 0.01)
    d2, gi = global_morton_query(forest, qs, k=k, mesh=mesh)
    bf_d2, _ = bruteforce.knn_exact_d2(jnp.asarray(pts), qs, k=k)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2),
                               rtol=1e-4, atol=1e-6)
    gi_np = np.asarray(gi)
    gather = np.sum((np.asarray(qs)[:, None, :] - pts[gi_np]) ** 2, axis=-1)
    np.testing.assert_allclose(gather, np.asarray(d2), rtol=1e-4, atol=1e-6)


def test_scale_512k_over_8_devices():
    """VERDICT r1 item 10: a >=512k-point global build over 8 virtual devices
    with nontrivial per-device work (64k rows/device)."""
    n, dim, k = 1 << 19, 3, 4
    qs = generate_queries(123, dim, 16)
    d2, gi = global_morton_knn(77, dim, n, qs, k=k, mesh=make_mesh(8))
    # oracle on the materialized problem (host-side, one-off)
    pts = generate_points_rowwise(77, dim, n)
    bf_d2, _ = bruteforce.knn(pts, qs, k=k)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-5)


def test_forest_build_query_split_and_checkpoint(tmp_path):
    """First-class scale engine (VERDICT r2 item 3): a built forest is a
    checkpointable object; build+query composition == the fused entry; the
    round-tripped forest answers identically; the mesh-free query (loaded
    forest on different hardware) agrees with the mesh query."""
    from kdtree_tpu.parallel.global_morton import (
        build_global_morton, global_morton_query,
    )
    from kdtree_tpu.utils.checkpoint import load_tree, save_tree

    n, dim, k, p = 1037, 3, 4, 8
    pts, qs, bf_d2, _ = _oracle(13, dim, n, 8, k)
    mesh = make_mesh(p)
    forest = build_global_morton(13, dim, n, mesh=mesh)
    d2, gi = global_morton_query(forest, qs, k=k, mesh=mesh)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-5)

    path = str(tmp_path / "forest.npz")
    save_tree(path, forest, meta={"seed": 13, "generator": "threefry"})
    loaded, meta = load_tree(path)
    assert meta["seed"] == 13
    assert loaded.num_points == n and loaded.devices == p
    d2b, gib = global_morton_query(loaded, qs, k=k, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(d2b), np.asarray(d2))
    np.testing.assert_array_equal(np.asarray(gib), np.asarray(gi))

    # mesh-free path (what a 1-chip load of an 8-device forest runs)
    d2c, gic = global_morton_query(loaded, qs, k=k, mesh=make_mesh(1))
    np.testing.assert_allclose(np.asarray(d2c), np.asarray(d2), rtol=1e-6)


def test_forest_tiled_query_matches():
    """The big-Q serving path (per-device tiled engine + merge) must agree
    with the SPMD DFS query and the oracle."""
    from kdtree_tpu.parallel.global_morton import (
        build_global_morton, global_morton_query, global_morton_query_tiled,
    )

    n, dim, k, p = 1037, 3, 4, 8
    mesh = make_mesh(p)
    forest = build_global_morton(13, dim, n, mesh=mesh)
    qs = generate_queries(4, dim, 200)
    d2a, _ = global_morton_query(forest, qs, k=k, mesh=mesh)
    d2b, gib = global_morton_query_tiled(forest, qs, k=k)
    np.testing.assert_allclose(np.asarray(d2b), np.asarray(d2a), rtol=1e-6)
    pts = generate_points_rowwise(13, dim, n)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=k)
    np.testing.assert_allclose(np.asarray(d2b), np.asarray(bf), rtol=1e-5)
    assert int(np.asarray(gib).max()) < n


def test_spmd_tiled_dense_query_routes_and_matches():
    """VERDICT r3 item 2: at dense low-D shapes the forest query must run
    the tiled engine INSIDE shard_map (not the per-query DFS), and the SPMD
    answer must match both the mesh-free tiled path and the oracle."""
    from unittest import mock

    from kdtree_tpu.parallel import global_morton
    from kdtree_tpu.parallel.global_morton import (
        build_global_morton, global_morton_query, global_morton_query_tiled,
    )

    n, dim, k, p = 4096, 3, 4, 8
    mesh = make_mesh(p)
    forest = build_global_morton(21, dim, n, mesh=mesh)
    qs = generate_queries(9, dim, 2048)  # dense: Q >= 512 and Q*64 >= N

    # the dense crossover must actually route to the SPMD tiled program
    with mock.patch.object(
        global_morton, "_query_tiled_spmd",
        side_effect=global_morton._query_tiled_spmd,
    ) as spmd:
        d2, gi = global_morton_query(forest, qs, k=k, mesh=mesh)
        assert spmd.call_count == 1

    pts = generate_points_rowwise(21, dim, n)
    bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=k)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-5)
    assert int(np.asarray(gi).max()) < n and int(np.asarray(gi).min()) >= 0

    # the mesh-free serving path (checkpoint on different hardware) agrees
    d2m, _ = global_morton_query_tiled(forest, qs, k=k, mesh=make_mesh(1))
    np.testing.assert_allclose(np.asarray(d2m), np.asarray(d2), rtol=1e-6)


def test_spmd_tiled_k_exceeds_shard_rows():
    """k larger than the ~N/P per-shard row count: each shard's k-buffer
    pads with (inf, -1) and the merge still recovers the exact global k."""
    from kdtree_tpu.parallel.global_morton import (
        build_global_morton, global_morton_query_tiled,
    )

    n, dim, k, p = 64, 3, 16, 8  # 8 rows/device < k
    mesh = make_mesh(p)
    forest = build_global_morton(3, dim, n, mesh=mesh, slack=8.0)
    qs = generate_queries(11, dim, 512)
    d2, gi = global_morton_query_tiled(forest, qs, k=k, mesh=mesh)
    pts = generate_points_rowwise(3, dim, n)
    bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=k)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-5)
    assert int(np.asarray(gi).max()) < n


def test_tiny_non_divisible_n_no_spurious_overflow():
    """Masked phantom rows must not count toward sample-sort overflow: n=9 on
    8 devices generates 7 phantoms that all carry the top Morton code, and
    dropping a padding row is harmless (receivers pad with inf/-1 anyway)."""
    n, dim, k = 9, 3, 2
    pts, qs, bf_d2, _ = _oracle(0, dim, n, 4, k)
    d2, gi = global_morton_knn(0, dim, n, qs, k=k, mesh=make_mesh(8), slack=8.0)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-5)
    assert int(np.asarray(gi).max()) < n
