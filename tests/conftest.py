"""Test environment: fake an 8-device pod on CPU.

The analog of the reference's ``mpirun --oversubscribe`` (Makefile:36): the
same sharded code paths run against 8 virtual CPU devices so multi-chip logic
is exercised without a pod. Must run before the first ``import jax``.
"""

import os
import tempfile

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Isolate the persistent tiled-plan store (docs/TUNING.md): a fresh per-run
# tmp dir, so tests never read profiles from the developer's real cache or
# from a previous suite run — warm-vs-cold behavior inside one run is
# still exercised (and pinned down) by tests/test_tuning.py.
if "KDTREE_TPU_PLAN_CACHE" not in os.environ:
    os.environ["KDTREE_TPU_PLAN_CACHE"] = tempfile.mkdtemp(
        prefix="kdtree-tpu-plans-"
    )

# Isolate flight-recorder incident dumps the same way: tests exercise the
# CLI failure and serve error paths on purpose, and their auto-dumps must
# land in a per-run tmp dir, not in the developer's working tree.
if "KDTREE_TPU_FLIGHT_DIR" not in os.environ:
    os.environ["KDTREE_TPU_FLIGHT_DIR"] = tempfile.mkdtemp(
        prefix="kdtree-tpu-flight-"
    )

# Serving snapshots (docs/SERVING.md "Snapshots & replica fleets"):
# relative snapshot dirs resolve under this base, so a test (or a serve
# subprocess a test spawns) that names a bare "snapdir" can never litter
# the working tree — same per-run isolation as the plan store above.
if "KDTREE_TPU_SNAPSHOT_DIR" not in os.environ:
    os.environ["KDTREE_TPU_SNAPSHOT_DIR"] = tempfile.mkdtemp(
        prefix="kdtree-tpu-snapshots-"
    )

# And the lock-order sanitizer's graph artifacts (docs/OBSERVABILITY.md
# "Concurrency sanitizer"): when CI runs tier-1 under
# KDTREE_TPU_LOCKWATCH=1 it sets the dir explicitly so it can assert
# zero cycles afterwards; a dev run without one must not litter cwd.
if "KDTREE_TPU_LOCKWATCH_DIR" not in os.environ:
    os.environ["KDTREE_TPU_LOCKWATCH_DIR"] = tempfile.mkdtemp(
        prefix="kdtree-tpu-lockwatch-"
    )

import pytest

# Lane split (VERDICT r4 weak #7): the full suite needs xdist on a small
# host (one process accumulating every XLA CPU compilation segfaults the
# compiler near the end), but gating a change must not cost 40 minutes.
# Files here hold the mesh/CLI/scale tests that dominate runtime (measured
# --durations, round 5); everything else is the "fast" lane — <5 min
# single-process, no xdist needed:
#   python -m pytest tests/ -q -m "not mesh and not slow"   # fast lane
#   python -m pytest tests/ -q -n 4 --dist loadfile         # full suite
_MESH_LANE_FILES = {
    "test_clustered.py",
    "test_ensemble.py",
    "test_global_exact.py",
    "test_global_morton.py",
    "test_global_tree.py",
    "test_protocol.py",
    "test_tile_query.py",
    "test_utils.py",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.fspath.basename in _MESH_LANE_FILES:
            item.add_marker(pytest.mark.mesh)


# Every compiled XLA executable holds ~9 anonymous mappings in the CPU
# client; a full tier-1 run accumulates tens of thousands against the
# kernel's vm.max_map_count ceiling (65530 default). Past the ceiling
# mmap fails and XLA SEGFAULTS mid-compile — observed twice at the
# suite's alphabetical tail once the verb kernels pushed the total
# over. Clear the jit caches when we get close; the handful of tests
# that recompile afterwards cost seconds, the crash cost the suite.
_MAP_GUARD_THRESHOLD = 52000


@pytest.fixture(autouse=True)
def _jit_cache_map_guard():
    import gc

    try:
        with open("/proc/self/maps") as f:
            n_maps = sum(1 for _ in f)
    except OSError:  # no procfs (darwin) — the ceiling is linux-only
        n_maps = 0
    if n_maps > _MAP_GUARD_THRESHOLD:
        jax.clear_caches()
        gc.collect()
    yield


@pytest.fixture(autouse=True)
def _isolate_flight_dump_rate_limit():
    """The process-wide flight recorder rate-limits auto-dumps per
    reason (5 s); without isolation, any two tests that dump the same
    reason pass or fail by collection ORDER (the PR 9 gotcha:
    test_flight's shed-burst vs test_slo's flood e2e). Clearing the
    limiter before every test makes every hand-picked order behave
    like a fresh process."""
    import threading

    from kdtree_tpu.obs import flight, trace

    flight.recorder().reset_dump_rate_limit()
    # same reasoning for the process-wide trace buffer: promotion state
    # (pinned ids, last-promoted pointers) must not leak across tests
    trace.reset()
    yield
    # drain stray dump writers before the next test: the dump thread is
    # deliberately non-daemon and unjoined (flight.py KDT404 note), so a
    # test that triggered one can otherwise leak it into a neighbor that
    # asserts on dump files or on the limiter it just reset
    for t in threading.enumerate():
        if t.name == "kdtree-flight-dump" and t is not threading.current_thread():
            t.join(timeout=5.0)


@pytest.fixture
def mesh8():
    from kdtree_tpu.parallel.mesh import make_mesh

    return make_mesh(8)

import jax  # noqa: E402

# The container's sitecustomize force-registers the axon TPU backend and
# prepends it to jax_platforms; pin the config back to pure CPU so the
# virtual 8-device mesh is what tests see.
jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the mesh-lane tests compile dozens of
# 8-device SPMD programs, which dominates suite wall time. Caching them
# across runs keeps repeat tier-1 runs inside the timeout window (the
# first run still pays full compile). KDTREE_TPU_XLA_CACHE=none disables.
_cache_dir = os.environ.get(
    "KDTREE_TPU_XLA_CACHE", "/tmp/kdtree_tpu_xla_cache"
)
if _cache_dir and _cache_dir.lower() != "none":
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
