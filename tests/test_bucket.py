import jax.numpy as jnp
import numpy as np
import pytest

from kdtree_tpu import build_bucket, bucket_knn, generate_problem
from kdtree_tpu.ops import bruteforce
from kdtree_tpu.ops.bucket import bucket_spec


@pytest.mark.parametrize("n,cap", [(1, 8), (7, 8), (8, 8), (9, 8), (1000, 16), (4096, 128)])
def test_spec_partitions_points(n, cap):
    spec = bucket_spec(n, cap)
    covered = list(spec.med_pos)
    for s, ln in zip(spec.bucket_start, spec.bucket_len):
        covered.extend(range(s, s + ln))
        assert 1 <= ln <= cap
    assert sorted(covered) == list(range(n))


@pytest.mark.parametrize(
    "n,d,k,cap",
    [(100, 3, 1, 8), (1000, 3, 16, 16), (2048, 3, 4, 128), (777, 5, 3, 32), (50, 2, 1, 128)],
)
def test_bucket_knn_matches_bruteforce(n, d, k, cap):
    pts, qs = generate_problem(seed=n + d + k, dim=d, num_points=n, num_queries=10)
    tree = build_bucket(pts, bucket_cap=cap)
    d2, idx = bucket_knn(tree, qs, k=k)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=k)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf), rtol=1e-6)
    gather = np.sum(
        (np.asarray(qs)[:, None, :] - np.asarray(pts)[np.asarray(idx)]) ** 2, axis=-1
    )
    np.testing.assert_allclose(gather, np.asarray(d2), rtol=1e-6)


@pytest.mark.parametrize(
    "n,d,cap", [(100, 3, 8), (1000, 3, 16), (4096, 3, 128), (777, 5, 32), (513, 2, 4)]
)
def test_presort_strategy_identical_tree(n, d, cap):
    pts, _ = generate_problem(seed=n + d, dim=d, num_points=n, num_queries=1)
    a = build_bucket(pts, bucket_cap=cap, strategy="sort")
    b = build_bucket(pts, bucket_cap=cap, strategy="presort")
    np.testing.assert_array_equal(np.asarray(a.node_gid), np.asarray(b.node_gid))
    np.testing.assert_array_equal(np.asarray(a.node_bucket), np.asarray(b.node_bucket))
    np.testing.assert_array_equal(np.asarray(a.bucket_gid), np.asarray(b.bucket_gid))
    np.testing.assert_array_equal(np.asarray(a.bucket_pts), np.asarray(b.bucket_pts))
    np.testing.assert_array_equal(np.asarray(a.node_coords), np.asarray(b.node_coords))


def test_bucket_cap_one_rejected():
    pts, _ = generate_problem(seed=1, dim=3, num_points=64, num_queries=1)
    with pytest.raises(ValueError):
        build_bucket(pts, bucket_cap=1)


def test_whole_tree_is_one_bucket():
    pts, qs = generate_problem(seed=9, dim=3, num_points=50, num_queries=5)
    tree = build_bucket(pts, bucket_cap=128)
    assert tree.num_levels == 0 and tree.bucket_pts.shape[0] == 1
    d2, _ = bucket_knn(tree, qs, k=2)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=2)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf), rtol=1e-6)


def test_chunked_query_equals_unchunked():
    pts, _ = generate_problem(seed=4, dim=3, num_points=2000)
    qs = generate_problem(seed=5, dim=3, num_points=1000, num_queries=1)[0]
    tree = build_bucket(pts, bucket_cap=64)
    a_d, a_i = bucket_knn(tree, qs, k=3, chunk=128)
    b_d, b_i = bucket_knn(tree, qs, k=3, chunk=1024)
    np.testing.assert_array_equal(np.asarray(a_d), np.asarray(b_d))
    np.testing.assert_array_equal(np.asarray(a_i), np.asarray(b_i))


def test_duplicate_points_bucket():
    pts = jnp.zeros((300, 3), jnp.float32)
    tree = build_bucket(pts, bucket_cap=64)
    d2, idx = bucket_knn(tree, jnp.ones((2, 3)), k=4)
    np.testing.assert_allclose(np.asarray(d2), 3.0, rtol=1e-6)
    assert (np.asarray(idx) >= 0).all()
