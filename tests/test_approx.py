"""The recall dial (kdtree_tpu/approx/, docs/SERVING.md "Degradation
ladder").

The contract under test has three layers:

- **search**: recall@k is monotone in visit_cap (truncations of one
  fixed lb-ascending ranking are nested), and the full cap is
  byte-identical to the exact tiled engine across shapes — the
  exactness contract is untouched by default;
- **calibration**: the harness's measured recall_target → visit_cap
  table round-trips through the plan store and resolves at serving
  batch signatures; an uncalibrated target falls back to the
  documented conservative heuristic;
- **serving**: a /v1/knn recall_target answers with the gear echoed
  (NOT flagged degraded — a kept contract is no degradation), requests
  without one stay byte-identical to the oracle, and the degradation
  ladder steps down under a deterministic injected dispatch-latency
  fault and climbs back after it clears — transitions on /metrics and
  in the flight ring.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from kdtree_tpu import approx, obs
from kdtree_tpu.approx.ladder import GEARS, DegradationLadder, gear_token
from kdtree_tpu.approx.recall import (
    calibrate_caps,
    persist_calibration,
    recall_at_k,
    sweep_recall,
)
from kdtree_tpu.approx.search import resolve_visit_cap

SEED = 7


@pytest.fixture(scope="module")
def tree():
    from kdtree_tpu.ops.generate import generate_points_rowwise
    from kdtree_tpu.ops.morton import build_morton

    return build_morton(generate_points_rowwise(SEED, 3, 20000))


@pytest.fixture(scope="module")
def queries():
    from kdtree_tpu.ops.generate import generate_queries

    return generate_queries(SEED + 1, 3, 1024)


# ---------------------------------------------------------------------------
# bounded-visit search: monotonicity + full-cap byte-identity
# ---------------------------------------------------------------------------


def test_recall_monotone_in_visit_cap(tree, queries):
    from kdtree_tpu.ops.tile_query import morton_knn_tiled

    _, exact_ids = morton_knn_tiled(tree, queries, k=8)
    exact_ids = np.asarray(exact_ids)
    prev = 0.0
    for cap in (1, 2, 4, 8, 16, 32, tree.num_buckets):
        _, ids = approx.morton_knn_approx(tree, queries, k=8,
                                          visit_cap=cap)
        r = recall_at_k(np.asarray(ids), exact_ids)
        assert r >= prev - 1e-12, (cap, r, prev)
        prev = r
    assert prev == 1.0  # the full cap finds everything


@pytest.mark.parametrize("dim,n,k", [(2, 3000, 1), (3, 20000, 8),
                                     (4, 6000, 16)])
def test_full_cap_byte_identical_across_shapes(dim, n, k):
    from kdtree_tpu.ops.generate import (
        generate_points_rowwise,
        generate_queries,
    )
    from kdtree_tpu.ops.morton import build_morton
    from kdtree_tpu.ops.tile_query import morton_knn_tiled

    t = build_morton(generate_points_rowwise(SEED, dim, n))
    q = generate_queries(SEED + 1, dim, 512)
    d2e, ide = morton_knn_tiled(t, q, k=k)
    d2a, ida = approx.morton_knn_approx(t, q, k=k,
                                        visit_cap=t.num_buckets)
    assert np.array_equal(np.asarray(d2a), np.asarray(d2e))
    assert np.array_equal(np.asarray(ida), np.asarray(ide))


def test_approx_answers_are_exact_over_visited_points(tree, queries):
    """Approximate distances are never estimates: every returned
    (distance, id) pair is a true pair — the only error mode is a
    missing member."""
    _, ids = approx.morton_knn_approx(tree, queries, k=4, visit_cap=4)
    d2, _ = approx.morton_knn_approx(tree, queries, k=4, visit_cap=4)
    flat_pts = np.asarray(tree.bucket_pts).reshape(-1, tree.dim)
    flat_gid = np.asarray(tree.bucket_gid).reshape(-1)
    by_gid = {int(g): flat_pts[i] for i, g in enumerate(flat_gid)
              if g >= 0}
    q = np.asarray(queries)
    ids = np.asarray(ids)
    d2 = np.asarray(d2)
    for qi in (0, 17, 1023):
        for j in range(4):
            gid = int(ids[qi, j])
            if gid < 0:
                continue
            true_d2 = float(((q[qi] - by_gid[gid]) ** 2).sum())
            assert d2[qi, j] == pytest.approx(true_d2, rel=1e-5)


# ---------------------------------------------------------------------------
# recall_at_k semantics
# ---------------------------------------------------------------------------


def test_recall_at_k_padding_and_empty_truth():
    exact = np.array([[1, 2, -1], [-1, -1, -1]])
    found = np.array([[1, -1, -1], [-1, -1, -1]])
    # row 0: 1 of 2 real ids found; row 1: nothing to find = 1.0
    assert recall_at_k(found, exact) == pytest.approx((0.5 + 1.0) / 2)
    with pytest.raises(ValueError):
        recall_at_k(np.zeros((2, 3)), np.zeros((2, 4)))


# ---------------------------------------------------------------------------
# resolution: calibration first, heuristic fallback
# ---------------------------------------------------------------------------


def test_resolve_exact_for_none_and_full_target():
    assert resolve_visit_cap(None, 256, 8, 64) is None
    assert resolve_visit_cap(1.0, 256, 8, 64) is None


def test_resolve_prefers_smallest_covering_calibrated_cap():
    prof = {"recall_caps": {"0.9": 12, "0.99": 40, "0.5": 4}}
    assert resolve_visit_cap(0.9, 256, 8, 64, profile=prof) == 12
    assert resolve_visit_cap(0.95, 256, 8, 64, profile=prof) == 40
    # below every calibrated target: the smallest covering one wins
    assert resolve_visit_cap(0.4, 256, 8, 64, profile=prof) == 4


def test_resolve_heuristic_fallback_and_k_floor():
    # no calibration: conservative fraction of the bucket count
    assert resolve_visit_cap(0.99, 256, 8, 64) == 128
    assert resolve_visit_cap(0.9, 256, 8, 64) == 64
    # k floor: enough buckets to even hold k real candidates
    cap = resolve_visit_cap(0.5, 256, 200, 16)
    assert cap is not None and cap * 16 >= 200
    # a cap that reaches the bucket count IS exact
    assert resolve_visit_cap(0.99, 2, 8, 64) is None


def test_resolve_ignores_malformed_calibration_entries():
    prof = {"recall_caps": {"bogus": 3, "0.95": "x", "0.99": True}}
    # nothing usable: falls back to the heuristic
    assert resolve_visit_cap(0.9, 256, 8, 64, profile=prof) == 64


# ---------------------------------------------------------------------------
# the harness: sweep + calibration persistence
# ---------------------------------------------------------------------------


def test_sweep_block_monotone_and_calibration(tree, queries):
    block = sweep_recall(tree, queries, k=8, caps=(2, 8, 32,
                                                   tree.num_buckets))
    assert block["recall_version"] == 1
    curve = block["curve"]
    assert [r["visit_cap"] for r in curve] == sorted(
        r["visit_cap"] for r in curve)
    recalls = [r["recall"] for r in curve]
    assert recalls == sorted(recalls)
    assert recalls[-1] == 1.0
    caps = calibrate_caps(curve, targets=(0.5, 0.99, 1.0))
    # smallest measured cap per reached target; every value is a
    # swept cap
    swept = {r["visit_cap"] for r in curve}
    assert set(caps.values()) <= swept
    assert caps["1"] == tree.num_buckets


def test_calibrate_caps_omits_unreached_targets():
    curve = [{"visit_cap": 2, "recall": 0.4},
             {"visit_cap": 8, "recall": 0.8}]
    caps = calibrate_caps(curve, targets=(0.5, 0.99))
    assert caps == {"0.5": 8}  # 0.99 never reached: absent, not lied


def test_calibration_roundtrips_to_serving_buckets(tree, queries,
                                                   tmp_path,
                                                   monkeypatch):
    from kdtree_tpu import tuning

    monkeypatch.setenv("KDTREE_TPU_PLAN_CACHE", str(tmp_path))
    block = sweep_recall(tree, queries, k=8,
                         caps=(4, 16, tree.num_buckets))
    out = persist_calibration(tree, queries.shape[0], 3, 8, block)
    assert out["persisted"]
    # the calibration resolves at a serving BATCH signature (pow2
    # bucket well below the sweep's Q), through the raw-profile path
    sig = tuning.make_signature(8, 3, tree.n_real, 8, tree.bucket_size,
                                tree.num_buckets, devices=1)
    prof = tuning.profile_for(sig)
    assert prof is not None and prof["recall_caps"] == out["recall_caps"]
    cap = resolve_visit_cap(0.5, tree.num_buckets, 8, tree.bucket_size,
                            profile=prof)
    assert cap == int(out["recall_caps"]["0.5"])
    # a later feedback-style merge must not erase the calibration
    store = tuning.default_store()
    store.record(sig, cmax=64)
    assert tuning.profile_for(sig)["recall_caps"] == out["recall_caps"]


# ---------------------------------------------------------------------------
# the ladder state machine
# ---------------------------------------------------------------------------


def test_ladder_steps_down_and_recovers_with_hysteresis():
    lad = DegradationLadder(slo_engine=None, down_after=2, up_after=3)
    assert lad.gear() == 0
    assert lad.tick(burning=True) == 0   # one PAGE tick: not yet
    assert lad.tick(burning=True) == 1   # two: downshift
    assert lad.tick(burning=True) == 1
    assert lad.tick(burning=True) == 2
    for _ in range(10):
        lad.tick(burning=True)
    assert lad.gear() == len(GEARS) - 1  # parked at the floor, no wrap
    assert lad.spec().brute
    # recovery: up_after consecutive OK ticks per gear, one at a time
    assert lad.tick(burning=False) == len(GEARS) - 1
    assert lad.tick(burning=False) == len(GEARS) - 1
    assert lad.tick(burning=False) == len(GEARS) - 2
    for _ in range(3 * len(GEARS)):
        lad.tick(burning=False)
    assert lad.gear() == 0


def test_ladder_disabled_never_shifts_and_gauges_export():
    reg = obs.get_registry()
    lad = DegradationLadder(slo_engine=None, enabled=False)
    for _ in range(10):
        assert lad.tick(burning=True) == 0
    on = DegradationLadder(slo_engine=None, down_after=1)
    on.tick(burning=True)
    snap = reg.snapshot()
    assert snap["gauges"]["kdtree_recall_gear"] == 1.0
    assert snap["gauges"]["kdtree_recall_estimate"] == pytest.approx(
        0.99)
    assert snap["counters"][
        'kdtree_recall_ladder_transitions_total{to="approx-0.99"}'] >= 1


def test_gear_tokens():
    assert gear_token(GEARS[0]) is None
    assert gear_token(GEARS[1]) == "approx:0.99"
    assert gear_token(GEARS[2]) == "approx:0.9"
    assert gear_token(GEARS[3]) == "brute-deadline"


def test_router_merge_gear_accounting():
    from kdtree_tpu.serve.router import merge_gear

    assert merge_gear([{"gear": None}, {}]) is None
    assert merge_gear([{"gear": "approx:0.99"}, {}]) == "approx:0.99"
    # the merged recall bound is the WORST shard's target
    assert merge_gear([{"gear": "approx:0.99"},
                       {"gear": "approx:0.9"}]) == "approx:0.9"
    assert merge_gear([{"gear": "brute-deadline"}]) == "brute-deadline"
    assert merge_gear([{"gear": "brute-deadline"},
                       {"gear": "approx:0.9"}]) == "approx:0.9"


# ---------------------------------------------------------------------------
# serving e2e: the dial on /v1/knn + the ladder under injected overload
# ---------------------------------------------------------------------------


def _url(httpd, path):
    return f"http://127.0.0.1:{httpd.server_address[1]}{path}"


def _post(httpd, payload, timeout=120.0):
    req = urllib.request.Request(
        _url(httpd, "/v1/knn"), data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(httpd, path, timeout=30.0):
    with urllib.request.urlopen(_url(httpd, path), timeout=timeout) as r:
        return r.read().decode()


@pytest.fixture()
def dial_server(tree, tmp_path, monkeypatch):
    """A server with a persisted calibration, the ladder armed over a
    test-scale SLO window, and a mutable fault set."""
    from kdtree_tpu.obs import history as obs_history
    from kdtree_tpu.obs import slo as obs_slo
    from kdtree_tpu.ops.generate import generate_queries
    from kdtree_tpu.serve import lifecycle, server as srv
    from kdtree_tpu.serve.faults import FaultSet

    monkeypatch.setenv("KDTREE_TPU_PLAN_CACHE", str(tmp_path))
    qs = generate_queries(SEED + 1, 3, 512)
    block = sweep_recall(tree, qs, k=4, caps=(4, 16, tree.num_buckets))
    persist_calibration(tree, 512, 3, 4, block)
    # test-scale burn windows so the ladder reacts (and recovers)
    # within seconds instead of SRE minutes
    spec = obs_slo.SloSpec(
        name="request-p99-latency",
        objective="test: p99 within 150 ms",
        target=0.99, kind="latency",
        hist='kdtree_serve_request_seconds{phase="total"}',
        threshold=0.15,
        fast=obs_slo.BurnWindow(long_s=1.5, short_s=0.5, max_burn=2.0),
        slow=obs_slo.BurnWindow(long_s=3.0, short_s=1.0, max_burn=2.0),
    )
    engine = obs_slo.SloEngine(specs=[spec],
                               history=obs_history.MetricHistory())
    state = lifecycle.build_state(tree=tree, k=4, max_batch=64,
                                  slo_engine=engine,
                                  history_period_s=0.05,
                                  ladder_enabled=True)
    faults = FaultSet("")
    httpd = srv.make_server(state, port=0, max_wait_ms=1.0,
                            faults=faults)
    httpd.start(warmup_buckets=[8])
    try:
        yield httpd, faults
    finally:
        httpd.stop()


def test_recall_target_request_echoes_gear_not_degraded(dial_server,
                                                        tree):
    httpd, _ = dial_server
    q = np.asarray([[0.5, 0.5, 0.5], [0.1, 0.9, 0.2]], dtype=np.float32)
    status, body = _post(httpd, {"queries": q.tolist(), "k": 4,
                                 "recall_target": 0.5})
    assert status == 200
    assert body["degraded"] is None  # a kept contract, not degradation
    assert body["gear"] == "approx:0.5"
    # an explicit 1.0 (and absent) stay exact: no gear field at all
    for payload in ({"queries": q.tolist(), "k": 4},
                    {"queries": q.tolist(), "k": 4,
                     "recall_target": 1.0}):
        status, body = _post(httpd, payload)
        assert status == 200 and "gear" not in body
    # exact answers are byte-identical to the oracle, with approx
    # traffic interleaved on the same server
    import jax.numpy as jnp

    from kdtree_tpu.ops.tile_query import morton_knn_tiled

    d2, ids = morton_knn_tiled(tree, jnp.asarray(q), k=4)
    assert body["ids"] == np.asarray(ids).tolist()
    assert body["distances"] == np.sqrt(
        np.asarray(d2).astype(np.float64)).tolist()


def test_recall_target_validation(dial_server):
    httpd, _ = dial_server
    for bad in (0.0, -0.5, 1.5, "0.9", True):
        status, body = _post(httpd, {"queries": [[0.1, 0.2, 0.3]],
                                     "recall_target": bad})
        assert status == 400, bad
        assert "recall_target" in body["error"]


@pytest.mark.slow
def test_ladder_steps_down_and_recovers_under_injected_latency(
        dial_server):
    """The acceptance drill: a deterministic dispatch-latency fault
    burns the watched p99 SLO, the ladder steps down (transitions on
    /metrics and in the flight ring, forced answers flagged degraded),
    and after the fault clears the ladder climbs back to exact."""
    httpd, faults = dial_server

    def gear():
        for line in _get(httpd, "/metrics").splitlines():
            if line.startswith("kdtree_recall_gear "):
                return int(float(line.split()[1]))
        return None

    assert gear() == 0
    faults.set_spec("batch=latency:400")
    q = [[0.4, 0.4, 0.4]]
    saw_forced = None
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        status, body = _post(httpd, {"queries": q, "k": 2})
        if status == 200 and body.get("degraded"):
            saw_forced = body
            break
        time.sleep(0.02)
    assert saw_forced is not None, "ladder never stepped down"
    assert saw_forced["degraded"].startswith(("approx:",
                                              "brute-deadline"))
    assert gear() >= 1
    flight_dump = json.loads(_get(httpd, "/debug/flight"))
    shifts = [e for e in flight_dump["events"]
              if e.get("type") == "ladder.shift"]
    assert shifts and shifts[0]["to"].startswith("approx")
    # clear the fault: cheap exact traffic, the burn ages out of the
    # short windows, and the ladder climbs back gear by gear
    faults.clear()
    deadline = time.monotonic() + 120.0
    while time.monotonic() < deadline:
        status, body = _post(httpd, {"queries": q, "k": 2})
        if status == 200 and not body.get("degraded") and gear() == 0:
            break
        time.sleep(0.05)
    assert gear() == 0, "ladder never recovered"
    flight_dump = json.loads(_get(httpd, "/debug/flight"))
    ups = [e for e in flight_dump["events"]
           if e.get("type") == "ladder.shift"
           and e.get("reason") == "recovered"]
    assert ups, "no recovery transition recorded"
    metrics = _get(httpd, "/metrics")
    assert 'kdtree_recall_ladder_transitions_total{to="approx-0.99"}' \
        in metrics


# ---------------------------------------------------------------------------
# review-pass pins
# ---------------------------------------------------------------------------


def test_parse_recall_target_shared_wire_contract():
    from kdtree_tpu.approx.search import parse_recall_target

    assert parse_recall_target(None) == (True, None)
    assert parse_recall_target(1.0) == (True, None)  # explicit exact
    assert parse_recall_target(1) == (True, None)
    assert parse_recall_target(0.9) == (True, 0.9)
    for bad in (0.0, -0.5, 1.5, "0.9", True, False):
        assert parse_recall_target(bad)[0] is False, bad


def test_client_requested_approx_never_moves_the_slo_gauge(dial_server):
    """The served-recall SLO watches the LADDER's engaged gear, never a
    client-requested target: steady recall_target=0.5 traffic is a
    kept contract and must not park kdtree_recall_estimate below the
    SLO floor (which would page on traffic doing exactly what it
    asked)."""
    httpd, _ = dial_server
    q = [[0.3, 0.3, 0.3]]
    for _ in range(3):
        status, body = _post(httpd, {"queries": q, "k": 2,
                                     "recall_target": 0.5})
        assert status == 200 and body["gear"] == "approx:0.5"
    snap = obs.get_registry().snapshot()
    assert snap["gauges"]["kdtree_recall_estimate"] == 1.0


# ---------------------------------------------------------------------------
# online recall sampler (ISSUE 15 satellite: the served-recall SLO's
# measured twin — docs/SERVING.md "Degradation ladder")
# ---------------------------------------------------------------------------


@pytest.fixture()
def sampled_server(tree, tmp_path, monkeypatch):
    """A server with the sampler at fraction 1.0 (every approx batch
    shadow-answered) — deterministic for the assertions below."""
    from kdtree_tpu.serve import lifecycle, server as srv

    monkeypatch.setenv("KDTREE_TPU_PLAN_CACHE", str(tmp_path))
    state = lifecycle.build_state(tree=tree, k=4, max_batch=64)
    httpd = srv.make_server(state, port=0, max_wait_ms=1.0,
                            recall_sample=1.0)
    httpd.start(warmup_buckets=[8])
    try:
        yield httpd
    finally:
        httpd.stop()


def test_recall_sampler_measures_approx_batches_only(sampled_server):
    """Every approx batch is shadow-answered exactly: the samples
    counter advances, the measured-recall gauge appears (EWMA in
    [0, 1]), and EXACT batches are never sampled (nothing to
    measure)."""
    httpd = sampled_server

    def counters():
        snap = obs.get_registry().snapshot()
        return (snap["counters"].get("kdtree_recall_samples_total", 0.0),
                snap["gauges"].get("kdtree_recall_sampled"))

    before, gauge_before = counters()
    # exact traffic first: no sampling
    status, body = _post(httpd, {"queries": [[0.5, 0.5, 0.5]], "k": 2})
    assert status == 200 and "gear" not in body
    mid, _ = counters()
    assert mid == before
    # approx traffic: each batch sampled (fraction 1.0)
    for i in range(3):
        status, body = _post(httpd, {
            "queries": [[0.1 * i, 0.2, 0.3]], "k": 4,
            "recall_target": 0.5})
        assert status == 200 and body["gear"] == "approx:0.5"
    # the shadow dispatch runs AFTER the sampled batch's answers left
    # (that is the point: sampling must not delay what it measures), so
    # poll briefly for the third sample to land
    import time as _time

    deadline = _time.monotonic() + 30.0
    while _time.monotonic() < deadline:
        after, gauge = counters()
        if after >= mid + 3:
            break
        _time.sleep(0.05)
    assert after >= mid + 3
    assert gauge is not None and 0.0 <= gauge <= 1.0
    # the flight ring carries the per-sample evidence
    import urllib.request as _rq

    with _rq.urlopen(_url(httpd, "/debug/flight"), timeout=30) as r:
        ring = json.loads(r.read())
    samples = [e for e in ring["events"]
               if e.get("type") == "recall.sample"]
    assert samples and all("measured" in e and "estimate" in e
                           for e in samples)


def test_recall_sampler_defaults_off():
    """In-process embedders get no sampler unless they opt in (the
    serve CLI arms its default) — same posture as the ladder."""
    from kdtree_tpu.serve.batcher import MicroBatcher

    assert MicroBatcher.__init__.__defaults__[
        MicroBatcher.__init__.__code__.co_varnames.index("recall_sample")
        - 3] == 0.0  # (engine, queue) have no defaults; offset by them


def test_sampled_recall_slo_spec_armed():
    """recall_specs carries the sampled-recall gauge_min spec next to
    the estimate-watching one, on the same floor."""
    from kdtree_tpu.obs import slo as obs_slo

    specs = {s.name: s for s in obs_slo.recall_specs()}
    assert "sampled-recall" in specs and "served-recall" in specs
    spec = specs["sampled-recall"]
    assert spec.kind == "gauge_min"
    assert spec.gauge == "kdtree_recall_sampled"
    assert spec.threshold == specs["served-recall"].threshold
