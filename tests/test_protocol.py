"""Harness-protocol and golden-parity tests (SURVEY.md §4 test plan items 3/5)."""

import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parents[1]
GOLDEN = REPO / "tests" / "golden"


def _native_available() -> bool:
    from kdtree_tpu import native

    return native.available()

needs_native = pytest.mark.skipif(
    not _native_available(), reason="no g++ toolchain for the mt19937 generator"
)


def _run_cli(args, stdin=None, timeout=600):
    env = dict(os.environ)
    # hermetic CPU subprocess: env alone is NOT enough — the axon
    # sitecustomize overrides JAX_PLATFORMS with a config update, so pass the
    # CLI's --platform flag too, which pins the config after parsing.
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    return subprocess.run(
        [sys.executable, "-m", "kdtree_tpu", "--platform", "cpu", *args],
        input=stdin, capture_output=True, text=True, timeout=timeout,
        cwd=REPO, env=env,
    )


def _parse(out: str):
    lines = out.strip().splitlines()
    assert lines[0] == "READY", lines[:2]
    assert lines[-1] == "DONE", lines[-3:]
    ids, dists = [], []
    for ln in lines[1:-1]:
        # exact reference layout: "ID: <id> \t DISTANCE: <d>" (Utility.cpp:123)
        assert ln.startswith("ID: ") and " \t DISTANCE: " in ln, ln
        a, b = ln.split(" \t DISTANCE: ")
        ids.append(int(a[4:]))
        dists.append(float(b))
    return ids, dists


@pytest.mark.slow
@needs_native
def test_golden_parity_grading_config():
    """Interactive mode, seed 42, hardcoded 128-D/500k (Utility.cpp:98-99):
    output must match the compiled reference binary's capture. The 128-D
    grading config is the one configuration where the reference is correct
    (SURVEY.md §3.5), so value parity is meaningful."""
    res = _run_cli(["harness"], stdin="42\n")
    assert res.returncode == 0, res.stderr[-2000:]
    ids, dists = _parse(res.stdout)
    g_ids, g_dists = _parse((GOLDEN / "ref_seed42_128d_500k.txt").read_text())
    assert ids == g_ids
    np.testing.assert_allclose(dists, g_dists, rtol=1e-4)


@needs_native
def test_argv_mode_small():
    """argv mode (Utility.cpp:104-120) on a small problem; distances must
    match the brute-force oracle computed in-process."""
    res = _run_cli(["harness", "5", "8", "2000"])
    assert res.returncode == 0, res.stderr[-2000:]
    ids, dists = _parse(res.stdout)
    assert ids == list(range(2000, 2010))

    from kdtree_tpu import native
    from kdtree_tpu.ops import bruteforce

    pts, qs = native.generate_problem_mt19937(5, 8, 2000, 10)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=1)
    np.testing.assert_allclose(dists, np.sqrt(np.asarray(bf)[:, 0]), rtol=1e-4)


def test_argv_mode_engines_agree():
    """All engines are exact, so the protocol output is engine-independent."""
    outs = []
    for engine in ("tree", "bucket", "morton", "tiled", "bruteforce",
                   "ensemble", "global"):
        # threefry generator: engine agreement must hold without a toolchain
        res = _run_cli(["--generator", "threefry", "--engine", engine,
                        "harness", "3", "3", "500"])
        assert res.returncode == 0, (engine, res.stderr[-2000:])
        outs.append(_parse(res.stdout))
    base_ids, base_d = outs[0]
    for ids, d in outs[1:]:
        assert ids == base_ids
        np.testing.assert_allclose(d, base_d, rtol=1e-5)


def test_validation_errors():
    """validate_input parity (Utility.cpp:66-89): bad input exits 1."""
    for spec in (["-1", "3", "100"], ["1", "0", "100"], ["1", "3", "0"]):
        res = _run_cli(["harness", *spec])
        assert res.returncode == 1, spec
    res = _run_cli(["harness", "0", "3", "100"])  # seed 0: warn, proceed
    assert res.returncode == 0
    assert "Warning: default value 0 used as seed." in res.stderr


def test_usage_error():
    res = _run_cli(["harness", "1", "2"])
    assert res.returncode == 1
    assert "Usage:" in res.stderr


def test_malformed_spec():
    """Non-integer argv spec exits 1 with a diagnostic, not a traceback."""
    res = _run_cli(["harness", "42", "x", "500000"])
    assert res.returncode == 1
    assert "must be integers" in res.stderr
    assert "Traceback" not in res.stderr


@pytest.mark.parametrize("engine", ["global-morton", "global-exact"])
def test_generative_engine_protocol(engine):
    """The scale engines are first-class CLI citizens (VERDICT r2 item 3):
    harness output must equal the brute-force oracle over their own point
    set (the threefry row stream — shard-generated, never materialized)."""
    res = _run_cli(["--engine", engine, "--devices", "8",
                    "harness", "11", "3", "777"])
    assert res.returncode == 0, res.stderr[-2000:]
    ids, dists = _parse(res.stdout)
    assert ids == list(range(777, 787))

    from kdtree_tpu.ops import bruteforce
    from kdtree_tpu.ops.generate import generate_points_rowwise, generate_queries

    pts = generate_points_rowwise(11, 3, 777)
    qs = generate_queries(11, 3, 10)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=1)
    np.testing.assert_allclose(dists, np.sqrt(np.asarray(bf)[:, 0]), rtol=1e-4)


def test_ensemble_generative_never_materializes(monkeypatch, capsys):
    """VERDICT r3 item 5: --engine ensemble with the threefry generator takes
    the shard-local generative path (ensemble_knn_gen) — the [N, D] point
    array is never built. mt19937 keeps the materialized bit-exact replay,
    so only the threefry route is asserted here."""
    from kdtree_tpu.utils import cli

    def boom(*a, **kw):
        raise AssertionError("materialized [N, D] generation was called")

    monkeypatch.setattr(cli, "_generate", boom)
    cli.main(["--generator", "threefry", "--engine", "ensemble",
              "--devices", "8", "harness", "6", "3", "700"])
    ids, dists = _parse(capsys.readouterr().out)
    assert ids == list(range(700, 710))

    from kdtree_tpu.ops import bruteforce
    from kdtree_tpu.ops.generate import generate_points_rowwise, generate_queries

    pts = generate_points_rowwise(6, 3, 700)
    qs = generate_queries(6, 3, 10)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=1)
    np.testing.assert_allclose(dists, np.sqrt(np.asarray(bf)[:, 0]), rtol=1e-4)


def test_user_file_validation(tmp_path):
    """Advisor r3 items: unreadable/empty user arrays fail with the crisp
    stderr + exit-code contract (no tracebacks), and k > n prints a clamping
    notice instead of silently shrinking the --out npz."""
    tree_f = str(tmp_path / "t.npz")

    # missing file: one-line diagnostic, not an np.load traceback
    res = _run_cli(["--engine", "morton", "build",
                    "--points", str(tmp_path / "nope.npy"), "--out", tree_f])
    assert res.returncode == 1 and "cannot load" in res.stderr
    assert "Traceback" not in res.stderr

    # empty axis: rejected at the door, not deep inside an engine
    empty_f = str(tmp_path / "empty.npy")
    np.save(empty_f, np.zeros((0, 3), np.float32))
    res = _run_cli(["--engine", "morton", "build", "--points", empty_f,
                    "--out", tree_f])
    assert res.returncode == 1 and "non-empty" in res.stderr

    # k > n: engines clamp internally; the CLI must say so
    pts_f, qs_f = str(tmp_path / "p.npy"), str(tmp_path / "q.npy")
    out_f = str(tmp_path / "r.npz")
    rng = np.random.default_rng(0)
    np.save(pts_f, rng.uniform(-50, 50, (5, 3)).astype(np.float32))
    np.save(qs_f, rng.uniform(-50, 50, (3, 3)).astype(np.float32))
    res = _run_cli(["--engine", "morton", "build", "--points", pts_f,
                    "--out", tree_f])
    assert res.returncode == 0, res.stderr[-2000:]
    res = _run_cli(["query", "--tree", tree_f, "--queries", qs_f,
                    "--k", "10", "--out", out_f])
    assert res.returncode == 0, res.stderr[-2000:]
    assert "exceeds the tree's 5 points" in res.stderr
    assert np.load(out_f)["d2"].shape == (3, 5)


def test_bench_reports_three_phases():
    """VERDICT r2 item 7: bench reports gen/build/query separately."""
    import json

    res = _run_cli(["--generator", "threefry", "--engine", "morton",
                    "bench", "--n", "400", "--dim", "3"])
    assert res.returncode == 0, res.stderr[-2000:]
    rep = json.loads(res.stdout.strip().splitlines()[-1])
    for phase in ("generate", "build", "query", "total", "pts_per_sec"):
        assert phase in rep, rep


def test_bench_trace_writes_profile(tmp_path):
    """--trace wraps the timed run in jax.profiler.trace and leaves a
    Perfetto-openable artifact behind (VERDICT r2 item 7's second half)."""
    trace_dir = str(tmp_path / "trace")
    res = _run_cli(["--generator", "threefry", "--engine", "morton",
                    "bench", "--n", "400", "--dim", "3",
                    "--trace", trace_dir])
    assert res.returncode == 0, res.stderr[-2000:]
    written = [p for p in Path(trace_dir).rglob("*") if p.is_file()]
    assert written, f"no trace files under {trace_dir}"
    assert any("trace" in p.name for p in written), written


@pytest.mark.parametrize("engine", ["tree", "bucket", "morton", "global"])
def test_build_query_roundtrip(tmp_path, engine):
    """build saves provenance; query replays it regardless of --seed —
    for every checkpointable engine (mirrors the reference's per-mode run
    targets, Makefile:31-46)."""
    tree_path = str(tmp_path / "t.npz")
    res = _run_cli(["--generator", "threefry", "--engine", engine, "build",
                    "--seed", "7", "--dim", "3", "--n", "500", "--out", tree_path])
    assert res.returncode == 0, res.stderr[-2000:]
    res = _run_cli(["query", "--tree", tree_path, "--seed", "42"])
    assert res.returncode == 0, res.stderr[-2000:]
    assert "ignoring --seed 42" in res.stderr
    lines = res.stdout.strip().splitlines()
    assert lines[-1] == "DONE" and len(lines) == 11

    from kdtree_tpu.ops import bruteforce
    from kdtree_tpu.ops.generate import generate_points_rowwise, generate_queries

    # the CLI's threefry problem IS the row stream (one seeded definition
    # for generative and materialized engines alike)
    pts = generate_points_rowwise(7, 3, 500)
    qs = generate_queries(7, 3, 10)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=1)
    got = [float(ln.split(" \t DISTANCE: ")[1]) for ln in lines[:-1]]
    np.testing.assert_allclose(got, np.sqrt(np.asarray(bf)[:, 0]), rtol=1e-4)


@pytest.mark.parametrize("engine", ["global-morton", "global-exact"])
def test_build_query_roundtrip_generative(tmp_path, engine):
    """Generative-engine checkpoints via the CLI; same row-stream problem
    definition as every other threefry engine."""
    tree_path = str(tmp_path / "f.npz")
    res = _run_cli(["--engine", engine, "--devices", "8", "build",
                    "--seed", "7", "--dim", "3", "--n", "500",
                    "--out", tree_path])
    assert res.returncode == 0, res.stderr[-2000:]
    res = _run_cli(["query", "--tree", tree_path])
    assert res.returncode == 0, res.stderr[-2000:]
    lines = res.stdout.strip().splitlines()
    assert lines[-1] == "DONE" and len(lines) == 11

    from kdtree_tpu.ops import bruteforce
    from kdtree_tpu.ops.generate import generate_points_rowwise, generate_queries

    pts = generate_points_rowwise(7, 3, 500)
    qs = generate_queries(7, 3, 10)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=1)
    got = [float(ln.split(" \t DISTANCE: ")[1]) for ln in lines[:-1]]
    np.testing.assert_allclose(got, np.sqrt(np.asarray(bf)[:, 0]), rtol=1e-4)


def test_build_clustered_generative(tmp_path):
    """--distribution clustered flows through the generative scale engine
    end to end (build -> checkpoint -> protocol queries), oracle-checked
    against the materialized clustered stream; non-generative engines
    reject the flag crisply."""
    tree_path = str(tmp_path / "c.npz")
    res = _run_cli(["--engine", "global-morton", "--devices", "8", "build",
                    "--seed", "3", "--dim", "3", "--n", "2000",
                    "--distribution", "clustered", "--out", tree_path])
    assert res.returncode == 0, res.stderr[-2000:]
    res = _run_cli(["query", "--tree", tree_path])
    assert res.returncode == 0, res.stderr[-2000:]
    lines = res.stdout.strip().splitlines()
    assert lines[-1] == "DONE" and len(lines) == 11

    from kdtree_tpu.ops import bruteforce
    from kdtree_tpu.ops.generate import (
        generate_points_shard_clustered, generate_queries,
    )

    pts = generate_points_shard_clustered(3, 3, 0, 2000)
    qs = generate_queries(3, 3, 10)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=1)
    got = [float(ln.split(" \t DISTANCE: ")[1]) for ln in lines[:-1]]
    np.testing.assert_allclose(got, np.sqrt(np.asarray(bf)[:, 0]), rtol=1e-4)

    res = _run_cli(["--engine", "morton", "build", "--seed", "1", "--dim",
                    "3", "--n", "100", "--distribution", "clustered",
                    "--out", tree_path])
    assert res.returncode == 1 and "generative scale engine" in res.stderr


def test_build_query_user_files(tmp_path):
    """File-based I/O: build over user .npy points, query a user .npy set,
    read (d2, ids) back from --out — oracle-checked end to end."""
    rng = np.random.default_rng(3)
    pts = rng.uniform(-50, 50, (700, 3)).astype(np.float32)
    qs = rng.uniform(-50, 50, (37, 3)).astype(np.float32)
    pts_f, qs_f = str(tmp_path / "p.npy"), str(tmp_path / "q.npy")
    np.save(pts_f, pts)
    np.save(qs_f, qs)
    tree_f, out_f = str(tmp_path / "t.npz"), str(tmp_path / "r.npz")

    res = _run_cli(["--engine", "morton", "build", "--points", pts_f,
                    "--out", tree_f])
    assert res.returncode == 0, res.stderr[-2000:]
    res = _run_cli(["query", "--tree", tree_f, "--queries", qs_f,
                    "--k", "4", "--out", out_f])
    assert res.returncode == 0, res.stderr[-2000:]

    from kdtree_tpu.ops import bruteforce

    z = np.load(out_f)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=4)
    np.testing.assert_allclose(z["d2"], np.asarray(bf), rtol=1e-5)
    assert z["ids"].shape == (37, 4) and (z["ids"] >= 0).all()

    # a file-built checkpoint has no seeded protocol queries to fall back to
    res = _run_cli(["query", "--tree", tree_f])
    assert res.returncode == 1 and "--queries" in res.stderr

    # k=1 without --out prints protocol lines for the user queries
    res = _run_cli(["query", "--tree", tree_f, "--queries", qs_f])
    assert res.returncode == 0, res.stderr[-2000:]
    lines = res.stdout.strip().splitlines()
    assert lines[-1] == "DONE" and len(lines) == 38
    got = [float(ln.split(" \t DISTANCE: ")[1]) for ln in lines[:-1]]
    np.testing.assert_allclose(got, np.sqrt(bf[:, 0]), rtol=1e-4)

    # k>1 without --out would silently drop neighbors — must refuse
    res = _run_cli(["query", "--tree", tree_f, "--queries", qs_f, "--k", "4"])
    assert res.returncode == 1 and "--out" in res.stderr

    # NaN-poisoned input fails loudly (SURVEY §5 guard at the boundary)
    bad_f = str(tmp_path / "bad.npy")
    bad = pts.copy()
    bad[5, 1] = np.nan
    np.save(bad_f, bad)
    res = _run_cli(["--engine", "morton", "build", "--points", bad_f,
                    "--out", tree_f])
    assert res.returncode == 1 and "non-finite" in res.stderr


def test_build_capacity_error_exits_crisply(tmp_path, monkeypatch, capsys):
    """ADVICE r4: the HBM capacity guard's BuildCapacityError must surface
    from the CLI as the crisp stderr + exit-code contract (C10), not a raw
    traceback. In-process so the TPU backend + tiny budget can be faked."""
    import jax

    from kdtree_tpu.utils import cli

    monkeypatch.setenv("KDTREE_TPU_MAX_BUILD_BYTES", "64")
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    with pytest.raises(SystemExit) as ei:
        cli.main(["--engine", "morton", "--generator", "threefry",
                  "build", "--n", "512", "--out", str(tmp_path / "t.npz")])
    assert ei.value.code == 1
    err = capsys.readouterr().err
    assert "global-morton" in err and "Traceback" not in err


def test_cli_scale_engine_ingests_user_points(tmp_path):
    """VERDICT r4 missing #3, CLI surface: `build --engine global-morton
    --points f.npy` builds a forest over the 8-device mesh from user data
    and `query --queries` answers oracle-exact; global-exact still refuses
    with a pointer at the supported route."""
    rng = np.random.default_rng(11)
    n, dim, k = 20_000, 3, 4
    pts = (rng.normal(size=(n, dim)) * [3.0, 30.0, 0.3]).astype(np.float32)
    qs = (pts[::1000] + 0.01).astype(np.float32)
    pts_f, qs_f = str(tmp_path / "p.npy"), str(tmp_path / "q.npy")
    np.save(pts_f, pts)
    np.save(qs_f, qs)
    tree_f, out_f = str(tmp_path / "t.npz"), str(tmp_path / "r.npz")

    res = _run_cli(["--engine", "global-morton", "build", "--points", pts_f,
                    "--out", tree_f])
    assert res.returncode == 0, res.stderr[-2000:]
    res = _run_cli(["query", "--tree", tree_f, "--queries", qs_f,
                    "--k", str(k), "--out", out_f])
    assert res.returncode == 0, res.stderr[-2000:]

    from kdtree_tpu.ops import bruteforce

    z = np.load(out_f)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=k)
    np.testing.assert_allclose(z["d2"], np.asarray(bf), rtol=1e-4, atol=1e-6)
    assert (z["ids"] >= 0).all() and (z["ids"] < n).all()

    # the exact-median engine stays generative-only, pointing at the
    # supported ingest route
    res = _run_cli(["--engine", "global-exact", "build", "--points", pts_f,
                    "--out", tree_f])
    assert res.returncode == 1 and "global-morton" in res.stderr


def test_cli_presharded_ingest(tmp_path):
    """CLI surface for pre-sharded ingest: --points with a {i} placeholder
    maps file i onto device i with no redistribution; protocol queries on
    the checkpoint require --queries (file provenance), and answers are
    oracle-exact over the files' concatenation order."""
    rng = np.random.default_rng(13)
    dim, k = 3, 3
    parts = [rng.normal(size=(m, dim)).astype(np.float32) * 5.0
             for m in (3000, 1500, 2500, 3000)]
    for i, part in enumerate(parts):
        np.save(tmp_path / f"part-{i}.npy", part)
    cat = np.concatenate(parts)
    qs = (cat[::800] + 0.01).astype(np.float32)
    qs_f = str(tmp_path / "q.npy")
    np.save(qs_f, qs)
    tree_f, out_f = str(tmp_path / "t.npz"), str(tmp_path / "r.npz")

    res = _run_cli(["--engine", "global-morton", "build",
                    "--points", str(tmp_path / "part-{i}.npy"),
                    "--out", tree_f])
    assert res.returncode == 0, res.stderr[-2000:]
    res = _run_cli(["query", "--tree", tree_f, "--queries", qs_f,
                    "--k", str(k), "--out", out_f])
    assert res.returncode == 0, res.stderr[-2000:]

    from kdtree_tpu.ops import bruteforce

    z = np.load(out_f)
    bf, _ = bruteforce.knn_exact_d2(cat, qs, k=k)
    np.testing.assert_allclose(z["d2"], np.asarray(bf), rtol=1e-4, atol=1e-6)
    assert (z["ids"] >= 0).all() and (z["ids"] < len(cat)).all()

    # a pattern matching no files fails crisply
    res = _run_cli(["--engine", "global-morton", "build",
                    "--points", str(tmp_path / "nope-{i}.npy"),
                    "--out", tree_f])
    assert res.returncode == 1 and "no shard files" in res.stderr

    # a GAP in the sequence must refuse (partial index = silent wrong
    # answers), and --devices conflicting with the file count must refuse
    (tmp_path / "part-1.npy").unlink()
    res = _run_cli(["--engine", "global-morton", "build",
                    "--points", str(tmp_path / "part-{i}.npy"),
                    "--out", tree_f])
    assert res.returncode == 1 and "gap" in res.stderr
    np.save(tmp_path / "part-1.npy", parts[1])
    res = _run_cli(["--engine", "global-morton", "--devices", "2", "build",
                    "--points", str(tmp_path / "part-{i}.npy"),
                    "--out", tree_f])
    assert res.returncode == 1 and "conflicts" in res.stderr

    # stray braces beyond {i} fail crisply, not with a format() traceback
    res = _run_cli(["--engine", "global-morton", "build",
                    "--points", str(tmp_path / "part-{i}-{run}.npy"),
                    "--out", tree_f])
    assert res.returncode == 1 and "pattern" in res.stderr
    assert "Traceback" not in res.stderr


def test_cli_slack_flag(tmp_path):
    """--slack is the overflow error's documented remedy: an absurdly
    tight value must fail crisply (no traceback), and a generous one must
    build; both through the generative scale engine."""
    tree_f = str(tmp_path / "t.npz")
    res = _run_cli(["--engine", "global-morton", "--devices", "8",
                    "--generator", "threefry", "build", "--n", "4096",
                    "--slack", "0.02", "--out", tree_f])
    assert res.returncode == 1 and "overflow" in res.stderr
    assert "Traceback" not in res.stderr
    res = _run_cli(["--engine", "global-morton", "--devices", "8",
                    "--generator", "threefry", "build", "--n", "4096",
                    "--slack", "3.0", "--out", tree_f])
    assert res.returncode == 0, res.stderr[-2000:]
    assert "saved GlobalMortonForest" in res.stdout
