"""Scatter/gather routing (docs/SERVING.md "Routing & fault tolerance").

Two layers of evidence:

1. **Exactness**: with every shard healthy, the routed answer is
   byte-identical (ids AND distances) to the single-index oracle — over
   in-process shards for speed, and over real multi-process
   ``kdtree-tpu serve`` spawns for the acceptance e2e.
2. **Robustness**: every injected fault class (latency, error, hang,
   connection drop — ``serve/faults.py``) is pinned by a deterministic
   test: the router meets its own deadline, failures surface as flagged
   partial results or crisp 503s (never silent wrong answers), and the
   faulty shard's breaker opens then recovers half-open → closed when
   the fault clears.
"""

from __future__ import annotations

import contextlib
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from kdtree_tpu import obs
from kdtree_tpu.serve import faults as faults_mod
from kdtree_tpu.serve import lifecycle
from kdtree_tpu.serve import router as rt
from kdtree_tpu.serve import server as srv

REPO = Path(__file__).resolve().parents[1]
DIM, K = 3, 4
SHARD_N = 1024
N_SHARDS = 3
SEED = 7


# ---------------------------------------------------------------------------
# helpers / fixtures
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def points():
    from kdtree_tpu.ops.generate import generate_points_rowwise

    return np.asarray(
        generate_points_rowwise(SEED, DIM, N_SHARDS * SHARD_N)
    )


@pytest.fixture(scope="module")
def oracle_tree(points):
    import jax.numpy as jnp

    from kdtree_tpu.ops.morton import build_morton

    return build_morton(jnp.asarray(points))


def _oracle(tree, queries, k):
    import jax.numpy as jnp

    from kdtree_tpu.ops.tile_query import morton_knn_tiled

    d2, ids = morton_knn_tiled(tree, jnp.asarray(queries), k=k)
    return (
        np.sqrt(np.asarray(d2).astype(np.float64)).tolist(),
        np.asarray(ids).tolist(),
    )


class Shards:
    """N in-process shard servers over a contiguous partition, each with
    its own FaultSet — one shard faults, its neighbors don't."""

    def __init__(self, points):
        self.servers = []
        self.faults = []
        self.urls = []
        for i in range(N_SHARDS):
            sub = points[i * SHARD_N:(i + 1) * SHARD_N]
            state = lifecycle.build_state(
                points=sub, k=K, max_batch=64, id_offset=i * SHARD_N,
            )
            fset = faults_mod.FaultSet()
            httpd = srv.make_server(state, port=0, faults=fset)
            httpd.start(warmup_buckets=[8])
            self.servers.append(httpd)
            self.faults.append(fset)
            self.urls.append(f"http://127.0.0.1:{httpd.server_address[1]}")

    def clear_faults(self):
        for f in self.faults:
            f.clear()

    def stop(self):
        for httpd in self.servers:
            httpd.stop()


@pytest.fixture(scope="module")
def shards(points):
    sh = Shards(points)
    yield sh
    sh.clear_faults()
    sh.stop()


@contextlib.contextmanager
def router_for(shards, health_loop=False, **cfg):
    defaults = dict(deadline_s=30.0, retries=2, backoff_base_s=0.01,
                    hedge_min_s=0.05, breaker_failures=2,
                    breaker_reset_s=0.3, health_period_s=0.2)
    defaults.update(cfg)
    router = rt.make_router(shards.urls, config=rt.RouterConfig(**defaults))
    router.start(health_loop=health_loop)
    try:
        yield router
    finally:
        router.stop()


def _post(router, payload, timeout=120.0, headers=None):
    url = f"http://127.0.0.1:{router.server_address[1]}/v1/knn"
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(router, path, timeout=30.0):
    url = f"http://127.0.0.1:{router.server_address[1]}{path}"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _queries(rows, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.random((rows, DIM)) * 200.0 - 100.0).astype(np.float32)


def _counter(key):
    return obs.get_registry().snapshot()["counters"].get(key, 0.0)


# ---------------------------------------------------------------------------
# fault-spec + breaker + merge units
# ---------------------------------------------------------------------------


def test_fault_spec_parsing():
    fs = faults_mod.parse_spec(
        "knn=latency:250,healthz=error:503*2,knn=hang"
    )
    assert [f.kind for f in fs] == ["latency", "error", "hang"]
    assert fs[0].param == 250.0 and fs[1].remaining == 2
    assert faults_mod.parse_spec("") == []
    for bad in ("knn", "knn=bogus", "knn=latency", "knn=error*0",
                "knn=error*x", "=error", "knn=error:9000",
                "knn=latency:abc",
                # sites are a bounded enum: a typo'd site must be a
                # parse error, never a silently-inert clause
                "helthz=error", "kn=hang"):
        with pytest.raises(faults_mod.FaultSpecError):
            faults_mod.parse_spec(bad)


def test_fault_budget_spends_deterministically():
    fs = faults_mod.FaultSet("knn=error*2")
    assert fs.fire("knn")["status"] == 500
    assert fs.fire("knn")["kind"] == "error"
    assert fs.fire("knn") is None  # spent
    assert fs.fire("healthz") is None  # site mismatch never fires
    assert fs.describe()[0]["fired"] == 2


def test_fault_hang_param_is_milliseconds():
    """hang's optional max-park bound shares latency's unit (ms): a
    hang:50 releases itself in ~50 ms, not 50 s."""
    fs = faults_mod.FaultSet("knn=hang:50")
    t0 = time.monotonic()
    assert fs.fire("knn") is None
    assert time.monotonic() - t0 < 2.0
    with pytest.raises(faults_mod.FaultSpecError):
        faults_mod.parse_spec("knn=hang:-5")


def test_fault_hang_releases_on_clear():
    fs = faults_mod.FaultSet("knn=hang")
    released = []

    def victim():
        fs.fire("knn")  # parks on the unblock event
        released.append(time.monotonic())

    t = threading.Thread(target=victim)
    t.start()
    time.sleep(0.1)
    assert not released  # genuinely parked
    fs.clear()
    t.join(timeout=5)
    assert released, "clear() must release a parked hang"


def test_breaker_state_machine():
    b = rt.CircuitBreaker(failures=2, reset_s=0.15)
    assert b.allow()
    b.record_failure()
    assert b.state == rt.CLOSED and b.allow()
    b.record_failure()
    assert b.state == rt.OPEN and not b.allow()
    time.sleep(0.16)
    assert b.allow()  # half-open probe
    assert b.state == rt.HALF_OPEN
    assert not b.allow()  # only ONE probe at a time
    b.record_failure()  # probe failed: re-open for another cooldown
    assert b.state == rt.OPEN and not b.allow()
    time.sleep(0.16)
    assert b.allow()
    b.record_success()
    assert b.state == rt.CLOSED and b.allow()


def test_breaker_success_resets_consecutive_count():
    b = rt.CircuitBreaker(failures=2, reset_s=10.0)
    b.record_failure()
    b.record_success()
    b.record_failure()
    assert b.state == rt.CLOSED  # 2 failures, but not consecutive


def test_merge_topk_matches_forest_tie_break():
    a = {"k": 2, "ids": [[5, 1]], "distances": [[0.5, 1.5]]}
    b = {"k": 2, "ids": [[2, 0]], "distances": [[1.5, 3.0]]}
    dists, ids, kk = rt.merge_topk([a, b], 2)
    # the 1.5 tie breaks by id (stable (distance, id) sort — the
    # _merge_partials rule), so id 1 wins over id 2
    assert kk == 2 and dists == [[0.5, 1.5]] and ids == [[5, 1]]
    dists, ids, kk = rt.merge_topk([a, b], None)
    assert kk == 2  # k defaults to the min shard k
    dists, ids, kk = rt.merge_topk([a], 1)
    assert ids == [[5]]


# ---------------------------------------------------------------------------
# exactness: routed == oracle
# ---------------------------------------------------------------------------


def test_routed_matches_oracle_in_process(shards, oracle_tree):
    """All shards healthy: merged ids AND distances byte-identical to
    the single-index oracle, per-request k respected, degraded null."""
    with router_for(shards) as router:
        for rows, k, seed in ((5, K, 1), (3, 2, 2), (8, 1, 3)):
            q = _queries(rows, seed=seed)
            status, out = _post(router, {"queries": q.tolist(), "k": k})
            assert status == 200
            dist, ids = _oracle(oracle_tree, q, k)
            assert out["ids"] == ids
            assert out["distances"] == dist
            assert out["degraded"] is None
            assert out["shards"] == {"total": N_SHARDS,
                                     "contacted": N_SHARDS,
                                     "answered": N_SHARDS, "missing": [],
                                     "pruned": 0}


def test_router_trace_id_threads_to_shards(shards):
    with router_for(shards) as router:
        status, out = _post(router, {"queries": _queries(2).tolist()},
                            headers={"X-Request-Id": "route-trace-1"})
        assert status == 200
        assert out["trace_id"] == "route-trace-1"
        # the SAME id flows to every shard (X-Request-Id forwarded), so
        # the shard-side flight rings correlate with the router's
        from kdtree_tpu.obs import flight

        events = flight.recorder().snapshot()
        mine = [e for e in events if e.get("type") == "serve.request"
                and e.get("trace") == "route-trace-1"]
        assert len(mine) >= N_SHARDS


# ---------------------------------------------------------------------------
# fault classes: error / latency / hang / drop
# ---------------------------------------------------------------------------


def test_error_fault_healed_by_bounded_retry(shards):
    """A transient error (2 bounded 503s) is absorbed by the retry
    policy: the client sees a full, exact answer and the retry counter
    moved."""
    retry_key = 'kdtree_router_retries_total{shard="1"}'
    shards.faults[1].set_spec("knn=error:503*2")
    try:
        # breaker threshold ABOVE the in-request failure count: this
        # test is about retries healing, not the breaker opening
        with router_for(shards, retries=2, breaker_failures=5) as router:
            r0 = _counter(retry_key)
            status, out = _post(router, {"queries": _queries(4).tolist()})
            assert status == 200
            assert out["degraded"] is None
            assert out["shards"]["answered"] == N_SHARDS
            assert _counter(retry_key) >= r0 + 2
    finally:
        shards.clear_faults()


def test_error_fault_partial_then_breaker_opens(shards):
    """A persistently erroring shard: responses degrade to flagged
    partials (never 5xx, never silent wrong answers), the partial
    counter moves, and the shard's breaker opens."""
    partial_key = "kdtree_router_partial_total"
    shards.faults[2].set_spec("knn=error")
    try:
        with router_for(shards, retries=1) as router:
            p0 = _counter(partial_key)
            for i in range(2):
                status, out = _post(
                    router, {"queries": _queries(4, seed=i).tolist()}
                )
                assert status == 200
                assert out["degraded"] == f"partial:2/{N_SHARDS}"
                assert out["shards"]["missing"] == [2]
            assert _counter(partial_key) == p0 + 2
            report = router.shard_report()
            assert report[2]["breaker"] == "open"
            assert not report[2]["routable"]
            # breaker state is live on the router's registry too
            gauges = obs.get_registry().snapshot()["gauges"]
            assert gauges['kdtree_router_breaker_state{shard="2"}'] == rt.OPEN
    finally:
        shards.clear_faults()


def test_breaker_recovers_half_open_to_closed(shards, oracle_tree):
    """Fault cleared: after the cooldown the half-open probe succeeds
    and the breaker closes — the shard is back in every merge."""
    shards.faults[0].set_spec("knn=error")
    try:
        with router_for(shards, retries=0,
                        breaker_reset_s=0.25) as router:
            for i in range(2):  # 2 consecutive failures open the breaker
                _post(router, {"queries": _queries(3, seed=i).tolist()})
            assert router.shard_report()[0]["breaker"] == "open"
            shards.clear_faults()
            time.sleep(0.3)  # past the cooldown: next allow() is the probe
            q = _queries(5, seed=9)
            status, out = _post(router, {"queries": q.tolist(), "k": K})
            assert status == 200
            assert out["degraded"] is None
            dist, ids = _oracle(oracle_tree, q, K)
            assert out["ids"] == ids and out["distances"] == dist
            assert router.shard_report()[0]["breaker"] == "closed"
            trans = _counter(
                'kdtree_router_breaker_transitions_total'
                '{shard="0",to="closed"}'
            )
            assert trans >= 1
    finally:
        shards.clear_faults()


def test_latency_fault_triggers_hedge_still_exact(shards, oracle_tree):
    """A slow shard (injected latency past the hedge delay) fires a
    hedge; the answer stays full and exact, within the deadline."""
    hedge_key = 'kdtree_router_hedges_total{shard="1"}'
    shards.faults[1].set_spec("knn=latency:400")
    try:
        with router_for(shards, deadline_s=10.0,
                        hedge_min_s=0.05) as router:
            h0 = _counter(hedge_key)
            q = _queries(4, seed=11)
            t0 = time.monotonic()
            status, out = _post(router, {"queries": q.tolist(), "k": K})
            elapsed = time.monotonic() - t0
            assert status == 200 and out["degraded"] is None
            dist, ids = _oracle(oracle_tree, q, K)
            assert out["ids"] == ids and out["distances"] == dist
            assert _counter(hedge_key) >= h0 + 1
            assert elapsed < 10.0  # well inside the deadline
    finally:
        shards.clear_faults()


def test_hang_fault_partial_within_deadline(shards):
    """A hung shard: the router answers a flagged partial no later than
    deadline + one hedge interval (the acceptance bound), and the shard
    handler is released by the fault clear, not the router."""
    deadline_s = 1.0
    shards.faults[2].set_spec("knn=hang")
    try:
        with router_for(shards, deadline_s=deadline_s, retries=0,
                        hedge_min_s=0.05) as router:
            t0 = time.monotonic()
            status, out = _post(router, {"queries": _queries(3).tolist()})
            elapsed = time.monotonic() - t0
            assert status == 200
            assert out["degraded"] == f"partial:2/{N_SHARDS}"
            assert out["shards"]["missing"] == [2]
            assert elapsed < deadline_s + 0.05 + 1.0  # deadline + hedge + slack
    finally:
        shards.clear_faults()


def test_drop_fault_partial_and_fast(shards):
    """A connection-dropping shard fails FAST (protocol error, not a
    timeout): the partial answer arrives in a fraction of the deadline
    and the attempt counter records a network outcome."""
    net_key = 'kdtree_router_shard_attempts_total{outcome="network",shard="0"}'
    shards.faults[0].set_spec("knn=drop")
    try:
        with router_for(shards, deadline_s=5.0, retries=0) as router:
            n0 = _counter(net_key)
            t0 = time.monotonic()
            status, out = _post(router, {"queries": _queries(3).tolist()})
            elapsed = time.monotonic() - t0
            assert status == 200
            assert out["degraded"] == f"partial:2/{N_SHARDS}"
            assert out["shards"]["missing"] == [0]
            assert elapsed < 2.0  # drop is fast, nothing waited out 5 s
            assert _counter(net_key) >= n0 + 1
    finally:
        shards.clear_faults()


def test_below_quorum_crisp_503_never_silent(shards):
    """Two of three shards erroring (majority quorum = 2): a crisp 503
    naming the failing shards — a sub-quorum merge must never pass as an
    answer."""
    shards.faults[0].set_spec("knn=error")
    shards.faults[1].set_spec("knn=error")
    try:
        with router_for(shards, retries=0) as router:
            status, out = _post(router, {"queries": _queries(3).tolist()})
            assert status == 503
            assert "quorum" in out["error"]
            assert out["shards"]["missing"] == [0, 1]
            assert _counter(
                'kdtree_router_requests_total{status="unavailable"}'
            ) >= 1
    finally:
        shards.clear_faults()


def test_client_error_propagates_not_retried(shards):
    """k beyond the shards' compiled cap is the CLIENT's error: the
    router propagates the 400 instead of retrying it into a partial."""
    with router_for(shards) as router:
        status, out = _post(
            router, {"queries": _queries(2).tolist(), "k": K + 1}
        )
        assert status == 400
        assert "k" in out["error"]
        # malformed router-level bodies reject at the router itself
        assert _post(router, {"nope": 1})[0] == 400
        assert _post(router, {"queries": [[0.0] * DIM], "k": 0})[0] == 400


# ---------------------------------------------------------------------------
# health aggregation + ejection
# ---------------------------------------------------------------------------


def test_healthz_aggregates_and_ejects(shards):
    with router_for(shards) as router:
        for shard in router.shards:
            router._probe_health(shard)
        status, body = _get(router, "/healthz")
        assert status == 200 and body["status"] == "ok"
        assert body["available"] == N_SHARDS and body["quorum"] == 2
        # fail one shard's health endpoint: the probe ejects it, the
        # aggregate stays 200 (quorum still holds) and names it
        shards.faults[1].set_spec("healthz=error:503")
        try:
            router._probe_health(router.shards[1])
            status, body = _get(router, "/healthz")
            assert status == 200 and body["available"] == N_SHARDS - 1
            assert body["shards"][1]["healthy"] is False
            assert not body["shards"][1]["routable"]
            # an ejected shard is skipped by the scatter: partial answer
            # without burning the deadline on a known-bad shard
            status, out = _post(router, {"queries": _queries(2).tolist()})
            assert status == 200
            assert out["degraded"] == f"partial:2/{N_SHARDS}"
        finally:
            shards.clear_faults()
        router._probe_health(router.shards[1])
        assert router.shards[1].healthy
        status, body = _get(router, "/debug/shards")
        assert status == 200 and len(body["shards"]) == N_SHARDS


def test_healthz_below_quorum_503(shards):
    with router_for(shards) as router:
        shards.faults[0].set_spec("healthz=error:503")
        shards.faults[1].set_spec("healthz=error:503")
        try:
            for shard in router.shards:
                router._probe_health(shard)
            status, body = _get(router, "/healthz")
            assert status == 503 and body["status"] == "unavailable"
            assert body["available"] == 1
        finally:
            shards.clear_faults()
        for shard in router.shards:
            router._probe_health(shard)


# ---------------------------------------------------------------------------
# /metrics federation (one scrape for the fleet)
# ---------------------------------------------------------------------------


def _get_text(router, path, timeout=30.0):
    url = f"http://127.0.0.1:{router.server_address[1]}{path}"
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


def test_metrics_federation_shard_labeled_and_family_grouped(shards):
    with router_for(shards) as router:
        status, text = _get_text(router, "/metrics?federate=1")
        assert status == 200
        lines = text.splitlines()
        # every shard's serving families appear, shard-labeled
        for i in range(N_SHARDS):
            assert any(
                ln.startswith(f'kdtree_serve_ready{{shard="{i}"}}')
                for ln in lines
            ), f"shard {i} series missing"
            assert f'kdtree_router_federated_up{{shard="{i}"}} 1' in lines
        # the router's own families ride along un-labeled
        assert any(ln.startswith("kdtree_router_shards ")
                   for ln in lines)
        # format requirement: each family is ONE contiguous block —
        # a # TYPE header may appear only once per family
        seen = set()
        for ln in lines:
            if ln.startswith("# TYPE "):
                name = ln.split(" ")[2]
                assert name not in seen, f"family {name} split in two"
                seen.add(name)
        # shard-labeled histograms keep their inner labels too
        assert any(
            ln.startswith('kdtree_serve_request_seconds_bucket{shard="0",')
            for ln in lines
        )


def test_metrics_federation_reports_dead_shard_not_scrape_failure(shards):
    with router_for(shards) as router:
        # point shard 2's table entry at a dead port: the scrape must
        # still answer 200 and name the gap instead of failing
        real_port = router.shards[2].port
        router.shards[2].port = 1  # nothing listens there
        status, text = _get_text(router, "/metrics?federate=1")
        router.shards[2].port = real_port
        assert status == 200
        assert 'kdtree_router_federated_up{shard="2"} 0' in text
        assert 'kdtree_router_federated_up{shard="0"} 1' in text
        # the failure counted; it lands on the router's own exposition
        status, text = _get_text(router, "/metrics")
        assert 'kdtree_router_federate_errors_total{shard="2"}' in text


def test_plain_metrics_unchanged_by_federation_flag(shards):
    with router_for(shards) as router:
        status, text = _get_text(router, "/metrics")
        assert status == 200
        # no synthetic federation families, no federation-injected
        # shard labels (in-process shards share this registry, so the
        # serve families themselves legitimately appear un-labeled)
        assert "kdtree_router_federated_up" not in text
        assert 'kdtree_serve_ready{shard="' not in text
        assert any(ln.startswith("# TYPE kdtree_router_shards")
                   for ln in text.splitlines())


# ---------------------------------------------------------------------------
# write passthrough (mutable index): ids partition by owning shard
# ---------------------------------------------------------------------------


@pytest.fixture
def write_shards(points):
    """A fresh 2-shard fleet for WRITE tests — the module-scoped
    ``shards`` fixture must stay immutable (the oracle-identity tests
    depend on its content)."""
    servers, urls = [], []
    for i in range(2):
        sub = points[i * SHARD_N:(i + 1) * SHARD_N]
        state = lifecycle.build_state(
            points=sub, k=K, max_batch=64, id_offset=i * SHARD_N,
            max_delta_rows=1 << 20,
        )
        httpd = srv.make_server(state, port=0)
        httpd.start(warmup_buckets=[8])
        servers.append(httpd)
        urls.append(f"http://127.0.0.1:{httpd.server_address[1]}")
    yield servers, urls
    for httpd in servers:
        httpd.stop()


@contextlib.contextmanager
def write_router(urls, probe=True, **cfg):
    defaults = dict(deadline_s=30.0, retries=1, backoff_base_s=0.01)
    defaults.update(cfg)
    router = rt.make_router(urls, config=rt.RouterConfig(**defaults))
    router.start(health_loop=False)
    try:
        if probe:
            for shard in router.shards:
                router._probe_health(shard)
        yield router
    finally:
        router.stop()


def _post_path(router, path, payload, timeout=120.0):
    url = f"http://127.0.0.1:{router.server_address[1]}{path}"
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_route_write_partitions_by_id_range(write_shards):
    servers, urls = write_shards
    with write_router(urls) as router:
        assert [s.id_offset for s in router.shards] == [0, SHARD_N]
        # one request spanning both shards + a brand-new id (beyond
        # every range → owned by the last shard)
        ids = [5, SHARD_N + 7, 10 * SHARD_N]
        pts = [[300.0, 300.0, 300.0], [310.0, 310.0, 310.0],
               [320.0, 320.0, 320.0]]
        status, body = _post_path(router, "/v1/upsert",
                                  {"ids": ids, "points": pts})
        assert status == 200 and body["applied"] == 3, body
        assert set(body["shards"]) == {"0", "1"}
        assert body["shards"]["0"]["applied"] == 1
        assert body["shards"]["1"]["applied"] == 2
        # the routed read sees all three, with GLOBAL ids
        status, body = _post_path(router, "/v1/knn",
                                  {"queries": [[305.0, 305.0, 305.0]],
                                   "k": 3})
        assert status == 200
        assert sorted(body["ids"][0]) == sorted(ids)
        # routed delete: only the owning shard applies it
        status, body = _post_path(router, "/v1/delete",
                                  {"ids": [SHARD_N + 7]})
        assert status == 200 and body["applied"] == 1
        assert list(body["shards"]) == ["1"]
        status, body = _post_path(router, "/v1/knn",
                                  {"queries": [[305.0, 305.0, 305.0]],
                                   "k": 3})
        assert SHARD_N + 7 not in body["ids"][0]


def test_route_write_validation_and_unknown_ranges(write_shards):
    servers, urls = write_shards
    with write_router(urls, probe=False) as router:
        # no health probe has run: id ranges unknown — refusing beats
        # guessing a partition
        status, body = _post_path(router, "/v1/upsert",
                                  {"ids": [1],
                                   "points": [[1.0, 2.0, 3.0]]})
        assert status == 503 and "id ranges unknown" in body["error"]
    with write_router(urls) as router:
        status, body = _post_path(router, "/v1/upsert",
                                  {"ids": [], "points": []})
        assert status == 400
        status, body = _post_path(router, "/v1/upsert", {"ids": [3]})
        assert status == 400 and "points" in body["error"]
        status, body = _post_path(router, "/v1/delete", {"ids": [1.5]})
        assert status == 400
        # duplicates must be rejected BEFORE partitioning: a dup
        # spanning shards would be 400d by one shard after another
        # already applied — a guaranteed half-write
        status, body = _post_path(
            router, "/v1/upsert",
            {"ids": [5, 5, SHARD_N + 7],
             "points": [[1.0, 2.0, 3.0]] * 3},
        )
        assert status == 400 and "duplicate" in body["error"]
        assert "applied" not in body or body.get("applied") in (None, 0)
        # a shard-side rejection (wrong dim) propagates as a clean 4xx
        # when a single shard owns the whole request
        status, body = _post_path(router, "/v1/upsert",
                                  {"ids": [3], "points": [[1.0, 2.0]]})
        assert status == 400, body
        assert body["applied"] == 0


def test_route_write_failed_shard_answers_502_partial_visible(
    write_shards,
):
    servers, urls = write_shards
    with write_router(urls) as router:
        # kill shard 1's listener: a spanning write must answer 502
        # with the per-shard outcome visible, never a silent half-write
        real_port = router.shards[1].port
        router.shards[1].port = 1
        status, body = _post_path(
            router, "/v1/upsert",
            {"ids": [6, SHARD_N + 8],
             "points": [[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]]},
        )
        assert status == 502, body
        assert body["applied"] == 1  # shard 0's half DID apply
        assert body["shards"]["0"]["applied"] == 1
        assert "error" in body["shards"]["1"]
        router.shards[1].port = real_port


# ---------------------------------------------------------------------------
# Retry-After honored
# ---------------------------------------------------------------------------


class _ScriptedShard:
    """A stub shard: scripted (status, headers, body) responses, so shed
    semantics are tested without timing a real queue into 429."""

    def __init__(self, script):
        import http.server

        stub = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", "0"))
                self.rfile.read(length)
                status, headers, body = stub.script.pop(0)
                stub.served.append((time.monotonic(), status))
                raw = json.dumps(body).encode()
                self.send_response(status)
                for key, val in headers.items():
                    self.send_header(key, val)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

        self.script = list(script)
        self.served = []
        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                                     Handler)
        self.thread = threading.Thread(target=self.httpd.serve_forever)
        self.thread.start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def stop(self):
        self.httpd.shutdown()
        self.thread.join()
        self.httpd.server_close()


def test_router_honors_shard_retry_after():
    """A shard shedding with Retry-After: 1 must not see its retry
    before that second has passed — the shard's measured advice outranks
    the router's generic backoff schedule."""
    ok_body = {"k": 1, "ids": [[3]], "distances": [[0.25]],
               "degraded": None, "trace_id": ""}
    stub = _ScriptedShard([
        (429, {"Retry-After": "1"}, {"error": "overloaded"}),
        (200, {}, ok_body),
    ])
    try:
        router = rt.make_router(
            [stub.url],
            config=rt.RouterConfig(deadline_s=10.0, retries=2, quorum=1,
                                   backoff_base_s=0.01),
        )
        router.start(health_loop=False)
        try:
            status, out = _post(router, {"queries": [[0.0] * DIM]})
            assert status == 200 and out["ids"] == [[3]]
            assert len(stub.served) == 2
            gap = stub.served[1][0] - stub.served[0][0]
            assert gap >= 0.9, f"retried after only {gap:.2f}s"
            shed = _counter(
                'kdtree_router_shard_attempts_total'
                '{outcome="shed",shard="0"}'
            )
            assert shed >= 1
        finally:
            router.stop()
    finally:
        stub.stop()


# ---------------------------------------------------------------------------
# shutdown under partial failure
# ---------------------------------------------------------------------------


def test_half_open_probe_answered_with_4xx_closes_breaker():
    """A 4xx is the shard ANSWERING: a half-open probe that draws a
    client error must release the probe slot and close the breaker —
    the leak would otherwise refuse the shard forever."""
    ok_body = {"k": 1, "ids": [[3]], "distances": [[0.25]],
               "degraded": None, "trace_id": ""}
    stub = _ScriptedShard([
        (500, {}, {"error": "boom"}),
        (500, {}, {"error": "boom"}),
        (400, {}, {"error": "bad k"}),   # the half-open probe
        (200, {}, ok_body),
    ])
    try:
        router = rt.make_router(
            [stub.url],
            config=rt.RouterConfig(deadline_s=10.0, retries=0, quorum=1,
                                   breaker_failures=2, breaker_reset_s=0.2),
        )
        router.start(health_loop=False)
        try:
            for _ in range(2):  # open the breaker
                assert _post(router, {"queries": [[0.0] * DIM]})[0] == 503
            assert router.shards[0].breaker.state == rt.OPEN
            time.sleep(0.25)
            # the probe: shard answers 400 -> propagated, breaker CLOSED
            assert _post(router, {"queries": [[0.0] * DIM]})[0] == 400
            assert router.shards[0].breaker.state == rt.CLOSED
            status, out = _post(router, {"queries": [[0.0] * DIM]})
            assert status == 200 and out["ids"] == [[3]]
        finally:
            router.stop()
    finally:
        stub.stop()


def test_shutdown_mid_fanout_drains_in_flight_scatter(shards):
    """SIGTERM contract (cmd_route wires SIGTERM to exactly this
    ``stop()``): stopping the router while a scatter is mid-flight — one
    shard hung — still answers the in-flight request (partial or
    complete, never dropped), and stop() returns with every handler and
    scatter thread joined, no shard connection orphaned."""
    deadline_s = 1.2
    shards.faults[1].set_spec("knn=hang")
    try:
        router = rt.make_router(
            shards.urls,
            config=rt.RouterConfig(deadline_s=deadline_s, retries=0,
                                   hedge_min_s=0.05),
        )
        router.start(health_loop=False)
        out = [None]

        def client():
            try:
                out[0] = _post(router, {"queries": _queries(3).tolist()},
                               timeout=30.0)
            except OSError as e:  # a dropped in-flight request fails the test
                out[0] = ("refused", repr(e))

        t = threading.Thread(target=client)
        t.start()
        time.sleep(0.3)  # the scatter is now mid-flight, shard 1 hung
        t0 = time.monotonic()
        router.stop()  # must drain, not drop
        stop_elapsed = time.monotonic() - t0
        t.join(timeout=30)
        assert out[0] is not None and out[0][0] == 200, out[0]
        assert out[0][1]["degraded"] == f"partial:2/{N_SHARDS}"
        # stop() waited for the in-flight scatter but not much longer
        assert stop_elapsed < deadline_s + 5.0
        # post-stop requests are refused at the TCP level
        with pytest.raises(OSError):
            _post(router, {"queries": _queries(2).tolist()}, timeout=2)
    finally:
        shards.clear_faults()


# ---------------------------------------------------------------------------
# acceptance e2e: multi-process spawn
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def spawned_shards(points, tmp_path_factory):
    """Three REAL ``kdtree-tpu serve`` processes over a contiguous
    3-way partition, global ids via --id-offset."""
    tmp = tmp_path_factory.mktemp("route-shards")
    procs, logs, urls = [], [], []
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    for i in range(N_SHARDS):
        shard_file = tmp / f"shard{i}.npy"
        np.save(shard_file, points[i * SHARD_N:(i + 1) * SHARD_N])
        log = open(tmp / f"serve{i}.log", "w")
        proc = subprocess.Popen(
            [sys.executable, "-m", "kdtree_tpu", "--platform", "cpu",
             "serve", "--points", str(shard_file), "--port", "0",
             "--k", str(K), "--max-batch", "8", "--debug-faults",
             "--id-offset", str(i * SHARD_N)],
            cwd=REPO, env=env, stderr=log,
            stdout=subprocess.DEVNULL,
        )
        procs.append(proc)
        logs.append(tmp / f"serve{i}.log")
    try:
        deadline = time.monotonic() + 180
        for i in range(N_SHARDS):
            port = None
            while time.monotonic() < deadline:
                if procs[i].poll() is not None:
                    raise RuntimeError(
                        f"shard {i} died: {logs[i].read_text()[-2000:]}"
                    )
                for line in logs[i].read_text().splitlines():
                    if line.startswith("ready:"):
                        port = int(line.rsplit("port", 1)[1].strip())
                        break
                if port is not None:
                    break
                time.sleep(0.2)
            assert port is not None, f"shard {i} never became ready"
            urls.append(f"http://127.0.0.1:{port}")
        yield urls
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                assert proc.wait(timeout=60) == 0  # graceful drain, exit 0
            except subprocess.TimeoutExpired:
                proc.kill()
                raise


def test_multiprocess_routed_byte_identical_to_oracle(
    spawned_shards, oracle_tree,
):
    """THE acceptance e2e: three real serve processes, routed answers
    byte-identical (ids and distances) to the single-index oracle."""
    router = rt.make_router(
        spawned_shards, config=rt.RouterConfig(deadline_s=60.0)
    )
    router.start(health_loop=True)
    try:
        for rows, k, seed in ((5, K, 21), (7, 2, 22)):
            q = _queries(rows, seed=seed)
            status, out = _post(router, {"queries": q.tolist(), "k": k},
                                timeout=120.0)
            assert status == 200
            dist, ids = _oracle(oracle_tree, q, k)
            assert out["ids"] == ids
            assert out["distances"] == dist
            assert out["degraded"] is None
    finally:
        router.stop()


def test_multiprocess_fault_injection_over_http(spawned_shards):
    """The drill an operator would run: arm a hang fault on one REAL
    shard process via POST /debug/faults, watch the routed answer go
    partial inside the deadline, clear the fault, watch it recover."""
    def arm(url, spec):
        req = urllib.request.Request(
            f"{url}/debug/faults",
            data=json.dumps(spec).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())

    router = rt.make_router(
        spawned_shards,
        config=rt.RouterConfig(deadline_s=1.5, retries=0, hedge_min_s=0.05,
                               breaker_failures=2, breaker_reset_s=0.3),
    )
    router.start(health_loop=False)
    try:
        armed = arm(spawned_shards[2], {"spec": "knn=hang"})
        assert armed["active"][0]["kind"] == "hang"
        status, out = _post(router, {"queries": _queries(3).tolist()},
                            timeout=30.0)
        assert status == 200
        assert out["degraded"] == f"partial:2/{N_SHARDS}"
        armed = arm(spawned_shards[2], {"clear": True})
        assert armed["active"] == []
        status, out = _post(router, {"queries": _queries(3).tolist()},
                            timeout=30.0)
        assert status == 200 and out["degraded"] is None
    finally:
        router.stop()


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------


def test_router_config_validation():
    with pytest.raises(ValueError):
        rt.RouterConfig(quorum=5).resolve_quorum(3)
    with pytest.raises(ValueError):
        rt.RouterConfig(quorum=0).resolve_quorum(3)
    assert rt.RouterConfig().resolve_quorum(3) == 2
    assert rt.RouterConfig(quorum=3).resolve_quorum(3) == 3
    with pytest.raises(ValueError):
        rt.make_router([])
    with pytest.raises(ValueError):
        rt.ShardState(0, "ftp://x", rt.CircuitBreaker())


def test_route_cli_needs_shards(capsys):
    from kdtree_tpu.utils import cli

    with pytest.raises(SystemExit) as e:
        cli.main(["route"])
    assert e.value.code == 1
    assert "--shard" in capsys.readouterr().err


def test_route_cli_no_slo_disables_router_slo_engine(monkeypatch):
    """--no-slo must wire slo_engine=None (the bench harness depends on
    it: a PAGE is sticky for the burn window, so one over-the-knee
    ladder step would leave an upstream parent ejecting this router
    through every later step); the default stays the router SLO ladder."""
    from kdtree_tpu.utils import cli

    captured = {}

    def fake_make_router(urls, **kw):
        captured.update(kw)
        raise ValueError("captured — stop before binding a port")

    monkeypatch.setattr(rt, "make_router", fake_make_router)
    with pytest.raises(SystemExit) as e:
        cli.main(["route", "--shard", "http://127.0.0.1:1", "--no-slo"])
    assert e.value.code == 1
    assert captured["slo_engine"] is None

    captured.clear()
    with pytest.raises(SystemExit):
        cli.main(["route", "--shard", "http://127.0.0.1:1"])
    assert captured["slo_engine"] is not None


# ---------------------------------------------------------------------------
# replica sets (docs/SERVING.md "Snapshots & replica fleets")
# ---------------------------------------------------------------------------


class Replicas:
    """One shard's replica set: N in-process servers over the SAME
    partition (replica 0 is the primary; the rest are read-only)."""

    def __init__(self, points, n_replicas=3):
        self.servers = []
        self.faults = []
        self.urls = []
        for j in range(n_replicas):
            state = lifecycle.build_state(
                points=points, k=K, max_batch=64,
                read_only=j > 0,
            )
            fset = faults_mod.FaultSet()
            httpd = srv.make_server(state, port=0, faults=fset)
            httpd.start(warmup_buckets=[8])
            self.servers.append(httpd)
            self.faults.append(fset)
            self.urls.append(
                f"http://127.0.0.1:{httpd.server_address[1]}")

    @property
    def entry(self):
        return "|".join(self.urls)

    def stop(self):
        for f in self.faults:
            f.clear()
        for httpd in self.servers:
            httpd.stop()


@pytest.fixture(scope="module")
def replica_points(points):
    return points[:SHARD_N]


@pytest.fixture(scope="module")
def replicas(replica_points):
    reps = Replicas(replica_points)
    yield reps
    reps.stop()


@pytest.fixture(scope="module")
def replica_oracle(replica_points):
    import jax.numpy as jnp

    from kdtree_tpu.ops.morton import build_morton

    return build_morton(jnp.asarray(replica_points))


@contextlib.contextmanager
def replica_router(reps, health_loop=False, **cfg):
    defaults = dict(deadline_s=30.0, retries=2, backoff_base_s=0.01,
                    hedge_min_s=5.0, breaker_failures=2,
                    breaker_reset_s=0.3, health_period_s=0.2)
    defaults.update(cfg)
    router = rt.make_router([reps.entry],
                            config=rt.RouterConfig(**defaults))
    router.start(health_loop=health_loop)
    try:
        yield router
    finally:
        router.stop()


def test_replica_reads_spread_and_byte_identical(
    replicas, replica_oracle,
):
    """ONE shard set, three replicas: every routed answer is the
    single-index oracle's (exactness dedupe is by shard ownership — a
    replica set can never duplicate a point), reads round-robin over
    all three replicas, and the shard-count gauge counts SETS."""
    qs = _queries(6, seed=31)
    od, oi = _oracle(replica_oracle, qs, K)
    with replica_router(replicas) as router:
        for _ in range(9):
            status, out = _post(router, {"queries": qs.tolist(), "k": K})
            assert status == 200
            assert out["degraded"] is None
            assert out["ids"] == oi and out["distances"] == od
            assert out["shards"]["total"] == 1
        gauges = obs.get_registry().snapshot()["gauges"]
        assert gauges["kdtree_router_shards"] == 1
        assert gauges['kdtree_router_replicas{shard="0"}'] == 3
        for j in range(3):
            assert _counter(
                "kdtree_router_replica_requests_total"
                f'{{replica="{j}",shard="0"}}') > 0, j
        status, report = _get(router, "/debug/shards")
        assert status == 200
        (entry,) = report["shards"]
        assert len(entry["replicas"]) == 3
        assert entry["routable"] is True


def test_replica_failure_fails_over_exact_not_partial(
    replicas, replica_oracle,
):
    """One replica erroring is invisible to the caller: the retry
    re-picks a sibling, the set still answers, and the result is the
    FULL exact answer (not a partial) — losing a replica loses
    capacity, never answer quality."""
    qs = _queries(4, seed=32)
    od, oi = _oracle(replica_oracle, qs, K)
    replicas.faults[1].set_spec("knn=error:500*100")
    try:
        with replica_router(replicas) as router:
            for _ in range(8):
                status, out = _post(router,
                                    {"queries": qs.tolist(), "k": K})
                assert status == 200
                assert out["degraded"] is None
                assert out["ids"] == oi and out["distances"] == od
    finally:
        replicas.faults[1].clear()


def test_replica_all_down_breaker_open_crisp_503(replicas):
    """Every replica refusing = the SET is down: below quorum, crisp
    503 naming the shard — never a silent wrong answer."""
    for f in replicas.faults:
        f.set_spec("knn=error:500*100")
    try:
        with replica_router(replicas, retries=1) as router:
            status = None
            for _ in range(6):
                status, out = _post(router, {
                    "queries": _queries(2).tolist(), "k": K})
                if status == 503:
                    break
            assert status == 503
    finally:
        for f in replicas.faults:
            f.clear()
        # let the breakers close again for the module's other tests
        time.sleep(0.4)


def test_replica_write_goes_to_primary_only(replicas):
    """Writes partition to the shard PRIMARY (replica 0): secondaries
    are read-only (403 writes), so a write routed anywhere else would
    fail this request. The health loop must first learn the set's
    id_offset from any replica."""
    with replica_router(replicas, health_loop=True) as router:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and \
                router._owner_table() is None:
            time.sleep(0.05)
        assert router._owner_table() is not None
        url = (f"http://127.0.0.1:{router.server_address[1]}"
               "/v1/upsert")
        wid = SHARD_N + 777
        req = urllib.request.Request(
            url,
            data=json.dumps({"ids": [wid],
                             "points": [[0.5] * DIM]}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            out = json.loads(resp.read())
        assert out["applied"] == 1
        # applied on the primary's engine, nowhere else
        deltas = [s.state.engine.stats()["delta_rows"]
                  for s in replicas.servers]
        assert deltas[0] >= 1 and deltas[1:] == [0, 0]


def test_replica_entry_validation():
    with pytest.raises(ValueError, match="empty replica"):
        rt.Router(("127.0.0.1", 0),
                  ["http://127.0.0.1:1|"])
    with pytest.raises(ValueError, match="http"):
        rt.Router(("127.0.0.1", 0),
                  ["http://127.0.0.1:1|ftp://x"])


def test_cross_replica_hedge_win_fails_over_wedged_replica(
    replicas, replica_oracle,
):
    """Breaker accounting lands on the replica that ANSWERED: a picked
    replica whose sibling had to rescue the request via the hedge gets
    a failure mark, so a wedged process opens its breaker instead of
    absorbing ~1/R of the reads at full hedge cost forever."""
    qs = _queries(2, seed=33)
    od, oi = _oracle(replica_oracle, qs, K)
    # replica 1 answers, but only after 1.5s — far past the 50ms hedge
    # floor, so every pick of it is rescued by a sibling
    replicas.faults[1].set_spec("knn=latency:1500*100")
    try:
        with replica_router(replicas, hedge_min_s=0.05, retries=0,
                            breaker_failures=1) as router:
            opened = False
            for _ in range(8):
                status, out = _post(router,
                                    {"queries": qs.tolist(), "k": K})
                assert status == 200
                assert out["ids"] == oi and out["distances"] == od
                _, report = _get(router, "/debug/shards")
                states = [r["breaker"]
                          for r in report["shards"][0]["replicas"]]
                if states[1] == "open":
                    opened = True
                    break
            assert opened, "wedged replica's breaker never opened"
    finally:
        replicas.faults[1].clear()
        time.sleep(0.4)  # let the breaker cooldown pass for later tests


# ---------------------------------------------------------------------------
# spatial sharding + selective fan-out (ISSUE 15,
# docs/SERVING.md "Spatial sharding & selective fan-out")
# ---------------------------------------------------------------------------


SP_SHARDS = 4
SP_CENTERS = np.array(
    [[-60.0, -60.0, -60.0], [60.0, 60.0, 60.0],
     [-60.0, 60.0, 0.0], [60.0, -60.0, 0.0]], dtype=np.float32,
)


class SpatialFleet:
    """A 4-shard spatially-partitioned in-process fleet over a
    clustered cloud: each shard serves a Morton-range partition with
    GLOBAL morton-rank gids (id_offset 0) and publishes its box +
    region on /healthz — exactly what ``kdtree-tpu partition`` + serve
    produce, minus the disk round-trip. Tracks the live cloud
    host-side so any moment's single-index oracle is reconstructible
    byte-for-byte."""

    def __init__(self, max_delta_rows=8):
        from kdtree_tpu.serve import spatial as sp

        rng = np.random.default_rng(17)
        pts = np.concatenate([
            c + rng.normal(0.0, 3.0, (400, 3)) for c in SP_CENTERS
        ]).astype(np.float32)
        self.plan = sp.plan_partition(pts, SP_SHARDS)
        order = self.plan["order"]
        # the live cloud, keyed by GLOBAL id (= morton rank at build)
        self.cloud = {int(i): pts[order[i]].copy()
                      for i in range(pts.shape[0])}
        self.n0 = pts.shape[0]
        self.servers = []
        self.urls = []
        import jax.numpy as jnp

        from kdtree_tpu.ops.morton import morton_view

        for i, ((s, e), (c0, c1)) in enumerate(
            zip(self.plan["bounds"], self.plan["code_ranges"])
        ):
            tree = morton_view(
                jnp.asarray(pts[order[s:e]]),
                gid=jnp.asarray(np.arange(s, e, dtype=np.int32)),
                n_real=int(e - s),
            )
            state = lifecycle.build_state(
                tree=tree, k=K, max_batch=64,
                max_delta_rows=max_delta_rows,
                meta={"spatial": {
                    "grid": self.plan["grid"].to_json(),
                    "code_range": [int(c0), int(c1)],
                    "id_range": [int(s), int(e)],
                    "shard": i, "shards": SP_SHARDS,
                }},
            )
            httpd = srv.make_server(state, port=0)
            httpd.start(warmup_buckets=[8])
            self.servers.append(httpd)
            self.urls.append(
                f"http://127.0.0.1:{httpd.server_address[1]}")

    def oracle(self, queries, k):
        """Single-index oracle over the CURRENT live cloud (original
        global ids preserved) — the byte-identity reference."""
        import jax.numpy as jnp

        from kdtree_tpu.ops.morton import morton_view
        from kdtree_tpu.ops.tile_query import morton_knn_tiled

        ids = sorted(self.cloud)
        pts = np.stack([self.cloud[i] for i in ids])
        tree = morton_view(
            jnp.asarray(pts),
            gid=jnp.asarray(np.asarray(ids, dtype=np.int32)),
            n_real=len(ids),
        )
        kk = min(k, len(ids))
        d2, gids = morton_knn_tiled(tree, jnp.asarray(queries), k=kk)
        return (
            np.sqrt(np.asarray(d2).astype(np.float64)).tolist(),
            np.asarray(gids).tolist(),
        )

    def stop(self):
        for httpd in self.servers:
            httpd.stop()


@pytest.fixture(scope="module")
def spatial_fleet():
    fleet = SpatialFleet()
    yield fleet
    fleet.stop()


@contextlib.contextmanager
def spatial_router(fleet, fanout="selective", health_loop=True, **cfg):
    defaults = dict(deadline_s=30.0, retries=1, backoff_base_s=0.01,
                    health_period_s=0.1, fanout=fanout)
    defaults.update(cfg)
    router = rt.make_router(fleet.urls,
                            config=rt.RouterConfig(**defaults))
    router.start(health_loop=health_loop)
    try:
        if health_loop:
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                if all(ss.box() is not None
                       and ss.code_range_known() is not None
                       for ss in router.shard_sets):
                    break
                time.sleep(0.05)
            else:
                raise AssertionError("fleet topology never learned")
        yield router
    finally:
        router.stop()


def _near(center, seed, rows=1, spread=2.0):
    rng = np.random.default_rng(seed)
    return (center + rng.normal(0.0, spread, (rows, 3))).astype(
        np.float32)


def test_spatial_selective_byte_identical_and_prunes(spatial_fleet):
    """The tentpole pin: on a spatially-partitioned 4-shard fleet,
    selective answers are byte-identical (distances AND ids) to the
    single-index oracle AND to a full-fan-out router, while contacting
    fewer shards for clustered queries — with the pruning visible on
    the metrics."""
    pruned_before = _counter("kdtree_router_shards_pruned_total")
    contacts = []
    # spec_wave off for deterministic contact accounting: speculation
    # widens early whenever a wave-1 shard is transiently slow
    with spatial_router(spatial_fleet, spec_wave=False) as sel_router, \
            spatial_router(spatial_fleet, fanout="full") as full_router:
        for si, center in enumerate(SP_CENTERS):
            q = _near(center, seed=40 + si)
            payload = {"queries": q.tolist(), "k": K}
            status, out = _post(sel_router, payload)
            assert status == 200 and out["degraded"] is None
            dist, ids = spatial_fleet.oracle(q, K)
            assert out["ids"] == ids
            assert out["distances"] == dist
            status_f, out_f = _post(full_router, payload)
            assert status_f == 200
            assert out_f["ids"] == ids and out_f["distances"] == dist
            # full mode contacts everything; selective prunes
            assert out_f["shards"]["contacted"] == SP_SHARDS
            assert out_f["shards"]["pruned"] == 0
            contacts.append(out["shards"]["contacted"])
            assert out["shards"]["contacted"] + \
                out["shards"]["pruned"] == SP_SHARDS
    # the acceptance selectivity bar: mean contacted <= 50% of shards
    # on the clustered smoke shape
    assert np.mean(contacts) <= 0.5 * SP_SHARDS, contacts
    assert _counter("kdtree_router_shards_pruned_total") > pruned_before


def test_spatial_batch_spanning_clusters_stays_exact(spatial_fleet):
    rng = np.random.default_rng(77)
    q = np.concatenate([
        _near(SP_CENTERS[0], 50, rows=2),
        _near(SP_CENTERS[1], 51, rows=2),
        (rng.random((2, 3)) * 300.0 - 150.0).astype(np.float32),
    ])
    with spatial_router(spatial_fleet) as router:
        status, out = _post(router, {"queries": q.tolist(), "k": K})
    assert status == 200
    dist, ids = spatial_fleet.oracle(q, K)
    assert out["ids"] == ids and out["distances"] == dist


def test_spatial_heterogeneous_legacy_shard_never_pruned(spatial_fleet):
    """A fleet mixing box-publishing and legacy (no-box) shards must
    degrade to full fan-out for the legacy ones — they are ALWAYS
    contacted, never silently pruned."""
    legacy = 2
    # spec_wave off and hedging pinned far out: both deliberately trade
    # extra contacts for latency when a wave-1 shard is transiently
    # slow (each pinned by its own tests), which would make this
    # test's per-shard dispatch accounting timing-dependent
    with spatial_router(spatial_fleet, health_loop=False,
                        spec_wave=False, hedge_min_s=30.0) as router:
        for shard in router.shards:
            router._probe_health(shard)
        # strip one set's spatial evidence: a legacy serve build that
        # never published a box looks exactly like this
        for rep in router.shard_sets[legacy].replicas:
            rep.box = None
        router.shard_sets[legacy]._box_ext = None
        attempts_key = ('kdtree_router_replica_requests_total'
                        '{replica="0",shard="%d"}')
        before = {i: _counter(attempts_key % i)
                  for i in range(SP_SHARDS)}
        n_req = 0
        for si, center in enumerate(SP_CENTERS):
            q = _near(center, seed=60 + si)
            status, out = _post(router, {"queries": q.tolist(), "k": K})
            assert status == 200
            dist, ids = spatial_fleet.oracle(q, K)
            assert out["ids"] == ids and out["distances"] == dist
            n_req += 1
        after = {i: _counter(attempts_key % i)
                 for i in range(SP_SHARDS)}
        # the legacy shard was contacted by EVERY request...
        assert after[legacy] - before[legacy] == n_req
        # ...while boxed shards still got pruned when provably useless
        assert sum(after[i] - before[i]
                   for i in range(SP_SHARDS)) < n_req * SP_SHARDS


def test_spatial_write_routing_upsert_move_delete(spatial_fleet):
    """Spatial write routing: a fresh upsert lands ONLY on the shard
    whose region contains the point; a moved id dies on its old shard
    (stale-copy delete broadcast); deletes broadcast-resolve by id.
    Answers stay byte-identical to the oracle at every step."""
    from kdtree_tpu.serve import spatial as sp

    fleet = spatial_fleet
    with spatial_router(fleet) as router:
        # fresh insert near cluster 1
        new_id = fleet.n0 + 1000
        p_new = (SP_CENTERS[1] + np.float32(1.5)).astype(np.float32)
        owner = int(sp.owner_of(p_new.reshape(1, 3), fleet.plan["grid"],
                                fleet.plan["code_ranges"])[0])
        status, out = _post_path(router, "/v1/upsert", {
            "ids": [new_id], "points": [p_new.tolist()]})
        assert status == 200 and out["applied"] == 1
        assert out["routing"] == "spatial"
        assert out["shards"][str(owner)]["applied"] == 1
        # the stale-copy broadcast rode along, applying nothing
        for i in range(SP_SHARDS):
            if i != owner:
                assert out["shards"][f"{i}:delete"]["applied"] == 0
        fleet.cloud[new_id] = p_new
        q = p_new.reshape(1, 3)
        status, out = _post(router, {"queries": q.tolist(), "k": K})
        dist, ids = fleet.oracle(q, K)
        assert out["ids"] == ids and out["distances"] == dist
        assert out["ids"][0][0] == new_id
        # MOVE an existing id from cluster 0's region into cluster 1's:
        # the upsert routes to the NEW owner, the old copy dies by the
        # stale-copy delete on its old shard
        moved = 0  # morton rank 0 lives in some region; move it far
        p_moved = (SP_CENTERS[1] - np.float32(1.5)).astype(np.float32)
        status, out = _post_path(router, "/v1/upsert", {
            "ids": [moved], "points": [p_moved.tolist()]})
        assert status == 200 and out["applied"] == 1
        old_pos = fleet.cloud[moved]
        fleet.cloud[moved] = p_moved
        for q in (p_moved.reshape(1, 3), old_pos.reshape(1, 3),
                  _near(SP_CENTERS[0], 70)):
            status, out = _post(router, {"queries": q.tolist(), "k": K})
            assert status == 200
            dist, ids = fleet.oracle(q, K)
            assert out["ids"] == ids and out["distances"] == dist
        # DELETE broadcast-resolves by id
        status, out = _post_path(router, "/v1/delete",
                                 {"ids": [new_id, moved]})
        assert status == 200 and out["routing"] == "spatial"
        assert out["applied"] == 2
        del fleet.cloud[new_id]
        del fleet.cloud[moved]
        q = p_new.reshape(1, 3)
        status, out = _post(router, {"queries": q.tolist(), "k": K})
        dist, ids = fleet.oracle(q, K)
        assert out["ids"] == ids and out["distances"] == dist
        assert new_id not in out["ids"][0]


def test_spatial_exact_across_epoch_swap_with_live_writes(spatial_fleet):
    """The acceptance's hardest pin: byte-identity to the oracle holds
    across an epoch swap triggered by live routed upserts (the shard's
    box is recomputed at the swap; the delta-expanded and router-side
    boxes cover the window before it)."""
    from kdtree_tpu.serve import spatial as sp

    fleet = spatial_fleet
    # 12 candidate points that provably share ONE owning region (the
    # Z-curve can split even close neighbors across shard cuts, so pick
    # by computed ownership instead of proximity)
    rng = np.random.default_rng(93)
    cands = (SP_CENTERS[3] + rng.normal(0.0, 1.0, (64, 3))).astype(
        np.float32)
    owners = sp.owner_of(cands, fleet.plan["grid"],
                         fleet.plan["code_ranges"])
    owner = int(np.bincount(owners).argmax())
    cands = cands[owners == owner][:12]
    assert cands.shape[0] == 12
    with spatial_router(fleet) as router:
        base = fleet.n0 + 2000
        epochs_before = [
            json.loads(urllib.request.urlopen(
                u + "/healthz", timeout=10).read())["epoch"]
            for u in fleet.urls
        ]
        for j in range(12):  # > max_delta_rows=8 on the owning shard
            p = cands[j]
            status, out = _post_path(router, "/v1/upsert", {
                "ids": [base + j], "points": [p.tolist()]})
            assert status == 200, out
            fleet.cloud[base + j] = p
            q = p.reshape(1, 3)
            status, out = _post(router, {"queries": q.tolist(),
                                         "k": K})
            assert status == 200
            dist, ids = fleet.oracle(q, K)
            assert out["ids"] == ids and out["distances"] == dist
        # some shard compacted: its epoch moved past the bootstrap one
        deadline = time.monotonic() + 30.0
        swapped = False
        while time.monotonic() < deadline and not swapped:
            epochs = [
                json.loads(urllib.request.urlopen(
                    u + "/healthz", timeout=10).read())["epoch"]
                for u in fleet.urls
            ]
            swapped = any(e > b for e, b in zip(epochs, epochs_before))
            if not swapped:
                time.sleep(0.1)
        assert swapped, "no epoch swap despite 12 routed upserts"
        # post-swap: still byte-identical, still selective
        for si, c in enumerate(SP_CENTERS):
            q = _near(c, seed=90 + si)
            status, out = _post(router, {"queries": q.tolist(), "k": K})
            assert status == 200
            dist, ids = fleet.oracle(q, K)
            assert out["ids"] == ids and out["distances"] == dist


def test_spatial_recall_target_stops_widening_with_gear(spatial_fleet):
    """A recall_target lets the router stop widening once the
    guaranteed-query fraction reaches the target: fewer contacts than
    exact mode, the spatial truncation echoed in the gear token, and
    absent target = exact (no gear) — the PR 14 contract spatially."""
    fleet = spatial_fleet
    # 3 queries deep inside cluster 2 + 1 in the dead middle: the
    # middle query is the one whose exactness needs extra shards
    q = np.concatenate([
        _near(SP_CENTERS[2], 80, rows=3, spread=1.0),
        np.zeros((1, 3), dtype=np.float32),
    ])
    with spatial_router(fleet) as router:
        status, exact_out = _post(router, {"queries": q.tolist(),
                                           "k": K})
        assert status == 200 and "gear" not in exact_out
        dist, ids = fleet.oracle(q, K)
        assert exact_out["ids"] == ids
        status, approx_out = _post(router, {
            "queries": q.tolist(), "k": K, "recall_target": 0.7})
        assert status == 200
        m_exact = exact_out["shards"]["contacted"]
        m_approx = approx_out["shards"]["contacted"]
        assert m_approx <= m_exact
        if m_approx < m_exact:
            # widening actually stopped early: the response must say so
            assert approx_out["gear"] == "approx:0.7"
            # the 3 guaranteed queries' rows are still the exact rows
            for row in range(3):
                assert approx_out["ids"][row] == ids[row]


def test_router_config_fanout_validation():
    with pytest.raises(ValueError, match="fanout"):
        rt.RouterConfig(fanout="nope")
    assert rt.RouterConfig(fanout="full").fanout == "full"
    assert rt.RouterConfig().fanout == "selective"


def test_spatial_gear_combination():
    assert rt.Router._spatial_gear(None, None) is None
    assert rt.Router._spatial_gear(None, 0.8) == "approx:0.8"
    assert rt.Router._spatial_gear("approx:0.5", 0.8) == "approx:0.5"
    assert rt.Router._spatial_gear("approx:0.9", 0.8) == "approx:0.8"
    assert rt.Router._spatial_gear("brute-deadline", 0.8) == "approx:0.8"


def test_spatial_write_owner_correct_under_shuffled_shard_order(
        spatial_fleet):
    """Review-pass pin: the operator's --shard flag order is arbitrary,
    but owner_of's searchsorted needs ascending code-range lows — the
    router must sort and map back, or a shuffled fleet mints wrong
    owners (and the stale-delete broadcast would delete the id from
    its REAL owner while applying it nowhere)."""
    from kdtree_tpu.serve import spatial as sp

    fleet = spatial_fleet
    shuffled = list(reversed(fleet.urls))
    router = rt.make_router(shuffled, config=rt.RouterConfig(
        deadline_s=30.0, health_period_s=0.1))
    router.start(health_loop=True)
    try:
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if all(ss.code_range_known() is not None
                   for ss in router.shard_sets):
                break
            time.sleep(0.05)
        new_id = fleet.n0 + 3000
        p = (SP_CENTERS[0] - np.float32(1.0)).astype(np.float32)
        plan_owner = int(sp.owner_of(p.reshape(1, 3),
                                     fleet.plan["grid"],
                                     fleet.plan["code_ranges"])[0])
        router_owner = len(fleet.urls) - 1 - plan_owner  # reversed
        status, out = _post_path(router, "/v1/upsert", {
            "ids": [new_id], "points": [p.tolist()]})
        assert status == 200 and out["applied"] == 1, out
        assert out["shards"][str(router_owner)]["applied"] == 1, out
        fleet.cloud[new_id] = p
        status, out = _post(router, {"queries": [p.tolist()], "k": K})
        dist, ids = fleet.oracle(p.reshape(1, 3), K)
        assert out["ids"] == ids and out["distances"] == dist
        assert out["ids"][0][0] == new_id
        # restore the module fleet's state
        status, out = _post_path(router, "/v1/delete", {"ids": [new_id]})
        assert status == 200 and out["applied"] == 1
        del fleet.cloud[new_id]
    finally:
        router.stop()


def test_spatial_hung_wave1_shard_still_answers_partial_200(
        spatial_fleet):
    """Review-pass pin: wave 1 gets at most HALF the budget when a
    widening wave may follow — a hung nearest shard degrades the
    answer to a flagged partial over the others instead of burning the
    whole deadline and 503ing a request full fan-out would answer."""
    from kdtree_tpu.serve import spatial as sp

    fleet = spatial_fleet
    center = SP_CENTERS[0]
    owner = int(sp.owner_of(center.reshape(1, 3), fleet.plan["grid"],
                            fleet.plan["code_ranges"])[0])
    fleet.servers[owner].faults.set_spec("knn=hang")
    try:
        with spatial_router(fleet, deadline_s=3.0, retries=0) as router:
            status, out = _post(router, {
                "queries": [center.tolist()], "k": K})
            assert status == 200, out
            assert out["degraded"] == "partial:3/4", out
            assert out["shards"]["contacted"] == SP_SHARDS
            assert out["shards"]["missing"] == [owner]
    finally:
        fleet.servers[owner].faults.clear()
        time.sleep(0.1)


def test_idrange_routed_upsert_expands_cached_box(write_shards):
    """Review-pass pin: the box contract is mode-independent — an
    id-range routed upsert expands the owner set's cached box too, so
    selective reads racing the next health probe can never prune the
    shard that just acknowledged the write."""
    _, urls = write_shards
    with write_router(urls) as router:
        sset = router.shard_sets[1]
        far = np.asarray([500.0, 500.0, 500.0], np.float32)
        box0 = sset.box()  # probed: the shard's own data box
        assert box0 is not None and not bool((box0[1] >= far).all())
        status, out = _post_path(router, "/v1/upsert", {
            "ids": [1500], "points": [far.tolist()]})
        assert status == 200, out
        box = sset.box()
        assert (box[0] <= far + 1e-6).all()
        assert (box[1] >= far - 1e-6).all()
        # clean up the write so sibling tests see the fixture state
        _post_path(router, "/v1/delete", {"ids": [1500]})


# ---------------------------------------------------------------------------
# router scale-out: connection pooling, speculative wave 2, two levels
# (docs/SERVING.md "Scaling the router")
# ---------------------------------------------------------------------------


def _pool_discards(reason):
    return _counter(
        f'kdtree_router_pool_discards_total{{reason="{reason}"}}')


def test_pooled_connections_reused_byte_identical(shards, oracle_tree):
    """The pooling tentpole pin: back-to-back requests reuse keep-alive
    connections (hits counted, idle list populated) and the answers
    stay byte-identical to the single-index oracle — reuse is a
    transport optimization, never a semantics change."""
    hits0 = _counter("kdtree_router_pool_hits_total")
    q = _queries(4, seed=21)
    payload = {"queries": q.tolist(), "k": K}
    dist, ids = _oracle(oracle_tree, q, K)
    with router_for(shards) as router:
        assert router.pool is not None
        for _ in range(3):
            status, out = _post(router, payload)
            assert status == 200 and out["degraded"] is None
            assert out["ids"] == ids and out["distances"] == dist
        # requests 2 and 3 ran over request 1's connections (a cold
        # first request may hedge and lose a twin's connection, so the
        # bound is one full round of reuse, not two)
        assert _counter("kdtree_router_pool_hits_total") - hits0 >= \
            N_SHARDS
        assert router.pool.idle_count() >= 1
    hits_after = _counter("kdtree_router_pool_hits_total")
    # the --no-pool A/B arm: same answers, no pool, no new hits
    with router_for(shards, pool=False) as router:
        assert router.pool is None
        status, out = _post(router, payload)
        assert status == 200
        assert out["ids"] == ids and out["distances"] == dist
    assert _counter("kdtree_router_pool_hits_total") == hits_after


class _DelayShard:
    """A keep-alive stub whose i-th POST sleeps ``delays[i]`` before a
    fixed 200 body — hedging needs a SLOW first exchange, which the
    scripted-response stub cannot express."""

    def __init__(self, delays, body):
        import http.server

        stub = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", "0"))
                self.rfile.read(length)
                with stub.lock:
                    i = stub.count
                    stub.count += 1
                if i < len(stub.delays):
                    time.sleep(stub.delays[i])
                raw = json.dumps(stub.body).encode()
                try:
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(raw)))
                    self.end_headers()
                    self.wfile.write(raw)
                except (BrokenPipeError, ConnectionResetError, OSError):
                    # the hedge winner closed this socket mid-response:
                    # normal weather for the losing twin
                    self.close_connection = True

        self.delays = list(delays)
        self.body = body
        self.count = 0
        self.lock = threading.Lock()
        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                                     Handler)
        self.httpd.daemon_threads = True
        self.thread = threading.Thread(target=self.httpd.serve_forever)
        self.thread.start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def stop(self):
        self.httpd.shutdown()
        self.thread.join()
        self.httpd.server_close()


def test_hedge_loser_pooled_connection_discarded_never_released():
    """The pooling x hedging composition pin: the hedge winner closes
    the loser's POOLED connection; the loser's lease is discarded
    (reason=abort) and never returns to the idle list — only the
    winner's fully-drained connection is reusable afterwards."""
    ok_body = {"k": 1, "ids": [[3]], "distances": [[0.25]],
               "degraded": None, "trace_id": ""}
    aborts0 = _pool_discards("abort")
    stub = _DelayShard([0.8], ok_body)  # first POST slow, rest fast
    try:
        router = rt.make_router(
            [stub.url],
            config=rt.RouterConfig(deadline_s=10.0, retries=0, quorum=1,
                                   hedge_min_s=0.05),
        )
        router.start(health_loop=False)
        try:
            status, out = _post(router, {"queries": [[0.0] * DIM]})
            assert status == 200 and out["ids"] == [[3]]
            assert _counter('kdtree_router_hedges_total{shard="0"}') >= 1
            # the loser's connection: closed, counted, NOT pooled. The
            # losing thread may still be blocked in its read when the
            # winner returns — its discard lands when it unwinds.
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and \
                    _pool_discards("abort") - aborts0 < 1:
                time.sleep(0.05)
            assert _pool_discards("abort") - aborts0 >= 1
            assert router.pool.idle_count() <= 1
            # and the winner's connection IS reusable
            hits0 = _counter("kdtree_router_pool_hits_total")
            status, out = _post(router, {"queries": [[0.0] * DIM]})
            assert status == 200 and out["ids"] == [[3]]
            assert _counter("kdtree_router_pool_hits_total") - hits0 >= 1
        finally:
            router.stop()
    finally:
        stub.stop()


def test_stale_pooled_connection_retried_crisply(shards, oracle_tree):
    """The keep-alive shard-restart pin: a pooled connection whose
    server side went away (restart, idle reaper) fails the next reuse
    CRISPLY and the router transparently retries that one attempt on a
    fresh connection — with retries=0, so an un-transparent failure
    would surface as a 503, never a hang or a wrong answer."""
    q = _queries(2, seed=22)
    payload = {"queries": q.tolist(), "k": K}
    dist, ids = _oracle(oracle_tree, q, K)
    retries0 = _counter('kdtree_router_retries_total{shard="0"}')
    with router_for(shards, retries=0) as router:
        status, out = _post(router, payload)
        assert status == 200
        # simulate every shard restarting: close the pooled sockets
        # server-side-style (the pool cannot know — no abort mark, the
        # entries still look fresh) so the next lease reuses them and
        # hits the dead socket
        with router.pool._lock:
            stale = [pc for b in router.pool._idle.values() for pc in b]
        assert len(stale) >= N_SHARDS
        for pc in stale:
            pc.conn.sock.close()
        stale0 = _pool_discards("stale")
        status, out = _post(router, payload)
        assert status == 200 and out["degraded"] is None
        assert out["ids"] == ids and out["distances"] == dist
        assert _pool_discards("stale") - stale0 >= N_SHARDS
    # the transparent retry is NOT a counted (backed-off) retry
    assert _counter('kdtree_router_retries_total{shard="0"}') == retries0


def test_optimistic_worst_proves_only_certain_shards():
    """Unit pin for the speculative early trigger: the optimistic bound
    assumes every pending wave-1 shard delivers k candidates AT its
    box lower bound, so a remaining shard it still fails to prune is
    needed under ANY actual answer — and one it prunes is not proven
    either way."""
    import types

    host = types.SimpleNamespace(
        _running_worst=rt.Router._running_worst)
    nq, k = 1, 2
    answered = [{"k": 2, "distances": [[1.0, 2.0]], "ids": [[5, 6]]}]
    # pending wave-1 shard with lb 3.0: optimistically contributes
    # k candidates at 3.0 -> optimistic worst = 2.0 (from answered)
    worst, short = rt.Router._optimistic_worst(
        host, answered, [np.asarray([3.0])], nq, k)
    assert worst.tolist() == [2.0] and not short[0]
    # no answers yet and no k: nothing is provable
    worst, short = rt.Router._optimistic_worst(host, [], [None], nq, None)
    assert worst.tolist() == [0.0] and not short[0]
    # pending shard closer than the answered candidates caps the bound
    # (k assumed candidates at lb 0.5 dominate the answered pair)
    worst, _ = rt.Router._optimistic_worst(
        host, answered, [np.asarray([0.5])], nq, k)
    assert worst.tolist() == [0.5]


def test_spec_wave_overlaps_slow_wave1_and_stays_exact(spatial_fleet):
    """The speculative wave-2 tentpole pin: when the wave-1 owner is
    slow, the router fires the conservative widening wave at the
    p95-derived delay instead of waiting — the request still merges
    every answer byte-identically, the extra contacts are visible, and
    the losing bets are counted as wasted."""
    from kdtree_tpu.serve import spatial as sp

    fleet = spatial_fleet
    center = SP_CENTERS[0]
    owner = int(sp.owner_of(center.reshape(1, 3), fleet.plan["grid"],
                            fleet.plan["code_ranges"])[0])
    q = _near(center, seed=60)
    spec0 = (_counter('kdtree_router_spec_wave_total{outcome="needed"}')
             + _counter('kdtree_router_spec_wave_total{outcome="wasted"}'))
    fleet.servers[owner].faults.set_spec("knn=latency:500")
    try:
        with spatial_router(fleet, retries=0) as router:
            t0 = time.monotonic()
            status, out = _post(router, {"queries": q.tolist(), "k": K})
            elapsed = time.monotonic() - t0
    finally:
        fleet.servers[owner].faults.clear()
        time.sleep(0.1)
    assert status == 200 and out["degraded"] is None
    dist, ids = fleet.oracle(q, K)
    assert out["ids"] == ids and out["distances"] == dist
    # the hedge-style bet fanned out rather than waiting serially
    assert out["shards"]["contacted"] == SP_SHARDS
    spec1 = (_counter('kdtree_router_spec_wave_total{outcome="needed"}')
             + _counter('kdtree_router_spec_wave_total{outcome="wasted"}'))
    assert spec1 - spec0 >= SP_SHARDS - 1
    # the slow owner bounded the request, not spec-delay + owner
    assert elapsed < 2.0, elapsed


def test_spec_wave_off_keeps_serial_pruning(spatial_fleet):
    """The --no-spec-wave A/B arm: the same slow-owner scenario widens
    only on the full wave-1 evidence — no speculative contacts, fewer
    shards touched, same exact answer."""
    from kdtree_tpu.serve import spatial as sp

    fleet = spatial_fleet
    center = SP_CENTERS[0]
    owner = int(sp.owner_of(center.reshape(1, 3), fleet.plan["grid"],
                            fleet.plan["code_ranges"])[0])
    q = _near(center, seed=61)
    spec0 = (_counter('kdtree_router_spec_wave_total{outcome="needed"}')
             + _counter('kdtree_router_spec_wave_total{outcome="wasted"}'))
    fleet.servers[owner].faults.set_spec("knn=latency:300")
    try:
        with spatial_router(fleet, retries=0, spec_wave=False) as router:
            status, out = _post(router, {"queries": q.tolist(), "k": K})
    finally:
        fleet.servers[owner].faults.clear()
        time.sleep(0.1)
    assert status == 200
    dist, ids = fleet.oracle(q, K)
    assert out["ids"] == ids and out["distances"] == dist
    assert out["shards"]["contacted"] < SP_SHARDS
    assert (_counter('kdtree_router_spec_wave_total{outcome="needed"}')
            + _counter('kdtree_router_spec_wave_total{outcome="wasted"}')
            ) == spec0


@contextlib.contextmanager
def _two_level(fleet, **parent_cfg):
    """Two child routers over half the spatial fleet each, one parent
    over the children — the ``route --parent`` topology in-process."""
    with spatial_router(fleet) as _probe:
        pass  # ensure the fleet is warm/probeable before splitting
    half = len(fleet.urls) // 2
    children = []
    try:
        for urls in (fleet.urls[:half], fleet.urls[half:]):
            child = rt.make_router(urls, config=rt.RouterConfig(
                deadline_s=30.0, retries=1, backoff_base_s=0.01,
                health_period_s=0.1))
            child.start(health_loop=True)
            children.append(child)
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if all(ss.box() is not None for c in children
                   for ss in c.shard_sets):
                break
            time.sleep(0.05)
        else:
            raise AssertionError("children never learned shard boxes")
        defaults = dict(deadline_s=30.0, retries=1, backoff_base_s=0.01,
                        health_period_s=0.1, parent=True)
        defaults.update(parent_cfg)
        child_urls = [
            f"http://127.0.0.1:{c.server_address[1]}" for c in children
        ]
        parent = rt.make_router(child_urls,
                                config=rt.RouterConfig(**defaults))
        parent.start(health_loop=True)
        try:
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                if all(ss.box() is not None
                       for ss in parent.shard_sets):
                    break
                time.sleep(0.05)
            else:
                raise AssertionError("parent never learned child boxes")
            yield parent, children
        finally:
            parent.stop()
    finally:
        for c in children:
            c.stop()


def test_two_level_routing_byte_identical_and_aggregates(spatial_fleet):
    """The hierarchical tentpole pin: a parent router over two child
    routers answers byte-identically to the single-index oracle AND to
    a flat router over all four shards — the exact (distance, id)
    merge is associative, so byte-identity survives the tree. Health,
    federation, and trace context aggregate through."""
    fleet = spatial_fleet
    qs = np.concatenate([
        _near(SP_CENTERS[0], 70, rows=2),
        _near(SP_CENTERS[3], 71, rows=2),
    ])
    payload = {"queries": qs.tolist(), "k": K}
    dist, ids = fleet.oracle(qs, K)
    with _two_level(fleet) as (parent, children):
        status, out = _post(parent, payload)
        assert status == 200 and out["degraded"] is None, out
        assert out["ids"] == ids and out["distances"] == dist
        assert out["shards"]["total"] == 2  # children, at this level
        with spatial_router(fleet, fanout="full") as flat:
            status_f, out_f = _post(flat, payload)
        assert status_f == 200
        assert out_f["ids"] == out["ids"]
        assert out_f["distances"] == out["distances"]
        # /healthz aggregates: the parent is as ready as its children
        status, health = _get(parent, "/healthz")
        assert status == 200 and health["status"] == "ok"
        assert health["total"] == 2 and health["available"] == 2
        # the parent publishes the fleet-union box for a grandparent
        assert "box" in health
        # federation recurses: one parent scrape carries child-labeled
        # series, including the children's own shard-labeled ones
        status, text = _get_text(parent, "/metrics?federate=1")
        assert status == 200
        assert 'kdtree_router_federated_up{child="0"} 1' in \
            text.splitlines()
        assert 'child="1"' in text
        # trace context composes: the child ADOPTS the parent's span
        # rather than minting its own root, so the child-side route
        # spans join the parent's trace id
        tid = "two-level-trace-1"
        status, out = _post(parent, payload,
                            headers={"X-Request-Id": tid})
        assert status == 200 and out["trace_id"] == tid
        from kdtree_tpu.obs import trace as trace_mod
        local = trace_mod.get_trace(tid)
        assert local is not None
        names = {s["name"] for s in local["spans"]}
        # both levels recorded under ONE trace id: the parent's root +
        # its route/shard bars and each child's own route/request span
        assert "route/request" in names and "route/shard" in names
        roots = [s for s in local["spans"]
                 if s["name"] == "route/request"]
        assert len(roots) >= 2  # parent's + adopted children's


def test_parent_router_refuses_writes_crisply(spatial_fleet):
    """Writes through a parent are a crisp 503 refusal — a child
    router publishes no ownership evidence, and guessing would
    half-apply the write across subtrees (docs/SERVING.md)."""
    with _two_level(spatial_fleet) as (parent, _children):
        status, out = _post_path(parent, "/v1/upsert", {
            "ids": [99999], "points": [[0.0, 0.0, 0.0]]})
        assert status == 503
        assert "parent" in out["error"]
        # and reads keep working after the refusal
        q = _near(SP_CENTERS[1], 72)
        status, out = _post(parent, {"queries": q.tolist(), "k": K})
        assert status == 200


# ---------------------------------------------------------------------------
# fleet capacity headroom (docs/OBSERVABILITY.md "Cost accounting")
# ---------------------------------------------------------------------------


def test_fleet_headroom_aggregation_and_ejection(shards):
    """The router sums routable shards' health-detail headroom blocks;
    an ejected shard contributes NOTHING, so losing capacity reads as a
    predicted-rate drop, never as phantom headroom."""
    with router_for(shards) as router:
        for shard in router.shards:
            router._probe_health(shard)
        # the real probe already carries each shard's headroom block
        for shard in router.shards:
            assert "headroom" in shard.health_detail
        hr = router.fleet_headroom()
        assert hr["shards_total"] == N_SHARDS
        # the aggregation itself is dict math over health_detail —
        # fabricate live blocks to pin the sums exactly
        for i, shard in enumerate(router.shards):
            shard.health_detail = {"headroom": {
                "data": True, "predicted_rate": 100.0 + i,
                "observed_rate": 10.0, "headroom_frac": 0.9,
            }}
        hr = router.fleet_headroom()
        assert hr["data"] is True
        assert hr["shards_reporting"] == N_SHARDS
        assert hr["predicted_rate"] == pytest.approx(303.0)
        assert hr["observed_rate"] == pytest.approx(30.0)
        assert hr["headroom_frac"] == pytest.approx(1.0 - 30.0 / 303.0)
        # ejection: shard 1 unhealthy -> its 101 req/s leave the fleet
        router.shards[1].healthy = False
        hr = router.fleet_headroom()
        assert hr["shards_reporting"] == N_SHARDS - 1
        assert hr["predicted_rate"] == pytest.approx(202.0)
        ent = hr["shards"][1]
        assert ent["routable"] is False and "headroom" not in ent
        router.shards[1].healthy = True
        # a malformed block reads as absent, never a crash
        router.shards[2].health_detail = {"headroom": {
            "data": True, "predicted_rate": "wat"}}
        hr = router.fleet_headroom()
        assert hr["shards_reporting"] == N_SHARDS - 1
        assert hr["predicted_rate"] == pytest.approx(201.0)
        # a data:false block counts as present-but-not-reporting
        router.shards[2].health_detail = {"headroom": {"data": False}}
        hr = router.fleet_headroom()
        assert hr["shards_reporting"] == N_SHARDS - 1
        # the router /healthz carries the fleet block
        status, body = _get(router, "/healthz")
        assert status == 200 and "headroom" in body
        assert body["headroom"]["shards_total"] == N_SHARDS


def test_router_debug_costs_fans_out(shards):
    """GET /debug/costs on the router returns every shard's ledger plus
    the fleet headroom aggregation; a dead shard is an error entry,
    never a failed fan-out."""
    with router_for(shards) as router:
        # drive one routed request so every shard has a knn class
        status, _ = _post(router, {"queries": _queries(2).tolist()})
        assert status == 200
        status, rep = _get(router, "/debug/costs")
        assert status == 200
        assert rep["headroom"]["shards_total"] == N_SHARDS
        with_ledgers = [e for e in rep["shards"] if "costs" in e]
        assert len(with_ledgers) == N_SHARDS
        for ent in with_ledgers:
            classes = ent["costs"]["classes"]
            assert any(c["verb"] == "knn" and c["requests"] >= 1
                       for c in classes), (ent["shard"], classes)
        # unreachable shard: error entry, the rest still answer
        router.shards[0].port = 1  # nothing listens there
        try:
            status, rep = _get(router, "/debug/costs")
            assert status == 200
            errs = [e for e in rep["shards"] if "error" in e]
            assert len(errs) == 1 and errs[0]["error"] == "unreachable"
            assert len([e for e in rep["shards"] if "costs" in e]) \
                == N_SHARDS - 1
        finally:
            router.shards[0].port = int(
                router.shards[0].url.rsplit(":", 1)[1])
