import jax.numpy as jnp
import numpy as np
import pytest

from kdtree_tpu import build_jit, generate_problem, tree_spec, validate_invariants
from kdtree_tpu.models.tree import node_levels


@pytest.mark.parametrize("n", [1, 2, 3, 7, 8, 100, 1000])
def test_spec_consumes_every_point(n):
    spec = tree_spec(n)
    pos = spec.all_medpos
    assert sorted(pos.tolist()) == list(range(n))
    assert len(set(spec.all_nodes.tolist())) == n
    assert spec.num_levels <= int(np.ceil(np.log2(n + 1))) + 1


def test_spec_matches_reference_split_arithmetic():
    """left = n/2, node = 1, right = n - n/2 - 1 (kdtree_sequential.cpp:51-56)."""
    spec = tree_spec(10)
    # root consumes position 10 // 2 = 5 as heap node 0
    assert spec.level_medpos[0][0] == 5 and spec.level_nodes[0][0] == 0
    # level 1: left segment [0, 5) -> median 2, right segment [6, 10) -> median 8
    assert spec.level_medpos[1].tolist() == [2, 8]
    assert spec.level_nodes[1].tolist() == [1, 2]


@pytest.mark.parametrize("n,d", [(1, 3), (2, 3), (3, 2), (17, 3), (128, 2), (1000, 3), (513, 8)])
def test_invariants(n, d):
    pts, _ = generate_problem(seed=n + d, dim=d, num_points=n)
    tree = build_jit(pts)
    validate_invariants(tree)


def test_node_levels():
    lv = node_levels(15)
    assert lv.tolist() == [0, 1, 1, 2, 2, 2, 2, 3, 3, 3, 3, 3, 3, 3, 3]


def test_build_deterministic():
    pts, _ = generate_problem(seed=3, dim=3, num_points=257)
    t1 = build_jit(pts)
    t2 = build_jit(pts)
    np.testing.assert_array_equal(np.asarray(t1.node_point), np.asarray(t2.node_point))


def test_build_with_duplicate_points():
    """f32 ties: exact-median determinism via the (coord, id) composite key."""
    base = jnp.ones((16, 3), jnp.float32)
    pts = jnp.concatenate([base, 2.0 * base, base], axis=0)
    tree = build_jit(pts)
    validate_invariants(tree)


def test_validator_rejects_corruption():
    """A validator that cannot fail proves nothing: corrupt one split value
    and one permutation slot and expect loud failure."""
    from kdtree_tpu.models.tree import KDTree

    pts, _ = generate_problem(seed=11, dim=3, num_points=500)
    tree = build_jit(pts)
    sval = np.asarray(tree.split_val).copy()
    root_axis_vals = np.asarray(tree.points)[:, 0]
    sval[0] = root_axis_vals.min() - 1.0  # root split below every left point
    bad = KDTree(tree.points, tree.node_point, jnp.asarray(sval))
    with pytest.raises(AssertionError):
        validate_invariants(bad)

    npnt = np.asarray(tree.node_point).copy()
    npnt[1] = npnt[2]  # duplicate a point id -> not a permutation
    bad = KDTree(tree.points, jnp.asarray(npnt), tree.split_val)
    with pytest.raises(AssertionError):
        validate_invariants(bad)


@pytest.mark.slow
def test_invariants_1m_points():
    """VERDICT r2 item 8: the vectorized validator must handle 1M points in
    seconds (the old per-node DFS was O(heap * subtree))."""
    import time

    pts, _ = generate_problem(seed=1, dim=3, num_points=1 << 20)
    tree = build_jit(pts)
    np.asarray(tree.split_val)  # materialize before timing
    t0 = time.monotonic()
    validate_invariants(tree)
    assert time.monotonic() - t0 < 60.0
