"""Fused Pallas scan kernel vs the XLA reference path (identity pattern of
tests/test_build_presort.py: same algorithm, two implementations) plus the
brute-force oracle. Runs in interpreter mode on the CPU test mesh.

The whole module gates on an interpret-path PROBE (not a version pin).
PR 6 ported the kernel to the jax 0.4.x interpret machinery (the
early-exit decision is carried through the while_loop instead of read
from refs in its cond — 0.4.x cannot discharge ref effects in a while
cond), so these tests now RUN on this container's jax 0.4.37. The probe
stays: an even older jax missing other discharge rules must read as
SKIPPED, not FAILED — and the probe un-skips itself wherever the
interpreter works, which is exactly how these 5 tests came back.
"""

import functools

import numpy as np
import pytest

import jax.numpy as jnp

from kdtree_tpu import build_morton, generate_problem
from kdtree_tpu.ops import bruteforce
from kdtree_tpu.ops import tile_query as tq
from kdtree_tpu.pallas.scan_knn import scan_tiles_fused


def _mk_tiles(pts, qs, tile, k, cmax, seeds=8):
    tree = build_morton(pts)
    T = qs.shape[0] // tile
    tiles = qs[: T * tile].reshape(T, tile, qs.shape[1])
    box_lo, box_hi = jnp.min(tiles, axis=1), jnp.max(tiles, axis=1)
    inf_b = jnp.full(T, jnp.inf, jnp.float32)
    seed_cand, seed_lb, _ = tq._frontier(tree, box_lo, box_hi, inf_b, seeds)
    sd, _ = tq._scan_tiles(tree, tiles, seed_cand, seed_lb, k, 8, 8)
    bound = jnp.max(sd[..., k - 1], axis=1)
    cand, lb, _ = tq._frontier(tree, box_lo, box_hi, bound, cmax)
    return tree, tiles, cand, lb


@functools.lru_cache(maxsize=1)
def _interpret_supported() -> bool:
    """Probe the ACTUAL kernel (tiny shape) in interpret mode — a trivial
    probe kernel would pass on jax versions whose interpreter lacks only
    the state-discharge rules this kernel's while_loop/run_scoped use."""
    try:
        pts, _ = generate_problem(seed=11, dim=2, num_points=256, num_queries=1)
        qs, _ = generate_problem(seed=12, dim=2, num_points=16, num_queries=1)
        tree, tiles, cand, lb = _mk_tiles(pts, qs, tile=8, k=2, cmax=16)
        np.asarray(scan_tiles_fused(tree, tiles, cand, lb, 2, interpret=True))
        return True
    except NotImplementedError:
        return False


pytestmark = pytest.mark.skipif(
    not _interpret_supported(),
    reason="pallas CPU interpret path lacks primitives this kernel needs "
           "on this jax (NotImplementedError); kernel verified on real TPU "
           "backends — ROADMAP 'Pallas on-CPU interpret parity'",
)


@pytest.mark.parametrize("n,d,k,tile", [(4096, 3, 4, 16), (2000, 2, 16, 8)])
def test_matches_xla_scan(n, d, k, tile):
    pts, _ = generate_problem(seed=1, dim=d, num_points=n, num_queries=1)
    qs, _ = generate_problem(seed=2, dim=d, num_points=128, num_queries=1)
    tree, tiles, cand, lb = _mk_tiles(pts, qs, tile, k, cmax=64)
    xd, xi = tq._scan_tiles(tree, tiles, cand, lb, k, 8, 8)
    pd, pi = scan_tiles_fused(tree, tiles, cand, lb, k, interpret=True)
    np.testing.assert_allclose(np.asarray(pd), np.asarray(xd), rtol=1e-6)
    # ids may differ on exact distance ties; they must reproduce distances
    gather = np.sum(
        (np.asarray(tiles)[:, :, None, :] -
         np.asarray(pts)[np.maximum(np.asarray(pi), 0)]) ** 2,
        axis=-1,
    )
    finite = np.isfinite(np.asarray(pd))
    np.testing.assert_allclose(
        np.where(finite, gather, np.inf), np.asarray(pd), rtol=1e-5
    )


def test_full_engine_with_pallas_matches_oracle():
    pts, _ = generate_problem(seed=3, dim=3, num_points=8192, num_queries=1)
    qs, _ = generate_problem(seed=4, dim=3, num_points=300, num_queries=1)
    tree = build_morton(pts)
    d2, gi = tq.morton_knn_tiled(tree, qs, k=5, use_pallas=True)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=5)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf), rtol=1e-5)


def test_early_exit_does_not_miss_neighbors():
    """Clustered points make many candidates prunable — the early exit must
    never drop a true neighbor."""
    rng = np.random.default_rng(5)
    centers = rng.uniform(-80, 80, (6, 3))
    pts = jnp.asarray(
        centers[rng.integers(0, 6, 6000)] + rng.normal(0, 0.3, (6000, 3)),
        jnp.float32,
    )
    qs = jnp.asarray(
        centers[rng.integers(0, 6, 96)] + rng.normal(0, 0.3, (96, 3)),
        jnp.float32,
    )
    tree = build_morton(pts)
    d2, _ = tq.morton_knn_tiled(tree, qs, k=8, use_pallas=True)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=8)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf), rtol=1e-5)


def test_k_exceeds_real_candidates():
    """Tiles over a tiny tree: k > points, padding ids/-inf handling."""
    pts, _ = generate_problem(seed=6, dim=3, num_points=40, num_queries=1)
    qs, _ = generate_problem(seed=7, dim=3, num_points=32, num_queries=1)
    tree = build_morton(pts)
    d2, gi = tq.morton_knn_tiled(tree, qs, k=64, use_pallas=True)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=40)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf), rtol=1e-5)
