import jax.numpy as jnp
import numpy as np
import pytest

from kdtree_tpu import build_jit, generate_problem, validate_invariants
from kdtree_tpu.ops import bruteforce
from kdtree_tpu.ops.build_presort import build_presort
from kdtree_tpu.ops.query import knn


@pytest.mark.parametrize(
    "n,d", [(1, 3), (2, 3), (3, 2), (17, 3), (100, 3), (1000, 3), (513, 8), (777, 2), (999, 5)]
)
def test_identical_to_sort_based_build(n, d):
    """Both builds order segments by (coord, id), so the trees must be
    bit-identical — the strongest possible cross-check."""
    pts, _ = generate_problem(seed=n * 7 + d, dim=d, num_points=n)
    a = build_jit(pts)
    b = build_presort(pts)
    np.testing.assert_array_equal(np.asarray(a.node_point), np.asarray(b.node_point))
    np.testing.assert_array_equal(np.asarray(a.split_val), np.asarray(b.split_val))


def test_identical_with_duplicates():
    base = jnp.ones((16, 3), jnp.float32)
    pts = jnp.concatenate([base, 2.0 * base, base], axis=0)
    a = build_jit(pts)
    b = build_presort(pts)
    np.testing.assert_array_equal(np.asarray(a.node_point), np.asarray(b.node_point))
    validate_invariants(b)


def test_presort_tree_queries_match_oracle():
    pts, qs = generate_problem(seed=5, dim=3, num_points=2048, num_queries=10)
    tree = build_presort(pts)
    d2, idx = knn(tree, qs, k=8)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=8)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf), rtol=1e-6)
