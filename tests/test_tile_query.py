"""Tiled batched k-NN vs the brute-force oracle (SURVEY.md §4 item 1: the
oracle is the only trustworthy reference, §3.5)."""

import jax.numpy as jnp
import numpy as np
import pytest

from kdtree_tpu import build_morton, generate_problem
from kdtree_tpu.ops import bruteforce
from kdtree_tpu.ops.tile_query import morton_knn_tiled


def _check(pts, qs, k, **kw):
    tree = build_morton(pts)
    d2, gi = morton_knn_tiled(tree, qs, k=k, **kw)
    bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=k)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-5)
    # ids must reproduce the distances
    gia = np.asarray(gi)
    finite = np.isfinite(np.asarray(d2))
    assert np.all((gia >= 0) == finite)
    gather = np.sum(
        (np.asarray(qs)[:, None, :] - np.asarray(pts)[np.maximum(gia, 0)]) ** 2,
        axis=-1,
    )
    np.testing.assert_allclose(
        np.where(finite, gather, np.inf), np.asarray(d2), rtol=1e-5
    )


@pytest.mark.parametrize(
    "n,d,k,q", [(4096, 3, 4, 1000), (20000, 2, 16, 513), (3000, 5, 1, 64)]
)
def test_matches_bruteforce(n, d, k, q):
    pts, _ = generate_problem(seed=3, dim=d, num_points=n, num_queries=10)
    qs, _ = generate_problem(seed=99, dim=d, num_points=q, num_queries=1)
    _check(pts, qs, k)


def test_query_count_not_multiple_of_tile():
    pts, _ = generate_problem(seed=1, dim=3, num_points=5000, num_queries=1)
    qs, _ = generate_problem(seed=2, dim=3, num_points=777, num_queries=1)
    _check(pts, qs, 3, tile=256)


def test_small_query_batch():
    pts, qs = generate_problem(seed=4, dim=3, num_points=8192, num_queries=10)
    _check(pts, qs, 5)


def test_tiny_tree_collect_all():
    pts, qs = generate_problem(seed=5, dim=3, num_points=100, num_queries=50)
    _check(pts, qs, 7)


def test_k_larger_than_bucket():
    pts, qs = generate_problem(seed=6, dim=2, num_points=4096, num_queries=100)
    _check(pts, qs, 200)  # k > bucket_cap=128 forces a wider scan chunk


def test_k_larger_than_n():
    pts, qs = generate_problem(seed=7, dim=3, num_points=37, num_queries=9)
    tree = build_morton(pts)
    d2, gi = morton_knn_tiled(tree, qs, k=50)
    assert d2.shape == (9, 37)
    bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=37)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-5)


def test_duplicate_points():
    pts = jnp.tile(jnp.asarray([[1.0, 2.0, 3.0]]), (600, 1))
    qs = jnp.asarray([[1.0, 2.0, 3.0], [0.0, 0.0, 0.0]])
    tree = build_morton(pts)
    d2, gi = morton_knn_tiled(tree, qs, k=4)
    np.testing.assert_allclose(np.asarray(d2)[0], 0.0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(d2)[1], 14.0, rtol=1e-6)
    assert len(set(np.asarray(gi)[0].tolist())) == 4  # distinct ids for dups


def test_clustered_queries_and_points():
    """Clustered data (the grading config's load-imbalance analog): tight
    blobs exercise the overflow->retry growth path."""
    rng = np.random.default_rng(0)
    centers = rng.uniform(-80, 80, (8, 3))
    pts = jnp.asarray(
        (centers[rng.integers(0, 8, 30000)] + rng.normal(0, 0.5, (30000, 3))),
        jnp.float32,
    )
    qs = jnp.asarray(
        centers[rng.integers(0, 8, 500)] + rng.normal(0, 0.5, (500, 3)),
        jnp.float32,
    )
    _check(pts, qs, 8)


def test_multibatch_async_dispatch_and_retry(monkeypatch):
    """Exercise the multi-batch driver: >1 sub-batch program, the stacked
    overflow fetch, and mid-stream doubling retries (the production-scale
    path that default _BATCH_Q=65536 hides from small CI shapes).

    The first batch (Hilbert order puts the corner cluster there) settles a
    small cap; the later uniform batches need more candidate buckets, so
    they must overflow at the settled cap and go through the
    stacked-flags retry rounds. Results are oracle-checked either way."""
    import kdtree_tpu.ops.tile_query as tqm

    monkeypatch.setattr(tqm, "_BATCH_Q", 256)
    calls = []
    real = tqm._tiled_batch

    def spy(*a, **kw):
        calls.append(a[6])  # the cmax this batch ran at
        return real(*a, **kw)

    monkeypatch.setattr(tqm, "_tiled_batch", spy)

    rng = np.random.default_rng(42)
    pts, _ = generate_problem(seed=11, dim=2, num_points=30000, num_queries=1)
    # 300 queries tightly clustered at the domain corner (cheap tiles, sorted
    # first) + 724 uniform queries (wide tiles, need many candidate buckets)
    corner = -100.0 + rng.random((300, 2)).astype(np.float32)
    spread = rng.uniform(-100, 100, (724, 2)).astype(np.float32)
    qs = jnp.asarray(np.concatenate([corner, spread]))
    tree = build_morton(pts)
    d2, gi = tqm.morton_knn_tiled(tree, qs, k=4, tile=8, cmax=2)
    bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=4)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-5)
    n_batches = 1024 // 256
    assert len(calls) > n_batches, "no retry round ran — weaken the setup"
    assert len(set(calls)) > 1, "cap never grew across retries"


def test_matches_per_query_dfs():
    """Tiled and per-query DFS engines must agree on distances (both exact)."""
    from kdtree_tpu import morton_knn

    pts, _ = generate_problem(seed=8, dim=3, num_points=10000, num_queries=1)
    qs, _ = generate_problem(seed=9, dim=3, num_points=333, num_queries=1)
    tree = build_morton(pts)
    td, _ = morton_knn_tiled(tree, qs, k=6)
    dd, _ = morton_knn(tree, qs, k=6)
    np.testing.assert_allclose(np.asarray(td), np.asarray(dd), rtol=1e-6)


def test_drive_batches_cap_settle_and_straggler_retry():
    """The shared async batch driver: the FIRST batch settles the cap in
    doubling rounds, remaining batches dispatch at the settled cap, and a
    geometry-driven straggler retries alone without re-running the rest."""
    from kdtree_tpu.ops.tile_query import drive_batches

    calls = []

    def run_batch(b0, cap):
        calls.append((b0, cap))
        # batch 0 needs cap >= 4; batch 4 is a straggler needing cap >= 8
        need = 8 if b0 == 4 else 4
        ov = cap < need
        return (
            jnp.full((2, 1), float(cap)),
            jnp.full((2, 1), b0, jnp.int32),
            jnp.asarray(ov),
        )

    d2, gi = drive_batches(run_batch, [0, 2, 4], cmax=1, nbp=16)
    # settle: (0,1)->(0,2)->(0,4); dispatch (2,4),(4,4); retry only (4,8)
    assert calls == [(0, 1), (0, 2), (0, 4), (2, 4), (4, 4), (4, 8)], calls
    assert d2.shape == (6, 1) and gi.shape == (6, 1)
    np.testing.assert_array_equal(
        np.asarray(gi).ravel(), [0, 0, 2, 2, 4, 4]
    )
    # batches 0/2 answered at cap 4, straggler at cap 8
    np.testing.assert_array_equal(
        np.asarray(d2).ravel(), [4.0, 4.0, 4.0, 4.0, 8.0, 8.0]
    )


def test_drive_batches_pipelined_retire_and_retry_counting():
    """The bounded-lookahead pipeline: with more batches than the window,
    the oldest in-flight batch is retired per new dispatch; a retired
    batch that overflows retries ALONE (younger in-flight batches are
    never re-dispatched for it), and the retry counter counts exactly the
    re-dispatches — no double count for in-flight lookahead."""
    from kdtree_tpu import obs
    from kdtree_tpu.ops.tile_query import drive_batches

    retc = obs.get_registry().counter("kdtree_tile_overflow_retries_total")
    calls = []

    def run_batch(b0, cap):
        calls.append((b0, cap))
        need = 8 if b0 == 4 else 2  # one straggler mid-stream
        return (
            jnp.full((2, 1), float(cap)),
            jnp.full((2, 1), b0, jnp.int32),
            jnp.asarray(cap < need),
        )

    r0 = retc.value
    offsets = [0, 2, 4, 6, 8, 10]
    d2, gi = drive_batches(run_batch, offsets, cmax=2, nbp=16, lookahead=2)
    # settle (0@2 clean); fill window (2@2, 4@2); retire 2 (clean) ->
    # dispatch 6@2; retire 4: overflow -> 4@4 -> 4@8 clean; dispatch 8@8;
    # retire 6 (clean); dispatch 10@8; drain [8, 10] stacked, clean.
    assert calls == [(0, 2), (2, 2), (4, 2), (6, 2), (4, 4), (4, 8),
                     (8, 8), (10, 8)], calls
    # retry counter == re-dispatches (2 for the straggler), NOT the
    # in-flight batches that happened to be queued behind it
    assert retc.value - r0 == 2
    np.testing.assert_array_equal(
        np.asarray(gi).ravel(), np.repeat(offsets, 2)
    )
    # every batch's answer comes from its LAST (clean) dispatch cap
    np.testing.assert_array_equal(
        np.asarray(d2).ravel(), [2, 2, 2, 2, 8, 8, 2, 2, 8, 8, 8, 8]
    )


def test_pipelined_undersized_cmax_byte_identical(monkeypatch):
    """The issue-6 acceptance contract for pipelining x overflow-retry: a
    forced-undersized cmax under a multi-batch pipelined drive (lookahead
    > 1, exercised via the env knob) must settle to results BYTE-IDENTICAL
    to a never-overflowing run, and the retry counter must count exactly
    the extra dispatches (probe doubling + per-batch retries), never the
    in-flight lookahead batches that retired clean."""
    import kdtree_tpu.ops.tile_query as tqm
    from kdtree_tpu import obs

    monkeypatch.setattr(tqm, "_BATCH_Q", 256)
    pts, _ = generate_problem(seed=11, dim=2, num_points=30000,
                              num_queries=1)
    qs, _ = generate_problem(seed=12, dim=2, num_points=1024, num_queries=1)
    tree = build_morton(pts)
    # oracle: cap = nbp can never overflow -> zero retries by construction
    od2, ogi = tqm.morton_knn_tiled(tree, qs, k=4, tile=8,
                                    cmax=tree.num_buckets)

    calls = []
    real = tqm._tiled_batch

    def spy(*a, **kw):
        calls.append(a[6])
        return real(*a, **kw)

    monkeypatch.setattr(tqm, "_tiled_batch", spy)
    retc = obs.get_registry().counter("kdtree_tile_overflow_retries_total")
    for lookahead in ("1", "2"):
        monkeypatch.setenv("KDTREE_TPU_TILE_LOOKAHEAD", lookahead)
        calls.clear()
        r0 = retc.value
        d2, gi = tqm.morton_knn_tiled(tree, qs, k=4, tile=8, cmax=2)
        n_batches = 1024 // 256
        assert len(calls) > n_batches, "no retry ran — weaken the setup"
        # every call beyond one-per-batch is a retry; exact equality IS
        # the no-double-count assertion
        assert retc.value - r0 == len(calls) - n_batches
        np.testing.assert_array_equal(np.asarray(d2), np.asarray(od2))
        np.testing.assert_array_equal(np.asarray(gi), np.asarray(ogi))


def test_plan_small_tile_forces_wide_fold_regardless_of_bucket_size():
    """The small-tile heuristic branch must land in _fold_block's WIDE
    regime even when the bucket size is small enough that _SCAN_V chunks
    would slip under the narrow width gate (review finding: narrow
    extract at tiny tiles is a measured throughput regression — the
    branch exists to avoid it, so it must actually do so)."""
    from kdtree_tpu.ops import tile_query as tq

    for B in (32, 64, 256):
        plan = tq.plan_tiled(1024, 3, 30000, 512, B, 4, tile=8)
        assert plan.v * B + 4 > tq._EXTRACT_W_MAX, (B, plan.v)
    # the wide-tile branch is untouched: single-bucket narrow chunks
    plan = tq.plan_tiled(1024, 3, 30000, 512, 256, 4, tile=128)
    assert plan.v == 1


def test_drive_batches_drain_retries_stale_cap_batches():
    """Exactness regression (PR 6 review): when retiring an earlier
    straggler grows bcmax to the nbp CEILING while tail batches are still
    in flight at a stale smaller cap, the drain must retry those batches —
    the ceiling short-circuit applies per batch (its LAST dispatch ran at
    nbp), never to bcmax, or an overflow-flagged (incomplete) result is
    silently returned."""
    from kdtree_tpu.ops.tile_query import drive_batches

    calls = []

    def run_batch(b0, cap):
        calls.append((b0, cap))
        need = 4 if b0 in (2, 4) else 2
        return (
            jnp.full((2, 1), float(cap)),
            jnp.full((2, 1), b0, jnp.int32),
            jnp.asarray(cap < need),
        )

    offsets = [0, 2, 4, 6]
    d2, gi = drive_batches(run_batch, offsets, cmax=2, nbp=4, lookahead=2,
                           settle_first=False)
    # fill (0@2, 2@2); retire 0 clean; dispatch 4@2; retire 2: overflow ->
    # bcmax grows to nbp=4 -> 2@4 clean; dispatch 6@4; drain [4, 6]:
    # batch 4 overflowed at its STALE cap 2 and must redispatch at 4 even
    # though bcmax == nbp already (the old break returned its bad result)
    assert calls == [(0, 2), (2, 2), (4, 2), (2, 4), (6, 4), (4, 4)], calls
    np.testing.assert_array_equal(
        np.asarray(d2).ravel(), [2, 2, 4, 4, 4, 4, 4, 4]
    )
    np.testing.assert_array_equal(
        np.asarray(gi).ravel(), np.repeat(offsets, 2)
    )


def test_drive_batches_cap_ceiling_stops_retries():
    """At cap == nbp the driver must stop doubling even if a batch still
    flags overflow (overflow is impossible at nbp by construction; a buggy
    flag must not loop forever)."""
    from kdtree_tpu.ops.tile_query import drive_batches

    calls = []

    def run_batch(b0, cap):
        calls.append((b0, cap))
        return (
            jnp.zeros((1, 1)),
            jnp.zeros((1, 1), jnp.int32),
            jnp.asarray(True),  # always claims overflow
        )

    d2, _ = drive_batches(run_batch, [0], cmax=2, nbp=8)
    # settle rounds: 2 -> 4 -> 8, then stop (cap == nbp)
    assert calls == [(0, 2), (0, 4), (0, 8)], calls
    assert d2.shape == (1, 1)
