import numpy as np
import pytest

from kdtree_tpu import build_jit, generate_problem, knn
from kdtree_tpu.utils.checkpoint import load_tree, save_tree
from kdtree_tpu.utils.timing import PhaseTimer


def test_checkpoint_roundtrip(tmp_path):
    pts, qs = generate_problem(seed=2, dim=3, num_points=300, num_queries=5)
    tree = build_jit(pts)
    path = str(tmp_path / "tree.npz")
    save_tree(path, tree, meta={"seed": 2, "generator": "threefry"})
    tree2, meta = load_tree(path)
    assert meta == {"seed": 2, "generator": "threefry"}
    np.testing.assert_array_equal(np.asarray(tree.node_point), np.asarray(tree2.node_point))
    d1, i1 = knn(tree, qs, k=3)
    d2, i2 = knn(tree2, qs, k=3)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_checkpoint_roundtrip_bucket(tmp_path):
    from kdtree_tpu.ops.bucket import BucketKDTree, bucket_knn, build_bucket

    pts, qs = generate_problem(seed=3, dim=3, num_points=500, num_queries=5)
    tree = build_bucket(pts, bucket_cap=32)
    path = str(tmp_path / "bucket.npz")
    save_tree(path, tree, meta={"seed": 3, "generator": "threefry"})
    tree2, meta = load_tree(path)
    assert isinstance(tree2, BucketKDTree)
    assert meta["seed"] == 3
    assert (tree2.n_real, tree2.num_levels) == (tree.n_real, tree.num_levels)
    d1, i1 = bucket_knn(tree, qs, k=3)
    d2, i2 = bucket_knn(tree2, qs, k=3)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_checkpoint_roundtrip_global(tmp_path):
    from kdtree_tpu.parallel import make_mesh
    from kdtree_tpu.parallel.global_tree import (
        GlobalKDTree, build_global, global_knn,
    )

    pts, qs = generate_problem(seed=4, dim=3, num_points=256, num_queries=5)
    tree = build_global(pts, mesh=make_mesh(4))
    path = str(tmp_path / "global.npz")
    save_tree(path, tree, meta={"seed": 4})
    tree2, meta = load_tree(path)
    assert isinstance(tree2, GlobalKDTree)
    d1, i1 = global_knn(tree, qs, k=2)
    d2, i2 = global_knn(tree2, qs, k=2)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_checkpoint_sharded_roundtrip(tmp_path):
    """VERDICT r3 item 7: forest checkpoints as per-device shards + manifest.
    Save writes one npz per mesh position (peak host memory ~1/P of the
    forest); load reassembles onto a matching mesh (sharded arrays) or, on
    different hardware, into dense host arrays — answers identical either
    way, and to the single-npz format."""
    import jax
    from kdtree_tpu.parallel import make_mesh
    from kdtree_tpu.parallel.global_morton import (
        GlobalMortonForest, build_global_morton, global_morton_query,
    )
    from kdtree_tpu.ops.generate import generate_queries
    from kdtree_tpu.utils import checkpoint

    n, dim, k, p = 1037, 3, 4, 8
    mesh = make_mesh(p)
    forest = build_global_morton(13, dim, n, mesh=mesh)
    qs = generate_queries(5, dim, 16)
    d0, i0 = global_morton_query(forest, qs, k=k, mesh=mesh)

    path = str(tmp_path / "forest.npz")
    save_tree(path, forest, meta={"seed": 13, "generator": "threefry"},
              sharded=True)
    shard_files = sorted(tmp_path.glob("forest.npz.shard*.npz"))
    assert len(shard_files) == p

    loaded, meta = load_tree(path)
    assert isinstance(loaded, GlobalMortonForest)
    assert meta["seed"] == 13 and loaded.num_points == n
    # 8 CPU devices available -> assembled sharded over the mesh
    assert len(loaded.node_lo.sharding.device_set) == p
    d1, i1 = global_morton_query(loaded, qs, k=k, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))

    # cross-hardware load path (hardware with < P devices): dense host
    # assembly, identical children
    real_devices = jax.devices()
    import unittest.mock as mock
    with mock.patch.object(jax, "devices", return_value=real_devices[:1]):
        dense, _ = load_tree(path)
    children, _ = GlobalMortonForest.tree_flatten(dense)
    ref_children, _ = GlobalMortonForest.tree_flatten(forest)
    for c, rc in zip(children, ref_children):
        np.testing.assert_array_equal(np.asarray(c), np.asarray(rc))
    d2m, _ = global_morton_query(dense, qs, k=k, mesh=make_mesh(1))
    np.testing.assert_allclose(np.asarray(d2m), np.asarray(d0), rtol=1e-6)

    # the auto threshold keeps small trees in the single-npz format
    auto_path = str(tmp_path / "auto.npz")
    save_tree(auto_path, forest, meta={"seed": 13})
    with np.load(auto_path) as zz:
        assert "format" not in zz.files

    # non-forest trees must refuse the sharded format loudly
    from kdtree_tpu import build_jit as _build
    pts, _ = generate_problem(seed=2, dim=3, num_points=64, num_queries=1)
    with pytest.raises(TypeError, match="leading device axis"):
        save_tree(str(tmp_path / "x.npz"), _build(pts), sharded=True)

    # re-saving at the same path supersedes the old shard set completely
    # (tagged files + atomic manifest: never a mixed assembly)
    forest2 = build_global_morton(14, dim, n, mesh=mesh)
    save_tree(path, forest2, meta={"seed": 14, "generator": "threefry"},
              sharded=True)
    assert len(sorted(tmp_path.glob("forest.npz.shard*.npz"))) == p
    loaded2, meta2 = load_tree(path)
    assert meta2["seed"] == 14
    d14, _ = global_morton_query(loaded2, qs, k=k, mesh=mesh)
    ref14, _ = global_morton_query(forest2, qs, k=k, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(d14), np.asarray(ref14))


def test_checkpoint_single_save_atomic(tmp_path, monkeypatch):
    """ADVICE r4: a crashed single-npz re-save must not truncate the
    previous checkpoint — the write goes to a tmp file and os.replace's
    into place (same discipline as the sharded manifest)."""
    pts, _ = generate_problem(seed=2, dim=3, num_points=300, num_queries=1)
    tree = build_jit(pts)
    path = str(tmp_path / "tree.npz")
    save_tree(path, tree, meta={"seed": 2, "generator": "threefry"})

    def boom(*a, **kw):
        raise RuntimeError("disk died mid-write")

    monkeypatch.setattr(np, "savez_compressed", boom)
    with pytest.raises(RuntimeError, match="disk died"):
        save_tree(path, tree, meta={"seed": 99})
    monkeypatch.undo()
    # the old checkpoint survives intact, and no tmp litter remains
    tree2, meta = load_tree(path)
    assert meta["seed"] == 2
    np.testing.assert_array_equal(
        np.asarray(tree.node_point), np.asarray(tree2.node_point))
    assert list(tmp_path.glob("tree.npz.tmp-*")) == []


def test_checkpoint_meshfree_load_budget_guard(tmp_path, monkeypatch):
    """VERDICT r4 weak #5: loading a sharded checkpoint without a matching
    mesh concatenates every shard on the host — above the budget that must
    fail crisply (naming the opt-out) instead of OOMing."""
    import unittest.mock as mock

    import jax

    from kdtree_tpu.parallel import make_mesh
    from kdtree_tpu.parallel.global_morton import (
        GlobalMortonForest, build_global_morton,
    )
    from kdtree_tpu.utils import checkpoint

    forest = build_global_morton(13, 3, 1037, mesh=make_mesh(8))
    path = str(tmp_path / "f.npz")
    assert save_tree(path, forest, sharded=True) == "sharded"

    real_devices = jax.devices()
    monkeypatch.setattr(checkpoint, "_HOST_MATERIALIZE_BYTES", 1024)
    with mock.patch.object(jax, "devices", return_value=real_devices[:1]):
        with pytest.raises(ValueError, match="allow_host_materialize"):
            load_tree(path)
        # explicit opt-in takes the dense fallback and round-trips exactly
        dense, _ = load_tree(path, allow_host_materialize=True)
    children, _ = GlobalMortonForest.tree_flatten(dense)
    ref_children, _ = GlobalMortonForest.tree_flatten(forest)
    for c, rc in zip(children, ref_children):
        np.testing.assert_array_equal(np.asarray(c), np.asarray(rc))


def test_checkpoint_sharded_sidecar_and_cleanup(tmp_path):
    """Code-review findings: a manifest copied without its sidecar shard
    files must fail with a message naming them (not a bare ENOENT), and a
    later single-npz save at the same path must sweep the stale shards."""
    import shutil

    from kdtree_tpu.parallel import make_mesh
    from kdtree_tpu.parallel.global_morton import build_global_morton

    forest = build_global_morton(13, 3, 1037, mesh=make_mesh(8))
    path = str(tmp_path / "f.npz")
    assert save_tree(path, forest, sharded=True) == "sharded"

    lone = tmp_path / "lone" / "f.npz"
    lone.parent.mkdir()
    shutil.copy(path, lone)  # manifest only, no sidecars
    with pytest.raises(FileNotFoundError, match="copied as a set"):
        load_tree(str(lone))

    assert len(list(tmp_path.glob("f.npz.shard*.npz"))) == 8
    assert save_tree(path, forest, sharded=False) == "single"
    assert list(tmp_path.glob("f.npz.shard*.npz")) == []
    tree2, _ = load_tree(path)
    assert tree2.num_points == forest.num_points


def test_checkpoint_sharded_global_exact(tmp_path):
    """GlobalExactTree's replicated top heap (leading dim Htop != P) rides
    in the manifest; the per-device children shard — round trip must be
    exact (the code-review repro for the mixed-leading-axis crash)."""
    from kdtree_tpu.parallel import make_mesh
    from kdtree_tpu.parallel.global_exact import (
        GlobalExactTree, build_global_exact, global_exact_query,
    )
    from kdtree_tpu.ops.generate import generate_queries

    n, dim, k, p = 1000, 3, 3, 8
    mesh = make_mesh(p)
    tree = build_global_exact(9, dim, n, mesh=mesh)
    qs = generate_queries(2, dim, 12)
    d0, i0 = global_exact_query(tree, qs, k=k, mesh=mesh)

    path = str(tmp_path / "exact.npz")
    save_tree(path, tree, meta={"seed": 9}, sharded=True)
    loaded, meta = load_tree(path)
    assert isinstance(loaded, GlobalExactTree) and meta["seed"] == 9
    d1, i1 = global_exact_query(loaded, qs, k=k, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))


def test_phase_timer():
    t = PhaseTimer()
    with t.phase("a") as h:
        x, _ = generate_problem(seed=1, dim=2, num_points=64)
        h.append(x)
    with t.phase("b"):
        pass
    rep = t.report()
    assert set(rep) == {"a", "b", "total"}
    assert rep["total"] >= rep["a"] >= 0.0


def test_graft_entry():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    import __graft_entry__ as ge

    import jax

    fn, args = ge.entry()
    d2, idx = jax.jit(fn)(*args)
    assert d2.shape == (64, 16)
    # small scale keeps the default suite fast; the driver (and the slow
    # marker below) run the full 1M-per-device default
    ge.dryrun_multichip(8, points_per_device=1 << 14)


@pytest.mark.slow
def test_graft_entry_full_scale():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_tree_knn_dense_batch_routing():
    """_tree_knn routes dense low-D batches to the tiled engines and stays
    exact (CLI `query --queries` with a big user file hits this path)."""
    import jax.numpy as jnp

    from kdtree_tpu import build_morton, generate_problem
    from kdtree_tpu.ops import bruteforce
    from kdtree_tpu.parallel.global_morton import build_global_morton
    from kdtree_tpu.parallel.mesh import make_mesh
    from kdtree_tpu.utils.cli import _tree_knn

    rng = np.random.default_rng(2)
    qs = jnp.asarray(rng.uniform(-100, 100, (600, 3)).astype(np.float32))

    pts, _ = generate_problem(seed=6, dim=3, num_points=900, num_queries=1)
    d2, _ = _tree_knn(build_morton(pts), qs, k=3)  # dense: 600*64 >= 900
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=3)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf), rtol=1e-5)

    forest = build_global_morton(6, 3, 900, mesh=make_mesh(8))
    fd2, _ = _tree_knn(forest, qs, k=3)
    from kdtree_tpu.ops.generate import generate_points_rowwise

    fbf, _ = bruteforce.knn_exact_d2(generate_points_rowwise(6, 3, 900), qs, k=3)
    np.testing.assert_allclose(np.asarray(fd2), np.asarray(fbf), rtol=1e-5)

    # classic and bucket trees also serve dense batches (via a one-time
    # cached Morton view over their stored points), ids included
    from kdtree_tpu import build_jit
    from kdtree_tpu.ops.bucket import build_bucket

    ct = build_jit(pts)
    cd2, ci = _tree_knn(ct, qs, k=3)
    np.testing.assert_allclose(np.asarray(cd2), np.asarray(bf), rtol=1e-5)
    assert hasattr(ct, "_morton_view")  # the dense path actually ran
    gather = np.sum(
        (np.asarray(qs)[:, None, :] - np.asarray(pts)[np.asarray(ci)]) ** 2,
        axis=-1,
    )
    np.testing.assert_allclose(gather, np.asarray(cd2), rtol=1e-5)

    bt = build_bucket(pts, bucket_cap=32)
    bd2, bi = _tree_knn(bt, qs, k=3)
    np.testing.assert_allclose(np.asarray(bd2), np.asarray(bf), rtol=1e-5)
    assert hasattr(bt, "_morton_view")
    assert int(np.asarray(bi).min()) >= 0
