import numpy as np
import pytest

from kdtree_tpu import build_jit, generate_problem, knn
from kdtree_tpu.utils.checkpoint import load_tree, save_tree
from kdtree_tpu.utils.timing import PhaseTimer


def test_checkpoint_roundtrip(tmp_path):
    pts, qs = generate_problem(seed=2, dim=3, num_points=300, num_queries=5)
    tree = build_jit(pts)
    path = str(tmp_path / "tree.npz")
    save_tree(path, tree, meta={"seed": 2, "generator": "threefry"})
    tree2, meta = load_tree(path)
    assert meta == {"seed": 2, "generator": "threefry"}
    np.testing.assert_array_equal(np.asarray(tree.node_point), np.asarray(tree2.node_point))
    d1, i1 = knn(tree, qs, k=3)
    d2, i2 = knn(tree2, qs, k=3)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_checkpoint_roundtrip_bucket(tmp_path):
    from kdtree_tpu.ops.bucket import BucketKDTree, bucket_knn, build_bucket

    pts, qs = generate_problem(seed=3, dim=3, num_points=500, num_queries=5)
    tree = build_bucket(pts, bucket_cap=32)
    path = str(tmp_path / "bucket.npz")
    save_tree(path, tree, meta={"seed": 3, "generator": "threefry"})
    tree2, meta = load_tree(path)
    assert isinstance(tree2, BucketKDTree)
    assert meta["seed"] == 3
    assert (tree2.n_real, tree2.num_levels) == (tree.n_real, tree.num_levels)
    d1, i1 = bucket_knn(tree, qs, k=3)
    d2, i2 = bucket_knn(tree2, qs, k=3)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_checkpoint_roundtrip_global(tmp_path):
    from kdtree_tpu.parallel import make_mesh
    from kdtree_tpu.parallel.global_tree import (
        GlobalKDTree, build_global, global_knn,
    )

    pts, qs = generate_problem(seed=4, dim=3, num_points=256, num_queries=5)
    tree = build_global(pts, mesh=make_mesh(4))
    path = str(tmp_path / "global.npz")
    save_tree(path, tree, meta={"seed": 4})
    tree2, meta = load_tree(path)
    assert isinstance(tree2, GlobalKDTree)
    d1, i1 = global_knn(tree, qs, k=2)
    d2, i2 = global_knn(tree2, qs, k=2)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_phase_timer():
    t = PhaseTimer()
    with t.phase("a") as h:
        x, _ = generate_problem(seed=1, dim=2, num_points=64)
        h.append(x)
    with t.phase("b"):
        pass
    rep = t.report()
    assert set(rep) == {"a", "b", "total"}
    assert rep["total"] >= rep["a"] >= 0.0


def test_graft_entry():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    import __graft_entry__ as ge

    import jax

    fn, args = ge.entry()
    d2, idx = jax.jit(fn)(*args)
    assert d2.shape == (64, 16)
    # small scale keeps the default suite fast; the driver (and the slow
    # marker below) run the full 1M-per-device default
    ge.dryrun_multichip(8, points_per_device=1 << 14)


@pytest.mark.slow
def test_graft_entry_full_scale():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_tree_knn_dense_batch_routing():
    """_tree_knn routes dense low-D batches to the tiled engines and stays
    exact (CLI `query --queries` with a big user file hits this path)."""
    import jax.numpy as jnp

    from kdtree_tpu import build_morton, generate_problem
    from kdtree_tpu.ops import bruteforce
    from kdtree_tpu.parallel.global_morton import build_global_morton
    from kdtree_tpu.parallel.mesh import make_mesh
    from kdtree_tpu.utils.cli import _tree_knn

    rng = np.random.default_rng(2)
    qs = jnp.asarray(rng.uniform(-100, 100, (600, 3)).astype(np.float32))

    pts, _ = generate_problem(seed=6, dim=3, num_points=900, num_queries=1)
    d2, _ = _tree_knn(build_morton(pts), qs, k=3)  # dense: 600*64 >= 900
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=3)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf), rtol=1e-5)

    forest = build_global_morton(6, 3, 900, mesh=make_mesh(8))
    fd2, _ = _tree_knn(forest, qs, k=3)
    from kdtree_tpu.ops.generate import generate_points_rowwise

    fbf, _ = bruteforce.knn_exact_d2(generate_points_rowwise(6, 3, 900), qs, k=3)
    np.testing.assert_allclose(np.asarray(fd2), np.asarray(fbf), rtol=1e-5)
