import numpy as np

from kdtree_tpu import build_jit, generate_problem, knn
from kdtree_tpu.utils.checkpoint import load_tree, save_tree
from kdtree_tpu.utils.timing import PhaseTimer


def test_checkpoint_roundtrip(tmp_path):
    pts, qs = generate_problem(seed=2, dim=3, num_points=300, num_queries=5)
    tree = build_jit(pts)
    path = str(tmp_path / "tree.npz")
    save_tree(path, tree, meta={"seed": 2, "generator": "threefry"})
    tree2, meta = load_tree(path)
    assert meta == {"seed": 2, "generator": "threefry"}
    np.testing.assert_array_equal(np.asarray(tree.node_point), np.asarray(tree2.node_point))
    d1, i1 = knn(tree, qs, k=3)
    d2, i2 = knn(tree2, qs, k=3)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_phase_timer():
    t = PhaseTimer()
    with t.phase("a") as h:
        x, _ = generate_problem(seed=1, dim=2, num_points=64)
        h.append(x)
    with t.phase("b"):
        pass
    rep = t.report()
    assert set(rep) == {"a", "b", "total"}
    assert rep["total"] >= rep["a"] >= 0.0


def test_graft_entry():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    import __graft_entry__ as ge

    import jax

    fn, args = ge.entry()
    d2, idx = jax.jit(fn)(*args)
    assert d2.shape == (64, 16)
    ge.dryrun_multichip(8)
