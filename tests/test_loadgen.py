"""Load harness (kdtree_tpu/loadgen/, docs/OBSERVABILITY.md "Load
harness & capacity curves").

The contract under test is open-loop honesty: the schedule is a pure
function of the seed (same seed = identical arrivals, ops, payloads),
arrivals fire on schedule no matter how slowly the service answers
(coordinated omission structurally impossible), latency is measured
from intended send times, and the capacity block's knee verdict moves
when — and only when — the service genuinely slows. The e2e half pins
the acceptance flow: a live serve process, a mixed read/write ladder,
server-side write-latency evidence in the block, and a latency fault
measurably lowering the knee while the schedule stays byte-identical.
"""

from __future__ import annotations

import json
import threading
import time
from collections import Counter
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from kdtree_tpu.loadgen import build_schedule
from kdtree_tpu.loadgen import runner as lg_runner
from kdtree_tpu.loadgen.schedule import MixSpec, parse_mix

# ---------------------------------------------------------------------------
# schedule determinism (satellite: same seed => identical schedule)
# ---------------------------------------------------------------------------


def test_same_seed_same_schedule_different_seed_differs():
    a = build_schedule([20, 40], 1.0, 7, 3)
    b = build_schedule([20, 40], 1.0, 7, 3)
    assert a.keys() == b.keys()
    c = build_schedule([20, 40], 1.0, 8, 3)
    assert a.keys() != c.keys()


def test_diurnal_shape_is_seeded_and_modulated():
    a = build_schedule([200], 2.0, 5, 2, shape="diurnal",
                       diurnal_amp=0.5)
    b = build_schedule([200], 2.0, 5, 2, shape="diurnal",
                       diurnal_amp=0.5)
    assert a.keys() == b.keys()
    # first half-period modulates UP (sin > 0), second half DOWN — the
    # arrival mass must tilt toward the first half
    first = sum(1 for ar in a.arrivals if ar.t < 1.0)
    assert first > len(a.arrivals) - first


def test_mix_fractions_and_zipf_skew():
    sched = build_schedule([500], 4.0, 3, 3,
                           mix=MixSpec(0.7, 0.2, 0.1), regions=16)
    ops = Counter(ar.op for ar in sched.arrivals)
    total = sum(ops.values())
    assert abs(ops["query"] / total - 0.7) < 0.1
    assert ops["upsert"] > ops["delete"] > 0
    # Zipf skew: the hottest region must absorb far more than the
    # uniform share of queries (regions=16 -> uniform ~6%)
    centers = np.random.default_rng(3).random((16, 3))
    hits = Counter()
    for ar in sched.arrivals:
        if ar.op == "query":
            hits[int(np.argmin(
                np.linalg.norm(centers - ar.point, axis=1)))] += 1
    top = max(hits.values())
    assert top / sum(hits.values()) > 0.15


def test_deletes_only_target_earlier_upserts():
    sched = build_schedule([300], 4.0, 9, 3,
                           mix=MixSpec(0.4, 0.3, 0.3), write_base=1000)
    live = set()
    deletes = 0
    for ar in sched.arrivals:
        if ar.op == "upsert":
            assert ar.gid >= 1000
            live.add(ar.gid)
        elif ar.op == "delete":
            deletes += 1
            assert ar.gid in live, "delete targets an id never upserted"
            live.remove(ar.gid)
    assert deletes > 0


def test_schedule_and_mix_validation():
    with pytest.raises(ValueError):
        build_schedule([], 1.0, 1, 3)
    with pytest.raises(ValueError):
        build_schedule([10, -1], 1.0, 1, 3)
    with pytest.raises(ValueError):
        build_schedule([10], 1.0, 1, 3, shape="sawtooth")
    with pytest.raises(ValueError):
        parse_mix("query:0.5,upsrt:0.5")
    with pytest.raises(ValueError):
        parse_mix("query:nope")
    with pytest.raises(ValueError):
        MixSpec(0.0, 0.0, 0.0)
    m = parse_mix("query:3,upsert:1")
    assert abs(m.query - 0.75) < 1e-12 and m.delete == 0.0


# ---------------------------------------------------------------------------
# knee + scrape units
# ---------------------------------------------------------------------------


def test_compute_knee_picks_highest_passing_step():
    steps = [
        {"rate": 10, "sent": 20, "p50_ms": 20.0, "p99_ms": 50.0,
         "bad_frac": 0.0},
        {"rate": 20, "sent": 40, "p50_ms": 40.0, "p99_ms": 100.0,
         "bad_frac": 0.01},
        {"rate": 40, "sent": 80, "p50_ms": 300.0, "p99_ms": 400.0,
         "bad_frac": 0.0},
        {"rate": 80, "sent": 80, "p50_ms": 30.0, "p99_ms": 60.0,
         "bad_frac": 0.5},
    ]
    assert lg_runner.compute_knee(steps, slo_ms=250) == 20.0
    # every step violating -> measured zero capacity, not "no data"
    assert lg_runner.compute_knee(steps, slo_ms=15) == 0.0
    # the quantile knob selects which latency column is judged
    assert lg_runner.compute_knee(steps, slo_ms=250,
                                  slo_quantile=0.5) == 20.0


def test_prom_scrape_parsing_sums_across_extra_labels():
    text = "\n".join([
        "# HELP kdtree_write_latency_ms x",
        "# TYPE kdtree_write_latency_ms histogram",
        'kdtree_write_latency_ms_count{op="upsert"} 5',
        'kdtree_write_latency_ms_sum{op="upsert"} 10.0',
        'kdtree_write_latency_ms_count{op="upsert",shard="1"} 3',
        'kdtree_write_latency_ms_sum{op="upsert",shard="1"} 6.0',
        "kdtree_epoch 2",
    ])
    parsed = lg_runner._parse_prom_lines(text)
    assert lg_runner._sum_series(
        parsed, "kdtree_write_latency_ms_count", 'op="upsert"') == 8
    assert lg_runner._sum_series(parsed, "kdtree_epoch") == 2
    assert lg_runner._sum_series(parsed, "kdtree_missing") is None
    # stateful gauges federate as one series PER shard/replica: the
    # fleet summary is the max (six replicas at epoch 1 are not
    # "epoch 6"), which is what scrape_server_block publishes
    multi = lg_runner._parse_prom_lines("\n".join([
        'kdtree_epoch{shard="0"} 1',
        'kdtree_epoch{shard="0",replica="1"} 1',
        'kdtree_epoch{shard="1"} 2',
    ]))
    assert lg_runner._max_series(multi, "kdtree_epoch") == 2
    assert lg_runner._max_series(multi, "kdtree_missing") is None


# ---------------------------------------------------------------------------
# open-loop independence against a scripted stub (no jax)
# ---------------------------------------------------------------------------


class _StubHandler(BaseHTTPRequestHandler):
    sleep_s = 0.0
    status = 200

    def log_message(self, fmt, *args):
        pass

    def _answer(self, code, obj):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path.startswith("/healthz"):
            self._answer(200, {"status": "ok", "n": 100, "dim": 3,
                               "k_max": 4, "id_offset": 0})
        else:
            self._answer(200, {})

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        self.rfile.read(length)
        if type(self).sleep_s:
            time.sleep(type(self).sleep_s)
        code = type(self).status
        if code != 200:
            self._answer(code, {"error": "scripted"})
            return
        if self.path == "/v1/knn":
            self._answer(200, {"ids": [[0]], "distances": [[0.0]],
                               "degraded": None})
        else:
            self._answer(200, {"applied": 1})


def _stub_server(sleep_s=0.0, status=200):
    class Handler(_StubHandler):
        pass

    Handler.sleep_s = sleep_s
    Handler.status = status
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def test_open_loop_arrivals_fire_on_schedule_despite_slow_service():
    """The coordinated-omission pin: a service 150 ms slow per request
    must neither delay the arrival schedule (send lag stays small) nor
    hide its queueing (intended latency carries the full 150 ms+)."""
    httpd, target = _stub_server(sleep_s=0.15)
    try:
        sched = build_schedule([20], 1.5, 11, 3, mix=MixSpec(1, 0, 0))
        ref = build_schedule([20], 1.5, 11, 3, mix=MixSpec(1, 0, 0))
        rep = lg_runner.run_load(target, sched, max_inflight=32,
                                 timeout_s=5.0, scrape=False)
        # the schedule the runner replayed is the one built BEFORE the
        # run — response latency cannot have touched it
        assert sched.keys() == ref.keys()
        step = rep["capacity"]["steps"][0]
        assert step["sent"] == step["intended"] > 0
        assert step["p50_ms"] >= 150.0
        assert step["send_lag_p99_ms"] < 120.0, step
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_runner_classifies_shed_and_errors():
    httpd, target = _stub_server(status=429)
    try:
        sched = build_schedule([30], 1.0, 2, 3, mix=MixSpec(1, 0, 0))
        rep = lg_runner.run_load(target, sched, scrape=False)
        step = rep["capacity"]["steps"][0]
        assert step["shed"] == step["sent"] > 0
        assert rep["capacity"]["knee_rate"] == 0.0
    finally:
        httpd.shutdown()
        httpd.server_close()
    httpd, target = _stub_server(status=500)
    try:
        sched = build_schedule([30], 1.0, 2, 3, mix=MixSpec(1, 0, 0))
        rep = lg_runner.run_load(target, sched, scrape=False)
        step = rep["capacity"]["steps"][0]
        assert step["errors"] == step["sent"] > 0
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_discover_reads_shard_and_router_shapes():
    httpd, target = _stub_server()
    try:
        facts = lg_runner.discover(target, retries=3)
        assert facts == {"dim": 3, "n": 100, "k_max": 4,
                         "write_base": 100}
    finally:
        httpd.shutdown()
        httpd.server_close()

    class RouterStub(_StubHandler):
        def do_GET(self):
            self._answer(200, {"status": "ok", "shards": [
                {"detail": {"dim": 3, "n": 50, "k_max": 4,
                            "id_offset": 0}},
                {"detail": {"dim": 3, "n": 70, "k_max": 8,
                            "id_offset": 50}},
            ]})

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), RouterStub)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        facts = lg_runner.discover(
            f"http://127.0.0.1:{httpd.server_address[1]}", retries=3)
        assert facts == {"dim": 3, "n": 120, "k_max": 4,
                         "write_base": 120}
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_discover_recurses_parent_router_breakdowns():
    """Under two-level routing a parent's healthz entries are CHILD
    ROUTERS whose detail is their own aggregated breakdown, not a leaf
    shard body: discover must recurse to the data-bearing leaves so a
    parent target sums n (and mins k_max) over the whole tree."""

    class ParentStub(_StubHandler):
        def do_GET(self):
            self._answer(200, {"status": "ok", "shards": [
                {"detail": {"status": "ok", "shards": [
                    {"detail": {"dim": 3, "n": 40, "k_max": 8,
                                "id_offset": 0}},
                    {"detail": {"dim": 3, "n": 60, "k_max": 4,
                                "id_offset": 40}},
                ]}},
                {"detail": {"status": "ok", "shards": [
                    {"detail": {"dim": 3, "n": 25, "k_max": 8,
                                "id_offset": 100}},
                ]}},
            ]})

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), ParentStub)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        facts = lg_runner.discover(
            f"http://127.0.0.1:{httpd.server_address[1]}", retries=3)
        assert facts == {"dim": 3, "n": 125, "k_max": 4,
                         "write_base": 125}
    finally:
        httpd.shutdown()
        httpd.server_close()


# ---------------------------------------------------------------------------
# e2e: a real serve process, mixed load, fault-injected slowdown
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def live_server():
    from kdtree_tpu.ops.generate import generate_points_rowwise
    from kdtree_tpu.ops.morton import build_morton
    from kdtree_tpu.serve import lifecycle, server as srv
    from kdtree_tpu.serve.faults import FaultSet

    tree = build_morton(generate_points_rowwise(7, 3, 4096))
    state = lifecycle.build_state(tree=tree, k=4, max_batch=64,
                                  max_delta_rows=1 << 20)
    httpd = srv.make_server(state, port=0, max_wait_ms=1.0,
                            faults=FaultSet(""))
    httpd.start(warmup_buckets=[8])
    yield httpd
    httpd.stop()


def _target(httpd):
    return f"http://127.0.0.1:{httpd.server_address[1]}"


def test_e2e_capacity_block_with_write_mix_and_fault_knee_drop(
        live_server, tmp_path):
    """The acceptance flow in-process: healthy ladder -> well-formed
    capacity block with server-side write evidence and a knee; then the
    SAME seed re-run under an injected latency fault -> identical
    schedule (open loop), measurably lower knee, and `trend` flags a
    NEW capacity-drop against the two reports."""
    from kdtree_tpu import obs
    from kdtree_tpu.obs import flight, trend as tr

    target = _target(live_server)
    facts = lg_runner.discover(target, retries=10)
    assert facts["dim"] == 3 and facts["write_base"] >= 4096

    def one_run(tag):
        sched = build_schedule(
            [25, 50, 100], 1.6, 13, facts["dim"],
            mix=MixSpec(0.8, 0.15, 0.05),
            write_base=facts["write_base"],
        )
        rep = lg_runner.run_load(target, sched, k=2, slo_ms=250.0,
                                 timeout_s=10.0)
        path = tmp_path / f"loadgen_{tag}.json"
        path.write_text(json.dumps(rep))
        return sched, rep, str(path)

    sched_ok, rep_ok, path_ok = one_run("healthy")
    cap = rep_ok["capacity"]
    assert cap["capacity_version"] == 1
    assert len(cap["steps"]) == 3
    assert cap["knee_rate"] > 0.0
    for step in cap["steps"]:
        assert step["sent"] > 0
        assert step["p99_ms"] is not None
        assert step["writes_ok"] > 0 or step["rate"] == 25.0
        # each step names its slowest exchange's trace id — the handle
        # `kdtree-tpu trace --id` resolves against the server's buffer
        assert step["slowest_trace_id"].startswith("lg13-")
        assert step["slowest_ms"] >= step["p99_ms"] * 0.5
    # server-side write-path evidence made it into the block
    server = cap["server"]
    assert server is not None
    assert server["write_latency_ms"]["upsert"]["count"] > 0
    # the offered rate threaded through to the serving process: gauge
    # set + a change-gated flight event per step (the SLO-PAGE dump
    # names the offered rate through exactly this pair)
    assert obs.get_registry().snapshot()["gauges"][
        "kdtree_loadgen_offered_rate"] == 100.0
    # the ring is bounded (older per-request events fall off under a
    # few hundred requests), so assert presence, not per-step counts
    kinds = Counter(e["type"] for e in flight.recorder().snapshot())
    assert kinds["loadgen.knee"] >= 1
    assert kinds["loadgen.rate"] >= 1 or kinds["loadgen.step"] >= 1

    # inject the slowdown (fault layer latency clause), same seed
    live_server.faults.set_spec("knn=latency:300")
    try:
        sched_slow, rep_slow, path_slow = one_run("slow")
    finally:
        live_server.faults.clear()
    assert sched_slow.keys() == sched_ok.keys(), \
        "response latency leaked into the arrival schedule"
    assert rep_slow["capacity"]["knee_rate"] < cap["knee_rate"]

    runs = [tr.load_run(path_ok), tr.load_run(path_slow)]
    findings, _ = tr.analyze(runs, band=0.3)
    rules = {f["rule"] for f in findings}
    assert "capacity-drop" in rules, findings
    # the committed baseline knows nothing about these labels -> NEW
    base = tr.load_baseline("trend_baseline.json")
    assert any(f["rule"] == "capacity-drop"
               for f in tr.partition(findings, base))


def test_compute_knee_rejects_unsupported_quantile():
    """The PR 12 satellite contract: a quantile the steps don't report
    is a ValueError naming the supported set — never a silent fall-back
    to p99 that contradicts the slo_quantile the artifact publishes."""
    steps = [{"rate": 10, "sent": 20, "p50_ms": 20.0, "p95_ms": 30.0,
              "p99_ms": 50.0, "bad_frac": 0.0}]
    for q in (0.9, 0.999, 0.0, 1.0):
        with pytest.raises(ValueError, match="0.5 / 0.95 / 0.99"):
            lg_runner.compute_knee(steps, slo_ms=250, slo_quantile=q)
    # the supported set passes
    for q in (0.5, 0.95, 0.99):
        assert lg_runner.compute_knee(steps, slo_ms=250,
                                      slo_quantile=q) == 10.0


def test_cli_rejects_unsupported_slo_quantile(capsys):
    """`kdtree-tpu loadgen --slo-quantile 0.9` fails BEFORE the sweep
    (and before the target is ever contacted — the bogus port proves
    it), with a crisp error naming the supported set."""
    from kdtree_tpu.utils import cli

    with pytest.raises(SystemExit) as e:
        cli.main(["loadgen", "--target", "http://127.0.0.1:9",
                  "--rates", "10", "--slo-quantile", "0.9"])
    assert e.value.code == 1
    err = capsys.readouterr().err
    assert "--slo-quantile must be 0.5, 0.95, or 0.99" in err
    assert "0.9" in err


# ---------------------------------------------------------------------------
# the recall dial in the harness (PR 14 satellite)
# ---------------------------------------------------------------------------


def test_parse_recall_mix_forms_and_validation():
    from kdtree_tpu.loadgen.schedule import parse_recall_mix

    assert parse_recall_mix(None) is None
    assert parse_recall_mix("") is None
    assert parse_recall_mix("exact") is None
    assert parse_recall_mix("0.99") == [(0.99, 1.0)]
    mix = parse_recall_mix("exact:1,0.99:2,0.9:1")
    assert [t for t, _ in mix] == [None, 0.99, 0.9]
    assert sum(w for _, w in mix) == pytest.approx(1.0)
    assert dict(mix)[0.99] == pytest.approx(0.5)
    for bad in ("1.2", "0.99:x", "nope:1", "0.99:-1", "exact:0"):
        with pytest.raises(ValueError):
            parse_recall_mix(bad)


def test_recall_mix_is_seeded_and_only_on_queries():
    from kdtree_tpu.loadgen.schedule import parse_recall_mix

    mix = parse_recall_mix("exact:0.5,0.9:0.5")
    a = build_schedule([200], 1.0, 3, 3, recall_mix=mix)
    b = build_schedule([200], 1.0, 3, 3, recall_mix=mix)
    assert a.keys() == b.keys()  # still a pure function of the seed
    targets = Counter(ar.recall for ar in a.arrivals
                      if ar.op == "query")
    assert set(targets) == {None, 0.9}
    assert min(targets.values()) > 0  # both gears actually drawn
    for ar in a.arrivals:
        if ar.op != "query":
            assert ar.recall is None  # writes carry no dial
    assert a.describe()["recall_mix"] == [["exact", 0.5], [0.9, 0.5]]
    # a recall mix is part of schedule identity: with vs without differ
    c = build_schedule([200], 1.0, 3, 3)
    assert a.keys() != c.keys()


def test_runner_sends_recall_target_and_records_gear_distribution():
    """The capacity block's per-step gear distribution: the runner
    forwards each query's recall_target and tallies the response's
    gear token (exact when a 200 carries none)."""
    from kdtree_tpu.loadgen.schedule import parse_recall_mix

    class Handler(_StubHandler):
        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            if self.path == "/v1/knn":
                out = {"ids": [[0]], "distances": [[0.0]],
                       "degraded": None}
                rt = payload.get("recall_target")
                if rt is not None:
                    out["gear"] = f"approx:{rt:g}"
                self._answer(200, out)
            else:
                self._answer(200, {"applied": 1})

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    target = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        sched = build_schedule(
            [80], 1.0, 5, 3, mix=MixSpec(1, 0, 0),
            recall_mix=parse_recall_mix("exact:0.5,0.9:0.5"),
        )
        rep = lg_runner.run_load(target, sched, scrape=False)
        step = rep["capacity"]["steps"][0]
        gears = step["gears"]
        assert set(gears) == {"exact", "approx:0.9"}
        assert sum(gears.values()) == step["ok"]
        # gear-echoed answers are NOT degraded: a kept contract
        assert step["degraded"] == 0
    finally:
        httpd.shutdown()
        httpd.server_close()


# ---------------------------------------------------------------------------
# fan-out fraction (ISSUE 15 satellite: the selective fan-out evidence
# in the capacity block — docs/SERVING.md "Spatial sharding")
# ---------------------------------------------------------------------------


def test_fanout_of_parses_router_and_shard_shapes():
    fo = lg_runner._fanout_of
    router_body = {"shards": {"total": 4, "contacted": 2,
                              "answered": 2}}
    assert fo("query", 200, router_body) == 0.5
    # pre-selective routers carry no contacted key: answered stands in
    assert fo("query", 200,
              {"shards": {"total": 3, "answered": 3}}) == 1.0
    assert fo("query", 200, {"ids": [[1]]}) is None  # plain shard
    assert fo("upsert", 200, router_body) is None    # writes: no gear
    assert fo("query", 429, router_body) is None     # failures
    assert fo("query", 200, {"shards": {"total": 0}}) is None


def test_fanout_frac_lands_in_steps_and_capacity():
    """A router-shaped stub answering a shards block: the per-step
    fanout_frac and the capacity-level mean are recorded; a plain
    shard target records None (absent, not 1.0)."""

    class RouterishStub(_StubHandler):
        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0))
            self.rfile.read(length)
            self._answer(200, {
                "ids": [[0]], "distances": [[0.0]], "degraded": None,
                "shards": {"total": 4, "contacted": 1, "answered": 1,
                           "missing": [], "pruned": 3},
            })

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), RouterishStub)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    target = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        sched = build_schedule([30], 1.0, 5, 3, mix=MixSpec(1, 0, 0))
        rep = lg_runner.run_load(target, sched, scrape=False)
        step = rep["capacity"]["steps"][0]
        assert step["fanout_frac"] == 0.25
        assert rep["capacity"]["fanout_frac"] == 0.25
    finally:
        httpd.shutdown()
        httpd.server_close()
    # plain shard stub: no shards block -> fraction absent
    httpd, target = _stub_server()
    try:
        sched = build_schedule([30], 1.0, 5, 3, mix=MixSpec(1, 0, 0))
        rep = lg_runner.run_load(target, sched, scrape=False)
        assert rep["capacity"]["steps"][0]["fanout_frac"] is None
        assert rep["capacity"]["fanout_frac"] is None
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_discover_write_base_respects_spatial_id_range():
    """A spatial shard serves GLOBAL morton-rank ids at id_offset 0:
    its occupied span is spatial.id_range, so write_base must clear
    the FLEET's id space, not this shard's [0, n)."""
    assert lg_runner._write_base_of(
        {"id_offset": 0, "n": 500,
         "spatial": {"id_range": [1500, 2000]}}) == 2000
    assert lg_runner._write_base_of({"id_offset": 100, "n": 50}) == 150
    # malformed spatial blocks fall back to offset + n
    assert lg_runner._write_base_of(
        {"id_offset": 0, "n": 7, "spatial": {"id_range": "x"}}) == 7

    class SpatialRouterStub(_StubHandler):
        def do_GET(self):
            self._answer(200, {"status": "ok", "shards": [
                {"detail": {"dim": 3, "n": 500, "k_max": 4,
                            "id_offset": 0,
                            "spatial": {"id_range": [0, 500]}}},
                {"detail": {"dim": 3, "n": 500, "k_max": 4,
                            "id_offset": 0,
                            "spatial": {"id_range": [500, 1000]}}},
            ]})

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), SpatialRouterStub)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        facts = lg_runner.discover(
            f"http://127.0.0.1:{httpd.server_address[1]}", retries=3)
        assert facts["write_base"] == 1000 and facts["n"] == 1000
    finally:
        httpd.shutdown()
        httpd.server_close()


# ---------------------------------------------------------------------------
# connection-reuse fraction + the A/B capacity block (PR 17 satellite)
# ---------------------------------------------------------------------------


def test_reuse_frac_window_math():
    assert lg_runner._reuse_frac((0.0, 0.0), (30.0, 10.0)) == 0.75
    # no leases in the window / a lost boundary scrape: absent, not 0
    assert lg_runner._reuse_frac((5.0, 5.0), (5.0, 5.0)) is None
    assert lg_runner._reuse_frac(None, (3.0, 1.0)) is None
    assert lg_runner._reuse_frac((3.0, 1.0), None) is None


def test_conn_reuse_frac_lands_in_steps_and_capacity():
    """A router-shaped stub publishing pool counters: the per-step and
    run-level conn_reuse_frac are computed from counter DELTAS across
    the step boundaries; a target without the families records None
    (absent evidence, never a fake zero)."""

    class PooledRouterStub(_StubHandler):
        posts = 0

        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0))
            self.rfile.read(length)
            type(self).posts += 1
            self._answer(200, {"ids": [[0]], "distances": [[0.0]],
                               "degraded": None})

        def do_GET(self):
            if self.path.startswith("/metrics"):
                # a constant 3:1 hit:miss ratio, so ANY window with
                # traffic reads 0.75 — the step-attribution jitter the
                # async boundary scrape allows cannot move the answer
                n = type(self).posts
                body = (f"kdtree_router_pool_hits_total {3 * n}\n"
                        f"kdtree_router_pool_misses_total {n}\n"
                        ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                super().do_GET()

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), PooledRouterStub)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    target = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        sched = build_schedule([30, 30], 1.0, 5, 3, mix=MixSpec(1, 0, 0))
        rep = lg_runner.run_load(target, sched, scrape=True)
        cap = rep["capacity"]
        assert cap["conn_reuse_frac"] == 0.75
        fracs = [s["conn_reuse_frac"] for s in cap["steps"]]
        assert all(f in (0.75, None) for f in fracs), fracs
        assert 0.75 in fracs  # at least one boundary pair survived
    finally:
        httpd.shutdown()
        httpd.server_close()
    # plain shard stub: no pool families -> fraction absent everywhere
    httpd, target = _stub_server()
    try:
        sched = build_schedule([30], 1.0, 5, 3, mix=MixSpec(1, 0, 0))
        rep = lg_runner.run_load(target, sched, scrape=True)
        assert rep["capacity"]["conn_reuse_frac"] is None
        assert rep["capacity"]["steps"][0]["conn_reuse_frac"] is None
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_cli_embeds_variant_and_ab_baseline(tmp_path, capsys):
    """The A/B loop end to end: arm 1 writes a report under --variant,
    arm 2 runs with --ab-baseline pointing at it and publishes the
    capacity.ab block the trend knee-drop rule judges."""
    from kdtree_tpu.utils import cli

    httpd, target = _stub_server()
    base_out = str(tmp_path / "base.json")
    cand_out = str(tmp_path / "cand.json")
    try:
        cli.main(["loadgen", "--target", target, "--rates", "40",
                  "--step-seconds", "0.5", "--mix", "query:1",
                  "--variant", "fresh", "--out", base_out])
        cli.main(["loadgen", "--target", target, "--rates", "40",
                  "--step-seconds", "0.5", "--mix", "query:1",
                  "--variant", "pooled", "--ab-baseline", base_out,
                  "--out", cand_out])
    finally:
        httpd.shutdown()
        httpd.server_close()
    capsys.readouterr()
    with open(base_out) as f:
        base = json.load(f)
    with open(cand_out) as f:
        cand = json.load(f)
    assert base["capacity"]["variant"] == "fresh"
    assert "ab" not in base["capacity"]
    ab = cand["capacity"]["ab"]
    assert ab["baseline_file"] == "base.json"
    assert ab["baseline_variant"] == "fresh"
    assert ab["baseline_knee_rate"] == base["capacity"]["knee_rate"]
    assert ab["knee_delta"] == pytest.approx(
        cand["capacity"]["knee_rate"] - base["capacity"]["knee_rate"])
    assert cand["capacity"]["variant"] == "pooled"


def test_cli_rejects_garbage_ab_baseline(tmp_path, capsys):
    """A bogus --ab-baseline fails BEFORE the sweep (and before the
    target is contacted — the bogus port proves it)."""
    from kdtree_tpu.utils import cli

    bad = tmp_path / "bad.json"
    bad.write_text("{\"not\": \"a capacity report\"}")
    with pytest.raises(SystemExit) as e:
        cli.main(["loadgen", "--target", "http://127.0.0.1:9",
                  "--rates", "10", "--ab-baseline", str(bad)])
    assert e.value.code == 1
    assert "missing capacity.knee_rate" in capsys.readouterr().err
    missing = tmp_path / "nope.json"
    with pytest.raises(SystemExit) as e:
        cli.main(["loadgen", "--target", "http://127.0.0.1:9",
                  "--rates", "10", "--ab-baseline", str(missing)])
    assert e.value.code == 1
    assert "cannot read --ab-baseline" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# the verb mix (ISSUE 19 satellite: docs/SERVING.md "Query verbs" —
# radius/range/count drawn per arrival, per-verb capacity columns)
# ---------------------------------------------------------------------------


def test_parse_verb_mix_forms_and_validation():
    from kdtree_tpu.loadgen.schedule import parse_verb_mix

    assert parse_verb_mix(None) is None
    assert parse_verb_mix("") is None
    mix = parse_verb_mix("knn:0.7,radius:0.2,count:0.1")
    assert [v for v, _ in mix] == ["knn", "radius", "count"]
    assert abs(sum(w for _, w in mix) - 1.0) < 1e-9
    for bad in ("upsert:1", "radius", "radius:x", "radius:-1", "knn:0"):
        with pytest.raises(ValueError):
            parse_verb_mix(bad)


def test_verb_mix_is_seeded_and_preserves_schedule_identity():
    """The draw is seeded and response-blind, rides only on queries, and
    happens ONLY when a mix is configured — an unmixed schedule stays
    byte-identical to what pre-verb loadgen built from the same seed."""
    from kdtree_tpu.loadgen.schedule import parse_verb_mix

    mix = parse_verb_mix("knn:0.5,radius:0.3,count:0.2")
    a = build_schedule([200], 1.0, 3, 3, verb_mix=mix)
    b = build_schedule([200], 1.0, 3, 3, verb_mix=mix)
    assert a.keys() == b.keys()
    verbs = Counter(ar.verb for ar in a.arrivals if ar.op == "query")
    assert set(verbs) == {"knn", "radius", "count"}
    for ar in a.arrivals:
        if ar.op != "query":
            assert ar.verb == "knn"  # writes never draw a verb
    # no mix -> no extra rng draw: identical to a pre-verb schedule
    assert build_schedule([200], 1.0, 3, 3).keys() \
        == build_schedule([200], 1.0, 3, 3, verb_mix=None).keys()
    desc = a.describe()
    assert desc["verbs"] == dict(verbs)
    assert [v for v, _ in desc["verb_mix"]] == ["knn", "radius", "count"]


def test_runner_routes_verbs_and_reports_per_verb_columns():
    """A mixed run hits the right endpoints with the configured radius,
    and the capacity block gains per-step per-verb columns plus a
    per-verb knee; an unmixed run's artifact carries neither key."""
    from kdtree_tpu.loadgen.schedule import parse_verb_mix

    seen = []

    class VerbStub(_StubHandler):
        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            seen.append((self.path, payload))
            if self.path == "/v1/knn":
                self._answer(200, {"ids": [[0]], "distances": [[0.0]],
                                   "degraded": None})
            elif self.path in ("/v1/radius", "/v1/range", "/v1/count"):
                out = {"counts": [1], "truncated": False,
                       "degraded": None}
                if self.path != "/v1/count":
                    out["ids"] = [[0]]
                self._answer(200, out)
            else:
                self._answer(200, {"applied": 1})

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), VerbStub)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    target = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        sched = build_schedule(
            [120], 1.0, 5, 3, mix=MixSpec(1, 0, 0),
            verb_mix=parse_verb_mix("knn:0.4,radius:0.3,count:0.3"))
        rep = lg_runner.run_load(target, sched, scrape=False,
                                 verb_radius=0.05)
        paths = Counter(p for p, _ in seen)
        assert paths["/v1/radius"] > 0 and paths["/v1/count"] > 0
        for p, payload in seen:
            if p in ("/v1/radius", "/v1/count"):
                assert payload["r"] == 0.05
        step = rep["capacity"]["steps"][0]
        assert set(step["verbs"]) == {"knn", "radius", "count"}
        assert sum(v["sent"] for v in step["verbs"].values()) \
            == step["ok"] + step["errors"] + step["timeouts"] \
            + step["shed"]
        for col in step["verbs"].values():
            assert {"sent", "ok", "goodput_rps", "bad_frac",
                    "p50_ms", "p95_ms", "p99_ms"} <= set(col)
        knees = rep["capacity"]["verbs"]
        assert set(knees) == {"knn", "radius", "count"}
        for verb in knees:
            assert knees[verb]["knee_rate"] == 120.0
    finally:
        httpd.shutdown()
        httpd.server_close()
    # unmixed: the artifact stays byte-compatible with pre-verb loadgen
    httpd, target = _stub_server()
    try:
        sched = build_schedule([30], 1.0, 5, 3, mix=MixSpec(1, 0, 0))
        rep = lg_runner.run_load(target, sched, scrape=False)
        assert "verbs" not in rep["capacity"]
        assert "verbs" not in rep["capacity"]["steps"][0]
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_trend_treats_changed_verb_mix_as_incommensurable():
    """The knee comparison must not cross a changed verb mix — same
    machinery as the gear gate: mixed-vs-mixed compares, changed mix
    does not mint a capacity-drop."""
    from kdtree_tpu.obs.trend import _capacity_facts

    def cap(verbs):
        steps = [{"rate": 100.0, "p99_ms": 5.0, "goodput_rps": 90.0}]
        if verbs is not None:
            steps[0]["verbs"] = {v: {"sent": 1} for v in verbs}
        return {"capacity_version": 1, "knee_rate": 100.0,
                "steps": steps}

    plain = _capacity_facts(cap(None))
    mixed = _capacity_facts(cap(["knn", "radius", "count"]))
    assert plain["verbs"] is None
    assert mixed["verbs"] == ["count", "knn", "radius"]
    # identical mixes stay comparable
    assert _capacity_facts(
        cap(["knn", "radius", "count"]))["verbs"] == mixed["verbs"]


# ---------------------------------------------------------------------------
# cost columns + the predicted-knee A/B (docs/OBSERVABILITY.md
# "Cost accounting & capacity headroom")
# ---------------------------------------------------------------------------


def test_cost_delta_arithmetic_and_absence():
    snap0 = {"knn/exact/ok": {"requests": 10.0, "device_ms": 20.0}}
    snap1 = {"knn/exact/ok": {"requests": 25.0, "device_ms": 80.0},
             "radius/exact/ok": {"requests": 5.0, "device_ms": 10.0}}
    d = lg_runner._cost_delta(snap0, snap1)
    assert d["knn/exact/ok"] == {"requests": 15, "device_ms": 60.0,
                                 "cost_ms": 4.0}
    # a class born mid-window deltas against zero
    assert d["radius/exact/ok"]["requests"] == 5
    # missing snapshots and empty windows are None, never fake zeros
    assert lg_runner._cost_delta(None, snap1) is None
    assert lg_runner._cost_delta(snap0, None) is None
    assert lg_runner._cost_delta(snap1, snap1) is None


def test_scrape_cost_classes_sums_federation_labels():
    text = "\n".join([
        '# TYPE kdtree_cost_requests_total counter',
        'kdtree_cost_requests_total{shard="0",gear="exact",'
        'outcome="ok",verb="knn"} 3',
        'kdtree_cost_requests_total{shard="1",gear="exact",'
        'outcome="ok",verb="knn"} 4',
        'kdtree_cost_device_ms_total{shard="0",gear="exact",'
        'outcome="ok",verb="knn"} 9.5',
        'kdtree_cost_device_ms_total{shard="1",gear="exact",'
        'outcome="ok",verb="knn"} 2.5',
        'kdtree_cost_requests_total{gear="approx",outcome="ok",'
        'verb="radius"} 2',
    ])
    classes = lg_runner._parse_cost_classes(text)
    assert classes["knn/exact/ok"]["requests"] == 7.0
    assert classes["knn/exact/ok"]["device_ms"] == 12.0
    assert classes["radius/approx/ok"]["requests"] == 2.0


def test_cost_columns_and_predicted_block_e2e(live_server):
    """Each ladder step carries the boundary-scraped per-class cost
    deltas, and the capacity block carries the headroom model's
    predicted rate judged against the measured knee."""
    target = _target(live_server)
    facts = lg_runner.discover(target, retries=10)
    sched = build_schedule([40, 80], 1.5, 29, facts["dim"])
    rep = lg_runner.run_load(target, sched, k=2, slo_ms=250.0,
                             timeout_s=10.0, knee_band=4.0)
    cap = rep["capacity"]
    costed = [s for s in cap["steps"] if s.get("costs")]
    assert costed, cap["steps"]
    for s in costed:
        for ck, ent in s["costs"].items():
            verb, gear, outcome = ck.split("/")
            assert ent["requests"] > 0
            assert ent["cost_ms"] == pytest.approx(
                ent["device_ms"] / ent["requests"], rel=1e-2)
    pred = cap["predicted"]
    assert pred["cost_per_query_ms"] > 0
    assert pred["predicted_rate"] == pytest.approx(
        1000.0 / pred["cost_per_query_ms"], rel=1e-2)
    assert pred["band"] == 4.0
    assert pred["knee_rate"] == cap["knee_rate"]
    assert pred["within_band"] in (True, False)
    assert any(ck.startswith("knn/") for ck in pred["classes"])
