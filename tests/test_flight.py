"""Flight recorder (docs/OBSERVABILITY.md "Flight recorder"): the
always-on ring must wrap correctly under concurrency, dump atomically
and parseably (SIGUSR2 included), fire on the serve error path, and stay
in the host-cheap telemetry tier."""

from __future__ import annotations

import json
import os
import signal
import threading
import time

import numpy as np
import pytest

from kdtree_tpu.obs import flight


def test_ring_wraps_and_reports_dropped():
    rec = flight.FlightRecorder(capacity=8)
    for i in range(20):
        rec.record("e", i=i)
    snap = rec.snapshot()
    assert len(snap) == 8
    # oldest events fell off the front; the newest 8 survive, in order
    assert [e["i"] for e in snap] == list(range(12, 20))
    st = rec.stats()
    assert st["events"] == 8 and st["dropped"] == 12
    rep = rec.report("unit")
    assert rep["dropped"] == 12 and rep["reason"] == "unit"


def test_ring_concurrent_writers_lose_nothing_within_capacity():
    rec = flight.FlightRecorder(capacity=4096)
    threads = [
        threading.Thread(
            target=lambda t=t: [rec.record("e", t=t, i=i)
                                for i in range(256)]
        )
        for t in range(8)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    snap = rec.snapshot()
    assert len(snap) == 8 * 256
    # seq is the global order stamp: strictly increasing, gap-free
    seqs = [e["seq"] for e in snap]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    # every writer's own stream arrives complete and in its own order
    for t in range(8):
        mine = [e["i"] for e in snap if e["t"] == t]
        assert mine == list(range(256))


def test_record_never_raises_on_unserializable_fields(tmp_path):
    rec = flight.FlightRecorder(capacity=4)
    rec.record("weird", obj=object())  # not JSON-serializable
    rec.record("ok", x=1)
    # the dump must still produce a parseable file: the default=str
    # fallback is deliberate — never lose the parseable ring to one field
    path = rec.dump(str(tmp_path / "f.json"), reason="unit")
    data = json.loads(open(path).read())
    assert [e["type"] for e in data["events"]] == ["weird", "ok"]


def test_dump_atomic_and_parseable(tmp_path):
    rec = flight.FlightRecorder(capacity=16)
    for i in range(5):
        rec.record("evt", i=i)
    path = str(tmp_path / "flight.json")
    out = rec.dump(path, reason="test")
    assert out == path
    data = json.loads(open(path).read())
    assert data["flight_version"] == flight.DUMP_VERSION
    assert data["pid"] == os.getpid()
    assert [e["i"] for e in data["events"]] == list(range(5))
    # no tmp litter: the write is tmp + os.replace
    assert [f for f in os.listdir(tmp_path) if "tmp" in f] == []


def test_sigusr2_dump_end_to_end(tmp_path, monkeypatch):
    monkeypatch.setenv("KDTREE_TPU_FLIGHT_DIR", str(tmp_path))
    assert flight.install_signal_handler()
    flight.record("before-signal", marker=1234)
    signal.raise_signal(signal.SIGUSR2)
    path = tmp_path / "flight-sigusr2.json"
    assert path.exists()
    data = json.loads(path.read_text())
    assert data["reason"] == "sigusr2"
    assert any(e.get("marker") == 1234 for e in data["events"])
    # concurrent writers + a second signal: the dump must stay parseable
    stop = threading.Event()

    def spam():
        while not stop.is_set():
            flight.record("spam")

    th = threading.Thread(target=spam)
    th.start()
    try:
        signal.raise_signal(signal.SIGUSR2)
        data = json.loads(path.read_text())
        assert data["flight_version"] == flight.DUMP_VERSION
    finally:
        stop.set()
        th.join()


def test_sigusr2_while_main_thread_holds_ring_lock(tmp_path, monkeypatch):
    """Deadlock regression: the signal handler runs on the MAIN thread
    between any two bytecodes — including inside record()'s critical
    section. The ring lock is reentrant so the dump completes instead of
    hanging the process on its own lock."""
    monkeypatch.setenv("KDTREE_TPU_FLIGHT_DIR", str(tmp_path))
    assert flight.install_signal_handler()
    flight.record("locked-section-marker")
    with flight.recorder()._lock:  # the interrupted critical section
        signal.raise_signal(signal.SIGUSR2)
    path = tmp_path / "flight-sigusr2.json"
    assert path.exists()
    data = json.loads(path.read_text())
    assert any(e["type"] == "locked-section-marker" for e in data["events"])


def test_env_capacity_defaults_on_garbage(monkeypatch):
    """A malformed KDTREE_TPU_FLIGHT_EVENTS must default, not crash the
    import that every instrumented module performs."""
    monkeypatch.setenv("KDTREE_TPU_FLIGHT_EVENTS", "abc")
    assert flight._env_capacity() == flight.DEFAULT_CAPACITY
    monkeypatch.setenv("KDTREE_TPU_FLIGHT_EVENTS", "0")
    assert flight._env_capacity() == flight.DEFAULT_CAPACITY
    monkeypatch.setenv("KDTREE_TPU_FLIGHT_EVENTS", "17")
    assert flight._env_capacity() == 17
    monkeypatch.delenv("KDTREE_TPU_FLIGHT_EVENTS")
    assert flight._env_capacity() == flight.DEFAULT_CAPACITY


def test_auto_dump_rate_limited_and_disableable(tmp_path, monkeypatch):
    monkeypatch.setenv("KDTREE_TPU_FLIGHT_DIR", str(tmp_path))
    rec = flight.FlightRecorder(capacity=4)
    rec.record("x")
    first = rec.auto_dump("unit-reason")
    assert first and os.path.exists(first)
    # within the rate-limit window the second dump is suppressed...
    assert rec.auto_dump("unit-reason") is None
    # ...unless forced (the operator's SIGUSR2 path)
    assert rec.auto_dump("unit-reason", force=True) == first
    # disabled dir -> no file, no error
    monkeypatch.setenv("KDTREE_TPU_FLIGHT_DIR", "none")
    assert rec.auto_dump("other-reason") is None


def test_reset_dump_rate_limit_unblocks_every_reason(tmp_path,
                                                     monkeypatch):
    """The conftest isolation hook: clearing the limiter makes the next
    auto_dump of ANY reason write immediately — this is what decouples
    the shed-burst test here from test_slo's flood e2e (the PR 9
    collection-order gotcha)."""
    monkeypatch.setenv("KDTREE_TPU_FLIGHT_DIR", str(tmp_path))
    rec = flight.FlightRecorder(capacity=4)
    rec.record("evt")
    assert rec.auto_dump("iso-reason") is not None
    assert rec.auto_dump("iso-reason") is None  # rate-limited
    rec.reset_dump_rate_limit()
    assert rec.auto_dump("iso-reason") is not None


def test_burst_detector_fires_on_burst_not_trickle():
    det = flight.BurstDetector(threshold=5, window_s=10.0)
    fired = [det.mark() for _ in range(5)]
    assert fired == [False, False, False, False, True]
    # after firing, the window restarts — the next mark alone cannot fire
    assert det.mark() is False
    # a trickle slower than the window never fires
    slow = flight.BurstDetector(threshold=3, window_s=0.001)
    fired = []
    for _ in range(6):
        fired.append(slow.mark())
        time.sleep(0.005)
    assert fired == [False] * 6


def test_span_completions_land_in_ring():
    from kdtree_tpu import obs

    rec = flight.recorder()
    before = rec.stats()["events"] + rec.stats()["dropped"]
    with obs.span("flighttest.section", sync=False):
        pass
    snap = rec.snapshot()
    mine = [e for e in snap if e.get("span") == "flighttest.section"]
    assert mine and mine[-1]["type"] == "span"
    assert mine[-1]["seconds"] >= 0.0
    assert rec.stats()["events"] + rec.stats()["dropped"] > before


def test_serve_error_path_triggers_ring_event_and_dump(tmp_path, monkeypatch):
    """A batch-dispatch failure must leave a serve.batch_error event AND
    an incident dump file (the tentpole's serve-error trigger)."""
    monkeypatch.setenv("KDTREE_TPU_FLIGHT_DIR", str(tmp_path))
    from kdtree_tpu.serve.admission import AdmissionQueue, PendingRequest
    from kdtree_tpu.serve.batcher import MicroBatcher

    class BoomEngine:
        def knn_batch(self, q):
            raise RuntimeError("boom")

        def fallback_knn(self, q, k):
            raise RuntimeError("boom-fallback")

    queue = AdmissionQueue(max_rows=64)
    b = MicroBatcher(BoomEngine(), queue, max_batch=8, max_wait_ms=1.0)
    req = PendingRequest(np.zeros((2, 3), np.float32), k=1,
                         trace_id="trace-boom")
    b.start()
    try:
        queue.submit(req)
        assert req.event.wait(timeout=30.0)
    finally:
        b.stop()
    assert req.error is not None and "boom" in req.error
    events = flight.recorder().snapshot()
    errs = [e for e in events if e["type"] == "serve.batch_error"]
    assert errs and "trace-boom" in errs[-1]["traces"]
    # incident dumps serialize on a background writer thread (the batch
    # worker must not pay file I/O inline) — poll, don't assert instantly
    dump = tmp_path / "flight-serve-error.json"
    deadline = time.monotonic() + 30.0
    while not dump.exists() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert dump.exists()
    data = json.loads(dump.read_text())
    assert data["reason"] == "serve-error"


def test_shed_burst_triggers_dump(tmp_path, monkeypatch):
    monkeypatch.setenv("KDTREE_TPU_FLIGHT_DIR", str(tmp_path))
    from kdtree_tpu.serve.admission import (
        SHED_BURST_THRESHOLD,
        AdmissionQueue,
        PendingRequest,
        QueueFullError,
    )

    queue = AdmissionQueue(max_rows=1)
    blocker = PendingRequest(np.zeros((1, 3), np.float32), k=1)
    queue.submit(blocker)  # fills the budget; everything below sheds
    for _ in range(SHED_BURST_THRESHOLD):
        with pytest.raises(QueueFullError):
            queue.submit(PendingRequest(np.zeros((1, 3), np.float32), k=1,
                                        trace_id="shedder"))
    dump = tmp_path / "flight-serve-shed-burst.json"
    deadline = time.monotonic() + 30.0  # async writer thread — poll
    while not dump.exists() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert dump.exists()
    data = json.loads(dump.read_text())
    sheds = [e for e in data["events"] if e["type"] == "serve.shed"]
    assert len(sheds) >= SHED_BURST_THRESHOLD


def test_recorder_overhead_is_host_cheap():
    """The always-on tier promise: recording is a dict build + locked
    deque append. Budget is generous for CI-container noise but still
    orders of magnitude below anything that could move a <2% bench
    overhead bar (events are per span/batch/request, never per row)."""
    rec = flight.FlightRecorder(capacity=1024)
    n = 20_000
    t0 = time.perf_counter()
    for i in range(n):
        rec.record("bench", i=i, rows=128, plan="warm")
    per_event = (time.perf_counter() - t0) / n
    assert per_event < 50e-6, f"record() cost {per_event * 1e6:.1f}us/event"
