"""Mutable index (docs/SERVING.md "Mutable index"): the LSM-style delta
buffer + tombstones + epoch rebuild must never change an answer.

The contract under test is the exactness invariant: after ANY
interleaving of upserts, deletes, and queries — including the k-boundary
case where the deleted point was the k-th hit, and including queries
in flight across an epoch swap — the engine's answer is byte-identical
(distances AND ids) to a rebuild-from-scratch index over the surviving
points. Epoch mechanics (threshold trigger fires exactly once, swap is
atomic between batches, journal replay loses nothing) are pinned on
top of that.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from kdtree_tpu import obs
from kdtree_tpu.mutable import DeltaBuffer, MutableEngine, merge_rows
from kdtree_tpu.serve import lifecycle, server as srv
from kdtree_tpu.serve.lifecycle import ServeEngine

DIM, N, K = 3, 512, 4
SEED = 7


@pytest.fixture(scope="module")
def base_points():
    from kdtree_tpu.ops.generate import generate_points_rowwise

    return np.asarray(generate_points_rowwise(SEED, DIM, N))


@pytest.fixture(scope="module")
def queries():
    from kdtree_tpu.ops.generate import generate_points_rowwise

    return np.asarray(
        generate_points_rowwise(11, DIM, 8), dtype=np.float32
    )


def fresh_engine(points, **kw) -> MutableEngine:
    import jax.numpy as jnp

    from kdtree_tpu.ops.morton import build_morton

    kw.setdefault("max_delta_rows", 1 << 30)
    kw.setdefault("max_delta_frac", 0.0)
    return MutableEngine(
        ServeEngine(build_morton(jnp.asarray(points)), K), **kw
    )


def oracle_answer(model, queries, k=K):
    """The rebuild-from-scratch oracle: a fresh Morton index over the
    surviving points (original ids preserved), queried through the same
    serving facade."""
    import jax.numpy as jnp

    from kdtree_tpu.ops.morton import morton_view

    ids = np.array(sorted(model), dtype=np.int64)
    pts = np.stack([model[i] for i in ids.tolist()]).astype(np.float32)
    tree = morton_view(
        jnp.asarray(pts), gid=jnp.asarray(ids.astype(np.int32)),
        n_real=int(ids.size),
    )
    d2, gids, _ = ServeEngine(tree, k).knn_batch(queries)
    return d2, gids


def assert_exact(eng, model, queries, tag=""):
    d2, ids, _ = eng.knn_batch(queries)
    od2, oids = oracle_answer(model, queries)
    np.testing.assert_array_equal(ids, oids, err_msg=f"ids differ ({tag})")
    np.testing.assert_array_equal(d2, od2, err_msg=f"d2 differ ({tag})")


def _counter(key):
    return obs.get_registry().snapshot()["counters"].get(key, 0.0)


# ---------------------------------------------------------------------------
# units: delta buffer + merge
# ---------------------------------------------------------------------------


def test_delta_buffer_put_update_drop_grow():
    buf = DeltaBuffer(dim=2, min_capacity=2)
    assert buf.capacity >= 64  # floor guards capacity >= any sane k
    cap0 = buf.capacity
    assert buf.put(5, np.array([1.0, 2.0]))       # fresh
    assert not buf.put(5, np.array([3.0, 4.0]))   # update, same slot
    assert buf.rows == 1
    np.testing.assert_array_equal(buf.get(5), [3.0, 4.0])
    assert buf.drop(5) and not buf.drop(5)
    assert buf.rows == 0 and buf.get(5) is None
    # growth doubles the pow2 capacity and keeps every live row
    for i in range(cap0 + 1):
        buf.put(100 + i, np.array([float(i), 0.0]))
    assert buf.capacity == 2 * cap0 and buf.rows == cap0 + 1
    np.testing.assert_array_equal(buf.get(100), [0.0, 0.0])


def test_delta_view_is_a_stable_snapshot():
    buf = DeltaBuffer(dim=2)
    buf.put(1, np.array([1.0, 1.0]))
    buf.refresh()
    pts_a, gid_a = buf.view()
    buf.put(2, np.array([2.0, 2.0]))
    buf.refresh()
    pts_b, gid_b = buf.view()
    # the old snapshot still describes the old state: a query that
    # grabbed it before the write must not see a half-applied buffer
    assert gid_a.tolist().count(2) == 0
    assert gid_b.tolist().count(2) == 1
    assert np.isinf(np.asarray(pts_a)[1]).all()


def test_merge_rows_distance_id_order_and_padding():
    d2 = np.array([[0.5, np.inf, 0.25], [1.0, 1.0, np.inf]],
                  dtype=np.float32)
    ids = np.array([[7, -1, 9], [3, 1, -1]], dtype=np.int32)
    md, mi = merge_rows(d2, ids, k=2)
    assert mi.tolist() == [[9, 7], [1, 3]]  # ties break by id
    assert md.tolist() == [[0.25, 0.5], [1.0, 1.0]]
    # fewer real candidates than k: (inf, -1) padding survives, last
    md, mi = merge_rows(d2[:1], ids[:1], k=3)
    assert mi.tolist() == [[9, 7, -1]]
    assert md[0, 2] == np.inf


# ---------------------------------------------------------------------------
# exactness: interleavings vs the rebuild-from-scratch oracle
# ---------------------------------------------------------------------------


def test_interleaved_mutations_byte_identical_to_oracle(base_points,
                                                        queries):
    eng = fresh_engine(base_points)
    model = {i: base_points[i].copy() for i in range(N)}
    rng = np.random.default_rng(0)
    assert_exact(eng, model, queries, "pristine (empty overlay)")
    # inserts: brand-new ids beyond the main range
    new_ids = np.arange(N, N + 24)
    new_pts = rng.uniform(-100, 100, (24, DIM)).astype(np.float32)
    eng.upsert(new_ids, new_pts)
    for i, p in zip(new_ids.tolist(), new_pts):
        model[i] = p
    assert_exact(eng, model, queries, "after inserts")
    # updates: move existing main points (shadow the main copy)
    mv_ids = np.array([1, 50, 200])
    mv_pts = rng.uniform(-100, 100, (3, DIM)).astype(np.float32)
    eng.upsert(mv_ids, mv_pts)
    for i, p in zip(mv_ids.tolist(), mv_pts):
        model[i] = p
    assert_exact(eng, model, queries, "after moves")
    # deletes across both tiers: a main id and a delta id
    eng.delete(np.array([3, int(new_ids[0])]))
    model.pop(3), model.pop(int(new_ids[0]))
    assert_exact(eng, model, queries, "after mixed deletes")
    # delete a moved id: both its delta copy and its masked main slot die
    eng.delete(np.array([1]))
    model.pop(1)
    assert_exact(eng, model, queries, "after deleting a moved id")
    eng.close()


def test_tombstone_at_k_boundary(base_points, queries):
    """Delete exactly the k-th hit of a query row: the masked slot's
    replacement (the true (k+1)-th point) must surface — the correction
    path, not just masking."""
    eng = fresh_engine(base_points)
    model = {i: base_points[i].copy() for i in range(N)}
    before = _counter("kdtree_mutable_corrections_total")
    d2, ids, _ = eng.knn_batch(queries)
    victim = int(ids[0, K - 1])     # row 0's k-th hit
    eng.delete(np.array([victim]))
    model.pop(victim)
    assert_exact(eng, model, queries, "k-th hit deleted")
    assert _counter("kdtree_mutable_corrections_total") > before
    # and the 1st hit too — the strongest boundary
    d2, ids, _ = eng.knn_batch(queries)
    victim = int(ids[0, 0])
    eng.delete(np.array([victim]))
    model.pop(victim)
    assert_exact(eng, model, queries, "1st hit deleted")
    eng.close()


def test_fallback_path_exact_over_surviving(base_points, queries):
    """The brute-force degradation path (deadline/oversized answers)
    must apply the same overlay: masked main + delta, merged."""
    eng = fresh_engine(base_points)
    model = {i: base_points[i].copy() for i in range(N)}
    rng = np.random.default_rng(1)
    ins = rng.uniform(-100, 100, (5, DIM)).astype(np.float32)
    eng.upsert(np.arange(N, N + 5), ins)
    for i, p in zip(range(N, N + 5), ins):
        model[i] = p
    eng.delete(np.array([0, 7]))
    model.pop(0), model.pop(7)
    d2, ids = eng.fallback_knn(queries, K)
    od2, oids = oracle_answer(model, queries)
    np.testing.assert_array_equal(ids, oids)
    np.testing.assert_array_equal(d2, od2)
    eng.close()


def test_write_validation():
    eng = fresh_engine(np.arange(30.0).reshape(10, 3).astype(np.float32))
    with pytest.raises(ValueError, match="duplicate"):
        eng.upsert(np.array([1, 1]), np.zeros((2, 3), np.float32))
    with pytest.raises(ValueError, match="int32"):
        eng.upsert(np.array([2**31]), np.zeros((1, 3), np.float32))
    with pytest.raises(ValueError, match=">= 0|\\[0,"):
        eng.delete(np.array([-1]))
    with pytest.raises(ValueError, match="3-D"):
        eng.upsert(np.array([1]), np.zeros((1, 2), np.float32))
    eng.close()
    with pytest.raises(RuntimeError, match="closed"):
        eng.delete(np.array([1]))


# ---------------------------------------------------------------------------
# epoch rebuild
# ---------------------------------------------------------------------------


def test_delta_overflow_triggers_rebuild_exactly_once(base_points,
                                                      queries):
    eng = fresh_engine(base_points, max_delta_rows=16)
    model = {i: base_points[i].copy() for i in range(N)}
    rng = np.random.default_rng(2)
    before = _counter("kdtree_mutable_rebuilds_total")
    ids = np.arange(N, N + 16)
    pts = rng.uniform(-100, 100, (16, DIM)).astype(np.float32)
    eng.upsert(ids, pts)   # backlog 16 >= threshold 16: trigger
    for i, p in zip(ids.tolist(), pts):
        model[i] = p
    deadline = time.monotonic() + 120
    while eng.epoch < 1 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert eng.epoch == 1, eng.stats()
    st = eng.stats()
    assert st["delta_rows"] == 0 and st["tombstones"] == 0
    assert st["n"] == N + 16
    assert _counter("kdtree_mutable_rebuilds_total") == before + 1
    assert_exact(eng, model, queries, "post-swap")
    # one more write below the threshold: no second rebuild
    eng.upsert(np.array([N + 100]),
               rng.uniform(-100, 100, (1, DIM)).astype(np.float32))
    time.sleep(0.3)
    assert eng.epoch == 1
    assert _counter("kdtree_mutable_rebuilds_total") == before + 1
    eng.close()


def test_writes_during_rebuild_replay_onto_new_epoch(base_points,
                                                     queries):
    """The journal: writes landing while the compaction runs apply live
    AND survive the swap — nothing lost, nothing doubled."""
    eng = fresh_engine(base_points, max_delta_rows=8)
    model = {i: base_points[i].copy() for i in range(N)}
    rng = np.random.default_rng(3)
    # slow the compaction down so the mid-rebuild writes land in the
    # journal deterministically
    orig = eng._compact
    gate = threading.Event()

    def slow_compact(*a, **kw):
        gate.wait(timeout=30)
        return orig(*a, **kw)

    eng._compact = slow_compact
    ids = np.arange(N, N + 8)
    pts = rng.uniform(-100, 100, (8, DIM)).astype(np.float32)
    eng.upsert(ids, pts)   # triggers; compaction parked on the gate
    for i, p in zip(ids.tolist(), pts):
        model[i] = p
    assert eng.stats()["rebuilding"]
    # mid-rebuild traffic: an insert and a delete
    eng.upsert(np.array([N + 50]),
               np.array([[55.0, 55.0, 55.0]], np.float32))
    model[N + 50] = np.array([55.0, 55.0, 55.0], np.float32)
    eng.delete(np.array([int(ids[0]), 9]))
    model.pop(int(ids[0])), model.pop(9)
    assert_exact(eng, model, queries, "mid-rebuild (live overlay)")
    gate.set()
    deadline = time.monotonic() + 120
    while eng.epoch < 1 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert eng.epoch == 1
    assert_exact(eng, model, queries, "post-swap with journal replay")
    st = eng.stats()
    # the replayed writes live on as the new epoch's overlay: the N+50
    # insert is a delta row, and BOTH deletes are tombstones — ids[0]
    # was compacted into the new main, so its delete masks the new copy
    assert st["delta_rows"] == 1 and st["tombstones"] == 2
    eng.close()


def test_epoch_swap_under_concurrent_queries_every_answer_exact(
    base_points, queries,
):
    """Queries hammering across the swap: every single answer —
    pre-swap overlay, post-swap fresh tree, and anything in between —
    must be byte-identical to the oracle; every call must answer
    exactly once (no drops, no doubles)."""
    eng = fresh_engine(base_points)
    model = {i: base_points[i].copy() for i in range(N)}
    rng = np.random.default_rng(4)
    ids = np.arange(N, N + 12)
    pts = rng.uniform(-100, 100, (12, DIM)).astype(np.float32)
    eng.upsert(ids, pts)
    for i, p in zip(ids.tolist(), pts):
        model[i] = p
    eng.delete(np.array([5, 6]))
    model.pop(5), model.pop(6)
    od2, oids = oracle_answer(model, queries)
    # next write triggers: the backlog already equals the new threshold
    eng.max_delta_rows = eng.stats()["delta_rows"] + \
        eng.stats()["tombstones"]
    orig = eng._compact

    def slow_compact(*a, **kw):
        time.sleep(0.4)   # guarantee queries overlap the rebuild window
        return orig(*a, **kw)

    eng._compact = slow_compact
    stop = threading.Event()
    failures: list = []
    counts = [0, 0, 0]

    def qworker(slot):
        while not stop.is_set():
            d2, rids, _ = eng.knn_batch(queries)
            if not (np.array_equal(d2, od2) and np.array_equal(rids,
                                                               oids)):
                failures.append((slot, rids.tolist()))
                return
            counts[slot] += 1

    threads = [threading.Thread(target=qworker, args=(i,))
               for i in range(3)]
    for t in threads:
        t.start()
    # the trigger is a content-no-op: re-upsert an existing delta id
    # with its existing coordinates — backlog crosses, answers don't
    eng.upsert(ids[:1], pts[:1])
    deadline = time.monotonic() + 120
    while eng.epoch < 1 and time.monotonic() < deadline:
        time.sleep(0.02)
    time.sleep(0.2)   # a little post-swap traffic too
    stop.set()
    for t in threads:
        t.join()
    assert not failures, failures[:1]
    assert eng.epoch == 1
    assert all(c > 0 for c in counts), counts
    assert_exact(eng, model, queries, "steady state after swap")
    eng.close()


def test_churn_counts_toward_backlog_and_compacts(base_points, queries):
    """Upsert-then-delete churn leaves dropped slots the buffer never
    reuses; they must count toward the backlog so a compaction reclaims
    them — otherwise capacity doubles forever while delta_rows reads 0."""
    eng = fresh_engine(base_points, max_delta_rows=16)
    model = {i: base_points[i].copy() for i in range(N)}
    rng = np.random.default_rng(8)
    for i in range(8):   # 8 upsert+delete pairs of delta-only ids
        gid = N + 1000 + i
        eng.upsert(np.array([gid]),
                   rng.uniform(-100, 100, (1, DIM)).astype(np.float32))
        eng.delete(np.array([gid]))
    st = eng.stats()
    assert st["delta_rows"] == 0 and st["tombstones"] == 0
    assert st["backlog"] == 8  # the holes ARE the backlog
    for i in range(8):   # 8 more pairs cross the threshold
        gid = N + 2000 + i
        eng.upsert(np.array([gid]),
                   rng.uniform(-100, 100, (1, DIM)).astype(np.float32))
        eng.delete(np.array([gid]))
    deadline = time.monotonic() + 120
    while eng.epoch < 1 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert eng.epoch == 1
    st = eng.stats()
    # the holes are reclaimed; the one leftover unit is the final
    # pair's delete, which lands relative to the NEW epoch (its id was
    # compacted into the new main, so the delete is now a tombstone)
    assert st["delta_rows"] == 0 and st["backlog"] <= 1
    assert eng._state.delta.holes == 0
    assert_exact(eng, model, queries, "after churn compaction")
    eng.close()


def test_requested_k_survives_growth_past_bootstrap_size():
    """A tiny bootstrap index must not pin k forever: the CONFIGURED k
    is the request contract from the first batch (small epochs pad with
    (inf, -1)), k_effective reports what actually exists, and a rebuilt
    epoch over enough points serves the full k with no padding."""
    import jax.numpy as jnp

    from kdtree_tpu.ops.morton import build_morton

    seed = np.arange(15.0).reshape(5, 3).astype(np.float32)
    eng = MutableEngine(ServeEngine(build_morton(jnp.asarray(seed)), 16),
                        max_delta_rows=40, max_delta_frac=0.0,
                        requested_k=16)
    # the configured k IS the contract — no bootstrap clamp on the cap
    assert eng.k == 16
    assert eng.k_effective == 5  # only 5 live points to return yet
    q = np.zeros((1, 3), dtype=np.float32)
    d2, ids, _ = eng.knn_batch(q)
    assert d2.shape == (1, 16) and ids.shape == (1, 16)
    assert (ids[:, 5:] == -1).all() and np.isinf(d2[:, 5:]).all()
    rng = np.random.default_rng(6)
    eng.upsert(np.arange(5, 45),
               rng.uniform(-100, 100, (40, 3)).astype(np.float32))
    deadline = time.monotonic() + 120
    while eng.epoch < 1 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert eng.epoch == 1
    assert eng.k == 16 and eng.k_effective == 16  # 45 points now
    d2, ids, _ = eng.knn_batch(q)
    assert (ids >= 0).all()  # full k of real neighbors, no padding
    eng.close()


def test_configured_k_survives_deletes_below_k(base_points, queries):
    """The PR 10 carried-forward gotcha, pinned: deletes pushing the
    live count below --k must not shrink k_max (the /v1/knn request
    cap) — neither before NOR after the compaction. Answers pad with
    (inf, -1), and healthz-visible stats report configured vs
    effective k."""
    pts = base_points[:8]
    eng = fresh_engine(pts)  # K = 4 configured
    assert eng.k == K and eng.k_effective == K
    eng.delete(np.arange(6))  # 2 survivors < K
    assert eng.k == K, "k_max shrank under deletes"
    assert eng.k_effective == 2
    st = eng.stats()
    assert st["k_configured"] == K and st["k_effective"] == 2
    d2, ids, _ = eng.knn_batch(queries)
    assert ids.shape[1] == K
    assert (ids[:, 2:] == -1).all() and np.isinf(d2[:, 2:]).all()
    # the two real hits are exact vs the rebuild oracle over survivors
    model = {i: pts[i] for i in (6, 7)}
    od2, oids = oracle_answer(model, queries, k=2)
    np.testing.assert_array_equal(ids[:, :2], oids)
    np.testing.assert_array_equal(d2[:, :2], od2)
    # the degradation path obeys the same contract
    fd2, fids = eng.fallback_knn(queries, K)
    np.testing.assert_array_equal(fids, ids)
    np.testing.assert_array_equal(fd2, d2)
    eng.close()

    # across a compaction: a tighter threshold forces the rebuild; the
    # epoch over 2 survivors still answers the configured k, padded
    eng2 = fresh_engine(pts, max_delta_rows=4)
    eng2.delete(np.arange(6))  # backlog 6 >= 4 -> rebuild
    deadline = time.monotonic() + 120
    while eng2.epoch < 1 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert eng2.epoch == 1
    assert eng2.k == K, "k_max shrank across the epoch swap"
    assert eng2.k_effective == 2
    d2b, idsb, _ = eng2.knn_batch(queries)
    np.testing.assert_array_equal(idsb, ids)
    np.testing.assert_array_equal(d2b, d2)
    eng2.close()


def test_delta_padding_never_leaks_a_real_id():
    """A FULL delta buffer with one survivor: brute force can return the
    init carry's -1 index for the empty tail of the top-k, and an
    unguarded gid map would wrap it to the LAST slot's real id — a
    phantom duplicate at distance inf."""
    import jax.numpy as jnp

    from kdtree_tpu.ops.morton import build_morton

    seed = np.arange(30.0).reshape(10, 3).astype(np.float32)
    eng = fresh_engine(seed)
    cap = eng._state.delta.capacity
    rng = np.random.default_rng(7)
    ids = np.arange(100, 100 + cap)
    eng.upsert(ids, rng.uniform(-100, 100, (cap, 3)).astype(np.float32))
    assert eng._state.delta.capacity == cap  # full, not yet grown
    eng.delete(ids[:-1])  # only the LAST slot stays live
    snap = eng._snapshot()
    d2, got = eng._delta_knn(np.zeros((8, 3), np.float32), snap, k=4)
    keep = int(ids[-1])
    assert got[0, 0] == keep and d2[0, 0] < np.inf
    # the empty tail is honest padding — never the survivor's id again
    assert got[0, 1:].tolist() == [-1, -1, -1]
    assert np.isinf(d2[0, 1:]).all()
    eng.close()


# ---------------------------------------------------------------------------
# HTTP: the serving write path
# ---------------------------------------------------------------------------


def _post(httpd, path, payload, timeout=120.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{httpd.server_address[1]}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(httpd, path, timeout=30.0):
    url = f"http://127.0.0.1:{httpd.server_address[1]}{path}"
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


@pytest.fixture
def mutable_server(base_points):
    state = lifecycle.build_state(points=base_points, k=K, max_batch=64,
                                  max_delta_rows=32)
    httpd = srv.make_server(state, port=0, max_wait_ms=1.0)
    httpd.start(warmup_buckets=[8])
    yield httpd
    httpd.stop()


def test_http_upsert_query_delete_roundtrip(mutable_server):
    httpd = mutable_server
    sentinel = [500.0, 500.0, 500.0]
    st, body = _post(httpd, "/v1/upsert",
                     {"ids": [9000], "points": [sentinel]})
    assert st == 200 and body["applied"] == 1 and body["op"] == "upsert"
    assert body["delta_rows"] == 1 and body["epoch"] == 0
    st, body = _post(httpd, "/v1/knn",
                     {"queries": [[499.0, 499.0, 499.0]], "k": 1})
    assert st == 200 and body["ids"][0][0] == 9000
    st, body = _post(httpd, "/v1/delete", {"ids": [9000]})
    assert st == 200 and body["applied"] == 1
    st, body = _post(httpd, "/v1/knn",
                     {"queries": [[499.0, 499.0, 499.0]], "k": 1})
    assert st == 200 and body["ids"][0][0] != 9000
    # healthz carries the mutable block the router and operators read
    st, raw = _get(httpd, "/healthz")
    hz = json.loads(raw)
    assert hz["epoch"] == 0 and hz["id_offset"] == 0
    assert hz["mutable"]["tombstones"] == 0  # delete of a delta-only id
    assert hz["mutable"]["threshold"] == 32


def test_http_write_validation_rejections(mutable_server):
    httpd = mutable_server
    cases = [
        ("/v1/upsert", {"ids": [1]}),                       # no points
        ("/v1/upsert", {"ids": [1], "points": [[1.0]]}),    # wrong dim
        ("/v1/upsert", {"ids": "x", "points": []}),         # ids not list
        ("/v1/upsert", {"ids": [1], "points": [[1e400, 0, 0]]}),
        ("/v1/upsert", {"ids": [True], "points": [[1.0, 2.0, 3.0]]}),
        ("/v1/delete", {"ids": []}),
        ("/v1/delete", {"ids": [1, 1]}),                    # duplicates
        # past int64: must be a 400, not a dead handler thread and a
        # dropped connection (np.asarray raises OverflowError)
        ("/v1/delete", {"ids": [2**63]}),
        ("/v1/upsert", {"ids": [2**63], "points": [[1.0, 2.0, 3.0]]}),
    ]
    for path, payload in cases:
        st, body = _post(httpd, path, payload)
        assert st == 400, (path, payload, st, body)
        assert "error" in body


def test_http_write_on_warming_server_keeps_connection_in_sync(
    base_points,
):
    """An early 503 (warming) must still CONSUME the request body: on a
    keep-alive connection the unread JSON would otherwise be parsed as
    the next request line — the retry the 503 itself invited would get
    garbage instead of service."""
    import http.client

    state = lifecycle.build_state(points=base_points, k=K, max_batch=64)
    httpd = srv.make_server(state, port=0)
    accept = threading.Thread(target=httpd.serve_forever)
    accept.start()
    try:
        assert not state.ready  # no warmup ran: every write 503s
        conn = http.client.HTTPConnection(
            "127.0.0.1", httpd.server_address[1], timeout=30
        )
        try:
            body = json.dumps({"ids": [9000],
                               "points": [[1.0, 2.0, 3.0]]})
            for _ in range(2):  # SAME connection, back to back
                conn.request("POST", "/v1/upsert", body=body,
                             headers={"Content-Type":
                                      "application/json"})
                resp = conn.getresponse()
                payload = json.loads(resp.read())
                assert resp.status == 503, payload
                assert "warming" in payload["error"]
        finally:
            conn.close()
    finally:
        httpd.shutdown()
        accept.join()
        httpd.batcher.start()
        httpd.batcher.stop()
        httpd.server_close()


def test_http_id_offset_writes_are_global(base_points):
    """A sharded serve process owns [offset, ...): global write ids are
    localized on the way in and answers come back global — the router's
    merge depends on both."""
    state = lifecycle.build_state(points=base_points, k=K, max_batch=64,
                                  id_offset=1000, max_delta_rows=1 << 20)
    httpd = srv.make_server(state, port=0, max_wait_ms=1.0)
    httpd.start(warmup_buckets=[8])
    try:
        st, body = _post(httpd, "/v1/upsert",
                         {"ids": [50], "points": [[1.0, 2.0, 3.0]]})
        assert st == 400 and "id_offset" in body["error"]
        st, body = _post(httpd, "/v1/upsert",
                         {"ids": [1000 + N + 5],
                          "points": [[600.0, 600.0, 600.0]]})
        assert st == 200 and body["applied"] == 1
        st, body = _post(httpd, "/v1/knn",
                         {"queries": [[600.0, 600.0, 600.0]], "k": 1})
        assert st == 200 and body["ids"][0][0] == 1000 + N + 5
        st, raw = _get(httpd, "/healthz")
        assert json.loads(raw)["id_offset"] == 1000
    finally:
        httpd.stop()


def test_mutation_e2e_under_concurrent_load(base_points, queries):
    """The acceptance e2e: a live serve process under concurrent query
    load absorbs upserts+deletes, crosses the delta threshold, rebuilds
    and swaps an epoch — with zero failed responses, and every
    post-swap answer byte-identical to a fresh-build oracle over the
    surviving points."""
    state = lifecycle.build_state(points=base_points, k=K, max_batch=64,
                                  max_delta_rows=24)
    httpd = srv.make_server(state, port=0, max_wait_ms=1.0,
                            queue_rows=4096)
    httpd.start(warmup_buckets=[8])
    model = {i: base_points[i].copy() for i in range(N)}
    rng = np.random.default_rng(5)
    stop = threading.Event()
    bad: list = []
    ok_counts = [0, 0, 0]
    body = {"queries": queries[:4].tolist(), "k": K}

    def client(slot):
        while not stop.is_set():
            st, resp = _post(httpd, "/v1/knn", body)
            if st != 200:
                bad.append((slot, st, resp))
                return
            ok_counts[slot] += 1

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(3)]
    try:
        for t in threads:
            t.start()
        # write traffic: moves, deletes, inserts — crossing threshold 24
        mv = np.array([10, 11, 12])
        mvp = rng.uniform(-100, 100, (3, DIM)).astype(np.float32)
        st, _ = _post(httpd, "/v1/upsert",
                      {"ids": mv.tolist(), "points": mvp.tolist()})
        assert st == 200
        for i, p in zip(mv.tolist(), mvp):
            model[i] = p
        st, _ = _post(httpd, "/v1/delete", {"ids": [20, 21]})
        assert st == 200
        model.pop(20), model.pop(21)
        ins = np.arange(N, N + 20)
        insp = rng.uniform(-100, 100, (20, DIM)).astype(np.float32)
        st, resp = _post(httpd, "/v1/upsert",
                         {"ids": ins.tolist(), "points": insp.tolist()})
        assert st == 200 and resp["rebuilding"], resp
        for i, p in zip(ins.tolist(), insp):
            model[i] = p
        # wait for the swap, with queries still hammering
        deadline = time.monotonic() + 120
        epoch = 0
        while time.monotonic() < deadline:
            st, raw = _get(httpd, "/metrics")
            for line in raw.splitlines():
                if line.startswith("kdtree_epoch "):
                    epoch = int(float(line.split(" ")[1]))
            if epoch >= 1:
                break
            time.sleep(0.1)
        assert epoch == 1, "epoch never swapped"
        time.sleep(0.2)  # post-swap traffic under load too
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not bad, bad[:2]
    assert all(c > 0 for c in ok_counts), ok_counts
    # every post-swap answer byte-identical to the fresh-build oracle,
    # through the same JSON transform the HTTP boundary applies
    st, resp = _post(httpd, "/v1/knn",
                     {"queries": queries.tolist(), "k": K})
    assert st == 200 and resp["degraded"] is None
    od2, oids = oracle_answer(model, np.asarray(queries))
    assert resp["ids"] == oids.tolist()
    assert resp["distances"] == np.sqrt(
        od2.astype(np.float64)
    ).tolist()
    st, raw = _get(httpd, "/healthz")
    hz = json.loads(raw)
    assert hz["epoch"] == 1 and hz["mutable"]["delta_rows"] == 0
    httpd.stop()


# ---------------------------------------------------------------------------
# ISSUE 12: the write path is timed, and its lock never holds a compile
# ---------------------------------------------------------------------------


def _hist_count(key):
    snap = obs.get_registry().snapshot()["histograms"].get(key)
    return 0 if snap is None else int(snap["count"])


def test_http_writes_record_latency_histogram(mutable_server):
    """kdtree_write_latency_ms{op=...} must grow with every applied
    write — the load harness's server-side write-path evidence."""
    up0 = _hist_count('kdtree_write_latency_ms{op="upsert"}')
    de0 = _hist_count('kdtree_write_latency_ms{op="delete"}')
    st, _ = _post(mutable_server, "/v1/upsert",
                  {"ids": [9100], "points": [[7.0, 7.0, 7.0]]})
    assert st == 200
    st, _ = _post(mutable_server, "/v1/delete", {"ids": [9100]})
    assert st == 200
    assert _hist_count('kdtree_write_latency_ms{op="upsert"}') == up0 + 1
    assert _hist_count('kdtree_write_latency_ms{op="delete"}') == de0 + 1
    # and the family is on the live scrape (the loadgen runner's source)
    st, raw = _get(mutable_server, "/metrics")
    assert st == 200
    assert 'kdtree_write_latency_ms_count{op="upsert"}' in raw


def test_offered_rate_header_mirrors_into_gauge_and_ring(mutable_server):
    """X-Loadgen-Rate -> gauge + change-gated flight event: the pair
    that lets an SLO-PAGE dump name the offered rate mid-run."""
    from kdtree_tpu.obs import flight

    def post_with_rate(rate):
        req = urllib.request.Request(
            f"http://127.0.0.1:{mutable_server.server_address[1]}/v1/knn",
            data=json.dumps({"queries": [[0.5, 0.5, 0.5]], "k": 1}
                            ).encode(),
            headers={"Content-Type": "application/json",
                     "X-Loadgen-Rate": str(rate)},
        )
        with urllib.request.urlopen(req, timeout=60.0) as resp:
            assert resp.status == 200

    post_with_rate(37.5)
    post_with_rate(37.5)  # unchanged: must NOT mint a second event
    post_with_rate(75.0)
    gauges = obs.get_registry().snapshot()["gauges"]
    assert gauges["kdtree_loadgen_offered_rate"] == 75.0
    rates = [e["rate"] for e in flight.recorder().snapshot()
             if e["type"] == "loadgen.rate"]
    assert rates.count(37.5) == 1 and rates.count(75.0) == 1


def test_mask_bucket_ladder_and_padding_exactness(base_points, queries):
    """Mask scatters pad to the pow2 rung by repeating a position —
    idempotent, so answers stay byte-identical to the oracle while the
    write path cycles exactly len(_MASK_PAD_BUCKETS) compiled shapes."""
    from kdtree_tpu.mutable.engine import _MASK_PAD_BUCKETS, _mask_bucket

    assert _mask_bucket(1) == _MASK_PAD_BUCKETS[0]
    assert _mask_bucket(8) == 8
    assert _mask_bucket(9) == 64
    assert _mask_bucket(4096) == 4096
    assert _mask_bucket(5000) == 8192  # pow2 fallback past the ladder
    eng = fresh_engine(base_points)
    model = {i: base_points[i] for i in range(N)}
    # 3 masked positions pad to 8 with a repeated index: exactness must
    # survive the duplicate scatter rows
    ids = np.array([3, 5, 9])
    eng.delete(ids)
    for i in ids.tolist():
        model.pop(i)
    assert_exact(eng, model, queries, "padded mask scatter")
    eng.close()


def test_write_lock_hold_budget_met_under_lockwatch(monkeypatch,
                                                    tmp_path):
    """The PR 11 artifact's real finding, closed: the FIRST masked
    write on a fresh engine used to compile the tombstone scatter
    (~432 ms) INSIDE the write lock. The scatter shapes are now padded
    to a fixed ladder and pre-warmed off the lock, so under the
    runtime sanitizer a cold engine's first masked writes must leave
    ZERO hold violations on mutable.engine. A distinct index size
    keeps the scatter shape cold for this process — the compile
    genuinely happens here, just not under the lock."""
    from kdtree_tpu.analysis import lockwatch
    from kdtree_tpu.ops.generate import generate_points_rowwise

    monkeypatch.setenv(lockwatch.ENV_ENABLE, "1")
    monkeypatch.setenv(lockwatch.ENV_DIR, str(tmp_path))
    monkeypatch.setenv(lockwatch.ENV_HOLD_MS, "100")
    monkeypatch.delenv(lockwatch.ENV_STRICT, raising=False)
    w = lockwatch.watcher()
    saved = w.export_state()
    w.reset()
    try:
        pts = np.asarray(generate_points_rowwise(SEED, DIM, 300))
        eng = fresh_engine(pts)
        model = {i: pts[i] for i in range(300)}
        qs = np.asarray(generate_points_rowwise(12, DIM, 4),
                        dtype=np.float32)
        # the historical trigger: upsert-of-existing-id (mask path) and
        # a delete, both on a cold engine
        moved = np.array([[9.0, 9.0, 9.0]], dtype=np.float32)
        eng.upsert(np.array([3]), moved)
        model[3] = moved[0]
        eng.delete(np.array([5]))
        model.pop(5)
        assert_exact(eng, model, qs, "writes under lockwatch")
        bad = [v for v in w.violations()
               if v["lock"] == "mutable.engine"]
        assert bad == [], f"write lock held past budget with I/O: {bad}"
        eng.close()
    finally:
        w.reset()
        w.merge_state(saved)


def test_rebuild_impact_history_join():
    """The epoch-rebuild p99 delta is a pure history-ring join: quantile
    over the rebuild window minus the same-width window before it."""
    from kdtree_tpu.mutable.engine import (
        _REQUEST_LATENCY_KEY,
        rebuild_impact,
    )
    from kdtree_tpu.obs.history import MetricHistory

    key = _REQUEST_LATENCY_KEY

    def hist(fast, slow):
        return {key: {
            "count": fast + slow, "sum": fast * 0.01 + slow * 0.5,
            "buckets": {"0.025": fast, "1.0": fast + slow,
                        "+Inf": fast + slow},
        }}

    h = MetricHistory(capacity=16)
    h.record({"histograms": hist(0, 0)}, ts=0.0)
    h.record({"histograms": hist(100, 0)}, ts=10.0)   # calm before
    h.record({"histograms": hist(100, 100)}, ts=20.0)  # burn during
    # a LATER sample must not leak into either window: samples() now
    # applies the upper bound too, else the "before" window silently
    # extended to the newest sample and included the rebuild itself
    h.record({"histograms": hist(100, 600)}, ts=30.0)
    impact = rebuild_impact(h, 10.0, 20.0)
    assert impact is not None
    assert impact["p99_during_ms"] > impact["p99_before_ms"]
    assert impact["p99_delta_ms"] > 0
    assert impact["window_s"] == 10.0
    # a window the ring cannot cover reads as absent, never as zero
    assert rebuild_impact(h, 100.0, 110.0) is None
    assert rebuild_impact(h, 20.0, 20.0) is None


def test_rebuild_records_impact_flight_event(base_points, queries):
    """An epoch rebuild leaves a mutable.rebuild_impact event naming
    the swap window, even when the ring had no latency data (nulls,
    not silence)."""
    from kdtree_tpu.obs import flight

    eng = fresh_engine(base_points, max_delta_rows=4, max_delta_frac=0.0)
    fresh = np.arange(4, dtype=np.float32).reshape(-1, 1) + \
        np.zeros((4, DIM), dtype=np.float32)
    eng.upsert(np.array([N + 1, N + 2, N + 3, N + 4]), fresh)
    deadline = time.monotonic() + 60
    while eng.epoch == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert eng.epoch == 1
    eng.close()
    events = [e for e in flight.recorder().snapshot()
              if e["type"] == "mutable.rebuild_impact"]
    assert events, "rebuild completed without an impact event"
    ev = events[-1]
    assert ev["epoch"] == 1 and ev["previous_epoch"] == 0
    assert ev["duration_ms"] > 0


def test_rebuild_impact_gauge_lands_on_metrics(base_points):
    """Once a rebuild window HAS latency data, the p99 delta must be a
    live gauge on the Prometheus exposition (absent before — an unset
    gauge would read 'measured, no impact')."""
    from kdtree_tpu.mutable.engine import _REQUEST_LATENCY_KEY
    from kdtree_tpu.obs import history as obs_history
    from kdtree_tpu.obs.export import prometheus_text

    def hist(fast, slow):
        return {_REQUEST_LATENCY_KEY: {
            "count": fast + slow, "sum": fast * 0.01 + slow * 0.5,
            "buckets": {"0.025": fast, "1.0": fast + slow,
                        "+Inf": fast + slow},
        }}

    # synthetic samples in the FUTURE: the window filter is a lower
    # bound (ts >= now - window), so only a future t_base keeps real
    # sampler samples from other tests out of these windows
    t_base = time.time() + 1000.0
    ring = obs_history.get_history()
    ring.record({"histograms": hist(0, 0)}, ts=t_base)
    ring.record({"histograms": hist(50, 0)}, ts=t_base + 10)
    ring.record({"histograms": hist(50, 50)}, ts=t_base + 20)
    eng = fresh_engine(base_points)
    eng._note_rebuild_impact(0, 1, t_base + 10, t_base + 20)
    eng.close()
    text = prometheus_text()
    line = [ln for ln in text.splitlines()
            if ln.startswith("kdtree_mutable_rebuild_p99_delta_ms ")]
    assert line, "gauge missing after a measured rebuild window"
    assert float(line[0].split()[-1]) > 0


# ---------------------------------------------------------------------------
# published bounding box (ISSUE 15: the selective fan-out's pruning
# input — never stale-exclusive, tightened at epoch swaps)
# ---------------------------------------------------------------------------


def test_bounds_expand_on_upsert_never_shrink_on_delete(base_points):
    eng = fresh_engine(base_points)
    lo0, hi0 = eng.bounds()
    assert (lo0 <= base_points.min(axis=0) + 1e-6).all()
    # an upsert OUTSIDE the box expands it immediately (pre-probe: the
    # /healthz box is never stale-exclusive of a delta point)
    far = (base_points.max(axis=0) + np.float32(50.0)).reshape(1, -1)
    eng.upsert(np.array([900000]), far.astype(np.float32))
    lo1, hi1 = eng.bounds()
    assert (hi1 >= far[0] - 1e-6).all() and (lo1 == lo0).all()
    # deleting it does NOT shrink the box (conservative until the next
    # epoch recompute — a tight-but-wrong box would cost answers)
    eng.delete(np.array([900000]))
    lo2, hi2 = eng.bounds()
    assert (hi2 == hi1).all() and (lo2 == lo1).all()
    eng.close()


def test_bounds_tighten_at_epoch_swap(base_points):
    eng = fresh_engine(base_points, max_delta_rows=4)
    _, hi0 = eng.bounds()
    far = (base_points.max(axis=0) + np.float32(50.0)).reshape(1, -1)
    eng.upsert(np.array([900000]), far.astype(np.float32))
    eng.delete(np.array([900000]))
    # churn past the threshold: the compaction drops the far point and
    # the NEW epoch's recomputed box tightens back
    for j in range(4):
        eng.upsert(
            np.array([900100 + j]),
            base_points[j].reshape(1, -1).astype(np.float32))
    deadline = time.monotonic() + 60.0
    while eng.epoch == 0 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert eng.epoch >= 1
    _, hi2 = eng.bounds()
    assert (hi2 <= hi0 + 1e-6).all()
    eng.close()
