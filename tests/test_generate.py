import jax.numpy as jnp
import numpy as np

from kdtree_tpu import generate_points_rowwise, generate_points_shard, generate_problem


def test_range_and_shape():
    pts, qs = generate_problem(seed=42, dim=3, num_points=1000, num_queries=10)
    assert pts.shape == (1000, 3) and qs.shape == (10, 3)
    assert pts.dtype == jnp.float32
    assert float(pts.min()) >= -100.0 and float(pts.max()) < 100.0


def test_determinism():
    a, qa = generate_problem(7, 4, 256)
    b, qb = generate_problem(7, 4, 256)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(qa), np.asarray(qb))
    c, _ = generate_problem(8, 4, 256)
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_shard_generation_matches_rowwise():
    """The counter-based analog of the reference's mt19937 discard trick
    (kdtree_mpi.cpp:24,32): shards of the global array generated independently
    must be bit-identical to the whole array generated at once."""
    full = np.asarray(generate_points_rowwise(5, 3, 64))
    parts = [np.asarray(generate_points_shard(5, 3, s, 16)) for s in (0, 16, 32, 48)]
    np.testing.assert_array_equal(full, np.concatenate(parts, axis=0))
