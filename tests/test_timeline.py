"""Device-timeline profiler (obs/profile.py + obs/timeline.py): the
Chrome-trace join must classify events correctly on synthetic traces,
and the real capture window must produce a parseable timeline with at
least one correlated host-span/device-slice pair on the CPU backend —
the same assertion CI's profile-smoke makes."""

from __future__ import annotations

import json

import pytest

from kdtree_tpu.obs import profile as obs_profile
from kdtree_tpu.obs import timeline as tl

# ---------------------------------------------------------------------------
# synthetic-trace units
# ---------------------------------------------------------------------------


def X(name, ts, dur, pid=1, tid=1, args=None):
    e = {"ph": "X", "name": name, "ts": float(ts), "dur": float(dur),
         "pid": pid, "tid": tid}
    if args:
        e["args"] = args
    return e


def M_proc(pid, name):
    return {"ph": "M", "name": "process_name", "pid": pid,
            "args": {"name": name}}


def _trace(*events):
    return {"traceEvents": list(events)}


def test_span_exec_overlap_and_busy_union():
    rep = tl.parse_timeline(_trace(
        M_proc(1, "/host:CPU"),
        X("query.tiled", 0, 100),
        # nested op slices (a call containing its fusion child) must
        # count ONCE in busy time
        X("call", 10, 40, tid=2, args={"hlo_op": "call",
                                       "hlo_module": "jit_f"}),
        X("fusion.1", 12, 30, tid=2, args={"hlo_op": "fusion.1",
                                           "hlo_module": "jit_f"}),
        X("reduce.2", 70, 10, tid=3, args={"hlo_op": "reduce.2",
                                           "hlo_module": "jit_f"}),
    ))
    span = rep["spans"]["query.tiled"]
    assert span["count"] == 1 and span["n_slices"] == 3
    assert span["device_busy_us"] == pytest.approx(50.0)  # 40 + 10, union
    assert span["device_idle_us"] == pytest.approx(50.0)
    assert rep["correlated_spans"] == 1
    assert rep["correlated_pairs"] == 3
    assert rep["device"]["busy_us"] == pytest.approx(50.0)
    mods = {m["module"]: m["busy_us"] for m in rep["device"]["modules"]}
    assert mods == {"jit_f": pytest.approx(50.0)}


def test_device_process_slices_without_hlo_args_count():
    # TPU layout: op events live in /device:* processes, no hlo_op args
    rep = tl.parse_timeline(_trace(
        M_proc(1, "/host:CPU"),
        M_proc(7, "/device:TPU:0 (pid 7)"),
        X("serve.batch", 0, 100),
        X("fused_computation", 20, 30, pid=7),
    ))
    assert rep["correlated_spans"] == 1
    assert rep["spans"]["serve.batch"]["device_busy_us"] == pytest.approx(30.0)


def test_internal_host_events_are_not_spans():
    rep = tl.parse_timeline(_trace(
        M_proc(1, "/host:CPU"),
        X("$api.py:141 jit", 0, 50),
        X("TfrtCpuExecutable::Execute", 5, 10),
        X("ParseArguments", 2, 1),
        X("bench.build", 0, 100),
    ))
    assert set(rep["spans"]) == {"bench.build"}


def test_explicit_span_names_override_heuristic():
    rep = tl.parse_timeline(_trace(
        M_proc(1, "/host:CPU"),
        X("bench.build", 0, 50),
        X("myregion", 50, 50),
    ), span_names={"myregion"})
    assert set(rep["spans"]) == {"myregion"}


def test_dispatch_windows_lag_and_compiles():
    rep = tl.parse_timeline(_trace(
        M_proc(1, "/host:CPU"),
        X("backend_compile", 0, 100),
        X("tile.dispatch", 100, 5, args={"batch": 0}),
        X("op.1", 120, 30, tid=2, args={"hlo_op": "op.1",
                                        "hlo_module": "jit_b"}),
        X("tile.dispatch", 200, 5, args={"batch": 1}),
        X("op.2", 210, 90, tid=2, args={"hlo_op": "op.2",
                                        "hlo_module": "jit_b"}),
    ))
    disp = rep["dispatches"]
    assert disp["count"] == 2
    w0, w1 = disp["windows"]
    assert w0["window_us"] == pytest.approx(100.0)  # dispatch 0 -> dispatch 1
    assert w0["busy_us"] == pytest.approx(30.0)
    assert w0["idle_us"] == pytest.approx(70.0)
    assert w0["lag_us"] == pytest.approx(20.0)
    assert w1["lag_us"] == pytest.approx(10.0)
    assert disp["lag_us"]["max"] == pytest.approx(20.0)
    assert rep["compile"]["count"] == 1
    assert rep["compile"]["total_us"] == pytest.approx(100.0)
    # dispatches are not spans; the compile is not device busy time
    assert rep["spans"] == {}
    assert rep["device"]["busy_us"] == pytest.approx(120.0)


def test_dispatch_stage_decomposition_and_busy_frac_median():
    """PR 6: each dispatch window's host time decomposes by driver stage
    (prep / retire-wait / drain-wait, from the tile.retire / tile.drain
    annotations), and per-dispatch busy_frac gets a median over ALL
    windows — the >90% acceptance gate's mechanical form."""
    rep = tl.parse_timeline(_trace(
        M_proc(1, "/host:CPU"),
        X("tile.dispatch", 0, 5, args={"batch": 0}),
        X("op.1", 10, 80, tid=2, args={"hlo_op": "op.1",
                                       "hlo_module": "jit_b"}),
        # the driver blocked 20us on batch 0's overflow flag here
        X("tile.retire", 60, 20, args={"batch": 0}),
        X("tile.dispatch", 100, 5, args={"batch": 1}),
        X("op.2", 110, 20, tid=2, args={"hlo_op": "op.2",
                                        "hlo_module": "jit_b"}),
        X("tile.drain", 150, 50, args={"batches": 1}),
    ))
    disp = rep["dispatches"]
    assert disp["count"] == 2
    st = disp["stages"]
    assert st["retire_us"] == pytest.approx(20.0)
    assert st["drain_us"] == pytest.approx(50.0)
    # windows: [0, 100) + [100, 200) = 200 wall, minus 70 stage-wait
    assert st["prep_us"] == pytest.approx(130.0)
    # per-window fracs: 80/100 and 20/100 -> median (even n: upper mid)
    assert disp["busy_frac_median"] == pytest.approx(0.8)
    w0, w1 = disp["windows"]
    assert w0["retire_us"] == pytest.approx(20.0)
    assert w0["drain_us"] == pytest.approx(0.0)
    assert w1["drain_us"] == pytest.approx(50.0)
    # stage annotations are dotted names and still correlate as spans too
    assert "tile.retire" in rep["spans"]
    # the human rendering surfaces the split
    text = tl.render_timeline(rep)
    assert "host-stage split" in text and "retire=" in text


def test_idle_gaps_reported_largest_first():
    rep = tl.parse_timeline(_trace(
        M_proc(1, "/host:CPU"),
        X("profile.query", 0, 300),
        X("a", 0, 10, tid=2, args={"hlo_op": "a", "hlo_module": "m"}),
        X("b", 110, 10, tid=2, args={"hlo_op": "b", "hlo_module": "m"}),
        X("c", 150, 150, tid=2, args={"hlo_op": "c", "hlo_module": "m"}),
    ))
    gaps = rep["device"]["largest_gaps"]
    assert gaps[0]["gap_us"] == pytest.approx(100.0)  # 10 -> 110
    assert gaps[1]["gap_us"] == pytest.approx(30.0)   # 120 -> 150


def test_dispatch_aggregates_cover_beyond_listing_cap():
    """busy_frac / lag stats must aggregate over ALL dispatches even when
    the per-window listing is capped at _MAX_LISTED — `count` and the
    aggregates must describe the same population. All the device work
    lands in the LAST dispatch's window here, so a truncated aggregate
    would report busy_frac == 0."""
    n = tl._MAX_LISTED + 10
    events = [M_proc(1, "/host:CPU")]
    for i in range(n):
        events.append(X("tile.dispatch", i * 100.0, 1, args={"batch": i}))
    last = (n - 1) * 100.0
    events.append(X("op", last + 10, 50, tid=2,
                    args={"hlo_op": "op", "hlo_module": "m"}))
    rep = tl.parse_timeline(_trace(*events))
    disp = rep["dispatches"]
    assert disp["count"] == n
    assert len(disp["windows"]) == tl._MAX_LISTED
    total_wall = last + 60.0  # first dispatch -> capture end
    assert disp["busy_frac"] == pytest.approx(50.0 / total_wall)
    assert disp["lag_us"]["n"] == n  # the op start is ahead of every one
    assert disp["lag_us"]["max"] == pytest.approx(last + 10)


def test_empty_trace_parses_to_empty_report():
    rep = tl.parse_timeline(_trace())
    assert rep["capture"]["wall_us"] == 0.0
    assert rep["correlated_spans"] == 0
    assert rep["dispatches"]["count"] == 0
    # and renders without crashing
    assert "capture" in tl.render_timeline(rep)


def test_render_timeline_mentions_the_load_bearing_numbers():
    rep = tl.parse_timeline(_trace(
        M_proc(1, "/host:CPU"),
        X("query.tiled", 0, 100),
        X("backend_compile", 0, 10),
        X("tile.dispatch", 5, 2, args={"batch": 0}),
        X("op", 10, 50, tid=2, args={"hlo_op": "op", "hlo_module": "jit_q"}),
    ))
    text = tl.render_timeline(rep)
    assert "device busy" in text
    assert "query.tiled" in text
    assert "not steady state" in text  # a compile polluted the window
    assert "jit_q" in text
    assert "dispatch->exec lag" in text


# ---------------------------------------------------------------------------
# real capture window (CPU backend)
# ---------------------------------------------------------------------------


@pytest.mark.slow  # first start_trace pays ~14s of one-time profiler
# init in this container; CI's profile-smoke step gates the capture e2e
# on every PR, so the fast tier-1 lane keeps only the synthetic-trace
# parser tests above
def test_capture_correlates_real_span_to_device_slices(tmp_path):
    """The acceptance-criterion shape, in-process: a capture window
    around a span-wrapped jitted computation must yield >= 1 correlated
    host-span/device-slice pair on the CPU backend. The single-capture
    lock is asserted inside the same window (capture start/stop pairs
    are seconds-scale on this runtime; one window checks both)."""
    import jax
    import jax.numpy as jnp

    from kdtree_tpu import obs

    f = jax.jit(lambda x: jnp.sin(x).sum())
    x = jnp.arange(1 << 16, dtype=jnp.float32)
    f(x).block_until_ready()  # compile outside the window
    with obs_profile.capture(str(tmp_path / "trace")) as cap:
        assert obs_profile.capture_active()
        with pytest.raises(obs_profile.CaptureBusyError):
            with obs_profile.capture(str(tmp_path / "t2")):
                pass
        with obs.span("profiletest.region") as h:
            h += [f(x)]
    assert not obs_profile.capture_active()
    assert cap.trace_file is not None and cap.trace_file.endswith(
        ".trace.json.gz"
    )
    rep = tl.analyze_trace_file(cap.trace_file)
    assert rep["trace_file"] == cap.trace_file
    span = rep["spans"].get("profiletest.region")
    assert span is not None, f"span not found in {sorted(rep['spans'])}"
    assert span["n_slices"] >= 1
    assert span["device_busy_us"] > 0.0
    assert rep["correlated_spans"] >= 1


@pytest.mark.slow  # capture window + fresh XLA compiles for the
# workload shapes; the artifact/correlation contract is also CI-gated by
# the profile-smoke step
def test_profile_cli_writes_timeline_artifact(tmp_path, capsys):
    """`kdtree-tpu profile` end-to-end on CPU: artifact exists, parses,
    correlates, and carries the dispatch/compile sections."""
    from kdtree_tpu.utils.cli import main

    out = tmp_path / "timeline.json"
    main([
        "--platform", "cpu", "--generator", "threefry", "profile",
        "--n", "4096", "--q", "512", "--k", "2",
        "--trace-dir", str(tmp_path / "trace"),
        "--out", str(out), "--format", "json",
    ])
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["correlated_spans"] >= 1
    rep = json.loads(out.read_text())
    assert rep["timeline_version"] == tl.TIMELINE_VERSION
    assert rep["correlated_spans"] >= 1
    assert rep["dispatches"]["count"] >= 1
    assert rep["workload"]["q"] == 512
    # warm profile: the capture window itself must be compile-free
    assert rep["compile"]["count"] == 0
    assert rep["spans"]["profile.query"]["n_slices"] >= 1
    # the raw trace artifact survives for Perfetto
    assert rep["trace_file"].endswith(".trace.json.gz")
