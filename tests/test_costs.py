"""Cost attribution, the profiling duty cycle, and the capacity-headroom
model (docs/OBSERVABILITY.md "Cost accounting & capacity headroom").

The load-bearing invariant is the accounting identity: a batch's
dispatch span amortized over its members sums back to the span EXACTLY
(integer-microsecond largest-remainder split) — cost totals reconcile
against wall clock, no request double-billed, none of the span leaked.
The enum tests pin the KDT105 discipline: unknown verbs/gears/outcomes
fold into "other" and can never mint a new series.
"""

from __future__ import annotations

import time

import pytest

from kdtree_tpu.obs import costs as cm
from kdtree_tpu.obs import history as hist
from kdtree_tpu.obs.registry import MetricsRegistry


def _micros(shares):
    return [int(round(s * 1000)) for s in shares]


# ---------------------------------------------------------------------------
# exact-sum amortization
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("span_ms,rows", [
    (10.0, [1, 3, 7]),
    (0.001, [5, 5, 5]),            # fewer micros than members
    (7.7777, [1, 1, 1, 1, 1, 1, 1]),
    (123.456789, [64, 1, 13, 0, 7]),
    (5.0, [0, 0, 3]),              # zero-row members get nothing extra
    (0.0004, [1, 2]),              # rounds to 0 us: all-zero split
])
def test_amortize_exact_sum_identity(span_ms, rows):
    shares = cm.amortize_span_ms(span_ms, rows)
    assert len(shares) == len(rows)
    assert sum(_micros(shares)) == int(round(span_ms * 1000))
    # monotone in rows: a bigger member never gets a smaller share
    for i, (ri, si) in enumerate(zip(rows, shares)):
        for rj, sj in zip(rows[i + 1:], shares[i + 1:]):
            if ri > rj:
                assert si >= sj


def test_amortize_degenerate_inputs():
    assert cm.amortize_span_ms(-1.0, [1, 2]) == [0.0, 0.0]
    assert cm.amortize_span_ms(10.0, [0, 0]) == [0.0, 0.0]
    assert cm.amortize_span_ms(10.0, []) == []
    # negative row weights are clamped, not propagated
    shares = cm.amortize_span_ms(6.0, [-5, 2, 1])
    assert shares[0] == 0.0 and sum(_micros(shares)) == 6000


def test_largest_remainder_split_is_deterministic_on_ties():
    a = cm._largest_remainder(10, [1, 1, 1])
    b = cm._largest_remainder(10, [1, 1, 1])
    assert a == b and sum(a) == 10
    # the extra unit goes to the earliest index on equal remainders
    assert a[0] >= a[-1]


def test_amortize_proportionality():
    shares = cm.amortize_span_ms(100.0, [75, 25])
    assert shares[0] == pytest.approx(75.0)
    assert shares[1] == pytest.approx(25.0)


# ---------------------------------------------------------------------------
# the ledger: attribution identity incl. retries and corrections
# ---------------------------------------------------------------------------


def _class_sum(reg, family):
    snap = reg.snapshot()["counters"]
    return sum(v for k, v in snap.items() if k.startswith(family))


def test_attribute_batch_identity_across_classes():
    reg = MetricsRegistry()
    led = cm.CostLedger(registry=reg)
    span = 42.4242
    members = [(8, 1.5, "ok"), (3, 0.2, "ok"), (5, 9.0, "degraded")]
    shares = led.attribute_batch(
        verb="knn", gear="approx:0.9", span_ms=span, members=members,
        retries=3, visits_per_row=4)
    # the identity: per-member shares and the class counters both sum
    # exactly to the span at microsecond resolution
    assert sum(_micros(shares)) == int(round(span * 1000))
    total_dev = _class_sum(reg, "kdtree_cost_device_ms_total")
    assert int(round(total_dev * 1000)) == int(round(span * 1000))
    assert _class_sum(reg, "kdtree_cost_requests_total") == 3
    assert _class_sum(reg, "kdtree_cost_rows_total") == 16
    assert _class_sum(reg, "kdtree_cost_retries_total") == 3
    assert _class_sum(reg, "kdtree_cost_visits_total") == 16 * 4
    assert _class_sum(reg, "kdtree_cost_queue_ms_total") == \
        pytest.approx(10.7)
    # outcomes split the class: ok and degraded series both exist
    snap = reg.snapshot()["counters"]
    assert any('outcome="ok"' in k for k in snap
               if k.startswith("kdtree_cost_requests_total"))
    assert any('outcome="degraded"' in k for k in snap
               if k.startswith("kdtree_cost_requests_total"))


def test_attribution_identity_survives_many_uneven_batches():
    reg = MetricsRegistry()
    led = cm.CostLedger(registry=reg)
    expect_us = 0
    for i in range(50):
        span = 0.137 * (i + 1) + 0.0007
        rows = [(i * j) % 11 for j in range(1, 6)]
        led.attribute_batch(
            verb="radius", gear=None, span_ms=span,
            members=[(r, 0.1, "ok") for r in rows], retries=i % 3)
        if sum(rows) > 0:
            expect_us += int(round(span * 1000))
    got = _class_sum(reg, "kdtree_cost_device_ms_total")
    assert int(round(got * 1000)) == expect_us


def test_attribute_request_is_a_batch_of_one():
    reg = MetricsRegistry()
    led = cm.CostLedger(registry=reg)
    dev = led.attribute_request(verb="knn", gear="exact", span_ms=3.25,
                               rows=70, queue_ms=0.5,
                               outcome="degraded")
    assert dev == pytest.approx(3.25)
    snap = reg.snapshot()["counters"]
    key = ('kdtree_cost_requests_total{gear="exact",outcome="degraded"'
           ',verb="knn"}')
    assert snap[key] == 1


def test_correction_is_maintenance_not_request_cost():
    reg = MetricsRegistry()
    led = cm.CostLedger(registry=reg)
    led.attribute_correction(12.5, 64)
    led.attribute_correction(-1.0, -3)   # clamped, never negative
    snap = reg.snapshot()["counters"]
    assert snap["kdtree_cost_correction_ms_total"] == \
        pytest.approx(12.5)
    assert snap["kdtree_cost_correction_rows_total"] == 64
    # no request class was charged
    assert _class_sum(reg, "kdtree_cost_requests_total") == 0
    assert _class_sum(reg, "kdtree_cost_device_ms_total") == 0
    rep = led.report(history=hist.MetricHistory(capacity=4))
    assert rep["maintenance"]["correction_ms"] == pytest.approx(12.5)
    assert rep["maintenance"]["correction_rows"] == 64


def test_write_and_rebuild_maintenance_fold():
    reg = MetricsRegistry()
    cm.count_write("upsert", 1.5, registry=reg)
    cm.count_write("compact", 2.0, registry=reg)   # folds to other
    cm.count_rebuild(250.0, registry=reg)
    snap = reg.snapshot()["counters"]
    assert snap['kdtree_cost_writes_total{op="upsert"}'] == 1
    assert snap['kdtree_cost_writes_total{op="other"}'] == 1
    assert snap["kdtree_cost_rebuilds_total"] == 1
    assert snap["kdtree_cost_rebuild_ms_total"] == pytest.approx(250.0)
    led = cm.CostLedger(registry=reg)
    rep = led.report(history=hist.MetricHistory(capacity=4))
    assert rep["maintenance"]["writes"] == 2
    assert rep["maintenance"]["write_ms"] == pytest.approx(3.5)
    assert rep["maintenance"]["rebuilds"] == 1


# ---------------------------------------------------------------------------
# bounded class enum (KDT105: folding is total, labels cannot be minted)
# ---------------------------------------------------------------------------


def test_class_folding_table():
    assert cm.verb_class(None) == "knn"
    assert cm.verb_class("count") == "count"
    assert cm.verb_class("count_radius") == "count"
    assert cm.verb_class("count_range") == "count"
    assert cm.verb_class("teleport") == "other"
    assert cm.gear_class(None) == "exact"
    assert cm.gear_class("") == "exact"
    assert cm.gear_class("approx:0.97") == "approx"
    assert cm.gear_class("brute-deadline") == "brute-deadline"
    assert cm.gear_class("hyperdrive") == "other"
    assert cm.outcome_class(None) == "ok"
    assert cm.outcome_class("degraded") == "degraded"
    assert cm.outcome_class("shed") == "other"


def test_unknown_labels_cannot_mint_series():
    reg = MetricsRegistry()
    led = cm.CostLedger(registry=reg)
    for i in range(100):
        led.attribute_batch(
            verb=f"verb-{i}", gear=f"gear-{i}", span_ms=1.0,
            members=[(1, 0.0, f"outcome-{i}")])
    snap = reg.snapshot()["counters"]
    req = [k for k in snap
           if k.startswith("kdtree_cost_requests_total")]
    # 100 distinct inputs, ONE folded series
    assert req == ['kdtree_cost_requests_total{gear="other",'
                   'outcome="other",verb="other"}']
    assert snap[req[0]] == 100
    # every label value anywhere in the cost families is from the enum
    for k in snap:
        if not k.startswith("kdtree_cost_") or "{" not in k:
            continue
        inner = k[k.index("{") + 1:-1]
        for pair in inner.split(","):
            name, _, val = pair.partition("=")
            val = val.strip('"')
            if name == "verb":
                assert val in cm.COST_VERBS
            elif name == "gear":
                assert val in cm.COST_GEARS
            elif name == "outcome":
                assert val in cm.COST_OUTCOMES


def test_ledger_never_raises_on_garbage():
    reg = MetricsRegistry()
    led = cm.CostLedger(registry=reg)
    shares = led.attribute_batch(verb="knn", gear=None,
                                 span_ms=float("nan"),
                                 members=[("x", None, "ok")])
    assert len(shares) == 1   # degraded to zeros, not an exception
    led.count_bytes(verb="knn", gear=None, outcome="ok",
                    bytes_in="junk", bytes_out=None)


# ---------------------------------------------------------------------------
# the windowed model and headroom math
# ---------------------------------------------------------------------------


def _traffic_history(reg, led, *, busy=None):
    """Two-sample ring: idle at t=100, then 30 requests x 2ms device
    time by t=160 (0.5 req/s over the 60s window)."""
    h = hist.MetricHistory(capacity=8)
    led.attribute_batch(verb="knn", gear=None, span_ms=0.0,
                        members=[])  # touch nothing, keep t=100 idle
    h.record(reg.snapshot(), ts=100.0)
    for _ in range(30):
        led.attribute_request(verb="knn", gear=None, span_ms=2.0,
                              rows=1, queue_ms=0.1)
    if busy is not None:
        reg.gauge("kdtree_device_busy_frac").set(busy)
    h.record(reg.snapshot(), ts=160.0)
    return h


def test_window_costs_none_when_idle():
    reg = MetricsRegistry()
    led = cm.CostLedger(registry=reg)
    h = hist.MetricHistory(capacity=4)
    h.record(reg.snapshot(), ts=100.0)
    h.record(reg.snapshot(), ts=160.0)
    assert led.window_costs(60.0, h, now=160.0) is None
    hr = led.headroom(60.0, h, now=160.0)
    assert hr == {"data": False, "window_s": 60.0, "busy_frac": None}


def test_headroom_math_without_busy_capture():
    reg = MetricsRegistry()
    led = cm.CostLedger(registry=reg)
    h = _traffic_history(reg, led)
    w = led.window_costs(60.0, h, now=160.0)
    assert w["requests"] == 30
    assert w["cost_per_query_ms"] == pytest.approx(2.0)
    assert w["observed_rate"] == pytest.approx(0.5)
    hr = led.headroom(60.0, h, now=160.0)
    assert hr["data"] is True
    # no capture yet: full 1000 ms/s budget => 500 req/s predicted
    assert hr["busy_frac"] is None
    assert hr["predicted_rate"] == pytest.approx(500.0)
    assert hr["headroom_frac"] == pytest.approx(1.0 - 0.5 / 500.0)


def test_headroom_budget_scales_with_measured_busy_frac():
    reg = MetricsRegistry()
    led = cm.CostLedger(registry=reg)
    h = _traffic_history(reg, led, busy=0.5)
    hr = led.headroom(60.0, h, now=160.0)
    assert hr["busy_frac"] == pytest.approx(0.5)
    # half the device budget => half the predicted rate
    assert hr["predicted_rate"] == pytest.approx(250.0)


def test_headroom_clamps_at_zero_when_over_predicted():
    reg = MetricsRegistry()
    led = cm.CostLedger(registry=reg)
    h = hist.MetricHistory(capacity=8)
    h.record(reg.snapshot(), ts=100.0)
    # 100ms/query at 20 req/s observed: observed >> predicted (10/s)
    for _ in range(1200):
        led.attribute_request(verb="knn", gear=None, span_ms=100.0,
                              rows=1, queue_ms=0.0)
    h.record(reg.snapshot(), ts=160.0)
    hr = led.headroom(60.0, h, now=160.0)
    assert hr["predicted_rate"] == pytest.approx(10.0)
    assert hr["headroom_frac"] == 0.0


def test_publish_registers_gauges_lazily():
    reg = MetricsRegistry()
    led = cm.CostLedger(registry=reg)
    h = hist.MetricHistory(capacity=8)
    h.record(reg.snapshot(), ts=100.0)
    h.record(reg.snapshot(), ts=130.0)
    led.publish(history=h, now=130.0)
    gauges = reg.snapshot()["gauges"]
    # absent means "no data", never a misleading 0
    assert "kdtree_capacity_headroom_frac" not in gauges
    assert "kdtree_cost_per_query_ms" not in gauges
    h2 = _traffic_history(reg, led)
    led.publish(history=h2, now=160.0)
    gauges = reg.snapshot()["gauges"]
    assert gauges["kdtree_capacity_predicted_rate"] == \
        pytest.approx(500.0)
    assert gauges["kdtree_cost_per_query_ms"] == pytest.approx(2.0)
    assert 0.0 <= gauges["kdtree_capacity_headroom_frac"] <= 1.0


def test_report_shape_and_totals_identity():
    reg = MetricsRegistry()
    led = cm.CostLedger(registry=reg)
    led.attribute_batch(verb="knn", gear=None, span_ms=10.0,
                        members=[(4, 1.0, "ok"), (4, 1.0, "ok")])
    led.attribute_batch(verb="radius", gear="approx:0.9", span_ms=6.0,
                        members=[(2, 0.5, "degraded")])
    led.count_bytes(verb="knn", gear=None, outcome="ok",
                    bytes_in=100, bytes_out=900)
    rep = led.report(history=hist.MetricHistory(capacity=4))
    assert rep["costs_version"] == cm.COSTS_VERSION
    classes = {(c["verb"], c["gear"], c["outcome"]): c
               for c in rep["classes"]}
    assert classes[("knn", "exact", "ok")]["requests"] == 2
    assert classes[("knn", "exact", "ok")]["bytes_out"] == 900
    assert classes[("radius", "approx", "degraded")]["cost_ms"] == \
        pytest.approx(6.0)
    t = rep["totals"]
    assert t["requests"] == 3
    assert int(round(t["device_ms"] * 1000)) == 16000
    assert rep["window"] is None and rep["headroom"]["data"] is False


# ---------------------------------------------------------------------------
# overhead: attribution is host-side counter math, within the <2% bar
# ---------------------------------------------------------------------------


def test_attribution_overhead_under_two_percent():
    """2000 batches x 8 members at a (simulated) 10ms span each is 20s
    of attributed device time; the attribution work itself must cost
    under 2% of that. The real ratio is ~100x under the bar — the test
    exists to catch an accidental O(classes) scan or device sync
    sneaking into the hot path, not to microbenchmark."""
    reg = MetricsRegistry()
    led = cm.CostLedger(registry=reg)
    members = [(8, 0.5, "ok")] * 8
    t0 = time.perf_counter()
    for _ in range(2000):
        led.attribute_batch(verb="knn", gear=None, span_ms=10.0,
                            members=members)
    elapsed = time.perf_counter() - t0
    attributed_s = 2000 * 10.0 / 1000.0
    assert elapsed < 0.02 * attributed_s, \
        f"attribution cost {elapsed:.3f}s on {attributed_s:.0f}s " \
        f"of simulated device time"


# ---------------------------------------------------------------------------
# the profiling duty cycle
# ---------------------------------------------------------------------------


def test_duty_env_knobs(monkeypatch):
    monkeypatch.setenv("KDTREE_TPU_PROFILE_DUTY_PERIOD_S", "17.5")
    monkeypatch.setenv("KDTREE_TPU_PROFILE_DUTY_WINDOW_S", "0.25")
    assert cm.duty_period_s() == 17.5
    assert cm.duty_window_s() == 0.25
    monkeypatch.setenv("KDTREE_TPU_PROFILE_DUTY_PERIOD_S", "garbage")
    monkeypatch.setenv("KDTREE_TPU_PROFILE_DUTY_WINDOW_S", "-3")
    assert cm.duty_period_s() == cm.DEFAULT_DUTY_PERIOD_S
    assert cm.duty_window_s() == cm.DEFAULT_DUTY_WINDOW_S


def test_duty_kill_switch_blocks_start(monkeypatch):
    monkeypatch.setattr(cm, "_DUTY_DISABLED", True)
    duty = cm.ProfileDutyCycle(period_s=0.05, window_s=0.01)
    assert not duty.enabled
    duty.start()
    assert not duty.running
    duty.stop()   # idempotent no-op


def test_duty_window_skips_when_capture_busy(monkeypatch, tmp_path):
    from kdtree_tpu.obs import flight, profile

    def busy(seconds, log_dir):
        raise profile.CaptureBusyError("manual capture in flight")

    monkeypatch.setattr(profile, "capture_for", busy)
    duty = cm.ProfileDutyCycle(log_dir=str(tmp_path))
    before = duty._skipped.value
    assert duty.run_window() is None
    assert duty._skipped.value == before + 1
    kinds = [e for e in flight.recorder().snapshot()
             if e.get("type") == "profile.duty_skip"]
    assert kinds and kinds[-1]["reason"] == "capture-busy"


def test_duty_window_publishes_and_cleans_artifact(monkeypatch, tmp_path):
    """A completed window analyzes the trace, counts, flight-records,
    and removes the multi-MB run directory — a long-lived replica must
    not fill the disk at one artifact per period."""
    from kdtree_tpu.obs import flight, profile, timeline

    run_dir = tmp_path / "plugins" / "profile" / "run-1"
    run_dir.mkdir(parents=True)
    trace = run_dir / "host.trace.json.gz"
    trace.write_bytes(b"fake")

    class FakeResult:
        trace_file = str(trace)

    monkeypatch.setattr(profile, "capture_for",
                        lambda seconds, log_dir: FakeResult())
    fake_rep = {"device": {"busy_frac": 0.7},
                "dispatches": {"lag_us": {"median": 42.0}}}
    monkeypatch.setattr(timeline, "analyze_trace_file",
                        lambda path: dict(fake_rep))
    duty = cm.ProfileDutyCycle(log_dir=str(tmp_path), period_s=300,
                               window_s=0.01)
    before = duty._windows.value
    rep = duty.run_window()
    assert rep["device"]["busy_frac"] == 0.7
    assert duty._windows.value == before + 1
    assert not run_dir.exists()   # artifact cleaned after analysis
    ev = [e for e in flight.recorder().snapshot()
          if e.get("type") == "profile.duty_window"]
    assert ev and ev[-1]["busy_frac"] == 0.7
    assert ev[-1]["lag_us_median"] == 42.0


def test_duty_thread_lifecycle(monkeypatch, tmp_path):
    from kdtree_tpu.obs import profile

    calls = []

    def fake_capture(seconds, log_dir):
        calls.append(seconds)
        raise profile.CaptureBusyError("keep the loop cheap")

    monkeypatch.setattr(profile, "capture_for", fake_capture)
    duty = cm.ProfileDutyCycle(log_dir=str(tmp_path), period_s=0.05,
                               window_s=0.01)
    duty.start()
    assert duty.running
    duty.start()   # idempotent
    deadline = time.time() + 5.0
    while not calls and time.time() < deadline:
        time.sleep(0.01)
    duty.stop()
    assert calls, "duty thread never attempted a window"
    assert not duty.running
    duty.stop()    # idempotent
