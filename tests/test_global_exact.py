"""Scalable exact-median global tree (SURVEY.md §7(b)) on the virtual
8-device CPU mesh. The load-bearing claims: (1) answers are exact k-NN over
the threefry row stream; (2) the top log2(P) heap levels are node-for-node
IDENTICAL to the single-chip exact build — true global medians with the
same (coordinate, id) tie order; (3) checkpoint + mesh-free portability."""

import numpy as np
import pytest

from kdtree_tpu.ops import bruteforce
from kdtree_tpu.ops.generate import generate_points_rowwise, generate_queries
from kdtree_tpu.parallel.global_exact import (
    GlobalExactTree,
    build_global_exact,
    global_exact_knn,
    global_exact_query,
)
from kdtree_tpu.parallel.mesh import make_mesh


def _oracle(seed, dim, n, nq, k):
    pts = generate_points_rowwise(seed, dim, n)
    qs = generate_queries(seed + 7777, dim, nq)
    bf_d2, bf_i = bruteforce.knn_exact_d2(pts, qs, k=k)
    return pts, qs, bf_d2, bf_i


@pytest.mark.parametrize("p", [1, 2, 4, 8])
@pytest.mark.parametrize("n,dim,k", [(2048, 3, 4), (1000, 2, 1), (1037, 3, 3),
                                     (1500, 8, 4)])
def test_matches_bruteforce_any_device_count(p, n, dim, k):
    # the 8-D case covers BASELINE.json configs[2]'s dimension: 4 Morton
    # bits/axis — much coarser codes, different splitter behavior
    pts, qs, bf_d2, _ = _oracle(47, dim, n, 8, k)
    d2, gi = global_exact_knn(47, dim, n, qs, k=k, mesh=make_mesh(p))
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-5)
    gather = np.sum(
        (np.asarray(qs)[:, None, :] - np.asarray(pts)[np.asarray(gi)]) ** 2,
        axis=-1,
    )
    np.testing.assert_allclose(gather, np.asarray(d2), rtol=1e-5)


def test_top_levels_identical_to_single_chip():
    """The heart of the 'exact median-split' claim: the distributed radix
    selects must pick THE SAME nodes (same point ids, same coordinates) as
    the single-chip level-synchronous build's top log2(P) heap levels."""
    from kdtree_tpu.ops.build import build_jit

    n, dim, p = 1037, 3, 8
    tree = build_global_exact(21, dim, n, mesh=make_mesh(p))
    ref = build_jit(generate_points_rowwise(21, dim, n))
    htop = p - 1
    ref_gid = np.asarray(ref.node_point)[:htop]
    got_gid = np.asarray(tree.top_gid)
    np.testing.assert_array_equal(got_gid, ref_gid)
    ref_pts = np.asarray(ref.points)[ref_gid]
    np.testing.assert_array_equal(np.asarray(tree.top_pts), ref_pts)


def test_device_count_invariance():
    qs = generate_queries(99, 3, 6)
    outs = [
        np.asarray(global_exact_knn(5, 3, 1500, qs, k=3, mesh=make_mesh(p))[0])
        for p in (1, 2, 4, 8)
    ]
    for o in outs[1:]:
        np.testing.assert_allclose(o, outs[0], rtol=1e-6)


@pytest.mark.parametrize("n", [9, 17, 1037])
def test_tiny_and_non_divisible_n(n):
    """Empty/size-1 top segments (n ~ P) and ceil-padding phantoms must
    never corrupt answers."""
    k = min(3, n)
    pts, qs, bf_d2, _ = _oracle(3, 3, n, 6, k)
    d2, gi = global_exact_knn(3, 3, n, qs, k=k, mesh=make_mesh(8))
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-5)
    assert int(np.asarray(gi).max()) < n


def test_radix_select_duplicate_keys():
    """The distributed select's tie rounds must resolve heavy exact-key
    duplication by id — the pure-tie worst case the generative path can't
    produce. Sharded crafted data: only 3 distinct key values spread over 8
    devices; the selected (key, id) must equal the host-sorted k-th pair
    for every rank k."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from kdtree_tpu.parallel.global_exact import _f32_key, _radix_select
    from kdtree_tpu.parallel.mesh import SHARD_AXIS

    rng = np.random.default_rng(0)
    vals = rng.choice(np.asarray([-1.5, 0.0, 7.25], np.float32), 64)
    gids = rng.permutation(64).astype(np.int32)
    order = np.lexsort((gids, vals))  # (value, id) ascending

    mesh = make_mesh(8)
    v = jnp.asarray(vals).reshape(8, 8)
    g = jnp.asarray(gids).reshape(8, 8)

    def body(v_, g_, kvec_):
        key = _f32_key(v_[0])
        mk, mg = _radix_select(
            key, g_[0], g_[0] >= 0, jnp.int32(0), kvec_, 1, SHARD_AXIS
        )
        return mk[None], mg[None]

    from kdtree_tpu.parallel.mesh import shard_map

    fn = jax.jit(shard_map(  # k is traced: ONE compile for all ranks
        body, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(None)),
        out_specs=(P(None), P(None)), check_vma=False,
    ))
    for k in (0, 5, 31, 32, 63):
        mk, mg = fn(v, g, jnp.asarray([k], jnp.int32))
        want_v, want_g = vals[order[k]], gids[order[k]]
        assert np.asarray(mg)[0] == want_g, (k, np.asarray(mg)[0], want_g)
        assert np.asarray(mk)[0] == np.asarray(_f32_key(jnp.float32(want_v))), k


def test_checkpoint_roundtrip_and_meshfree(tmp_path):
    from kdtree_tpu.utils.checkpoint import load_tree, save_tree

    n, dim, k, p = 1037, 3, 4, 8
    pts, qs, bf_d2, _ = _oracle(13, dim, n, 8, k)
    mesh = make_mesh(p)
    tree = build_global_exact(13, dim, n, mesh=mesh)
    d2, gi = global_exact_query(tree, qs, k=k, mesh=mesh)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-5)

    path = str(tmp_path / "gx.npz")
    save_tree(path, tree, meta={"seed": 13, "generator": "threefry"})
    loaded, meta = load_tree(path)
    assert isinstance(loaded, GlobalExactTree)
    assert loaded.num_points == n and loaded.devices == p
    d2b, _ = global_exact_query(loaded, qs, k=k, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(d2b), np.asarray(d2))
    # mesh-free (different-hardware) fallback
    d2c, _ = global_exact_query(loaded, qs, k=k, mesh=make_mesh(1))
    np.testing.assert_allclose(np.asarray(d2c), np.asarray(d2), rtol=1e-6)


def test_non_power_of_two_mesh_rejected():
    with pytest.raises(ValueError, match="power-of-2"):
        build_global_exact(1, 3, 100, mesh=make_mesh(3))


@pytest.mark.parametrize("dim", [3, 8])
def test_clustered_fit_default_slack(dim):
    """VERDICT r3 item 6 (exact-median engine): the Gaussian-mixture stream
    at DEFAULT slack must fit the mirror-exchange width with no overflow;
    exact medians keep the partition near-perfectly balanced regardless of
    skew (that invariance is the engine's whole point), and answers stay
    exact against the materialized oracle. dim=8 covers BASELINE.json
    configs[2]'s dimension (VERDICT r4 missing #4)."""
    import numpy as np

    from kdtree_tpu.ops import bruteforce
    from kdtree_tpu.ops.generate import generate_points_shard_clustered
    from kdtree_tpu.parallel.global_exact import (
        build_global_exact, global_exact_query,
    )
    from kdtree_tpu.parallel.mesh import make_mesh

    n, k, p = 1 << 13, 3, 8
    mesh = make_mesh(p)
    tree = build_global_exact(5, dim, n, mesh=mesh, distribution="clustered")
    occ = np.asarray((np.asarray(tree.local_gid) >= 0).sum(axis=1))
    in_top = int((np.asarray(tree.top_gid) >= 0).sum())
    assert occ.sum() + in_top == n
    assert occ.max() - occ.min() <= p, f"exact medians must balance: {occ}"

    pts = generate_points_shard_clustered(5, dim, 0, n)
    qs = pts[:24] + 0.05
    d2, gi = global_exact_query(tree, qs, k=k, mesh=mesh)
    bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=k)
    # same f32 summation-order tolerance note as the Morton fit test
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2),
                               rtol=1e-3, atol=1e-5)


def test_dense_query_routes_tiled_and_matches():
    """Dense low-D batches on the exact-median tree route to the tiled
    serving path (per-device Morton views + top-heap fold) and stay exact
    — VERDICT r3 missing #1 for the second global engine."""
    import numpy as np
    from unittest import mock

    from kdtree_tpu.ops import bruteforce
    from kdtree_tpu.ops.generate import generate_points_rowwise, generate_queries
    from kdtree_tpu.parallel import global_exact as ge
    from kdtree_tpu.parallel.mesh import make_mesh

    n, dim, k, p = 4096, 3, 4, 8
    mesh = make_mesh(p)
    tree = ge.build_global_exact(11, dim, n, mesh=mesh)
    qs = generate_queries(8, dim, 2048)  # dense: Q >= 512, Q*64 >= N

    with mock.patch.object(
        ge, "global_exact_query_tiled",
        side_effect=ge.global_exact_query_tiled,
    ) as tiled:
        d2, gi = ge.global_exact_query(tree, qs, k=k, mesh=mesh)
        assert tiled.call_count == 1

    pts = generate_points_rowwise(11, dim, n)
    bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=k)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-5)
    assert int(np.asarray(gi).max()) < n and int(np.asarray(gi).min()) >= 0

    # sparse batches keep the DFS path; answers agree across paths
    qs2 = generate_queries(9, dim, 64)
    a, _ = ge.global_exact_query(tree, qs2, k=k, mesh=mesh)
    b, _ = ge.global_exact_query_tiled(tree, qs2, k=k, mesh=mesh)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_forest_view_capacity_guard_falls_back_to_dfs(monkeypatch):
    """ADVICE r4 (medium): converting an exact tree to its forest view
    materializes a second copy of the local rows; when that would bust the
    chip's HBM budget the dense route must fall back to the in-place DFS
    query (mirroring _serve_dense_via_view) instead of compile-crashing."""
    import jax
    import numpy as np

    from kdtree_tpu.ops import bruteforce
    from kdtree_tpu.ops.generate import generate_points_rowwise, generate_queries
    from kdtree_tpu.ops.morton import BuildCapacityError
    from kdtree_tpu.parallel import global_exact as ge
    from kdtree_tpu.parallel.mesh import make_mesh

    n, dim, k, p = 4096, 3, 3, 8
    mesh = make_mesh(p)
    tree = ge.build_global_exact(17, dim, n, mesh=mesh)
    qs = generate_queries(3, dim, 1024)  # dense: Q >= 512, Q*64 >= N

    monkeypatch.setenv("KDTREE_TPU_MAX_BUILD_BYTES", "64")
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    # the explicit tiled entry point surfaces the budget failure crisply
    with pytest.raises(BuildCapacityError, match="global-morton"):
        ge.global_exact_query_tiled(tree, qs, k=k, mesh=mesh)
    # ... and the router absorbs it, serving the batch via DFS instead
    d2, gi = ge.global_exact_query(tree, qs, k=k, mesh=mesh)
    monkeypatch.undo()

    pts = generate_points_rowwise(17, dim, n)
    bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=k)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-5)
    assert int(np.asarray(gi).min()) >= 0
