import jax.numpy as jnp
import numpy as np
import pytest

from kdtree_tpu import build_jit, generate_problem, knn, nearest_neighbor
from kdtree_tpu.ops import bruteforce


@pytest.mark.parametrize(
    "n,d,q",
    [(1, 3, 2), (2, 3, 4), (100, 3, 10), (1000, 3, 10), (777, 2, 10), (500, 8, 10), (300, 5, 7)],
)
def test_1nn_matches_bruteforce(n, d, q):
    """The oracle test that catches the reference's sort off-by-one
    (SURVEY.md §3.5) — its low-D configs return wrong distances; ours must
    match brute force everywhere."""
    pts, qs = generate_problem(seed=n + d, dim=d, num_points=n, num_queries=q)
    tree = build_jit(pts)
    d2, idx = nearest_neighbor(tree, qs)
    bf_d2, bf_idx = bruteforce.knn_exact_d2(pts, qs, k=1)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2)[:, 0], rtol=1e-6)
    # indices may differ only on exact distance ties
    mism = np.asarray(idx) != np.asarray(bf_idx)[:, 0]
    if mism.any():
        np.testing.assert_allclose(
            np.asarray(d2)[mism], np.asarray(bf_d2)[mism, 0], rtol=0, atol=0
        )


@pytest.mark.parametrize("k", [1, 4, 16])
def test_knn_matches_bruteforce(k):
    pts, qs = generate_problem(seed=11, dim=3, num_points=512, num_queries=8)
    tree = build_jit(pts)
    d2, idx = knn(tree, qs, k=k)
    bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=k)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-6)
    # returned indices must actually produce the returned distances
    gather = np.sum(
        (np.asarray(qs)[:, None, :] - np.asarray(pts)[np.asarray(idx)]) ** 2, axis=-1
    )
    np.testing.assert_allclose(gather, np.asarray(d2), rtol=1e-6)


def test_knn_k_larger_than_n():
    pts, qs = generate_problem(seed=1, dim=3, num_points=5, num_queries=3)
    tree = build_jit(pts)
    d2, idx = knn(tree, qs, k=16)
    assert d2.shape == (3, 5)


def test_query_on_duplicate_points():
    pts = jnp.zeros((32, 3), jnp.float32)
    qs = jnp.ones((2, 3), jnp.float32)
    tree = build_jit(pts)
    d2, idx = nearest_neighbor(tree, qs)
    np.testing.assert_allclose(np.asarray(d2), 3.0, rtol=1e-6)


def test_bruteforce_tiled_matches_dense():
    pts, qs = generate_problem(seed=9, dim=4, num_points=1000, num_queries=6)
    a_d, _ = bruteforce.knn(pts, qs, k=8, tile=256)
    b_d, _ = bruteforce.knn_exact_d2(pts, qs, k=8)
    np.testing.assert_allclose(np.asarray(a_d), np.asarray(b_d), rtol=1e-5, atol=1e-3)


def test_ensemble_k_larger_than_n():
    """k is clamped to N in ensemble mode too (review finding)."""
    from kdtree_tpu.parallel import ensemble_knn, make_mesh

    pts, qs = generate_problem(seed=4, dim=3, num_points=6, num_queries=2)
    d2, idx = ensemble_knn(pts, qs, k=16, mesh=make_mesh(2))
    assert d2.shape == (2, 6)
    assert np.isfinite(np.asarray(d2)).all() and (np.asarray(idx) >= 0).all()
