"""Clustered (Gaussian-mixture) and high-D configs — the load-imbalance
dimension the course grades on (BASELINE.json configs[4]; Utility.cpp:98-99
hardcodes the 128-D shape). Every engine must stay EXACT under heavy skew;
the curse of dimensionality may only cost speed, never correctness
(SURVEY.md §3.5: in high D the reference's prune bug was masked — ours must
have nothing to mask)."""

import numpy as np
import pytest

from kdtree_tpu.ops import bruteforce
from kdtree_tpu.ops.generate import generate_clustered
from kdtree_tpu.ops.morton import build_morton, morton_knn
from kdtree_tpu.ops.tile_query import morton_knn_tiled


def test_mixture_is_clustered():
    """Sanity on the generator: mixture points concentrate mass far more
    than uniform draws (nearest-neighbor distances orders of magnitude
    smaller than the domain scale)."""
    pts, qs = generate_clustered(1, 3, 4000, num_queries=16)
    assert pts.shape == (4000, 3) and qs.shape == (16, 3)
    d2, _ = bruteforce.knn_exact_d2(pts, qs, k=1)
    # dense clusters: NN distance ~stddev, domain scale is 200
    assert float(np.median(np.sqrt(np.asarray(d2)))) < 5.0


@pytest.mark.parametrize("dim", [3, 16, 128])
def test_clustered_morton_exact(dim):
    """Morton tree exactness under skew, incl. the 128-D grading dimension
    (bits-per-axis degrades above D=32 — locality may die, answers not)."""
    pts, qs = generate_clustered(2, dim, 3000, num_queries=12)
    d2, gi = morton_knn(build_morton(pts), qs, k=5)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=5)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf), rtol=1e-5)


def test_clustered_128d_tiled_engine():
    """Tiled engine at 128-D clustered: frontier + dense scans stay exact
    when every query tile lands in a dense cluster."""
    pts, qs = generate_clustered(3, 128, 2000, num_queries=64)
    tree = build_morton(pts)
    d2, _ = morton_knn_tiled(tree, qs, k=4)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=4)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf), rtol=1e-5)


def test_clustered_128d_ensemble(mesh8):
    """Sharded ensemble on clustered 128-D input arrays."""
    from kdtree_tpu.parallel import ensemble_knn

    pts, qs = generate_clustered(4, 128, 1999, num_queries=10)
    d2, idx = ensemble_knn(pts, qs, k=3, mesh=mesh8)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=3)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf), rtol=1e-5)
    assert int(np.asarray(idx).max()) < 1999


def test_clustered_128d_matmul_refined():
    """The MXU (matmul-identity) brute-force path must survive clustered
    high-D data, where |x|^2 >> d^2 makes the identity cancel
    catastrophically in f32 — the refine pass (exact rescoring of k+slack
    coarse candidates) is what buys this."""
    pts, qs = generate_clustered(6, 128, 5000, num_queries=32)
    d2m, im = bruteforce.knn(pts, qs, k=5, method="matmul")
    bf, bi = bruteforce.knn_exact_d2(pts, qs, k=5)
    np.testing.assert_allclose(np.asarray(d2m), np.asarray(bf), rtol=1e-5)


def test_clustered_bucket_and_classic():
    """The remaining single-chip engines at a clustered mid-D shape."""
    from kdtree_tpu.ops.bucket import bucket_knn, build_bucket
    from kdtree_tpu.ops.build import build_jit
    from kdtree_tpu.ops.query import knn

    pts, qs = generate_clustered(5, 8, 2500, num_queries=10)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=3)
    d2b, _ = bucket_knn(build_bucket(pts), qs, k=3)
    np.testing.assert_allclose(np.asarray(d2b), np.asarray(bf), rtol=1e-5)
    d2c, _ = knn(build_jit(pts), qs, k=3)
    np.testing.assert_allclose(np.asarray(d2c), np.asarray(bf), rtol=1e-5)


@pytest.mark.parametrize("d", [128, 100])
def test_dsharded_128d(mesh8, d):
    """Feature-axis sharding (the TP analog, SURVEY §2): exact answers with
    the D axis split over 8 devices, incl. D not divisible by P."""
    from kdtree_tpu.parallel.dsharded import dsharded_knn

    pts, qs = generate_clustered(9, d, 3000, num_queries=16)
    d2, idx = dsharded_knn(pts, qs, k=5, mesh=mesh8)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=5)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf), rtol=1e-5)
    gather = np.sum(
        (np.asarray(qs)[:, None, :] - np.asarray(pts)[np.asarray(idx)]) ** 2,
        axis=-1,
    )
    np.testing.assert_allclose(gather, np.asarray(d2), rtol=1e-5)


def test_dsharded_non_divisible_n(mesh8):
    """Row padding (zero rows, position-masked) must never appear in
    results."""
    from kdtree_tpu.parallel.dsharded import dsharded_knn

    pts, qs = generate_clustered(10, 32, 777, num_queries=8)
    d2, idx = dsharded_knn(pts, qs, k=3, mesh=mesh8, tile=256)
    bf, _ = bruteforce.knn_exact_d2(pts, qs, k=3)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf), rtol=1e-5)
    assert int(np.asarray(idx).max()) < 777
