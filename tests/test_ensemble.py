import numpy as np
import pytest

from kdtree_tpu import generate_problem
from kdtree_tpu.ops import bruteforce
from kdtree_tpu.parallel import ensemble_knn, make_mesh


@pytest.mark.parametrize("n,d,k", [(512, 3, 1), (512, 3, 16), (1000, 5, 4)])
def test_ensemble_matches_bruteforce(mesh8, n, d, k):
    """The ensemble mode reproduces kdtree_mpi.cpp semantics (local trees +
    min-reduce) but exactly, with global indices, and for k-NN."""
    pts, qs = generate_problem(seed=n + k, dim=d, num_points=n, num_queries=10)
    d2, idx = ensemble_knn(pts, qs, k=k, mesh=mesh8)
    bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=k)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-6)
    gather = np.sum(
        (np.asarray(qs)[:, None, :] - np.asarray(pts)[np.asarray(idx)]) ** 2, axis=-1
    )
    np.testing.assert_allclose(gather, np.asarray(d2), rtol=1e-6)


def test_ensemble_unpadded_remainder(mesh8):
    """N not divisible by P: reference gives the remainder to the last rank
    (kdtree_mpi.cpp:208-216); we pad with +inf sentinels — results must still
    be exact and indices must never point at padding."""
    pts, qs = generate_problem(seed=2, dim=3, num_points=509, num_queries=10)
    d2, idx = ensemble_knn(pts, qs, k=3, mesh=mesh8)
    assert int(np.asarray(idx).max()) < 509 and int(np.asarray(idx).min()) >= 0
    bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=3)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-6)


def test_ensemble_matches_single_device(mesh8):
    """Same seed => same answer on 1 device and 8 (the reference's de-facto
    sequential-vs-MPI integration test, SURVEY.md §4)."""
    pts, qs = generate_problem(seed=13, dim=3, num_points=512, num_queries=10)
    d2_8, _ = ensemble_knn(pts, qs, k=2, mesh=mesh8)
    d2_1, _ = ensemble_knn(pts, qs, k=2, mesh=make_mesh(1))
    np.testing.assert_allclose(np.asarray(d2_8), np.asarray(d2_1), rtol=1e-6)


def test_ensemble_dense_batch_routes_tiled(mesh8):
    """Dense low-D batches take the tiled forest route (the measured
    ~100x crossover) — same exactness and global-id contract as the fused
    path, now with per-shard plans in the persistent store."""
    from kdtree_tpu.ops.generate import generate_queries
    from kdtree_tpu.ops.tile_query import dense_lowd

    pts, _ = generate_problem(seed=6, dim=3, num_points=20000, num_queries=1)
    qs = generate_queries(61, 3, 1024)
    assert dense_lowd(1024, 20000, 3)  # the shape really takes the route
    d2, idx = ensemble_knn(pts, qs, k=5, mesh=mesh8)
    bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=5)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-5)
    gather = np.sum(
        (np.asarray(qs)[:, None, :] - np.asarray(pts)[np.asarray(idx)]) ** 2,
        axis=-1,
    )
    np.testing.assert_allclose(gather, np.asarray(d2), rtol=1e-5)


def test_ensemble_gen_matches_oracle(mesh8):
    """Generative ensemble (VERDICT r2 item 5): shard-local generation, no
    [N, D] materialization; answers must equal brute force over the
    threefry row stream, for divisible and non-divisible N."""
    from kdtree_tpu.ops.generate import generate_points_rowwise, generate_queries
    from kdtree_tpu.parallel import ensemble_knn_gen

    for n in (512, 509):
        qs = generate_queries(7, 3, 10)
        d2, idx = ensemble_knn_gen(21, 3, n, qs, k=3, mesh=mesh8)
        pts = generate_points_rowwise(21, 3, n)
        bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=3)
        np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-6)
        assert int(np.asarray(idx).max()) < n and int(np.asarray(idx).min()) >= 0


def test_ensemble_gen_device_count_invariance(mesh8):
    """Same (seed, dim, n) => identical answers on 1..8 devices — the
    determinism the reference gets from its discard trick."""
    from kdtree_tpu.ops.generate import generate_queries
    from kdtree_tpu.parallel import ensemble_knn_gen

    qs = generate_queries(3, 3, 8)
    outs = [
        np.asarray(ensemble_knn_gen(9, 3, 700, qs, k=2, mesh=make_mesh(p))[0])
        for p in (1, 2, 4, 8)
    ]
    for o in outs[1:]:
        np.testing.assert_allclose(o, outs[0], rtol=1e-6)


def test_fused_jit_gate_matches_version_probe():
    """_FUSED_JIT_SAFE is exactly the first-class-shard_map probe: the
    0.4.x experimental-era SPMD partitioner miscompiles the fused
    build+query shard_map under an outer jit (ensemble.py's caveat), so
    legacy jax must run it eagerly and modern jax must not pay the
    op-by-op prelude."""
    import jax

    from kdtree_tpu.parallel import ensemble

    assert ensemble._FUSED_JIT_SAFE == hasattr(jax, "shard_map")


@pytest.mark.skipif(
    not hasattr(__import__("jax"), "shard_map"),
    reason="legacy jax (experimental shard_map): the fused-jit path is "
           "known to miscompile and is gated off — this pin un-skips "
           "the day the toolchain carries first-class jax.shard_map",
)
def test_fused_jit_path_exact_on_first_class_shard_map(mesh8):
    """On a jax with first-class shard_map the fused JITTED ensemble
    must be byte-identical to the eager run and exact vs brute force —
    the precise miscompilation signature that forced the legacy gate
    (wrong per-shard answers under an outer jit) must be gone."""
    from kdtree_tpu.models.tree import tree_spec
    from kdtree_tpu.ops.build import spec_arrays
    from kdtree_tpu.parallel import ensemble

    assert ensemble._FUSED_JIT_SAFE is True
    pts, qs = generate_problem(seed=5, dim=3, num_points=512,
                               num_queries=10)
    p = mesh8.shape[ensemble.SHARD_AXIS]
    n_local = (512 + p - 1) // p
    structure = spec_arrays(n_local, 3)
    num_levels = tree_spec(n_local).num_levels
    jd2, jidx = ensemble._ensemble_jit(
        pts, qs, structure, 3, mesh8, float("inf"), num_levels)
    ed2, eidx = ensemble._ensemble_impl(
        pts, qs, structure, 3, mesh8, float("inf"), num_levels)
    np.testing.assert_array_equal(np.asarray(jd2), np.asarray(ed2))
    np.testing.assert_array_equal(np.asarray(jidx), np.asarray(eidx))
    bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=3)
    np.testing.assert_allclose(np.asarray(jd2), np.asarray(bf_d2),
                               rtol=1e-6)
