import jax
import numpy as np
import pytest

from kdtree_tpu import generate_problem
from kdtree_tpu.ops import bruteforce
from kdtree_tpu.parallel import ensemble_knn, make_mesh


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest should have forced 8 CPU devices"
    return make_mesh(8)


@pytest.mark.parametrize("n,d,k", [(512, 3, 1), (512, 3, 16), (1000, 5, 4)])
def test_ensemble_matches_bruteforce(mesh8, n, d, k):
    """The ensemble mode reproduces kdtree_mpi.cpp semantics (local trees +
    min-reduce) but exactly, with global indices, and for k-NN."""
    pts, qs = generate_problem(seed=n + k, dim=d, num_points=n, num_queries=10)
    d2, idx = ensemble_knn(pts, qs, k=k, mesh=mesh8)
    bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=k)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-6)
    gather = np.sum(
        (np.asarray(qs)[:, None, :] - np.asarray(pts)[np.asarray(idx)]) ** 2, axis=-1
    )
    np.testing.assert_allclose(gather, np.asarray(d2), rtol=1e-6)


def test_ensemble_unpadded_remainder(mesh8):
    """N not divisible by P: reference gives the remainder to the last rank
    (kdtree_mpi.cpp:208-216); we pad with +inf sentinels — results must still
    be exact and indices must never point at padding."""
    pts, qs = generate_problem(seed=2, dim=3, num_points=509, num_queries=10)
    d2, idx = ensemble_knn(pts, qs, k=3, mesh=mesh8)
    assert int(np.asarray(idx).max()) < 509 and int(np.asarray(idx).min()) >= 0
    bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=3)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-6)


def test_ensemble_matches_single_device(mesh8):
    """Same seed => same answer on 1 device and 8 (the reference's de-facto
    sequential-vs-MPI integration test, SURVEY.md §4)."""
    pts, qs = generate_problem(seed=13, dim=3, num_points=512, num_queries=10)
    d2_8, _ = ensemble_knn(pts, qs, k=2, mesh=mesh8)
    d2_1, _ = ensemble_knn(pts, qs, k=2, mesh=make_mesh(1))
    np.testing.assert_allclose(np.asarray(d2_8), np.asarray(d2_1), rtol=1e-6)
