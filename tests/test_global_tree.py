"""Global-tree mode: one exact tree over mesh-sharded points.

The strongest test here is structural identity: the distributed build must
produce the *same* tree (same node -> global point id mapping) as the
single-chip build over the same global array, because both run the identical
level-synchronous algorithm — only the sort is distributed.
"""

import numpy as np
import pytest

from kdtree_tpu import build_jit, generate_problem
from kdtree_tpu.ops import bruteforce
from kdtree_tpu.parallel import build_global, global_build_knn, make_mesh


@pytest.mark.parametrize("n,d", [(512, 3), (1024, 5), (256, 2)])
def test_structural_identity_with_single_chip(mesh8, n, d):
    pts, _ = generate_problem(seed=n + d, dim=d, num_points=n)
    gtree = build_global(pts, mesh=mesh8)
    tree = build_jit(pts)
    np.testing.assert_array_equal(
        np.asarray(gtree.node_gid), np.asarray(tree.node_point)
    )
    # node coordinates must be the actual point coordinates
    npnt = np.asarray(tree.node_point)
    valid = npnt >= 0
    np.testing.assert_array_equal(
        np.asarray(gtree.node_coords)[valid], np.asarray(pts)[npnt[valid]]
    )


@pytest.mark.parametrize("n,d,k", [(512, 3, 1), (512, 3, 16), (777, 4, 3)])
def test_global_knn_matches_bruteforce(mesh8, n, d, k):
    pts, qs = generate_problem(seed=n + k, dim=d, num_points=n, num_queries=10)
    d2, idx = global_build_knn(pts, qs, k=k, mesh=mesh8)
    bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=k)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-6)
    gather = np.sum(
        (np.asarray(qs)[:, None, :] - np.asarray(pts)[np.asarray(idx)]) ** 2, axis=-1
    )
    np.testing.assert_allclose(gather, np.asarray(d2), rtol=1e-6)


def test_global_padding_orphans(mesh8):
    """Non-divisible N: padding sentinels become non-takeable suffix nodes;
    real points in their left subtrees must still be reachable (regression
    test for the orphaned-subtree hazard)."""
    for n in (509, 63, 9):
        pts, qs = generate_problem(seed=n, dim=3, num_points=n, num_queries=10)
        d2, idx = global_build_knn(pts, qs, k=2, mesh=mesh8)
        bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=2)
        np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2), rtol=1e-6)
        assert int(np.asarray(idx).max()) < n and int(np.asarray(idx).min()) >= 0


def test_global_two_devices():
    pts, qs = generate_problem(seed=1, dim=3, num_points=200, num_queries=6)
    d2, _ = global_build_knn(pts, qs, k=1, mesh=make_mesh(2))
    bf_d2, _ = bruteforce.knn_exact_d2(pts, qs, k=1)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(bf_d2)[:, :1], rtol=1e-6)


def test_non_power_of_two_mesh_rejected():
    with pytest.raises(ValueError):
        pts, _ = generate_problem(seed=1, dim=3, num_points=64)
        build_global(pts, mesh=make_mesh(3))


def test_build_global_gen_structural_identity(mesh8):
    """Generative global build (VERDICT r2 item 5): shard-local generation
    must produce the IDENTICAL tree to build_global over the materialized
    row stream — node ids and coordinates, divisible and non-divisible N."""
    from kdtree_tpu.ops.generate import generate_points_rowwise
    from kdtree_tpu.parallel import build_global, build_global_gen

    for n in (256, 251):
        ref = build_global(generate_points_rowwise(17, 3, n), mesh=mesh8)
        gen = build_global_gen(17, 3, n, mesh=mesh8)
        assert gen.n_real == ref.n_real == n
        np.testing.assert_array_equal(
            np.asarray(gen.node_gid), np.asarray(ref.node_gid)
        )
        np.testing.assert_array_equal(
            np.asarray(gen.node_coords), np.asarray(ref.node_coords)
        )
